"""Seeded antithetic OpenAI-ES over the continuous weight vector
(ISSUE 9).

The estimator of Salimans et al. ("Evolution Strategies as a Scalable
Alternative to RL") with the two standard variance reductions: mirrored
(antithetic) sampling — each draw eps contributes candidates mean±sigma*eps
— and centered-rank fitness shaping, which makes the update invariant to
monotone transforms of the objective (gpu_alloc percents and frag
percents need no calibration against each other).

Determinism contract (the tuning log's byte-identity hinges on it): the
generation-g perturbations come from `np.random.default_rng([seed, g])`
— a pure function of (seed, gen), independent of call history — so
`ask`/`tell` never carry RNG state, a resumed run re-derives exactly the
draws the interrupted run would have made, and `state_dict()` is just
(mean, sigma, lr): plain floats that round-trip JSON exactly.
"""

from __future__ import annotations

import numpy as np


def centered_ranks(scores) -> np.ndarray:
    """Fitness shaping: scores -> ranks scaled into [-0.5, 0.5] (ties
    broken by candidate index — deterministic). The mean-zero property
    makes the antithetic pairs cancel their common component exactly."""
    s = np.asarray(scores, np.float64)
    n = s.size
    ranks = np.empty(n, np.float64)
    ranks[np.argsort(s, kind="stable")] = np.arange(n, dtype=np.float64)
    if n == 1:
        return np.zeros(1, np.float64)
    return ranks / (n - 1) - 0.5


class OpenAIES:
    """Maximize f over R^d: ask(gen) -> [popsize, d] candidates,
    tell(gen, scores) updates the mean. popsize must be even (antithetic
    halves)."""

    algo = "es"

    def __init__(self, x0, sigma: float = 250.0, lr: float = 300.0,
                 popsize: int = 8, seed: int = 0):
        self.mean = np.asarray(x0, np.float64).copy()
        if self.mean.ndim != 1:
            raise ValueError(f"x0 must be a vector, got shape {self.mean.shape}")
        if popsize < 2 or popsize % 2:
            raise ValueError(f"popsize must be even and >= 2, got {popsize}")
        self.sigma = float(sigma)
        self.lr = float(lr)
        self.popsize = int(popsize)
        self.seed = int(seed)

    def _eps(self, gen: int) -> np.ndarray:
        """The generation's mirrored perturbations [popsize, d] — a pure
        function of (seed, gen), see module docstring."""
        rng = np.random.default_rng([self.seed, int(gen)])
        half = rng.standard_normal((self.popsize // 2, self.mean.size))
        return np.concatenate([half, -half], axis=0)

    def ask(self, gen: int) -> np.ndarray:
        return self.mean[None, :] + self.sigma * self._eps(gen)

    def tell(self, gen: int, scores) -> None:
        scores = np.asarray(scores, np.float64)
        if scores.shape != (self.popsize,):
            raise ValueError(
                f"scores must have shape ({self.popsize},), got "
                f"{scores.shape}"
            )
        util = centered_ranks(scores)
        eps = self._eps(gen)
        # normalized ascent direction (rank utilities are dimensionless,
        # |direction| = O(1)); lr is therefore in WEIGHT units — the mean
        # moves at most ~lr/2 per generation through the i32 operand
        # space, regardless of sigma
        direction = util @ eps / self.popsize
        self.mean = self.mean + self.lr * direction

    # ---- resumable state (tuning-log vocabulary) ----

    def state_dict(self) -> dict:
        return {
            "algo": self.algo,
            "mean": [float(x) for x in self.mean],
            "sigma": float(self.sigma),
            "lr": float(self.lr),
        }

    def load_state(self, state: dict) -> None:
        if state.get("algo") != self.algo:
            raise ValueError(
                f"state is for algo {state.get('algo')!r}, not {self.algo!r}"
            )
        mean = np.asarray(state["mean"], np.float64)
        if mean.shape != self.mean.shape:
            raise ValueError(
                f"state mean has shape {mean.shape}, expected "
                f"{self.mean.shape}"
            )
        self.mean = mean
        self.sigma = float(state["sigma"])
        self.lr = float(state["lr"])
