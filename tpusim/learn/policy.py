"""The learned scorer as a first-class policy (ISSUE 14).

PR 8 tunes a weight vector over FIXED built-in policies; this module
makes the policy itself learnable while keeping every engine contract
intact, by exploiting one identity: a LINEAR model over a per-node
feature row IS a weight vector over per-feature score kernels. Each
feature is a policy-kernel-shaped pure function of (node state, pod
spec) — exactly the quantities the score tables and the series plane
already compute (free/total GPU & CPU milli, per-device free-mask
stats, frag-category terms, the DOWN flag) — emitting i32 raw scores in
the [0, MAX_NODE_SCORE] score-table vocabulary with normalize="none".
A learned policy is then the family

    policies = [("LearnedScore[f]", theta_f) for f in features]

and the model parameters theta ARE the engines' traced i32 weight
operand (ISSUE 6): the sequential, flat-table, blocked-table, and
shard_map engines replay the learned policy bit-identically like any
built-in (their tables hold the feature rows; selectHost consumes
sum theta_f * feature_f), a parameter change is a device call, not a
recompile, `run_sweep` vmaps a POPULATION of parameter vectors in one
compiled scan (the ES trainer's rollout, learn.loop), the decision
flight recorder's raw/norm columns become per-FEATURE contributions
(`tpusim explain` attributes a learned choice exactly like a built-in's,
sum weight*norm == the recorded selectHost total), and the svc job plane
serves it unchanged (policies are just [name, weight] pairs).

The optional BUCKETED form appends indicator features (100 iff the
node's GPU occupancy falls in bucket k — the series plane's 10-bucket
node-utilization vocabulary, obs.series.UTIL_BUCKETS): linear over
indicators is a small-table/piecewise-constant model, same machinery.

Artifacts: a trained parameter vector persists as a digest-signed JSON
document (io.storage.write_signed_json — the lease/result discipline;
torn or edited files fail loudly) carrying the feature vocabulary it was
trained over, so `apply --policy LearnedScore:file.json`,
`serve --policy-preset NAME=file.json`, and submit jobs all replay the
exact same i32 family.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from tpusim.constants import MAX_GPUS_PER_NODE, MAX_NODE_SCORE, MILLI
from tpusim.ops.frag import frag_class, node_frag_score
from tpusim.policies.base import PolicyResult, ScoreContext
from tpusim.policies.fgd import fgd_score

POLICY_SCHEMA = "tpusim-learned-policy/1"

LEARNED_PREFIX = "LearnedScore["

# i32 parameter bounds of the learned family: features are <= 100, so
# |theta| <= 4000 keeps any total well inside i32 (4000 * 100 * F). The
# sign is meaningful — "more free GPU" can hurt a packing objective —
# which is why the learned lane's default bounds are symmetric where the
# built-in weight lane's are [0, 4000].
THETA_LO = -4000
THETA_HI = 4000


def _pct(num, den):
    """floor(100 * num / den) clipped into the [0, MAX_NODE_SCORE] score
    vocabulary — exact integer math, no f32 in the elementwise features."""
    val = num * MAX_NODE_SCORE // jnp.maximum(den, 1)
    return jnp.clip(val, 0, MAX_NODE_SCORE).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Feature kernels: i32[N] in [0, 100], pure in (node row, pod spec, tp)
# ---------------------------------------------------------------------------


def _free_gpu_pct(state, pod, ctx):
    """Idle GPU milli as a percent of the node's GPU capacity (DOWN
    nodes carry gpu_left == 0, so they read 0 without special casing)."""
    return _pct(state.gpu_left.sum(-1), state.gpu_cnt * MILLI)


def _free_cpu_pct(state, pod, ctx):
    return _pct(jnp.maximum(state.cpu_left, 0), state.cpu_cap)


def _free_mem_pct(state, pod, ctx):
    """mem_left == -1 is the DOWN sentinel — clipped to 0 here; the
    dedicated down feature carries the flag itself."""
    return _pct(jnp.maximum(state.mem_left, 0), state.mem_cap)


def _free_gpus_pct(state, pod, ctx):
    """Fully idle devices as a percent of the node's device count — the
    per-device free-mask statistic the PWR/packing family reduces."""
    return _pct((state.gpu_left == MILLI).sum(-1), state.gpu_cnt)


def _fit_dev_pct(state, pod, ctx):
    """Devices that could host one unit of this pod's per-GPU request,
    over the fixed MAX_GPUS_PER_NODE width (pad devices hold 0 milli and
    never fit). 0 for CPU-only pods."""
    need = jnp.maximum(pod.gpu_milli, 1)
    fits = (state.gpu_left >= need).sum(-1)
    return jnp.where(
        pod.total_gpu_milli() > 0,
        _pct(fits, jnp.int32(MAX_GPUS_PER_NODE)),
        0,
    ).astype(jnp.int32)


def _max_dev_free_pct(state, pod, ctx):
    """Largest per-device idle share — distinguishes one-nearly-free
    device from the same milli spread thin (what the share-GPU packers
    care about)."""
    return _pct(state.gpu_left.max(-1), jnp.int32(MILLI))


def _q3_sat_pct(state, pod, ctx):
    """Percent of the typical-pod frequency mass this node can host
    outright (frag class Q3) — the satisfaction half of the FGD frag
    decomposition, per node."""
    from tpusim.constants import Q3_SATISFIED

    def one(cpu_left, gpu_left, gpu_type):
        cls = frag_class(cpu_left, gpu_left, gpu_type, ctx.tp)
        sat = jnp.where(cls == Q3_SATISFIED, ctx.tp.freq, 0.0).sum()
        return jnp.clip(
            jnp.floor(sat * MAX_NODE_SCORE), 0, MAX_NODE_SCORE
        ).astype(jnp.int32)

    return jax.vmap(one)(state.cpu_left, state.gpu_left, state.gpu_type)


def _frag_pct(state, pod, ctx):
    """The node's own frag score (every class but Q3) as a percent of
    its idle GPU milli — the frag-category term of the series plane,
    normalized per node so it lives in the score vocabulary."""

    def one(cpu_left, gpu_left, gpu_type):
        total = gpu_left.sum().astype(jnp.float32)
        score = node_frag_score(cpu_left, gpu_left, gpu_type, ctx.tp)
        pct = jnp.floor(
            score * MAX_NODE_SCORE / jnp.maximum(total, 1.0)
        )
        return jnp.clip(pct, 0, MAX_NODE_SCORE).astype(jnp.int32)

    return jax.vmap(one)(state.cpu_left, state.gpu_left, state.gpu_type)


def _down(state, pod, ctx):
    """100 on a DOWN node (the mem_left == -1 fault sentinel). Filter
    already rejects DOWN nodes, so this never flips a selection — it
    exists so the vocabulary is complete for disruption-aware objectives
    and for explain's attribution rows."""
    return jnp.where(state.mem_left < 0, MAX_NODE_SCORE, 0).astype(jnp.int32)


def _frag_delta(state, pod, ctx):
    """The FGD frag-gradient term: how much placing THIS pod here
    improves the cluster frag outlook (the sigmoid-scored frag delta,
    policies.fgd). The one pod-interaction feature — a learned theta
    putting all mass here IS the FGDScore argmax, which is what makes
    imitation of an FGD teacher exactly representable."""
    return fgd_score(state, pod, ctx)


def _util_bucket(k: int):
    """Indicator feature (0 | 100) of GPU-occupancy bucket k — the
    series plane's node-utilization histogram math (obs.series
    cluster_stats: bucket = used * B // cap), restricted to UP GPU
    nodes. Linear over the 10 indicators = a bucketed table model."""
    from tpusim.obs.series import UTIL_BUCKETS

    def kernel(state, pod, ctx):
        cap = state.gpu_cnt * MILLI
        used = cap - state.gpu_left.sum(-1)
        bucket = jnp.clip(
            used * UTIL_BUCKETS // jnp.maximum(cap, 1), 0, UTIL_BUCKETS - 1
        )
        live = (state.mem_left >= 0) & (state.gpu_cnt > 0)
        return jnp.where(
            live & (bucket == k), MAX_NODE_SCORE, 0
        ).astype(jnp.int32)

    return kernel


_FEATURE_IMPLS = {
    "frag_delta": _frag_delta,
    "free_gpu_pct": _free_gpu_pct,
    "free_cpu_pct": _free_cpu_pct,
    "free_mem_pct": _free_mem_pct,
    "free_gpus_pct": _free_gpus_pct,
    "fit_dev_pct": _fit_dev_pct,
    "max_dev_free_pct": _max_dev_free_pct,
    "q3_sat_pct": _q3_sat_pct,
    "frag_pct": _frag_pct,
    "down": _down,
}
for _k in range(10):
    _FEATURE_IMPLS[f"util_bucket{_k}"] = _util_bucket(_k)

# the two shipped vocabularies; artifacts name their features explicitly
# so future vocabulary growth cannot silently re-interpret old thetas
LINEAR_FEATURES = (
    "frag_delta", "free_gpu_pct", "free_cpu_pct", "free_mem_pct",
    "free_gpus_pct", "fit_dev_pct", "max_dev_free_pct", "q3_sat_pct",
    "frag_pct", "down",
)
BUCKETED_FEATURES = LINEAR_FEATURES + tuple(
    f"util_bucket{k}" for k in range(10)
)
FEATURE_SETS = {"linear": LINEAR_FEATURES, "bucketed": BUCKETED_FEATURES}

FEATURE_NAMES = tuple(_FEATURE_IMPLS)

_KERNEL_CACHE: dict = {}


def learned_policy_name(feature: str) -> str:
    return f"{LEARNED_PREFIX}{feature}]"


def parse_learned_name(name: str):
    """'LearnedScore[feat]' -> 'feat', or None for non-learned names."""
    if name.startswith(LEARNED_PREFIX) and name.endswith("]"):
        return name[len(LEARNED_PREFIX):-1]
    return None


def is_learned_name(name: str) -> bool:
    feat = parse_learned_name(name)
    return feat is not None and feat in _FEATURE_IMPLS


def feature_policy(feature: str):
    """The singleton policy kernel of one feature — singletons because
    the engine caches key on kernel object identity (the make_policy
    contract every built-in honors). normalize='none': the raw feature
    value IS what the weighted sum consumes, which keeps the blocked /
    shard selects on their cheap none-normalize paths and makes
    explain's per-feature arithmetic exact by construction."""
    if feature not in _FEATURE_IMPLS:
        raise KeyError(
            f"unknown learned feature {feature!r} (known: "
            f"{', '.join(FEATURE_NAMES)})"
        )
    if feature not in _KERNEL_CACHE:
        impl = _FEATURE_IMPLS[feature]

        def kernel(state, pod, ctx: ScoreContext,
                   _impl=impl) -> PolicyResult:
            res = _impl(state, pod, ctx)
            if isinstance(res, PolicyResult):
                return res
            return PolicyResult(
                res, jnp.full(state.num_nodes, -1, jnp.int32)
            )

        kernel.normalize = "none"
        kernel.policy_name = learned_policy_name(feature)
        if feature == "frag_delta":
            # branch-specialized halves for the table engine's static
            # share/whole type partition (the fgd idiom)
            kernel.branches = dict(fgd_score.branches)
        _KERNEL_CACHE[feature] = kernel
    return _KERNEL_CACHE[feature]


def default_theta(features) -> list:
    """The FGD-equivalent starting point: all mass on the frag-gradient
    feature. Its argmax is FGDScore's argmax exactly (same raw rows,
    same tie-break), so it doubles as the tuned-vs-default baseline the
    holdout report compares against."""
    return [1000 if f == "frag_delta" else 0 for f in features]


def learned_policies(theta=None, features=LINEAR_FEATURES):
    """[(name, theta_f)] pairs — the SimulatorConfig.policies /
    TuneConfig form of a learned policy. theta None = default_theta."""
    features = tuple(features)
    for f in features:
        if f not in _FEATURE_IMPLS:
            raise ValueError(
                f"unknown learned feature {f!r} (known: "
                f"{', '.join(FEATURE_NAMES)})"
            )
    if theta is None:
        theta = default_theta(features)
    theta = [int(t) for t in theta]
    if len(theta) != len(features):
        raise ValueError(
            f"theta has {len(theta)} entries for {len(features)} features"
        )
    for t in theta:
        if not THETA_LO <= t <= THETA_HI:
            raise ValueError(
                f"theta entry {t} outside the i32 export bounds "
                f"[{THETA_LO}, {THETA_HI}]"
            )
    return [(learned_policy_name(f), t) for f, t in zip(features, theta)]


# ---------------------------------------------------------------------------
# The digest-signed policy artifact
# ---------------------------------------------------------------------------


def save_policy_artifact(path: str, theta, features=LINEAR_FEATURES,
                         meta=None) -> str:
    """Persist a trained parameter vector as a signed artifact (atomic,
    payload-digest header — a torn/edited file fails loudly on load).
    The document is exactly what load_policy_artifact hands back, and
    the features list pins the vocabulary the theta indexes."""
    from tpusim.io import storage

    pairs = learned_policies(theta, features)  # validates
    doc = {
        "features": [str(f) for f in features],
        "theta": [int(w) for _, w in pairs],
        "meta": dict(meta or {}),
    }
    return storage.write_signed_json(
        path, {"schema": POLICY_SCHEMA}, doc
    )


def load_policy_artifact(path: str):
    """(features tuple, theta list, meta dict) from a signed artifact;
    raises ValueError on torn/edited/wrong-schema files or unknown
    features (a vocabulary-drifted artifact must not silently score
    different quantities)."""
    from tpusim.io import storage

    _, doc = storage.read_signed_json(path, POLICY_SCHEMA)
    features = tuple(str(f) for f in doc.get("features", ()))
    theta = [int(t) for t in doc.get("theta", ())]
    learned_policies(theta, features)  # validates names/bounds/length
    return features, theta, dict(doc.get("meta") or {})


def policies_from_artifact(path: str):
    """Artifact file -> the [(name, weight)] policy pairs every config
    surface consumes (SimulatorConfig, job documents, tune)."""
    features, theta, _ = load_policy_artifact(path)
    return learned_policies(theta, features)


def parse_policy_spec(spec: str):
    """One `--policy` value -> [(name, weight)] pairs.

    Forms: 'LearnedScore:PATH' (a signed artifact), 'learned' /
    'learned-bucketed' (the default-theta families), or a built-in
    policy name at weight 1000 (the reference's single-plugin form)."""
    from tpusim.policies import POLICY_NAMES

    if spec.startswith("LearnedScore:"):
        path = spec[len("LearnedScore:"):]
        if not os.path.isfile(path):
            raise ValueError(f"--policy artifact not found: {path!r}")
        return policies_from_artifact(path)
    if spec in ("learned", "learned-linear"):
        return learned_policies()
    if spec == "learned-bucketed":
        return learned_policies(features=BUCKETED_FEATURES)
    if spec in POLICY_NAMES:
        return [(spec, 1000)]
    raise ValueError(
        f"unknown --policy {spec!r}: want LearnedScore:FILE.json, "
        "learned, learned-bucketed, or a built-in policy name "
        f"({', '.join(POLICY_NAMES)})"
    )
