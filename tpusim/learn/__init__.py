"""tpusim.learn — the learned-scoring lane (ISSUE 9).

Gradient-free tuning of the per-policy score weights over the
vectorized sweep: seeded antithetic OpenAI-ES (learn.es) and a minimal
diagonal CMA-ES (learn.cma) propose continuous weight vectors, projected
and dedup'd onto the engines' i32 operand space (learn.rollout), rolled
out through one compiled vmapped scan per generation locally or through
the `tpusim serve --jobs` replay service remotely (learn.rollout), and
scored on the paper's own metrics — gpu_alloc up, FGD frag down,
unscheduled bounded (learn.objective). The generation loop (learn.loop,
`tpusim tune`) keeps a digest-signed resumable tuning log whose bytes
are identical across backends and across kill/resume under a fixed seed.
"""

from tpusim.learn.cma import DiagonalCMA  # noqa: F401
from tpusim.learn.es import OpenAIES, centered_ranks  # noqa: F401
from tpusim.learn.loop import (  # noqa: F401
    LOG_SCHEMA,
    ImitateConfig,
    TuneConfig,
    TuneResult,
    format_holdout_report,
    holdout_report,
    make_optimizer,
    project_theta,
    read_log,
    run_imitation,
    run_tune,
    write_log,
)
from tpusim.learn.dataset import (  # noqa: F401
    ImitationPairs,
    TeacherReplay,
    feature_names_of,
    imitate_with_mining,
    load_teacher_log,
)
from tpusim.learn.policy import (  # noqa: F401
    BUCKETED_FEATURES,
    FEATURE_SETS,
    LINEAR_FEATURES,
    POLICY_SCHEMA,
    learned_policies,
    load_policy_artifact,
    parse_policy_spec,
    policies_from_artifact,
    save_policy_artifact,
)
from tpusim.learn.objective import (  # noqa: F401
    ObjectiveConfig,
    lane_terms,
    make_robust_eval,
    scalarize,
    terms_from_result,
    terms_from_simulate,
)
from tpusim.learn.rollout import (  # noqa: F401
    LocalRollout,
    RemoteRollout,
    dedup_rows,
    make_family_sim,
    project_weights,
)
