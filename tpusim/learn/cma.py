"""Minimal diagonal (separable) CMA-ES over the weight vector (ISSUE 9).

sep-CMA-ES (Ros & Hansen 2008): the full covariance is restricted to its
diagonal, which drops the update to O(d) and — with policy-weight
dimensions in the single digits — loses nothing while keeping CMA's two
adaptations ES lacks: per-dimension step sizes (frag-weight and
alloc-weight live on very different sensitivity scales) and cumulative
step-size control (fast on the separable objectives the tuning surface
largely is; value-function-based optimization, arxiv 2011.14486,
motivates exactly this sample-efficient gradient-free loop).

Same determinism contract as learn.es: the generation-g draws come from
`np.random.default_rng([seed, g])`, so tell() regenerates the z it needs
instead of carrying it, and `state_dict()` is the full strategy state
(mean, sigma, diagonal C, both evolution paths) as JSON-exact floats —
a resumed run continues bit-identically.
"""

from __future__ import annotations

import math

import numpy as np


class DiagonalCMA:
    """Maximize f over R^d: ask(gen) -> [popsize, d], tell(gen, scores).

    Standard CMA constants (Hansen's tutorial) with the sep-CMA c_mu
    boost (d+2)/3; recombination over the top half with log weights."""

    algo = "cma"

    def __init__(self, x0, sigma: float = 250.0, popsize: int = 8,
                 seed: int = 0):
        self.mean = np.asarray(x0, np.float64).copy()
        if self.mean.ndim != 1:
            raise ValueError(f"x0 must be a vector, got shape {self.mean.shape}")
        d = self.mean.size
        if popsize < 4:
            raise ValueError(f"popsize must be >= 4, got {popsize}")
        self.popsize = int(popsize)
        self.seed = int(seed)
        self.sigma = float(sigma)

        mu = self.popsize // 2
        w = np.log(mu + 0.5) - np.log(np.arange(1, mu + 1))
        self.weights = w / w.sum()  # [mu], positive, sums to 1
        self.mu_eff = float(1.0 / (self.weights ** 2).sum())

        self.cs = (self.mu_eff + 2.0) / (d + self.mu_eff + 5.0)
        self.ds = (
            1.0
            + 2.0 * max(0.0, math.sqrt((self.mu_eff - 1.0) / (d + 1.0)) - 1.0)
            + self.cs
        )
        self.cc = (4.0 + self.mu_eff / d) / (d + 4.0 + 2.0 * self.mu_eff / d)
        self.c1 = 2.0 / ((d + 1.3) ** 2 + self.mu_eff)
        cmu = min(
            1.0 - self.c1,
            2.0 * (self.mu_eff - 2.0 + 1.0 / self.mu_eff)
            / ((d + 2.0) ** 2 + self.mu_eff),
        )
        # sep-CMA: the diagonal restriction frees degrees of freedom, so
        # the rank-mu rate grows by (d+2)/3 (Ros & Hansen eq. 4)
        self.cmu = min(1.0 - self.c1, cmu * (d + 2.0) / 3.0)
        self.chi_n = math.sqrt(d) * (1.0 - 1.0 / (4.0 * d)
                                     + 1.0 / (21.0 * d * d))

        self.C = np.ones(d, np.float64)  # diagonal covariance
        self.ps = np.zeros(d, np.float64)  # step-size path
        self.pc = np.zeros(d, np.float64)  # covariance path
        self.gens_told = 0  # drives the hsig normalizer

    def _z(self, gen: int) -> np.ndarray:
        rng = np.random.default_rng([self.seed, int(gen)])
        return rng.standard_normal((self.popsize, self.mean.size))

    def ask(self, gen: int) -> np.ndarray:
        y = self._z(gen) * np.sqrt(self.C)[None, :]
        return self.mean[None, :] + self.sigma * y

    def tell(self, gen: int, scores) -> None:
        scores = np.asarray(scores, np.float64)
        if scores.shape != (self.popsize,):
            raise ValueError(
                f"scores must have shape ({self.popsize},), got "
                f"{scores.shape}"
            )
        d = self.mean.size
        z = self._z(gen)
        y = z * np.sqrt(self.C)[None, :]
        # maximize: best first; stable sort keeps ties deterministic
        order = np.argsort(-scores, kind="stable")[: self.weights.size]
        yw = self.weights @ y[order]  # [d]
        zw = self.weights @ z[order]  # [d] == C^{-1/2} yw, diagonally

        self.mean = self.mean + self.sigma * yw

        self.ps = (1.0 - self.cs) * self.ps + math.sqrt(
            self.cs * (2.0 - self.cs) * self.mu_eff
        ) * zw
        self.gens_told += 1
        ps_norm = float(np.linalg.norm(self.ps))
        hsig = ps_norm / math.sqrt(
            1.0 - (1.0 - self.cs) ** (2.0 * self.gens_told)
        ) < (1.4 + 2.0 / (d + 1.0)) * self.chi_n
        self.pc = (1.0 - self.cc) * self.pc + (
            math.sqrt(self.cc * (2.0 - self.cc) * self.mu_eff) * yw
            if hsig else 0.0
        )

        rank_mu = self.weights @ (y[order] ** 2)  # diagonal rank-mu term
        self.C = (
            (1.0 - self.c1 - self.cmu) * self.C
            + self.c1 * (
                self.pc ** 2
                + (0.0 if hsig else self.cc * (2.0 - self.cc)) * self.C
            )
            + self.cmu * rank_mu
        )
        # numerical floor: a collapsed axis would freeze the draw there
        self.C = np.maximum(self.C, 1e-20)
        self.sigma = self.sigma * math.exp(
            (self.cs / self.ds) * (ps_norm / self.chi_n - 1.0)
        )

    # ---- resumable state (tuning-log vocabulary) ----

    def state_dict(self) -> dict:
        return {
            "algo": self.algo,
            "mean": [float(x) for x in self.mean],
            "sigma": float(self.sigma),
            "C": [float(x) for x in self.C],
            "ps": [float(x) for x in self.ps],
            "pc": [float(x) for x in self.pc],
            "gens_told": int(self.gens_told),
        }

    def load_state(self, state: dict) -> None:
        if state.get("algo") != self.algo:
            raise ValueError(
                f"state is for algo {state.get('algo')!r}, not {self.algo!r}"
            )
        mean = np.asarray(state["mean"], np.float64)
        if mean.shape != self.mean.shape:
            raise ValueError(
                f"state mean has shape {mean.shape}, expected "
                f"{self.mean.shape}"
            )
        self.mean = mean
        self.sigma = float(state["sigma"])
        self.C = np.asarray(state["C"], np.float64)
        self.ps = np.asarray(state["ps"], np.float64)
        self.pc = np.asarray(state["pc"], np.float64)
        self.gens_told = int(state["gens_told"])
