"""Rollout backends of the tuning loop (ISSUE 9): one interface, two
executions.

LocalRollout drives `Simulator.run_sweep` — the whole generation's
population is ONE vmapped compiled scan (ISSUE 6), and because the
weight vectors are traced operands, generation after generation reuses
the same executable: zero recompiles after generation 1. The lane count
is pinned to `width` (short/dedup'd populations repeat their tail row —
the svc worker's padding trick), so the vmap axis never changes size.

RemoteRollout turns a `tpusim serve --jobs` service into the rollout
farm ROADMAP names: each candidate row becomes a job document, submitted
through the backpressure-honoring client (svc.client) and read back from
the digest-signed results. The service's content-digest dedup makes
re-evaluated candidates (CMA revisiting a region, resumed runs) free.

Both backends return the SAME term dicts (learn.objective lane_terms /
terms_from_result), so a tuning log records identical bytes whichever
executed the rollouts — the acceptance contract.

Candidates live in the engines' i32 operand space: `project_weights`
rounds/clips the optimizer's float vectors, `dedup_rows` collapses
integer collisions so a generation never replays the same vector twice.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from tpusim.learn.objective import lane_terms, terms_from_result


def project_weights(xs, lo: int = 0, hi: int = 4000) -> np.ndarray:
    """Float candidates [B, d] -> the engines' i32 operand space:
    round-half-even, clip to [lo, hi]. Weight 0 disables a policy's
    contribution (the extender-config vocabulary allows it for plain
    score plugins), negative weights never reach the engines."""
    if hi <= lo:
        raise ValueError(f"need lo < hi, got [{lo}, {hi}]")
    return np.clip(np.rint(np.asarray(xs, np.float64)), lo, hi).astype(
        np.int32
    )


def dedup_rows(rows: np.ndarray) -> Tuple[List[tuple], List[int]]:
    """Integer candidate rows -> (unique rows in first-seen order,
    per-candidate index into them). Projection collapses nearby float
    candidates onto the same integer vector; rolling the collision out
    twice would waste a lane (or a remote job) to learn nothing."""
    uniq: List[tuple] = []
    index: dict = {}
    where: List[int] = []
    for row in np.asarray(rows, np.int32):
        key = tuple(int(w) for w in row)
        if key not in index:
            index[key] = len(uniq)
            uniq.append(key)
        where.append(index[key])
    return uniq, where


def make_family_sim(nodes, pods, policies, gpu_sel: str = "best",
                    norm: str = "max", dim_ext: str = "share",
                    engine: str = "auto", table_cache_dir: str = ""):
    """A Simulator configured EXACTLY like the service worker's per-family
    sims (svc.worker._sim_for): same knobs, deterministic prep, reporting
    off. Local tuning over a trace and remote tuning against a service
    hosting that trace then replay identical trajectories — the
    local-vs-remote log-identity contract reduces to the sweep-vs-sweep
    bit-identity tests/test_svc.py already pins."""
    from tpusim.sim.driver import Simulator, SimulatorConfig

    cfg = SimulatorConfig(
        policies=tuple((str(n), int(w)) for n, w in policies),
        gpu_sel_method=gpu_sel,
        norm_method=norm,
        dim_ext_method=dim_ext,
        engine=engine,
        report_per_event=False,
        shuffle_pod=False,
        seed=42,
        table_cache_dir=table_cache_dir,
    )
    sim = Simulator(nodes, cfg)
    sim.set_workload_pods(list(pods))
    return sim


class LocalRollout:
    """Vectorized local backend: rollout(rows, seed) -> term dicts via
    one `run_sweep` dispatch of exactly `width` lanes."""

    name = "local"

    def __init__(self, sim, width: int, bucket: int = 512, fault=None):
        """`fault` (ISSUE 10): a FaultConfig makes every generation's
        rollout a CHAOS sweep — the whole population replays under the
        same seeded fault schedule (common random disruption, like the
        shared eval seed), so the objective's w_disrupt term trains on
        in-scan DisruptionMetrics instead of a post-hoc robustness
        report. Still one compiled scan per generation: the schedule is
        a lane operand."""
        self.fault = fault
        self._init_common(sim, width, bucket)

    def _init_common(self, sim, width: int, bucket: int):
        if width < 1:
            raise ValueError(f"width must be >= 1, got {width}")
        if sim.cfg.heartbeat_every:
            # the sweep strips in-scan heartbeats by REBUILDING a
            # heartbeat-free engine per run_sweep call (driver), which
            # would both recompile every generation and make
            # executables() track the wrong wrapper — reject up front
            raise ValueError(
                "LocalRollout needs a heartbeat-free Simulator "
                "(heartbeat_every=0): the vmapped sweep rebuilds a "
                "fresh engine per call under heartbeat_every, paying a "
                "recompile every generation"
            )
        self.sim = sim
        self.width = int(width)
        self.bucket = int(bucket)
        self._fns: set = set()  # jitted sweep wrappers dispatched

    def rollout(self, rows: Sequence[tuple], seed: int) -> List[dict]:
        from tpusim.sim.driver import _sweep_engine, _sweep_fault_engine

        if not rows:
            return []
        if len(rows) > self.width:
            raise ValueError(
                f"{len(rows)} unique candidates exceed the backend width "
                f"{self.width}"
            )
        # pad to the fixed lane count by repeating the tail row: the vmap
        # axis size is jaxpr structure, so a dedup-shrunk generation must
        # not compile its own executable (the svc worker's discipline)
        padded = list(rows) + [rows[-1]] * (self.width - len(rows))
        w = np.asarray(padded, np.int32)
        faults = [self.fault] * self.width if self.fault else None
        lanes = self.sim.run_sweep(
            w, seeds=[int(seed)] * self.width, bucket=self.bucket,
            faults=faults,
        )[: len(rows)]
        # track the dispatched wrapper so executables() can assert the
        # zero-recompile contract (the svc worker's /queue metric)
        used_table = self.sim._last_engine.startswith("table")
        if self.fault:
            # the chaos-sweep dispatch stashes its jitted wrapper
            self._fns.add(self.sim._last_sweep_fn)
        else:
            self._fns.add(_sweep_engine(
                self.sim._table_fn.engine.replay if used_table
                else self.sim.replay_fn.engine,
                table=used_table,
            ))
        return [lane_terms(lane) for lane in lanes]

    def executables(self) -> int:
        """Compiled sweep executables dispatched by this backend — must
        sit at 1 for a whole tuning run (`make tune-smoke` hard-checks
        it via jit._cache_size())."""
        return sum(fn._cache_size() for fn in self._fns)


class RemoteRollout:
    """Service-backed backend: rollout(rows, seed) -> term dicts via the
    `tpusim submit` machinery against a `serve --jobs` endpoint."""

    name = "remote"

    def __init__(self, url: str, policies, trace: str = "default",
                 gpu_sel: str = "best", norm: str = "max",
                 dim_ext: str = "share", engine: str = "auto",
                 timeout: float = 600.0, out=None):
        self.url = url.rstrip("/")
        self.policies = [[str(n), int(w)] for n, w in policies]
        self.trace = trace
        self.gpu_sel = gpu_sel
        self.norm = norm
        self.dim_ext = dim_ext
        self.engine = engine
        self.timeout = float(timeout)
        self.out = out

    def rollout(self, rows: Sequence[tuple], seed: int) -> List[dict]:
        from tpusim.svc.client import submit_and_wait

        if not rows:
            return []
        docs = [
            {
                "trace": self.trace,
                "policies": self.policies,
                "weights": [int(w) for w in row],
                "seed": int(seed),
                "gpu_sel": self.gpu_sel,
                "norm": self.norm,
                "dim_ext": self.dim_ext,
                "engine": self.engine,
            }
            for row in rows
        ]
        results = submit_and_wait(
            self.url, docs, timeout=self.timeout, out=self.out
        )
        return [terms_from_result(r) for r in results]
