"""The tuning loop: optimizer-in-the-loop over the vectorized sweep
(ISSUE 9, `tpusim tune`).

Each generation: ask the optimizer for a float population, project it
onto the engines' i32 operand space, dedup integer collisions, roll the
unique candidates out through ONE backend call (local vmapped sweep or
remote job plane — learn.rollout), scalarize (learn.objective), tell the
optimizer, and append a generation record to the tuning log.

The log is digest-signed JSONL (io.storage.write_signed_jsonl — the
decisions-file torn-write discipline): a header naming the trajectory-
defining config, then one record per generation carrying the full
population, the unique rollouts' term dicts, every candidate's
objective, the best-so-far, and the optimizer's complete state. It is
the loop's only state: `resume=True` restores the optimizer from the
last record and continues — and because generation-g draws are a pure
function of (seed, g), the resumed run's log is BYTE-identical to an
uninterrupted one. Everything written is deterministic (sorted keys, no
walls, no paths, no backend identity), so a remote-backed run under the
same seed reproduces a local run's log bit-for-bit: the acceptance
contract.

The final held-out report replays tuned-vs-default on a trace suffix
the optimizer never saw (one 2-lane sweep) — the generalization check
that the tuned vector beats the paper-default weights off its own
training data.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from tpusim.learn.cma import DiagonalCMA
from tpusim.learn.es import OpenAIES
from tpusim.learn.objective import ObjectiveConfig, lane_terms, scalarize
from tpusim.learn.rollout import dedup_rows, project_weights

LOG_SCHEMA = "tpusim-tune-log/1"


@dataclass
class TuneConfig:
    """Knobs of one tuning run. Everything here except `generations`
    defines the trajectory and lands in the log header (a resumed run
    must match it exactly); `generations` is only the stopping point —
    extending a finished run is a legitimate resume."""

    algo: str = "es"  # es | cma
    generations: int = 10
    popsize: int = 8
    sigma: float = 250.0
    lr: float = 300.0  # es only (cma adapts its own step sizes)
    seed: int = 0  # optimizer draw seed
    eval_seed: int = 42  # replay seed every candidate shares (common
    # random numbers — candidates differ by weights only)
    w_lo: int = 0
    w_hi: int = 4000
    objective: ObjectiveConfig = field(default_factory=ObjectiveConfig)

    def canonical(self, policies) -> dict:
        """The log-header form: trajectory-defining knobs only, JSON-
        deterministic. No backend identity, no paths, no generation
        count — local/remote and short/extended runs must share it."""
        return {
            "algo": self.algo,
            "popsize": int(self.popsize),
            "sigma": float(self.sigma),
            "lr": float(self.lr),
            "seed": int(self.seed),
            "eval_seed": int(self.eval_seed),
            "w_lo": int(self.w_lo),
            "w_hi": int(self.w_hi),
            "objective": self.objective.canonical(),
            "policies": [[str(n), int(w)] for n, w in policies],
        }


@dataclass
class TuneResult:
    best_weights: List[int]
    best_objective: float
    records: List[dict]
    log_path: str
    report: Optional[dict] = None


def make_optimizer(cfg: TuneConfig, x0):
    if cfg.algo == "es":
        return OpenAIES(x0, sigma=cfg.sigma, lr=cfg.lr,
                        popsize=cfg.popsize, seed=cfg.seed)
    if cfg.algo == "cma":
        return DiagonalCMA(x0, sigma=cfg.sigma, popsize=cfg.popsize,
                           seed=cfg.seed)
    raise ValueError(f"unknown algo {cfg.algo!r}: expected es | cma")


def write_log(log_path: str, header_cfg: dict, records: List[dict]) -> str:
    """Rewrite the whole signed log atomically (records are small — a
    few KB per generation; rewriting keeps the signature covering every
    line, so a torn tail can never read back as a shorter valid run)."""
    from tpusim.io import storage

    header = {"schema": LOG_SCHEMA, "config": header_cfg}
    lines = [
        json.dumps(r, sort_keys=True, separators=(",", ":"))
        for r in records
    ]
    return storage.write_signed_jsonl(log_path, header, lines)


def read_log(log_path: str):
    """(header, records) from a tuning log; torn/edited files raise."""
    from tpusim.io import storage

    header, payload = storage.read_signed_jsonl(log_path, LOG_SCHEMA)
    return header, [json.loads(line) for line in payload]


def run_tune(backend, policies, cfg: TuneConfig, log_path: str,
             resume: bool = False, robust_eval=None, robust_meta=None,
             train_fault_meta=None, out=None) -> TuneResult:
    """The generation loop (see module docstring). `backend` is a
    learn.rollout backend; `robust_eval` an optional callable
    (weights) -> terms re-running the generation's best candidate under
    injected faults (objective.make_robust_eval) — logged, never fed
    back into the optimizer (disruption robustness is a report by
    default; `tpusim tune --train-fault-*` instead rolls the whole
    population through the chaos sweep so w_disrupt trains directly,
    ISSUE 10). `robust_meta` / `train_fault_meta` describe the
    evaluator's / training schedule's knobs for the log header:
    both shape the log's bytes, so a resume that toggles or retunes
    them must fail the config check instead of appending records of a
    different shape."""
    header_cfg = cfg.canonical(policies)
    if (robust_eval is not None) or (robust_meta is not None):
        header_cfg["robust"] = robust_meta if robust_meta is not None \
            else True
    if train_fault_meta is not None:
        header_cfg["train_fault"] = train_fault_meta
    x0 = np.asarray([float(w) for _, w in policies], np.float64)
    opt = make_optimizer(cfg, x0)

    records: List[dict] = []
    start_gen = 0
    if resume and os.path.isfile(log_path):
        header, records = read_log(log_path)
        if header.get("config") != header_cfg:
            raise ValueError(
                f"{log_path}: existing log was tuned under a different "
                "config — resume needs identical algo/popsize/sigma/lr/"
                "seed/bounds/objective/policies/robust knobs (delete the "
                "log or match the flags)"
            )
        if records:
            opt.load_state(records[-1]["state"])
            start_gen = int(records[-1]["gen"]) + 1
            if out is not None:
                print(
                    f"[tune] resumed at generation {start_gen} from "
                    f"{log_path}", file=out,
                )

    best_obj = -float("inf")
    best_w: List[int] = [int(w) for _, w in policies]
    for r in records:
        if r["best"]["objective"] > best_obj:
            best_obj = r["best"]["objective"]
            best_w = list(r["best"]["weights"])

    for gen in range(start_gen, cfg.generations):
        xs = opt.ask(gen)
        rows = project_weights(xs, cfg.w_lo, cfg.w_hi)
        uniq, where = dedup_rows(rows)
        terms = backend.rollout(uniq, cfg.eval_seed)
        objs_u = [scalarize(t, cfg.objective) for t in terms]
        objs = [objs_u[where[i]] for i in range(cfg.popsize)]
        opt.tell(gen, np.asarray(objs, np.float64))

        gi = int(np.argmax(objs_u))
        gen_best = {"weights": list(uniq[gi]), "objective": objs_u[gi]}
        if gen_best["objective"] > best_obj:
            best_obj = gen_best["objective"]
            best_w = list(uniq[gi])

        rec = {
            "gen": gen,
            "population": [[int(w) for w in row] for row in rows],
            "unique": [list(u) for u in uniq],
            "candidate_unique": list(where),
            "terms": terms,
            "objectives": objs,
            "gen_best": gen_best,
            "best": {"weights": list(best_w), "objective": best_obj},
            "state": opt.state_dict(),
        }
        if robust_eval is not None:
            rterms = robust_eval(gen_best["weights"])
            rec["robust"] = {
                "terms": rterms,
                "objective": scalarize(rterms, cfg.objective),
            }
        records.append(rec)
        write_log(log_path, header_cfg, records)
        if out is not None:
            line = (
                f"[tune] gen {gen:>3}: best {gen_best['objective']:+.4f} "
                f"(weights {','.join(str(w) for w in gen_best['weights'])})"
                f"  best-so-far {best_obj:+.4f}"
                f"  [{len(uniq)}/{cfg.popsize} unique]"
            )
            if "robust" in rec:
                line += f"  robust {rec['robust']['objective']:+.4f}"
            print(line, file=out)

    return TuneResult(
        best_weights=list(best_w), best_objective=best_obj,
        records=records, log_path=log_path,
    )


# ---------------------------------------------------------------------------
# Supervised imitation of a teacher policy (ISSUE 14, `tpusim imitate`)
# ---------------------------------------------------------------------------


@dataclass
class ImitateConfig:
    """Knobs of the imitation trainer: full-batch Adam on the pairwise
    ranking loss over (winner, runner-up) feature rows. Pure numpy
    float64 — deterministic for a fixed seed, no device round trips
    (the data is a few thousand tiny rows).

    tie_w weighs the TIE-preservation term: pairs the teacher decided
    by rank (equal teacher totals) contribute (theta . d)^2 — breaking
    a teacher tie with an irrelevant feature overrides the rank order
    the engines reproduce for free, and is the dominant way a blended
    theta loses top-1 agreement."""

    steps: int = 500
    lr: float = 0.15
    l2: float = 1e-4
    tie_w: float = 1.0
    seed: int = 0
    theta_hi: int = 4000  # |theta| bound of the i32 export


def project_theta(theta, hi: int = 4000) -> List[int]:
    """Float parameters -> the engines' i32 operand space. The argmax is
    scale-invariant, so the vector is rescaled to fill [-hi, hi] before
    rounding — the export keeps as much ranking resolution as the i32
    vocabulary allows."""
    theta = np.asarray(theta, np.float64)
    m = float(np.max(np.abs(theta))) if theta.size else 0.0
    scale = (hi / m) if m > 0 else 1.0
    return [int(t) for t in
            np.clip(np.rint(theta * scale), -hi, hi).astype(np.int64)]


def run_imitation(pairs, cfg: ImitateConfig = None, out=None):
    """Train theta on the pairwise constraints of a teacher log:

      strict pairs (teacher totals differed)
          softplus(-(theta . d))      -- rank pos above neg
      tie pairs (teacher decided by rank)
          tie_w * (theta . d)^2       -- PRESERVE the tie

    with d = x_pos - x_neg, plus l2 |theta|^2. Returns (theta
    float64[F], theta_i32 list) — the i32 export is what replays (and
    what the agreement metric scores). Identical-feature rows never
    reach here (TeacherReplay.pairs drops them; the engines' shared
    tie-break rank reproduces those decisions for free)."""
    cfg = cfg or ImitateConfig()
    pos = np.asarray(pairs.pos, np.float64)
    neg = np.asarray(pairs.neg, np.float64)
    tie = np.asarray(
        getattr(pairs, "tie", np.zeros(pos.shape[0], bool)), bool
    )
    if pos.shape[0] == 0:
        raise ValueError(
            "no trainable imitation pairs (every recorded runner-up "
            "tied the winner feature-for-feature)"
        )
    # features live in [0, 100]; train at unit scale for conditioning
    d = (pos[~tie] - neg[~tie]) / 100.0  # [Ms, F] strict
    dt = (pos[tie] - neg[tie]) / 100.0  # [Mt, F] tie-preserving
    f = pos.shape[1]
    rng = np.random.default_rng(cfg.seed)
    theta = 0.01 * rng.standard_normal(f)
    m1 = np.zeros(f)
    m2 = np.zeros(f)
    b1, b2, eps = 0.9, 0.999, 1e-8
    for t in range(1, cfg.steps + 1):
        grad = 2.0 * cfg.l2 * theta
        z = np.zeros(0)
        if d.shape[0]:
            z = d @ theta
            sig = 1.0 / (1.0 + np.exp(np.clip(z, -60, 60)))  # sigma(-z)
            grad = grad - (sig[:, None] * d).mean(0)
        if dt.shape[0]:
            zt = dt @ theta
            grad = grad + 2.0 * cfg.tie_w * (zt[:, None] * dt).mean(0)
        m1 = b1 * m1 + (1 - b1) * grad
        m2 = b2 * m2 + (1 - b2) * grad * grad
        mh = m1 / (1 - b1 ** t)
        vh = m2 / (1 - b2 ** t)
        theta = theta - cfg.lr * mh / (np.sqrt(vh) + eps)
        if out is not None and (t % max(cfg.steps // 5, 1) == 0):
            loss = float(np.mean(np.logaddexp(0.0, -z))) if z.size else 0.0
            acc = float((z > 0).mean()) if z.size else 1.0
            print(
                f"[imitate] step {t:>5}: loss {loss:.4f}  pairwise "
                f"acc {acc:.3f}", file=out,
            )
    # rescale back to the raw-feature space before the i32 export (the
    # /100 training scale cancels in the argmax either way)
    return theta / 100.0, project_theta(theta, cfg.theta_hi)


# ---------------------------------------------------------------------------
# Held-out report: tuned vs paper-default on the trace suffix
# ---------------------------------------------------------------------------


def holdout_report(eval_sim, policies, tuned_weights,
                   objective: ObjectiveConfig = None,
                   eval_seed: int = 42, bucket: int = 512) -> dict:
    """Replay tuned-vs-default weight vectors over `eval_sim`'s workload
    (the held-out trace suffix) in one 2-lane sweep and scalarize both.
    Returns {"tuned": terms+objective, "default": ..., "improvement"}."""
    objective = objective or ObjectiveConfig()
    default_w = [int(w) for _, w in policies]
    rows = np.asarray([list(tuned_weights), default_w], np.int32)
    lanes = eval_sim.run_sweep(
        rows, seeds=[int(eval_seed)] * 2, bucket=bucket
    )
    out = {}
    for label, lane in zip(("tuned", "default"), lanes):
        terms = lane_terms(lane)
        out[label] = dict(terms, objective=scalarize(terms, objective))
    out["improvement"] = out["tuned"]["objective"] - out["default"]["objective"]
    return out


def format_holdout_report(report: dict, policies) -> str:
    """Terminal table of the held-out comparison — the `tpusim tune`
    epilogue."""
    names = ",".join(n for n, _ in policies)
    head = (
        f"{'config':<9} {'weights(' + names + ')':<32} {'placed':>7} "
        f"{'unsched':>8} {'gpu_alloc%':>10} {'frag_gpu_milli':>15} "
        f"{'objective':>11}"
    )
    rows = [head, "-" * len(head)]
    for label in ("tuned", "default"):
        t = report[label]
        rows.append(
            f"{label:<9} {','.join(str(w) for w in t['weights']):<32} "
            f"{t['placed']:>7} {t['unscheduled']:>8} "
            f"{t['gpu_alloc_pct']:>10.2f} {t['frag_gpu_milli']:>15.0f} "
            f"{t['objective']:>+11.4f}"
        )
    verdict = (
        "tuned beats default" if report["improvement"] > 0
        else "tuned does NOT beat default"
    )
    rows.append(
        f"held-out improvement: {report['improvement']:+.4f} ({verdict})"
    )
    return "\n".join(rows)
