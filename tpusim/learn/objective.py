"""Scalarized tuning objective over sweep-lane outputs (ISSUE 9).

The paper's own quality metrics are the objective: GPU allocation up,
FGD fragmentation down, unscheduled pods bounded ("Learning to Score",
arxiv 2603.10545, tunes score weights against exactly these). Every term
is already on a `SweepLane` (driver.schedule_pods_sweep) and on a
service result document (svc.worker.summarize_lane), so one rollout —
local vmapped sweep or remote `tpusim submit` loop — yields the same
scalar bit-for-bit:

    J(w) = w_alloc * gpu_alloc_pct
         - w_frag  * frag_pct           (frag gpu-milli / cluster GPU milli)
         - w_unsched * unsched_pct      (unscheduled pods / trace pods)

All three terms are percentages, so the default 1/1/1 weighting is
already scale-sane; the knobs exist because an operator who cares more
about disruption than packing should not have to edit code.

The optional robustness evaluator re-runs a candidate through
`Simulator.run_with_faults` (seeded disruption, ISSUE 2) and scores the
same objective on the faulted outcome — the per-generation held-out
check of the tuning loop.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class ObjectiveConfig:
    """Term weights of the scalarized objective (all terms in percent).

    w_disrupt (ISSUE 10) charges pods PERMANENTLY lost to disruption
    (max-retries-exceeded under a fault schedule) — trainable now that
    fault schedules are sweep operands (the chaos sweep rolls a whole
    population through one faulted compiled scan). 0 keeps the
    pre-fault objective AND the pre-fault log-header bytes (old tuning
    logs stay resumable)."""

    w_alloc: float = 1.0
    w_frag: float = 1.0
    w_unsched: float = 1.0
    w_disrupt: float = 0.0

    def canonical(self) -> list:
        """Deterministic JSON form for the tuning-log header. The
        disruption weight joins only when non-zero so pre-chaos logs
        keep their exact header bytes."""
        base = [float(self.w_alloc), float(self.w_frag),
                float(self.w_unsched)]
        if self.w_disrupt:
            base.append(float(self.w_disrupt))
        return base


def lane_terms(lane) -> dict:
    """SweepLane -> the objective's term dict. Keys and value types match
    terms_from_result exactly (the local-vs-remote bit-identity contract
    of the tuning log): plain ints and floats, JSON-stable."""
    from tpusim.constants import MILLI

    pn = np.asarray(lane.placed_node, np.int32)
    dm = np.asarray(lane.dev_mask, bool)
    h = hashlib.sha256()
    h.update(pn.tobytes())
    h.update(dm.tobytes())
    dis = getattr(lane, "disruption", None)
    return {
        "weights": [int(w) for w in lane.weights],
        "seed": int(lane.seed),
        "events": int(lane.events),
        "pods": int(pn.shape[0]),
        "placed": int(lane.placed),
        "failed": int(lane.failed),
        "unscheduled": int(lane.unscheduled),
        # chaos-sweep lanes (ISSUE 10): pods terminally lost to
        # disruption + total evictions; 0 on fault-free lanes so the
        # vocabulary is one dict either way
        "disrupted": int(dis.unscheduled_after_retries) if dis else 0,
        "evicted": int(dis.evicted_pods) if dis else 0,
        "gpu_total_milli": int(
            np.asarray(lane.state.gpu_cnt, np.int64).sum()
        ) * MILLI,
        "gpu_alloc_pct": float(lane.gpu_alloc_pct),
        "frag_gpu_milli": float(lane.frag_gpu_milli),
        "placements_sha256": h.hexdigest(),
    }


def terms_from_result(doc: dict) -> dict:
    """Service result document (svc.worker.summarize_lane) -> the same
    term dict lane_terms builds locally. JSON floats round-trip exactly
    (repr-faithful), so a remote rollout's terms are byte-identical to
    the local lane's in the tuning log."""
    return {
        "weights": [int(w) for w in doc["weights"]],
        "seed": int(doc["seed"]),
        "events": int(doc["events"]),
        "pods": int(doc["pods"]),
        "placed": int(doc["placed"]),
        "failed": int(doc["failed"]),
        "unscheduled": int(doc["unscheduled"]),
        # absent on pre-chaos service results -> the fault-free value
        "disrupted": int(doc.get("disrupted", 0)),
        "evicted": int(doc.get("evicted", 0)),
        "gpu_total_milli": int(doc["gpu_total_milli"]),
        "gpu_alloc_pct": float(doc["gpu_alloc_pct"]),
        "frag_gpu_milli": float(doc["frag_gpu_milli"]),
        "placements_sha256": str(doc["placements_sha256"]),
    }


def scalarize(terms: dict, cfg: ObjectiveConfig = None) -> float:
    """One term dict -> the scalar objective J(w) (maximize)."""
    cfg = cfg or ObjectiveConfig()
    frag_pct = 100.0 * terms["frag_gpu_milli"] / max(
        terms["gpu_total_milli"], 1
    )
    unsched_pct = 100.0 * terms["unscheduled"] / max(terms["pods"], 1)
    disrupt_pct = 100.0 * terms.get("disrupted", 0) / max(terms["pods"], 1)
    return (
        cfg.w_alloc * terms["gpu_alloc_pct"]
        - cfg.w_frag * frag_pct
        - cfg.w_unsched * unsched_pct
        - cfg.w_disrupt * disrupt_pct
    )


def terms_from_simulate(res, total_gpu_milli: int, typical) -> dict:
    """SimulateResult -> the same term vocabulary, for runs that did not
    go through the sweep (the robustness evaluator's run_with_faults
    outcome). Recomputes gpu_alloc/frag from the final state exactly as
    _slice_sweep_lane does."""
    from tpusim.constants import MILLI
    from tpusim.ops.frag import cluster_frag_amounts, frag_sum_except_q3

    import jax

    st = jax.tree.map(np.asarray, res.state)
    slot = (
        np.arange(st.gpu_left.shape[1])[None, :] < st.gpu_cnt[:, None]
    )
    # DOWN nodes park at the mem_left = -1 sentinel with gpu_left zeroed;
    # their slots read as fully allocated, which is what the disruption
    # objective should see (capacity lost to faults is not free capacity)
    denom = max(int(st.gpu_cnt.sum()) * MILLI, 1)
    alloc = 100.0 * float(
        np.where(slot, MILLI - st.gpu_left, 0).sum()
    ) / denom
    amounts = np.asarray(cluster_frag_amounts(res.state, typical).sum(0))
    pn = np.asarray(res.placed_node, np.int32)
    return {
        "weights": [],  # stamped by the caller (the candidate's vector)
        "seed": -1,
        "events": int(res.events),
        "pods": int(pn.shape[0]),
        "placed": int((pn >= 0).sum()),
        "failed": len(res.unscheduled_pods),
        "unscheduled": len(res.unscheduled_pods),
        "gpu_total_milli": int(total_gpu_milli),
        "gpu_alloc_pct": alloc,
        "frag_gpu_milli": float(frag_sum_except_q3(amounts)),
        "placements_sha256": hashlib.sha256(pn.tobytes()).hexdigest(),
    }


def make_robust_eval(nodes, workload_pods, policies, fault_cfg,
                     base_cfg=None):
    """Build the optional per-generation robustness evaluator: a callable
    (weights) -> (terms, objective-ready dict) that replays the workload
    through `run_with_faults` with the candidate weights baked into a
    fresh Simulator config (weights are traced operands since ISSUE 6,
    so the per-candidate Simulator shares the cached engines — no
    recompile) under the SAME seeded fault schedule every generation.
    Local-trace mode only: the remote job plane has no fault operands
    yet (ROADMAP names that lift)."""
    from tpusim.sim.driver import Simulator, SimulatorConfig

    base = base_cfg or SimulatorConfig()

    def evaluate(weights) -> dict:
        cfg = SimulatorConfig(
            policies=tuple(
                (name, int(w)) for (name, _), w in zip(policies, weights)
            ),
            gpu_sel_method=base.gpu_sel_method,
            norm_method=base.norm_method,
            dim_ext_method=base.dim_ext_method,
            engine=base.engine,
            seed=base.seed,
            report_per_event=False,
            shuffle_pod=False,
        )
        sim = Simulator(nodes, cfg)
        sim.set_workload_pods(list(workload_pods))
        res = sim.run_with_faults(fault_cfg)
        terms = terms_from_simulate(
            res, sim.node_total_milli_gpu, sim.typical
        )
        terms["weights"] = [int(w) for w in weights]
        terms["seed"] = int(base.seed)
        return terms

    return evaluate
