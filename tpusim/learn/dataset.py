"""DecisionRecord JSONLs -> imitation data for the learned policy
(ISSUE 14, `tpusim imitate`).

A PR 4 decision log is ready-made credit-assignment data: per create
event it names the teacher's chosen node AND the top-K runner-ups (with
totals and tie-break ranks). What it does not carry is the per-node
FEATURE rows the learned policy scores with — those are a function of
the cluster state at the decision, which this module reconstructs by
TEACHER-FORCING the trace: one compiled lax.scan walks the event
stream, binds every create to the RECORDED node (reproducing the
teacher's state trajectory exactly, including the Reserve-phase device
choice under the recorded gpu_sel), and at each step emits

  - the feature rows of the winner and the recorded runner-ups
    -> (feature-row, chosen-node, runner-up) imitation tuples, and
  - the LEARNED policy's own argmax at the teacher's state under a
    traced theta operand -> teacher-forced top-1 agreement, evaluable
    for many thetas on ONE compiled executable.

The features come out of the same `sim.step.score_pod_rows` the engines
select with (the learned kernels, weights = theta), so a projected i32
theta's agreement HERE is exactly what a real engine replay would
choose at those states — the imitation -> export -> replay chain has no
approximation step.

Sanity contract: at every create event the reconstructed Filter-phase
feasible count must equal the recorded one; a mismatch means the trace
or prep options do not match the log and raises instead of silently
training on wrong features.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from tpusim.learn.policy import (
    LINEAR_FEATURES,
    learned_policies,
    parse_learned_name,
)

DATASET_GPU_SEL = ("best", "worst") + tuple(
    # policy-delegated device picks are reproduced by evaluating the
    # selector kernel at the recorded node; per-event randomness is not
    # (the log does not carry the PRNG chain's draws)
    ("FGDScore", "PWRScore", "DotProductScore")
)


@dataclass
class ImitationPairs:
    """The (feature-row, chosen-node, runner-up) tuples of one log:
    pair i says 'the teacher ranked pos[i] above neg[i]' — STRICTLY when
    tie[i] is False (the teacher's totals differed), and 'the teacher
    considered them EQUAL' when tie[i] is True (identical teacher totals,
    decided by the tie-break rank). Tie pairs matter as much as strict
    ones: a learned theta that breaks a teacher tie with an irrelevant
    feature overrides the rank order the engines would otherwise
    reproduce for free, so the trainer drives theta . (pos - neg) -> 0
    on them. Rows whose winner and runner-up carry IDENTICAL features
    appear in neither set (no constraint to learn)."""

    features: Tuple[str, ...]
    pos: np.ndarray  # f64[M, F] winner feature rows
    neg: np.ndarray  # f64[M, F] runner-up feature rows
    event: np.ndarray  # i64[M] source event index of each pair
    tie: np.ndarray  # bool[M] teacher totals tied (rank-decided pair)


class TeacherReplay:
    """One decision log + its trace, compiled for feature extraction and
    teacher-forced evaluation. theta is a traced operand of the scan, so
    `agreement` over many candidate vectors reuses one executable."""

    def __init__(self, nodes, pods, header: dict, rows: List[dict],
                 features: Sequence[str] = LINEAR_FEATURES,
                 gpu_sel: str = "", seed: Optional[int] = None):
        import jax
        import jax.numpy as jnp

        from tpusim.io.trace import (
            build_events,
            nodes_to_state,
            pods_to_specs,
            tiebreak_rank,
        )
        from tpusim.obs.decisions import DECISION_TOPK
        from tpusim.policies import make_policy
        from tpusim.sim.typical import (
            TypicalPodsConfig,
            get_typical_pods,
            pad_typical_pods,
        )

        meta = header.get("meta") or {}
        self.features = tuple(features)
        self.policies = learned_policies(features=self.features)
        self.gpu_sel = gpu_sel or str(meta.get("gpu_sel", "best"))
        if self.gpu_sel not in DATASET_GPU_SEL:
            raise ValueError(
                f"gpu_sel {self.gpu_sel!r} cannot be replayed from a "
                "decision log (per-event random device draws are not "
                f"recorded); supported: {', '.join(DATASET_GPU_SEL)}"
            )
        self.seed = int(meta.get("seed", 42) if seed is None else seed)

        node_index = {n.name: i for i, n in enumerate(nodes)}
        self.state0 = nodes_to_state(nodes)
        self.specs = pods_to_specs(pods, node_index)
        ev_kind, ev_pod = build_events(pods, False)
        if len(ev_kind) != len(rows):
            raise ValueError(
                f"decision log has {len(rows)} events but the trace "
                f"builds {len(ev_kind)} — wrong trace or prep options "
                "(max_pods / shuffle must match the recorded run)"
            )
        self.ev_kind = np.asarray(ev_kind, np.int32)
        self.ev_pod = np.asarray(ev_pod, np.int32)
        self.rec_node = np.asarray([r["node"] for r in rows], np.int32)
        self.rec_feas = np.asarray([r["feasible"] for r in rows], np.int32)
        topk = np.full((len(rows), DECISION_TOPK), -1, np.int32)
        topk_total = np.zeros((len(rows), DECISION_TOPK), np.int64)
        for i, r in enumerate(rows):
            for j, (n, t, _rk) in enumerate(r.get("topk", [])):
                if j < DECISION_TOPK:
                    topk[i, j] = int(n)
                    topk_total[i, j] = int(t)
        self.topk = topk
        self.topk_total = topk_total
        # the recorded run's typical-pod distribution (the driver's
        # set_typical_pods path: histogram + bucket padding — zero-freq
        # pad rows are exact no-ops in every frag kernel)
        self.typical = pad_typical_pods(
            get_typical_pods(pods, TypicalPodsConfig())[0]
        )
        n = self.state0.num_nodes
        self.rank = jnp.asarray(tiebreak_rank(n, self.seed))

        pols = [
            (make_policy(name), w) for name, w in self.policies
        ]
        sel_fn = (
            make_policy(self.gpu_sel)
            if self.gpu_sel not in ("best", "worst") else None
        )
        specs = self.specs
        tp = self.typical
        rank = self.rank
        gpu_sel = self.gpu_sel
        num_pods = int(specs.cpu.shape[0])
        k = DECISION_TOPK

        def body(carry, ev):
            from tpusim.policies import ScoreContext
            from tpusim.sim.step import (
                bind_selected,
                packed_argmax,
                score_pod_rows,
                unschedule,
            )
            from tpusim.sim.engine import Placement

            state, placed, masks, key, theta = carry
            kind, idx, rec, tk = ev
            # the engines' per-event key-split discipline (unconsumed
            # here unless the selector draws, which DATASET_GPU_SEL
            # excludes — kept so the chain stays comparable)
            key, sub = jax.random.split(key)
            k_rand, k_sel = jax.random.split(sub)
            pod = jax.tree.map(lambda a: a[idx], specs)

            feasible, total, _, raws, _ = score_pod_rows(
                state, pod, k_rand, pols, gpu_sel, tp, weights=theta
            )
            pick, _, ok = packed_argmax(total, feasible, rank)
            pick = jnp.where(ok, pick, -1).astype(jnp.int32)
            # feature rows of the recorded top-K candidates
            sel = jnp.clip(tk, 0, state.num_nodes - 1)
            feats = jnp.where(
                (tk >= 0)[:, None], raws[:, sel].T, -1
            ).astype(jnp.int32)  # [K, F]

            # teacher-forced transition: bind the RECORDED winner
            is_create = kind == 0
            is_delete = kind == 1
            node = jnp.clip(rec, 0, state.num_nodes - 1)
            okb = is_create & (rec >= 0)
            if sel_fn is not None:
                from tpusim.sim.table_engine import _row_state

                row = _row_state(state, node)
                ctx1 = ScoreContext(
                    tp=tp, feasible=jnp.ones(1, jnp.bool_), rng=k_rand
                )
                pdev = sel_fn(row, pod, ctx1).share_dev[0]
            else:
                pdev = jnp.int32(-1)
            state, plc = bind_selected(
                state, pod, node, okb, pdev, gpu_sel, k_sel
            )
            # delete: return the recorded placement's resources
            del_node = jnp.where(is_delete, placed[idx], -1)
            state = unschedule(
                state, pod, Placement(del_node, masks[idx])
            )
            placed = placed.at[idx].set(
                jnp.where(okb, plc.node,
                          jnp.where(is_delete, -1, placed[idx]))
            )
            masks = masks.at[idx].set(
                jnp.where(okb, plc.dev_mask,
                          jnp.where(is_delete, False, masks[idx]))
            )
            # the learned pick's own feature row — hard-negative fuel
            # for the mining rounds (mined_pairs)
            pick_feats = raws[:, jnp.maximum(pick, 0)].astype(jnp.int32)
            ys = (
                feats, feasible.sum().astype(jnp.int32), pick, pick_feats,
            )
            return (state, placed, masks, key, theta), ys

        from tpusim.constants import MAX_GPUS_PER_NODE

        @jax.jit
        def scan(theta):
            placed0 = jnp.full(num_pods, -1, jnp.int32)
            masks0 = jnp.zeros((num_pods, MAX_GPUS_PER_NODE), jnp.bool_)
            carry0 = (
                self.state0, placed0, masks0,
                jax.random.PRNGKey(self.seed), theta,
            )
            _, ys = jax.lax.scan(
                body, carry0,
                (jnp.asarray(self.ev_kind), jnp.asarray(self.ev_pod),
                 jnp.asarray(self.rec_node), jnp.asarray(self.topk)),
            )
            return ys

        self._scan = scan
        self._jnp = jnp
        self._cache = None  # (theta tuple) -> host ys of the last scan

    def _run(self, theta) -> tuple:
        key = tuple(int(t) for t in theta)
        if self._cache is None or self._cache[0] != key:
            ys = self._scan(
                self._jnp.asarray(np.asarray(theta, np.int32))
            )
            self._cache = (key, tuple(np.asarray(y) for y in ys))
        return self._cache[1]

    def _check_feasible(self, feas: np.ndarray):
        creates = self.ev_kind == 0
        bad = creates & (feas != self.rec_feas)
        if bad.any():
            i = int(np.flatnonzero(bad)[0])
            raise ValueError(
                f"event {i}: reconstructed feasible count {int(feas[i])} "
                f"!= recorded {int(self.rec_feas[i])} — the trace/config "
                "does not match the decision log"
            )

    def pairs(self) -> ImitationPairs:
        """The imitation tuples: one (winner, runner-up) pair per
        recorded runner-up of every successful create event. Pairs whose
        teacher totals TIED carry tie=True — the teacher decided those
        by rank, so the trainer preserves the tie instead of learning to
        break it. Identical-feature rows are dropped (no constraint)."""
        theta0 = [w for _, w in self.policies]
        feats, feas, _, _ = self._run(theta0)
        self._check_feasible(feas)
        pos, neg, evs, ties = [], [], [], []
        creates = np.flatnonzero((self.ev_kind == 0) & (self.rec_node >= 0))
        for i in creates:
            if self.topk[i, 0] < 0:
                continue
            win = feats[i, 0].astype(np.float64)
            for j in range(1, self.topk.shape[1]):
                if self.topk[i, j] < 0:
                    continue
                run = feats[i, j].astype(np.float64)
                if np.array_equal(win, run):
                    continue
                pos.append(win)
                neg.append(run)
                evs.append(i)
                ties.append(
                    bool(self.topk_total[i, j] == self.topk_total[i, 0])
                )
        f = len(self.features)
        return ImitationPairs(
            features=self.features,
            pos=(np.stack(pos) if pos else np.zeros((0, f))),
            neg=(np.stack(neg) if neg else np.zeros((0, f))),
            event=np.asarray(evs, np.int64),
            tie=np.asarray(ties, bool),
        )

    def mined_pairs(self, theta, end_event: Optional[int] = None
                    ) -> ImitationPairs:
        """Hard-negative mining (the structured-perceptron move): replay
        under candidate `theta`, and wherever the learned argmax differs
        from the teacher's choice emit a (teacher-winner, learned-pick)
        pair. The recorded top-K negatives alone cannot constrain nodes
        outside the top-K; mining adds exactly the violated constraints,
        so a few train->mine->retrain rounds converge the global argmax
        onto the teacher's. Identical-feature mismatches are dropped
        (unlearnable: the shared tie-break rank owns those)."""
        feats, feas, pick, pick_feats = self._run(theta)
        self._check_feasible(feas)
        end = len(self.ev_kind) if end_event is None else int(end_event)
        pos, neg, evs = [], [], []
        creates = np.flatnonzero(
            (self.ev_kind[:end] == 0) & (self.rec_node[:end] >= 0)
        )
        for i in creates:
            if pick[i] < 0 or pick[i] == self.rec_node[i]:
                continue
            win = feats[i, 0].astype(np.float64)  # topk[0] IS the winner
            run = pick_feats[i].astype(np.float64)
            if self.topk[i, 0] < 0 or np.array_equal(win, run):
                continue
            pos.append(win)
            neg.append(run)
            evs.append(i)
        f = len(self.features)
        return ImitationPairs(
            features=self.features,
            pos=(np.stack(pos) if pos else np.zeros((0, f))),
            neg=(np.stack(neg) if neg else np.zeros((0, f))),
            event=np.asarray(evs, np.int64),
            tie=np.zeros(len(evs), bool),
        )

    def agreement(self, theta, start_event: int = 0,
                  end_event: Optional[int] = None) -> dict:
        """Teacher-forced top-1 agreement of integer parameter vector
        `theta` over events in [start_event, end_event): at each teacher
        state, does the learned argmax (the engines' packed_argmax over
        sum theta_f * feature_f with the shared tie-break rank) pick the
        teacher's node? The ONE metric implementation — the training
        loop scores its prefix with end_event, holdout reports with
        start_event — and every call runs the feasible-count
        cross-check. Returns {'matches', 'creates', 'agreement'}."""
        feats, feas, pick, _ = self._run(theta)
        self._check_feasible(feas)
        creates = (self.ev_kind == 0) & (self.rec_node >= 0)
        creates[:start_event] = False
        if end_event is not None:
            creates[int(end_event):] = False
        n = int(creates.sum())
        m = int((pick[creates] == self.rec_node[creates]).sum())
        return {
            "matches": m,
            "creates": n,
            "agreement": (m / n) if n else 1.0,
        }


def concat_pairs(parts: Sequence[ImitationPairs]) -> ImitationPairs:
    parts = [p for p in parts if p.pos.shape[0]]
    if not parts:
        raise ValueError("no imitation pairs to train on")
    return ImitationPairs(
        features=parts[0].features,
        pos=np.concatenate([p.pos for p in parts]),
        neg=np.concatenate([p.neg for p in parts]),
        event=np.concatenate([p.event for p in parts]),
        tie=np.concatenate([p.tie for p in parts]),
    )


def imitate_with_mining(replay: TeacherReplay, cfg=None,
                        end_event: Optional[int] = None,
                        rounds: int = 6, out=None):
    """The full imitation recipe (`tpusim imitate`): fit on the recorded
    (winner, runner-up) pairs, then alternate train -> mine hard
    negatives (events where the learned argmax still disagrees with the
    teacher, restricted to the TRAINING prefix `end_event`) -> retrain,
    until agreement stops improving or `rounds` is exhausted. Returns
    (theta float64, theta_i32 list, per-round train agreement)."""
    from tpusim.learn.loop import project_theta, run_imitation

    end = len(replay.ev_kind) if end_event is None else int(end_event)
    base = replay.pairs()
    keep = base.event < end
    pool = [ImitationPairs(base.features, base.pos[keep], base.neg[keep],
                           base.event[keep], base.tie[keep])]
    best = None  # (agreement, theta_f, theta_i32)
    history = []
    # the i32 export is evaluated at SEVERAL projection scales: a small
    # scale rounds trained-to-near-zero nuisance weights to exactly 0
    # (they would otherwise break teacher ties the rank owns), a large
    # one keeps fine ranking resolution — the replay picks empirically
    scales = (25, 100, 1000, 4000)
    for r in range(max(rounds, 1)):
        theta_f, _ = run_imitation(concat_pairs(pool), cfg)
        round_best = None
        for s in scales:
            cand = project_theta(theta_f, s)
            rep = replay.agreement(cand, end_event=end)
            if round_best is None or rep["agreement"] > round_best[0][
                    "agreement"]:
                round_best = (rep, cand)
        train_rep, theta = round_best
        history.append(train_rep["agreement"])
        if out is not None:
            print(
                f"[imitate] round {r}: {concat_pairs(pool).pos.shape[0]} "
                f"pairs, train agreement "
                f"{100 * train_rep['agreement']:.2f}%", file=out,
            )
        if best is None or train_rep["agreement"] > best[0]:
            best = (train_rep["agreement"], theta_f, theta)
        if train_rep["matches"] == train_rep["creates"]:
            break
        mined = replay.mined_pairs(theta, end_event=end)
        if mined.pos.shape[0] == 0:
            break  # every remaining miss is a feature-tie (rank-owned)
        pool.append(mined)
    # greedy sparsification: small integer residuals mostly encode noise
    # that breaks teacher ties — zero each (ascending magnitude) and
    # keep the zero whenever train agreement does not drop. <= F extra
    # eval scans, all on the one compiled executable.
    theta = list(best[2])
    score = best[0]
    order = sorted(
        (j for j in range(len(theta)) if theta[j] != 0),
        key=lambda j: abs(theta[j]),
    )
    for j in order:
        cand = list(theta)
        cand[j] = 0
        if not any(cand):
            continue
        rep = replay.agreement(cand, end_event=end)
        if rep["agreement"] >= score:
            theta, score = cand, rep["agreement"]
    if out is not None and score > best[0]:
        print(
            f"[imitate] sparsified: train agreement "
            f"{100 * score:.2f}%", file=out,
        )
    return best[1], theta, history


def load_teacher_log(path: str):
    """(header, rows) of a decision JSONL, verified (digest, schema) and
    checked to come from a learnable teacher: the log must carry create
    events with runner-ups (DECISION_TOPK > 1 recording)."""
    from tpusim.obs.decisions import read_decisions

    header, rows = read_decisions(path)
    if not any(r["kind"] == 0 and r["node"] >= 0 for r in rows):
        raise ValueError(
            f"{path}: no successful create events — nothing to imitate"
        )
    return header, rows


def feature_names_of(policies) -> Tuple[str, ...]:
    """The feature vocabulary of a learned policy family, failing on a
    mixed or non-learned family."""
    feats = []
    for name, _ in policies:
        f = parse_learned_name(str(name))
        if f is None:
            raise ValueError(
                f"{name!r} is not a learned-policy member (want "
                "LearnedScore[<feature>] names)"
            )
        feats.append(f)
    return tuple(feats)
