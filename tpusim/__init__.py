"""tpusim — a TPU-native cluster-scheduling simulator.

Re-implements the capabilities of hkust-adsl/kubernetes-scheduler-simulator
(USENIX ATC'23 "Beware of Fragmentation", FGD) as a JAX/XLA program: cluster
state is a struct-of-arrays over nodes, every scoring policy is a vmapped
kernel, and the trace replay loop is a compiled `lax.scan` — either the
sequential oracle engine or the exact-equivalent incremental score-table
engine (tpusim.sim.table_engine, the throughput path).

Layer map (mirrors SURVEY.md §1 of this repo):
  tpusim.ops       — resource algebra + fragmentation math   (ref: pkg/type, pkg/utils)
  tpusim.policies  — node-scoring policy kernels             (ref: pkg/simulator/plugin)
  tpusim.sim       — scheduler step, replay engines, analysis (ref: pkg/simulator, vendor scheduler)
  tpusim.io        — trace/config/storage ingestion, export  (ref: data/, pkg/api, scripts)
  tpusim.parallel  — mesh-sharded replay for large clusters  (ref: §2.9 — replaces goroutine fan-out)
  tpusim.native    — C++ host-runtime components (Bellman)   (ctypes-bound, Python fallback)
  tpusim.config    — Simon CR + scheduler-config planes      (ref: pkg/api, pkg/simulator/utils.go)
"""

from tpusim import constants
from tpusim.types import NodeState, PodSpec, TypicalPods

__version__ = "0.1.0"

__all__ = ["constants", "NodeState", "PodSpec", "TypicalPods", "__version__"]
