"""Native (C++) runtime components, bound via ctypes.

The compute path of this framework is JAX/XLA on TPU; these are the
host-runtime pieces where CPython overhead dominates, compiled on demand
with the system toolchain (no pybind11 dependency). Every component has a
pure-Python fallback so the package works without a compiler.

Currently: the Bellman expected-frag evaluator (tpusim/native/bellman.cpp),
the per-event reporting hot spot (see tpusim.sim.driver._bellman_series).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Sequence

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "bellman.cpp")
_LIB = os.path.join(_DIR, "_bellman.so")

_lib = None
_load_failed = False


def _ensure_lib():
    """Compile (if stale) and dlopen the shared library; None on failure."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    try:
        if (
            not os.path.exists(_LIB)
            or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        ):
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", _LIB + ".tmp", _SRC],
                check=True,
                capture_output=True,
            )
            os.replace(_LIB + ".tmp", _LIB)
        lib = ctypes.CDLL(_LIB)
        lib.bellman_new.restype = ctypes.c_void_p
        lib.bellman_new.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int32,
            ctypes.c_int32,
        ]
        lib.bellman_eval.restype = ctypes.c_double
        lib.bellman_eval.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        lib.bellman_series.restype = ctypes.c_int32
        lib.bellman_series.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int8),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.bellman_memo_size.restype = ctypes.c_int64
        lib.bellman_memo_size.argtypes = [ctypes.c_void_p]
        lib.bellman_truncations.restype = ctypes.c_int64
        lib.bellman_truncations.argtypes = [ctypes.c_void_p]
        lib.bellman_max_depth_seen.restype = ctypes.c_int32
        lib.bellman_max_depth_seen.argtypes = [ctypes.c_void_p]
        lib.bellman_free.argtypes = [ctypes.c_void_p]
        _lib = lib
    except (OSError, subprocess.CalledProcessError):
        _load_failed = True
    return _lib


class BellmanEvaluator:
    """Memoized Bellman value function over node states for ONE typical-pod
    distribution (the memo lifetime contract of the reference's per-run
    fragMemo — construct one evaluator per experiment).

    Falls back to tpusim.ops.frag.node_frag_bellman when the native library
    is unavailable; `native` reports which path is active.
    """

    def __init__(self, typical: Sequence[tuple], max_depth: int = 64):
        """typical: [(cpu, gpu_milli, gpu_num, gpu_mask, freq)]."""
        self._typical = [
            (int(c), int(m), int(n), int(k), float(f))
            for c, m, n, k, f in typical
        ]
        self._handle: Optional[int] = None
        self._pymemo: dict = {}
        self._pystats: dict = {}
        lib = _ensure_lib()
        if lib is not None:
            t = len(self._typical)
            arr = lambda ctype, vals: (ctype * t)(*vals)
            self._handle = lib.bellman_new(
                arr(ctypes.c_int32, (p[0] for p in self._typical)),
                arr(ctypes.c_int32, (p[1] for p in self._typical)),
                arr(ctypes.c_int32, (p[2] for p in self._typical)),
                arr(ctypes.c_int64, (p[3] for p in self._typical)),
                arr(ctypes.c_double, (p[4] for p in self._typical)),
                t,
                max_depth,
            )
        self._max_depth = max_depth

    @property
    def native(self) -> bool:
        return self._handle is not None

    def eval(self, cpu_left: int, gpu_left: Sequence[int], gpu_type: int) -> float:
        if self._handle is not None:
            g = (ctypes.c_int32 * 8)(*[int(x) for x in gpu_left])
            return _lib.bellman_eval(
                self._handle, int(cpu_left), g, int(gpu_type)
            )
        from tpusim.ops.frag import node_frag_bellman

        return node_frag_bellman(
            (int(cpu_left), tuple(int(x) for x in gpu_left), int(gpu_type)),
            self._typical,
            max_depth=self._max_depth,
            memo=self._pymemo,
            stats=self._pystats,
        )

    def eval_series(
        self,
        cpu_left,
        gpu_left,
        gpu_type,
        ev_node,
        ev_dev,
        ev_sign,
        ev_cpu,
        ev_gpu,
    ):
        """Whole-event-stream cluster value series in one native call.

        cpu_left i32[N], gpu_left i32[N,8], gpu_type i32[N] are the INITIAL
        node state; ev_node i32[E] (-1 = untouched event), ev_dev bool[E,8],
        ev_sign i8[E] (+1 create / -1 delete), ev_cpu/ev_gpu i32[E] the
        event pod's milli requests. Returns f64[E]: the cluster total after
        each event (the `(bellman)` report series, analysis.go:110).
        """
        import numpy as np

        cpu_left = np.ascontiguousarray(cpu_left, np.int32)
        gpu_left = np.ascontiguousarray(gpu_left, np.int32)
        gpu_type = np.ascontiguousarray(gpu_type, np.int32)
        ev_node = np.ascontiguousarray(ev_node, np.int32)
        ev_dev = np.ascontiguousarray(ev_dev, np.uint8)
        ev_sign = np.ascontiguousarray(ev_sign, np.int8)
        ev_cpu = np.ascontiguousarray(ev_cpu, np.int32)
        ev_gpu = np.ascontiguousarray(ev_gpu, np.int32)
        n, e = len(cpu_left), len(ev_node)
        out = np.empty(e, np.float64)
        if self._handle is not None:
            ptr = lambda a, t: a.ctypes.data_as(ctypes.POINTER(t))
            _lib.bellman_series(
                self._handle,
                n,
                ptr(cpu_left, ctypes.c_int32),
                ptr(gpu_left, ctypes.c_int32),
                ptr(gpu_type, ctypes.c_int32),
                e,
                ptr(ev_node, ctypes.c_int32),
                ptr(ev_dev, ctypes.c_uint8),
                ptr(ev_sign, ctypes.c_int8),
                ptr(ev_cpu, ctypes.c_int32),
                ptr(ev_gpu, ctypes.c_int32),
                ptr(out, ctypes.c_double),
            )
            return out
        # pure-Python fallback: same bookkeeping through eval()
        cpu = cpu_left.copy()
        gpu = gpu_left.copy()
        val = np.array(
            [self.eval(int(cpu[i]), gpu[i], int(gpu_type[i])) for i in range(n)]
        )
        total = float(val.sum())
        for k in range(e):
            node = int(ev_node[k])
            if node >= 0:
                sign = int(ev_sign[k])
                cpu[node] -= sign * ev_cpu[k]
                gpu[node][ev_dev[k].astype(bool)] -= sign * ev_gpu[k]
                total -= float(val[node])
                val[node] = self.eval(int(cpu[node]), gpu[node], int(gpu_type[node]))
                total += float(val[node])
            out[k] = total
        return out

    def memo_size(self) -> int:
        if self._handle is not None:
            return int(_lib.bellman_memo_size(self._handle))
        return len(self._pymemo)

    def truncations(self) -> int:
        """How often the defensive max_depth cutoff fired (the Go reference
        recurses unboundedly, frag.go:231-283 — on real traces this must
        stay 0; tests/test_native.py asserts it over a full openb replay)."""
        if self._handle is not None:
            return int(_lib.bellman_truncations(self._handle))
        return int(self._pystats.get("truncations", 0))

    def max_depth_seen(self) -> int:
        """Deepest recursion level reached — the observed headroom under
        the max_depth bound."""
        if self._handle is not None:
            return int(_lib.bellman_max_depth_seen(self._handle))
        return int(self._pystats.get("max_depth_seen", 0))

    def __del__(self):
        if self._handle is not None and _lib is not None:
            _lib.bellman_free(self._handle)
            self._handle = None
