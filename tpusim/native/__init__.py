"""Native (C++) runtime components, bound via ctypes.

The compute path of this framework is JAX/XLA on TPU; these are the
host-runtime pieces where CPython overhead dominates, compiled on demand
with the system toolchain (no pybind11 dependency). Every component has a
pure-Python fallback so the package works without a compiler.

Currently: the Bellman expected-frag evaluator (tpusim/native/bellman.cpp),
the per-event reporting hot spot (see tpusim.sim.driver._bellman_series).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Sequence

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "bellman.cpp")
_LIB = os.path.join(_DIR, "_bellman.so")

_lib = None
_load_failed = False


def _ensure_lib():
    """Compile (if stale) and dlopen the shared library; None on failure."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    try:
        if (
            not os.path.exists(_LIB)
            or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        ):
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-o", _LIB + ".tmp", _SRC],
                check=True,
                capture_output=True,
            )
            os.replace(_LIB + ".tmp", _LIB)
        lib = ctypes.CDLL(_LIB)
        lib.bellman_new.restype = ctypes.c_void_p
        lib.bellman_new.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int32,
            ctypes.c_int32,
        ]
        lib.bellman_eval.restype = ctypes.c_double
        lib.bellman_eval.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        lib.bellman_memo_size.restype = ctypes.c_int64
        lib.bellman_memo_size.argtypes = [ctypes.c_void_p]
        lib.bellman_free.argtypes = [ctypes.c_void_p]
        _lib = lib
    except (OSError, subprocess.CalledProcessError):
        _load_failed = True
    return _lib


class BellmanEvaluator:
    """Memoized Bellman value function over node states for ONE typical-pod
    distribution (the memo lifetime contract of the reference's per-run
    fragMemo — construct one evaluator per experiment).

    Falls back to tpusim.ops.frag.node_frag_bellman when the native library
    is unavailable; `native` reports which path is active.
    """

    def __init__(self, typical: Sequence[tuple], max_depth: int = 64):
        """typical: [(cpu, gpu_milli, gpu_num, gpu_mask, freq)]."""
        self._typical = [
            (int(c), int(m), int(n), int(k), float(f))
            for c, m, n, k, f in typical
        ]
        self._handle: Optional[int] = None
        self._pymemo: dict = {}
        lib = _ensure_lib()
        if lib is not None:
            t = len(self._typical)
            arr = lambda ctype, vals: (ctype * t)(*vals)
            self._handle = lib.bellman_new(
                arr(ctypes.c_int32, (p[0] for p in self._typical)),
                arr(ctypes.c_int32, (p[1] for p in self._typical)),
                arr(ctypes.c_int32, (p[2] for p in self._typical)),
                arr(ctypes.c_int64, (p[3] for p in self._typical)),
                arr(ctypes.c_double, (p[4] for p in self._typical)),
                t,
                max_depth,
            )
        self._max_depth = max_depth

    @property
    def native(self) -> bool:
        return self._handle is not None

    def eval(self, cpu_left: int, gpu_left: Sequence[int], gpu_type: int) -> float:
        if self._handle is not None:
            g = (ctypes.c_int32 * 8)(*[int(x) for x in gpu_left])
            return _lib.bellman_eval(
                self._handle, int(cpu_left), g, int(gpu_type)
            )
        from tpusim.ops.frag import node_frag_bellman

        return node_frag_bellman(
            (int(cpu_left), tuple(int(x) for x in gpu_left), int(gpu_type)),
            self._typical,
            max_depth=self._max_depth,
            memo=self._pymemo,
        )

    def memo_size(self) -> int:
        if self._handle is not None:
            return int(_lib.bellman_memo_size(self._handle))
        return len(self._pymemo)

    def __del__(self):
        if self._handle is not None and _lib is not None:
            _lib.bellman_free(self._handle)
            self._handle = None
