// Bellman expected-fragmentation value function — native evaluator.
//
// Exact port of tpusim/ops/frag.py::node_frag_bellman (itself the host
// re-derivation of the reference's NodeGpuFragBellman, frag.go:231-283):
// memoized recursion over (cpu_left, sorted-desc gpu vector, gpu_type)
// states against a typical-pod distribution, with the same cum_prob cutoff,
// 0.999 ratio-except-Q3 shortcut, and non-memoized max-depth truncation.
// The per-event series evaluation in tpusim/sim/driver.py is ~5 us/call in
// CPython; this evaluator brings the dominant per-experiment host cost down
// ~20x. Equivalence is pinned by tests/test_native.py against the Python
// implementation.
//
// C ABI (consumed via ctypes from tpusim/native/__init__.py):
//   bellman_new(cpu[], milli[], num[], mask[], freq[], T, max_depth) -> handle
//   bellman_eval(handle, cpu_left, gpu[8], gpu_type) -> double
//   bellman_memo_size(handle) -> size
//   bellman_free(handle)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kMaxGpus = 8;

struct Key {
    int32_t cpu;
    int32_t type;
    int16_t g[kMaxGpus];
    bool operator==(const Key& o) const {
        return cpu == o.cpu && type == o.type &&
               std::memcmp(g, o.g, sizeof(g)) == 0;
    }
};

struct KeyHash {
    size_t operator()(const Key& k) const {
        // FNV-1a over the packed bytes
        const unsigned char* p = reinterpret_cast<const unsigned char*>(&k);
        size_t h = 1469598103934665603ull;
        for (size_t i = 0; i < sizeof(Key); ++i) {
            h ^= p[i];
            h *= 1099511628211ull;
        }
        return h;
    }
};

struct TypicalPod {
    int32_t cpu;
    int32_t milli;
    int32_t num;
    int64_t mask;
    double freq;
};

struct Evaluator {
    std::vector<TypicalPod> pods;
    std::vector<int32_t> millis;  // distinct positive, ascending
    int max_depth;
    std::unordered_map<Key, double, KeyHash> memo;

    double rec(int32_t cpu_left, int16_t* g /* sorted desc */, int32_t type,
               double cum_prob, int depth) {
        Key key;
        key.cpu = cpu_left;
        key.type = type;
        std::memcpy(key.g, g, sizeof(key.g));
        auto it = memo.find(key);
        if (it != memo.end()) return it->second;

        int64_t total = 0;
        for (int i = 0; i < kMaxGpus; ++i) total += g[i];
        if (total == 0 || static_cast<double>(total) * cum_prob < 1.0)
            return 0.0;

        // fit count per distinct milli (g sorted desc -> prefix counts)
        int nfit[64];
        {
            int i = kMaxGpus;
            for (size_t mi = 0; mi < millis.size(); ++mi) {
                int32_t m = millis[mi];
                while (i > 0 && g[i - 1] < m) --i;
                nfit[mi] = i;
            }
        }
        auto fit_of = [&](int32_t milli) {
            // millis is tiny (<= ~16); linear lookup
            for (size_t mi = 0; mi < millis.size(); ++mi)
                if (millis[mi] == milli) return nfit[mi];
            return 0;
        };
        int64_t node_bit = type >= 0 ? (1ll << type) : 0;

        double ratio_except_q3 = 0.0;
        for (const auto& t : pods) {
            if (t.milli == 0 || (t.mask != 0 && !(t.mask & node_bit)) ||
                fit_of(t.milli) < t.num || cpu_left < t.cpu)
                ratio_except_q3 += t.freq;
        }
        if (depth >= max_depth) return static_cast<double>(total);

        double frag;
        if (ratio_except_q3 < 0.999) {
            double pv = 0.0;
            for (const auto& t : pods) {
                if (t.freq == 0.0) continue;  // zero-frequency padding rows
                if (cpu_left < t.cpu || kMaxGpus < t.num) {
                    pv += static_cast<double>(total) * t.freq;
                    continue;
                }
                if (t.num == 0 || t.milli == 0) {
                    pv += t.freq * rec(cpu_left - t.cpu, g, type,
                                       cum_prob * t.freq, depth + 1);
                    continue;
                }
                int j = fit_of(t.milli);
                if (j < t.num) {
                    pv += static_cast<double>(total) * t.freq;
                    continue;
                }
                // take the t.num least-free fitting: g[j-num..j), each
                // -milli; re-sort desc
                int16_t g2[kMaxGpus];
                std::memcpy(g2, g, sizeof(g2));
                for (int d = j - t.num; d < j; ++d)
                    g2[d] = static_cast<int16_t>(g2[d] - t.milli);
                std::sort(g2, g2 + kMaxGpus, std::greater<int16_t>());
                pv += t.freq * rec(cpu_left - t.cpu, g2, type,
                                   cum_prob * t.freq, depth + 1);
            }
            frag = pv;
        } else {
            frag = static_cast<double>(total);
        }
        memo.emplace(key, frag);
        return frag;
    }
};

}  // namespace

extern "C" {

void* bellman_new(const int32_t* cpu, const int32_t* milli,
                  const int32_t* num, const int64_t* mask,
                  const double* freq, int32_t t, int32_t max_depth) {
    auto* ev = new Evaluator();
    ev->max_depth = max_depth;
    ev->pods.reserve(t);
    for (int i = 0; i < t; ++i)
        ev->pods.push_back({cpu[i], milli[i], num[i], mask[i], freq[i]});
    std::vector<int32_t> ms;
    for (int i = 0; i < t; ++i)
        if (milli[i] > 0) ms.push_back(milli[i]);
    std::sort(ms.begin(), ms.end());
    ms.erase(std::unique(ms.begin(), ms.end()), ms.end());
    if (ms.size() > 64) { delete ev; return nullptr; }
    ev->millis = std::move(ms);
    return ev;
}

double bellman_eval(void* handle, int32_t cpu_left, const int32_t* gpu,
                    int32_t gpu_type) {
    auto* ev = static_cast<Evaluator*>(handle);
    int16_t g[kMaxGpus];
    for (int i = 0; i < kMaxGpus; ++i) g[i] = static_cast<int16_t>(gpu[i]);
    std::sort(g, g + kMaxGpus, std::greater<int16_t>());
    return ev->rec(cpu_left, g, gpu_type, 1.0, 0);
}

int64_t bellman_memo_size(void* handle) {
    return static_cast<int64_t>(
        static_cast<Evaluator*>(handle)->memo.size());
}

void bellman_free(void* handle) { delete static_cast<Evaluator*>(handle); }

}  // extern "C"
