// Bellman expected-fragmentation value function — native evaluator.
//
// Exact port of tpusim/ops/frag.py::node_frag_bellman (itself the host
// re-derivation of the reference's NodeGpuFragBellman, frag.go:231-283):
// memoized recursion over (cpu_left, sorted-desc gpu vector, gpu_type)
// states against a typical-pod distribution, with the same cum_prob cutoff,
// 0.999 ratio-except-Q3 shortcut, and non-memoized max-depth truncation.
// The per-event series evaluation in tpusim/sim/driver.py is ~5 us/call in
// CPython; this evaluator brings the dominant per-experiment host cost down
// ~20x. Equivalence is pinned by tests/test_native.py against the Python
// implementation.
//
// C ABI (consumed via ctypes from tpusim/native/__init__.py):
//   bellman_new(cpu[], milli[], num[], mask[], freq[], T, max_depth) -> handle
//   bellman_eval(handle, cpu_left, gpu[8], gpu_type) -> double
//   bellman_series(handle, n, cpu_left[], gpu_left[], gpu_type[],
//                  e, ev_node[], ev_dev[], ev_sign[], ev_cpu[], ev_gpu[],
//                  out[]) -> 0
//   bellman_memo_size(handle) -> size
//   bellman_free(handle)
//
// bellman_series is the per-event cluster series (the `(bellman)` [Report]
// line, analysis.go:110) in ONE native call: it owns the node-state replay
// bookkeeping that tpusim/sim/driver.py used to do per event through
// ~10k ctypes round-trips, evaluating only the node each event touches
// (the value function depends on node state alone).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <functional>  // std::greater — not transitively provided by every
                       // libstdc++; older toolchains fail the on-demand build
#include <vector>

namespace {

constexpr int kMaxGpus = 8;

// Node state key packed into three 64-bit words: (cpu|type, g[0..3],
// g[4..7]). Word-wise compare + a 3-word mix hash keep the memo's inner
// loop (hundreds of probes per rec expansion) branch-light.
struct Key {
    uint64_t w0, w1, w2;
    bool operator==(const Key& o) const {
        return w0 == o.w0 && w1 == o.w1 && w2 == o.w2;
    }
};

inline Key make_key(int32_t cpu, int32_t type, const int16_t* g) {
    Key k;
    k.w0 = (static_cast<uint64_t>(static_cast<uint32_t>(cpu)) << 32) |
           static_cast<uint32_t>(type);
    std::memcpy(&k.w1, g, 8);
    std::memcpy(&k.w2, g + 4, 8);
    return k;
}

inline uint64_t mix64(uint64_t x) {
    x ^= x >> 33;
    x *= 0xff51afd7ed558ccdull;
    x ^= x >> 33;
    x *= 0xc4ceb9fe1a85ec53ull;
    x ^= x >> 33;
    return x;
}

inline uint64_t key_hash(const Key& k) {
    return mix64(k.w0 ^ mix64(k.w1 ^ mix64(k.w2)));
}

// Open-addressing memo (linear probing, power-of-two capacity). The
// ~200k-state memo a full-trace series accumulates made std::unordered_map
// the evaluator's dominant cost; a flat table roughly halves series time.
class FlatMap {
  public:
    FlatMap() { rehash(1 << 16); }

    // returns pointer to value if present, else nullptr
    const double* find(const Key& k) const {
        size_t i = key_hash(k) & mask_;
        while (used_[i]) {
            if (keys_[i] == k) return &vals_[i];
            i = (i + 1) & mask_;
        }
        return nullptr;
    }

    void insert(const Key& k, double v) {
        if ((count_ + 1) * 10 >= capacity_ * 7) rehash(capacity_ * 2);
        size_t i = key_hash(k) & mask_;
        while (used_[i]) {
            if (keys_[i] == k) {
                vals_[i] = v;
                return;
            }
            i = (i + 1) & mask_;
        }
        used_[i] = 1;
        keys_[i] = k;
        vals_[i] = v;
        ++count_;
    }

    size_t size() const { return count_; }

  private:
    void rehash(size_t cap) {
        std::vector<uint8_t> used(cap, 0);
        std::vector<Key> keys(cap);
        std::vector<double> vals(cap);
        size_t mask = cap - 1;
        for (size_t i = 0; i < capacity_; ++i) {
            if (!used_[i]) continue;
            size_t j = key_hash(keys_[i]) & mask;
            while (used[j]) j = (j + 1) & mask;
            used[j] = 1;
            keys[j] = keys_[i];
            vals[j] = vals_[i];
        }
        used_ = std::move(used);
        keys_ = std::move(keys);
        vals_ = std::move(vals);
        capacity_ = cap;
        mask_ = mask;
    }

    std::vector<uint8_t> used_;
    std::vector<Key> keys_;
    std::vector<double> vals_;
    size_t capacity_ = 0;
    size_t mask_ = 0;
    size_t count_ = 0;
};

// Branchless descending sort of 8 int16s (Batcher odd-even merge network,
// 19 compare-exchanges) — replaces the std::sort call each child state
// re-sort paid in the recursion's hottest loop.
inline void sort8_desc(int16_t* g) {
#define CSWP(a, b)                          \
    {                                       \
        int16_t lo = std::min(g[a], g[b]);  \
        int16_t hi = std::max(g[a], g[b]);  \
        g[a] = hi;                          \
        g[b] = lo;                          \
    }
    CSWP(0, 1) CSWP(2, 3) CSWP(4, 5) CSWP(6, 7)
    CSWP(0, 2) CSWP(1, 3) CSWP(4, 6) CSWP(5, 7)
    CSWP(1, 2) CSWP(5, 6)
    CSWP(0, 4) CSWP(1, 5) CSWP(2, 6) CSWP(3, 7)
    CSWP(2, 4) CSWP(3, 5)
    CSWP(1, 2) CSWP(3, 4) CSWP(5, 6)
#undef CSWP
}

struct TypicalPod {
    int32_t cpu;
    int32_t milli;
    int32_t num;
    int64_t mask;
    double freq;
    int32_t mi;  // index into Evaluator::millis (-1: milli == 0) — avoids
                 // the per-row linear milli lookup in the recursion's two
                 // hottest loops
};

struct Evaluator {
    std::vector<TypicalPod> pods;
    std::vector<int32_t> millis;  // distinct positive, ascending
    int max_depth;
    int64_t truncations = 0;  // times the depth cutoff fired (see below)
    int max_depth_seen = 0;   // deepest recursion level reached
    FlatMap memo;

    double rec(int32_t cpu_left, int16_t* g /* sorted desc */, int32_t type,
               double cum_prob, int depth) {
        Key key = make_key(cpu_left, type, g);
        if (const double* v = memo.find(key)) return *v;

        int64_t total = 0;
        for (int i = 0; i < kMaxGpus; ++i) total += g[i];
        if (total == 0 || static_cast<double>(total) * cum_prob < 1.0)
            return 0.0;

        // fit count per distinct milli (g sorted desc -> prefix counts)
        int nfit[64];
        {
            int i = kMaxGpus;
            for (size_t mi = 0; mi < millis.size(); ++mi) {
                int32_t m = millis[mi];
                while (i > 0 && g[i - 1] < m) --i;
                nfit[mi] = i;
            }
        }
        auto fit_of = [&](const TypicalPod& t) {
            return t.mi >= 0 ? nfit[t.mi] : 0;
        };
        int64_t node_bit = type >= 0 ? (1ll << type) : 0;

        double ratio_except_q3 = 0.0;
        for (const auto& t : pods) {
            if (t.milli == 0 || (t.mask != 0 && !(t.mask & node_bit)) ||
                fit_of(t) < t.num || cpu_left < t.cpu)
                ratio_except_q3 += t.freq;
        }
        if (depth > max_depth_seen) max_depth_seen = depth;
        if (depth >= max_depth) {
            // the Go reference has no depth limit (frag.go:231-283); this
            // guard exists only for pathological distributions, and the
            // counter lets callers assert it never fires on real traces
            ++truncations;
            return static_cast<double>(total);
        }

        double frag;
        if (ratio_except_q3 < 0.999) {
            double pv = 0.0;
            for (const auto& t : pods) {
                if (t.freq == 0.0) continue;  // zero-frequency padding rows
                if (cpu_left < t.cpu || kMaxGpus < t.num) {
                    pv += static_cast<double>(total) * t.freq;
                    continue;
                }
                if (t.num == 0 || t.milli == 0) {
                    pv += t.freq * rec(cpu_left - t.cpu, g, type,
                                       cum_prob * t.freq, depth + 1);
                    continue;
                }
                int j = fit_of(t);
                if (j < t.num) {
                    pv += static_cast<double>(total) * t.freq;
                    continue;
                }
                // take the t.num least-free fitting: g[j-num..j), each
                // -milli; re-sort desc
                int16_t g2[kMaxGpus];
                std::memcpy(g2, g, sizeof(g2));
                for (int d = j - t.num; d < j; ++d)
                    g2[d] = static_cast<int16_t>(g2[d] - t.milli);
                sort8_desc(g2);
                pv += t.freq * rec(cpu_left - t.cpu, g2, type,
                                   cum_prob * t.freq, depth + 1);
            }
            frag = pv;
        } else {
            frag = static_cast<double>(total);
        }
        memo.insert(key, frag);
        return frag;
    }
};

}  // namespace

extern "C" {

void* bellman_new(const int32_t* cpu, const int32_t* milli,
                  const int32_t* num, const int64_t* mask,
                  const double* freq, int32_t t, int32_t max_depth) {
    auto* ev = new Evaluator();
    ev->max_depth = max_depth;
    ev->pods.reserve(t);
    // zero-frequency rows (typical-axis padding) contribute exactly 0.0 to
    // every freq-weighted sum, so dropping them here is bit-identical and
    // shrinks the recursion's per-miss loops
    for (int i = 0; i < t; ++i)
        if (freq[i] != 0.0)
            ev->pods.push_back({cpu[i], milli[i], num[i], mask[i], freq[i], -1});
    std::vector<int32_t> ms;
    for (const auto& p : ev->pods)
        if (p.milli > 0) ms.push_back(p.milli);
    std::sort(ms.begin(), ms.end());
    ms.erase(std::unique(ms.begin(), ms.end()), ms.end());
    if (ms.size() > 64) { delete ev; return nullptr; }
    ev->millis = std::move(ms);
    for (auto& p : ev->pods)
        if (p.milli > 0)
            p.mi = static_cast<int32_t>(
                std::lower_bound(ev->millis.begin(), ev->millis.end(), p.milli)
                - ev->millis.begin());
    return ev;
}

double bellman_eval(void* handle, int32_t cpu_left, const int32_t* gpu,
                    int32_t gpu_type) {
    auto* ev = static_cast<Evaluator*>(handle);
    int16_t g[kMaxGpus];
    for (int i = 0; i < kMaxGpus; ++i) g[i] = static_cast<int16_t>(gpu[i]);
    sort8_desc(g);
    return ev->rec(cpu_left, g, gpu_type, 1.0, 0);
}

// Per-event cluster Bellman series. State arrays are the replay's INITIAL
// node state (cpu_left[n], gpu_left[n*8] unsorted, gpu_type[n]); events
// carry the touched node (-1 = none: skip/failed events keep the previous
// total), the bool[8] touched-device mask, the sign (+1 create, -1 delete)
// and the pod's cpu/gpu milli. out[e] = sum over nodes of the memoized
// value after applying events 0..e.
int32_t bellman_series(void* handle, int32_t n, const int32_t* cpu_left,
                       const int32_t* gpu_left, const int32_t* gpu_type,
                       int64_t e, const int32_t* ev_node,
                       const uint8_t* ev_dev, const int8_t* ev_sign,
                       const int32_t* ev_cpu, const int32_t* ev_gpu,
                       double* out) {
    auto* ev = static_cast<Evaluator*>(handle);
    std::vector<int32_t> cpu(cpu_left, cpu_left + n);
    std::vector<int32_t> gpu(gpu_left, gpu_left + n * kMaxGpus);
    std::vector<double> val(n);
    auto eval_node = [&](int32_t i) {
        int16_t g[kMaxGpus];
        for (int d = 0; d < kMaxGpus; ++d)
            g[d] = static_cast<int16_t>(gpu[i * kMaxGpus + d]);
        std::sort(g, g + kMaxGpus, std::greater<int16_t>());
        return ev->rec(cpu[i], g, gpu_type[i], 1.0, 0);
    };
    double total = 0.0;
    for (int32_t i = 0; i < n; ++i) {
        val[i] = eval_node(i);
        total += val[i];
    }
    for (int64_t k = 0; k < e; ++k) {
        int32_t node = ev_node[k];
        if (node >= 0) {
            int32_t sign = ev_sign[k];
            cpu[node] -= sign * ev_cpu[k];
            for (int d = 0; d < kMaxGpus; ++d)
                if (ev_dev[k * kMaxGpus + d])
                    gpu[node * kMaxGpus + d] -= sign * ev_gpu[k];
            total -= val[node];
            val[node] = eval_node(node);
            total += val[node];
        }
        out[k] = total;
    }
    return 0;
}

int64_t bellman_memo_size(void* handle) {
    return static_cast<int64_t>(
        static_cast<Evaluator*>(handle)->memo.size());
}

int64_t bellman_truncations(void* handle) {
    return static_cast<Evaluator*>(handle)->truncations;
}

int32_t bellman_max_depth_seen(void* handle) {
    return static_cast<Evaluator*>(handle)->max_depth_seen;
}

void bellman_free(void* handle) { delete static_cast<Evaluator*>(handle); }

}  // extern "C"
