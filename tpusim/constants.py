"""Static lookup tables and constants.

Mirrors the reference's pkg/type/const.go and
pkg/type/open-gpu-share/utils/const.go:4-121: GPU model registry (14 models),
GPU memory sizes, CPU/GPU energy tables, and the milli-resource conventions.
Strings are interned into integer ids at trace-ingest time so that all device
arrays are integer-typed; gpu_type `-1` means "no GPU", while unknown CPU
models map to id 0 (the reference's fallback energy profile, const.go:49).
"""

from __future__ import annotations

import numpy as np

MILLI = 1000  # 1 GPU == 1000 milli-GPU (ref: utils/const.go:14)
MAX_GPUS_PER_NODE = 8  # ref: pkg/type/const.go MaxNumGpuPerNode
MAX_SPEC_CPU = 128_000  # milli vCPU (ref: utils/const.go:16)
MAX_SPEC_MEM = 1_048_576  # MiB (ref: utils/const.go:17)
MAX_SPEC_GPU = 8_000  # milli GPU (ref: utils/const.go:18)

MAX_NODE_SCORE = 100  # k8s framework.MaxNodeScore
MIN_NODE_SCORE = 0

# Fragmentation classes (ref: pkg/utils/frag.go:17-35). Order == array index.
Q1_LACK_BOTH = 0
Q2_LACK_GPU = 1
Q3_SATISFIED = 2
Q4_LACK_CPU = 3
XL_SATISFIED = 4
XR_LACK_CPU = 5
NO_ACCESS = 6
NUM_FRAG_CLASSES = 7
FRAG_CLASS_NAMES = (
    "q1_lack_both",
    "q2_lack_gpu",
    "q3_satisfied",
    "q4_lack_cpu",
    "xl_satisfied",
    "xr_lack_cpu",
    "no_access",
)

# GPU model registry. Index == integer id used in device arrays; a pod's
# gpu_spec "A|B" OR-list becomes a bitmask over these ids
# (ref: utils/const.go:23-38 MapGpuTypeMemoryMiB; data/README.md gpu_spec).
# The reference treats the model as an opaque string (its tables just miss
# unknown names), so models outside the trace's 14 register dynamically —
# capped by the int32 gpu_mask bit width.
MAX_GPU_MODELS = 31
GPU_MODELS = [
    "P4",
    "2080",
    "1080",
    "M40",
    "T4",
    "V100M16",
    "P100",
    "A10",
    "3090",
    "V100M32",
    "A100",
    "G1",
    "G2",
    "G3",
]
GPU_MODEL_IDS = {name: i for i, name in enumerate(GPU_MODELS)}
NO_GPU = -1  # gpu_type id of CPU-only nodes


def register_gpu_model(name: str) -> int:
    """id of `name`, registering unknown models with zeroed memory/energy
    tables (matching the reference's missing-map-entry behavior)."""
    mid = GPU_MODEL_IDS.get(name)
    if mid is None:
        if len(GPU_MODELS) >= MAX_GPU_MODELS:
            raise ValueError(
                f"too many distinct GPU models (> {MAX_GPU_MODELS}): the "
                "gpu_spec bitmask is int32"
            )
        mid = len(GPU_MODELS)
        GPU_MODELS.append(name)
        GPU_MODEL_IDS[name] = mid
    return mid

GPU_MEMORY_MIB = {
    "P4": 7980711936 // 1024 // 1024,
    "2080": 11554258944 // 1024 // 1024,
    "1080": 11720982528 // 1024 // 1024,
    "M40": 12004098048 // 1024 // 1024,
    "T4": 15842934784 // 1024 // 1024,
    "V100M16": 16944988160 // 1024 // 1024,
    "P100": 17070817280 // 1024 // 1024,
    "A10": 23835181056 // 1024 // 1024,
    "3090": 25446842368 // 1024 // 1024,
    "V100M32": 34089205760 // 1024 // 1024,
    "A100": 85198045184 // 1024 // 1024,
    "G1": 1048576000 // 1024 // 1024,
    "G2": 20971520000 // 1024 // 1024,
    "G3": 31457280000 // 1024 // 1024,
}

# CPU model registry (ref: utils/const.go:48-55 MapCpuTypeEnergyConsumption).
# Index 0 is the "unknown model" fallback profile (2682's numbers).
CPU_MODELS = (
    "",
    "Intel-Xeon-8269CY",
    "Intel-Xeon-8163",
    "Intel-Xeon-ES-2682-V4",
    "Intel-Xeon-6326",
    "Intel-Xeon-8369B",
)
CPU_MODEL_IDS = {name: i for i, name in enumerate(CPU_MODELS)}

_CPU_ENERGY = {
    "": (15.0, 120.0, 16.0),
    "Intel-Xeon-8269CY": (20.0, 205.0, 26.0),
    "Intel-Xeon-8163": (20.0, 165.0, 24.0),
    "Intel-Xeon-ES-2682-V4": (15.0, 120.0, 16.0),
    "Intel-Xeon-6326": (20.0, 185.0, 16.0),
    "Intel-Xeon-8369B": (20.0, 270.0, 32.0),
}
# Dense (idle, full, ncores) tables indexed by cpu_type id.
CPU_IDLE_W = np.array([_CPU_ENERGY[m][0] for m in CPU_MODELS], np.float32)
CPU_FULL_W = np.array([_CPU_ENERGY[m][1] for m in CPU_MODELS], np.float32)
CPU_NCORES = np.array([_CPU_ENERGY[m][2] for m in CPU_MODELS], np.float32)

# GPU energy (idle W, full W) per model id; models absent from the reference's
# MapGpuTypeModelEnergy (P4/2080/1080/M40/3090/G1 — calling them would panic in
# the Go code) get zeros (ref: utils/const.go:62-121; G2≈A10, G3≈A100).
_GPU_ENERGY = {
    "T4": (10.0, 70.0),
    "A10": (30.0, 150.0),
    "P100": (25.0, 250.0),
    "V100M16": (30.0, 300.0),
    "V100M32": (30.0, 300.0),
    "A100": (50.0, 400.0),
    "G2": (30.0, 150.0),
    "G3": (50.0, 400.0),
}
# Fixed MAX_GPU_MODELS width so dynamically registered models (always
# zero-energy, like every other model missing from the reference's map)
# index in range without reshaping tables a jit may have captured.
GPU_IDLE_W = np.zeros(MAX_GPU_MODELS, np.float32)
GPU_FULL_W = np.zeros(MAX_GPU_MODELS, np.float32)
for _i, _m in enumerate(GPU_MODELS):
    GPU_IDLE_W[_i], GPU_FULL_W[_i] = _GPU_ENERGY.get(_m, (0.0, 0.0))

# Pod "GPU affinity" classes used by the GpuClustering policy
# (ref: open-gpu-share/utils/pod.go:111-123): share-gpu plus "N-gpu" for
# N in 1..8. no-gpu pods are tracked separately (they never enter the map).
AFF_SHARE = 0  # gpu_count == 1 and milli < 1000
NUM_AFF_CLASSES = 1 + MAX_GPUS_PER_NODE  # share + 1..8 whole-GPU


def gpu_affinity_class(gpu_num: int, gpu_milli: int) -> int:
    """Affinity class id, or -1 for no-gpu pods."""
    if gpu_num == 0:
        return -1
    if gpu_num == 1 and gpu_milli < MILLI:
        return AFF_SHARE
    return gpu_num  # "N-gpu" → class N (1..8)


def gpu_spec_to_mask(spec: str) -> int:
    """Encode a 'V100M16|V100M32' OR-list as a bitmask over GPU_MODELS.

    Empty spec (no constraint) → 0 (ref: pkg/utils/utils.go:957-1005
    IsNodeAccessibleToPodByType: empty pod type is accessible everywhere).
    """
    mask = 0
    for part in str(spec).split("|"):
        part = part.strip()
        if not part or part == "nan":
            continue
        mask |= 1 << register_gpu_model(part)
    return mask


DEFAULT_TYPICAL_POD_POPULARITY = 60  # ref: pkg/type/resource.go:46-49
DEFAULT_TYPICAL_POD_INCREASE_STEP = 10
