"""Virtual-mesh bootstrap — importable WITHOUT touching the rest of the
packages that initialize the JAX backend through their module graphs
(importing tpusim.parallel — even a submodule of it, since the package
__init__ always runs first — creates device values; after that the
platform can no longer be switched). Lives directly under tpusim, whose
__init__ stays import-light by design."""

from __future__ import annotations


def force_virtual_cpu_devices(n_devices: int) -> None:
    """Best-effort: before first backend init, force an n-device virtual
    CPU platform when the only accelerator is the single-chip 'axon' TPU
    tunnel. Plain JAX_PLATFORMS env vars are not enough in this image —
    the sitecustomize-registered axon PJRT plugin wins backend selection
    regardless — so drop its factory registration pre-init (the strategy
    tests/conftest.py and __graft_entry__.py use). No-op on real
    multi-device platforms or once a backend is up."""
    import os
    import re

    import jax
    from jax._src import xla_bridge as _xb

    if _xb._backends:  # backend already up; nothing safe to do
        return
    if n_devices > 1 and "axon" in _xb._backend_factories:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            os.environ.get("XLA_FLAGS", ""),
        )
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
