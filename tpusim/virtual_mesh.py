"""Virtual-mesh bootstrap — importable WITHOUT touching the rest of the
packages that initialize the JAX backend through their module graphs
(importing tpusim.parallel — even a submodule of it, since the package
__init__ always runs first — creates device values; after that the
platform can no longer be switched). Lives directly under tpusim, whose
__init__ stays import-light by design."""

from __future__ import annotations


def force_virtual_cpu_devices(n_devices: int, force: bool = False) -> None:
    """Best-effort: before first backend init, force an n-device virtual
    CPU platform when the host would otherwise come up with fewer devices
    than the requested mesh. Two cases act:

    - the single-chip 'axon' TPU tunnel: plain JAX_PLATFORMS env vars are
      not enough in this image — the sitecustomize-registered axon PJRT
      plugin wins backend selection regardless — so drop its factory
      registration pre-init (the strategy tests/conftest.py and
      __graft_entry__.py use);
    - a plain CPU-only host (no accelerator plugin at all): the default
      backend is a single CPU device, so --mesh N would fail Simulator
      construction with 'needs N devices'; forcing
      --xla_force_host_platform_device_count gives it the virtual mesh.

    No-op on real multi-device accelerator platforms (cuda, multi-chip
    tpu, ...) or once a backend is up. force=True skips the
    accelerator-factory guard (still never acts on an already-up
    backend): the CPU-by-design smokes (`make mesh-chaos-smoke`,
    bench_multichip) must get their virtual mesh even on images that
    register inert cuda/rocm/tpu plugin factories."""
    import os
    import re

    import jax
    from jax._src import xla_bridge as _xb

    if _xb._backends:  # backend already up; nothing safe to do
        return
    accel = [
        name for name in _xb._backend_factories
        if name not in ("cpu", "interpreter")
    ]
    if n_devices <= 1 or (not force and accel not in ([], ["axon"])):
        return
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    _xb._backend_factories.pop("axon", None)
