"""Policy-kernel plumbing shared by all scoring policies.

The reference runs each enabled ScorePlugin over the feasible node list,
optionally min-max normalizes (plugin_utils.go:48-74 NormalizeScore), applies
the config weight, sums, and picks the max-score node with
smallest-node-name tie-breaking (vendored generic_scheduler.go:185-210
selectHost). Here each policy is a function over the whole NodeState
struct-of-arrays producing

    raw_scores: i32[N]  — the plugin's Score() output per node
    share_dev:  i32[N]  — per node, the device the policy would hand a
                          share-GPU pod at Reserve time (-1 = none); whole-GPU
                          pods always use allocate_exclusive at bind
                          (open_gpu_share.go:285-343 + AllocateExclusiveGpuId)

and the framework semantics (normalize over feasible nodes only, integer
division, weighting, argmax with a fixed random tie-break permutation
standing in for the reference's random node-name prefixes,
simulator.go:584-588) live in tpusim.sim.step.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax.numpy as jnp

from tpusim.constants import MAX_NODE_SCORE
from tpusim.types import NodeState, PodSpec, TypicalPods


class ScoreContext(NamedTuple):
    """Dynamic inputs every policy may consume.

    feasible: bool[N] Filter-phase mask — normalization reductions and the
    Random policy's node draw only look at feasible nodes, like the vendored
    framework which scores feasible nodes only.
    """

    tp: TypicalPods
    feasible: jnp.ndarray  # bool[N]
    rng: jnp.ndarray  # jax PRNG key (Random policy, random gpu-sel)


class PolicyResult(NamedTuple):
    raw_scores: jnp.ndarray  # i32[N]
    share_dev: jnp.ndarray  # i32[N], -1 = no share-GPU choice


# A policy is (state, pod, ctx) -> PolicyResult, plus a `normalize` mode
# consumed by the step: "none" | "minmax" | "pwr".
PolicyFn = Callable[[NodeState, PodSpec, ScoreContext], PolicyResult]


def feasible_min_max(scores, feasible):
    """(lo, hi) over feasible entries — the reduction half of the min-max
    normalizations, split out so sharded callers can feed pmin/pmax-combined
    global extrema into the same scaling core."""
    big = jnp.int32(jnp.iinfo(jnp.int32).max)
    lo = jnp.min(jnp.where(feasible, scores, big))
    hi = jnp.max(jnp.where(feasible, scores, -big))
    return lo, hi


def minmax_scale_i32(scores, feasible, lo, hi, degenerate):
    """The scaling core of the reference's integer NormalizeScore
    (plugin_utils.go:48-74): rescale to [0, MAX_NODE_SCORE] against the
    supplied extrema; a zero range maps everything to `degenerate`.
    Infeasible rows pass through untouched (the reference never sees them);
    callers mask them out before use."""
    rng = hi - lo
    scaled = jnp.where(
        rng == 0, degenerate,
        (scores - lo) * MAX_NODE_SCORE // jnp.maximum(rng, 1),
    )
    return jnp.where(feasible, scaled, scores)


def minmax_normalize_i32(scores, feasible):
    """Integer min-max rescale to [0, 100] over feasible nodes
    (ref: plugin_utils.go:48-74). oldRange == 0 → all MinNodeScore(0)."""
    lo, hi = feasible_min_max(scores, feasible)
    return minmax_scale_i32(scores, feasible, lo, hi, 0)


def pwr_normalize_i32(scores, feasible):
    """PWR's own NormalizeScore (pwr_score.go:104-139): min-max to [0,100]
    but the degenerate all-equal case maps to 100, not 0."""
    lo, hi = feasible_min_max(scores, feasible)
    return minmax_scale_i32(scores, feasible, lo, hi, MAX_NODE_SCORE)


# zero-range (all-equal) value per normalize mode — what block-reducing
# callers pass as `degenerate` to minmax_scale_i32 so their apply half
# matches minmax_normalize_i32 / pwr_normalize_i32 exactly
NORMALIZE_DEGENERATE = {"minmax": 0, "pwr": MAX_NODE_SCORE}
