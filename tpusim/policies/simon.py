"""Simon scoring (ref: plugin/simon.go:47-71).

score = round(100 × max over resource dims of share(podReq_d, alloc_d − req_d))
with share(a, t) = a/t, or 1 when t == 0 and a > 0 (algo/greed.go:78-91).
Dims: milli-CPU, memory MiB, total milli-GPU. NOTE the reference reads
`node.Status.Allocatable` — static CAPACITY, which the fake cluster never
decrements on binding (usage lives in pod objects) — so the score base is
capacity, not free resources. Min-max normalized by the shared
NormalizeScore extension.
"""

from __future__ import annotations

import jax.numpy as jnp

from tpusim.constants import MAX_NODE_SCORE, MILLI
from tpusim.policies.base import PolicyResult, ScoreContext
from tpusim.types import NodeState, PodSpec


def _share(alloc, total):
    return jnp.where(
        total == 0,
        jnp.where(alloc == 0, 0.0, 1.0),
        alloc / jnp.where(total == 0, 1.0, total),
    )


def simon_score(state: NodeState, pod: PodSpec, ctx: ScoreContext) -> PolicyResult:
    req = [
        pod.cpu.astype(jnp.float32),
        pod.mem.astype(jnp.float32),
        pod.total_gpu_milli().astype(jnp.float32),
    ]
    alloc = [
        state.cpu_cap.astype(jnp.float32),
        state.mem_cap.astype(jnp.float32),
        (state.gpu_cnt * MILLI).astype(jnp.float32),
    ]
    res = jnp.zeros(state.num_nodes, jnp.float32)
    for a, f in zip(req, alloc):
        res = jnp.maximum(res, _share(a, f - a))
    scores = jnp.round(MAX_NODE_SCORE * res).astype(jnp.int32)
    share_dev = jnp.full(state.num_nodes, -1, jnp.int32)
    return PolicyResult(scores, share_dev)


simon_score.normalize = "minmax"
simon_score.policy_name = "Simon"
