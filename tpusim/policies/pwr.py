"""PWR — power-aware scoring (ref: plugin/pwr_score.go).

score(node) = trunc(oldPower − newPower) after hypothetically placing the pod
(per fitting device for share-GPU pods, pwr_score.go:150-200; Sub for
whole-GPU / CPU-only, pwr_score.go:204-218). Raw scores are ≤ 0 watts-deltas;
the plugin's own NormalizeScore maps them to [0, 100] with the all-equal case
pinned to 100 (pwr_score.go:104-139).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpusim.constants import MAX_GPUS_PER_NODE
from tpusim.ops.energy import node_power
from tpusim.ops.resource import sub_pod
from tpusim.policies.base import PolicyResult, ScoreContext
from tpusim.types import NodeState, PodSpec

_NEG_INF = jnp.int32(-(2**31) + 1)  # stands in for Go's math.MinInt64 init


def _power(cpu_left, cpu_cap, gpu_left, gpu_cnt, gpu_type, cpu_type):
    c, g = node_power(cpu_left, cpu_cap, gpu_left, gpu_cnt, gpu_type, cpu_type)
    return c + g


def _pwr_node(row: NodeState, pod: PodSpec):
    old = _power(
        row.cpu_left, row.cpu_cap, row.gpu_left, row.gpu_cnt, row.gpu_type, row.cpu_type
    )

    def per_dev(d):
        hyp = row.gpu_left.at[d].add(-pod.gpu_milli)
        return _power(
            row.cpu_left - pod.cpu, row.cpu_cap, hyp, row.gpu_cnt, row.gpu_type,
            row.cpu_type,
        )

    new_per_dev = jax.vmap(per_dev)(jnp.arange(MAX_GPUS_PER_NODE))
    fits = row.gpu_left >= pod.gpu_milli
    dev_scores = jnp.where(fits, (old - new_per_dev).astype(jnp.int32), _NEG_INF)
    best_dev = jnp.argmax(dev_scores).astype(jnp.int32)
    share_score = jnp.where(fits.any(), dev_scores[best_dev], _NEG_INF)
    share_dev = jnp.where(fits.any(), best_dev, -1).astype(jnp.int32)

    c2, _, g2, _, _ = sub_pod(row.cpu_left, row.mem_left, row.gpu_left, pod)
    whole_score = (
        old - _power(c2, row.cpu_cap, g2, row.gpu_cnt, row.gpu_type, row.cpu_type)
    ).astype(jnp.int32)

    is_share = pod.is_gpu_share()
    return (
        jnp.where(is_share, share_score, whole_score),
        jnp.where(is_share, share_dev, -1).astype(jnp.int32),
    )


_pwr_nodes = jax.vmap(_pwr_node, in_axes=(NodeState(0, 0, 0, 0, 0, 0, 0, 0, 0), None))


def pwr_score(state: NodeState, pod: PodSpec, ctx: ScoreContext) -> PolicyResult:
    scores, share_dev = _pwr_nodes(state, pod)
    return PolicyResult(scores, share_dev)


pwr_score.normalize = "pwr"
pwr_score.policy_name = "PWRScore"
