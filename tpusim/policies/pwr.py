"""PWR — power-aware scoring (ref: plugin/pwr_score.go).

score(node) = trunc(oldPower − newPower) after hypothetically placing the pod
(per fitting device for share-GPU pods, pwr_score.go:150-200; Sub for
whole-GPU / CPU-only, pwr_score.go:204-218). Raw scores are ≤ 0 watts-deltas;
the plugin's own NormalizeScore maps them to [0, 100] with the all-equal case
pinned to 100 (pwr_score.go:104-139).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpusim.constants import MILLI
from tpusim.ops.energy import cpu_power_watts, gpu_busy_delta_watts, gpu_power_watts
from tpusim.ops.resource import sub_pod
from tpusim.policies.base import PolicyResult, ScoreContext
from tpusim.types import NodeState, PodSpec

_NEG_INF = jnp.int32(-(2**31) + 1)  # stands in for Go's math.MinInt64 init


def _pwr_node(row: NodeState, pod: PodSpec):
    """Placing a pod changes power through exactly two channels: the CPU
    package count (recomputed once from cpu_left − pod.cpu) and devices
    flipping from fully-idle to working. Per-device hypotheticals are thus
    derived without re-running the whole power model 9 times; watt tables
    times small integer counts are exact in f32, so the scores equal the
    direct form (randomized old-vs-new equivalence in
    tests/test_policies.py::test_pwr_matches_direct_form)."""
    cpu_old = cpu_power_watts(row.cpu_left, row.cpu_cap, row.cpu_type)
    gpu_old = gpu_power_watts(row.gpu_left, row.gpu_cnt, row.gpu_type)
    old = cpu_old + gpu_old
    cpu_new = cpu_power_watts(row.cpu_left - pod.cpu, row.cpu_cap, row.cpu_type)
    busy_delta = gpu_busy_delta_watts(row.gpu_type)

    # share-GPU: device d flips idle->working iff it was fully idle AND the
    # pod actually takes milli from it (zero-milli share pods — num_gpu=1
    # with a sanitized-to-0 request — change nothing)
    was_idle = row.gpu_left == MILLI
    new_per_dev = cpu_new + gpu_old + jnp.where(
        was_idle & (pod.gpu_milli > 0), busy_delta, 0.0
    )
    fits = row.gpu_left >= pod.gpu_milli
    dev_scores = jnp.where(fits, (old - new_per_dev).astype(jnp.int32), _NEG_INF)
    best_dev = jnp.argmax(dev_scores).astype(jnp.int32)
    share_score = jnp.where(fits.any(), dev_scores[best_dev], _NEG_INF)
    share_dev = jnp.where(fits.any(), best_dev, -1).astype(jnp.int32)

    # whole-GPU / CPU-only: Sub's taken devices flip iff previously idle
    _, _, _, dev_mask, _ = sub_pod(row.cpu_left, row.mem_left, row.gpu_left, pod)
    flips = (dev_mask & was_idle).sum().astype(jnp.float32)
    whole_score = (old - (cpu_new + gpu_old + flips * busy_delta)).astype(jnp.int32)

    is_share = pod.is_gpu_share()
    return (
        jnp.where(is_share, share_score, whole_score),
        jnp.where(is_share, share_dev, -1).astype(jnp.int32),
    )


_pwr_nodes = jax.vmap(_pwr_node, in_axes=(NodeState(0, 0, 0, 0, 0, 0, 0, 0, 0), None))


def pwr_score(state: NodeState, pod: PodSpec, ctx: ScoreContext) -> PolicyResult:
    scores, share_dev = _pwr_nodes(state, pod)
    return PolicyResult(scores, share_dev)


pwr_score.normalize = "pwr"
pwr_score.policy_name = "PWRScore"
