"""GpuPacking scoring (ref: plugin/gpu_packing_score.go:67-117), 3 tiers:

  case-1 share used GPUs:          max(100 − Σ trunc(left·100/1000)/10, 50)
  case-2 dip into fully-free GPUs: max(50 − #fullyFreeUsed, 33)
  case-3 fully-free node:          max(33 − #freeGpus, #freeGpus)

Allocation simulation mirrors Sub: fitting devices taken least-free-first
(stable by index) until gpu_num are found.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpusim.constants import MAX_NODE_SCORE, MILLI
from tpusim.ops.resource import select_devices_packed
from tpusim.policies.base import PolicyResult, ScoreContext
from tpusim.types import NodeState, PodSpec

_T3 = MAX_NODE_SCORE // 3  # 33
_T2 = MAX_NODE_SCORE // 2  # 50


def _packing_node(gpu_left, gpu_cnt, pod: PodSpec):
    fully_free = (gpu_left == MILLI).sum().astype(jnp.int32)

    # case-3: every device on the node is idle (gpu_packing_score.go:76-81)
    case3 = jnp.maximum(_T3 - fully_free, fully_free)

    # simulate the ascending-packed allocation (gpu_packing_score.go:83-100)
    dev_mask, ok = select_devices_packed(gpu_left, pod.gpu_milli, pod.gpu_num)
    free_used = (dev_mask & (gpu_left == MILLI)).sum().astype(jnp.int32)

    # case-2: had to consume fully-free devices
    case2 = jnp.maximum(_T2 - free_used, _T3)

    # case-1: only shared (partially-used) devices
    ratio = jnp.where(dev_mask, gpu_left * 100 // MILLI, 0).sum().astype(jnp.int32)
    case1 = jnp.maximum(MAX_NODE_SCORE - ratio // 10, _T2)

    score = jnp.where(
        fully_free == gpu_cnt,
        case3,
        jnp.where(~ok, 0, jnp.where(free_used > 0, case2, case1)),
    )
    # non-GPU pods score MinNodeScore (gpu_packing_score.go:36-39)
    return jnp.where(pod.total_gpu_milli() > 0, score, 0).astype(jnp.int32)


_packing_nodes = jax.vmap(_packing_node, in_axes=(0, 0, None))


def packing_score(state: NodeState, pod: PodSpec, ctx: ScoreContext) -> PolicyResult:
    scores = _packing_nodes(state.gpu_left, state.gpu_cnt, pod)
    share_dev = jnp.full(state.num_nodes, -1, jnp.int32)
    return PolicyResult(scores, share_dev)


packing_score.normalize = "none"
packing_score.policy_name = "GpuPackingScore"
