"""Random scoring (ref: plugin/random_score.go:42-68).

PreScore draws one node uniformly; Score gives it 100 and everyone else 0.
The reference draws from the PreScore node list (the feasible set), so the
draw here is uniform over ctx.feasible.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpusim.constants import MAX_NODE_SCORE
from tpusim.policies.base import PolicyResult, ScoreContext
from tpusim.types import NodeState, PodSpec


def random_score(state: NodeState, pod: PodSpec, ctx: ScoreContext) -> PolicyResult:
    n = state.num_nodes
    u = jax.random.uniform(ctx.rng, (n,))
    pick = jnp.argmax(jnp.where(ctx.feasible, u, -1.0))
    scores = jnp.where(jnp.arange(n) == pick, MAX_NODE_SCORE, 0).astype(jnp.int32)
    share_dev = jnp.full(n, -1, jnp.int32)
    return PolicyResult(scores, share_dev)


random_score.normalize = "none"
random_score.policy_name = "RandomScore"
