"""FGD — Fragmentation Gradient Descent (ref: plugin/fgd_score.go).

score(node) = trunc(sigmoid((frag(node) − frag(node ⊖ pod)) / 1000) × 100)

For a share-GPU pod the hypothetical placement is tried on every fitting
device and the best per-device score wins (fgd_score.go:111-134, first device
on ties); for whole-GPU / CPU-only pods the placement is NodeResource.Sub
(fgd_score.go:137-148). Reserve re-runs the same computation to pick the
device (allocateGpuIdBasedOnFGDScore, fgd_score.go:153-156).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpusim.constants import MAX_GPUS_PER_NODE, MAX_NODE_SCORE
from tpusim.ops.frag import node_frag_score
from tpusim.ops.resource import sub_pod
from tpusim.policies.base import PolicyResult, ScoreContext
from tpusim.types import NodeState, PodSpec


def _sigmoid_score(cur, new):
    """trunc(sigmoid((cur-new)/1000) * MaxNodeScore) — fgd_score.go:124."""
    s = jax.nn.sigmoid((cur - new) / 1000.0)
    return jnp.floor(s * MAX_NODE_SCORE).astype(jnp.int32)


def _fgd_node(cpu_left, mem_left, gpu_left, gpu_type, pod: PodSpec, tp):
    cur = node_frag_score(cpu_left, gpu_left, gpu_type, tp)

    # --- share-GPU branch: hypothetical per device (fgd_score.go:111-134) ---
    def per_dev(d):
        hyp = gpu_left.at[d].add(-pod.gpu_milli)
        return node_frag_score(cpu_left - pod.cpu, hyp, gpu_type, tp)

    new_per_dev = jax.vmap(per_dev)(jnp.arange(MAX_GPUS_PER_NODE))  # f32[8]
    fits = gpu_left >= pod.gpu_milli
    dev_scores = jnp.where(fits, _sigmoid_score(cur, new_per_dev), jnp.int32(-1))
    best_dev = jnp.argmax(dev_scores).astype(jnp.int32)  # first max on ties
    share_score = jnp.where(fits.any(), dev_scores[best_dev], 0)
    share_dev = jnp.where(fits.any(), best_dev, -1).astype(jnp.int32)

    # --- whole-GPU / CPU-only branch: Sub hypothetical (fgd_score.go:137-148) ---
    c2, _, g2, _, _ = sub_pod(cpu_left, mem_left, gpu_left, pod)
    whole_score = _sigmoid_score(cur, node_frag_score(c2, g2, gpu_type, tp))

    is_share = pod.is_gpu_share()
    return (
        jnp.where(is_share, share_score, whole_score),
        jnp.where(is_share, share_dev, -1).astype(jnp.int32),
    )


_fgd_nodes = jax.vmap(_fgd_node, in_axes=(0, 0, 0, 0, None, None))


def fgd_score(state: NodeState, pod: PodSpec, ctx: ScoreContext) -> PolicyResult:
    scores, share_dev = _fgd_nodes(
        state.cpu_left, state.mem_left, state.gpu_left, state.gpu_type, pod, ctx.tp
    )
    return PolicyResult(scores, share_dev)


fgd_score.normalize = "none"
fgd_score.policy_name = "FGDScore"
