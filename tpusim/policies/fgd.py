"""FGD — Fragmentation Gradient Descent (ref: plugin/fgd_score.go).

score(node) = trunc(sigmoid((frag(node) − frag(node ⊖ pod)) / 1000) × 100)

For a share-GPU pod the hypothetical placement is tried on every fitting
device and the best per-device score wins (fgd_score.go:111-134, first device
on ties); for whole-GPU / CPU-only pods the placement is NodeResource.Sub
(fgd_score.go:137-148). Reserve re-runs the same computation to pick the
device (allocateGpuIdBasedOnFGDScore, fgd_score.go:153-156).

Implementation note (TPU): the naive form evaluates the full frag score on
9 hypothetical node states per node (current + 8 per-device). Because the
frag score decomposes as

    score = Σ_t freq_t × (isQ3_t ? total_left − fitsum_t : total_left)
    fitsum_t = Σ_e [g_e ≥ milli_t]·g_e ,  isQ3 from fit counts + cpu

a per-device hypothetical only perturbs one device's fit/fitsum term, so all
8 hypotheticals are derived from one [T, 8] precompute instead of 8 full
evaluations (~4× fewer element-ops). The share and whole branches are split
behind a lax.cond on the (scalar, per-pod) branch predicate so only the
branch the pod actually needs is executed. Equivalence with the direct form
is pinned by tests/test_policies.py golden values and the cross-check test.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpusim.constants import MAX_NODE_SCORE
from tpusim.ops.frag import node_frag_score
from tpusim.ops.resource import is_accessible, sub_pod
from tpusim.policies.base import PolicyResult, ScoreContext
from tpusim.types import NodeState, PodSpec


def _sigmoid_score(cur, new):
    """trunc(sigmoid((cur-new)/1000) * MaxNodeScore) — fgd_score.go:124."""
    s = jax.nn.sigmoid((cur - new) / 1000.0)
    return jnp.floor(s * MAX_NODE_SCORE).astype(jnp.int32)


def _share_terms(gpu_left, tp):
    """fit[T,8], fitcnt[T], fitsum[T] for the current device vector.

    fitcnt/fitsum come out of ONE stacked [T,8,2] reduction instead of two —
    on TPU each reduction is a fusion barrier (its own kernel launch inside
    the replay scan body), so merging reductions is the lever here, not
    FLOPs. Counts stay exact in f32 (<= 8)."""
    fit = (gpu_left[None, :] >= tp.gpu_milli[:, None]) & (tp.gpu_milli[:, None] > 0)
    g = gpu_left[None, :].astype(jnp.float32)
    both = jnp.stack(
        [fit.astype(jnp.float32), jnp.where(fit, g, 0.0)], axis=-1
    ).sum(1)  # [T, 2]
    return fit, both[:, 0], both[:, 1]


def _fgd_share_node(cpu_left, gpu_left, gpu_type, pod: PodSpec, tp):
    """Share-GPU branch: best per-device hypothetical (fgd_score.go:111-134).

    The current score and the 8 per-device hypotheticals reduce over T in a
    single [T, 9] sum (see _share_terms on why reductions are merged)."""
    acc = is_accessible(gpu_type, tp.gpu_mask)  # [T]
    gpu_pod = tp.gpu_milli > 0  # [T]
    fit, fitcnt, fitsum = _share_terms(gpu_left, tp)
    total = gpu_left.sum().astype(jnp.float32)

    # current frag score term per typical pod
    isq3 = gpu_pod & acc & (fitcnt >= tp.gpu_num) & (cpu_left >= tp.cpu)
    cur_t = tp.freq * jnp.where(isq3, total - fitsum, total)  # [T]

    # hypothetical on device d: only device d's fit/fitsum terms change
    p = pod.gpu_milli
    g = gpu_left[None, :].astype(jnp.float32)
    fitp = ((gpu_left[None, :] - p) >= tp.gpu_milli[:, None]) & (
        tp.gpu_milli[:, None] > 0
    )  # [T,8]
    fitcnt_h = fitcnt[:, None] - fit + fitp  # [T,8]
    fitsum_h = fitsum[:, None] - jnp.where(fit, g, 0.0) + jnp.where(fitp, g - p, 0.0)
    total_h = total - p
    cpu_ok_h = (cpu_left - pod.cpu) >= tp.cpu  # [T]
    isq3_h = (
        gpu_pod[:, None] & acc[:, None] & (fitcnt_h >= tp.gpu_num[:, None])
        & cpu_ok_h[:, None]
    )
    new_t = tp.freq[:, None] * jnp.where(isq3_h, total_h - fitsum_h, total_h)

    sums = jnp.concatenate([cur_t[:, None], new_t], axis=1).sum(0)  # f32[9]
    cur, new_per_dev = sums[0], sums[1:]

    fits = gpu_left >= p
    dev_scores = jnp.where(fits, _sigmoid_score(cur, new_per_dev), jnp.int32(-1))
    best_dev = jnp.argmax(dev_scores).astype(jnp.int32)  # first max on ties
    best_score = dev_scores[best_dev]
    ok = best_score >= 0  # == fits.any(): fitting devices always score >= 0
    score = jnp.where(ok, best_score, 0)
    dev = jnp.where(ok, best_dev, -1).astype(jnp.int32)
    return score, dev


def _decomposed_score(cpu_left, gpu_left, gpu_type, tp):
    """node_frag_score via the fit/fitsum decomposition (same value; pinned
    against ops.frag.node_frag_score by tests/test_policies.py)."""
    acc = is_accessible(gpu_type, tp.gpu_mask)
    fit, fitcnt, fitsum = _share_terms(gpu_left, tp)
    total = gpu_left.sum().astype(jnp.float32)
    isq3 = (tp.gpu_milli > 0) & acc & (fitcnt >= tp.gpu_num) & (cpu_left >= tp.cpu)
    return (tp.freq * jnp.where(isq3, total - fitsum, total)).sum()


def _fgd_whole_node(cpu_left, mem_left, gpu_left, gpu_type, pod: PodSpec, tp):
    """Whole-GPU / CPU-only branch: Sub hypothetical (fgd_score.go:137-148)."""
    cur = _decomposed_score(cpu_left, gpu_left, gpu_type, tp)
    c2, _, g2, _, _ = sub_pod(cpu_left, mem_left, gpu_left, pod)
    score = _sigmoid_score(cur, _decomposed_score(c2, g2, gpu_type, tp))
    return score, jnp.int32(-1)


_share_nodes = jax.vmap(_fgd_share_node, in_axes=(0, 0, 0, None, None))
_whole_nodes = jax.vmap(_fgd_whole_node, in_axes=(0, 0, 0, 0, None, None))


def _fgd_share(state: NodeState, pod: PodSpec, ctx: ScoreContext) -> PolicyResult:
    scores, dev = _share_nodes(
        state.cpu_left, state.gpu_left, state.gpu_type, pod, ctx.tp
    )
    return PolicyResult(scores, dev)


def _fgd_whole(state: NodeState, pod: PodSpec, ctx: ScoreContext) -> PolicyResult:
    scores, dev = _whole_nodes(
        state.cpu_left, state.mem_left, state.gpu_left, state.gpu_type, pod, ctx.tp
    )
    return PolicyResult(scores, dev)


def fgd_score(state: NodeState, pod: PodSpec, ctx: ScoreContext) -> PolicyResult:
    # pod.is_gpu_share() is a scalar (per-pod) predicate, so the cond stays a
    # real branch under the node vmap — only one branch's work is executed.
    return jax.lax.cond(
        pod.is_gpu_share(),
        lambda: _fgd_share(state, pod, ctx),
        lambda: _fgd_whole(state, pod, ctx),
    )


fgd_score.normalize = "none"
fgd_score.policy_name = "FGDScore"
# branch-specialized kernels for callers that know the pod's branch
# statically (the table engine partitions pod types host-side, avoiding the
# cond→select duplication under a type-axis vmap)
fgd_score.branches = {"share": _fgd_share, "whole": _fgd_whole}
