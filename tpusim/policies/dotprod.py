"""DotProduct (Tetris) scoring (ref: plugin/dot_product_score.go + the
virtual-resource expansion in pkg/type/resource.go:246-381 and
pkg/utils/utils.go:1274-1342 GenerateSchedulingMatchGroups).

score = trunc(100 × max over match groups of (1 − normalized dot product)).

The reference materializes virtual node/pod vector lists per dim-extension
method; here each method is a fixed-shape masked kernel over 9 virtual slots
(8 per-device slots + 1 idle-GPU pool), vmapped over nodes:

  merge  — one [cpu_left, Σgpu_left] vector per node
  share  — one slot per partially-used fitting device + the idle pool,
           CPU shared across slots
  divide — like share but CPU prorated by the slot's share of idle GPU milli
  extend — node vector lifted to per-group GPU dims (shared devices
           individually + merged idle pool), pod vector one-hot per group

Norm methods divide both vectors by node capacity / pod request / max spec
(NormalizeVector zeroes elements whose divisor ≤ 0); `pod` norm additionally
squashes with tanh(x/10) (dot_product_score.go:76-83).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpusim.constants import (
    MAX_GPUS_PER_NODE,
    MAX_NODE_SCORE,
    MAX_SPEC_CPU,
    MAX_SPEC_GPU,
    MILLI,
)
from tpusim.policies.base import PolicyResult, ScoreContext
from tpusim.types import NodeState, PodSpec

_NEG = jnp.float32(-jnp.inf)


def _safe_div(v, n):
    """NormalizeVector semantics (utils.go:1221-1244): v/n, 0 when n <= 0."""
    return jnp.where(n > 0, v / jnp.where(n > 0, n, 1.0), 0.0)


def _first_free_dev(gpu_left):
    """First fully-free device id (AllocateExclusiveGpuId head, for share
    pods that win the idle-pool slot)."""
    free = gpu_left == MILLI
    return jnp.where(free.any(), jnp.argmax(free), -1).astype(jnp.int32)


def _merge_node(row: NodeState, pod: PodSpec, norm: str):
    total_left = row.gpu_left.sum().astype(jnp.float32)
    node_vec = jnp.stack([row.cpu_left.astype(jnp.float32), total_left])
    pod_vec = jnp.stack(
        [pod.cpu.astype(jnp.float32), pod.total_gpu_milli().astype(jnp.float32)]
    )
    if norm == "node":
        div = jnp.stack(
            [row.cpu_cap.astype(jnp.float32), (row.gpu_cnt * MILLI).astype(jnp.float32)]
        )
    elif norm == "pod":
        div = pod_vec
    else:  # max
        div = jnp.asarray([MAX_SPEC_CPU, MAX_SPEC_GPU], jnp.float32)
    dot = (_safe_div(node_vec, div) * _safe_div(pod_vec, div)).sum() / 2.0
    if norm == "pod":
        dot = jnp.tanh(dot / 10.0)
    score = jnp.where(row.cpu_left >= pod.cpu, 1.0 - dot, _NEG)
    return score, jnp.int32(-1)


def _share_divide_node(row: NodeState, pod: PodSpec, norm: str, divide: bool):
    total_req = pod.total_gpu_milli()
    total_left = row.gpu_left.sum()
    idle_cnt = (row.gpu_left == MILLI).sum()
    slot_real = jnp.arange(MAX_GPUS_PER_NODE) < row.gpu_cnt

    # 8 per-device slots: partially-used fitting devices, share branch only
    # (resource.go:315-341); slot 8: the idle-GPU pool (resource.go:344-365).
    dev_active = (
        (total_req < MILLI)
        & slot_real
        & (row.gpu_left < MILLI)
        & (row.gpu_left >= total_req)
    )
    pool_active = total_req <= idle_cnt * MILLI
    pool_gpu = (idle_cnt * MILLI).astype(jnp.float32)

    slot_gpu = jnp.concatenate([row.gpu_left.astype(jnp.float32), pool_gpu[None]])
    active = jnp.concatenate([dev_active, pool_active[None]])
    cpu_f = row.cpu_left.astype(jnp.float32)
    if divide:
        slot_cpu = _safe_div(cpu_f * slot_gpu, total_left.astype(jnp.float32))
    else:
        slot_cpu = jnp.full(MAX_GPUS_PER_NODE + 1, cpu_f)

    pod_vec = jnp.stack(
        [pod.cpu.astype(jnp.float32), total_req.astype(jnp.float32)]
    )
    if norm == "node":
        div_cpu = row.cpu_cap.astype(jnp.float32)
        div_gpu = (row.gpu_cnt * MILLI).astype(jnp.float32)
    elif norm == "pod":
        div_cpu = pod_vec[0]
        div_gpu = pod_vec[1]
    else:
        div_cpu = jnp.float32(MAX_SPEC_CPU)
        div_gpu = jnp.float32(MAX_SPEC_GPU)

    dots = (
        _safe_div(slot_cpu, div_cpu) * _safe_div(pod_vec[0], div_cpu)
        + _safe_div(slot_gpu, div_gpu) * _safe_div(pod_vec[1], div_gpu)
    ) / 2.0
    if norm == "pod":
        dots = jnp.tanh(dots / 10.0)
    scores = jnp.where((row.cpu_left >= pod.cpu) & active, 1.0 - dots, _NEG)
    best = jnp.argmax(scores)
    share_dev = jnp.where(
        best < MAX_GPUS_PER_NODE, best.astype(jnp.int32), _first_free_dev(row.gpu_left)
    )
    return scores[best], jnp.where(scores[best] == _NEG, -1, share_dev)


def _extend_node(row: NodeState, pod: PodSpec, norm: str):
    total_req = pod.total_gpu_milli()
    idle_cnt = (row.gpu_left == MILLI).sum()
    slot_real = jnp.arange(MAX_GPUS_PER_NODE) < row.gpu_cnt

    # Formalized groups (resource.go:217-244): devices with 0 < left < MILLI
    # individually, plus one merged idle group.
    dev_group = slot_real & (row.gpu_left > 0) & (row.gpu_left < MILLI)
    pool_group = idle_cnt > 0
    group_active = jnp.concatenate([dev_group, pool_group[None]])
    group_left = jnp.concatenate(
        [row.gpu_left.astype(jnp.float32), (idle_cnt * MILLI).astype(jnp.float32)[None]]
    )
    n_groups = group_active.sum().astype(jnp.float32)

    # One pod vector per group with enough room (resource.go:263-287); each
    # match group's dot = cpu term + that group's gpu term; vector length for
    # the /len(podVec) normalization is 1 + n_groups.
    cand = group_active & (group_left >= total_req.astype(jnp.float32))
    if norm == "node":
        div_cpu = row.cpu_cap.astype(jnp.float32)
        div_gpu = (row.gpu_cnt * MILLI).astype(jnp.float32)
    elif norm == "pod":
        div_cpu = pod.cpu.astype(jnp.float32)
        div_gpu = total_req.astype(jnp.float32)
    else:
        div_cpu = jnp.float32(MAX_SPEC_CPU)
        div_gpu = jnp.float32(MAX_SPEC_GPU)

    cpu_term = _safe_div(row.cpu_left.astype(jnp.float32), div_cpu) * _safe_div(
        pod.cpu.astype(jnp.float32), div_cpu
    )
    gpu_terms = _safe_div(group_left, div_gpu) * _safe_div(
        total_req.astype(jnp.float32), div_gpu
    )
    dots = (cpu_term + gpu_terms) / jnp.maximum(1.0 + n_groups, 1.0)
    if norm == "pod":
        dots = jnp.tanh(dots / 10.0)
    scores = jnp.where((row.cpu_left >= pod.cpu) & cand, 1.0 - dots, _NEG)
    best = jnp.argmax(scores)
    share_dev = jnp.where(
        best < MAX_GPUS_PER_NODE, best.astype(jnp.int32), _first_free_dev(row.gpu_left)
    )
    return scores[best], jnp.where(scores[best] == _NEG, -1, share_dev)


from functools import lru_cache


@lru_cache(maxsize=None)
def make_dotprod(dim_ext: str = "share", norm: str = "max"):
    """Build the DotProduct policy for a (dimExtMethod, normMethod) config
    (ref: example scheduler configs use share/max). Cached per config so
    repeated Simulator constructions share one kernel object (and therefore
    one jit cache entry for the replay engines built around it)."""
    assert dim_ext in ("merge", "share", "divide", "extend"), dim_ext
    assert norm in ("node", "pod", "max"), norm

    def per_node(row: NodeState, pod: PodSpec):
        if dim_ext == "merge":
            s, dev = _merge_node(row, pod, norm)
        elif dim_ext in ("share", "divide"):
            s, dev = _share_divide_node(row, pod, norm, dim_ext == "divide")
        else:
            s, dev = _extend_node(row, pod, norm)
        # empty match-group set → MinNodeScore (dot_product_score.go:96-98);
        # int64() conversion truncates toward zero.
        raw = jnp.where(
            s == _NEG, 0, (MAX_NODE_SCORE * s).astype(jnp.int32)
        )
        return raw, dev

    nodes = jax.vmap(per_node, in_axes=(NodeState(0, 0, 0, 0, 0, 0, 0, 0, 0), None))

    def dotprod_score(state: NodeState, pod: PodSpec, ctx: ScoreContext) -> PolicyResult:
        scores, share_dev = nodes(state, pod)
        return PolicyResult(scores, share_dev)

    dotprod_score.normalize = "none"
    dotprod_score.policy_name = "DotProductScore"
    dotprod_score.dim_ext = dim_ext
    dotprod_score.norm = norm
    return dotprod_score
