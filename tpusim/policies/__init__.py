"""Scoring-policy kernel registry (ref: pkg/simulator/plugin/*, registered via
the out-of-tree registry in pkg/simulator/simulator.go:153-181; plugin names
from pkg/type/const.go:4-13).

make_policy(name, **cfg) resolves a scheduler-config plugin name to a policy
kernel `(NodeState, PodSpec, ScoreContext) -> PolicyResult`.
"""

from __future__ import annotations

from tpusim.policies.base import (
    NORMALIZE_DEGENERATE,
    PolicyFn,
    PolicyResult,
    ScoreContext,
    feasible_min_max,
    minmax_normalize_i32,
    minmax_scale_i32,
    pwr_normalize_i32,
)
from tpusim.policies.bestfit import bestfit_score
from tpusim.policies.clustering import clustering_score
from tpusim.policies.dotprod import make_dotprod
from tpusim.policies.fgd import fgd_score
from tpusim.policies.packing import packing_score
from tpusim.policies.pwr import pwr_score
from tpusim.policies.random_policy import random_score
from tpusim.policies.simon import simon_score


_JIT_CACHE = {}


def jit_policy(fn):
    """Jitted view of a policy kernel (eager per-primitive dispatch is far
    too slow for direct calls; inside the replay scan policies are already
    traced). Preserves the policy's metadata attributes."""
    import jax

    if fn not in _JIT_CACHE:
        j = jax.jit(fn)
        j.normalize = fn.normalize
        j.policy_name = fn.policy_name
        # config attrs (DotProduct carries dim_ext/norm; the pallas-engine
        # column resolver reads them)
        for attr in ("dim_ext", "norm"):
            if hasattr(fn, attr):
                setattr(j, attr, getattr(fn, attr))
        _JIT_CACHE[fn] = j
    return _JIT_CACHE[fn]


def make_policy(name: str, dim_ext_method: str = "share", norm_method: str = "max"):
    """Plugin-name → kernel (names as in scheduler-config YAML).

    Beside the built-ins, 'LearnedScore[<feature>]' names resolve to the
    learned-policy feature kernels (ISSUE 14, tpusim.learn.policy): a
    learned policy is a FAMILY of per-feature kernels whose weights are
    the model parameters, so every engine replays it like any built-in.
    Imported lazily — the policies package stays dependency-free for
    built-in-only configs."""
    if name.startswith("LearnedScore["):
        from tpusim.learn.policy import feature_policy, parse_learned_name

        feat = parse_learned_name(name)
        if feat is None:
            raise KeyError(f"malformed learned-policy name: {name!r}")
        return feature_policy(feat)  # KeyError names the known features
    table = {
        "FGDScore": lambda: fgd_score,
        "PWRScore": lambda: pwr_score,
        "BestFitScore": lambda: bestfit_score,
        "GpuPackingScore": lambda: packing_score,
        "GpuClusteringScore": lambda: clustering_score,
        "RandomScore": lambda: random_score,
        "Simon": lambda: simon_score,
        "DotProductScore": lambda: make_dotprod(dim_ext_method, norm_method),
    }
    if name not in table:
        raise KeyError(f"unknown score plugin: {name!r}")
    return table[name]()


POLICY_NAMES = (
    "FGDScore",
    "PWRScore",
    "BestFitScore",
    "GpuPackingScore",
    "GpuClusteringScore",
    "RandomScore",
    "Simon",
    "DotProductScore",
)


def is_policy_name(name: str) -> bool:
    """Whether `name` resolves through make_policy — a built-in or a
    learned feature kernel ('LearnedScore[<feature>]', ISSUE 14). The
    validation predicate job documents / the tune CLI share, so the
    learned family flows through every config surface the built-ins do."""
    if name in POLICY_NAMES:
        return True
    if name.startswith("LearnedScore["):
        from tpusim.learn.policy import is_learned_name

        return is_learned_name(name)
    return False

# The normalizers decompose into a block-reducible reduction half
# (feasible_min_max: associative min/max, so global extrema come exactly
# from per-block extrema) and an elementwise apply half (minmax_scale_i32,
# with NORMALIZE_DEGENERATE supplying each mode's zero-range value). The
# blocked table engine and the shard_map engine rely on this split to
# reduce over block/shard summaries instead of all N nodes while staying
# bit-identical to minmax_normalize_i32 / pwr_normalize_i32.

__all__ = [
    "PolicyFn",
    "PolicyResult",
    "ScoreContext",
    "make_policy",
    "make_dotprod",
    "feasible_min_max",
    "minmax_normalize_i32",
    "minmax_scale_i32",
    "pwr_normalize_i32",
    "NORMALIZE_DEGENERATE",
    "POLICY_NAMES",
    "is_policy_name",
]
