"""GpuClustering scoring (ref: plugin/gpu_clustering_score.go:32-56).

Quartile by the node's GPU-affinity profile vs the pod's affinity class
(share-gpu / N-gpu, open-gpu-share/utils/pod.go:111-123), plus an
integer-arithmetic packing term 25·(8000 − totalGpuLeft)//8000 inside each
quartile:

  (75,100] node whose only affinity class equals the pod's
  (50, 75] node with several classes including the pod's
  (25, 50] idle node (no GPU pods at all)
  ( 0, 25] node with only different classes
  0        pod requests no GPU
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpusim.constants import MAX_NODE_SCORE, MAX_SPEC_GPU, MILLI
from tpusim.policies.base import PolicyResult, ScoreContext
from tpusim.types import NodeState, PodSpec

_Q = MAX_NODE_SCORE // 4  # 25


def pod_affinity_class(pod: PodSpec):
    """share-gpu → 0, N whole GPUs → N, no GPU → -1 (ref: pod.go:111-123)."""
    share = (pod.gpu_num == 1) & (pod.gpu_milli < MILLI)
    cls = jnp.where(share, 0, pod.gpu_num)
    return jnp.where(pod.gpu_num == 0, -1, cls).astype(jnp.int32)


def clustering_score(state: NodeState, pod: PodSpec, ctx: ScoreContext) -> PolicyResult:
    cls = pod_affinity_class(pod)
    counts = state.aff_cnt  # i32[N, 9]
    n_classes = (counts > 0).sum(-1)  # len(GpuAffinity)
    has_cls = jnp.take_along_axis(
        counts, jnp.maximum(cls, 0)[None].repeat(counts.shape[0], 0)[:, None], axis=1
    )[:, 0] > 0

    pack = _Q * (MAX_SPEC_GPU - state.total_gpu_left()) // MAX_SPEC_GPU  # i32[N]
    base = jnp.where(
        has_cls,
        jnp.where(n_classes == 1, 3 * _Q, 2 * _Q),
        jnp.where(n_classes == 0, _Q, 0),
    )
    scores = jnp.where(cls < 0, 0, base + pack).astype(jnp.int32)
    share_dev = jnp.full(state.num_nodes, -1, jnp.int32)
    return PolicyResult(scores, share_dev)


clustering_score.normalize = "none"
clustering_score.policy_name = "GpuClusteringScore"
