"""BestFit scoring (ref: plugin/best_fit_score.go:66-97).

score = trunc((1 − Σ_i w_i (free_i − req_i)/maxSpec_i) × 100), dims = {cpu,
gpu-milli}, w = 0.5/0.5, maxSpec = 128000 milli-CPU / 8000 milli-GPU.
Min-max normalized by the shared NormalizeScore extension.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpusim.constants import MAX_NODE_SCORE, MAX_SPEC_CPU, MAX_SPEC_GPU
from tpusim.policies.base import PolicyResult, ScoreContext
from tpusim.types import NodeState, PodSpec


def bestfit_score(state: NodeState, pod: PodSpec, ctx: ScoreContext) -> PolicyResult:
    free_cpu = state.cpu_left.astype(jnp.float32)
    free_gpu = state.total_gpu_left().astype(jnp.float32)
    req_cpu = pod.cpu.astype(jnp.float32)
    req_gpu = pod.total_gpu_milli().astype(jnp.float32)
    s = (free_cpu - req_cpu) / MAX_SPEC_CPU * 0.5 + (free_gpu - req_gpu) / MAX_SPEC_GPU * 0.5
    scores = jnp.floor((1.0 - s) * MAX_NODE_SCORE).astype(jnp.int32)
    # free < req would be a framework error post-Filter (best_fit_score.go:79);
    # masked rows never win anyway.
    share_dev = jnp.full(state.num_nodes, -1, jnp.int32)
    return PolicyResult(scores, share_dev)


bestfit_score.normalize = "minmax"
bestfit_score.policy_name = "BestFitScore"
