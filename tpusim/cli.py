"""`tpusim` command-line interface (ref: cmd/, the cobra `simon` tree).

Subcommands mirror the reference binary, plus the decision-provenance
verbs (ISSUE 4) and the live-telemetry verbs (ISSUE 5):
  apply    run a simulation from a Simon-CR cluster config
           (ref: cmd/apply/apply.go:14-40)
  explain  why a node won one scheduling decision: per-policy score
           table + runner-ups, from a `--decisions-out` JSONL
  diff     first-divergence finder + divergence histogram between two
           decision JSONLs (e.g. FGD vs BestFit over the same trace)
  report   terminal summary of a run record's in-scan series (min /
           median / max + sparkline per series), from a `--profile`
           JSONL of a `--series-every` run
  serve    watch a directory of run records / checkpoints and expose
           /metrics, /healthz, /progress over HTTP; --jobs additionally
           grows the POST side — a queueing what-if replay service
           (ISSUE 7: POST /jobs, GET /jobs/<id>[/result], GET /queue);
           --workers N promotes it to a kill-tolerant worker FLEET
           (ISSUE 12: leased ownership, orphan stealing, aggregated
           /queue, fleet /healthz)
  worker   join a `serve --jobs` coordinator as a fleet worker
           (ISSUE 12): claim leased batches, renew while scanning,
           write signed results into the shared artifact dir
  submit   POST what-if jobs to a `serve --jobs` service, wait, and
           print the per-job results
  tune     learned-scoring lane (ISSUE 9): ES/CMA tuning of the
           per-policy score weights over the vectorized sweep, local
           or against a `serve --jobs` rollout service, with a
           digest-signed resumable tuning log and a held-out
           tuned-vs-default report
  version  print version/commit (ref: cmd/version/version.go)
  gen-doc  emit markdown docs for the CLI tree (ref: cmd/doc/)
  debug    scaffold, intentionally empty (ref: cmd/debug/debug.go)

Log level comes from env LOGLEVEL (debug|info|warn|error), matching
cmd/simon/simon.go:52-72.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

VERSION = "0.1.0"
COMMIT = os.environ.get("TPUSIM_COMMIT", "dev")


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpusim",
        description="TPU-native Kubernetes GPU-cluster scheduling simulator",
    )
    sub = parser.add_subparsers(dest="command")

    p_apply = sub.add_parser("apply", help="run a simulation")
    p_apply.add_argument(
        "-f", "--simon-config", required=True, help="cluster-config YAML (Simon CR)"
    )
    p_apply.add_argument(
        "-s",
        "--default-scheduler-config",
        default="",
        help="KubeSchedulerConfiguration YAML",
    )
    p_apply.add_argument(
        "--use-greed", action="store_true", help="greedy app-pod queue sort"
    )
    p_apply.add_argument(
        "-i", "--interactive", action="store_true", help="confirm app list"
    )
    p_apply.add_argument(
        "-e",
        "--extended-resources",
        default="gpu",
        help="comma-separated: gpu, open-local",
    )
    p_apply.add_argument(
        "--base-dir",
        default=".",
        help="root for relative paths inside the CR (default: cwd)",
    )
    p_apply.add_argument(
        "--report", action="store_true", help="print placement report tables"
    )
    # exact checkpoint/resume of the main replay (README "Checkpoint/resume")
    p_apply.add_argument(
        "--checkpoint-every", type=int, default=0, metavar="EVENTS",
        help="checkpoint the replay every N events (0 = off); a killed run "
        "re-invoked with identical inputs resumes bit-identically",
    )
    p_apply.add_argument(
        "--checkpoint-dir", default="",
        help="checkpoint directory (default: $TPUSIM_CHECKPOINT_DIR or "
        "<repo>/.tpusim_checkpoints)",
    )
    p_apply.add_argument(
        "--checkpoint-keep", type=int, default=0, metavar="N",
        help="checkpoint retention: 0 prunes behind the run (resume-only,"
        " the default), -1 keeps every segment carry (the warm-state "
        "fork ladder), N>0 keeps the newest N",
    )
    # fault injection (README "Fault injection"); all rates in EVENTS
    p_apply.add_argument(
        "--fault-mtbf", type=float, default=0.0, metavar="EVENTS",
        help="mean events between node failures (0 = no failures)",
    )
    p_apply.add_argument(
        "--fault-mttr", type=float, default=0.0, metavar="EVENTS",
        help="mean events until a failed node recovers (0 = permanent loss)",
    )
    p_apply.add_argument(
        "--fault-evict-every", type=float, default=0.0, metavar="EVENTS",
        help="mean events between single-pod evictions (0 = off)",
    )
    p_apply.add_argument(
        "--fault-seed", type=int, default=0,
        help="fault-schedule PRNG seed (fixed seed -> identical disruption)",
    )
    p_apply.add_argument(
        "--fault-max-retries", type=int, default=3,
        help="retry budget per evicted pod before it becomes terminally "
        "unscheduled",
    )
    # observability (README "Profiling & telemetry"; tpusim.obs)
    p_apply.add_argument(
        "--profile", nargs="?",
        const=os.path.join(".tpusim_obs", "tpusim_profile.jsonl"),
        default="", metavar="PATH",
        help="profile the run and append a JSONL run record (spans with "
        "compile/execute split, exact scan counters, degrade/fault "
        "counts); default path .tpusim_obs/tpusim_profile.jsonl (the "
        "ignored obs scratch dir — smoke artifacts stay out of the tree)",
    )
    p_apply.add_argument(
        "--metrics-out", default="", metavar="PATH",
        help="write a Prometheus textfile-collector snapshot of the run's "
        "telemetry (atomic rewrite; also enables profiling)",
    )
    p_apply.add_argument(
        "--trace-out", default="", metavar="PATH",
        help="write a Chrome-trace (chrome://tracing / Perfetto) timeline "
        "of the run's phase spans (also enables profiling)",
    )
    p_apply.add_argument(
        "--heartbeat-every", type=int, default=0, metavar="EVENTS",
        help="emit an in-scan progress line (events/s, ETA) every N "
        "processed events of long table-engine scans (0 = off)",
    )
    p_apply.add_argument(
        "--decisions-out", default="", metavar="PATH",
        help="record per-event decision provenance (winner, per-policy "
        "score contributions, top-K runner-ups) and write it as JSONL — "
        "the input of `tpusim explain` / `tpusim diff`",
    )
    # live cluster telemetry (README "Live monitoring"; ISSUE 5)
    p_apply.add_argument(
        "--series-every", type=int, default=0, metavar="EVENTS",
        help="sample the in-scan cluster time-series plane (utilization "
        "histogram, per-category frag, feasible count, per-policy score "
        "extrema) every N processed events (0 = off); lands in the "
        "--profile JSONL, the Chrome counter tracks, and `tpusim report`",
    )
    p_apply.add_argument(
        "--listen", default="", metavar="[HOST]:PORT",
        help="serve /metrics, /healthz, /progress over HTTP for the "
        "run's lifetime (the final /metrics scrape is byte-equal to "
        "--metrics-out); bare :PORT binds loopback only",
    )
    # config-axis sweep (ISSUE 6; README "Sweep many configs in one
    # compile")
    p_apply.add_argument(
        "--sweep-weights", default="", metavar="WEIGHTS.json",
        help="replace the main schedule with ONE vmapped what-if sweep "
        "over this [B, num_policies] weight grid (bare list-of-rows or "
        '{"weights": [[...]], "seeds": [...]}) and print the per-config '
        "summary table (gpu_alloc, frag, placed) — B configs, one "
        "compiled scan",
    )
    # chaos sweep (ISSUE 10; README "Chaos sweep")
    p_apply.add_argument(
        "--sweep-faults", default="", metavar="FAULTS.json",
        help="replace the main schedule with ONE vmapped chaos sweep: "
        "same trace, B fault schedules (per-lane FaultConfig documents — "
        "mtbf_events/mttr_events/evict_every_events/seed/backoff knobs; "
        'bare list or {"faults": [...], "weights": [[...]], "seeds": '
        "[...]}) and print the per-lane disruption frontier — B fault "
        "what-ifs, one compiled scan",
    )
    p_apply.add_argument(
        "--compile-cache-dir", default="", metavar="DIR",
        help="JAX persistent compilation cache (default "
        "$TPUSIM_COMPILE_CACHE_DIR): re-runs of the same job family "
        "load the compiled scan from disk instead of re-compiling; the "
        "obs record notes the probable hit/miss",
    )
    # the learned policy as a drop-in scorer (ISSUE 14)
    p_apply.add_argument(
        "--policy", default="", metavar="SPEC",
        help="override the scheduler-config score plugins: "
        "'LearnedScore:FILE.json' replays a signed learned-policy "
        "artifact (trained via `tpusim imitate` / `tpusim tune "
        "--policy learned`), 'learned'/'learned-bucketed' the "
        "default-parameter families, or a built-in policy name at "
        "weight 1000",
    )

    p_explain = sub.add_parser(
        "explain",
        help="why a node won one scheduling decision (per-policy score "
        "table from a --decisions-out JSONL)",
    )
    p_explain.add_argument("decisions", help="decision JSONL file")
    p_explain.add_argument(
        "-e", "--event", type=int, required=True,
        help="event index to explain",
    )

    p_diff = sub.add_parser(
        "diff",
        help="first-divergence finder + divergence histogram between two "
        "decision JSONLs (two runs/policies over the same trace)",
    )
    p_diff.add_argument("run_a", help="decision JSONL of run A")
    p_diff.add_argument("run_b", help="decision JSONL of run B")
    p_diff.add_argument(
        "--buckets", type=int, default=10,
        help="event-range buckets of the divergence histogram",
    )

    p_report = sub.add_parser(
        "report",
        help="terminal summary of a run record's in-scan series "
        "(min/median/max + sparkline, straight from the JSONL — no "
        "recomputation)",
    )
    p_report.add_argument(
        "run", help="run-record JSONL (a --profile output of a "
        "--series-every run)",
    )

    p_serve = sub.add_parser(
        "serve",
        help="watch a directory of run records / checkpoints and expose "
        "/metrics, /healthz, /progress over HTTP",
    )
    p_serve.add_argument(
        "dir", help="directory to watch (run-record JSONLs and "
        "io.storage checkpoint files)",
    )
    p_serve.add_argument(
        "--listen", default="", metavar="[HOST]:PORT",
        help="bind address (default loopback on port 8642); bare :PORT "
        "binds loopback only",
    )
    p_serve.add_argument(
        "--poll", type=float, default=2.0, metavar="SECONDS",
        help="directory poll interval",
    )
    p_serve.add_argument(
        "--once", action="store_true",
        help="publish a single poll, self-scrape /metrics and /healthz, "
        "print the verdict, and exit (the `make serve-smoke` mode; with "
        "--jobs it additionally self-checks /queue)",
    )
    # the queueing what-if replay service (ISSUE 7; README "Simulation
    # as a service"): POST /jobs onto the one-compile sweep axis
    p_serve.add_argument(
        "--jobs", action="store_true",
        help="grow the POST side: accept what-if replay jobs (policy "
        "weights x seed x tune factor over the hosted trace), batch "
        "compatible jobs onto ONE vmapped compiled scan, dedup "
        "identical jobs by content digest, and persist signed results "
        "into DIR; needs --nodes/--pods",
    )
    p_serve.add_argument(
        "--nodes", default="", metavar="CSV",
        help="node CSV of the hosted trace (--jobs mode)",
    )
    p_serve.add_argument(
        "--pods", default="", metavar="CSV",
        help="pod CSV of the hosted trace (--jobs mode)",
    )
    p_serve.add_argument(
        "--max-pods", type=int, default=0, metavar="N",
        help="truncate the hosted workload to its first N pods (0 = all)",
    )
    # multi-trace hosting (ISSUE 13): families already key by trace
    # name, so batching stays per-(trace, family) with one compiled
    # scan per family
    p_serve.add_argument(
        "--trace", action="append", default=[],
        metavar="NAME=NODES.csv:PODS.csv[:MAX_PODS]",
        help="host an ADDITIONAL named trace (repeatable); jobs select "
        'it via their "trace" key. --nodes/--pods host the trace named '
        "'default'; at least one trace must be given either way",
    )
    p_serve.add_argument(
        "--lane-width", type=int, default=8, metavar="B",
        help="sweep lanes per batch: up to B compatible jobs share one "
        "compiled scan (short batches pad to B so the executable count "
        "stays at one per job family)",
    )
    p_serve.add_argument(
        "--queue-size", type=int, default=64, metavar="N",
        help="bounded job queue depth; a full queue answers POST /jobs "
        "with 429 + Retry-After",
    )
    # the worker fleet (ISSUE 12; README "Worker fleet")
    p_serve.add_argument(
        "--workers", type=int, default=0, metavar="N",
        help="spawn N worker PROCESSES draining the one job queue "
        "under leased ownership (signed lease files, orphan stealing — "
        "a kill -9'd worker's jobs are reclaimed by any live worker); "
        "0 keeps the single in-process worker thread. Remote hosts "
        "join the same fleet with `tpusim worker --join URL`",
    )
    p_serve.add_argument(
        "--max-workers", type=int, default=0, metavar="M",
        help="autoscale ceiling (ISSUE 13; needs --workers N, M >= N): "
        "a queue backlog deeper than the live fleet can chew spawns "
        "extra workers up to M; an idle queue drains back down to N "
        "(graceful SIGTERM). The supervisor also respawns crashed "
        "children under capped backoff, with a crash-loop circuit "
        "breaker that degrades /healthz instead of spinning",
    )
    p_serve.add_argument(
        "--lease-s", type=float, default=0.0, metavar="SECONDS",
        help="job lease duration (default 15): a worker silent this "
        "long past its deadline forfeits its batch to the fleet",
    )
    p_serve.add_argument(
        "--family-quota", type=int, default=0, metavar="N",
        help="per-family admission quota: at most N queued jobs per "
        "job family (a hot trace can't starve the rest); overflow "
        "answers 429 + Retry-After naming the family (0 = no cap)",
    )
    # named learned-policy presets (ISSUE 14): the fleet serves a
    # trained artifact exactly like a built-in policy family
    p_serve.add_argument(
        "--policy-preset", action="append", default=[],
        metavar="NAME=ARTIFACT.json",
        help="register a named learned-policy preset from a signed "
        "artifact (repeatable); submit jobs reference it via "
        '{"policy_preset": "NAME"} and replay byte-identically to the '
        "artifact run locally",
    )
    # coordinator HA (ISSUE 17): leadership is one more signed file in
    # the artifact dir — a standby watches it and takes over, epoch-
    # fenced against the deposed leader
    p_serve.add_argument(
        "--standby", action="store_true",
        help="start as a STANDBY coordinator: watch the artifact dir's "
        "coordinator.lease.json and take over (bump the epoch, adopt "
        "pending jobs and live worker leases) when the leader's lease "
        "goes stale; mutating endpoints answer 503 + Retry-After until "
        "promotion. Implies --fleet",
    )
    p_serve.add_argument(
        "--fleet", action="store_true",
        help="arm the fleet coordinator plane (register/claim/renew/"
        "complete + the HA leadership lease) WITHOUT spawning local "
        "workers — remote hosts join with `tpusim worker --join`; "
        "--workers N implies it",
    )
    p_serve.add_argument(
        "--token-file", default="", metavar="FILE",
        help="bearer token (the file's stripped contents; or env "
        "TPUSIM_FLEET_TOKEN) required on every mutating endpoint — "
        "POST /jobs, claim/renew/complete/leases, result uploads, "
        "register. Constant-time compare; 401 without leaking whether "
        "a digest exists; token material never appears in logs or "
        "/queue",
    )
    p_serve.add_argument(
        "--slo-file", default="", metavar="FILE",
        help="SLO/alert rules JSON (threshold + multi-window burn-rate "
        "over the in-process metrics history; see obs.alerts) — "
        "overrides/extends the built-in defaults; firing transitions "
        "append kind=alert audit records, surface on GET /alerts, and "
        "page-severity burn flips /healthz (default $TPUSIM_SLO_FILE)",
    )
    p_serve.add_argument(
        "--table-cache-dir", default="", metavar="DIR",
        help="content-keyed init-table cache shared by the fleet "
        "(default $TPUSIM_TABLE_CACHE_DIR)",
    )
    p_serve.add_argument(
        "--compile-cache-dir", default="", metavar="DIR",
        help="JAX persistent compile cache shared by the fleet — a "
        "fresh joiner's first batch skips the ~5 s compile (default "
        "$TPUSIM_COMPILE_CACHE_DIR)",
    )

    # the fleet worker process (ISSUE 12): joins a `serve --jobs`
    # coordinator, pulls leased batches, writes signed results into the
    # shared artifact dir
    p_worker = sub.add_parser(
        "worker",
        help="join a `tpusim serve --jobs` coordinator as a fleet "
        "worker: claim leased batches, run them on this host's device, "
        "write signed results into the shared artifact dir, renew "
        "leases while scanning; SIGTERM drains the in-flight batch",
    )
    p_worker.add_argument(
        "--join", required=True, metavar="URL[,URL...]",
        help="coordinator base URL (the address `serve --jobs` "
        "printed); a comma-separated list names an HA pair/set — the "
        "worker rotates to the next coordinator on connection failure "
        "or standby 503, on the shared backoff schedule (ISSUE 17)",
    )
    p_worker.add_argument(
        "--id", default="", metavar="NAME",
        help="worker id (default: coordinator-assigned)",
    )
    p_worker.add_argument(
        "--poll", type=float, default=0.2, metavar="SECONDS",
        help="idle claim-poll interval",
    )
    p_worker.add_argument(
        "--max-batches", type=int, default=0, metavar="N",
        help="exit after serving N batches (0 = run until stopped)",
    )
    p_worker.add_argument(
        "--table-cache-dir", default="", metavar="DIR",
        help="shared content-keyed table cache",
    )
    p_worker.add_argument(
        "--compile-cache-dir", default="", metavar="DIR",
        help="shared JAX persistent compile cache",
    )
    # the no-shared-fs transport (ISSUE 13)
    p_worker.add_argument(
        "--mode", choices=("auto", "shared-fs", "remote"),
        default="auto",
        help="artifact-plane topology: shared-fs reads trace CSVs by "
        "path and writes results into the shared artifact dir; remote "
        "needs NO shared filesystem (digest-verified trace downloads "
        "into a local cache, signed-result uploads, lease POSTs); "
        "auto probes the handshake's paths and picks",
    )
    p_worker.add_argument(
        "--cache-dir", default="", metavar="DIR",
        help="remote-mode local cache root (downloaded traces keyed "
        "by content digest + this worker's artifact scratch); default "
        "a per-host tmp dir",
    )
    p_worker.add_argument(
        "--token-file", default="", metavar="FILE",
        help="bearer token for an auth-armed fleet (the file's "
        "stripped contents; or env TPUSIM_FLEET_TOKEN)",
    )

    # the learned-scoring lane (ISSUE 9; README "Tune policy weights"):
    # ES/CMA weight tuning over the vectorized sweep, with the job plane
    # as an optional remote rollout farm
    p_tune = sub.add_parser(
        "tune",
        help="tune the per-policy score weights with ES/CMA over the "
        "vectorized sweep (one compiled scan per generation; --url "
        "offloads rollouts to a `serve --jobs` service) and report "
        "tuned-vs-default on a held-out trace suffix",
    )
    p_tune.add_argument(
        "--nodes", required=True, metavar="CSV",
        help="node CSV of the tuning trace",
    )
    p_tune.add_argument(
        "--pods", required=True, metavar="CSV",
        help="pod CSV of the tuning trace",
    )
    p_tune.add_argument(
        "--max-pods", type=int, default=0, metavar="N",
        help="truncate the workload to its first N pods (0 = all)",
    )
    p_tune.add_argument(
        "--policies", default='[["FGDScore", 1000], ["BestFitScore", 500]]',
        metavar="JSON",
        help="policy family as [[name, default_weight], ...]; the "
        "default weights seed the optimizer AND are the held-out "
        "report's baseline",
    )
    # the learned policy as the tuned family (ISSUE 14): the parameter
    # vector IS the weight vector, so ES/CMA search over it reuses the
    # whole one-compile sweep machinery unchanged
    p_tune.add_argument(
        "--policy", default="", metavar="SPEC",
        help="tune a LEARNED policy instead of --policies: 'learned' "
        "(the linear feature vocabulary, FGD-equivalent init), "
        "'learned-bucketed' (plus the 10 occupancy-bucket table "
        "features), or 'LearnedScore:FILE.json' (resume search from a "
        "signed artifact, e.g. an imitation-trained one); --best-out "
        "then writes a signed policy ARTIFACT, and the weight bounds "
        "default to the symmetric [-4000, 4000] parameter space",
    )
    p_tune.add_argument(
        "--algo", choices=("es", "cma"), default="es",
        help="optimizer: antithetic OpenAI-ES or diagonal CMA-ES",
    )
    p_tune.add_argument("--generations", type=int, default=10)
    p_tune.add_argument("--popsize", type=int, default=8)
    p_tune.add_argument(
        "--sigma", type=float, default=250.0,
        help="initial perturbation scale in weight units",
    )
    p_tune.add_argument(
        "--lr", type=float, default=300.0,
        help="ES step size in weight units (cma adapts its own)",
    )
    p_tune.add_argument(
        "--seed", type=int, default=0,
        help="optimizer draw seed (fixed seed -> byte-identical log)",
    )
    p_tune.add_argument(
        "--eval-seed", type=int, default=42,
        help="replay seed every candidate shares (common random numbers)",
    )
    p_tune.add_argument(
        "--w-min", type=int, default=None,
        help="weight lower bound (default 0; -4000 under --policy "
        "learned — feature signs are meaningful)",
    )
    p_tune.add_argument(
        "--w-max", type=int, default=None,
        help="weight upper bound (default 4000)",
    )
    p_tune.add_argument(
        "--obj-alloc", type=float, default=1.0,
        help="objective weight on gpu_alloc_pct",
    )
    p_tune.add_argument(
        "--obj-frag", type=float, default=1.0,
        help="objective weight on frag percent of cluster GPU",
    )
    p_tune.add_argument(
        "--obj-unsched", type=float, default=1.0,
        help="objective weight on unscheduled percent of pods",
    )
    p_tune.add_argument(
        "--holdout", type=float, default=0.2, metavar="FRAC",
        help="trailing fraction of the pod list held out of tuning and "
        "used for the final tuned-vs-default report (0 disables)",
    )
    p_tune.add_argument(
        "--log", default=os.path.join(".tpusim_obs", "tune_log.jsonl"),
        metavar="PATH",
        help="digest-signed tuning log (JSONL; the --resume input and "
        "the `analysis --plot-tuning` source)",
    )
    p_tune.add_argument(
        "--resume", action="store_true",
        help="continue from the log's last generation (byte-identical "
        "to an uninterrupted run under the same flags)",
    )
    p_tune.add_argument(
        "--url", default="", metavar="URL",
        help="offload rollouts to a `tpusim serve --jobs` service (it "
        "must host the tuning trace prefix); default: local vmapped "
        "sweeps",
    )
    p_tune.add_argument(
        "--engine", choices=("auto", "table", "sequential"),
        default="auto", help="replay engine for the rollouts",
    )
    p_tune.add_argument(
        "--best-out", default="", metavar="PATH",
        help="write the tuned weight vector as a weights-grid JSON "
        "(apply --sweep-weights / submit shape)",
    )
    p_tune.add_argument(
        "--robust-mtbf", type=float, default=0.0, metavar="EVENTS",
        help="per-generation robustness eval: replay the generation "
        "best through seeded fault injection with this MTBF (0 = off; "
        "logged, not fed back into the optimizer)",
    )
    p_tune.add_argument(
        "--robust-mttr", type=float, default=0.0, metavar="EVENTS",
        help="mean events until a failed node recovers in the "
        "robustness eval",
    )
    p_tune.add_argument("--robust-seed", type=int, default=0)
    # chaos-sweep training (ISSUE 10): roll the POPULATION itself through
    # a seeded fault schedule (one compiled faulted scan per generation)
    # so the objective's disruption term trains directly
    p_tune.add_argument(
        "--train-fault-mtbf", type=float, default=0.0, metavar="EVENTS",
        help="train under disruption: every rollout lane replays under "
        "a seeded fault schedule with this MTBF (0 = fault-free "
        "training); local backend only",
    )
    p_tune.add_argument(
        "--train-fault-mttr", type=float, default=0.0, metavar="EVENTS")
    p_tune.add_argument(
        "--train-fault-evict-every", type=float, default=0.0,
        metavar="EVENTS")
    p_tune.add_argument("--train-fault-seed", type=int, default=0)
    p_tune.add_argument(
        "--obj-disrupt", type=float, default=0.0,
        help="objective weight on pods terminally lost to disruption "
        "(percent of trace pods); needs --train-fault-* to be non-zero "
        "to matter",
    )
    p_tune.add_argument(
        "--timeout", type=float, default=600.0, metavar="SECONDS",
        help="per-generation wait budget on the remote backend",
    )

    # the imitation trainer (ISSUE 14; README "Train and serve a learned
    # policy"): decision JSONL -> (feature-row, chosen, runner-up)
    # tuples -> a trained, i32-exported, digest-signed policy artifact
    p_imitate = sub.add_parser(
        "imitate",
        help="train a learned policy to imitate a recorded teacher: "
        "teacher-force the trace through a --decisions-out JSONL, build "
        "(winner, runner-up) feature pairs, fit the linear scorer, "
        "export it into the engines' i32 vocabulary, and report "
        "held-out top-1 agreement",
    )
    p_imitate.add_argument(
        "--nodes", required=True, metavar="CSV",
        help="node CSV of the recorded trace",
    )
    p_imitate.add_argument(
        "--pods", required=True, metavar="CSV",
        help="pod CSV of the recorded trace",
    )
    p_imitate.add_argument(
        "--decisions", required=True, metavar="JSONL",
        help="the teacher run's decision log (`tpusim apply "
        "--decisions-out`) — digest-verified on load",
    )
    p_imitate.add_argument(
        "--max-pods", type=int, default=0, metavar="N",
        help="truncate the workload to its first N pods (must match the "
        "recorded run)",
    )
    p_imitate.add_argument(
        "--features", choices=("linear", "bucketed"), default="linear",
        help="feature vocabulary: the 10 linear node/pod features, or "
        "plus the 10 occupancy-bucket table features",
    )
    p_imitate.add_argument("--steps", type=int, default=500)
    p_imitate.add_argument("--lr", type=float, default=0.15)
    p_imitate.add_argument("--l2", type=float, default=1e-4)
    p_imitate.add_argument("--seed", type=int, default=0)
    p_imitate.add_argument(
        "--holdout", type=float, default=0.2, metavar="FRAC",
        help="trailing fraction of EVENTS held out of training; the "
        "reported agreement is teacher-forced top-1 on this suffix",
    )
    p_imitate.add_argument(
        "--out", default="", metavar="PATH",
        help="write the trained policy as a digest-signed artifact "
        "(the `apply --policy LearnedScore:FILE.json` / `serve "
        "--policy-preset` / `tune --policy LearnedScore:FILE.json` "
        "input)",
    )

    p_submit = sub.add_parser(
        "submit",
        help="POST what-if jobs to a `tpusim serve --jobs` replay "
        "service, wait for completion, and print the per-job results",
    )
    p_submit.add_argument(
        "jobs",
        help="job JSON: one job object, {\"jobs\": [...]}, or an "
        "apply-style weights grid ([[w, ...], ...] or {\"weights\": "
        "[[...]], \"seeds\": [...], \"tunes\": [...], \"policies\": "
        "[[name, w], ...]})",
    )
    p_submit.add_argument(
        "--url", required=True, metavar="URL[,URL...]",
        help="service base URL (the address `serve --jobs` printed, "
        "e.g. http://127.0.0.1:8642); a comma-separated list names an "
        "HA pair/set — the client fails over to the next coordinator "
        "when one dies mid-wait (re-submission dedups by job digest)",
    )
    p_submit.add_argument(
        "--timeout", type=float, default=300.0, metavar="SECONDS",
        help="overall wait budget for results",
    )
    p_submit.add_argument(
        "--token-file", default="", metavar="FILE",
        help="bearer token for an auth-armed service (the file's "
        "stripped contents; or env TPUSIM_FLEET_TOKEN)",
    )

    p_trace = sub.add_parser(
        "trace",
        help="stitch a job's cross-process fleet timeline from the "
        "artifact dir's span files (admission, queue wait, claim, "
        "dispatch, upload, verify — abandoned attempts included)",
    )
    p_trace.add_argument(
        "job", nargs="?", default="",
        help="job digest (or unique prefix); omit for every span",
    )
    p_trace.add_argument(
        "-d", "--dir", default="runs", metavar="DIR",
        help="artifact dir the coordinator served from",
    )
    p_trace.add_argument(
        "--trace-id", default="", metavar="ID",
        help="filter by trace id instead of (or as well as) job digest",
    )
    p_trace.add_argument(
        "--out", default="", metavar="FILE",
        help="also write a Chrome-trace JSON (one track per process; "
        "open in chrome://tracing or Perfetto)",
    )

    p_audit = sub.add_parser(
        "audit",
        help="query or verify the hash-chained control-plane audit "
        "log (takeovers, depositions, steals, lease expiries, "
        "requeues, breaker trips, fence hits, degrades)",
    )
    p_audit.add_argument(
        "-d", "--dir", default="runs", metavar="DIR",
        help="artifact dir holding audit.jsonl",
    )
    p_audit.add_argument(
        "--verify", action="store_true",
        help="walk the whole chain + head sidecar; exit 1 loudly on "
        "any edit, truncation, or torn tail",
    )
    p_audit.add_argument(
        "--tail", type=int, default=20, metavar="N",
        help="show the last N matching records (0 = all)",
    )
    p_audit.add_argument("--kind", default="",
                         help="filter by record kind")
    p_audit.add_argument("--job", default="",
                         help="filter by job digest (prefix ok)")
    p_audit.add_argument("--worker", default="",
                         help="filter by worker id")
    p_audit.add_argument(
        "--url", default="", metavar="URL",
        help="tail a LIVE coordinator over HTTP instead of reading "
        "local files: polls GET /events with the seq cursor "
        "(?after=&limit=) so each poll ships only the delta",
    )
    p_audit.add_argument(
        "--follow", action="store_true",
        help="with --url: keep polling the cursor (Ctrl-C to stop)",
    )

    # the live fleet dashboard (ISSUE 20)
    p_top = sub.add_parser(
        "top",
        help="live terminal dashboard for a serve --jobs coordinator: "
        "queue, workers, firing alerts, and sparkline history "
        "stitched from /queue, /workers, /alerts, /query",
    )
    p_top.add_argument("url", help="coordinator base URL "
                       "(e.g. http://127.0.0.1:8642)")
    p_top.add_argument(
        "--interval", type=float, default=2.0, metavar="S",
        help="redraw interval seconds (default 2)",
    )
    p_top.add_argument(
        "--once", action="store_true",
        help="render one frame and exit (no screen clearing) — the "
        "scriptable/smoke form",
    )
    p_top.add_argument(
        "--width", type=int, default=0, metavar="COLS",
        help="frame width (default: terminal width, floor 60)",
    )

    sub.add_parser("version", help="print version")

    p_doc = sub.add_parser("gen-doc", help="generate markdown CLI docs")
    p_doc.add_argument("-d", "--dir", default="docs", help="output directory")

    sub.add_parser("debug", help="debug scaffold (no-op, ref parity)")
    return parser


def cmd_apply(args) -> int:
    from tpusim.apply import Applier, ApplyOptions

    opts = ApplyOptions(
        simon_config=args.simon_config,
        default_scheduler_config=args.default_scheduler_config,
        use_greed=args.use_greed,
        interactive=args.interactive,
        extended_resources=[
            e.strip() for e in args.extended_resources.split(",") if e.strip()
        ],
        base_dir=args.base_dir,
        report_tables=args.report,
        checkpoint_every=args.checkpoint_every,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_keep=args.checkpoint_keep,
        fault_mtbf=args.fault_mtbf,
        fault_mttr=args.fault_mttr,
        fault_evict_every=args.fault_evict_every,
        fault_seed=args.fault_seed,
        fault_max_retries=args.fault_max_retries,
        profile_out=args.profile,
        metrics_out=args.metrics_out,
        trace_out=args.trace_out,
        heartbeat_every=args.heartbeat_every,
        decisions_out=args.decisions_out,
        series_every=args.series_every,
        listen=args.listen,
        sweep_weights=args.sweep_weights,
        sweep_faults=args.sweep_faults,
        compile_cache_dir=args.compile_cache_dir,
        policy=args.policy,
    )
    Applier(opts).run()
    return 0


def cmd_explain(args) -> int:
    from tpusim.obs import decisions as obs_decisions

    # diff(1)-style exit codes: 0 ok, 2 on unusable input (missing /
    # torn / digest-mismatched file, event out of range) — a one-line
    # error, not a traceback
    try:
        header, rows = obs_decisions.read_decisions(args.decisions)
        print(obs_decisions.format_explain(header, rows, args.event))
    except (OSError, ValueError, json.JSONDecodeError) as err:
        print(f"tpusim explain: {err}", file=sys.stderr)
        return 2
    return 0


def cmd_diff(args) -> int:
    from tpusim.obs import decisions as obs_decisions

    try:
        ha, ra = obs_decisions.read_decisions(args.run_a)
        hb, rb = obs_decisions.read_decisions(args.run_b)
        # run_diff also rejects files from DIFFERENT traces (per-row
        # kind/pod mismatch) — a ValueError, not a bogus divergence
        d = obs_decisions.run_diff(
            ha, ra, hb, rb,
            label_a=os.path.basename(args.run_a),
            label_b=os.path.basename(args.run_b),
            buckets=args.buckets,
        )
    except (OSError, ValueError, json.JSONDecodeError) as err:
        print(f"tpusim diff: {err}", file=sys.stderr)
        return 2
    print(d["text"])
    # like diff(1): exit 0 on identical placements, 1 on divergence
    return 1 if d["first"] else 0


def cmd_report(args) -> int:
    from tpusim.obs.emitters import read_jsonl
    from tpusim.obs.series import format_report

    # same exit discipline as explain/diff: 2 on unusable input, with a
    # one-line error instead of a traceback
    try:
        records = read_jsonl(args.run)
        with_series = [r for r in records if r.get("series")]
        if not with_series:
            raise ValueError(
                f"{args.run}: no record carries a series block (was the "
                "run made with --series-every and --profile?)"
            )
        print(format_report(with_series[-1]["series"]))
    except (OSError, ValueError, json.JSONDecodeError) as err:
        print(f"tpusim report: {err}", file=sys.stderr)
        return 2
    return 0


def cmd_serve(args) -> int:
    from tpusim.obs.server import serve_dir

    try:
        if args.jobs:
            return _serve_jobs(args)
        if args.once:
            # smoke mode: one poll, a real self-scrape over HTTP, exit.
            # Exit 2 when the scrape fails or the /metrics text does not
            # parse — the `make serve-smoke` verdict.
            import urllib.request

            from tpusim.obs.emitters import parse_prometheus_text

            srv = serve_dir(args.dir, listen=args.listen,
                            poll_s=args.poll, once=True, out=sys.stderr)
            try:
                with urllib.request.urlopen(srv.url + "/healthz",
                                            timeout=10) as r:
                    health = json.loads(r.read().decode())
                try:
                    with urllib.request.urlopen(srv.url + "/metrics",
                                                timeout=10) as r:
                        text = r.read().decode()
                except urllib.error.HTTPError as err:
                    # 503 = no run record in the directory yet — the
                    # server is healthy, there is just nothing to scrape
                    print(f"[serve] once: healthz ok={health.get('ok')}, "
                          f"no run record yet (/metrics {err.code})",
                          file=sys.stderr)
                else:
                    n = len(parse_prometheus_text(text))
                    print(f"[serve] once: healthz ok={health.get('ok')}, "
                          f"/metrics parses ({n} series)", file=sys.stderr)
            finally:
                srv.stop()
            return 0
        serve_dir(args.dir, listen=args.listen, poll_s=args.poll,
                  out=sys.stderr)
    except (OSError, ValueError) as err:
        print(f"tpusim serve: {err}", file=sys.stderr)
        return 2
    return 0


def parse_trace_arg(entry: str):
    """One `--trace NAME=NODES.csv:PODS.csv[:MAX_PODS]` entry ->
    (name, nodes_csv, pods_csv, max_pods), failing loudly on anything
    malformed (ISSUE 13 multi-trace hosting)."""
    name, sep, rest = entry.partition("=")
    name = name.strip()
    if not sep or not name:
        raise ValueError(
            f"--trace {entry!r}: want NAME=NODES.csv:PODS.csv[:MAX_PODS]"
        )
    parts = rest.split(":")
    if len(parts) not in (2, 3) or not parts[0] or not parts[1]:
        raise ValueError(
            f"--trace {entry!r}: want NAME=NODES.csv:PODS.csv[:MAX_PODS]"
        )
    max_pods = 0
    if len(parts) == 3:
        try:
            max_pods = int(parts[2])
        except ValueError:
            raise ValueError(
                f"--trace {entry!r}: MAX_PODS must be an integer, got "
                f"{parts[2]!r}"
            )
    return name, parts[0], parts[1], max_pods


def _serve_jobs(args) -> int:
    """`tpusim serve DIR --jobs`: the queueing what-if replay service
    (ISSUE 7) — the monitor plane plus POST /jobs over the hosted
    trace(s); signed results land in DIR, which is also watched/
    republished like plain serve. --workers N runs the self-healing
    supervisor (ISSUE 13): respawn-on-exit with capped backoff, a
    crash-loop circuit breaker, and --max-workers M autoscale."""
    import time
    import urllib.request

    from tpusim.obs.server import watch_dir
    from tpusim.svc import load_trace, start_job_server
    from tpusim.svc.api import recover_pending_jobs
    from tpusim.svc.auth import describe as auth_describe
    from tpusim.svc.auth import load_token
    from tpusim.svc.coord import CoordinatorState, CoordKeeper

    traces = {}
    if args.nodes or args.pods:
        if not (args.nodes and args.pods):
            raise ValueError(
                "serve --jobs hosts a trace: pass BOTH --nodes "
                "NODES.csv and --pods PODS.csv"
            )
        traces["default"] = load_trace(
            "default", args.nodes, args.pods, max_pods=args.max_pods
        )
    for entry in args.trace:
        name, nodes_csv, pods_csv, max_pods = parse_trace_arg(entry)
        if name in traces:
            raise ValueError(f"--trace {name!r} given twice")
        traces[name] = load_trace(name, nodes_csv, pods_csv,
                                  max_pods=max_pods)
    if not traces:
        raise ValueError(
            "serve --jobs hosts at least one trace: pass --nodes/--pods "
            "(the trace named 'default') and/or --trace NAME=..."
        )
    fleet_n = int(getattr(args, "workers", 0) or 0)
    max_n = int(getattr(args, "max_workers", 0) or 0)
    if max_n and not fleet_n:
        raise ValueError("--max-workers needs --workers N")
    standby = bool(getattr(args, "standby", False))
    fleet_mode = fleet_n > 0 or standby or bool(getattr(args, "fleet", False))
    token = load_token(getattr(args, "token_file", ""))
    # the HA leadership lease (ISSUE 17): armed in fleet mode only —
    # the single in-process-worker service of PR 7 has no standby to
    # fence against and stays exactly as it was
    coord = None
    if fleet_mode:
        try:
            host = os.uname().nodename
        except (AttributeError, OSError):
            host = "localhost"
        coord = CoordinatorState(
            args.dir, name=f"{host}-{os.getpid()}", out=sys.stderr
        )
        if not standby:
            if not coord.try_acquire():
                print(
                    "[serve] another coordinator holds a LIVE "
                    "leadership lease (epoch "
                    f"{coord.epoch}) — running as standby; pass "
                    "--standby to silence this",
                    file=sys.stderr,
                )
    # named learned-policy presets (ISSUE 14): NAME=artifact.json ->
    # the [(name, weight)] pairs submit jobs reference by preset name
    presets = {}
    for entry in getattr(args, "policy_preset", []):
        name, sep, path = entry.partition("=")
        name = name.strip()
        if not sep or not name or not path:
            raise ValueError(
                f"--policy-preset {entry!r}: want NAME=ARTIFACT.json"
            )
        if name in presets:
            raise ValueError(f"--policy-preset {name!r} given twice")
        from tpusim.learn.policy import policies_from_artifact

        presets[name] = policies_from_artifact(path)
        print(
            f"[serve] policy preset {name!r} <- {path} "
            f"({len(presets[name])} features)", file=sys.stderr,
        )
    srv, service, worker = start_job_server(
        args.dir, traces, listen=args.listen,
        lane_width=args.lane_width, queue_size=args.queue_size,
        table_cache_dir=args.table_cache_dir,
        compile_cache_dir=args.compile_cache_dir,
        fleet=fleet_mode, lease_s=args.lease_s,
        family_quota=args.family_quota,
        policy_presets=presets,
        token=token, coord=coord,
        slo_file=args.slo_file or os.environ.get("TPUSIM_SLO_FILE", ""),
        out=sys.stderr,
    )
    if coord is not None:
        # the lease is re-staked with the bound URL at the next renewal
        coord.url = srv.url
    sup = None
    if fleet_n > 0:
        import subprocess

        from tpusim.svc.fleet import worker_command
        from tpusim.svc.supervisor import Supervisor

        cmd = worker_command(
            srv.url, table_cache_dir=args.table_cache_dir,
            compile_cache_dir=args.compile_cache_dir,
            token_file=getattr(args, "token_file", ""),
        )
        sup = Supervisor(
            lambda _n: subprocess.Popen(cmd), fleet_n,
            max_workers=max_n,
            load_fn=service.queue.depth,
            depth_per_worker=args.lane_width,
            on_exit=service.fleet.release_dead,
            out=sys.stderr,
        )
        service.fleet.supervisor = sup
        # respawns/breaker trips append to the coordinator's audit
        # chain (ISSUE 19)
        sup.audit = service.audit
        if coord is not None and coord.role != "leader":
            # a standby's local workers would only spin on its own
            # 503s — spawn them at promotion (resume fills the floor)
            sup.pause()
        sup.start()
    # HA plumbing (ISSUE 17): the leader renews its leadership lease on
    # a CoordKeeper timer; a standby (or a deposed ex-leader) polls
    # try_acquire on the watch cadence and promotes by adopting the
    # artifact dir's pending state — which the epoch fence guarantees
    # the old leader can no longer mutate
    ha = {"keeper": None}

    def _on_deposed():
        if sup is not None:
            sup.pause()

    def _promote():
        old = ha["keeper"]
        if old is not None:
            old.stop()
        recover_pending_jobs(service, out=sys.stderr)
        if service.fleet is not None:
            service.fleet.adopt_leases(out=sys.stderr)
        # the metrics half of the takeover (ISSUE 20): splice the
        # deposed leader's persisted tsdb snapshot under our ring and
        # resume the (standby-paused) sampler — /query history survives
        # the failover instead of starting blind
        service.adopt_history(out=sys.stderr)
        if sup is not None:
            sup.resume()
        ha["keeper"] = CoordKeeper(coord, on_deposed=_on_deposed).start()
        print(
            f"[serve] PROMOTED to leader at epoch {coord.epoch} — "
            "pending jobs requeued, live worker leases adopted, "
            "metrics history spliced",
            file=sys.stderr,
        )

    if coord is not None and coord.role == "leader":
        ha["keeper"] = CoordKeeper(coord, on_deposed=_on_deposed).start()
    # graceful shutdown (ISSUE 10): SIGTERM/SIGINT begin the drain —
    # /healthz flips to 503, POSTs answer 503 + Retry-After, the
    # in-flight batch finishes (worker.stop joins after it), and every
    # queued job's spec is already on disk for the next startup's
    # recovery pass
    import signal

    stop_flag = {"stop": False}

    def _graceful(_signum, _frame):
        stop_flag["stop"] = True
        srv.begin_drain()

    try:
        signal.signal(signal.SIGTERM, _graceful)
        signal.signal(signal.SIGINT, _graceful)
    except ValueError:
        pass  # non-main thread (tests drive _serve_jobs directly)
    mode = (f"supervised fleet of {fleet_n} worker processes"
            + (f" (autoscale to {max_n})" if max_n else "")
            if fleet_n else
            ("fleet coordinator (external workers)" if fleet_mode
             else "single in-process worker"))
    if coord is not None:
        mode += (f"; role {coord.role} epoch {coord.epoch}; "
                 f"auth {auth_describe(token)}")
    hosted = "; ".join(
        f"trace {name!r} = {len(t.nodes)} nodes x {len(t.pods)} pods"
        for name, t in traces.items()
    )
    print(
        f"[serve] job plane at {srv.url} (POST /jobs, GET "
        f"/jobs/<id>[/result], /queue, /workers, /traces, /metrics, "
        f"/healthz, /progress); {mode}; {hosted}; results -> "
        f"{os.path.abspath(args.dir)}", file=sys.stderr,
    )
    try:
        if args.once:
            # smoke mode: a real self-check of both planes over HTTP
            with urllib.request.urlopen(srv.url + "/healthz",
                                        timeout=10) as r:
                health = json.loads(r.read().decode())
            with urllib.request.urlopen(srv.url + "/queue",
                                        timeout=10) as r:
                queue = json.loads(r.read().decode())
            print(
                f"[serve] once: healthz ok={health.get('ok')}, /queue "
                f"depth={queue.get('depth')} capacity="
                f"{queue.get('capacity')} lanes={queue.get('lane_width')}",
                file=sys.stderr,
            )
            return 0
        while not stop_flag["stop"]:
            if (coord is not None and coord.role != "leader"
                    and coord.try_acquire()):
                _promote()
            record, progress = watch_dir(args.dir)
            if record is not None:
                srv.publish_record(record)
            if sup is not None:
                # the supervision pass (ISSUE 13): reap (releasing held
                # jobs immediately via release_dead — a kill -9 from
                # outside still goes the lease-expiry route), respawn
                # under backoff/breaker, autoscale
                sup.poll()
            time.sleep(max(args.poll, 0.2))
        print("[serve] draining: finishing the in-flight batch",
              file=sys.stderr)
    except KeyboardInterrupt:
        srv.begin_drain()
    finally:
        if ha["keeper"] is not None:
            # graceful exit releases the leadership lease so a standby
            # takes over immediately, not one lease + skew later
            ha["keeper"].stop(release=True)
        elif coord is not None:
            coord.release()
        if sup is not None:
            sup.stop()
        if worker is not None:
            worker.stop()  # joins after the current batch — the drain
        srv.stop()
    return 0


def cmd_worker(args) -> int:
    """`tpusim worker --join URL`: the fleet worker process (ISSUE 12).
    SIGTERM/SIGINT drain the in-flight batch before exit; a kill -9 is
    recovered by the lease protocol (the coordinator steals)."""
    import signal
    import threading

    from tpusim.svc.auth import load_token
    from tpusim.svc.client import ServiceError
    from tpusim.svc.fleet import run_worker

    stop_event = threading.Event()

    def _graceful(_signum, _frame):
        stop_event.set()

    try:
        signal.signal(signal.SIGTERM, _graceful)
        signal.signal(signal.SIGINT, _graceful)
    except ValueError:
        pass  # non-main thread (tests drive run_worker directly)
    try:
        served = run_worker(
            args.join, worker_id=args.id, poll_s=args.poll,
            max_batches=args.max_batches,
            table_cache_dir=args.table_cache_dir,
            compile_cache_dir=args.compile_cache_dir,
            out=sys.stderr, stop_event=stop_event,
            mode=args.mode, cache_dir=args.cache_dir,
            token=load_token(getattr(args, "token_file", "")),
        )
    except ServiceError as err:
        print(f"tpusim worker: {err}", file=sys.stderr)
        return 1
    except (OSError, ValueError, json.JSONDecodeError) as err:
        print(f"tpusim worker: {err}", file=sys.stderr)
        return 2
    print(f"[worker] drained after {served} batch(es)", file=sys.stderr)
    return 0


def cmd_tune(args) -> int:
    """`tpusim tune`: the learned-scoring lane's CLI (ISSUE 9)."""
    from tpusim.learn import (
        LocalRollout,
        ObjectiveConfig,
        RemoteRollout,
        TuneConfig,
        format_holdout_report,
        holdout_report,
        make_family_sim,
        make_robust_eval,
        run_tune,
    )
    from tpusim.policies import POLICY_NAMES, is_policy_name
    from tpusim.svc.client import ServiceError
    from tpusim.svc.worker import load_trace

    try:
        learned = False
        if args.policy:
            # the --policy spec (ISSUE 14): for a LEARNED family the
            # parameters ARE the weight vector, so the loop below is
            # unchanged — only the bounds default (signs are meaningful)
            # and the --best-out format (a signed policy artifact)
            # differ. parse_policy_spec also accepts a built-in name
            # (weight 1000), which tunes like a --policies run.
            from tpusim.learn.policy import parse_learned_name, parse_policy_spec

            policies = [
                (n, int(w)) for n, w in parse_policy_spec(args.policy)
            ]
            learned = all(
                parse_learned_name(n) is not None for n, _ in policies
            )
        else:
            policies = [
                (str(n), int(w)) for n, w in json.loads(args.policies)
            ]
        for name, _ in policies:
            if not is_policy_name(name):
                raise ValueError(
                    f"unknown policy {name!r} (known: "
                    f"{', '.join(POLICY_NAMES)}, "
                    "LearnedScore[<feature>])"
                )
        w_lo = args.w_min if args.w_min is not None else (
            -4000 if learned else 0
        )
        w_hi = args.w_max if args.w_max is not None else 4000
        if learned:
            # fail BEFORE the (potentially hours-long) search, not at
            # the artifact export: the i32 theta vocabulary is hard-
            # bounded, and a best vector outside it cannot be saved
            from tpusim.learn.policy import THETA_HI, THETA_LO

            if w_lo < THETA_LO or w_hi > THETA_HI:
                raise ValueError(
                    f"--policy learned bounds [{w_lo}, {w_hi}] exceed "
                    f"the i32 theta export range [{THETA_LO}, "
                    f"{THETA_HI}]"
                )
        if not 0.0 <= args.holdout < 1.0:
            raise ValueError(
                f"--holdout must be in [0, 1), got {args.holdout}"
            )
        trace = load_trace(
            "default", args.nodes, args.pods, max_pods=args.max_pods
        )
        n_train = len(trace.pods) - int(len(trace.pods) * args.holdout)
        train, held = trace.pods[:n_train], trace.pods[n_train:]
        if not train:
            raise ValueError("no training pods left after the holdout split")

        cfg = TuneConfig(
            algo=args.algo, generations=args.generations,
            popsize=args.popsize, sigma=args.sigma, lr=args.lr,
            seed=args.seed, eval_seed=args.eval_seed,
            w_lo=w_lo, w_hi=w_hi,
            objective=ObjectiveConfig(
                w_alloc=args.obj_alloc, w_frag=args.obj_frag,
                w_unsched=args.obj_unsched,
                w_disrupt=args.obj_disrupt,
            ),
        )
        train_fault = None
        train_fault_meta = None
        if args.train_fault_mtbf > 0 or args.train_fault_evict_every > 0:
            from tpusim.sim.faults import FaultConfig

            train_fault = FaultConfig(
                mtbf_events=args.train_fault_mtbf,
                mttr_events=args.train_fault_mttr,
                evict_every_events=args.train_fault_evict_every,
                seed=args.train_fault_seed,
            )
            train_fault_meta = {
                "mtbf": float(args.train_fault_mtbf),
                "mttr": float(args.train_fault_mttr),
                "evict_every": float(args.train_fault_evict_every),
                "seed": int(args.train_fault_seed),
            }
        if args.url:
            if train_fault is not None:
                raise ValueError(
                    "--train-fault-* needs the local backend (the remote "
                    "job plane takes per-job `fault` fields instead — "
                    "submit a chaos grid through `tpusim submit`)"
                )
            # the service must host the SAME train prefix this CLI
            # computed (serve --jobs --max-pods), else the tuned vector
            # describes a different workload
            print(
                f"[tune] remote rollouts via {args.url} (service must "
                f"host the {len(train)}-pod train prefix of "
                f"{os.path.basename(args.pods)})", file=sys.stderr,
            )
            backend = RemoteRollout(
                args.url, policies, engine=args.engine,
                timeout=args.timeout, out=sys.stderr,
            )
        else:
            sim = make_family_sim(
                trace.nodes, train, policies, engine=args.engine
            )
            backend = LocalRollout(
                sim, width=args.popsize, fault=train_fault
            )

        robust_eval, robust_meta = None, None
        if args.robust_mtbf > 0:
            from tpusim.sim.faults import FaultConfig

            robust_eval = make_robust_eval(
                trace.nodes, train, policies,
                FaultConfig(
                    mtbf_events=args.robust_mtbf,
                    mttr_events=args.robust_mttr,
                    seed=args.robust_seed,
                ),
            )
            # lands in the log header: the robustness knobs shape the
            # log's bytes, so a resume under different ones must fail
            # loudly instead of writing a mixed log
            robust_meta = {
                "mtbf": float(args.robust_mtbf),
                "mttr": float(args.robust_mttr),
                "seed": int(args.robust_seed),
            }

        result = run_tune(
            backend, policies, cfg, args.log, resume=args.resume,
            robust_eval=robust_eval, robust_meta=robust_meta,
            train_fault_meta=train_fault_meta,
            out=sys.stderr,
        )

        from tpusim.obs.emitters import format_tuning_curve

        print(format_tuning_curve(result.records))
        print(
            f"[tune] best weights "
            f"{','.join(str(w) for w in result.best_weights)} "
            f"(objective {result.best_objective:+.4f}) after "
            f"{len(result.records)} generations -> {result.log_path}"
        )
        if held:
            eval_sim = make_family_sim(
                trace.nodes, held, policies, engine=args.engine
            )
            report = holdout_report(
                eval_sim, policies, result.best_weights,
                objective=cfg.objective, eval_seed=cfg.eval_seed,
            )
            print(format_holdout_report(report, policies))
        if args.best_out:
            if learned:
                # the learned lane exports a signed policy ARTIFACT —
                # the apply --policy / serve --policy-preset input
                from tpusim.learn.dataset import feature_names_of
                from tpusim.learn.policy import save_policy_artifact

                path = save_policy_artifact(
                    args.best_out, result.best_weights,
                    features=feature_names_of(policies),
                    meta={
                        "trained": args.algo,
                        "objective": result.best_objective,
                        "source": "tune",
                    },
                )
                print(f"[tune] wrote learned-policy artifact {path}",
                      file=sys.stderr)
            else:
                from tpusim.apply import save_weights_payload

                path = save_weights_payload(
                    args.best_out, [result.best_weights],
                    policies=policies,
                )
                print(f"[tune] wrote tuned weights payload {path}",
                      file=sys.stderr)
    except ServiceError as err:
        # remote-backend failures (service down, job failed server-side,
        # wait timeout) exit 1 like `tpusim submit` — the run state is
        # safe: the log holds every completed generation and --resume
        # continues from it
        print(f"tpusim tune: {err}", file=sys.stderr)
        return 1
    except (OSError, ValueError, json.JSONDecodeError) as err:
        print(f"tpusim tune: {err}", file=sys.stderr)
        return 2
    return 0


def cmd_imitate(args) -> int:
    """`tpusim imitate`: the supervised-imitation trainer (ISSUE 14) —
    decision JSONL -> teacher-forced feature extraction -> pairwise
    ranking fit -> i32 export -> held-out top-1 agreement (+ optional
    signed artifact)."""
    import numpy as np

    from tpusim.learn import (
        ImitateConfig,
        TeacherReplay,
        imitate_with_mining,
        load_teacher_log,
        save_policy_artifact,
    )
    from tpusim.learn.policy import FEATURE_SETS
    from tpusim.sim.workload import sort_cluster_pods
    from tpusim.svc.worker import load_trace

    try:
        if not 0.0 <= args.holdout < 1.0:
            raise ValueError(
                f"--holdout must be in [0, 1), got {args.holdout}"
            )
        header, rows = load_teacher_log(args.decisions)
        teacher = "+".join(
            n for n, _ in header.get("policies", [])
        ) or "?"
        trace = load_trace(
            "default", args.nodes, args.pods, max_pods=args.max_pods
        )
        # the driver's run() prep: stable (creation_time, name) sort,
        # no shuffle/tuning — a log recorded under other prep options
        # fails the replay's feasible-count cross-check loudly
        pods = sort_cluster_pods(
            list(trace.pods), False, np.random.default_rng(233)
        )
        features = FEATURE_SETS[args.features]
        replay = TeacherReplay(
            trace.nodes, pods, header, rows, features=features
        )
        cut = len(rows) - int(len(rows) * args.holdout)
        print(
            f"[imitate] teacher {teacher}: {len(rows)} events, training "
            f"on [0, {cut}), holdout from event {cut}", file=sys.stderr,
        )
        _, theta, _hist = imitate_with_mining(
            replay,
            ImitateConfig(steps=args.steps, lr=args.lr, l2=args.l2,
                          seed=args.seed),
            end_event=cut, out=sys.stderr,
        )
        rep_train = replay.agreement(theta)
        rep_held = replay.agreement(theta, start_event=cut)
        print(
            f"[imitate] exported theta "
            f"{','.join(str(t) for t in theta)}"
        )
        print(
            f"[imitate] teacher-forced top-1 agreement: "
            f"{rep_train['matches']}/{rep_train['creates']} "
            f"({100 * rep_train['agreement']:.2f}%) overall, "
            f"{rep_held['matches']}/{rep_held['creates']} "
            f"({100 * rep_held['agreement']:.2f}%) on the held-out "
            "suffix"
        )
        if args.out:
            path = save_policy_artifact(
                args.out, theta, features=features,
                meta={
                    "trained": "imitation",
                    "teacher": header.get("policies", []),
                    "agreement_holdout": rep_held["agreement"],
                    "source": "imitate",
                },
            )
            print(f"[imitate] wrote learned-policy artifact {path}",
                  file=sys.stderr)
    except (OSError, ValueError, json.JSONDecodeError) as err:
        print(f"tpusim imitate: {err}", file=sys.stderr)
        return 2
    return 0


def cmd_submit(args) -> int:
    from tpusim.svc.client import (
        JobsFailed,
        ServiceError,
        format_results_table,
        submit_and_wait,
    )
    from tpusim.svc.jobs import docs_from_payload

    # exit discipline: 2 on unusable input or a failed round-trip (one-
    # line error), 1 when the service ran but some JOBS failed — partial
    # results still print, the exit code never reads as success
    try:
        with open(args.jobs) as f:
            payload = json.load(f)
        # shape-routed: grid files expand per row, single job documents
        # (incl. ones carrying a flat `weights` vector) pass through
        docs = docs_from_payload(payload)
        from tpusim.svc.auth import load_token

        results = submit_and_wait(
            args.url, docs, timeout=args.timeout, out=sys.stderr,
            token=load_token(getattr(args, "token_file", "")),
        )
    except JobsFailed as err:
        if err.results:
            print(format_results_table(err.results))
        for d in err.failed:
            print(
                f"[submit] FAILED {d['id']}: {d.get('error', '?')}",
                file=sys.stderr,
            )
        print(f"tpusim submit: {err}", file=sys.stderr)
        return 1
    except (OSError, ValueError, json.JSONDecodeError,
            ServiceError) as err:
        print(f"tpusim submit: {err}", file=sys.stderr)
        return 2
    print(f"[submit] {len(results)} job(s) done via {args.url}",
          file=sys.stderr)
    print(format_results_table(results))
    return 0


def cmd_trace(args) -> int:
    """`tpusim trace <job-digest>` — stitch the per-process span files
    under an artifact dir into one cross-process timeline (ISSUE 19).
    Exit 2 when the dir holds no matching spans (unusable input, the
    CLI discipline), 0 otherwise — file-level problems (torn lines,
    bad signatures) print loudly but don't fail the stitch."""
    from tpusim.obs import trace as obs_trace

    if not os.path.isdir(args.dir):
        print(f"tpusim trace: no such artifact dir {args.dir!r}",
              file=sys.stderr)
        return 2
    spans, problems = obs_trace.stitch(
        args.dir, job=args.job, trace=args.trace_id
    )
    for p in problems:
        print(f"[trace] WARNING: {p}", file=sys.stderr)
    if not spans:
        what = f" for job {args.job!r}" if args.job else ""
        print(f"tpusim trace: no spans{what} under {args.dir}",
              file=sys.stderr)
        return 2
    for line in obs_trace.format_timeline(spans):
        print(line)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(obs_trace.chrome_trace(spans), f)
        print(f"[trace] wrote Chrome trace {args.out} "
              f"({len(spans)} spans)", file=sys.stderr)
    return 0


def _audit_over_http(args) -> int:
    """The --url form of `tpusim audit`: GET /events with cursor
    pagination. One shot prints the newest --tail records; --follow
    keeps walking `after = next_after` so every poll is a delta."""
    import time
    import urllib.error
    import urllib.parse
    import urllib.request

    from tpusim.obs import audit as obs_audit

    base = args.url.rstrip("/")
    filters = {"kind": args.kind, "job": args.job, "worker": args.worker}

    def fetch(after: int, limit: int) -> dict:
        q = {k: v for k, v in filters.items() if v}
        q["limit"] = str(limit)
        if after:
            q["after"] = str(after)
        url = f"{base}/events?{urllib.parse.urlencode(q)}"
        with urllib.request.urlopen(url, timeout=10.0) as resp:
            return json.loads(resp.read().decode())

    try:
        doc = fetch(0, max(args.tail, 1) if args.tail else 500)
    except (urllib.error.URLError, OSError, ValueError) as err:
        print(f"tpusim audit: {base}/events unreachable: {err}",
              file=sys.stderr)
        return 2
    for line in obs_audit.format_records(doc.get("events") or []):
        print(line)
    if not args.follow:
        if not doc.get("events"):
            print("[audit] no matching records", file=sys.stderr)
        return 0
    cursor = int(doc.get("next_after") or 0)
    try:
        while True:
            time.sleep(2.0)
            try:
                doc = fetch(cursor, 500)
            except (urllib.error.URLError, OSError, ValueError) as err:
                print(f"[audit] poll failed ({err}); retrying",
                      file=sys.stderr)
                continue
            for line in obs_audit.format_records(doc.get("events") or []):
                print(line, flush=True)
            cursor = max(cursor, int(doc.get("next_after") or 0))
    except KeyboardInterrupt:
        return 0


def cmd_top(args) -> int:
    """`tpusim top URL` — the live fleet dashboard (ISSUE 20)."""
    from tpusim.obs import top as obs_top

    return obs_top.run(
        args.url, interval=args.interval, once=args.once,
        width=args.width,
    )


def cmd_audit(args) -> int:
    """`tpusim audit [--verify]` — query or verify the hash-chained
    control-plane audit log (ISSUE 19). --verify exits 1 LOUDLY on a
    broken chain (edit, truncation, torn tail, missing head).
    --url tails a LIVE coordinator via the /events seq cursor
    (ISSUE 20): each poll asks only for records past the last seen
    seq, so a long-lived fleet's tail ships deltas, not the chain."""
    from tpusim.obs import audit as obs_audit

    if args.url:
        return _audit_over_http(args)
    path = obs_audit.audit_path(args.dir)
    if not os.path.isfile(path):
        print(f"tpusim audit: no audit log at {path}", file=sys.stderr)
        return 2
    if args.verify:
        try:
            n = obs_audit.verify(path)
        except ValueError as err:
            print(f"tpusim audit: CHAIN BROKEN: {err}", file=sys.stderr)
            return 1
        print(f"[audit] chain intact: {n} record(s), head verified")
        return 0
    try:
        records = obs_audit.tail(
            path, n=args.tail, kind=args.kind, job=args.job,
            worker=args.worker,
        )
    except ValueError as err:
        print(f"tpusim audit: chain unreadable: {err}", file=sys.stderr)
        return 1
    for line in obs_audit.format_records(records):
        print(line)
    if not records:
        print("[audit] no matching records", file=sys.stderr)
    return 0


def cmd_gen_doc(parser: argparse.ArgumentParser, args) -> int:
    os.makedirs(args.dir, exist_ok=True)
    path = os.path.join(args.dir, "tpusim.md")
    with open(path, "w") as f:
        f.write(f"# tpusim\n\n```\n{parser.format_help()}\n```\n")
    print(f"wrote {path}")
    return 0


def main(argv=None) -> int:
    parser = _build_parser()
    args = parser.parse_args(argv)
    if args.command == "apply":
        return cmd_apply(args)
    if args.command == "explain":
        return cmd_explain(args)
    if args.command == "diff":
        return cmd_diff(args)
    if args.command == "report":
        return cmd_report(args)
    if args.command == "serve":
        return cmd_serve(args)
    if args.command == "worker":
        return cmd_worker(args)
    if args.command == "tune":
        return cmd_tune(args)
    if args.command == "imitate":
        return cmd_imitate(args)
    if args.command == "submit":
        return cmd_submit(args)
    if args.command == "trace":
        return cmd_trace(args)
    if args.command == "audit":
        return cmd_audit(args)
    if args.command == "top":
        return cmd_top(args)
    if args.command == "version":
        print(f"tpusim version {VERSION} (commit {COMMIT})")
        return 0
    if args.command == "gen-doc":
        return cmd_gen_doc(parser, args)
    if args.command == "debug":
        return 0  # ref: cmd/debug/debug.go run() is empty
    parser.print_help()
    return 1


if __name__ == "__main__":
    sys.exit(main())
