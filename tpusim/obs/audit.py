"""Hash-chained control-plane audit log (ISSUE 19).

Every decision the control plane makes on its own authority — an epoch
bump, a takeover, a deposition, a lease expiry + steal, a requeue of a
dead worker's jobs, a circuit-breaker trip, a 401/409 fence hit, a
[Degrade] — appends one record to `<artifact_dir>/audit.jsonl`. Each
record carries `prev` = the sha256 of its predecessor's exact line
bytes (io.storage.chain_append — the signed-JSONL discipline extended
to an append-only chain), and an atomically-rewritten `.head` sidecar
pins the tip, so:

  * editing ANY record breaks every successor's `prev` link
  * truncating the file contradicts the head sidecar
  * a writer killed mid-append leaves a torn tail that verify names

`tpusim audit --verify` / `chain_verify` fail loudly on all three.
Records are operator-facing facts, never secrets: token material MUST
NOT enter a record (svc.auth.describe is the only sanctioned
rendering — emitters pass worker/job/epoch facts only).

The log is multi-process safe (flock in chain_append): the HA pair
shares one artifact dir, and both the leader and the deposed standby
legitimately append (takeover on one side, deposition on the other).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional

from tpusim.io.storage import chain_append, chain_records, chain_verify

AUDIT_BASENAME = "audit.jsonl"
SCHEMA = "tpusim-audit-v1"

# kind vocabulary (ENGINES.md Round 22) — emitters stick to these so
# `tpusim audit --kind` filters stay predictable
KIND_TAKEOVER = "takeover"
KIND_DEPOSED = "deposed"
KIND_EPOCH_BUMP = "epoch_bump"
KIND_STEAL = "steal"
KIND_LEASE_EXPIRED = "lease_expired"
KIND_REQUEUE = "requeue"
KIND_BREAKER_TRIP = "breaker_trip"
KIND_RESPAWN = "respawn"
KIND_FENCE_409 = "fence_409"
KIND_AUTH_401 = "auth_401"
KIND_DEGRADE = "degrade"
# the SLO plane (ISSUE 20): alert firing/resolution transitions are
# control-plane decisions too — they chain like takeovers and steals
KIND_ALERT = "alert"


def audit_path(artifact_dir: str) -> str:
    return os.path.join(artifact_dir, AUDIT_BASENAME)


class AuditLog:
    """Append-only chained audit writer for one artifact dir. emit() is
    one flocked append — cheap enough for every control-plane decision,
    and a failure to write NEVER takes the control plane down with it
    (the decision already happened; the log is the witness, not the
    actor): write errors count and print once, they don't raise."""

    def __init__(self, artifact_dir: str, process: str = ""):
        self.path = audit_path(artifact_dir)
        self.process = str(process or f"pid-{os.getpid()}")
        self._lock = threading.Lock()
        self.write_errors = 0
        self._warned = False

    def emit(self, kind: str, job: str = "", worker: str = "",
             **fields) -> Optional[dict]:
        doc = {
            "schema": SCHEMA,
            "kind": str(kind),
            "t": round(time.time(), 6),
            "proc": self.process,
            "pid": os.getpid(),
        }
        if job:
            doc["job"] = str(job)
        if worker:
            doc["worker"] = str(worker)
        for k, v in sorted(fields.items()):
            if k not in doc:
                doc[k] = v
        try:
            with self._lock:
                chain_append(self.path, doc)
        except (OSError, ValueError) as err:
            self.write_errors += 1
            if not self._warned:
                self._warned = True
                print(f"[audit] WARNING: append failed ({err}) — "
                      f"decisions continue unrecorded")
            return None
        return doc


def verify(artifact_dir_or_path: str) -> int:
    """Record count of an intact chain; raises ValueError on tamper
    (broken link / truncation / torn tail / missing head)."""
    path = (audit_path(artifact_dir_or_path)
            if os.path.isdir(artifact_dir_or_path)
            else artifact_dir_or_path)
    return chain_verify(path)


def tail(artifact_dir_or_path: str, n: int = 20, kind: str = "",
         job: str = "", worker: str = "", after: int = 0) -> List[dict]:
    """Last `n` records matching the filters, oldest first. Walks (and
    therefore link-checks) the whole chain — an edited log can't serve
    queries. Job filters match by prefix (digests are long).

    Every record gains `seq` — its 1-based position in the chain — and
    `after > 0` keeps only records past that cursor (ISSUE 20): a
    long-lived fleet's audit poll ships the delta since its last seen
    seq instead of re-reading the whole chain's worth of JSON. With a
    cursor the WINDOW flips from tail to forward pagination — the
    OLDEST n past the cursor — so a poller walking `after = last seq`
    never skips records between polls."""
    path = (audit_path(artifact_dir_or_path)
            if os.path.isdir(artifact_dir_or_path)
            else artifact_dir_or_path)
    if not os.path.isfile(path):
        return []
    records = []
    after = max(int(after), 0)
    for seq, (doc, _) in enumerate(chain_records(path), start=1):
        if seq <= after:
            continue
        doc["seq"] = seq
        records.append(doc)
    if kind:
        records = [r for r in records if r.get("kind") == kind]
    if job:
        records = [r for r in records
                   if str(r.get("job", "")).startswith(job)]
    if worker:
        records = [r for r in records if r.get("worker") == worker]
    n = max(int(n), 0)
    if not n:
        return records
    return records[:n] if after else records[-n:]


def format_records(records) -> List[str]:
    """Terminal rendering of audit records, one line each."""
    lines = []
    for r in records:
        t = r.get("t")
        stamp = (time.strftime("%H:%M:%S", time.localtime(t))
                 if isinstance(t, (int, float)) else "--:--:--")
        extra = {k: v for k, v in r.items()
                 if k not in ("schema", "kind", "t", "proc", "pid",
                              "job", "worker", "prev", "seq")}
        parts = [f"{stamp}  {r.get('kind', '?'):<14}"]
        if r.get("job"):
            parts.append(f"job={str(r['job'])[:12]}")
        if r.get("worker"):
            parts.append(f"worker={r['worker']}")
        parts.append(f"by={r.get('proc', '?')}")
        if extra:
            parts.append(json.dumps(extra, sort_keys=True))
        lines.append("  ".join(parts))
    return lines
