"""Live monitoring endpoint — the scrape surface of obs (ISSUE 5).

A stdlib-threaded HTTP server with three endpoints:

  /metrics   Prometheus exposition text of the newest published run
             record (the same `prometheus_lines` rendering the
             --metrics-out textfile uses, so the final scrape of a
             finished run is byte-equal to the emitted file)
  /healthz   JSON liveness: {"ok": true, "phase": ..., "records": N}
  /progress  JSON run progress: phase, events done/total, ev/s, ETA —
             fed by the obs.heartbeat listener hook (in-scan ticks) and
             by the driver's per-chunk checkpoint boundaries

Two lifecycles share the implementation:

  MonitorServer   in-process: `tpusim apply --listen :PORT` starts one
                  before the run; the driver/heartbeat publish into it,
                  and a scraper sees live numbers mid-run. Publishing is
                  push-based — a scrape never touches the simulator (no
                  device syncs on the request path).
  watch + serve   standalone: `tpusim serve DIR` polls a directory for
                  the newest obs run record (*.jsonl) and checkpoint
                  files (io.storage naming) and republishes them — watch
                  a long checkpointed run from a second terminal without
                  touching its process.

Binding defaults to 127.0.0.1 (a monitoring endpoint must be opted into
the network: pass an explicit host as HOST:PORT to expose it).
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from tpusim.obs.emitters import prometheus_lines

DEFAULT_PORT = 8642


def parse_listen(listen: str) -> Tuple[str, int]:
    """'HOST:PORT' | ':PORT' | 'PORT' -> (host, port); empty host binds
    loopback only."""
    listen = str(listen or "").strip()
    host, sep, port = listen.rpartition(":")
    if not sep:
        host, port = "", listen
    try:
        port_i = int(port) if port else DEFAULT_PORT
    except ValueError:
        raise ValueError(f"--listen {listen!r}: port must be an integer")
    return host or "127.0.0.1", port_i


class MonitorServer:
    """Threaded HTTP monitor. publish_record()/publish_progress() are the
    write surface (thread-safe; renders the Prometheus text at publish
    time so the scrape path is a buffer copy); start()/stop() own the
    server thread."""

    # bound on the per-job /progress map: a long-lived service must not
    # grow state per job forever — the oldest entries age out FIFO
    MAX_JOB_PROGRESS = 64

    def __init__(self, listen: str = "", prefix: str = "tpusim"):
        self.host, self.port = parse_listen(listen)
        self.prefix = prefix
        self._lock = threading.Lock()
        self._metrics_text: Optional[str] = None
        self._progress: dict = {"phase": "starting"}
        self._records = 0
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._hb_listener = None
        # extension request handlers (tpusim.svc.api grows the POST side
        # here, ISSUE 7): each app's handle(method, path, body,
        # headers=None) returns (code, content_type, body_bytes[,
        # extra_headers]) or None to fall through; first non-None answer
        # wins, built-ins serve as the GET fallback. `headers` is the
        # request's header map (the fleet transfer plane reads Range
        # for resumable trace downloads, ISSUE 13).
        self._apps: list = []
        # graceful shutdown (ISSUE 10 satellite): once draining, POSTs
        # answer 503 + Retry-After (the client's connection-reset/503
        # retry path resubmits against the restarted process — specs are
        # already persisted) and /healthz flips to 503 so load balancers
        # stop routing here while the in-flight batch finishes
        self._draining = False
        # fleet liveness hook (ISSUE 12): a callable returning
        # (ok: bool, extra fields) merged into the /healthz document —
        # the job coordinator degrades to 503 only when NO worker is
        # live (one dead worker of three is the fleet working as
        # designed, not an outage). Draining still wins.
        self.health_hook = None
        # live exposition extras (ISSUE 20): a callable returning extra
        # Prometheus lines appended to every /metrics response — the
        # job plane exports its per-kind latency summaries here so the
        # scrape carries live queue telemetry, not just the newest
        # published run record. Served even before the first publish.
        self.metrics_extra_fn = None
        # shutdown hooks: stop() runs these (the SLO sampler thread
        # rides the server lifecycle)
        self._cleanups: list = []

    def begin_drain(self):
        with self._lock:
            self._draining = True
            self._progress = dict(self._progress, phase="draining")

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    # ---- write surface ----

    def publish_record(self, record: dict):
        """Render + swap in a new /metrics snapshot (the same lines
        write_prometheus would emit for this record)."""
        text = "\n".join(prometheus_lines(record, self.prefix)) + "\n"
        with self._lock:
            self._metrics_text = text
            self._records += 1

    def metrics_text(self, include_extra: bool = False) -> Optional[str]:
        """The current /metrics exposition text (None before the first
        publish) — the base the fleet coordinator's aggregated scrape
        merges worker series into (ISSUE 19). `include_extra` appends
        the live extras (metrics_extra_fn) so the merged fleet scrape
        and the plain GET serve one vocabulary; with no published
        record yet the extras alone still serve (a fleet coordinator
        never publishes a run record of its own)."""
        with self._lock:
            text = self._metrics_text
        if not include_extra or self.metrics_extra_fn is None:
            return text
        try:
            extra = self.metrics_extra_fn()
        except Exception:
            return text  # a broken extras hook must not break scrapes
        if not extra:
            return text
        extra_text = ("\n".join(extra) + "\n"
                      if isinstance(extra, (list, tuple)) else str(extra))
        return extra_text if text is None else text + extra_text

    def on_stop(self, fn) -> "MonitorServer":
        """Register a shutdown hook stop() runs exactly once."""
        self._cleanups.append(fn)
        return self

    def publish_progress(self, **fields):
        with self._lock:
            self._progress.update(fields)
            self._progress["updated_unix"] = time.time()

    def publish_job_progress(self, job: str, fields: dict):
        """Per-run/job progress (ISSUE 7): keyed under /progress's
        `jobs` map instead of flat-merged, so several queued jobs served
        by one process never interleave into one anonymous stream.
        `job` also lands top-level as the most-recently-active id."""
        job = str(job)
        with self._lock:
            jobs = self._progress.setdefault("jobs", {})
            entry = jobs.setdefault(job, {})
            entry.update(fields)
            entry["updated_unix"] = time.time()
            while len(jobs) > self.MAX_JOB_PROGRESS:
                jobs.pop(next(iter(jobs)))
            self._progress["job"] = job
            self._progress["updated_unix"] = time.time()

    def add_app(self, app) -> "MonitorServer":
        """Register an extension request handler (see __init__)."""
        self._apps.append(app)
        return self

    def _dispatch_app(self, method: str, path: str, body: bytes,
                      headers=None, query: str = ""):
        for app in self._apps:
            # apps opt into the raw query string (the /events filter
            # plane, ISSUE 19) by declaring `accepts_query = True`;
            # legacy apps keep the 4-arg handle() untouched
            if getattr(app, "accepts_query", False):
                resp = app.handle(method, path, body, headers, query)
            else:
                resp = app.handle(method, path, body, headers)
            if resp is not None:
                return resp
        return None

    def attach_heartbeat(self):
        """Feed /progress from the in-scan heartbeat ticks
        (obs.heartbeat listener hook). Ticks tagged with a job id
        (heartbeat.configure(job=...), ISSUE 7) land in the per-job
        `jobs` map; untagged ticks keep the flat single-run fields."""
        from tpusim.obs import heartbeat

        def on_tick(info):
            # final means THIS SCAN finished — a fault segment or chunk,
            # not necessarily the run; the driver/CLI publishes
            # phase="done" itself when the whole run's result lands
            fields = dict(
                phase="scan" if not info["final"] else "scan-done",
                events_done=info["done"], events_total=info["total"],
                ev_per_s=round(info["rate"], 1),
                eta_s=round(info["eta"], 1),
            )
            if info.get("worker"):
                fields["worker"] = info["worker"]
            job = info.get("job") or ""
            if job:
                self.publish_job_progress(job, fields)
            else:
                self.publish_progress(**fields)

        self._hb_listener = on_tick
        heartbeat.add_listener(on_tick)

    # ---- lifecycle ----

    def start(self) -> "MonitorServer":
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet: scrapes are not news
                pass

            def _send(self, code, ctype, body: bytes, headers=None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(body)

            def _try_apps(self, method: str) -> bool:
                """Route through the registered extension apps (the svc
                POST/job plane); True when one answered. An app exception
                becomes a 500 — one bad request must not kill the
                serving thread."""
                path, _, query = self.path.partition("?")
                body = b""
                if method == "POST":
                    length = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(length) if length > 0 else b""
                try:
                    # self.headers is an email.message.Message — apps
                    # get case-insensitive .get() (Range, Retry-After)
                    resp = srv._dispatch_app(method, path, body,
                                             self.headers, query)
                except Exception as err:
                    self._send(
                        500, "text/plain",
                        f"internal error: {type(err).__name__}: {err}\n"
                        .encode(),
                    )
                    return True
                if resp is None:
                    return False
                self._send(*resp)
                return True

            def do_POST(self):
                if srv.draining:
                    self._send(
                        503, "application/json",
                        b'{"error": "draining: service is shutting down"'
                        b', "retry_after_s": 2}\n',
                        headers={"Retry-After": "2"},
                    )
                    return
                if not self._try_apps("POST"):
                    self._send(404, "text/plain", b"not found\n")

            def do_GET(self):
                if self._try_apps("GET"):
                    return
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    text = srv.metrics_text(include_extra=True)
                    if text is None:
                        self._send(503, "text/plain",
                                   b"no run record published yet\n")
                        return
                    self._send(
                        200, "text/plain; version=0.0.4; charset=utf-8",
                        text.encode(),
                    )
                elif path == "/healthz":
                    hook = srv.health_hook
                    hook_ok, extra = True, {}
                    if hook is not None:
                        try:
                            hook_ok, extra = hook()
                        except Exception:  # a broken hook must not 500
                            hook_ok, extra = True, {}
                    with srv._lock:
                        draining = srv._draining
                        ok = not draining and hook_ok
                        if draining and "role" in extra:
                            # a draining coordinator is leaving the
                            # role — standbys/clients must not treat
                            # it as a live leader (ISSUE 17)
                            extra = dict(extra, role="draining")
                        body = json.dumps({
                            "ok": ok,
                            "phase": srv._progress.get("phase"),
                            "records": srv._records,
                            **extra,
                        }, sort_keys=True)
                    self._send(200 if ok else 503,
                               "application/json",
                               (body + "\n").encode())
                elif path == "/progress":
                    with srv._lock:
                        body = json.dumps(srv._progress, sort_keys=True)
                    self._send(200, "application/json",
                               (body + "\n").encode())
                else:
                    self._send(404, "text/plain", b"not found\n")

        class QuietServer(ThreadingHTTPServer):
            def handle_error(self, request, client_address):
                # a client that vanished mid-response (a kill -9'd
                # fleet worker, a dropped WAN link) is ROUTINE for the
                # service plane — not a stack trace
                import sys as _sys

                exc = _sys.exc_info()[1]
                if isinstance(exc, (BrokenPipeError,
                                    ConnectionResetError)):
                    return
                super().handle_error(request, client_address)

        self._httpd = QuietServer((self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]  # resolve port 0
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tpusim-monitor",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self):
        cleanups, self._cleanups = self._cleanups, []
        for fn in cleanups:
            try:
                fn()
            except Exception:
                pass  # shutdown hooks must not block shutdown
        if self._hb_listener is not None:
            from tpusim.obs import heartbeat

            heartbeat.remove_listener(self._hb_listener)
            self._hb_listener = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


# ---------------------------------------------------------------------------
# Standalone watcher: `tpusim serve DIR`
# ---------------------------------------------------------------------------


def watch_dir(path: str) -> Tuple[Optional[dict], dict]:
    """One poll of a watched directory: (newest obs run record or None,
    progress dict). Records are the newest-mtime `*.jsonl` whose LAST
    line is an obs record; progress reads the newest checkpoint file's
    cursor out of the io.storage name (`<digest>.e<cursor>.ckpt.npz`) —
    a killed or running checkpointed replay is observable from its
    artifact directory alone."""
    from tpusim.io.storage import CHECKPOINT_SUFFIX
    from tpusim.obs.emitters import read_jsonl

    record = None
    progress: dict = {"phase": "watching", "dir": os.path.abspath(path)}
    if not os.path.isdir(path):
        progress["phase"] = "missing-dir"
        return None, progress

    def _mtime(fname: str) -> float:
        # stat defensively: live artifact dirs churn (checkpoint prunes,
        # tmp-file renames, result rewrites), so a file listed a moment
        # ago may be gone by stat time — rank vanished files oldest
        # instead of letting the OSError kill the whole poll
        try:
            return os.path.getmtime(os.path.join(path, fname))
        except OSError:
            return float("-inf")

    jsonls = sorted(
        (f for f in os.listdir(path) if f.endswith(".jsonl")),
        key=_mtime,
    )
    for fname in reversed(jsonls):
        try:
            recs = read_jsonl(os.path.join(path, fname))
        except (OSError, json.JSONDecodeError):
            continue
        obs_recs = [r for r in recs if "deterministic" in r]
        if obs_recs:
            record = obs_recs[-1]
            progress["record_file"] = fname
            break

    best = None
    for fname in os.listdir(path):
        if not fname.endswith(CHECKPOINT_SUFFIX):
            continue
        stem = fname[: -len(CHECKPOINT_SUFFIX)]
        digest, sep, cursor = stem.rpartition(".e")
        if not sep or not cursor.isdigit():
            continue
        cur = int(cursor)
        if best is None or cur > best[0]:
            best = (cur, fname)
    if best is not None:
        progress["phase"] = "checkpointed"
        progress["events_done"] = best[0]
        progress["checkpoint_file"] = best[1]
    return record, progress


def serve_dir(path: str, listen: str = "", poll_s: float = 2.0,
              once: bool = False, out=None) -> MonitorServer:
    """Start a MonitorServer republishing `path`'s newest artifacts every
    `poll_s`. once=True publishes a single poll and returns (the test /
    embedding surface); otherwise blocks until KeyboardInterrupt."""
    srv = MonitorServer(listen).start()
    if out is not None:
        print(f"[serve] watching {os.path.abspath(path)} at {srv.url} "
              f"(/metrics /healthz /progress)", file=out)

    def poll_once():
        record, progress = watch_dir(path)
        if record is not None:
            srv.publish_record(record)
        srv.publish_progress(**progress)

    poll_once()
    if once:
        return srv
    try:
        while True:
            time.sleep(max(poll_s, 0.2))
            poll_once()
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return srv
