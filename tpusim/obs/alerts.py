"""Declarative SLO / alert rules over the tsdb ring (ISSUE 20).

Two rule types, both evaluated once per sampler tick against the
in-process TSDB:

  threshold   "metric OP value sustained for_s" — the pager-classic
              form for states (breaker open, queue saturated, steal
              rate hot). Fires after the condition holds `for_s`
              seconds; resolves after it clears `clear_for_s` (the
              hysteresis that keeps a flapping metric from paging once
              per tick).
  burn_rate   the SRE multi-window form for SLOs: each sample either
              meets the objective or burns error budget; the rule fires
              when the burn FRACTION over EVERY window exceeds
              burn x budget (a fast window for detection speed, a slow
              window so a single spike can't page), and resolves once
              no window burns for `clear_for_s`.

Rules load from `--slo-file` JSON (a list, or {"rules": [...],
"defaults": false} to drop the built-ins); DEFAULT_RULES cover the
SLOs the repo already measures ad hoc: fork p99, availability, queue
depth vs capacity, steal / lease-expiry rate, breaker state.

Every firing/resolution transition is a control-plane decision, so it
appends a `kind=alert` record to the hash-chained audit.jsonl —
`tpusim audit --verify` covers the alert history exactly like
takeovers and steals — and page-severity burns flip the /healthz
readiness detail via compose_health (wrapping, not replacing, the
fleet's own liveness hook).
"""

from __future__ import annotations

import json
import operator
import os
import threading
import time
from typing import Dict, List, Optional

from tpusim.obs.audit import KIND_ALERT

SEVERITIES = ("page", "ticket")
OPS = {">": operator.gt, ">=": operator.ge,
       "<": operator.lt, "<=": operator.le}

# how recent a threshold rule's newest sample must be to count: stale
# series (a worker that left, a kind that stopped completing) silently
# stop asserting rather than pinning the last value forever
DEFAULT_STALENESS_S = 15.0

DEFAULT_RULES: List[dict] = [
    {
        # the serving SLO the gate measures ad hoc since ISSUE 16:
        # admission->result p99 of warm-state forks. "p99 <= 2s" as a
        # burn rule: budget 0.01 over per-completion event samples IS
        # the 99th percentile, measured continuously — fires when the
        # fraction of slow completions in both windows exceeds
        # burn x 1%, resolves when fast completions displace them
        "name": "fork-p99-burn",
        "type": "burn_rate",
        "severity": "page",
        "metric": "tpusim_queue_latency_event_seconds",
        "label": {"kind": "fork"},
        "objective": 2.0,
        "op": ">",
        "budget": 0.01,
        "windows": [
            {"window_s": 60.0, "burn": 14.0},
            {"window_s": 300.0, "burn": 6.0},
        ],
        "clear_for_s": 30.0,
    },
    {
        # availability: fraction of completed jobs that failed, per tick
        "name": "availability-burn",
        "type": "burn_rate",
        "severity": "page",
        "metric": "tpusim_queue_error_ratio",
        "objective": 0.0,
        "op": ">",
        "budget": 0.05,
        "windows": [
            {"window_s": 60.0, "burn": 6.0},
            {"window_s": 300.0, "burn": 3.0},
        ],
        "clear_for_s": 30.0,
    },
    {
        "name": "queue-saturation",
        "type": "threshold",
        "severity": "ticket",
        "metric": "tpusim_queue_saturation",
        "op": ">=",
        "value": 0.9,
        "for_s": 10.0,
        "clear_for_s": 10.0,
    },
    {
        "name": "steal-rate",
        "type": "threshold",
        "severity": "ticket",
        "metric": "tpusim_queue_steals_rate",
        "op": ">",
        "value": 0.5,
        "for_s": 5.0,
        "clear_for_s": 15.0,
    },
    {
        "name": "lease-expiry-rate",
        "type": "threshold",
        "severity": "ticket",
        "metric": "tpusim_queue_lease_expired_rate",
        "op": ">",
        "value": 0.5,
        "for_s": 5.0,
        "clear_for_s": 15.0,
    },
    {
        # the supervisor's crash-loop circuit breaker: open = the fleet
        # cannot keep workers alive — that IS a page
        "name": "breaker-open",
        "type": "threshold",
        "severity": "page",
        "metric": "tpusim_fleet_breaker_open",
        "op": ">=",
        "value": 1.0,
        "for_s": 0.0,
        "clear_for_s": 5.0,
    },
]


def validate_rule(doc: dict) -> dict:
    """Normalized copy of one rule doc; ValueError names the field on
    anything malformed — a typo'd SLO file must fail at load, not
    silently never fire."""
    if not isinstance(doc, dict):
        raise ValueError(f"rule must be an object, got {type(doc).__name__}")
    name = str(doc.get("name") or "")
    if not name:
        raise ValueError("rule needs a non-empty name")
    kind = str(doc.get("type") or "")
    if kind not in ("threshold", "burn_rate"):
        raise ValueError(
            f"rule {name!r}: type must be threshold|burn_rate, got {kind!r}"
        )
    sev = str(doc.get("severity") or "ticket")
    if sev not in SEVERITIES:
        raise ValueError(
            f"rule {name!r}: severity must be one of {SEVERITIES}, "
            f"got {sev!r}"
        )
    metric = str(doc.get("metric") or "")
    if not metric:
        raise ValueError(f"rule {name!r}: metric is required")
    op = str(doc.get("op") or ">")
    if op not in OPS:
        raise ValueError(
            f"rule {name!r}: op must be one of {sorted(OPS)}, got {op!r}"
        )
    label = doc.get("label") or {}
    if not isinstance(label, dict):
        raise ValueError(f"rule {name!r}: label must be an object")
    out = {
        "name": name, "type": kind, "severity": sev, "metric": metric,
        "op": op, "label": {str(k): str(v) for k, v in label.items()},
        "for_s": float(doc.get("for_s", 0.0)),
        "clear_for_s": float(doc.get("clear_for_s", 0.0)),
        "staleness_s": float(doc.get("staleness_s", DEFAULT_STALENESS_S)),
    }
    if kind == "threshold":
        if "value" not in doc:
            raise ValueError(f"rule {name!r}: threshold needs value")
        out["value"] = float(doc["value"])
    else:
        if "objective" not in doc:
            raise ValueError(f"rule {name!r}: burn_rate needs objective")
        out["objective"] = float(doc["objective"])
        budget = float(doc.get("budget", 0.0))
        if not 0.0 < budget <= 1.0:
            raise ValueError(
                f"rule {name!r}: budget must be in (0, 1], got {budget}"
            )
        out["budget"] = budget
        windows = doc.get("windows") or []
        if not windows:
            raise ValueError(f"rule {name!r}: burn_rate needs windows")
        norm = []
        for w in windows:
            ws = float(w.get("window_s", 0.0))
            burn = float(w.get("burn", 0.0))
            if ws <= 0 or burn <= 0:
                raise ValueError(
                    f"rule {name!r}: each window needs window_s > 0 and "
                    f"burn > 0, got {w}"
                )
            norm.append({"window_s": ws, "burn": burn})
        out["windows"] = sorted(norm, key=lambda w: w["window_s"])
    return out


def load_rules(path: str = "") -> List[dict]:
    """The --slo-file loader: JSON list of rules, or {"rules": [...],
    "defaults": false}. File rules override same-named defaults;
    defaults fill the rest unless the doc opts out. No path -> the
    built-ins alone."""
    defaults = [validate_rule(r) for r in DEFAULT_RULES]
    if not path:
        return defaults
    with open(path) as f:
        doc = json.load(f)
    keep_defaults = True
    if isinstance(doc, dict):
        keep_defaults = bool(doc.get("defaults", True))
        doc = doc.get("rules")
    if not isinstance(doc, list):
        raise ValueError(
            f"{path}: want a JSON list of rules or "
            '{"rules": [...], "defaults": bool}'
        )
    rules = [validate_rule(r) for r in doc]
    names = [r["name"] for r in rules]
    if len(set(names)) != len(names):
        raise ValueError(f"{path}: duplicate rule names {names}")
    if keep_defaults:
        have = set(names)
        rules += [r for r in defaults if r["name"] not in have]
    return rules


class AlertEngine:
    """Per-rule ok -> firing -> ok state machine over the tsdb. One
    evaluate() per sampler tick; transitions land in the audit chain
    and a bounded in-memory transition ring feeds GET /alerts."""

    MAX_TRANSITIONS = 256

    def __init__(self, tsdb, rules: Optional[List[dict]] = None,
                 audit=None):
        self.tsdb = tsdb
        self.rules = [validate_rule(r) for r in (
            rules if rules is not None else DEFAULT_RULES
        )]
        self.audit = audit
        self._lock = threading.Lock()
        self._state: Dict[str, dict] = {
            r["name"]: {"state": "ok", "breach_since": None,
                        "clear_since": None, "fired_unix": 0.0,
                        "value": 0.0, "detail": {}}
            for r in self.rules
        }
        self.transitions: List[dict] = []
        self.evaluations = 0

    # ---- evaluation ----

    def _eval_threshold(self, rule: dict, now: float):
        """(breaching, value, detail) for a threshold rule: newest
        fresh sample of any matching series; worst offender wins."""
        op = OPS[rule["op"]]
        rows = self.tsdb.latest(rule["metric"], label=rule["label"],
                                within_s=rule["staleness_s"], now=now)
        breaching, worst, labels = False, None, {}
        for lbl, _, v in rows:
            if worst is None or op(v, worst):
                worst, labels = v, lbl
            if op(v, rule["value"]):
                breaching = True
        value = worst if worst is not None else 0.0
        return breaching, value, {"value": round(value, 6),
                                  "threshold": rule["value"],
                                  "labels": labels}

    def _eval_burn(self, rule: dict, now: float):
        """(breaching, value, detail): breach fraction per window over
        every matching series' samples; fires only when ALL windows
        burn past burn x budget."""
        op = OPS[rule["op"]]
        burning_all = True
        detail_windows = []
        fast_frac = 0.0
        for i, w in enumerate(rule["windows"]):
            series = self.tsdb.query(
                rule["metric"], label=rule["label"],
                since=now - w["window_s"], step=0.0, now=now,
            )
            pts = [v for s in series for _, v in s["points"]]
            frac = (sum(1 for v in pts if op(v, rule["objective"]))
                    / len(pts)) if pts else 0.0
            need = min(w["burn"] * rule["budget"], 1.0)
            burning = bool(pts) and frac >= need
            burning_all = burning_all and burning
            if i == 0:
                fast_frac = frac
            detail_windows.append({
                "window_s": w["window_s"], "burn_fraction": round(frac, 4),
                "need": round(need, 4), "samples": len(pts),
                "burning": burning,
            })
        return burning_all, fast_frac, {
            "objective": rule["objective"], "budget": rule["budget"],
            "windows": detail_windows,
        }

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """Advance every rule's state machine; returns the transitions
        this pass produced (also retained in self.transitions)."""
        if now is None:
            now = time.time()
        fired = []
        with self._lock:
            self.evaluations += 1
            for rule in self.rules:
                if rule["type"] == "threshold":
                    breaching, value, detail = self._eval_threshold(
                        rule, now)
                else:
                    breaching, value, detail = self._eval_burn(rule, now)
                st = self._state[rule["name"]]
                st["value"], st["detail"] = value, detail
                if breaching:
                    st["clear_since"] = None
                    if st["breach_since"] is None:
                        st["breach_since"] = now
                    if (st["state"] == "ok"
                            and now - st["breach_since"]
                            >= rule["for_s"]):
                        st["state"] = "firing"
                        st["fired_unix"] = now
                        fired.append(self._transition(
                            rule, "firing", value, now))
                else:
                    st["breach_since"] = None
                    if st["state"] == "firing":
                        if st["clear_since"] is None:
                            st["clear_since"] = now
                        if (now - st["clear_since"]
                                >= rule["clear_for_s"]):
                            st["state"] = "ok"
                            st["clear_since"] = None
                            fired.append(self._transition(
                                rule, "resolved", value, now))
        return fired

    def _transition(self, rule: dict, state: str, value: float,
                    now: float) -> dict:
        rec = {"t": round(now, 3), "alert": rule["name"], "state": state,
               "severity": rule["severity"], "value": round(value, 6),
               "rule": rule["type"], "metric": rule["metric"]}
        self.transitions.append(rec)
        del self.transitions[:-self.MAX_TRANSITIONS]
        if self.audit is not None:
            self.audit.emit(
                KIND_ALERT, alert=rule["name"], state=state,
                severity=rule["severity"], value=round(value, 6),
                rule=rule["type"], metric=rule["metric"],
            )
        return rec

    # ---- views ----

    def firing(self) -> List[dict]:
        with self._lock:
            out = []
            for rule in self.rules:
                st = self._state[rule["name"]]
                if st["state"] != "firing":
                    continue
                out.append({
                    "alert": rule["name"],
                    "severity": rule["severity"],
                    "rule": rule["type"],
                    "metric": rule["metric"],
                    "since_unix": round(st["fired_unix"], 3),
                    "value": round(st["value"], 6),
                    "detail": st["detail"],
                })
            return out

    def page_firing(self) -> List[str]:
        """Names of firing page-severity alerts — the /healthz flip."""
        return [f["alert"] for f in self.firing()
                if f["severity"] == "page"]

    def describe(self) -> dict:
        """The GET /alerts document."""
        firing = self.firing()
        with self._lock:
            return {
                "firing": firing,
                "rules": [
                    {"name": r["name"], "type": r["type"],
                     "severity": r["severity"], "metric": r["metric"],
                     "label": r["label"],
                     "state": self._state[r["name"]]["state"]}
                    for r in self.rules
                ],
                "transitions": list(self.transitions[-50:]),
                "evaluations": self.evaluations,
            }

    def compose_health(self, prev_hook=None):
        """A MonitorServer health_hook that ANDs the previous hook (the
        fleet's worker-liveness view) with "no page-severity alert is
        firing" and merges alert detail into the /healthz document —
        wrap, never replace: a page burn must not hide a dead fleet and
        vice versa."""
        def hook():
            ok, extra = (prev_hook() if prev_hook is not None
                         else (True, {}))
            pages = self.page_firing()
            extra = dict(extra, alerts_firing=len(self.firing()),
                         alerts_page=pages)
            if pages:
                ok = False
            return ok, extra

        return hook


def slo_file_from_env() -> str:
    """TPUSIM_SLO_FILE fallback for surfaces that don't thread the
    flag (the gate's subprocess coordinators set the env instead)."""
    return os.environ.get("TPUSIM_SLO_FILE", "")
