"""`tpusim top URL` — the live fleet dashboard (ISSUE 20).

One terminal pane stitching the coordinator's whole observable state:
/healthz (role, epoch, readiness), /queue (depth, counters, per-kind
latency), /workers (the fleet roster with measured profiles), /alerts
(the SLO rule engine's firing set + recent transitions), and sparkline
history pulled from /query — the single view the fleet never had.

Stdlib only, plain redraw loop (ANSI home+clear each frame, no curses
dependency): `watch`-style robustness over widget polish. --once
renders a single frame with no escape codes — the scriptable form the
slo smoke asserts against.
"""

from __future__ import annotations

import json
import shutil
import sys
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import List, Optional

from tpusim.obs.series import sparkline

POLL_TIMEOUT_S = 5.0


def _get_json(base: str, path: str, query: Optional[dict] = None,
              ok_codes=(200,)) -> Optional[dict]:
    """GET base+path -> parsed JSON, or None when unreachable. /healthz
    legitimately answers 503 (draining, degraded, page burn) with a
    JSON body the dashboard still wants — `ok_codes` widens per call."""
    url = base + path
    if query:
        pairs = []
        for k, v in query.items():
            for vv in (v if isinstance(v, list) else [v]):
                pairs.append((k, vv))
        url += "?" + urllib.parse.urlencode(pairs)
    try:
        with urllib.request.urlopen(url, timeout=POLL_TIMEOUT_S) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as err:
        if err.code in ok_codes or err.code == 503:
            try:
                return json.loads(err.read().decode())
            except (ValueError, OSError):
                return None
        return None
    except (urllib.error.URLError, OSError, ValueError):
        return None


def _spark_of(base: str, name: str, labels: dict, since: float,
              width: int) -> str:
    doc = _get_json(base, "/query", {
        "name": name,
        "label": [f"{k}={v}" for k, v in labels.items()],
        "since": str(-abs(since)),
    })
    if not doc:
        return ""
    pts = [v for s in doc.get("series") or [] for _, v in s["points"]]
    return sparkline(pts, width=width) if pts else ""


def _fmt_s(v) -> str:
    try:
        v = float(v)
    except (TypeError, ValueError):
        return "?"
    return f"{v * 1e3:.0f}ms" if v < 1.0 else f"{v:.2f}s"


def render(base: str, width: int = 0) -> str:
    """One dashboard frame as plain text."""
    if width <= 0:
        width = max(shutil.get_terminal_size((100, 24)).columns, 60)
    base = base.rstrip("/")
    health = _get_json(base, "/healthz")
    queue = _get_json(base, "/queue")
    alerts = _get_json(base, "/alerts")

    lines: List[str] = []
    stamp = time.strftime("%H:%M:%S")
    if health is None and queue is None:
        lines.append(f"tpusim top — {base}  {stamp}")
        lines.append("")
        lines.append(f"  UNREACHABLE: no /healthz or /queue at {base}")
        return "\n".join(lines) + "\n"

    h = health or {}
    head = [f"tpusim top — {base}"]
    if h.get("role"):
        head.append(f"role={h['role']} epoch={h.get('epoch', '?')}")
    head.append(f"ok={h.get('ok', '?')}")
    if h.get("alerts_page"):
        head.append("PAGE:" + ",".join(h["alerts_page"]))
    head.append(stamp)
    lines.append("  ".join(head)[:width])
    lines.append("-" * min(width, 100))

    q = queue or {}
    depth = int(q.get("depth", 0))
    cap = max(int(q.get("capacity", 1) or 1), 1)
    barw = 20
    fill = min(int(round(barw * depth / cap)), barw)
    lines.append(
        f"queue  {depth}/{cap} [{'#' * fill}{'.' * (barw - fill)}]  "
        f"submitted={q.get('submitted', 0)} done={q.get('done', 0)} "
        f"failed={q.get('failed', 0)} steals={q.get('steals', 0)} "
        f"dedup={q.get('dedup_hits', 0)}"[:width]
    )
    depth_spark = _spark_of(base, "tpusim_queue_depth", {}, 300,
                            min(40, width - 20))
    if depth_spark:
        lines.append(f"  depth 5m  {depth_spark}")

    latency = q.get("latency") or {}
    if latency:
        lines.append("latency (admission->result)")
        for kind in sorted(latency):
            row = latency[kind]
            spark = _spark_of(
                base, "tpusim_queue_latency_seconds",
                {"kind": kind, "quantile": "0.99"}, 300,
                min(30, width - 44),
            )
            lines.append(
                f"  {kind:<6} p50={_fmt_s(row.get('p50_s')):<7} "
                f"p99={_fmt_s(row.get('p99_s')):<7} "
                f"n={row.get('count', 0):<5} {spark}"[:width]
            )

    workers = q.get("workers") or {}
    if workers:
        live = q.get("workers_live", 0)
        lines.append(f"workers ({live} live / {len(workers)} known)")
        lines.append(
            f"  {'id':<14}{'live':<6}{'mode':<10}{'claims':>7}"
            f"{'done':>6}{'fail':>6}{'leases':>7}{'ewma':>9}"
        )
        for wid in sorted(workers)[:12]:
            row = workers[wid]
            prof = row.get("profile") or {}
            lines.append(
                f"  {wid[:13]:<14}{str(bool(row.get('live'))):<6}"
                f"{str(row.get('mode', ''))[:9]:<10}"
                f"{row.get('claims', 0):>7}{row.get('jobs_done', 0):>6}"
                f"{row.get('jobs_failed', 0):>6}"
                f"{row.get('leases_held', 0):>7}"
                f"{_fmt_s(prof.get('ewma_dispatch_s', 0)):>9}"[:width]
            )

    a = alerts or {}
    firing = a.get("firing") or []
    if firing:
        lines.append(f"ALERTS ({len(firing)} firing)")
        for f in firing:
            lines.append(
                f"  {f.get('severity', '?').upper():<7}"
                f"{f.get('alert', '?'):<24} value={f.get('value')} "
                f"metric={f.get('metric', '')}"[:width]
            )
    else:
        lines.append("alerts: none firing")
    trans = (a.get("transitions") or [])[-5:]
    if trans:
        lines.append("recent transitions")
        for t in trans:
            ts = time.strftime("%H:%M:%S", time.localtime(t.get("t", 0)))
            lines.append(
                f"  {ts} {t.get('state', '?'):<9}"
                f"{t.get('alert', '?'):<24}"
                f"({t.get('severity', '?')})"[:width]
            )
    return "\n".join(lines) + "\n"


def run(url: str, interval: float = 2.0, once: bool = False,
        width: int = 0, out=None) -> int:
    """The redraw loop. --once prints a single frame (exit 2 when the
    coordinator is unreachable — the smoke's assertion hook)."""
    if out is None:
        out = sys.stdout
    if once:
        frame = render(url, width=width)
        out.write(frame)
        out.flush()
        return 2 if "UNREACHABLE" in frame else 0
    try:
        while True:
            frame = render(url, width=width)
            # home + clear-to-end: repaint without full-screen flash
            out.write("\x1b[H\x1b[2J" + frame)
            out.flush()
            time.sleep(max(interval, 0.2))
    except KeyboardInterrupt:
        out.write("\n")
        return 0
