"""In-scan replay counters — the exact, bit-reproducible half of obs.

The counter vector rides INSIDE each engine's lax.scan carry (a `ctr`
leaf of FlatTableCarry / BlockedTableCarry / ShardTableCarry and the
sequential engine's scan tuple), so the counts are integer adds on
device, bit-identical across engines for the same trace, and — because
the carry IS the checkpoint (tpusim.io.storage) — preserved exactly
across kill/resume and across the fault path's segment splits.

Vocabulary (COUNTER_FIELDS order is the array layout — append-only, the
JSONL schema names these fields):

    creates       creation events attempted (EV_CREATE)
    binds         creations that placed (node >= 0)
    fail_creates  creations rejected (no feasible node)
    deletes       deletion events applied (EV_DELETE)
    skips         EV_SKIP events, INCLUDING the driver's bucket padding;
                  the driver subtracts the padding when it records a run
                  (Recorder.note_scan(pad_skips=...)), so emitted records
                  count only trace skips
    rebuilds      blocked-select summary-row rebuilds (the extrema-drift
                  cond in the single-device blocked table engine). Engine
                  -specific by nature: 0 on the flat/sequential/pallas
                  paths and on the shard engine (which refreshes block
                  summaries unconditionally) — cross-engine equality
                  holds for COUNTER_FIELDS[:5], pinned by tests/test_obs.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

COUNTER_FIELDS = (
    "creates", "binds", "fail_creates", "deletes", "skips", "rebuilds",
)
NUM_COUNTERS = len(COUNTER_FIELDS)
# engine-invariant prefix (everything but `rebuilds`)
INVARIANT_FIELDS = COUNTER_FIELDS[:5]


def zero_counters():
    """i32[NUM_COUNTERS] carry leaf at event 0."""
    import jax.numpy as jnp

    return jnp.zeros(NUM_COUNTERS, jnp.int32)


def counter_delta(kc, node, rebuilt=None):
    """Per-event counter increment vector from the (clipped) event kind
    and the replicated placement decision — the ONE definition every
    engine's scan body adds to its `ctr` leaf, so the counts cannot drift
    apart across engines. `rebuilt` is the blocked engine's summary-row
    rebuild predicate (None/0 elsewhere)."""
    import jax.numpy as jnp

    is_create = kc == 0
    if rebuilt is None:
        rebuilt = jnp.bool_(False)
    return jnp.stack([
        is_create.astype(jnp.int32),
        (is_create & (node >= 0)).astype(jnp.int32),
        (is_create & (node < 0)).astype(jnp.int32),
        (kc == 1).astype(jnp.int32),
        (kc == 2).astype(jnp.int32),
        jnp.asarray(rebuilt).astype(jnp.int32),
    ])


def counters_to_dict(ctr, pad_skips: int = 0) -> Dict[str, int]:
    """Host dict from a counter vector; `pad_skips` = EV_SKIP events the
    driver appended as bucket padding (subtracted so records describe the
    trace, not the executable's padded shape)."""
    vals = np.asarray(ctr).astype(np.int64)
    d = {name: int(v) for name, v in zip(COUNTER_FIELDS, vals)}
    d["skips"] = max(d["skips"] - int(pad_skips), 0)
    return d


def counters_from_telemetry(ev_kind, event_node) -> Optional[np.ndarray]:
    """Derive the engine-invariant counters from a replay's per-event
    telemetry — the fallback for engines whose scan carry does not count
    (the fused Pallas kernel, the host-loop extender engine). Exact by
    construction for COUNTER_FIELDS[:5]; `rebuilds` is 0 (those engines
    have no blocked summaries). Returns i64[NUM_COUNTERS]."""
    kinds = np.asarray(ev_kind)
    nodes = np.asarray(event_node)
    if kinds.size != nodes.size:
        return None
    is_c = kinds == 0
    out = np.zeros(NUM_COUNTERS, np.int64)
    out[0] = int(is_c.sum())
    out[1] = int((is_c & (nodes >= 0)).sum())
    out[2] = int((is_c & (nodes < 0)).sum())
    out[3] = int((kinds == 1).sum())
    out[4] = int((kinds == 2).sum())
    return out
