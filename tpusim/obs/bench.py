"""Shared bench harness: the ONE timing protocol + JSON emission the
three bench scripts (bench.py, bench_scale.py, bench_multichip.py) used
to each re-implement.

The protocol (pinned round 5, unchanged here): one cold call (compile +
first run), then `warm_runs` warm calls; the headline wall is the STABLE
MINIMUM over the warm samples — the tunneled chip's run-to-run variance
is ±20%, and the minimum estimates the noise-free device cost. All raw
samples ship alongside so a reader can judge the spread.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Callable, List

# warm replays per measurement — the historical bench.py constant, now
# single-sourced for every bench lane
WARM_RUNS = 6


def measure(fn: Callable[[], object], warm_runs: int = WARM_RUNS) -> dict:
    """Cold + warm-minimum measurement of a nullary callable (the callable
    must block on its device work). Returns
    {first_s, samples_s, min_s} — callers rename/round per their row
    schema via `round_row`."""
    t0 = time.perf_counter()
    fn()
    first = time.perf_counter() - t0
    samples: List[float] = []
    for _ in range(max(warm_runs, 1)):
        t0 = time.perf_counter()
        fn()
        samples.append(time.perf_counter() - t0)
    return {"first_s": first, "samples_s": samples, "min_s": min(samples)}


def measure_cold_warm(fn: Callable[[], object]) -> dict:
    """The two-call variant (multichip lane: every mesh size compiles its
    own program, one warm call is the signal)."""
    m = measure(fn, warm_runs=1)
    return {"cold_s": m["first_s"], "warm_s": m["min_s"]}


def round_row(row: dict, places: int = 3) -> dict:
    """Round the float leaves of a bench row (list leaves element-wise) —
    the shared presentation the BENCH_*.json consumers parse."""
    out = {}
    for k, v in row.items():
        if isinstance(v, float):
            out[k] = round(v, places)
        elif isinstance(v, list) and v and all(
            isinstance(x, float) for x in v
        ):
            out[k] = [round(x, places) for x in v]
        else:
            out[k] = v
    return out


def write_json(path: str, payload: dict, announce: bool = True) -> str:
    """Atomic JSON emission (tmp + rename) with the schema-stable layout
    the committed BENCH_*.json / BENCH_DETAILS.json files carry; prints
    the destination to stderr like every bench script did."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, path)
    if announce:
        print(f"[bench] wrote {path}", file=sys.stderr)
    return path
