"""In-process metrics history — the SLO plane's time axis (ISSUE 20).

Every observability surface before this PR was instantaneous: `/metrics`
is the newest scrape, `/queue` the current rings, the audit chain a list
of discrete decisions. Nothing could answer "fork p99 has been degrading
for ten minutes" without an external Prometheus. This module keeps that
history in-process:

  TSDB             fixed-interval sample store with downsampled
                   retention tiers (default 1 s x 15 m -> 15 s x 4 h).
                   Each tier holds per-series buckets of (sum, count);
                   reads return the bucket mean, so a coarse tier is the
                   honest average of the fine one, not a decimation.
  ServiceCollector reads the coordinator's own in-process sources (the
                   JobQueue stats/latency rings, the fleet registry's
                   measured worker profiles, the supervisor breaker) and
                   emits one sample batch per tick in the SAME metric
                   vocabulary `/metrics` exposes — one set of names for
                   scrapers, the tsdb, and the alert rules.
  MetricsSampler   the daemon thread driving collect -> ingest ->
                   alerts.evaluate once per interval, persisting the
                   ring as a periodic SIGNED snapshot in the artifact
                   dir. A standby coordinator starts the sampler PAUSED
                   (sampling while not leading would interleave two
                   writers); at promotion it adopts the leader's last
                   snapshot and resumes, so `/query` history survives an
                   epoch-fenced takeover instead of starting blind.
  TsdbApp          the MonitorServer extension app serving
                   GET /query?name=&label=&since=&step= (JSON series)
                   and GET /alerts (the rule engine's view).

The snapshot rides io.storage.write_signed_json — atomic tmp+rename, a
digest-signed header — so a kill -9 mid-write leaves the previous
snapshot intact and a torn/edited file is rejected at adopt time, never
silently merged.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse
from typing import Dict, Iterable, List, Optional, Tuple

from tpusim.io.storage import (
    read_signed_json,
    tsdb_snapshot_path,
    write_signed_json,
)

SNAPSHOT_SCHEMA = "tpusim-tsdb-snapshot/1"

# (step seconds, bucket capacity) fine -> coarse; every sample feeds
# every tier, retention prunes each tier independently:
#   1 s x 900  = 15 minutes at full resolution
#   15 s x 960 = 4 hours downsampled
DEFAULT_TIERS: Tuple[Tuple[float, int], ...] = ((1.0, 900), (15.0, 960))

_JSON = "application/json"


def _json_body(code: int, doc):
    return code, _JSON, (json.dumps(doc, sort_keys=True) + "\n").encode()


def _labels_key(labels) -> Tuple[Tuple[str, str], ...]:
    """Canonical hashable form of a label set. Values pass through
    verbatim — hostile worker names (quotes, backslashes, newlines) are
    data here; only the Prometheus TEXT rendering needs escaping."""
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class TSDB:
    """Thread-safe multi-tier sample store. Series are keyed by
    (metric name, label set); each tier maps bucket index -> [sum,
    count] so same-bucket samples merge into a mean instead of
    overwriting each other."""

    def __init__(self, tiers: Iterable[Tuple[float, int]] = DEFAULT_TIERS):
        tiers = tuple((float(s), int(c)) for s, c in tiers)
        if not tiers:
            raise ValueError("tsdb needs at least one retention tier")
        steps = [s for s, _ in tiers]
        if steps != sorted(steps) or len(set(steps)) != len(steps):
            raise ValueError(
                f"tier steps must be strictly ascending, got {steps}"
            )
        if any(s <= 0 or c < 2 for s, c in tiers):
            raise ValueError(f"bad tier shape {tiers}: want step > 0, "
                             "capacity >= 2")
        self.tiers = tiers
        self._lock = threading.Lock()
        # (name, labels_key) -> [tier dict: bucket -> [sum, count]]
        self._series: Dict[Tuple[str, tuple], List[Dict[int, list]]] = {}
        self.ingested = 0

    # ---- write side ----

    def ingest(self, samples, now: Optional[float] = None) -> int:
        """Fold one batch of (name, labels|None, value) samples in at
        time `now`. Returns the number accepted (non-finite values are
        dropped — a NaN in the ring would poison every mean)."""
        if now is None:
            now = time.time()
        n = 0
        with self._lock:
            for name, labels, value in samples:
                try:
                    v = float(value)
                except (TypeError, ValueError):
                    continue
                if v != v or v in (float("inf"), float("-inf")):
                    continue
                key = (str(name), _labels_key(labels))
                tiers = self._series.get(key)
                if tiers is None:
                    tiers = [{} for _ in self.tiers]
                    self._series[key] = tiers
                for (step, cap), buckets in zip(self.tiers, tiers):
                    b = int(now / step)
                    cell = buckets.get(b)
                    if cell is None:
                        buckets[b] = [v, 1]
                        # prune: retention is bucket-count per tier
                        floor = b - cap + 1
                        if len(buckets) > cap:
                            for old in [x for x in buckets if x < floor]:
                                del buckets[old]
                    else:
                        cell[0] += v
                        cell[1] += 1
                n += 1
            self.ingested += n
        return n

    # ---- read side ----

    def _pick_tier(self, since: float, step: float, now: float) -> int:
        """Finest tier that satisfies the requested step AND whose
        retention window reaches back to `since`; the coarsest tier is
        the fallback when nothing reaches that far."""
        chosen = 0
        for i, (tier_step, cap) in enumerate(self.tiers):
            if step > 0 and tier_step < step:
                continue
            chosen = i
            if since > 0 and since < now - tier_step * cap:
                continue  # this tier can't reach back far enough
            break
        return chosen

    def query(self, name: str = "", label=None, since: float = 0.0,
              step: float = 0.0, now: Optional[float] = None) -> List[dict]:
        """JSON-ready series list. `label` filters on a dict subset
        (every given pair must match). `since` <= 0 means "that many
        seconds ago"; absolute unix stamps pass through. Points are
        [bucket start unix, mean] ascending."""
        if now is None:
            now = time.time()
        if since < 0:
            since = now + since  # relative-ago form
        elif since == 0:
            since = -1.0  # 0 -> everything (any positive stamp passes)
        want = dict(label or {})
        ti = self._pick_tier(since, step, now)
        tier_step = self.tiers[ti][0]
        out = []
        with self._lock:
            for (sname, lkey), tiers in sorted(self._series.items()):
                if name and sname != name:
                    continue
                labels = dict(lkey)
                if any(labels.get(k) != str(v) for k, v in want.items()):
                    continue
                pts = []
                for b in sorted(tiers[ti]):
                    t = b * tier_step
                    if t < since or t > now:
                        continue
                    s, c = tiers[ti][b]
                    pts.append([round(t, 3), s / c])
                if pts:
                    out.append({"name": sname, "labels": labels,
                                "step_s": tier_step, "points": pts})
        return out

    def latest(self, name: str, label=None, within_s: float = 0.0,
               now: Optional[float] = None) -> List[Tuple[dict, float, float]]:
        """(labels, t, value) of each matching series' newest point —
        the threshold rules' read. `within_s` > 0 drops stale series."""
        if now is None:
            now = time.time()
        res = []
        for s in self.query(name, label=label, since=0.0, step=0.0,
                            now=now):
            t, v = s["points"][-1]
            if within_s > 0 and now - t > within_s:
                continue
            res.append((s["labels"], t, v))
        return res

    def names(self) -> List[dict]:
        """The discovery document: every series name with its label
        sets and fine-tier point counts."""
        with self._lock:
            rows: Dict[str, list] = {}
            for (name, lkey), tiers in sorted(self._series.items()):
                rows.setdefault(name, []).append(
                    {"labels": dict(lkey), "points": len(tiers[0])}
                )
        return [{"name": n, "series": s} for n, s in sorted(rows.items())]

    # ---- snapshot persistence (the takeover handoff) ----

    def snapshot_doc(self, now: Optional[float] = None) -> dict:
        if now is None:
            now = time.time()
        with self._lock:
            series = []
            for (name, lkey), tiers in sorted(self._series.items()):
                series.append({
                    "name": name,
                    "labels": dict(lkey),
                    "tiers": [
                        [[b, cell[0], cell[1]]
                         for b, cell in sorted(buckets.items())]
                        for buckets in tiers
                    ],
                })
        return {
            "t": round(now, 3),
            "tiers": [[s, c] for s, c in self.tiers],
            "series": series,
        }

    def write_snapshot(self, artifact_dir: str,
                       now: Optional[float] = None) -> str:
        path = tsdb_snapshot_path(artifact_dir)
        return write_signed_json(
            path, {"schema": SNAPSHOT_SCHEMA}, self.snapshot_doc(now)
        )

    def adopt(self, artifact_dir_or_path: str) -> int:
        """Merge a predecessor's snapshot into this ring: foreign
        buckets fill gaps, LOCAL buckets win collisions (the adopter is
        the live writer; the snapshot is history). Returns the number of
        buckets adopted; a missing snapshot is 0, a torn/edited one
        raises ValueError (read_signed_json's digest check) so a
        takeover never splices corrupt history silently."""
        path = (tsdb_snapshot_path(artifact_dir_or_path)
                if os.path.isdir(artifact_dir_or_path)
                else artifact_dir_or_path)
        if not os.path.isfile(path):
            return 0
        _, doc = read_signed_json(path, SNAPSHOT_SCHEMA)
        their_tiers = [tuple(t) for t in doc.get("tiers") or []]
        # map their tier index -> ours by step value; mismatched layouts
        # adopt only the tiers both sides share
        index = {float(s): i for i, (s, _) in enumerate(self.tiers)}
        n = 0
        with self._lock:
            for row in doc.get("series") or []:
                key = (str(row.get("name", "")),
                       _labels_key(row.get("labels") or {}))
                tiers = self._series.get(key)
                if tiers is None:
                    tiers = [{} for _ in self.tiers]
                    self._series[key] = tiers
                for ti, cells in enumerate(row.get("tiers") or []):
                    if ti >= len(their_tiers):
                        break
                    mine = index.get(float(their_tiers[ti][0]))
                    if mine is None:
                        continue
                    buckets = tiers[mine]
                    cap = self.tiers[mine][1]
                    for b, s, c in cells:
                        b = int(b)
                        if b not in buckets:
                            buckets[b] = [float(s), int(c)]
                            n += 1
                    if len(buckets) > cap:
                        for old in sorted(buckets)[:-cap]:
                            del buckets[old]
        return n


# ---------------------------------------------------------------------------
# The coordinator's sample source
# ---------------------------------------------------------------------------


class ServiceCollector:
    """One tick's samples off the live JobService: queue gauges,
    counter-derived rates, per-kind latency percentiles, fleet worker
    profiles, breaker state. Stateful: rates are deltas against the
    previous tick's counters."""

    # counters whose per-second rate the alert rules watch
    RATE_COUNTERS = ("steals", "lease_expired", "done", "failed")

    def __init__(self, service):
        self.service = service
        self._prev_t = 0.0
        self._prev: Dict[str, float] = {}
        self._lat_cursors: Dict[str, int] = {}

    def __call__(self, now: Optional[float] = None):
        if now is None:
            now = time.time()
        service = self.service
        queue = service.queue
        samples = []

        stats = queue.stats()
        depth = float(stats.get("depth", 0))
        cap = float(stats.get("capacity", 1) or 1)
        samples.append(("tpusim_queue_depth", None, depth))
        samples.append(("tpusim_queue_capacity", None, cap))
        samples.append(("tpusim_queue_saturation", None, depth / cap))
        for fam, d in (stats.get("families") or {}).items():
            samples.append(
                ("tpusim_queue_family_depth", {"family": fam}, float(d))
            )
        for key in ("submitted", "done", "failed", "rejected",
                    "dedup_hits", "quota_rejected", "steals",
                    "lease_expired", "dup_completions", "starved_claims"):
            samples.append(
                (f"tpusim_queue_{key}_total", None,
                 float(stats.get(key, 0)))
            )

        # counter rates: the burn-rate rules want "per second", not
        # "since boot" — computed against the previous tick
        dt = now - self._prev_t if self._prev_t else 0.0
        cur = {k: float(stats.get(k, 0)) for k in self.RATE_COUNTERS}
        if dt > 0:
            for k in ("steals", "lease_expired"):
                rate = max(cur[k] - self._prev.get(k, cur[k]), 0.0) / dt
                samples.append((f"tpusim_queue_{k}_rate", None, rate))
            dd = max(cur["done"] - self._prev.get("done", cur["done"]), 0.0)
            df = max(cur["failed"] - self._prev.get("failed",
                                                    cur["failed"]), 0.0)
            total = dd + df
            samples.append(
                ("tpusim_queue_error_ratio", None,
                 (df / total) if total else 0.0)
            )
        self._prev_t, self._prev = now, cur

        # per-kind admission->result percentiles, same names as the
        # /metrics summary rendering (emitters.latency_summary_lines)
        for kind, row in (stats.get("latency") or {}).items():
            kl = {"kind": kind}
            samples.append(("tpusim_queue_latency_seconds",
                            dict(kl, quantile="0.5"),
                            float(row.get("p50_s", 0.0))))
            samples.append(("tpusim_queue_latency_seconds",
                            dict(kl, quantile="0.99"),
                            float(row.get("p99_s", 0.0))))
            samples.append(("tpusim_queue_latency_seconds_count", kl,
                            float(row.get("count", 0))))
            if "adjusted_p99_s" in row:
                samples.append(("tpusim_queue_latency_adjusted_seconds",
                                dict(kl, quantile="0.99"),
                                float(row["adjusted_p99_s"])))

        # per-COMPLETION worst latency since the last tick: the burn-
        # rate SLI. A "p99 <= X" SLO is exactly a burn-rate rule with a
        # 1% budget over these event samples — and unlike the ring p99
        # gauge (which one slow job pins for 1024 completions), event
        # samples age out of the burn windows, so alerts RESOLVE once
        # the service is actually fast again
        for kind, vals in queue.latency_samples_since(
                self._lat_cursors).items():
            samples.append(("tpusim_queue_latency_event_seconds",
                            {"kind": kind}, max(vals)))

        fleet = getattr(service, "fleet", None)
        if fleet is not None:
            reg = fleet.registry
            now_u = time.time()
            samples.append(("tpusim_fleet_workers_live", None,
                            float(reg.live_count(now_u))))
            sup = getattr(fleet, "supervisor", None)
            if sup is not None:
                br = sup.describe().get("breaker") or {}
                samples.append(
                    ("tpusim_fleet_breaker_open", None,
                     1.0 if br.get("state") == "open" else 0.0)
                )
            for wid, row in reg.describe(queue).items():
                wl = {"worker": wid}
                prof = row.get("profile") or {}
                samples.append(("tpusim_fleet_worker_ewma_dispatch_s", wl,
                                float(prof.get("ewma_dispatch_s", 0.0))))
                samples.append(("tpusim_fleet_worker_transfer_bps", wl,
                                float(prof.get("transfer_bps", 0.0))))
                samples.append(("tpusim_fleet_worker_compile_hit_rate",
                                wl,
                                float(prof.get("compile_hit_rate", 0.0))))
                samples.append(("tpusim_fleet_worker_leases_held", wl,
                                float(row.get("leases_held", 0))))
        return samples


# ---------------------------------------------------------------------------
# Sampler thread
# ---------------------------------------------------------------------------


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        v = float(raw)
        return v if v > 0 else default
    except ValueError:
        return default


class MetricsSampler:
    """The clock of the SLO plane: one daemon thread ticking
    collect -> ingest -> alerts.evaluate at a fixed interval, writing a
    signed snapshot every `snapshot_every_s`. pause()/resume() gate the
    whole loop — a standby holds the sampler paused until promotion
    (only the leader may write history), and resume() after adopt()
    splices new samples onto the inherited ring."""

    def __init__(self, tsdb: TSDB, collect, alerts=None,
                 artifact_dir: str = "", interval_s: float = 0.0,
                 snapshot_every_s: float = 0.0, paused: bool = False):
        self.tsdb = tsdb
        self.collect = collect
        self.alerts = alerts
        self.artifact_dir = artifact_dir
        self.interval_s = interval_s or _env_float("TPUSIM_TSDB_STEP_S",
                                                   1.0)
        self.snapshot_every_s = (
            snapshot_every_s or _env_float("TPUSIM_TSDB_SNAPSHOT_S", 5.0)
        )
        self.ticks = 0
        self.snapshot_errors = 0
        self._last_snapshot = 0.0
        self._stop = threading.Event()
        self._active = threading.Event()
        if not paused:
            self._active.set()
        self._thread: Optional[threading.Thread] = None

    @property
    def paused(self) -> bool:
        return not self._active.is_set()

    def start(self) -> "MetricsSampler":
        self._thread = threading.Thread(
            target=self._run, name="tpusim-sampler", daemon=True
        )
        self._thread.start()
        return self

    def pause(self):
        self._active.clear()

    def resume(self):
        self._active.set()

    def stop(self):
        self._stop.set()
        self._active.set()  # unblock a paused loop so it can exit
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def tick(self, now: Optional[float] = None):
        """One sampling step, callable directly from tests (the thread
        loop is just this on a timer)."""
        if now is None:
            now = time.time()
        self.tsdb.ingest(self.collect(now), now)
        if self.alerts is not None:
            self.alerts.evaluate(now)
        self.ticks += 1
        if (self.artifact_dir
                and now - self._last_snapshot >= self.snapshot_every_s):
            self._last_snapshot = now
            try:
                self.tsdb.write_snapshot(self.artifact_dir, now)
            except OSError:
                self.snapshot_errors += 1  # history is best-effort

    def _run(self):
        while not self._stop.is_set():
            self._active.wait()
            if self._stop.is_set():
                return
            try:
                self.tick()
            except Exception:
                # one bad tick (a racing shutdown, a half-built fleet)
                # must not kill the history thread
                pass
            self._stop.wait(self.interval_s)


# ---------------------------------------------------------------------------
# HTTP surface: GET /query and GET /alerts
# ---------------------------------------------------------------------------


class TsdbApp:
    """MonitorServer extension app for the SLO plane's read side."""

    accepts_query = True

    MAX_WINDOW_S = 4 * 3600.0

    def __init__(self, tsdb: TSDB, alerts=None):
        self.tsdb = tsdb
        self.alerts = alerts

    def handle(self, method: str, path: str, body: bytes, headers=None,
               query: str = ""):
        if method != "GET":
            return None
        if path == "/query":
            return self._query(query)
        if path == "/alerts":
            if self.alerts is None:
                return _json_body(200, {"rules": [], "firing": [],
                                        "transitions": []})
            return _json_body(200, self.alerts.describe())
        return None

    def _query(self, query: str):
        q = urllib.parse.parse_qs(query or "")

        def one(key, default=""):
            vals = q.get(key) or [default]
            return vals[0]

        name = one("name")
        if not name:
            return _json_body(200, {"names": self.tsdb.names()})
        label = {}
        for pair in q.get("label") or []:
            k, sep, v = pair.partition("=")
            if not sep or not k:
                return _json_body(
                    400, {"error": f"label must be key=value, got "
                          f"{pair!r}"}
                )
            label[k] = v
        try:
            since = float(one("since", "-900"))
            step = float(one("step", "0"))
        except ValueError:
            return _json_body(
                400, {"error": "since and step must be numbers"}
            )
        now = time.time()
        if since <= 0:
            since = max(since, -self.MAX_WINDOW_S)
        series = self.tsdb.query(name, label=label, since=since,
                                 step=step, now=now)
        return _json_body(
            200, {"now": round(now, 3), "series": series}
        )
