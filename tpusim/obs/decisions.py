"""Decision provenance — the per-event flight recorder of obs (ISSUE 4).

The paper's whole argument is comparative: FGD places pods *differently*
from BestFit/DotProd/Packing, and that difference IS the fragmentation
win. End-state aggregates show *that* two policies diverge; this module
captures *which event* diverged first and *why a node won*, at scan time
instead of by re-running.

Vocabulary (the fixed-shape per-event record every engine emits from its
scan — the decision twin of the `counters.py` `ctr` leaf):

    node        i32     winning node (-1 = failed create / non-create)
    total       i32     the winner's weighted selectHost total
    raw         i32[π]  the winner's per-policy RAW plugin scores
    norm        i32[π]  the winner's per-policy NORMALIZED scores — the
                        values the weighted sum actually consumed, so
                        Σ weight·norm == total holds exactly
    topk_node   i32[K]  top-K candidates in selection order (entry 0 IS
                        the packed_argmax winner; -1 pads)
    topk_total  i32[K]  their weighted totals
    topk_rank   i32[K]  their tie-break ranks (the lexicographic second
                        key — why equal-total candidates lost)
    feasible    i32     Filter-phase candidate count (pinning included)
    block       i32     the block id that won in a blocked select.
                        Engine-SPECIFIC by nature (like the counters'
                        `rebuilds` slot): -1 on the flat/sequential
                        paths — cross-engine bit-identity is pinned on
                        INVARIANT_FIELDS.

All leaves are exact i32, so the stream is bit-reproducible across
engines, transparent to checkpoint kill/resume (the driver persists the
accumulated stream beside event_node/event_dev in the same
content-addressed checkpoint), and continuous across fault segmentation.

Persistence is one JSONL file per run: a header line (schema, policies +
weights, meta, and a sha256 payload digest under the io.storage
checkpoint-digest discipline — a torn or hand-edited file fails loudly
on read), then one line per event. `tpusim explain` and `tpusim diff`
consume these files.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

DECISION_SCHEMA = "tpusim-decisions-v1"

# Top-K depth of the runner-up capture. A fixed constant — NOT a knob —
# because every engine must emit the same shape for the cross-engine
# bit-identity contract to be checkable with array_equal.
DECISION_TOPK = 3


class DecisionRecord(NamedTuple):
    """One scheduling decision (field semantics in the module docstring).
    Engines stack these over the event axis as lax.scan outputs; every
    leaf is i32."""

    node: object
    total: object
    raw: object  # [num_policies]
    norm: object  # [num_policies]
    topk_node: object  # [DECISION_TOPK]
    topk_total: object  # [DECISION_TOPK]
    topk_rank: object  # [DECISION_TOPK]
    feasible: object
    block: object


# engine-invariant fields (everything but the blocked-select block id)
INVARIANT_FIELDS = tuple(
    f for f in DecisionRecord._fields if f != "block"
)


class DecisionLog(NamedTuple):
    """A replay's full decision stream plus the event stream it describes
    — what SimulateResult.decisions carries and write_decisions persists.
    All members are host numpy arrays with a leading event axis."""

    records: DecisionRecord
    ev_kind: object  # i32[E]
    ev_pod: object  # i32[E]


def no_decision(num_policies: int) -> DecisionRecord:
    """The inert record non-create events (and the disabled branches of
    the engines' event switch) emit — fixed shape, all sentinels."""
    import jax.numpy as jnp

    z = jnp.int32(0)
    return DecisionRecord(
        node=jnp.int32(-1),
        total=z,
        raw=jnp.zeros(num_policies, jnp.int32),
        norm=jnp.zeros(num_policies, jnp.int32),
        topk_node=jnp.full(DECISION_TOPK, -1, jnp.int32),
        topk_total=jnp.zeros(DECISION_TOPK, jnp.int32),
        topk_rank=jnp.full(DECISION_TOPK, -1, jnp.int32),
        feasible=z,
        block=jnp.int32(-1),
    )


def concat_logs(logs: Sequence[DecisionLog]) -> Optional[DecisionLog]:
    """Concatenate segment logs along the event axis (the fault path's
    per-segment streams; checkpoint resume concatenates the same way)."""
    logs = [l for l in logs if l is not None]
    if not logs:
        return None
    rec = DecisionRecord(
        *(
            np.concatenate([np.asarray(getattr(l.records, f)) for l in logs])
            for f in DecisionRecord._fields
        )
    )
    return DecisionLog(
        rec,
        np.concatenate([np.asarray(l.ev_kind) for l in logs]),
        np.concatenate([np.asarray(l.ev_pod) for l in logs]),
    )


# ---------------------------------------------------------------------------
# Host-side rows + JSONL persistence
# ---------------------------------------------------------------------------


def decision_rows(log: DecisionLog, pod_names=None) -> List[dict]:
    """One JSON-ready dict per event from a stacked DecisionLog."""
    r = log.records
    node = np.asarray(r.node)
    total = np.asarray(r.total)
    raw = np.asarray(r.raw)
    norm = np.asarray(r.norm)
    tkn = np.asarray(r.topk_node)
    tkt = np.asarray(r.topk_total)
    tkr = np.asarray(r.topk_rank)
    feas = np.asarray(r.feasible)
    blk = np.asarray(r.block)
    kinds = np.asarray(log.ev_kind)
    pods = np.asarray(log.ev_pod)
    rows = []
    for i in range(node.shape[0]):
        row = {
            "e": int(i),
            "kind": int(kinds[i]),
            "pod": int(pods[i]),
            "node": int(node[i]),
            "total": int(total[i]),
            "raw": raw[i].astype(int).tolist(),
            "norm": norm[i].astype(int).tolist(),
            "topk": [
                [int(tkn[i, j]), int(tkt[i, j]), int(tkr[i, j])]
                for j in range(tkn.shape[1])
            ],
            "feasible": int(feas[i]),
            "block": int(blk[i]),
        }
        if pod_names is not None:
            row["name"] = str(pod_names[int(pods[i])])
        rows.append(row)
    return rows


def _row_lines(rows: List[dict]) -> List[str]:
    return [
        json.dumps(r, sort_keys=True, separators=(",", ":")) for r in rows
    ]


def _payload_digest(lines: List[str]) -> str:
    from tpusim.io.storage import checkpoint_digest

    return checkpoint_digest(
        (line + "\n").encode() for line in lines
    )


def write_decisions(
    path: str,
    log: DecisionLog,
    policies: Sequence,
    meta: Optional[dict] = None,
    pod_names=None,
) -> str:
    """Persist one run's decision stream as JSONL: a header line carrying
    the schema, the policy list with weights (what `explain` multiplies
    the norm column by), caller meta, and the sha256 digest of the
    payload lines (io.storage.checkpoint_digest — the same
    content-digest discipline checkpoints use, so read_decisions rejects
    torn/edited files), then one line per event. Written atomically
    (tmp + os.replace)."""
    rows = decision_rows(log, pod_names)
    lines = _row_lines(rows)
    header = {
        "schema": DECISION_SCHEMA,
        "topk": DECISION_TOPK,
        "events": len(rows),
        "policies": [[str(n), int(w)] for n, w in policies],
        "meta": dict(meta or {}),
        "digest": _payload_digest(lines),
    }
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(header, sort_keys=True, separators=(",", ":")))
        f.write("\n")
        for line in lines:
            f.write(line + "\n")
    os.replace(tmp, path)
    return path


def read_decisions(path: str) -> Tuple[dict, List[dict]]:
    """(header, rows) from a decision JSONL; verifies the header's payload
    digest so a torn/truncated/edited file fails loudly instead of
    producing a silently wrong explain/diff."""
    with open(path) as f:
        raw_lines = [l.rstrip("\n") for l in f if l.strip()]
    if not raw_lines:
        raise ValueError(f"{path}: empty decision file")
    header = json.loads(raw_lines[0])
    if header.get("schema") != DECISION_SCHEMA:
        raise ValueError(
            f"{path}: not a {DECISION_SCHEMA} file "
            f"(schema={header.get('schema')!r})"
        )
    payload = raw_lines[1:]
    digest = _payload_digest(payload)
    if digest != header.get("digest"):
        raise ValueError(
            f"{path}: payload digest mismatch (torn or edited file): "
            f"header {header.get('digest')} != computed {digest}"
        )
    if len(payload) != int(header.get("events", len(payload))):
        raise ValueError(
            f"{path}: header says {header.get('events')} events, file has "
            f"{len(payload)}"
        )
    return header, [json.loads(l) for l in payload]


# ---------------------------------------------------------------------------
# Run-diff divergence tracing
# ---------------------------------------------------------------------------


def check_comparable(rows_a: List[dict], rows_b: List[dict]) -> None:
    """Reject a diff of two runs that do not describe the same trace:
    every compared row must agree on (kind, pod) — the event stream —
    and on the pod NAME where both runs recorded one (pod indices alone
    are too weak: unrelated traces both open with 'create pod 0') — or
    the 'divergence' the diff reports is an artifact of comparing
    unrelated runs, not a policy difference. Lengths may differ (a
    shorter run diffs on the overlap); content may not."""
    for ra, rb in zip(rows_a, rows_b):
        same = int(ra["kind"]) == int(rb["kind"]) and int(
            ra["pod"]
        ) == int(rb["pod"])
        if same and "name" in ra and "name" in rb:
            same = ra["name"] == rb["name"]
        if not same:
            na = ra.get("name", f"pod[{ra['pod']}]")
            nb = rb.get("name", f"pod[{rb['pod']}]")
            raise ValueError(
                f"runs are not comparable: event {ra['e']} is "
                f"{_kind_name(ra['kind'])} {na} in one run but "
                f"{_kind_name(rb['kind'])} {nb} in the other — the two "
                "files describe different traces"
            )


def run_diff(
    header_a: dict, rows_a: List[dict],
    header_b: dict, rows_b: List[dict],
    label_a: str = "A", label_b: str = "B", buckets: int = 10,
) -> dict:
    """The one-stop diff entry `tpusim diff` and
    experiments.analysis.diff_decision_runs share: verifies the two runs
    describe the same trace (check_comparable — raises ValueError
    otherwise), then computes the divergence histogram, the
    first-divergence detail, and the formatted report in a single pass
    over the rows. Returns {'first', 'histogram', 'text'}."""
    check_comparable(rows_a, rows_b)
    hist = divergence_histogram(rows_a, rows_b, buckets)
    first = None
    if hist["first"] is not None:
        i = hist["first"]
        first = {"event": int(rows_a[i]["e"]), "a": rows_a[i],
                 "b": rows_b[i]}
    return {
        "first": first,
        "histogram": hist,
        "text": format_diff(
            header_a, rows_a, header_b, rows_b,
            label_a=label_a, label_b=label_b, buckets=buckets,
            hist=hist, first=first,
        ),
    }


def first_divergence(rows_a: List[dict], rows_b: List[dict]) -> Optional[dict]:
    """First event where the two runs placed differently (node differs),
    or None when the compared prefix agrees. Deletes/skips inherit their
    divergence from the creating event, so comparing `node` across all
    events finds the first *decision* divergence."""
    for ra, rb in zip(rows_a, rows_b):
        if int(ra["node"]) != int(rb["node"]):
            return {"event": int(ra["e"]), "a": ra, "b": rb}
    return None


def divergence_histogram(
    rows_a: List[dict], rows_b: List[dict], buckets: int = 10
) -> dict:
    """Where the two runs disagree: per-event-range bucket counts of
    differing placements, plus summary totals. Compares the common event
    prefix (runs of different lengths diff on the overlap)."""
    n = min(len(rows_a), len(rows_b))
    diff_idx = [
        i
        for i, (ra, rb) in enumerate(zip(rows_a, rows_b))
        if int(ra["node"]) != int(rb["node"])
    ]
    buckets = max(1, min(buckets, max(n, 1)))
    width = max(1, -(-n // buckets))
    counts = [0] * buckets
    for i in diff_idx:
        counts[min(i // width, buckets - 1)] += 1
    return {
        "events": n,
        "diverged": len(diff_idx),
        "bucket_width": width,
        "counts": counts,
        "first": diff_idx[0] if diff_idx else None,
        "last": diff_idx[-1] if diff_idx else None,
    }


def _policy_label(header: dict) -> str:
    return "+".join(n for n, _ in header.get("policies", [])) or "?"


def format_diff(
    header_a: dict, rows_a: List[dict], header_b: dict, rows_b: List[dict],
    label_a: str = "A", label_b: str = "B", buckets: int = 10,
    hist: Optional[dict] = None, first: Optional[dict] = None,
) -> str:
    """Human-readable run diff: first-divergence detail + the divergence
    histogram. Deterministic text for deterministic inputs (golden-output
    testable). `hist`/`first` accept precomputed results (run_diff passes
    them) so a large run is scanned once, not per consumer."""
    if hist is None:
        hist = divergence_histogram(rows_a, rows_b, buckets)
        first = first_divergence(rows_a, rows_b)
    out = [
        f"[diff] {label_a}: {_policy_label(header_a)} "
        f"({len(rows_a)} events)  vs  {label_b}: "
        f"{_policy_label(header_b)} ({len(rows_b)} events)",
        f"[diff] compared {hist['events']} events: "
        f"{hist['diverged']} diverged placements",
    ]
    if first is None:
        out.append("[diff] no divergence on the compared prefix")
        return "\n".join(out)
    ra, rb = first["a"], first["b"]
    name = ra.get("name", f"pod[{ra['pod']}]")
    out.append(
        f"[diff] first divergence at event {first['event']} "
        f"({_kind_name(ra['kind'])} {name}):"
    )
    out.append(
        f"[diff]   {label_a}: node {ra['node']} total {ra['total']} "
        f"(feasible {ra['feasible']})"
    )
    out.append(
        f"[diff]   {label_b}: node {rb['node']} total {rb['total']} "
        f"(feasible {rb['feasible']})"
    )
    out.append(
        f"[diff] histogram (bucket = {hist['bucket_width']} events): "
        + " ".join(str(c) for c in hist["counts"])
    )
    out.append(
        f"[diff] first diverged event {hist['first']}, last "
        f"{hist['last']}"
    )
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Explain — why a node won
# ---------------------------------------------------------------------------


def _kind_name(kind: int) -> str:
    return {0: "create", 1: "delete", 2: "skip"}.get(int(kind), f"kind{kind}")


def format_explain(header: dict, rows: List[dict], event: int) -> str:
    """The human-readable per-policy score table for one event: winner,
    raw/normalized/weighted contributions per policy (the weighted sum
    must reproduce the recorded selectHost total exactly — a mismatch
    raises ValueError, the unusable-input path of `tpusim explain`),
    top-K runner-ups with totals and tie-break ranks, and the 'why n
    beat m' line."""
    if not 0 <= event < len(rows):
        raise ValueError(
            f"event {event} out of range (run has {len(rows)} events)"
        )
    r = rows[event]
    name = r.get("name", f"pod[{r['pod']}]")
    kind = _kind_name(r["kind"])
    out = [f"event {event}: {kind} {name}"]
    if r["kind"] != 0:
        out.append(
            f"  no scheduling decision recorded for {kind} events "
            "(provenance is captured at creation time)"
        )
        return "\n".join(out)
    if r["node"] < 0:
        out.append(
            f"  unschedulable: {r['feasible']} feasible nodes after Filter"
        )
        return "\n".join(out)
    out.append(
        f"winner: node {r['node']}  total={r['total']}  "
        f"feasible={r['feasible']}"
        + (f"  block={r['block']}" if r["block"] >= 0 else "")
    )
    policies = header.get("policies", [])
    out.append(f"  {'policy':<20}{'weight':>8}{'raw':>10}{'norm':>8}"
               f"{'weighted':>12}")
    total = 0
    for i, (pname, weight) in enumerate(policies):
        raw = r["raw"][i] if i < len(r["raw"]) else 0
        norm = r["norm"][i] if i < len(r["norm"]) else 0
        contrib = int(weight) * int(norm)
        total += contrib
        out.append(
            f"  {pname:<20}{weight:>8}{raw:>10}{norm:>8}{contrib:>12}"
        )
    if total != int(r["total"]):
        raise ValueError(
            f"event {event}: weighted sum of per-policy contributions "
            f"({total}) != recorded winner total ({r['total']}) — the "
            "file's norm/weights are inconsistent with its totals"
        )
    out.append(
        f"  {'weighted sum':<46}{total:>12}  == recorded total "
        f"{r['total']}"
    )
    out.append(f"top-{len(r['topk'])} candidates (selection order):")
    for j, (n, t, rk) in enumerate(r["topk"]):
        if n < 0:
            continue
        tagline = "  <- winner" if j == 0 else ""
        out.append(f"  #{j + 1} node {n}  total={t}  rank={rk}{tagline}")
    runner = next(
        ((n, t, rk) for (n, t, rk) in r["topk"][1:] if n >= 0), None
    )
    if runner is not None:
        wn, wt, wr = r["topk"][0]
        rn, rt, rr = runner
        if wt != rt:
            why = f"higher total ({wt} > {rt})"
        else:
            why = f"equal totals, smaller tie-break rank ({wr} < {rr})"
        out.append(f"why node {wn} beat node {rn}: {why}")
    elif r["feasible"] == 1:
        out.append(
            f"node {r['node']} was the only feasible candidate"
        )
    return "\n".join(out)
