"""In-scan cluster time-series — the live-state half of obs (ISSUE 5).

The paper's output is two end-state numbers; PR 3/4 added exact counters
and per-decision provenance, but the only *per-event* view of cluster
state is still the metrics postpass, which runs after the scan finished.
This module gives every engine a fixed-stride sampling plane: when a
replay is built with `series_every = s > 0`, its scan body emits one
bounded-shape `SeriesSample` per event — a real sample whenever the
processed-event count crosses a multiple of `s`, an inert sentinel row
otherwise — so a long run's utilization/fragmentation/score
distributions are recorded AS THE SCAN RUNS and can be scraped live
(`tpusim apply --listen`, tpusim.obs.server).

Vocabulary (every leaf i32; like the counters, append-only):

    pos         processed-event count when the sample was taken (the
                stride clock = creates+deletes+skips applied so far,
                including the driver's bucket-padding skips); -1 marks
                the sentinel rows the host filters out
    util_hist   [UTIL_BUCKETS] UP GPU nodes bucketed by GPU-milli
                occupancy (bucket = used*B//cap, integer math — exact)
    nodes_down  nodes carrying the DOWN sentinel (mem_left < 0;
                tpusim.sim.faults) — 0 outside fault runs
    feasible    Filter-feasible node count for the sampled event's pod
                type (pinning excluded: a type-level property, so the
                value is comparable across events)
    frag        [7] cluster frag by FGD failure category (the
                `frag_amounts` row the end-state report sums away),
                in whole GPU-milli: each node's f32 row is rounded to
                integer milli BEFORE the cluster sum, so the total is an
                associative integer sum — bit-identical for any node
                partition, including the shard engine's psum. DOWN
                nodes are excluded (their capacity is dark, accounted
                by DisruptionMetrics instead). i32 bounds the exact
                range to ~250k nodes — beyond the current scale lane.
    score_hi    [num_policies] max NORMALIZED per-policy score over the
                feasible set (the value selectHost weights) — the
                "winning score" of each policy's lens
    score_lo    [num_policies] min normalized score over the feasible
                set; hi - lo is the per-policy score spread the
                policy-tuning line (PAPERS.md "Learning to Score") needs
    (retry_depth — the fault path's queue depth — is host-side state
    the driver fills per segment; it lives on SeriesLog, not the sample)

Engine invariance: every field is an integer reduction over (cluster
state after the previously-committed event, the event's pod-type score
rows) — inputs all four engines maintain identically — so the sampled
values are bit-identical across flat/blocked/sequential/shard, and,
because the stride clock rides the carry's `ctr` leaf, bit-identical
across checkpoint kill/resume. Fault runs restart the stride at each
segment (each segment is a fresh scan): every segment therefore OPENS
with a sample of the post-fault cluster, and the driver rebases `pos`
to the global event clock when it concatenates the segment logs.

Layering: like the rest of obs this module imports nothing from sim/ —
state-level stats come from tpusim.ops/tpusim.policies; engine-specific
inputs (score rows, feasibility) are handed in by the engines.

RandomScore's slot is always zero (its score row is a per-event PRNG
draw; sampling it would burn key splits and perturb the trajectory).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import numpy as np

SERIES_SCHEMA = "tpusim-series-v1"

# occupancy buckets of the node-utilization histogram: bucket i covers
# [i*100/B, (i+1)*100/B) percent of the node's GPU-milli capacity, with
# the top bucket closed at 100%. A fixed constant, NOT a knob — every
# engine must emit the same shape for array_equal-checkable invariance.
UTIL_BUCKETS = 10

# frag category names, in tpusim.constants class-id order (Q1..NO_ACCESS)
FRAG_CATEGORY_NAMES = (
    "q1_lack_both", "q2_lack_gpu", "q3_satisfied", "q4_lack_cpu",
    "xl_satisfied", "xr_lack_cpu", "no_access",
)


class SeriesSample(NamedTuple):
    """One stride sample (field semantics in the module docstring).
    Engines stack these over the event axis as lax.scan outputs; every
    leaf is i32."""

    pos: object
    util_hist: object  # [UTIL_BUCKETS]
    nodes_down: object
    feasible: object
    frag: object  # [7] whole GPU-milli
    score_hi: object  # [num_policies]
    score_lo: object  # [num_policies]


# every field is engine-invariant (there is no engine-specific slot)
INVARIANT_FIELDS = SeriesSample._fields


def no_sample(num_policies: int) -> SeriesSample:
    """The inert sentinel row emitted between stride points (and the
    not-taken branch of the sampling cond) — fixed shape, pos == -1."""
    import jax.numpy as jnp

    z = jnp.int32(0)
    return SeriesSample(
        pos=jnp.int32(-1),
        util_hist=jnp.zeros(UTIL_BUCKETS, jnp.int32),
        nodes_down=z,
        feasible=z,
        frag=jnp.zeros(len(FRAG_CATEGORY_NAMES), jnp.int32),
        score_hi=jnp.zeros(num_policies, jnp.int32),
        score_lo=jnp.zeros(num_policies, jnp.int32),
    )


def cluster_stats(state, tp, node_mask=None):
    """(util_hist i32[B], nodes_down i32, frag i32[7]) for one cluster
    state — the per-node half of a sample. `node_mask` masks node-axis
    padding rows out (the shard engine's mesh pad rows carry the same
    mem_left == -1 sentinel as DOWN nodes and must count as neither);
    single-device engines pass None (every row is real). All outputs are
    integer sums over nodes, so a sharded caller psums the per-shard
    partials exactly."""
    import jax
    import jax.numpy as jnp

    from tpusim.constants import MILLI
    from tpusim.ops.frag import node_frag_amounts

    n = state.num_nodes
    mask = (
        jnp.ones(n, jnp.bool_) if node_mask is None
        else jnp.asarray(node_mask)
    )
    down = (state.mem_left < 0) & mask
    up = mask & ~down
    cap = state.gpu_cnt * MILLI
    used = cap - state.gpu_left.sum(-1)
    gpu_up = up & (state.gpu_cnt > 0)
    bucket = jnp.clip(
        used * UTIL_BUCKETS // jnp.maximum(cap, 1), 0, UTIL_BUCKETS - 1
    )
    hist = (
        jax.nn.one_hot(bucket, UTIL_BUCKETS, dtype=jnp.int32)
        * gpu_up[:, None].astype(jnp.int32)
    ).sum(0)
    rows = jax.vmap(node_frag_amounts, in_axes=(0, 0, 0, None))(
        state.cpu_left, state.gpu_left, state.gpu_type, tp
    )  # f32[N, 7]
    # round each NODE's row to whole milli before summing: integer sums
    # are associative, so the cluster total cannot depend on how the node
    # axis is partitioned (the shard-psum exactness contract)
    frag = jnp.where(
        up[:, None], jnp.round(rows).astype(jnp.int32), 0
    ).sum(0)
    return hist, down.sum().astype(jnp.int32), frag


def score_stats(raws, feasible, policies):
    """(score_hi i32[pi], score_lo i32[pi]) over the feasible set from
    per-policy RAW score rows — normalization applied exactly as the
    select consumes it (minmax/pwr over the feasible mask, none =
    identity, RandomScore = zeros). With no feasible node both come out
    0. Used by the single-device engines; the shard engine reproduces
    the same values through pmin/pmax collectives (min/max are exact in
    any combine order)."""
    import jax.numpy as jnp

    from tpusim.policies import minmax_normalize_i32, pwr_normalize_i32

    big = jnp.int32(jnp.iinfo(jnp.int32).max)
    any_f = feasible.any()
    his, los = [], []
    for i, (fn, _) in enumerate(policies):
        raw = raws[i]
        if fn.policy_name == "RandomScore":
            nrm = jnp.zeros_like(raw)
        elif fn.normalize == "minmax":
            nrm = minmax_normalize_i32(raw, feasible)
        elif fn.normalize == "pwr":
            nrm = pwr_normalize_i32(raw, feasible)
        else:
            nrm = raw
        hi = jnp.max(jnp.where(feasible, nrm, -big))
        lo = jnp.min(jnp.where(feasible, nrm, big))
        his.append(jnp.where(any_f, hi, 0))
        los.append(jnp.where(any_f, lo, 0))
    return (
        jnp.stack(his).astype(jnp.int32),
        jnp.stack(los).astype(jnp.int32),
    )


def build_sample(state, tp, raws, feasible, policies, processed
                 ) -> SeriesSample:
    """Assemble one sample from the inputs every single-device engine
    has at the top of its scan body: the committed state, the sampled
    event's per-policy raw score rows ([pi, N] — pad columns must be
    infeasible) and type-level feasibility row. `processed` becomes
    `pos`."""
    import jax.numpy as jnp

    hist, down, frag = cluster_stats(state, tp)
    hi, lo = score_stats(raws, feasible, policies)
    return SeriesSample(
        pos=jnp.asarray(processed).astype(jnp.int32),
        util_hist=hist,
        nodes_down=down,
        feasible=feasible.sum().astype(jnp.int32),
        frag=frag,
        score_hi=hi,
        score_lo=lo,
    )


def emit_from_scan(every: int, processed, build_fn, num_policies: int
                   ) -> SeriesSample:
    """The sampling hook engines inline into their scan body: run
    `build_fn` (the O(N) sample assembly) only when the processed-event
    count sits on the stride, else emit the sentinel. `every` is static
    (baked into the jaxpr — part of the engine cache key); the cond
    bounds the amortized cost to O(N/every) extra work per event."""
    import jax

    return jax.lax.cond(
        (processed % every) == 0,
        build_fn,
        lambda: no_sample(num_policies),
    )


# ---------------------------------------------------------------------------
# Host-side log + JSONL record + rendering
# ---------------------------------------------------------------------------


class SeriesLog(NamedTuple):
    """A run's filtered sample stream on host: numpy arrays with a
    leading sample axis, plus the host-filled retry-queue depth (the
    fault driver knows the queue; the scan does not)."""

    pos: object  # i64[S] global event positions
    util_hist: object  # i32[S, UTIL_BUCKETS]
    nodes_down: object  # i32[S]
    feasible: object  # i32[S]
    frag: object  # i64[S, 7]
    score_hi: object  # i32[S, pi]
    score_lo: object  # i32[S, pi]
    retry_depth: object  # i64[S]


def log_from_stacked(stacked: SeriesSample, base_pos: int = 0,
                     retry_depth: int = 0) -> SeriesLog:
    """Filter a scan's stacked per-event SeriesSample down to the real
    samples (pos >= 0) and rebase their positions onto the run-global
    event clock (`base_pos` = events replayed before this scan — the
    fault path's segment offset). `retry_depth` fills the host column
    for every sample of this scan (constant within a segment)."""
    pos = np.asarray(stacked.pos)
    keep = pos >= 0
    s = int(keep.sum())
    return SeriesLog(
        pos=pos[keep].astype(np.int64) + int(base_pos),
        util_hist=np.asarray(stacked.util_hist)[keep],
        nodes_down=np.asarray(stacked.nodes_down)[keep],
        feasible=np.asarray(stacked.feasible)[keep],
        frag=np.asarray(stacked.frag)[keep].astype(np.int64),
        score_hi=np.asarray(stacked.score_hi)[keep],
        score_lo=np.asarray(stacked.score_lo)[keep],
        retry_depth=np.full(s, int(retry_depth), np.int64),
    )


def concat_series(logs: Sequence[Optional[SeriesLog]]
                  ) -> Optional[SeriesLog]:
    """Concatenate segment logs along the sample axis (fault segments,
    schedule_additional appends)."""
    logs = [l for l in logs if l is not None]
    if not logs:
        return None
    return SeriesLog(*(
        np.concatenate([np.asarray(getattr(l, f)) for l in logs])
        for f in SeriesLog._fields
    ))


def series_to_record(log: SeriesLog, every: int,
                     policy_names: Sequence[str]) -> dict:
    """The JSONL `series` block: pure-integer columns (deterministic —
    part of the record's bit-identity contract), plus the vocabulary
    needed to render without recomputation."""
    return {
        "schema": SERIES_SCHEMA,
        "every": int(every),
        "util_buckets": UTIL_BUCKETS,
        "frag_categories": list(FRAG_CATEGORY_NAMES),
        "policies": [str(p) for p in policy_names],
        "pos": np.asarray(log.pos).astype(int).tolist(),
        "util_hist": np.asarray(log.util_hist).astype(int).tolist(),
        "nodes_down": np.asarray(log.nodes_down).astype(int).tolist(),
        "feasible": np.asarray(log.feasible).astype(int).tolist(),
        "frag": np.asarray(log.frag).astype(int).tolist(),
        "score_hi": np.asarray(log.score_hi).astype(int).tolist(),
        "score_lo": np.asarray(log.score_lo).astype(int).tolist(),
        "retry_depth": np.asarray(log.retry_depth).astype(int).tolist(),
    }


def series_from_record(d: dict) -> SeriesLog:
    """Inverse of series_to_record (the `tpusim report` / plot input)."""
    if d.get("schema") != SERIES_SCHEMA:
        raise ValueError(
            f"not a {SERIES_SCHEMA} series block "
            f"(schema={d.get('schema')!r})"
        )
    s = len(d["pos"])
    pi = len(d.get("policies", []))
    return SeriesLog(
        pos=np.asarray(d["pos"], np.int64),
        util_hist=np.asarray(d["util_hist"], np.int64).reshape(
            s, d.get("util_buckets", UTIL_BUCKETS)),
        nodes_down=np.asarray(d["nodes_down"], np.int64),
        feasible=np.asarray(d["feasible"], np.int64),
        frag=np.asarray(d["frag"], np.int64).reshape(
            s, len(d.get("frag_categories", FRAG_CATEGORY_NAMES))),
        score_hi=np.asarray(d["score_hi"], np.int64).reshape(s, pi),
        score_lo=np.asarray(d["score_lo"], np.int64).reshape(s, pi),
        retry_depth=np.asarray(
            d.get("retry_depth", [0] * s), np.int64
        ),
    )


def series_tracks(log: SeriesLog) -> dict:
    """Chrome-trace counter-track dict (track name -> one value per
    sample; obs.emitters.chrome_counter_events) — the series' timeline
    view, sharing the emitter the frag/alloc postpass tracks use."""
    out = {
        "series_feasible_nodes": np.asarray(log.feasible).tolist(),
        "series_nodes_down": np.asarray(log.nodes_down).tolist(),
        "series_retry_depth": np.asarray(log.retry_depth).tolist(),
    }
    frag = np.asarray(log.frag)
    for j, name in enumerate(FRAG_CATEGORY_NAMES):
        out[f"series_frag_{name}"] = frag[:, j].tolist()
    return out


_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values, width: int = 40) -> str:
    """Coarse unicode sparkline (strided to `width` points, final value
    always kept — the terminal twin of the Chrome counter tracks)."""
    vals = [float(v) for v in values]
    if not vals:
        return ""
    if len(vals) > width:
        stride = -(-len(vals) // width)
        idx = list(range(0, len(vals), stride))
        if idx[-1] != len(vals) - 1:
            idx.append(len(vals) - 1)
        vals = [vals[i] for i in idx]
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(
        _SPARK[min(int((v - lo) / span * len(_SPARK)), len(_SPARK) - 1)]
        for v in vals
    )


def _stat_line(name: str, vals) -> str:
    a = np.asarray(vals, np.float64)
    if a.size == 0:
        return f"  {name:<28} (no samples)"
    return (
        f"  {name:<28}{a.min():>12.0f}{np.median(a):>12.0f}"
        f"{a.max():>12.0f}  {sparkline(a)}"
    )


def format_report(series: dict) -> str:
    """Terminal summary of a run record's series block: one line per
    scalar series (min / median / max + sparkline), expanded per
    category/bucket/policy for the vector series. Renders straight from
    the JSONL — no recomputation, no simulator."""
    log = series_from_record(series)
    n = len(np.asarray(log.pos))
    out = [
        f"[series] {n} samples, stride {series.get('every')} events "
        f"(pos {log.pos[0] if n else '-'}..{log.pos[-1] if n else '-'})",
        f"  {'series':<28}{'min':>12}{'median':>12}{'max':>12}",
        _stat_line("feasible_nodes", log.feasible),
        _stat_line("nodes_down", log.nodes_down),
        _stat_line("retry_depth", log.retry_depth),
    ]
    frag = np.asarray(log.frag)
    for j, name in enumerate(series.get(
            "frag_categories", FRAG_CATEGORY_NAMES)):
        out.append(_stat_line(f"frag_{name} (milli)", frag[:, j]))
    hist = np.asarray(log.util_hist)
    buckets = hist.shape[1] if hist.ndim == 2 else UTIL_BUCKETS
    for b in range(buckets):
        lo_pct = 100 * b // buckets
        hi_pct = 100 * (b + 1) // buckets
        out.append(_stat_line(
            f"util[{lo_pct}-{hi_pct}%) nodes", hist[:, b]
        ))
    hi = np.asarray(log.score_hi)
    lo = np.asarray(log.score_lo)
    for i, pname in enumerate(series.get("policies", [])):
        out.append(_stat_line(f"score_hi[{pname}]", hi[:, i]))
        out.append(_stat_line(
            f"score_spread[{pname}]", hi[:, i] - lo[:, i]
        ))
    return "\n".join(out)
