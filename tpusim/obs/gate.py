"""Bench regression gate: diff a profiled smoke run against the newest
committed `BENCH_r*.json` baseline (`make bench-gate`).

Every round's driver commits a BENCH_rNN.json capture of `python
bench.py` ({n, cmd, rc, tail, parsed}); until now nothing ever read them
back. The gate closes that loop:

  1. parse the newest committed baseline (highest rNN with rc == 0):
     headline placements/sec from `parsed.value`, plus the
     machine-INDEPENDENT quality numbers from the tail line —
     `events=`, `placed=`, `gpu_alloc=` — and the backend it ran on
     (the jax platform warning names it);
  2. re-run the same headline measurement (openb default trace, FGD,
     tune 1.3, seed 42) with obs profiling on, emitting the smoke
     profile JSONL/Prometheus files under --out;
  3. fail (exit 1) if a DETERMINISTIC quality number moved — event count
     or placement count off by even one, GPU allocation beyond
     --alloc-tol — or if throughput regressed more than --tol on the
     SAME backend as the baseline. Cross-backend throughput (CPU gate
     vs a TPU-captured baseline) is advisory: printed, never failed on,
     because the two machines measure different hardware.

Placements are backend-independent by the engine-equality contracts
(ENGINES.md; the f32 divergence channel is report-only), so the
quality half of the gate is exact everywhere.

The gate also smoke-checks the decision-provenance surface (ISSUE 4):
a small decision-recording replay writes its decision JSONL under
--out and the digest-verified read-back must round-trip exactly —
`tpusim explain`/`diff` depend on that file format.

And the live-telemetry surface (ISSUE 5): the smoke run's record is
published to an ephemeral MonitorServer and scraped over HTTP — the
scrape must parse as valid Prometheus exposition text and be byte-equal
to the gate_metrics.prom textfile, the same
final-scrape-equals-textfile contract `tpusim apply --listen` promises.

And the config-axis sweep surface (ISSUE 6): a small vmapped weight
sweep must run, reuse ONE compiled executable across weight grids (the
weights-are-operands contract), and its marginal per-config cost is
printed next to the newest committed `bench_scale.py --sweep` capture's
numbers — advisory only, since sweep walls are machine-shaped.

And the replay-service surface (ISSUE 7): a 4-job grid POSTed to an
ephemeral `serve --jobs` instance must come back dedup'd (the duplicate
answered from the digest cache) and batched onto ONE compiled sweep,
with a second weights+tune wave adding zero executables
(jit._cache_size() stable — the zero-recompile contract end-to-end
through the POST path). `--svc-only` runs just this check (the `make
svc-smoke` mode).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_TAIL_EVENTS = re.compile(r"events=(\d+)")
_TAIL_PLACED = re.compile(r"placed=(\d+)")
_TAIL_ALLOC = re.compile(r"gpu_alloc=([0-9.]+)%")
_TAIL_BACKEND = re.compile(r"Platform '(\w+)'")


def _iter_captures(repo: str):
    """Yield (path, round_number, data) for every readable committed
    BENCH_rNN.json with rc == 0. Malformed files — unreadable, bad JSON,
    a non-numeric `n` — are skipped, never raised: one torn capture must
    not take the whole gate down."""
    for path in glob.glob(os.path.join(repo, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
            if data.get("rc") != 0:
                continue
            n = int(data.get("n") or m.group(1))
        except (OSError, json.JSONDecodeError, TypeError, ValueError):
            continue
        yield path, n, data


def latest_baseline(repo: str = REPO) -> Optional[dict]:
    """Newest committed BENCH_rNN.json with a clean run, parsed into
    {path, n, throughput, events, placed, gpu_alloc, backend} (quality
    fields None when the tail did not carry them)."""
    best = None
    for path, n, data in _iter_captures(repo):
        if not data.get("parsed"):
            continue
        if best is None or n > best["n"]:
            tail = data.get("tail", "")
            ev = _TAIL_EVENTS.search(tail)
            pl = _TAIL_PLACED.search(tail)
            al = _TAIL_ALLOC.search(tail)
            be = _TAIL_BACKEND.search(tail)
            best = {
                "path": path,
                "n": n,
                "throughput": float(data["parsed"].get("value", 0.0)),
                "events": int(ev.group(1)) if ev else None,
                "placed": int(pl.group(1)) if pl else None,
                "gpu_alloc": float(al.group(1)) if al else None,
                "backend": be.group(1) if be else "cpu",
            }
    return best


def latest_sweep(repo: str = REPO) -> Optional[dict]:
    """Newest committed BENCH_rNN.json carrying a `sweep` block (written
    by `bench_scale.py --sweep ... --sweep-out`), parsed into the block
    plus {path, n}. Sweep captures deliberately ship WITHOUT a `parsed`
    key so latest_baseline never mistakes them for the headline
    throughput baseline."""
    best = None
    for path, n, data in _iter_captures(repo):
        if not isinstance(data.get("sweep"), dict):
            continue
        if best is None or n > best["n"]:
            best = {"path": path, "n": n, **data["sweep"]}
    return best


def sweep_advisory(nodes, pods, base: Optional[dict],
                   b: int = 4) -> Tuple[bool, List[str]]:
    """ISSUE 6 satellite: smoke the config-axis sweep surface and print
    an advisory throughput comparison against the newest committed sweep
    capture. Measures a B-config weight sweep over an openb prefix —
    warm wall, marginal per-config cost, and the marginal/standalone
    ratio (the number ENGINES.md Round 11 budgets; ratios travel across
    machines of one backend far better than raw walls). The comparison
    NEVER gates — cross-machine walls aren't comparable — but an
    exception on the sweep path is a FAIL: a broken sweep surface is
    exactly what the gate exists to catch. Also hard-checks the
    one-compile contract: a second sweep with different weights must not
    grow the compiled-executable count."""
    import time

    import numpy as np

    from tpusim.sim.driver import (
        Simulator,
        SimulatorConfig,
        _sweep_engine,
        schedule_pods_sweep,
    )

    try:
        import jax

        sim = Simulator(nodes, SimulatorConfig(
            policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
            report_per_event=False, seed=42,
        ))
        sim.set_workload_pods(pods[:200])
        sim.set_typical_pods()
        trace = sim.prepare_pods()

        def run(grid):
            t0 = time.perf_counter()
            lanes = schedule_pods_sweep(sim, trace, grid)
            return lanes, time.perf_counter() - t0

        grid = np.stack(
            [np.asarray([1000 - i], np.int32) for i in range(b)]
        )
        run(grid)  # compile run
        lanes, warm = run(grid)
        grid1 = grid[:1]
        run(grid1)
        _, warm1 = run(grid1)
        # one jaxpr per job family: a different weight grid must reuse
        # the compiled sweep executable, not add one — inspect the
        # engine the sweep ACTUALLY dispatched (the small smoke workload
        # may select the sequential path)
        used_table = sim._last_engine.startswith("table")
        fn = _sweep_engine(
            sim._table_fn.engine.replay if used_table
            else sim.replay_fn.engine,
            table=used_table,
        )
        before = fn._cache_size()
        if before < 1:
            return False, [
                f"[gate] sweep: {sim._last_engine!r} dispatched but its "
                "vmapped executable cache is empty — engine bookkeeping "
                "broken (FAIL)"
            ]
        run(np.stack(
            [np.asarray([500 + i], np.int32) for i in range(b)]
        ))
        if fn._cache_size() != before:
            return False, [
                "[gate] sweep: weight change RECOMPILED the sweep "
                f"engine ({before} -> {fn._cache_size()} executables) "
                "(FAIL)"
            ]
        marginal = max(warm - warm1, 0.0) / max(b - 1, 1)
    except Exception as err:
        return False, [
            f"[gate] sweep: FAIL ({type(err).__name__}: {err})"
        ]
    msgs = [
        f"[gate] sweep: B={b} x {lanes[0].events} events warm "
        f"{warm:.3f}s, marginal {marginal * 1000:.0f} ms/config, "
        f"placed[0]={lanes[0].placed} — weight change reused the "
        "compiled sweep executable (0 recompiles)"
    ]
    if base is not None and base.get("rows"):
        brow = max(base["rows"], key=lambda r: r.get("b", 0))
        msgs.append(
            f"[gate] sweep baseline {os.path.basename(base['path'])} "
            f"(round {base['n']}, backend {base.get('backend')!r}, "
            f"nodes={base.get('nodes')}, B={brow.get('b')}): "
            f"per_config {brow.get('per_config_s')}s, "
            f"ratio_vs_standalone {brow.get('ratio_vs_standalone')} — "
            "advisory only (different workload shape)"
        )
    else:
        msgs.append(
            "[gate] sweep: no committed sweep capture to compare "
            "(bench_scale.py --sweep 1,4,16 --sweep-out BENCH_rNN.json)"
        )
    return True, msgs


def compare(base: dict, cur: dict, tol: float, alloc_tol: float
            ) -> Tuple[bool, List[str]]:
    """Gate verdict + report lines. `cur` needs {throughput, events,
    placed, gpu_alloc, backend}."""
    ok = True
    msgs = []

    def check(label, b, c, exact=False, tol_abs=None):
        nonlocal ok
        if b is None:
            msgs.append(f"  {label}: baseline missing, current {c} (skip)")
            return
        if exact:
            good = b == c
        else:
            good = abs(c - b) <= tol_abs
        mark = "ok" if good else "REGRESSED"
        msgs.append(f"  {label}: baseline {b} vs current {c} [{mark}]")
        ok = ok and good

    check("events", base["events"], cur["events"], exact=True)
    check("placed pods", base["placed"], cur["placed"], exact=True)
    check("gpu_alloc %", base["gpu_alloc"], cur["gpu_alloc"],
          tol_abs=alloc_tol)
    ratio = (
        cur["throughput"] / base["throughput"] if base["throughput"] else 0.0
    )
    if cur["backend"] == base["backend"]:
        good = ratio >= 1.0 - tol
        mark = "ok" if good else "REGRESSED"
        msgs.append(
            f"  throughput: baseline {base['throughput']:.1f} vs current "
            f"{cur['throughput']:.1f} placements/s "
            f"({100 * ratio:.0f}%, tol -{100 * tol:.0f}%) [{mark}]"
        )
        ok = ok and good
    else:
        msgs.append(
            f"  throughput: {cur['throughput']:.1f} placements/s on "
            f"{cur['backend']!r} (baseline {base['throughput']:.1f} on "
            f"{base['backend']!r} — cross-backend, advisory only)"
        )
    return ok, msgs


def decisions_roundtrip(nodes, pods, out_dir: str) -> Tuple[bool, str]:
    """ISSUE 4 satellite: run a small decision-recording replay (openb
    prefix of the bench trace), write its decision JSONL, read it back
    through the digest-verified loader, and require the rows to
    round-trip exactly. A failure here means the provenance surface the
    explain/diff verbs depend on is broken — gate-worthy, so ANY
    exception on the record/write/read path becomes a FAIL verdict (the
    exit-1-with-messages contract of main()), not a traceback that also
    skips the baseline compare."""
    from tpusim.obs import decisions as obs_decisions
    from tpusim.sim.driver import Simulator, SimulatorConfig

    try:
        sim = Simulator(nodes[:200], SimulatorConfig(
            policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
            report_per_event=False, record_decisions=True, seed=42,
        ))
        sim.set_workload_pods(pods[:120])
        res = sim.run()
        if res.decisions is None:
            return False, "[gate] decisions: no stream recorded (FAIL)"
        names = [p.name for p in res.pods]
        path = os.path.join(out_dir, "gate_decisions.jsonl")
        obs_decisions.write_decisions(
            path, res.decisions, policies=list(sim.cfg.policies),
            meta=sim._telemetry_meta(), pod_names=names,
        )
        header, rows = obs_decisions.read_decisions(path)
    except Exception as err:
        return False, f"[gate] decisions: FAIL ({type(err).__name__}: {err})"
    expect = obs_decisions.decision_rows(res.decisions, names)
    if rows != expect:
        return False, (
            f"[gate] decisions: JSONL round-trip MISMATCH ({path})"
        )
    return True, (
        f"[gate] decisions: JSONL round-trip ok — {path} "
        f"({len(rows)} events, digest {header['digest'][:12]}…)"
    )


def svc_smoke(nodes, pods, out_dir: str, b: int = 4) -> Tuple[bool, List[str]]:
    """ISSUE 7 satellite: boot the queueing replay service (the `serve
    --jobs` machinery) on an ephemeral port, POST a b-job grid over real
    HTTP (weights + tune-factor variants plus one exact duplicate), poll
    to done, and hard-check the service contracts: the duplicate is
    answered from the digest cache (dedup_hits, bit-identical result),
    the fresh jobs ride ONE batch, and a second wave differing only in
    weights+tune adds NO compiled sweep executable — the PR 6
    jit._cache_size() zero-recompile check, now end-to-end through the
    POST path. Any exception on the serve/submit path is a FAIL verdict,
    not a traceback."""
    msgs: List[str] = []
    try:
        import shutil

        from tpusim.svc import TraceRef, start_job_server
        from tpusim.svc.client import _request, submit_and_wait
        from tpusim.svc.jobs import trace_digest

        # a fresh artifact dir per run: stale signed results would turn
        # the batching/dedup checks into no-ops (every job a disk hit)
        art = os.path.join(out_dir, "svc_smoke")
        if os.path.isdir(art):
            shutil.rmtree(art)
        os.makedirs(art)
        sub_nodes, sub_pods = nodes[:200], pods[:120]
        trace = TraceRef(
            "default", sub_nodes, sub_pods,
            trace_digest(sub_nodes, sub_pods),
        )
        srv, service, worker = start_job_server(
            art, {"default": trace}, listen=":0", lane_width=b,
            queue_size=4 * b,
        )
        try:
            fam = [["FGDScore", 1000]]
            docs = [
                {"policies": fam, "weights": [1000], "seed": 42},
                {"policies": fam, "weights": [500], "seed": 43,
                 "tune": 0.5},
                {"policies": fam, "weights": [250], "seed": 42},
                {"policies": fam, "weights": [1000], "seed": 42},  # dup
            ]
            results = submit_and_wait(srv.url, docs, timeout=600)
            _, _, q = _request(srv.url + "/queue")
            if (results[0]["placements_sha256"]
                    != results[3]["placements_sha256"]):
                return False, [
                    "[gate] svc: duplicate job's result diverged (FAIL)"
                ]
            if q.get("dedup_hits", 0) < 1:
                return False, [
                    f"[gate] svc: duplicate submission not dedup'd "
                    f"({q}) (FAIL)"
                ]
            execs = q.get("sweep_executables", -1)
            if execs != 1:
                return False, [
                    f"[gate] svc: expected ONE compiled sweep executable "
                    f"after the first wave, found {execs} (FAIL)"
                ]
            submit_and_wait(
                srv.url,
                [{"policies": fam, "weights": [123], "tune": 0.3,
                  "seed": 5}],
                timeout=600,
            )
            _, _, q2 = _request(srv.url + "/queue")
            if q2.get("sweep_executables") != execs:
                return False, [
                    f"[gate] svc: a weights+tune wave RECOMPILED "
                    f"({execs} -> {q2.get('sweep_executables')} "
                    f"executables) (FAIL)"
                ]
            msgs.append(
                f"[gate] svc: {len(results)} jobs + a weights+tune wave "
                f"via {q2['batches_run']} batches, dedup_hits="
                f"{q2['dedup_hits']}, sweep executables stable at "
                f"{execs} (zero recompiles)"
            )
        finally:
            worker.stop()
            srv.stop()
    except Exception as err:
        return False, [f"[gate] svc: FAIL ({type(err).__name__}: {err})"]
    return True, msgs


# hard admission->result p99 SLO for WARM forks on the gate's tiny
# trace (ISSUE 16): generous against poll jitter, far below a cold
# compile or a silent full replay — either blows straight through it
SERVE_P99_SLO_S = 2.5


def _p99(xs):
    s = sorted(xs)
    return s[min(len(s) - 1, max(0, int(0.99 * len(s) + 0.999999) - 1))]


def serve_latency_smoke(nodes, pods, out_dir: str, b: int = 4,
                        n_pods: int = 2000, k: int = 5
                        ) -> Tuple[bool, List[str]]:
    """ISSUE 16: the interactive what-if serving plane end-to-end over
    real HTTP. Runs a base job (checkpoint ladder + fork index entry),
    then a warmup fork/full pair (compiles the wave's three entries),
    then a timed wave of k warm forks + their k from-event-0 "full"
    twins through ONE POST — more jobs than lanes, so late arrivals
    JOIN the running wave at chunk boundaries. Hard checks:

      - every fork's result is field-identical to its full twin
        (placements sha256, counters, gpu_alloc, frag) — warm-state
        bit-identity through the POST path;
      - every warm fork executed <= tail + one chunk events, and every
        full twin replayed from event 0;
      - the wave executable count is UNCHANGED by the timed wave
        (zero recompiles across joins — jit._cache_size() live);
      - admission->result p99 of the warm forks meets the hard SLO
        AND beats the full-replay p99 by >= 3x (the latency win).
    """
    msgs: List[str] = []
    try:
        import shutil

        from tpusim.svc import TraceRef, start_job_server
        from tpusim.svc.client import (
            _request, fetch_results, submit_and_wait, submit_jobs,
            wait_jobs,
        )
        from tpusim.svc.jobs import trace_digest

        art = os.path.join(out_dir, "serve_latency_smoke")
        if os.path.isdir(art):
            shutil.rmtree(art)
        os.makedirs(art)
        sub_nodes, sub_pods = nodes[:200], pods[:n_pods]
        trace = TraceRef(
            "default", sub_nodes, sub_pods,
            trace_digest(sub_nodes, sub_pods),
        )
        srv, service, worker = start_job_server(
            art, {"default": trace}, listen=":0", lane_width=b,
            queue_size=8 * b,
        )
        try:
            fam = [["FGDScore", 1000]]
            (base_res,) = submit_and_wait(
                srv.url,
                [{"policies": fam, "weights": [1000], "seed": 42,
                  "base": True}],
                timeout=600, poll_s=0.05,
            )
            br = base_res.get("base_run") or {}
            E = int(br.get("events", 0))
            chunk = int(br.get("checkpoint_every", 0))
            if not (E and chunk):
                return False, [
                    f"[gate] serve-latency: base result carries no "
                    f"base_run meta ({sorted(base_res)}) (FAIL)"
                ]
            base_digest = base_res["job"]

            def fork_doc(event, tail, mode="fork"):
                doc = {"fork": {"base": base_digest, "event": int(event),
                                "tail": [[int(a), int(p)]
                                         for a, p in tail]}}
                if mode != "fork":
                    doc["fork"]["mode"] = mode
                return doc

            # warmup pair: compiles the wave's step/scatter/finish
            wtail = [[1, 0], [0, 0]]
            submit_and_wait(
                srv.url,
                [fork_doc(E // 2, wtail),
                 fork_doc(E // 2, wtail, "full")],
                timeout=600, poll_s=0.05,
            )
            _, _, q1 = _request(srv.url + "/queue")
            execs = (q1.get("waves") or {}).get("executables", -1)
            if execs < 0:
                return False, [
                    f"[gate] serve-latency: /queue carries no wave "
                    f"executable census ({sorted(q1)}) (FAIL)"
                ]

            # the timed wave: k warm forks near the end of the base
            # stream + their from-0 twins, one POST, tight poll (a
            # millisecond fork must not be measured through a
            # second-scale poll schedule). Forks FIRST, fulls after:
            # claim order is FIFO, so each class's p99 measures its own
            # replay cost — a fork queued BEHIND a 32-chunk full replay
            # would measure the lane wait, not the warm-state win
            docs, tails = [], []
            for j in range(k):
                tail = [[1, 2 * j], [1, 2 * j + 1], [0, 2 * j]]
                tails.append(tail)
                docs.append(fork_doc(E - 1 - (j % 3) * chunk, tail))
            for j in range(k):
                docs.append(
                    fork_doc(E - 1 - (j % 3) * chunk, tails[j], "full")
                )
            acc = submit_jobs(srv.url, docs, timeout=60)
            ids = [a["id"] for a in acc]
            final = wait_jobs(srv.url, ids, timeout=600, poll_s=0.02)
            results = fetch_results(srv.url, ids)

            fork_lat, full_lat = [], []
            for j in range(k):
                fr, vr = results[j], results[k + j]
                for f in ("placements_sha256", "counters",
                          "gpu_alloc_pct", "frag_gpu_milli", "placed",
                          "failed"):
                    if fr[f] != vr[f]:
                        return False, [
                            f"[gate] serve-latency: fork pair {j} "
                            f"diverged on {f}: {fr[f]!r} != {vr[f]!r} "
                            f"(FAIL)"
                        ]
                fm, vm = fr["fork"], vr["fork"]
                if fm["degrade"] or fm["source_cursor"] <= 0:
                    return False, [
                        f"[gate] serve-latency: fork {j} replayed COLD "
                        f"({fm}) — the warm-state path is broken (FAIL)"
                    ]
                if fm["events_executed"] > 3 + chunk:
                    return False, [
                        f"[gate] serve-latency: fork {j} executed "
                        f"{fm['events_executed']} events > tail(3) + "
                        f"chunk({chunk}) (FAIL)"
                    ]
                if vm["source_cursor"] != 0:
                    return False, [
                        f"[gate] serve-latency: full twin {j} did not "
                        f"replay from event 0 ({vm}) (FAIL)"
                    ]
                fork_lat.append(float(final[j]["latency_s"]))
                full_lat.append(float(final[k + j]["latency_s"]))

            _, _, q2 = _request(srv.url + "/queue")
            w = q2.get("waves") or {}
            if w.get("executables") != execs:
                return False, [
                    f"[gate] serve-latency: the timed wave RECOMPILED "
                    f"({execs} -> {w.get('executables')} wave "
                    f"executables) (FAIL)"
                ]
            if w.get("joins", 0) < 1:
                return False, [
                    f"[gate] serve-latency: {2 * k} jobs over {b} lanes "
                    f"produced no boundary join ({w}) — continuous "
                    f"batching is not engaging (FAIL)"
                ]
            if "fork" not in (q2.get("latency") or {}):
                return False, [
                    f"[gate] serve-latency: /queue latency plane "
                    f"missing fork percentiles ({q2.get('latency')}) "
                    f"(FAIL)"
                ]
            p99f, p99v = _p99(fork_lat), _p99(full_lat)
            if p99f > SERVE_P99_SLO_S:
                return False, [
                    f"[gate] serve-latency: warm-fork p99 {p99f:.3f}s "
                    f"breaks the {SERVE_P99_SLO_S}s SLO (FAIL)"
                ]
            if p99f * 3.0 > p99v:
                return False, [
                    f"[gate] serve-latency: warm-fork p99 {p99f:.3f}s "
                    f"is not >=3x faster than full-replay p99 "
                    f"{p99v:.3f}s (FAIL)"
                ]
            msgs.append(
                f"[gate] serve-latency: base {E} ev (chunk {chunk}), "
                f"{k} warm forks bit-identical to their from-0 twins; "
                f"p99 fork {p99f * 1000:.0f}ms vs full "
                f"{p99v * 1000:.0f}ms ({p99v / max(p99f, 1e-9):.1f}x, "
                f"SLO {SERVE_P99_SLO_S}s), {w['joins']} boundary "
                f"join(s), wave executables stable at {execs} "
                f"(zero recompiles)"
            )
        finally:
            worker.stop()
            srv.stop()
    except Exception as err:
        return False, [
            f"[gate] serve-latency: FAIL ({type(err).__name__}: {err})"
        ]
    return True, msgs


def chaos_smoke(nodes, pods, b: int = 8) -> Tuple[bool, List[str]]:
    """ISSUE 10 satellite: the chaos sweep end-to-end on a tiny trace
    prefix — B fault schedules (varying seed/MTBF/evict cadence) in ONE
    compiled vmapped scan, with three hard checks: exactly one compiled
    chaos executable after the first wave, a second wave with DIFFERENT
    schedules adds none (jit._cache_size() stable — fault schedules are
    operands, never jaxpr), and lane 0's placements + DisruptionMetrics
    reconcile exactly against the standalone single-lane
    run_with_faults path."""
    msgs: List[str] = []
    try:
        import numpy as np

        from tpusim.sim.driver import Simulator, SimulatorConfig
        from tpusim.sim.faults import FaultConfig

        sub_nodes, sub_pods = nodes[:200], pods[:120]

        def mk():
            sim = Simulator(sub_nodes, SimulatorConfig(
                policies=(("FGDScore", 1000),),
                gpu_sel_method="FGDScore", report_per_event=False,
                shuffle_pod=False, seed=42,
            ))
            sim.set_workload_pods(list(sub_pods))
            return sim

        def schedules(seed0):
            # explicit queue capacity: retry-slot blocks scale with it,
            # so pinning it (as a real service config would) keeps every
            # wave's merged stream in one power-of-two shape class
            return [
                FaultConfig(
                    mtbf_events=30 + 7 * i, mttr_events=40,
                    evict_every_events=25 - 3 * i, seed=seed0 + i,
                    backoff_base=4, backoff_cap=32, max_retries=3,
                    queue_capacity=16,
                )
                for i in range(b)
            ]
        w = np.asarray([[1000]] * b, np.int32)

        sim = mk()
        lanes = sim.run_sweep(w, seeds=[42] * b, faults=schedules(100))
        fn = sim._last_sweep_fn
        execs = fn._cache_size()
        if execs != 1:
            return False, [
                f"[gate] chaos: expected ONE compiled chaos executable, "
                f"found {execs} (FAIL)"
            ]
        # lane 0 vs the standalone single-lane fault path: placements
        # and every DisruptionMetrics number must reconcile
        solo = mk()
        res = solo.run_with_faults(fault_cfg=schedules(100)[0])
        if not np.array_equal(res.placed_node, lanes[0].placed_node):
            return False, [
                "[gate] chaos: lane 0 placements diverge from the "
                "standalone run_with_faults path (FAIL)"
            ]
        a, c = solo.last_disruption.as_dict(), lanes[0].disruption.as_dict()
        for k in a:
            same = (abs(a[k] - c[k]) < 1e-6 if isinstance(a[k], float)
                    else a[k] == c[k])
            if not same:
                return False, [
                    f"[gate] chaos: DisruptionMetrics[{k}] diverges "
                    f"(standalone {a[k]} vs lane {c[k]}) (FAIL)"
                ]
        # second wave, different schedules, same Simulator (the service
        # worker keeps per-family sims, so its sticky shape floors
        # apply): zero recompiles — the HARD operand contract
        sim.run_sweep(w, seeds=[42] * b, faults=schedules(900))
        if sim._last_sweep_fn is not fn or fn._cache_size() != execs:
            return False, [
                f"[gate] chaos: a new fault-schedule wave RECOMPILED "
                f"({execs} -> {fn._cache_size()} executables) (FAIL)"
            ]
        dm = lanes[0].disruption
        msgs.append(
            f"[gate] chaos: {b}-lane fault sweep x2 waves on one "
            f"executable (zero recompiles); lane0 reconciles standalone "
            f"(evicted={dm.evicted_pods} resched={dm.rescheduled_pods} "
            f"dead={dm.unscheduled_after_retries})"
        )
    except Exception as err:
        return False, [f"[gate] chaos: FAIL ({type(err).__name__}: {err})"]
    return True, msgs


def _write_fleet_trace(base: str, n_nodes: int = 16,
                       n_pods: int = 40) -> Tuple[str, str]:
    """Write a tiny synthetic node/pod CSV pair (the tune_smoke cluster
    shape) — the fleet smoke hosts a REAL file-backed trace because the
    register handshake hands CSV paths to worker processes."""
    import csv

    import numpy as np

    rng = np.random.default_rng(3)
    nodes_csv = os.path.join(base, "nodes.csv")
    pods_csv = os.path.join(base, "pods.csv")
    with open(nodes_csv, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["sn", "cpu_milli", "memory_mib", "gpu", "model"])
        for i, g in enumerate(rng.choice([0, 2, 4, 8], n_nodes)):
            w.writerow([f"n{i:03d}", 32000, 131072, int(g),
                        "V100M16" if g else ""])
    with open(pods_csv, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["name", "cpu_milli", "memory_mib", "num_gpu",
                    "gpu_milli"])
        for i in range(n_pods):
            gpu = int(rng.choice([0, 1, 2]))
            milli = 1000 if gpu > 1 else int(rng.choice([300, 500, 1000]))
            if gpu == 0:
                milli = 0
            w.writerow([f"p{i:04d}", int(rng.choice([1000, 2000, 4000])),
                        2048, gpu, milli])
    return nodes_csv, pods_csv


def _fleet_jobs() -> list:
    """The smoke's job mix: weight/seed/tune variants plus fault jobs
    with DIFFERENT tunes (the ISSUE 12 chaos x tune lift — they must
    share one compiled scan). engine pinned so both phases and every
    worker resolve the identical jaxpr."""
    # two policies: a meatier jaxpr widens the cold-compile vs
    # cache-hit gap the phase-3 joiner check measures
    fam = [["FGDScore", 1000], ["BestFitScore", 500]]
    fault = {"mtbf_events": 12.0, "mttr_events": 15.0, "seed": 7,
             "backoff_base": 2, "backoff_cap": 16, "max_retries": 2,
             "queue_capacity": 16}
    docs = [
        {"policies": fam, "weights": [1000 + 37 * i, 500 + 13 * i],
         "seed": 40 + i % 3, "tune": [0.0, 0.0, 0.3][i % 3],
         "engine": "sequential"}
        for i in range(8)
    ]
    docs += [
        {"policies": fam, "weights": [900, 450], "seed": 42, "tune": 0.0,
         "engine": "sequential", "fault": dict(fault, seed=11)},
        {"policies": fam, "weights": [1100, 550], "seed": 42,
         "tune": 0.4, "engine": "sequential",
         "fault": dict(fault, seed=13)},
    ]
    return docs


def fleet_chaos_smoke(out_dir: str, n_workers: int = 3
                      ) -> Tuple[bool, List[str]]:
    """ISSUE 12 (`make fleet-chaos-smoke`): the kill-tolerant fleet
    end-to-end. Phase 1 runs every job on a single in-process worker
    with FRESH caches — the byte-identity reference and the cold
    compile wall. Phase 2 boots a coordinator + N worker processes on
    the SAME caches, submits the same jobs over real HTTP, `kill -9`s
    the first worker observed holding leases mid-batch, and hard-checks
    the fleet contracts: (a) 100%% of accepted jobs reach signed
    results BYTE-identical to the single-worker run, (b) the dead
    worker's leases are stolen without operator action (/queue steals +
    lease_expired counters), and (c) a FRESH worker joined after the
    chaos wave serves its first batch well under the phase-1 cold
    compile wall (the shared persistent-compile/table caches). Any
    exception is a FAIL verdict, not a traceback."""
    msgs: List[str] = []
    procs = []
    srv = worker = None
    try:
        import shutil
        import signal as _signal
        import time as _time

        from tpusim.svc import load_trace, start_job_server
        from tpusim.svc.client import _request, submit_jobs, wait_jobs
        from tpusim.svc.fleet import spawn_local_workers, stop_workers

        base = os.path.join(out_dir, "fleet_smoke")
        if os.path.isdir(base):
            shutil.rmtree(base)
        os.makedirs(base)
        nodes_csv, pods_csv = _write_fleet_trace(base)
        ccache = os.path.join(base, "compile_cache")
        tcache = os.path.join(base, "table_cache")
        docs = _fleet_jobs()

        # ---- phase 1: the single-worker reference (cold caches)
        art1 = os.path.join(base, "ref")
        os.makedirs(art1)
        trace = load_trace("default", nodes_csv, pods_csv)
        srv, service, worker = start_job_server(
            art1, {"default": trace}, listen=":0", lane_width=2,
            queue_size=64, compile_cache_dir=ccache,
            table_cache_dir=tcache,
        )
        accepted = [service.submit_payload(d) for d in docs]
        digests = [a["digest"] for a in accepted]
        if not service.queue.wait_idle(timeout=300):
            return False, ["[gate] fleet: phase-1 reference run did "
                           "not drain (FAIL)"]
        cold_s = worker.first_dispatch_s
        ref_bytes = {}
        for d in digests:
            from tpusim.svc.jobs import result_path

            with open(result_path(art1, d), "rb") as f:
                ref_bytes[d] = f.read()
        worker.stop()
        srv.stop()
        worker = srv = None

        # ---- phase 2: the fleet, same caches, fresh artifact dir
        art2 = os.path.join(base, "fleet")
        os.makedirs(art2)
        srv, service, _ = start_job_server(
            art2, {"default": trace}, listen=":0", lane_width=2,
            queue_size=64, fleet=True, lease_s=2.0,
            compile_cache_dir=ccache, table_cache_dir=tcache,
        )
        # queue the jobs BEFORE the workers join: every worker's first
        # claim then lands mid-compile — the widest kill window
        accepted2 = submit_jobs(srv.url, docs)
        ids2 = [a["id"] for a in accepted2]
        procs = spawn_local_workers(
            srv.url, n_workers, table_cache_dir=tcache,
            compile_cache_dir=ccache,
        )
        killed = ""
        deadline = _time.time() + 240
        while _time.time() < deadline:
            _, _, q = _request(srv.url + "/queue")
            if not killed:
                for wid, row in (q.get("workers") or {}).items():
                    if row.get("leases_held", 0) > 0 and row.get("pid"):
                        os.kill(row["pid"], _signal.SIGKILL)
                        killed = wid
                        msgs.append(
                            f"[gate] fleet: kill -9'd {wid} (pid "
                            f"{row['pid']}) holding "
                            f"{row['leases_held']} lease(s) mid-batch"
                        )
                        break
            if q.get("done", 0) >= len(docs) and killed:
                break
            _time.sleep(0.05)
        if not killed:
            return False, ["[gate] fleet: never observed a worker "
                           "holding leases to kill (FAIL)"]
        final = wait_jobs(srv.url, ids2, timeout=240)
        bad = [d["id"] for d in final if d["status"] != "done"]
        if bad:
            return False, [
                f"[gate] fleet: {len(bad)} job(s) never completed "
                f"after the kill: {bad} (FAIL)"
            ]
        _, _, q = _request(srv.url + "/queue")
        if q.get("steals", 0) < 1 or q.get("lease_expired", 0) < 1:
            return False, [
                f"[gate] fleet: dead worker's leases were NOT stolen "
                f"(steals={q.get('steals')}, "
                f"lease_expired={q.get('lease_expired')}) (FAIL)"
            ]
        # byte-identity of every result file against the single-worker
        # reference — the whole idempotency argument, checked as bytes
        from tpusim.svc.jobs import result_path

        for d in digests:
            with open(result_path(art2, d), "rb") as f:
                got = f.read()
            if got != ref_bytes[d]:
                return False, [
                    f"[gate] fleet: result {d[:12]}… diverges from the "
                    "single-worker reference bytes (FAIL)"
                ]
        msgs.append(
            f"[gate] fleet: {len(docs)} jobs (incl. mixed fault/tune "
            f"lanes) on {n_workers} workers survived a mid-batch "
            f"kill -9 — steals={q['steals']}, "
            f"lease_expired={q['lease_expired']}, every result "
            "byte-identical to the single-worker reference"
        )

        # ---- phase 3: the fresh joiner skips the compile. Drain the
        # original fleet first so the joiner — not a warm survivor —
        # provably serves the next wave
        stop_workers(procs)
        procs = []
        joiner = spawn_local_workers(
            srv.url, 1, table_cache_dir=tcache, compile_cache_dir=ccache,
        )
        procs = joiner
        fresh = [
            dict(d, weights=[5000 + 11 * i, 2500 + 7 * i])
            for i, d in enumerate(_fleet_jobs()[:4])
        ]
        acc3 = submit_jobs(srv.url, fresh)
        wait_jobs(srv.url, [a["id"] for a in acc3], timeout=240)
        _, _, q = _request(srv.url + "/queue")
        rows = q.get("workers") or {}
        jrow = next(
            (r for r in rows.values() if r.get("pid") == joiner[0].pid),
            None,
        )
        if jrow is None or not jrow.get("first_dispatch_s"):
            return False, ["[gate] fleet: the fresh joiner never "
                           "served a batch (FAIL)"]
        js = jrow["first_dispatch_s"]
        # the relative margin alone flakes on loaded machines: this
        # trace's cold compile is only ~2 s, and the joiner's wall has
        # an irreducible claim+dispatch overhead floor (~1.3 s of
        # subprocess jax startup noise) that 0.65x can undercut. A
        # BROKEN compile cache still fails — the joiner would pay the
        # full cold wall, well above both bounds.
        if js >= max(0.65 * cold_s, 1.6):
            return False, [
                f"[gate] fleet: fresh joiner's first batch "
                f"({js:.2f}s) did not skip the cold compile "
                f"({cold_s:.2f}s) via the shared caches (FAIL)"
            ]
        msgs.append(
            f"[gate] fleet: fresh joiner's first batch {js:.2f}s vs "
            f"{cold_s:.2f}s cold — the shared compile/table caches "
            "carried the warm state"
        )
    except Exception as err:
        return False, [f"[gate] fleet: FAIL ({type(err).__name__}: {err})"]
    finally:
        try:
            if procs:
                from tpusim.svc.fleet import stop_workers

                stop_workers(procs)
            if worker is not None:
                worker.stop()
            if srv is not None:
                srv.stop()
        except Exception:
            pass
    return True, msgs


def _trace_smoke_jobs() -> list:
    """The flight-recorder smoke's job mix — its OWN policy family
    (PWRScore + DotProductScore). The other fleet smokes measure
    cold-compile walls on THEIR families (fleet: FGD+BestFit, wan:
    FGD+GpuPacking, HA: GpuClustering+BestFit), and sharing a
    bench-gate process must not pre-warm them."""
    fam = [["PWRScore", 800], ["DotProductScore", 300]]
    return [
        {"policies": fam, "weights": [800 + 29 * i, 300 + 17 * i],
         "seed": 60 + i % 3, "tune": [0.0, 0.2, 0.0][i % 3],
         "engine": "sequential"}
        for i in range(6)
    ]


def fleet_trace_smoke(out_dir: str, n_workers: int = 2
                      ) -> Tuple[bool, List[str]]:
    """ISSUE 19 (`make fleet-trace-smoke`): the fleet flight recorder
    end-to-end over real processes and real HTTP. Boots a coordinator +
    supervised worker pair, submits a job wave BEFORE the workers join
    (first claims land mid-compile — the widest kill window), `kill
    -9`s the first worker observed holding leases mid-batch, and
    hard-checks the observability contracts: (a) every job completes
    and its stitched cross-process timeline is gap-free — admission,
    claim, dispatch, upload and verify spans all sharing the ONE trace
    id minted at submit, zero orphan spans anywhere, and the killed
    worker's half-open attempt stitched as ABANDONED rather than lost;
    (b) the `tpusim trace` / `tpusim audit` verbs work against the
    artifact dir (exit 0, Chrome-trace export written, chain verified);
    (c) the hash-chained audit log records the steal AND the
    supervisor's respawn and verifies end-to-end; (d) the aggregated
    coordinator /metrics parses via parse_prometheus_text and carries a
    worker=-labeled series set for every live worker that served a
    batch. Any exception is a FAIL verdict, not a traceback."""
    msgs: List[str] = []
    srv = sup = None
    try:
        import json as _json
        import shutil
        import signal as _signal
        import subprocess
        import time as _time
        import urllib.request

        from tpusim.obs import audit as obs_audit
        from tpusim.obs import trace as obs_trace
        from tpusim.obs.emitters import parse_prometheus_text
        from tpusim.svc import load_trace, start_job_server
        from tpusim.svc.client import _request, submit_jobs, wait_jobs
        from tpusim.svc.fleet import worker_command
        from tpusim.svc.supervisor import Supervisor

        base = os.path.join(out_dir, "fleet_trace_smoke")
        if os.path.isdir(base):
            shutil.rmtree(base)
        os.makedirs(base)
        nodes_csv, pods_csv = _write_fleet_trace(base)
        ccache = os.path.join(base, "compile_cache")
        tcache = os.path.join(base, "table_cache")
        docs = _trace_smoke_jobs()

        art = os.path.join(base, "coord")
        os.makedirs(art)
        trace = load_trace("default", nodes_csv, pods_csv)
        srv, service, _ = start_job_server(
            art, {"default": trace}, listen=":0", lane_width=2,
            queue_size=64, fleet=True, lease_s=2.0,
            compile_cache_dir=ccache, table_cache_dir=tcache,
        )

        def spawn(n):
            return subprocess.Popen(worker_command(
                srv.url, table_cache_dir=tcache,
                compile_cache_dir=ccache,
            ))

        # NO on_exit=release_dead here: instant reclaim would requeue
        # the dead worker's jobs before the lease expires, and this
        # smoke exists to witness the STEAL path in the audit chain
        # (the wan smoke covers the release_dead fast path)
        sup = Supervisor(spawn, n_workers, breaker_k=6,
                         breaker_window_s=30.0)
        # the respawn lands in the SAME hash chain as the steal it
        # repairs — the audit log tells the whole story of the kill
        sup.audit = service.audit
        service.fleet.supervisor = sup

        accepted = submit_jobs(srv.url, docs)
        ids = [a["id"] for a in accepted]
        digests = [a["digest"] for a in accepted]
        sup.start()

        killed_wid, killed_pid = "", 0
        deadline = _time.time() + 240
        while _time.time() < deadline:
            sup.poll()
            _, _, q = _request(srv.url + "/queue")
            if not killed_wid:
                for wid, row in (q.get("workers") or {}).items():
                    if row.get("leases_held", 0) > 0 and row.get("pid"):
                        os.kill(row["pid"], _signal.SIGKILL)
                        killed_wid, killed_pid = wid, row["pid"]
                        msgs.append(
                            f"[gate] trace: kill -9'd {wid} (pid "
                            f"{killed_pid}) holding "
                            f"{row['leases_held']} lease(s) mid-batch"
                        )
                        break
            if q.get("done", 0) >= len(docs) and killed_wid:
                break
            _time.sleep(0.05)
        if not killed_wid:
            return False, ["[gate] trace: never observed a worker "
                           "holding leases to kill (FAIL)"]
        final = None
        deadline = _time.time() + 240
        while _time.time() < deadline:
            sup.poll()  # keep reaping/respawning while jobs finish
            try:
                final = wait_jobs(srv.url, ids, timeout=2.0)
                break
            except Exception:
                continue
        if final is None:
            return False, ["[gate] trace: jobs did not finish after "
                           "the kill (FAIL)"]
        bad = [d["id"] for d in final if d["status"] != "done"]
        if bad:
            return False, [
                f"[gate] trace: {len(bad)} job(s) never completed "
                f"after the kill: {bad} (FAIL)"
            ]
        sup.poll()  # reap the killed child: its pid must read as DEAD
        # (not zombie) for stitch() to classify its corpse as abandoned

        # ---- (a) the stitched cross-process timelines
        spans, problems = obs_trace.stitch(art)
        if problems:
            return False, [
                f"[gate] trace: span files damaged: {problems} (FAIL)"
            ]
        orphans = [s for s in spans if s["status"] == "orphan"]
        if orphans:
            return False, [
                f"[gate] trace: {len(orphans)} orphan span(s) — "
                "end-without-begin should be impossible (FAIL)"
            ]
        abandoned = [s for s in spans if s["status"] == "abandoned"]
        if not abandoned:
            return False, [
                "[gate] trace: the killed worker left NO abandoned "
                "span — the stolen attempt vanished from the "
                "timeline (FAIL)"
            ]
        want = {obs_trace.SPAN_ADMIT, obs_trace.SPAN_QUEUE_WAIT,
                obs_trace.SPAN_CLAIM, obs_trace.SPAN_DISPATCH,
                obs_trace.SPAN_UPLOAD, obs_trace.SPAN_VERIFY}
        for d in digests:
            mine = [s for s in spans if s["job"] == d]
            names = {s["name"] for s in mine if s["status"] == "ok"}
            missing = want - names
            if missing:
                return False, [
                    f"[gate] trace: job {d[:12]}… timeline has gaps — "
                    f"missing {sorted(missing)} (FAIL)"
                ]
            tids = {s["trace"] for s in mine} - {""}
            if len(tids) != 1:
                return False, [
                    f"[gate] trace: job {d[:12]}… spans carry "
                    f"{len(tids)} trace ids (want exactly the one "
                    "minted at submit) (FAIL)"
                ]
        n_procs = len({s["proc"] for s in spans})
        msgs.append(
            f"[gate] trace: {len(spans)} spans across {n_procs} "
            f"processes — every job's timeline complete, "
            f"{len(abandoned)} abandoned attempt(s) from the kill, "
            "zero orphans"
        )

        # ---- (b) the CLI verbs against the same artifact dir
        stolen = next((d for d in final if d.get("stolen")), None)
        probe = (stolen or final[0])["digest"]
        chrome_out = os.path.join(base, "trace.json")
        r = subprocess.run(
            [sys.executable, "-m", "tpusim", "trace", probe,
             "-d", art, "--out", chrome_out],
            capture_output=True, text=True, timeout=120,
        )
        if r.returncode != 0 or not os.path.isfile(chrome_out):
            return False, [
                f"[gate] trace: `tpusim trace` failed (rc={r.returncode}"
                f", stderr={r.stderr.strip()[-200:]}) (FAIL)"
            ]
        with open(chrome_out) as f:
            if not _json.load(f).get("traceEvents"):
                return False, ["[gate] trace: Chrome-trace export is "
                               "empty (FAIL)"]
        r = subprocess.run(
            [sys.executable, "-m", "tpusim", "audit", "-d", art,
             "--verify"],
            capture_output=True, text=True, timeout=120,
        )
        if r.returncode != 0:
            return False, [
                f"[gate] trace: `tpusim audit --verify` failed "
                f"(rc={r.returncode}, stderr="
                f"{r.stderr.strip()[-200:]}) (FAIL)"
            ]
        msgs.append(
            f"[gate] trace: `tpusim trace {probe[:12]}…` stitched the "
            f"{'stolen ' if stolen else ''}job and `tpusim audit "
            "--verify` passed over the live chain"
        )

        # ---- (c) the audit chain records the whole incident
        n_audit = obs_audit.verify(art)
        kinds = {r["kind"] for r in obs_audit.tail(art, n=0)}
        for needed in ("steal", "respawn"):
            if needed not in kinds:
                return False, [
                    f"[gate] trace: audit chain ({n_audit} records, "
                    f"kinds={sorted(kinds)}) never recorded the "
                    f"{needed!r} (FAIL)"
                ]
        msgs.append(
            f"[gate] trace: audit chain intact — {n_audit} records "
            f"covering {sorted(kinds)}"
        )

        # ---- (d) the aggregated /metrics
        with urllib.request.urlopen(srv.url + "/metrics",
                                    timeout=30) as resp:
            metrics_text = resp.read().decode()
        series = parse_prometheus_text(metrics_text)
        if ("tpusim_fleet_workers_live", ()) not in series:
            return False, ["[gate] trace: merged /metrics lacks the "
                           "fleet gauges (FAIL)"]
        by_worker = {
            dict(labels).get("worker")
            for (_, labels) in series
            if dict(labels).get("worker")
        }
        _, _, q = _request(srv.url + "/queue")
        served = [
            wid for wid, row in (q.get("workers") or {}).items()
            if row.get("batches", 0) > 0 and row.get("pid") != killed_pid
        ]
        missing_w = [w for w in served if w not in by_worker]
        if not by_worker or missing_w:
            return False, [
                f"[gate] trace: merged /metrics missing worker series "
                f"for {missing_w or 'every worker'} "
                f"(have {sorted(by_worker)}) (FAIL)"
            ]
        msgs.append(
            f"[gate] trace: /metrics aggregates {len(by_worker)} live "
            f"worker(s) under worker= labels "
            f"({len(series)} series parse clean)"
        )
    except Exception as err:
        return False, [f"[gate] trace: FAIL ({type(err).__name__}: "
                       f"{err})"]
    finally:
        try:
            if sup is not None:
                sup.stop()
            if srv is not None:
                srv.stop()
        except Exception:
            pass
    return True, msgs


def _ha_jobs() -> list:
    """The HA smoke's job mix: weight/seed/tune variants plus one fault
    job (capability-routed — every spawned worker declares fault-lane
    support). The policy family deliberately differs from _fleet_jobs()
    and _wan_jobs(): those smokes measure cold-compile walls on THEIR
    families, and sharing a process (bench-gate) must not pre-warm
    them."""
    fam = [["GpuClusteringScore", 900], ["BestFitScore", 450]]
    docs = [
        {"policies": fam, "weights": [900 + 31 * i, 450 + 11 * i],
         "seed": 50 + i % 2, "tune": [0.0, 0.0, 0.25][i % 3],
         "engine": "sequential"}
        for i in range(6)
    ]
    docs.append(
        {"policies": fam, "weights": [1000, 500], "seed": 52, "tune": 0.0,
         "engine": "sequential",
         "fault": {"mtbf_events": 12.0, "mttr_events": 15.0, "seed": 9,
                   "backoff_base": 2, "backoff_cap": 16, "max_retries": 2,
                   "queue_capacity": 16}}
    )
    return docs


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def fleet_ha_smoke(out_dir: str) -> Tuple[bool, List[str]]:
    """ISSUE 17 (`make fleet-ha-smoke`): coordinator failover end to
    end, over real processes and real HTTP. Phase 1 runs the job mix on
    a single in-process coordinator — the byte-identity reference.
    Phase 2 boots a token-armed leader + standby CLI pair sharing one
    artifact dir, joins two workers against BOTH urls, submits the same
    jobs through the failover client, `kill -9`s the LEADER while
    leases are held mid-batch, and hard-checks the HA contracts:
    (a) the standby promotes (role/epoch on /healthz) and 100%% of jobs
    complete with per-file byte-identity vs the reference, (b) a
    stale-epoch op and missing/forged tokens are rejected (409 / 401 on
    every mutating endpoint), (c) the resurrected old leader fences
    itself to standby against the live lease, and (d) token material
    never appears in /queue. Any exception is a FAIL verdict."""
    msgs: List[str] = []
    procs: list = []
    coords: list = []
    srv = worker = None
    try:
        import shutil
        import signal as _signal
        import subprocess
        import threading
        import time as _time

        from tpusim.svc import load_trace, start_job_server
        from tpusim.svc.auth import bearer_headers
        from tpusim.svc.client import _request, submit_and_wait
        from tpusim.svc.fleet import stop_workers
        from tpusim.svc.jobs import result_path

        base = os.path.join(out_dir, "fleet_ha_smoke")
        if os.path.isdir(base):
            shutil.rmtree(base)
        os.makedirs(base)
        nodes_csv, pods_csv = _write_fleet_trace(base)
        ccache = os.path.join(base, "compile_cache")
        tcache = os.path.join(base, "table_cache")
        docs = _ha_jobs()

        # ---- phase 1: the single-coordinator reference
        art1 = os.path.join(base, "ref")
        os.makedirs(art1)
        trace = load_trace("default", nodes_csv, pods_csv)
        srv, service, worker = start_job_server(
            art1, {"default": trace}, listen=":0", lane_width=2,
            queue_size=64, compile_cache_dir=ccache,
            table_cache_dir=tcache,
        )
        accepted = [service.submit_payload(d) for d in docs]
        digests = [a["digest"] for a in accepted]
        if not service.queue.wait_idle(timeout=300):
            return False, ["[gate] fleet-ha: phase-1 reference run did "
                           "not drain (FAIL)"]
        ref_bytes = {}
        for d in digests:
            with open(result_path(art1, d), "rb") as f:
                ref_bytes[d] = f.read()
        worker.stop()
        srv.stop()
        worker = srv = None

        # ---- phase 2: leader + standby CLI pair, token-armed
        token = "ha-smoke-" + os.urandom(12).hex()
        token_file = os.path.join(base, "token.txt")
        with open(token_file, "w") as f:
            f.write(token + "\n")
        art2 = os.path.join(base, "fleet")
        os.makedirs(art2)
        p1, p2 = _free_port(), _free_port()
        u1, u2 = f"http://127.0.0.1:{p1}", f"http://127.0.0.1:{p2}"
        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            TPUSIM_COORD_LEASE_S="1.5", TPUSIM_COORD_SKEW_S="0.5",
        )

        def _coord_cmd(port: int, standby: bool = False) -> list:
            cmd = [
                sys.executable, "-m", "tpusim", "serve", art2, "--jobs",
                "--nodes", nodes_csv, "--pods", pods_csv, "--fleet",
                "--listen", f"127.0.0.1:{port}", "--poll", "0.3",
                "--lane-width", "2", "--lease-s", "2.0",
                "--token-file", token_file,
                "--table-cache-dir", tcache,
                "--compile-cache-dir", ccache,
            ]
            if standby:
                cmd.append("--standby")
            return cmd

        def _spawn_coord(port: int, tag: str, standby: bool = False):
            log = open(os.path.join(base, f"coord_{tag}.log"), "ab")
            proc = subprocess.Popen(
                _coord_cmd(port, standby), env=env,
                stdout=log, stderr=log,
            )
            coords.append(proc)
            return proc

        def _wait_role(url: str, want: str, timeout_s: float) -> dict:
            end = _time.time() + timeout_s
            last = "?"
            while _time.time() < end:
                try:
                    _, _, h = _request(url + "/healthz", timeout=5)
                    last = h.get("role", "?")
                    if last == want:
                        return h
                except OSError:
                    pass
                _time.sleep(0.1)
            raise RuntimeError(
                f"{url} never reached role {want!r} (last: {last!r})"
            )

        leader = _spawn_coord(p1, "leader")
        _wait_role(u1, "leader", 60)
        _spawn_coord(p2, "standby", standby=True)
        _wait_role(u2, "standby", 60)

        wcmd = [
            sys.executable, "-m", "tpusim", "worker",
            "--join", f"{u1},{u2}", "--token-file", token_file,
            "--table-cache-dir", tcache, "--compile-cache-dir", ccache,
        ]
        for i in range(2):
            log = open(os.path.join(base, f"worker_{i}.log"), "ab")
            procs.append(
                subprocess.Popen(wcmd, env=env, stdout=log, stderr=log)
            )

        # submit through the failover client against BOTH urls; it must
        # ride out the leader's death mid-wait
        box: dict = {}

        def _submit():
            try:
                box["results"] = submit_and_wait(
                    f"{u1},{u2}", docs, timeout=300, token=token
                )
            except Exception as err:  # surfaced below as a FAIL
                box["err"] = err

        th = threading.Thread(target=_submit, daemon=True)
        th.start()

        # kill -9 the LEADER once a worker provably holds leases
        deadline = _time.time() + 120
        held = False
        while _time.time() < deadline and not held:
            try:
                _, _, q = _request(u1 + "/queue", timeout=5)
            except OSError:
                break  # leader already gone?
            for row in (q.get("workers") or {}).values():
                if row.get("leases_held", 0) > 0:
                    held = True
                    break
            _time.sleep(0.05)
        if not held:
            return False, ["[gate] fleet-ha: never observed a worker "
                           "holding leases before the kill (FAIL)"]
        os.kill(leader.pid, _signal.SIGKILL)
        msgs.append(
            f"[gate] fleet-ha: kill -9'd the LEADER (pid {leader.pid}) "
            "with leases held mid-batch"
        )

        h = _wait_role(u2, "leader", 30)
        epoch = int(h.get("epoch", 0))
        if epoch < 2:
            return False, [
                f"[gate] fleet-ha: standby promoted WITHOUT bumping the "
                f"epoch (epoch={epoch}) (FAIL)"
            ]
        msgs.append(
            f"[gate] fleet-ha: standby took over as leader at epoch "
            f"{epoch}"
        )

        # fencing probe: an op stamped with the dead leader's epoch
        auth = bearer_headers(token)
        code, _, doc = _request(
            u2 + "/workers/claim",
            json.dumps({"worker": "ghost", "epoch": 1}).encode(),
            headers=auth,
        )
        if code != 409 or not doc.get("stale_epoch"):
            return False, [
                f"[gate] fleet-ha: stale-epoch claim answered {code} "
                f"{doc} instead of 409 stale_epoch (FAIL)"
            ]
        # auth probes: every mutating endpoint, tokenless AND forged
        mutating = [
            ("/jobs", b"{}"), ("/workers/register", b"{}"),
            ("/workers/claim", b"{}"), ("/workers/renew", b"{}"),
            ("/workers/complete", b"{}"), ("/leases", b"{}"),
            ("/results/deadbeef", b"x"),
        ]
        for path, body in mutating:
            for hdrs in (None, {"Authorization": "Bearer forged"}):
                code, _, _doc = _request(u2 + path, body, headers=hdrs)
                if code != 401:
                    return False, [
                        f"[gate] fleet-ha: POST {path} with "
                        f"{'no' if hdrs is None else 'a forged'} token "
                        f"answered {code}, want 401 (FAIL)"
                    ]
        msgs.append(
            "[gate] fleet-ha: stale-epoch op fenced (409) and all "
            f"{len(mutating)} mutating endpoints reject missing/forged "
            "tokens (401)"
        )

        th.join(300)
        if "err" in box:
            return False, [
                f"[gate] fleet-ha: submit flow failed across the "
                f"failover ({type(box['err']).__name__}: {box['err']}) "
                "(FAIL)"
            ]
        results = box.get("results") or []
        if len(results) != len(docs):
            return False, [
                f"[gate] fleet-ha: {len(results)}/{len(docs)} jobs "
                "completed after the failover (FAIL)"
            ]

        # the resurrected old leader must fence itself to standby
        res = _spawn_coord(p1, "resurrected")
        _wait_role(u1, "standby", 30)
        msgs.append(
            f"[gate] fleet-ha: resurrected old leader (pid {res.pid}) "
            "fenced itself to standby against the live epoch-"
            f"{epoch} lease"
        )

        # byte-identity vs the single-coordinator reference
        for d in digests:
            with open(result_path(art2, d), "rb") as f:
                got = f.read()
            if got != ref_bytes[d]:
                return False, [
                    f"[gate] fleet-ha: result {d[:12]}… diverges from "
                    "the single-coordinator reference bytes (FAIL)"
                ]
        # token redaction: /queue must describe auth without material
        _, _, q = _request(u2 + "/queue", timeout=5)
        blob = json.dumps(q)
        if token in blob:
            return False, ["[gate] fleet-ha: token material LEAKED "
                           "into /queue (FAIL)"]
        if not str(q.get("auth", "")).startswith("enabled"):
            return False, [
                f"[gate] fleet-ha: /queue auth field says "
                f"{q.get('auth')!r}, want 'enabled (...)' (FAIL)"
            ]
        msgs.append(
            f"[gate] fleet-ha: {len(docs)} jobs (incl. a fault lane) "
            "survived a leader kill -9 — every result byte-identical "
            "to the single-coordinator reference; auth described, "
            "never leaked"
        )
    except Exception as err:
        return False, [
            f"[gate] fleet-ha: FAIL ({type(err).__name__}: {err})"
        ]
    finally:
        try:
            if procs:
                from tpusim.svc.fleet import stop_workers

                stop_workers(procs)
            for c in coords:
                if c.poll() is None:
                    try:
                        c.kill()
                    except OSError:
                        pass
            if worker is not None:
                worker.stop()
            if srv is not None:
                srv.stop()
        except Exception:
            pass
    return True, msgs


def slo_smoke(out_dir: str) -> Tuple[bool, List[str]]:
    """ISSUE 20 (`make slo-smoke`): the SLO plane end to end, over real
    HTTP. Three phases:

    (a) alert lifecycle — a coordinator armed with a tight --slo-file
        fork-p99 burn rule serves a base run, then a COLD fork wave (the
        deliberately induced latency regression: every completion eats
        the compile wall) fires the burn-rate page. While firing:
        /healthz degrades with the alert named, `tpusim top --once`
        shows the PAGE, /metrics carries the native latency summary,
        /query serves the event series, /events pages by cursor, and
        the kind=alert record sits in a VERIFYING audit chain. Then
        warm forks (recovery) displace the burn windows and the alert
        RESOLVES — with traffic still flowing, not by going silent.
    (b) breaker trip — a fleet-mode coordinator with the DEFAULT rules
        and a supervisor forced into a crash loop: the circuit breaker
        opens and the built-in breaker-open page fires off the sampled
        gauge, recorded in the chain.
    (c) takeover continuity — a leader + standby CLI pair sharing one
        artifact dir; jobs run, the leader is kill -9'd, the standby
        promotes at a bumped epoch and ADOPTS the signed tsdb snapshot:
        /query on the new leader must serve pre-kill history with no
        gap at the splice (newest adopted point within snapshot cadence
        of the kill) plus fresh post-promotion points.
    """
    msgs: List[str] = []
    procs: list = []
    coords: list = []
    srv = worker = srv_b = sup = None
    saved_env = {k: os.environ.get(k)
                 for k in ("TPUSIM_TSDB_STEP_S", "TPUSIM_TSDB_SNAPSHOT_S")}
    try:
        import shutil
        import signal as _signal
        import subprocess
        import time as _time
        import urllib.request

        from tpusim.obs import audit as obs_audit
        from tpusim.svc import load_trace, start_job_server
        from tpusim.svc.client import _request, submit_and_wait
        from tpusim.svc.supervisor import Supervisor

        # tight sampling so the smoke's windows have real resolution
        os.environ["TPUSIM_TSDB_STEP_S"] = "0.25"
        os.environ["TPUSIM_TSDB_SNAPSHOT_S"] = "0.5"

        base = os.path.join(out_dir, "slo_smoke")
        if os.path.isdir(base):
            shutil.rmtree(base)
        os.makedirs(base)
        nodes_csv, pods_csv = _write_fleet_trace(base)
        ccache = os.path.join(base, "compile_cache")
        tcache = os.path.join(base, "table_cache")
        trace = load_trace("default", nodes_csv, pods_csv)
        fam = [["FGDScore", 700]]

        # the smoke's SLO file: the fork-p99 rule reshaped to smoke
        # scale. objective 1.0s sits far above a warm fork (~ms) and
        # far below a cold compile (seconds); the 30s fast window keeps
        # the page up long enough to probe every surface, and budget
        # 0.25 x burn 2 = a 0.5 breach fraction, so the alert resolves
        # once warm completions OUTNUMBER the cold ones — recovery
        # under live traffic, not silence
        slo_file = os.path.join(base, "slo.json")
        with open(slo_file, "w") as f:
            json.dump({"defaults": False, "rules": [{
                "name": "fork-p99-burn", "type": "burn_rate",
                "severity": "page",
                "metric": "tpusim_queue_latency_event_seconds",
                "label": {"kind": "fork"},
                "objective": 1.0, "op": ">", "budget": 0.25,
                "windows": [{"window_s": 30.0, "burn": 2.0},
                            {"window_s": 60.0, "burn": 1.0}],
                "clear_for_s": 1.0,
            }]}, f)

        # ---- phase (a): fire -> probe every surface -> resolve
        art1 = os.path.join(base, "local")
        os.makedirs(art1)
        srv, service, worker = start_job_server(
            art1, {"default": trace}, listen=":0", lane_width=2,
            queue_size=64, compile_cache_dir=ccache,
            table_cache_dir=tcache, slo_file=slo_file,
        )
        (base_res,) = submit_and_wait(
            srv.url,
            [{"policies": fam, "weights": [700], "seed": 61,
              "base": True}],
            timeout=600, poll_s=0.05,
        )
        br = base_res.get("base_run") or {}
        E = int(br.get("events", 0))
        if not E:
            return False, [f"[gate] slo: base result carries no "
                           f"base_run meta ({sorted(base_res)}) (FAIL)"]
        bd = base_res["job"]

        def fork_doc(tail):
            return {"fork": {"base": bd, "event": E - 1,
                             "tail": [[int(a), int(p)]
                                      for a, p in tail]}}

        # the induced regression: the FIRST fork wave compiles the
        # fork-path executables cold — every completion in it pays the
        # compile wall, well past the 1s objective
        t0 = _time.time()
        submit_and_wait(
            srv.url, [fork_doc([[1, 0], [0, 0]]),
                      fork_doc([[1, 1], [0, 1]])],
            timeout=600, poll_s=0.05,
        )
        cold_s = _time.time() - t0
        if cold_s <= 1.0:
            return False, [
                f"[gate] slo: the cold fork wave finished in "
                f"{cold_s:.2f}s — too fast to breach the 1s objective, "
                "the regression never happened (FAIL)"
            ]

        deadline = _time.time() + 30
        fire = None
        while _time.time() < deadline and fire is None:
            _, _, a = _request(srv.url + "/alerts", timeout=5)
            for fd in a.get("firing") or []:
                if fd.get("alert") == "fork-p99-burn":
                    fire = fd
            if fire is None:
                _time.sleep(0.1)
        if fire is None:
            return False, [
                f"[gate] slo: cold fork wave ({cold_s:.1f}s "
                "completions) never fired fork-p99-burn (FAIL)"
            ]
        msgs.append(
            f"[gate] slo: induced fork regression ({cold_s:.1f}s cold "
            f"wave vs 1s objective) fired fork-p99-burn "
            f"(burn fraction {fire.get('value')})"
        )

        # while firing: /healthz flips, top shows the PAGE, /metrics
        # carries the native summary, /query serves the series
        code, _, h = _request(srv.url + "/healthz", timeout=5)
        if code != 503 or "fork-p99-burn" not in (
                h.get("alerts_page") or []):
            return False, [
                f"[gate] slo: /healthz did not degrade on the page "
                f"burn (HTTP {code}, body={h}) (FAIL)"
            ]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        top = subprocess.run(
            [sys.executable, "-m", "tpusim", "top", srv.url, "--once",
             "--width", "100"],
            capture_output=True, text=True, timeout=120, env=env,
        )
        if (top.returncode != 0 or "fork-p99-burn" not in top.stdout
                or "PAGE" not in top.stdout):
            return False, [
                f"[gate] slo: `tpusim top --once` does not show the "
                f"firing page (rc={top.returncode}):\n{top.stdout}"
                f"{top.stderr} (FAIL)"
            ]
        with urllib.request.urlopen(srv.url + "/metrics",
                                    timeout=5) as resp:
            mtext = resp.read().decode()
        if ("# TYPE tpusim_queue_latency_seconds summary" not in mtext
                or 'tpusim_queue_latency_seconds{kind="fork",'
                   'quantile="0.99"}' not in mtext):
            return False, [
                "[gate] slo: /metrics lacks the native per-kind "
                "latency summary series (FAIL)"
            ]
        _, _, qd = _request(
            srv.url + "/query?name=tpusim_queue_latency_event_seconds"
            "&label=kind%3Dfork&since=-120", timeout=5,
        )
        ev_pts = [p for s in qd.get("series") or []
                  for p in s["points"]]
        if not ev_pts:
            return False, ["[gate] slo: /query serves no fork event-"
                           "latency history (FAIL)"]

        # /events cursor pagination (live): page 1 record, then resume
        # from the cursor — no overlap, no skips
        _, _, ev1 = _request(srv.url + "/events?limit=1", timeout=5)
        cur = int(ev1.get("next_after", 0))
        if len(ev1.get("events") or []) != 1 or cur < 1:
            return False, [f"[gate] slo: /events?limit=1 answered "
                           f"{ev1} (FAIL)"]
        _, _, ev2 = _request(
            srv.url + f"/events?after={cur}&limit=500", timeout=5)
        seqs = [e.get("seq", 0) for e in ev2.get("events") or []]
        if any(s <= cur for s in seqs):
            return False, [
                f"[gate] slo: cursor page re-served seqs <= {cur}: "
                f"{seqs} (FAIL)"
            ]

        # recovery: warm forks (compile cached now, ~ms each) displace
        # the burn windows until the fraction drops and the page clears
        deadline = _time.time() + 90
        resolved = False
        j = 0
        while _time.time() < deadline and not resolved:
            submit_and_wait(
                srv.url,
                [fork_doc([[1, j % 40], [0, (j * 7 + 1) % 40]])],
                timeout=600, poll_s=0.05,
            )
            j += 1
            _, _, a = _request(srv.url + "/alerts", timeout=5)
            resolved = not (a.get("firing") or [])
            if not resolved:
                _time.sleep(0.3)
        if not resolved:
            return False, [
                f"[gate] slo: fork-p99-burn never resolved after "
                f"{j} warm recovery forks (FAIL)"
            ]
        code, _, h = _request(srv.url + "/healthz", timeout=5)
        if code != 200:
            return False, [f"[gate] slo: /healthz still {code} after "
                           "the alert resolved (FAIL)"]

        # the firing AND the resolution are records in a chain that
        # still verifies
        n_chain = obs_audit.verify(art1)
        alert_recs = obs_audit.tail(art1, n=0, kind="alert")
        states = [(r.get("alert"), r.get("state")) for r in alert_recs]
        if (("fork-p99-burn", "firing") not in states
                or ("fork-p99-burn", "resolved") not in states):
            return False, [
                f"[gate] slo: audit chain lacks the firing/resolved "
                f"alert records (got {states}) (FAIL)"
            ]
        msgs.append(
            f"[gate] slo: page visible on /healthz(503) + `tpusim top` "
            f"+ /metrics summary + /query; resolved after {j} warm "
            f"fork(s) under live traffic; firing+resolved records in a "
            f"verifying {n_chain}-record audit chain"
        )
        worker.stop()
        srv.stop()
        worker = srv = None

        # ---- phase (b): forced crash loop -> breaker-open page
        art_b = os.path.join(base, "breaker")
        os.makedirs(art_b)
        srv_b, service_b, _ = start_job_server(
            art_b, {"default": trace}, listen=":0", lane_width=2,
            queue_size=16, fleet=True, lease_s=2.0,
        )
        sup = Supervisor(
            lambda n: subprocess.Popen(
                [sys.executable, "-c", "raise SystemExit(3)"]),
            1, breaker_k=3, breaker_window_s=20.0,
            on_exit=service_b.fleet.release_dead,
        )
        sup.healthy_after_s = 3600.0  # every exit counts as a crash
        service_b.fleet.supervisor = sup
        sup.start()
        deadline = _time.time() + 60
        while _time.time() < deadline and not sup.breaker.open:
            sup.poll()
            _time.sleep(0.05)
        if not sup.breaker.open:
            return False, ["[gate] slo: forced crash loop never "
                           "tripped the breaker (FAIL)"]
        deadline = _time.time() + 20
        fired_b = False
        while _time.time() < deadline and not fired_b:
            _, _, a = _request(srv_b.url + "/alerts", timeout=5)
            fired_b = any(fd.get("alert") == "breaker-open"
                          for fd in a.get("firing") or [])
            if not fired_b:
                _time.sleep(0.1)
        if not fired_b:
            return False, [
                "[gate] slo: the open breaker never fired the default "
                "breaker-open page off the sampled gauge (FAIL)"
            ]
        obs_audit.verify(art_b)
        brecs = obs_audit.tail(art_b, n=0, kind="alert")
        if not any(r.get("alert") == "breaker-open"
                   and r.get("state") == "firing" for r in brecs):
            return False, ["[gate] slo: breaker-open firing record "
                           "missing from the audit chain (FAIL)"]
        msgs.append(
            "[gate] slo: crash-loop breaker trip fired the built-in "
            "breaker-open page, chained in audit"
        )
        sup.stop()
        sup = None
        srv_b.stop()
        srv_b = None

        # ---- phase (c): history survives an epoch-fenced takeover
        token = "slo-smoke-" + os.urandom(8).hex()
        token_file = os.path.join(base, "token.txt")
        with open(token_file, "w") as f:
            f.write(token + "\n")
        art2 = os.path.join(base, "fleet")
        os.makedirs(art2)
        p1, p2 = _free_port(), _free_port()
        u1, u2 = f"http://127.0.0.1:{p1}", f"http://127.0.0.1:{p2}"
        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            TPUSIM_COORD_LEASE_S="1.5", TPUSIM_COORD_SKEW_S="0.5",
            TPUSIM_TSDB_STEP_S="0.25", TPUSIM_TSDB_SNAPSHOT_S="0.5",
        )

        def _coord_cmd(port: int, standby: bool = False) -> list:
            cmd = [
                sys.executable, "-m", "tpusim", "serve", art2, "--jobs",
                "--nodes", nodes_csv, "--pods", pods_csv, "--fleet",
                "--listen", f"127.0.0.1:{port}", "--poll", "0.3",
                "--lane-width", "2", "--lease-s", "2.0",
                "--token-file", token_file,
                "--table-cache-dir", tcache,
                "--compile-cache-dir", ccache,
            ]
            if standby:
                cmd.append("--standby")
            return cmd

        def _spawn_coord(port: int, tag: str, standby: bool = False):
            log = open(os.path.join(base, f"coord_{tag}.log"), "ab")
            proc = subprocess.Popen(
                _coord_cmd(port, standby), env=env,
                stdout=log, stderr=log,
            )
            coords.append(proc)
            return proc

        def _wait_role(url: str, want: str, timeout_s: float) -> dict:
            end = _time.time() + timeout_s
            last = "?"
            while _time.time() < end:
                try:
                    _, _, hh = _request(url + "/healthz", timeout=5)
                    last = hh.get("role", "?")
                    if last == want:
                        return hh
                except OSError:
                    pass
                _time.sleep(0.1)
            raise RuntimeError(
                f"{url} never reached role {want!r} (last: {last!r})"
            )

        leader = _spawn_coord(p1, "leader")
        _wait_role(u1, "leader", 60)
        _spawn_coord(p2, "standby", standby=True)
        _wait_role(u2, "standby", 60)
        wcmd = [
            sys.executable, "-m", "tpusim", "worker",
            "--join", f"{u1},{u2}", "--token-file", token_file,
            "--table-cache-dir", tcache, "--compile-cache-dir", ccache,
        ]
        wlog = open(os.path.join(base, "worker_0.log"), "ab")
        procs.append(
            subprocess.Popen(wcmd, env=env, stdout=wlog, stderr=wlog))

        docs = [{"policies": fam, "weights": [700 + 13 * i], "seed": 61,
                 "engine": "sequential"} for i in range(4)]
        results = submit_and_wait(f"{u1},{u2}", docs, timeout=300,
                                  token=token)
        if len(results) != len(docs):
            return False, [f"[gate] slo: {len(results)}/{len(docs)} "
                           "jobs completed on the HA pair (FAIL)"]
        _time.sleep(1.5)  # >= two snapshot cadences: history on disk

        _, _, pre = _request(
            u1 + "/query?name=tpusim_queue_done_total&since=-120",
            timeout=5)
        if not any(s["points"] for s in pre.get("series") or []):
            return False, ["[gate] slo: leader served no done_total "
                           "history before the kill (FAIL)"]
        t_kill = _time.time()
        os.kill(leader.pid, _signal.SIGKILL)
        h = _wait_role(u2, "leader", 30)
        epoch = int(h.get("epoch", 0))
        if epoch < 2:
            return False, [f"[gate] slo: standby promoted without "
                           f"bumping the epoch ({epoch}) (FAIL)"]
        _time.sleep(2.0)  # let the adopted history gain fresh points

        _, _, post = _request(
            u2 + "/query?name=tpusim_queue_done_total&since=-180",
            timeout=5)
        pts = sorted((t, v) for s in post.get("series") or []
                     for t, v in s["points"])
        pre_side = [t for t, _ in pts if t <= t_kill]
        post_side = [t for t, _ in pts if t > t_kill]
        if not pre_side or not post_side:
            return False, [
                f"[gate] slo: promoted standby's /query did not splice "
                f"history ({len(pre_side)} pre-kill / {len(post_side)} "
                "post-promotion points) (FAIL)"
            ]
        gap = t_kill - max(pre_side)
        if gap > 3.0:
            return False, [
                f"[gate] slo: {gap:.1f}s of history lost at the splice "
                "(snapshot cadence is 0.5s) (FAIL)"
            ]
        ts = [t for t, _ in pts]
        if ts != sorted(ts) or len(set(ts)) != len(ts):
            return False, ["[gate] slo: spliced series timestamps are "
                           "not strictly increasing (FAIL)"]
        _, _, a2 = _request(u2 + "/alerts", timeout=5)
        if not a2.get("rules"):
            return False, ["[gate] slo: promoted standby serves no "
                           "alert rules (FAIL)"]
        n2 = obs_audit.verify(art2)
        msgs.append(
            f"[gate] slo: kill -9 takeover at epoch {epoch} adopted "
            f"{len(pre_side)} pre-kill points with {gap:.2f}s gap at "
            f"the splice (cadence 0.5s) + {len(post_side)} fresh "
            f"points; alert engine live on the new leader; shared "
            f"audit chain verifies ({n2} records)"
        )
    except Exception as err:
        return False, [f"[gate] slo: FAIL ({type(err).__name__}: {err})"]
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        try:
            if procs:
                from tpusim.svc.fleet import stop_workers

                stop_workers(procs)
            for c in coords:
                if c.poll() is None:
                    try:
                        c.kill()
                    except OSError:
                        pass
            if sup is not None:
                sup.stop()
            if worker is not None:
                worker.stop()
            if srv is not None:
                srv.stop()
            if srv_b is not None:
                srv_b.stop()
        except Exception:
            pass
    return True, msgs


class FlakyShim:
    """The WAN fault injector of `make fleet-wan-smoke` (ISSUE 13): a
    MonitorServer extension app inserted BEFORE the real fleet app that
    drops (503 + Retry-After: 0) or delays a seeded ~20% of
    transfer-plane and fleet-protocol requests — the workers' shared
    backoff schedule must absorb all of it."""

    PATHS = ("/traces/", "/results/", "/leases", "/workers/")

    def __init__(self, rate: float = 0.2, seed: int = 20817,
                 delay_s: float = 0.05):
        import random

        self.rng = random.Random(seed)
        self.rate = float(rate)
        self.delay_s = float(delay_s)
        self.seen = self.dropped = self.delayed = 0

    def handle(self, method, path, body, headers=None):
        import time as _time

        if not any(path.startswith(p) for p in self.PATHS):
            return None
        self.seen += 1
        r = self.rng.random()
        if r < self.rate:
            self.dropped += 1
            return (503, "application/json",
                    b'{"error": "injected WAN fault (FlakyShim)"}\n',
                    {"Retry-After": "0"})
        if r < 2 * self.rate:
            self.delayed += 1
            _time.sleep(self.delay_s)
        return None  # fall through to the real app


def _wan_jobs() -> list:
    """The WAN smoke's job mix: weight/seed/tune variants on the
    'default' trace plus two jobs on a SECOND hosted trace (the
    ISSUE 13 multi-trace hosting check — batching stays per-(trace,
    family)). The policy family deliberately differs from
    _fleet_jobs(): fleet_chaos_smoke measures a COLD compile wall on
    ITS family, and when both smokes share one process (bench-gate,
    resume-smoke) this smoke must not pre-warm that jaxpr."""
    fam = [["FGDScore", 1000], ["GpuPackingScore", 400]]
    docs = [
        {"policies": fam, "weights": [1000 + 41 * i, 500 + 17 * i],
         "seed": 40 + i % 2, "tune": [0.0, 0.0, 0.3][i % 3],
         "engine": "sequential"}
        for i in range(6)
    ]
    docs += [
        {"trace": "alt", "policies": fam, "weights": [900 + 50 * i, 450],
         "seed": 42, "engine": "sequential"}
        for i in range(2)
    ]
    return docs


def fleet_wan_smoke(out_dir: str, n_workers: int = 2
                    ) -> Tuple[bool, List[str]]:
    """ISSUE 13 (`make fleet-wan-smoke`): the wide-area fleet
    end-to-end, with NO shared filesystem between coordinator and
    workers. Phase 1 runs every job on a single in-process worker — the
    byte-identity reference. Phase 2 boots a coordinator hosting TWO
    traces behind a FlakyShim (drops/delays ~20% of transfer requests)
    and a Supervisor spawning N REMOTE-mode workers with fully isolated
    per-worker dirs (own trace cache, artifact scratch, compile/table
    caches), `kill -9`s a remote worker observed holding leases
    mid-batch, and hard-checks: (a) 100%% of jobs reach signed results
    BYTE-identical to the reference, (b) the supervisor respawned the
    killed child (respawn counter >= 1 in /queue), (c) workers report
    mode=remote with live transfer counters and the shim really
    injected faults, (d) a torn upload probe is rejected with nothing
    written. Phase 3 forces a crash loop (spawn_fn that exits
    immediately) and checks the circuit breaker opens — /healthz
    degrades loudly and /queue says why — instead of spinning."""
    import shutil
    import signal as _signal
    import subprocess
    import time as _time

    msgs: List[str] = []
    srv = worker = sup = None
    try:
        from tpusim.svc import load_trace, start_job_server
        from tpusim.svc.client import _request, submit_jobs, wait_jobs
        from tpusim.svc.fleet import _post_bytes, worker_command
        from tpusim.svc.jobs import result_path
        from tpusim.svc.supervisor import Supervisor

        base = os.path.join(out_dir, "fleet_wan")
        if os.path.isdir(base):
            shutil.rmtree(base)
        os.makedirs(base)
        t_dir = os.path.join(base, "traces_default")
        a_dir = os.path.join(base, "traces_alt")
        os.makedirs(t_dir)
        os.makedirs(a_dir)
        nodes_csv, pods_csv = _write_fleet_trace(t_dir)
        alt_nodes, alt_pods = _write_fleet_trace(a_dir, n_nodes=12,
                                                 n_pods=24)
        docs = _wan_jobs()

        # ---- phase 1: single-worker reference
        art1 = os.path.join(base, "ref")
        os.makedirs(art1)
        trace = load_trace("default", nodes_csv, pods_csv)
        alt = load_trace("alt", alt_nodes, alt_pods)
        srv, service, worker = start_job_server(
            art1, {"default": trace, "alt": alt}, listen=":0",
            lane_width=2, queue_size=64,
        )
        accepted = [service.submit_payload(d) for d in docs]
        digests = [a["digest"] for a in accepted]
        if not service.queue.wait_idle(timeout=300):
            return False, ["[gate] wan: phase-1 reference run did not "
                           "drain (FAIL)"]
        ref_bytes = {}
        for d in digests:
            with open(result_path(art1, d), "rb") as f:
                ref_bytes[d] = f.read()
        worker.stop()
        srv.stop()
        worker = srv = None

        # ---- phase 2: remote fleet behind the flaky shim
        art2 = os.path.join(base, "coord")
        os.makedirs(art2)
        srv, service, _ = start_job_server(
            art2, {"default": trace, "alt": alt}, listen=":0",
            lane_width=2, queue_size=64, fleet=True, lease_s=2.0,
        )
        shim = FlakyShim()
        srv._apps.insert(0, shim)

        def spawn_remote(n):
            wdir = os.path.join(base, f"wk{n}")
            return subprocess.Popen(worker_command(
                srv.url, mode="remote", cache_dir=wdir,
                table_cache_dir=os.path.join(wdir, "tables"),
                compile_cache_dir=os.path.join(wdir, "compile"),
            ))

        sup = Supervisor(
            spawn_remote, n_workers,
            breaker_k=4, breaker_window_s=20.0,
            on_exit=service.fleet.release_dead,
        )
        service.fleet.supervisor = sup

        accepted2 = submit_jobs(srv.url, docs)
        ids2 = [a["id"] for a in accepted2]
        sup.start()
        killed = ""
        deadline = _time.time() + 240
        while _time.time() < deadline:
            sup.poll()
            _, _, q = _request(srv.url + "/queue")
            if not killed:
                for wid, row in (q.get("workers") or {}).items():
                    if (row.get("leases_held", 0) > 0 and row.get("pid")
                            and row.get("mode") == "remote"):
                        os.kill(row["pid"], _signal.SIGKILL)
                        killed = wid
                        msgs.append(
                            f"[gate] wan: kill -9'd remote worker "
                            f"{wid} (pid {row['pid']}) holding "
                            f"{row['leases_held']} lease(s) mid-batch"
                        )
                        break
            if q.get("done", 0) >= len(docs) and killed:
                break
            _time.sleep(0.05)
        if not killed:
            return False, ["[gate] wan: never observed a remote worker "
                           "holding leases to kill (FAIL)"]
        deadline = _time.time() + 240
        final = None
        while _time.time() < deadline:
            sup.poll()  # keep supervising while the jobs finish
            try:
                final = wait_jobs(srv.url, ids2, timeout=2.0)
                break
            except Exception:
                continue
        if final is None:
            return False, ["[gate] wan: jobs did not finish after the "
                           "kill (FAIL)"]
        bad = [d["id"] for d in final if d["status"] != "done"]
        if bad:
            return False, [
                f"[gate] wan: {len(bad)} job(s) never completed after "
                f"the kill: {bad} (FAIL)"
            ]
        # 100% completion: every result byte-identical to the
        # single-worker reference, ACROSS the lossy transfer plane
        for d in digests:
            with open(result_path(art2, d), "rb") as f:
                if f.read() != ref_bytes[d]:
                    return False, [
                        f"[gate] wan: result {d[:12]}… diverges from "
                        "the single-worker reference bytes (FAIL)"
                    ]
        _, _, q = _request(srv.url + "/queue")
        supq = q.get("supervisor") or {}
        if supq.get("respawns", 0) < 1:
            return False, [
                f"[gate] wan: the killed worker was NOT respawned "
                f"(supervisor={supq}) (FAIL)"
            ]
        if q.get("steals", 0) < 1:
            return False, [
                f"[gate] wan: the dead worker's jobs were not "
                f"reclaimed (steals={q.get('steals')}) (FAIL)"
            ]
        rows = q.get("workers") or {}
        remote_rows = [r for r in rows.values()
                       if r.get("mode") == "remote"]
        if not remote_rows or not any(
            (r.get("transfers") or {}).get("uploads", 0) > 0
            for r in remote_rows
        ):
            return False, [
                "[gate] wan: no remote-mode worker reported upload "
                f"transfer counters (rows={rows}) (FAIL)"
            ]
        tr = q.get("transfer") or {}
        if shim.dropped < 1:
            return False, ["[gate] wan: the flaky shim never dropped a "
                           "request — the chaos was a no-op (FAIL)"]
        if tr.get("uploads_ok", 0) < len(digests):
            return False, [
                f"[gate] wan: only {tr.get('uploads_ok')} of "
                f"{len(digests)} results arrived via upload (FAIL)"
            ]
        # torn upload probe: truncated bytes must be rejected with the
        # landed file untouched
        probe = digests[0]
        code, _, _ = _post_bytes(
            srv.url, f"/results/{probe}", ref_bytes[probe][:-25],
            max_attempts=20,
        )
        with open(result_path(art2, probe), "rb") as f:
            intact = f.read() == ref_bytes[probe]
        if code != 400 or not intact:
            return False, [
                f"[gate] wan: torn upload probe not rejected cleanly "
                f"(HTTP {code}, intact={intact}) (FAIL)"
            ]
        msgs.append(
            f"[gate] wan: {len(docs)} jobs over 2 hosted traces on "
            f"{n_workers} REMOTE workers (no shared fs) survived "
            f"{shim.dropped} dropped + {shim.delayed} delayed "
            f"transfers and a mid-batch kill -9 — respawns="
            f"{supq.get('respawns')}, steals={q['steals']}, "
            f"uploads_ok={tr['uploads_ok']}, every result "
            "byte-identical to the single-worker reference"
        )

        # ---- phase 3: forced crash loop -> the breaker, not a spin
        sup.stop()
        sup.spawn_fn = lambda n: subprocess.Popen(
            [sys.executable, "-c", "raise SystemExit(3)"]
        )
        sup.healthy_after_s = 3600.0  # every exit counts as a crash
        sup.start()
        deadline = _time.time() + 60
        while _time.time() < deadline:
            sup.poll()
            if sup.breaker.open:
                break
            _time.sleep(0.05)
        if not sup.breaker.open:
            return False, ["[gate] wan: forced crash loop never "
                           "tripped the circuit breaker (FAIL)"]
        _, _, q = _request(srv.url + "/queue")
        br = (q.get("supervisor") or {}).get("breaker") or {}
        if br.get("state") != "open" or "crash loop" not in str(
            br.get("reason")
        ):
            return False, [
                f"[gate] wan: /queue does not say WHY respawning "
                f"stopped (breaker={br}) (FAIL)"
            ]
        code, _, h = _request(srv.url + "/healthz")
        if code != 503 or h.get("supervisor_breaker") != "open":
            return False, [
                f"[gate] wan: /healthz did not degrade on the open "
                f"breaker (HTTP {code}, body={h}) (FAIL)"
            ]
        msgs.append(
            f"[gate] wan: forced crash loop tripped the breaker after "
            f"{sup.counters['respawns']} respawns — /healthz 503, "
            "/queue names the reason, no spinning"
        )
    except Exception as err:
        return False, [f"[gate] wan: FAIL ({type(err).__name__}: {err})"]
    finally:
        try:
            if sup is not None:
                sup.stop()
            if worker is not None:
                worker.stop()
            if srv is not None:
                srv.stop()
        except Exception:
            pass
    return True, msgs


def latest_multichip(repo: str = REPO) -> Optional[dict]:
    """Newest committed MULTICHIP_r*.json carrying a `scale` block (the
    ISSUE 11 scale-lane capture written by `bench_multichip.py
    --scale-lane --json-out`), parsed into the block plus {path, n}.
    Older rounds' dryrun captures (n_devices/tail schema) are skipped."""
    best = None
    for path in glob.glob(os.path.join(repo, "MULTICHIP_r*.json")):
        m = re.search(r"MULTICHIP_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
            if data.get("rc") != 0 or not isinstance(
                data.get("scale"), dict
            ):
                continue
            n = int(data.get("n") or m.group(1))
        except (OSError, json.JSONDecodeError, TypeError, ValueError):
            continue
        if best is None or n > best["n"]:
            best = {"path": path, "n": n, **data["scale"]}
    return best


def multichip_advisory(base: Optional[dict]) -> Tuple[bool, List[str]]:
    """ISSUE 11 satellite: advisory comparison of the newest committed
    scale-lane capture, like the BENCH_r*.json baselines — never gates
    on walls (cross-machine), but prints the pipelined-vs-unpipelined
    speedups and the aggregate row so a missing/torn capture or a
    pipelined row that stopped beating the unpipelined body is visible
    in every `make bench-gate` run. FAILs only on a capture whose rows
    report placement divergence (equal=false) — that is a correctness
    bit, not a wall."""
    if base is None:
        return True, [
            "[gate] multichip: no committed scale-lane capture "
            "(bench_multichip.py --scale-lane --json-out "
            "MULTICHIP_rNN.json)"
        ]
    msgs = []
    ok = True
    for r in base.get("rows", []):
        if not r.get("equal", True):
            ok = False
            msgs.append(
                f"[gate] multichip: row nloc={r.get('nloc')} recorded "
                "pipelined/unpipelined placement DIVERGENCE (FAIL)"
            )
            continue
        msgs.append(
            f"[gate] multichip baseline "
            f"{os.path.basename(base['path'])} (round {base['n']}): "
            f"nloc={r.get('nloc')} "
            f"{r.get('us_per_event_pipelined')} us/ev pipelined vs "
            f"{r.get('us_per_event_unpipelined')} unpipelined "
            f"(x{r.get('speedup')}) — advisory"
        )
    agg = base.get("aggregate")
    if agg:
        line = (
            f"[gate] multichip aggregate: {agg.get('nodes')} nodes on "
            f"{agg.get('devices')} devices, "
            f"{agg.get('us_per_event')} us/ev (donated chunked stream)"
        )
        if agg.get("fault"):
            line += (
                f"; chaos {agg['fault'].get('us_per_event')} us/ev over "
                f"{agg['fault'].get('merged_events')} merged events"
            )
        msgs.append(line)
    return ok, msgs


def mesh_chaos_smoke(n_dev: int = 2) -> Tuple[bool, List[str]]:
    """ISSUE 11 satellite (`make mesh-chaos-smoke`): the pipelined shard
    engine end-to-end on a small forced-virtual mesh — (a) a FAULTED
    mesh replay must reproduce the single-device fault lane's placements
    and DisruptionMetrics (the pending registers carry fault kinds too),
    with the frag-delta degrade loud (warning + obs counter, not silent
    zeros); (b) a chunked replay with DONATION armed must hold ONE
    compiled executable across equal-size chunks
    (run_chunk_donated._cache_size), actually consume its input carries
    (donated buffers deleted), keep the live-buffer census stable across
    chunks (nothing re-materialized), and finish bit-identical to the
    one-shot replay. Skips (PASS) when fewer than `n_dev` devices are
    visible — `make mesh-chaos-smoke` forces a virtual CPU mesh."""
    msgs: List[str] = []
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        if len(jax.devices()) < n_dev:
            return True, [
                f"[gate] mesh-chaos: skipped — {len(jax.devices())} "
                f"device(s) visible, needs {n_dev} (run `make "
                "mesh-chaos-smoke` for the forced-virtual-mesh form)"
            ]
        from tpusim.io.trace import NodeRow, PodRow
        from tpusim.sim.driver import Simulator, SimulatorConfig
        from tpusim.sim.faults import FaultConfig

        rng = np.random.default_rng(7)
        nodes = [
            NodeRow(f"n{i:02d}", 32000, 131072, int(g),
                    "V100M16" if g else "")
            for i, g in enumerate(rng.choice([0, 2, 4, 8], 10))
        ]
        pods = [
            PodRow(f"p{i:03d}", int(rng.choice([1000, 2000])), 2048,
                   int(rng.choice([0, 1])), 500)
            for i in range(36)
        ]
        fcfg = FaultConfig(
            mtbf_events=9, mttr_events=8, evict_every_events=7, seed=5,
            backoff_base=2, backoff_cap=8, max_retries=2,
            queue_capacity=8,
        )

        def mk(mesh):
            sim = Simulator(nodes, SimulatorConfig(
                policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
                report_per_event=False, seed=42, mesh=mesh,
            ))
            sim.set_workload_pods(list(pods))
            return sim

        # (a) faulted mesh replay reconciles the single-device lane
        solo = mk(0)
        ra = solo.run_with_faults(fault_cfg=fcfg)
        mesh_sim = mk(n_dev)
        rb = mesh_sim.run_with_faults(fault_cfg=fcfg)
        if not mesh_sim._last_engine.startswith("shard_map"):
            return False, [
                f"[gate] mesh-chaos: fault replay ran on "
                f"{mesh_sim._last_engine!r}, not the shard engine (FAIL)"
            ]
        if not np.array_equal(ra.placed_node, rb.placed_node):
            return False, [
                "[gate] mesh-chaos: faulted mesh placements diverge "
                "from the single-device fault lane (FAIL)"
            ]
        a = solo.last_disruption.as_dict()
        b = mesh_sim.last_disruption.as_dict()
        for k in a:
            if k.startswith("post_recovery"):
                continue
            if a[k] != b[k]:
                return False, [
                    f"[gate] mesh-chaos: DisruptionMetrics[{k}] "
                    f"diverges ({a[k]} vs {b[k]}) (FAIL)"
                ]
        # the degrade must be LOUD when recovers were scheduled
        had_recover = solo.last_disruption.node_recoveries > 0
        degraded_loudly = any(
            "[Degrade] mesh fault replay" in l for l in mesh_sim.log.lines
        ) and mesh_sim.obs.counts.get("degrade_mesh_frag", 0) > 0
        if had_recover and not degraded_loudly:
            return False, [
                "[gate] mesh-chaos: frag-delta capture dropped "
                "SILENTLY (no [Degrade] line / obs counter) (FAIL)"
            ]

        # (b) donated chunked replay: one executable, buffers consumed,
        # census stable, bit-identical finish
        from tpusim.io.trace import pods_to_specs
        from tpusim.parallel import make_mesh, pad_nodes, shard_state
        from tpusim.parallel.shard_engine import (
            make_shardmap_table_replay,
        )
        from tpusim.policies import make_policy
        from tpusim.sim.table_engine import build_pod_types

        sim = mk(0)
        sim.set_typical_pods()
        specs = pods_to_specs(pods, sim.node_index)
        e = len(pods)
        ev_kind = jnp.zeros(e, jnp.int32)
        ev_pod = jnp.arange(e, dtype=jnp.int32)
        types = build_pod_types(specs)
        key = jax.random.PRNGKey(3)
        mesh = make_mesh(n_dev)
        state, rank = pad_nodes(sim.init_state, sim.rank, n_dev)
        state = shard_state(state, mesh)
        policies = [(make_policy("FGDScore"), 1000)]
        replay = make_shardmap_table_replay(
            policies, mesh, gpu_sel="FGDScore"
        )
        ref = replay(state, specs, types, ev_kind, ev_pod, sim.typical,
                     key, rank)
        chunk = e // 4
        carry = replay.init_carry(state, specs, types, sim.typical, key,
                                  rank)
        census = []
        steady = None
        for i in range(4):
            prev_leaves = jax.tree.leaves(carry)
            carry, _ys = replay.run_chunk_donated(
                carry, specs, types,
                ev_kind[i * chunk:(i + 1) * chunk],
                ev_pod[i * chunk:(i + 1) * chunk], sim.typical, rank,
            )
            jax.block_until_ready(jax.tree.leaves(carry))
            if i > 0 and not all(
                getattr(l, "is_deleted", lambda: True)()
                for l in prev_leaves
            ):
                return False, [
                    "[gate] mesh-chaos: donated input carry still "
                    "alive after the chunk dispatch — donation not "
                    "armed (FAIL)"
                ]
            census.append(len(jax.live_arrays()))
            if i == 1:
                # chunk 0 consumes the init-shaped carry (its own
                # executable); chunk 1 compiles the steady-state entry
                # every later chunk MUST reuse
                steady = replay.run_chunk_donated._cache_size()
        execs = replay.run_chunk_donated._cache_size()
        if execs != steady or execs > 2:
            return False, [
                f"[gate] mesh-chaos: donated chunk executables grew "
                f"past steady state ({steady} -> {execs}) — equal-size "
                "chunks recompiled (FAIL)"
            ]
        if len(set(census[1:])) != 1:
            return False, [
                f"[gate] mesh-chaos: live-buffer census drifted across "
                f"chunks {census} — donated buffers re-materialized "
                "(FAIL)"
            ]
        st, placed, masks, failed = replay.finish(carry)
        if not (
            np.array_equal(np.asarray(placed), np.asarray(ref.placed_node))
            and np.array_equal(np.asarray(masks), np.asarray(ref.dev_mask))
        ):
            return False, [
                "[gate] mesh-chaos: donated chunked replay diverges "
                "from the one-shot replay (FAIL)"
            ]
        dm = mesh_sim.last_disruption
        msgs.append(
            f"[gate] mesh-chaos: faulted {n_dev}-device replay "
            f"reconciles single-device (evicted={dm.evicted_pods} "
            f"resched={dm.rescheduled_pods}); donated chunked replay "
            f"held {execs} executable(s) at steady state, census stable "
            f"at {census[-1]} buffers, finish bit-identical"
        )
    except Exception as err:
        return False, [
            f"[gate] mesh-chaos: FAIL ({type(err).__name__}: {err})"
        ]
    return True, msgs


def tune_smoke(out_dir: str, generations: int = 3) -> Tuple[bool, List[str]]:
    """ISSUE 9 satellite (`make tune-smoke`): run the learned-scoring
    loop on a tiny synthetic trace for a few generations on the LOCAL
    backend and hard-check the lane's contracts — (a) zero recompiles
    after generation 1 (every generation's population rides ONE compiled
    sweep executable; jit._cache_size() via the backend's tracked
    wrapper), (b) the digest-signed tuning log reads back (signature
    verifies, one record per generation, optimizer state present), and
    (c) a resume of the finished log under the same flags is a no-op
    that reproduces the file byte-identically. Any exception is a FAIL
    verdict, not a traceback."""
    msgs: List[str] = []
    try:
        import numpy as np

        from tpusim.io.trace import NodeRow, PodRow
        from tpusim.learn import (
            LocalRollout,
            TuneConfig,
            make_family_sim,
            read_log,
            run_tune,
        )

        rng = np.random.default_rng(11)
        nodes = [
            NodeRow(f"n{i:03d}", 32000, 131072, int(g),
                    "V100M16" if g else "")
            for i, g in enumerate(rng.choice([0, 2, 4, 8], 16))
        ]
        pods = []
        for i in range(48):
            gpu = int(rng.choice([0, 1, 2]))
            milli = 1000 if gpu > 1 else int(rng.choice([300, 500, 1000]))
            if gpu == 0:
                milli = 0
            pods.append(PodRow(
                f"p{i:04d}", int(rng.choice([1000, 2000, 4000])), 2048,
                gpu, milli,
            ))
        policies = [("FGDScore", 1000), ("BestFitScore", 500)]
        cfg = TuneConfig(algo="es", generations=generations, popsize=4,
                         sigma=300.0, lr=400.0, seed=3)
        log_path = os.path.join(out_dir, "tune_smoke_log.jsonl")
        if os.path.isfile(log_path):
            os.unlink(log_path)

        sim = make_family_sim(nodes, pods, policies)
        backend = LocalRollout(sim, width=cfg.popsize)
        result = run_tune(backend, policies, cfg, log_path)

        execs = backend.executables()
        if execs != 1:
            return False, [
                f"[gate] tune: expected ONE compiled sweep executable "
                f"across {generations} generations, found {execs} (FAIL)"
            ]
        header, records = read_log(log_path)  # signature verifies here
        if len(records) != generations or any(
            "state" not in r for r in records
        ):
            return False, [
                f"[gate] tune: log carries {len(records)} records for "
                f"{generations} generations (FAIL)"
            ]
        with open(log_path, "rb") as f:
            before = f.read()
        resumed = run_tune(backend, policies, cfg, log_path, resume=True)
        with open(log_path, "rb") as f:
            after = f.read()
        if before != after:
            return False, [
                "[gate] tune: resume of a finished log rewrote it "
                "differently (FAIL)"
            ]
        if resumed.best_weights != result.best_weights:
            return False, [
                "[gate] tune: resume diverged from the original best "
                "(FAIL)"
            ]
        msgs.append(
            f"[gate] tune: {generations} generations x {cfg.popsize} "
            f"candidates on one compiled sweep (zero recompiles), log "
            f"signed + resume byte-identical — best "
            f"{','.join(str(w) for w in result.best_weights)} at "
            f"{result.best_objective:+.4f}"
        )
    except Exception as err:
        return False, [f"[gate] tune: FAIL ({type(err).__name__}: {err})"]
    return True, msgs


def pallas_hbm_smoke(out_dir: str) -> Tuple[bool, List[str]]:
    """ISSUE 15 (`make pallas-hbm-smoke`): the HBM-residency fused
    Pallas engine above the old VMEM ceiling — (a) a synthetic
    N = 8192 / K = 151 trace replayed by a forced pallas engine in
    interpreter mode must NOT degrade: the two-tier residency select
    routes the HBM kernel ("pallas (hbm)") and the placements/devices
    reconcile the blocked table engine BIT-exactly; (b) the residency
    auto-select is pinned at both tiers (old-ceiling shapes -> vmem,
    above-ceiling -> hbm, genuinely impossible -> degrade None) and the
    documented HBM ceiling clears 256k nodes at K = 151; (c) the run
    record carries the residency and the kernel's exact in-kernel DMA
    counters, with every started DMA waited. Any exception is a FAIL
    verdict, not a traceback."""
    msgs: List[str] = []
    try:
        import numpy as np

        from tpusim.io.trace import NodeRow, PodRow
        from tpusim.sim import pallas_engine
        from tpusim.sim.driver import Simulator, SimulatorConfig
        from tpusim.sim.typical import TypicalPodsConfig

        # (b) the two-tier footprint math, pinned first (no compiles)
        sel = pallas_engine.select_residency
        if sel(512, 151, 1, 2048, 4096) != "vmem":
            return False, ["[pallas-hbm] FAIL: old-ceiling shape did not "
                           "auto-select the VMEM tier"]
        if sel(8192, 151, 1, 2048, 4096) != "hbm":
            return False, ["[pallas-hbm] FAIL: above-ceiling shape did "
                           "not auto-select the HBM tier"]
        if sel(10**6, 151, 1, 2048, 4096) is not None:
            return False, ["[pallas-hbm] FAIL: an impossible shape did "
                           "not degrade"]
        ceiling = pallas_engine.hbm_ceiling_nodes(151, 1, 1)
        if ceiling < 256 * 1024:
            return False, [f"[pallas-hbm] FAIL: HBM ceiling {ceiling} < "
                           "256k at K = 151"]
        msgs.append(f"[pallas-hbm] residency select pinned at both tiers; "
                    f"HBM ceiling {ceiling} nodes at K=151")

        # (a) N = 8192, K = 151, above the old ceiling, interpreter mode
        rng = np.random.default_rng(7)
        nodes = [
            NodeRow(
                f"n{i:05d}", int(rng.choice([32000, 64000, 96000])),
                131072, int(g),
                ["2080", "T4", "V100M16"][i % 3] if g else "",
            )
            for i, g in enumerate(rng.choice([0, 2, 4, 8], 8192))
        ]
        kinds = rng.integers(0, 3, 151)
        pods = [
            PodRow(
                f"p{i:04d}", 1000 + 100 * i, 2048,
                (0 if kinds[i] == 0 else 1 if kinds[i] == 1
                 else int(rng.choice([1, 2]))),
                (0 if kinds[i] == 0
                 else int(rng.choice([250, 500])) if kinds[i] == 1
                 else 1000),
            )
            for i in range(151)
        ]

        def run(engine):
            sim = Simulator(nodes, SimulatorConfig(
                policies=(("FGDScore", 1000),),
                gpu_sel_method="FGDScore", seed=42,
                report_per_event=False, engine=engine,
                typical_pods=TypicalPodsConfig(
                    pod_popularity_threshold=95),
            ))
            sim.set_workload_pods(pods)
            return sim, sim.run()

        s_h, r_h = run("pallas")
        if s_h._last_engine != "pallas (hbm)":
            return False, msgs + [
                f"[pallas-hbm] FAIL: N=8192 dispatched "
                f"{s_h._last_engine!r}, not the HBM-residency kernel"]
        if any("[Degrade]" in l for l in s_h.log.lines):
            return False, msgs + [
                "[pallas-hbm] FAIL: the N=8192 run printed [Degrade]"]
        s_t, r_t = run("table")
        if not np.array_equal(r_t.placed_node, r_h.placed_node) or \
                not np.array_equal(r_t.dev_mask, r_h.dev_mask):
            return False, msgs + [
                "[pallas-hbm] FAIL: HBM-kernel placements diverge from "
                "the blocked table engine"]
        msgs.append("[pallas-hbm] N=8192 K=151 replay: pallas (hbm), no "
                    "degrade, bit-identical to the table engine "
                    f"({int((r_h.placed_node >= 0).sum())} placed)")

        # (c) residency + exact DMA counters in the run record
        det = s_h.run_telemetry().to_record()["deterministic"]
        if det.get("pallas_residency") != "hbm":
            return False, msgs + [
                "[pallas-hbm] FAIL: run record lacks "
                "pallas_residency=hbm"]
        waits = det["counts"].get("pallas_dma_waits", 0)
        starts = det["counts"].get("pallas_dma_starts", -1)
        if waits <= 0 or waits != starts:
            return False, msgs + [
                f"[pallas-hbm] FAIL: DMA counters absent or leaking "
                f"(waits={waits}, starts={starts})"]
        msgs.append(f"[pallas-hbm] run record: residency=hbm, "
                    f"dma_waits={waits} == dma_starts, "
                    f"rebuilds={det['counts'].get('pallas_hbm_rebuilds')}")
        return True, msgs
    except Exception as err:  # the gate reports, never tracebacks
        import traceback

        return False, msgs + [
            f"[pallas-hbm] FAIL: {type(err).__name__}: {err}",
            traceback.format_exc(limit=3),
        ]


def policy_smoke(out_dir: str) -> Tuple[bool, List[str]]:
    """ISSUE 14 satellite (`make policy-smoke`): the learned-policy lane
    end-to-end on a tiny synthetic trace — (a) tiny-trace imitation
    round-trip: record an FGD teacher's decisions, teacher-force the
    dataset builder through the log (feasible counts cross-checked),
    train + export, and require the i32 theta's teacher-forced
    agreement to clear the smoke bar; (b) learned-vs-built-in engine
    bit-identity: the exported theta replays identically on the
    sequential, flat, and blocked engines — plus the shard_map engine
    whenever >= 2 devices are visible (the `--policy-only` mode forces a
    2-device virtual CPU mesh, the mesh-chaos pattern); (c) ES policy
    search over theta adds ZERO compiled sweep executables after its
    first generation (hard jit._cache_size() check via the backend's
    tracked wrapper); (d) the signed artifact round-trips and a torn/
    edited copy is rejected loudly; (e) a service-side policy preset
    answers a submit job with the exact placements of the artifact run
    locally. Any exception is a FAIL verdict, not a traceback."""
    msgs: List[str] = []
    try:
        import json as _json

        import jax
        import numpy as np

        from tpusim.io.trace import NodeRow, PodRow
        from tpusim.learn import (
            ImitateConfig,
            LocalRollout,
            TeacherReplay,
            TuneConfig,
            load_policy_artifact,
            load_teacher_log,
            make_family_sim,
            policies_from_artifact,
            run_tune,
            save_policy_artifact,
        )
        from tpusim.learn.dataset import imitate_with_mining
        from tpusim.learn.policy import learned_policies
        from tpusim.obs import decisions as obs_dec
        from tpusim.sim.driver import Simulator, SimulatorConfig

        rng = np.random.default_rng(11)
        nodes = [
            NodeRow(f"n{i:03d}", 32000, 131072, int(g),
                    "V100M16" if g else "")
            for i, g in enumerate(rng.choice([0, 2, 4, 8], 16))
        ]
        pods = []
        for i in range(48):
            gpu = int(rng.choice([0, 1, 2]))
            milli = 1000 if gpu > 1 else int(rng.choice([300, 500, 1000]))
            if gpu == 0:
                milli = 0
            pods.append(PodRow(
                f"p{i:04d}", int(rng.choice([1000, 2000, 4000])), 2048,
                gpu, milli,
            ))

        def sim_for(policies, **kw):
            kw.setdefault("gpu_sel_method", "best")
            kw.setdefault("seed", 42)
            kw.setdefault("report_per_event", False)
            s = Simulator(nodes, SimulatorConfig(
                policies=tuple(policies), **kw))
            s.set_workload_pods(list(pods))
            return s

        # (a) imitation round-trip off a recorded FGD teacher
        teacher = sim_for(
            (("FGDScore", 1000),), gpu_sel_method="FGDScore",
            record_decisions=True,
        )
        tres = teacher.run()
        log_path = os.path.join(out_dir, "policy_smoke_teacher.jsonl")
        obs_dec.write_decisions(
            log_path, tres.decisions, policies=[("FGDScore", 1000)],
            meta=teacher._telemetry_meta(),
            pod_names=[p.name for p in tres.pods],
        )
        header, rows = load_teacher_log(log_path)
        replay = TeacherReplay(nodes, teacher.prepare_pods(), header, rows)
        cut = len(rows) - len(rows) // 5
        _, theta, _hist = imitate_with_mining(
            replay, ImitateConfig(steps=600, lr=0.3, l2=1e-6),
            end_event=cut, rounds=4,
        )
        rep = replay.agreement(theta)
        if rep["agreement"] < 0.7:
            return False, [
                f"[gate] policy: imitation agreement "
                f"{100 * rep['agreement']:.1f}% below the 70% smoke bar "
                f"(theta {theta}) (FAIL)"
            ]

        # (d) signed artifact round-trip + torn rejection
        art = os.path.join(out_dir, "policy_smoke_artifact.json")
        save_policy_artifact(art, theta, meta={"source": "policy-smoke"})
        feats, theta2, _ = load_policy_artifact(art)
        if list(theta2) != [int(t) for t in theta]:
            return False, ["[gate] policy: artifact round-trip drifted "
                           "(FAIL)"]
        with open(art) as f:
            lines = f.read().splitlines()
        doc = _json.loads(lines[1])
        doc["theta"][0] = int(doc["theta"][0]) + 1
        torn = os.path.join(out_dir, "policy_smoke_torn.json")
        with open(torn, "w") as f:
            f.write(lines[0] + "\n")
            f.write(_json.dumps(doc, sort_keys=True,
                                separators=(",", ":")) + "\n")
        try:
            load_policy_artifact(torn)
            return False, ["[gate] policy: a TORN artifact loaded "
                           "cleanly (FAIL)"]
        except ValueError:
            pass

        # (b) engine bit-identity of the exported theta
        pol = policies_from_artifact(art)
        engines = [
            ("sequential", dict(engine="sequential")),
            ("flat", dict(engine="table", block_size=-1)),
            ("blocked", dict(engine="table", block_size=4)),
        ]
        if len(jax.devices()) >= 2:
            engines.append(("shard", dict(engine="auto", mesh=2)))
        ref = None
        for label, kw in engines:
            r = sim_for(pol, **kw).run()
            if ref is None:
                ref = (label, r)
                continue
            if not (np.array_equal(np.asarray(ref[1].placed_node),
                                   np.asarray(r.placed_node))
                    and np.array_equal(np.asarray(ref[1].dev_mask),
                                       np.asarray(r.dev_mask))):
                return False, [
                    f"[gate] policy: {label} diverged from {ref[0]} "
                    "replaying the learned artifact (FAIL)"
                ]
        placed = int((np.asarray(ref[1].placed_node) >= 0).sum())

        # (c) one-executable ES generation: a second tuning run over the
        # same family must add ZERO compiled sweep executables (counts
        # read relative — the wrapper is process-global)
        fam = learned_policies(theta2)
        backend = LocalRollout(make_family_sim(nodes, pods, fam), width=4)
        cfg = TuneConfig(algo="es", generations=2, popsize=4,
                         sigma=300.0, lr=400.0, seed=3,
                         w_lo=-4000, w_hi=4000)
        run_tune(backend, fam, cfg,
                 os.path.join(out_dir, "policy_smoke_tune.jsonl"))
        before = backend.executables()
        if before < 1:
            return False, ["[gate] policy: ES backend tracked no "
                           "compiled sweep executable (FAIL)"]
        os.unlink(os.path.join(out_dir, "policy_smoke_tune.jsonl"))
        run_tune(backend, fam,
                 TuneConfig(algo="es", generations=2, popsize=4,
                            sigma=300.0, lr=400.0, seed=4,
                            w_lo=-4000, w_hi=4000),
                 os.path.join(out_dir, "policy_smoke_tune.jsonl"))
        if backend.executables() != before:
            return False, [
                f"[gate] policy: a second ES run grew the compiled "
                f"sweep executables ({before} -> "
                f"{backend.executables()}) (FAIL)"
            ]

        # (e) a served preset answers exactly like the local artifact
        from tpusim.svc import jobs as svc_jobs
        from tpusim.svc.api import JobService
        from tpusim.svc.batcher import JobQueue
        from tpusim.svc.worker import TraceRef, Worker

        trace = TraceRef("default", nodes, pods,
                         svc_jobs.trace_digest(nodes, pods))
        art_dir = os.path.join(out_dir, "policy_smoke_svc")
        os.makedirs(art_dir, exist_ok=True)
        queue = JobQueue(maxsize=8, lane_width=4)
        worker = Worker(queue, {"default": trace}, art_dir)
        service = JobService(
            queue, worker, {"default": trace}, art_dir,
            policy_presets={"smoke": pol},
        )
        resp = service.handle(
            "POST", "/jobs",
            _json.dumps({"policy_preset": "smoke", "seed": 42}).encode(),
        )
        if resp[0] not in (200, 202):
            return False, [f"[gate] policy: preset POST answered "
                           f"{resp[0]} (FAIL)"]
        job_id = _json.loads(resp[2].decode())["id"]
        while True:
            batch = queue.next_batch(timeout=0)
            if not batch:
                break
            worker.run_batch(batch)
        code, _, body = service.handle(
            "GET", f"/jobs/{job_id}/result", b"")[:3]
        got = _json.loads(body.decode())
        local = sim_for(pol).run()
        if code != 200 or not np.array_equal(
            np.asarray(got["placed_node"]), np.asarray(local.placed_node)
        ):
            return False, [
                "[gate] policy: the served preset's placements differ "
                "from the local artifact run (FAIL)"
            ]

        msgs.append(
            f"[gate] policy: imitation {rep['matches']}/"
            f"{rep['creates']} agreement, artifact signed + torn copy "
            f"rejected, {len(engines)}-engine bit-identity "
            f"({placed} placements), ES zero-recompile held at "
            f"{before} executable(s), served preset == local run"
        )
    except Exception as err:
        return False, [
            f"[gate] policy: FAIL ({type(err).__name__}: {err})"
        ]
    return True, msgs


def metrics_scrape_check(record: dict, prom_path: str) -> Tuple[bool, str]:
    """ISSUE 5 satellite: publish the smoke record to an ephemeral
    MonitorServer, scrape /metrics over real HTTP, and require (a) the
    scrape to parse as exposition-format text (parse_prometheus_text —
    the strict checks a textfile collector applies) and (b) the scrape
    to be byte-equal to the emitted textfile. Any exception on the
    serve/scrape path is a FAIL verdict, not a traceback."""
    import urllib.request

    from tpusim.obs.emitters import parse_prometheus_text
    from tpusim.obs.server import MonitorServer

    try:
        srv = MonitorServer(":0").start()
        try:
            srv.publish_record(record)
            with urllib.request.urlopen(srv.url + "/metrics",
                                        timeout=10) as resp:
                scrape = resp.read().decode()
        finally:
            srv.stop()
        parsed = parse_prometheus_text(scrape)
        with open(prom_path) as f:
            disk = f.read()
    except Exception as err:
        return False, f"[gate] scrape: FAIL ({type(err).__name__}: {err})"
    if scrape != disk:
        return False, (
            f"[gate] scrape: /metrics differs from {prom_path} (FAIL)"
        )
    return True, (
        f"[gate] scrape: /metrics parses ({len(parsed)} series) and is "
        f"byte-equal to {os.path.basename(prom_path)}"
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--tol", type=float, default=0.5,
        help="same-backend throughput regression tolerance as a fraction "
        "(default 0.5 — the tunneled chip's wall clocks vary ±20%%, and "
        "the gate must not flake on link noise)",
    )
    ap.add_argument(
        "--alloc-tol", type=float, default=0.05,
        help="absolute GPU-allocation-percent tolerance (default 0.05 — "
        "one rounding ulp of the 2-decimal bench print)",
    )
    ap.add_argument(
        "--warm-runs", type=int, default=2,
        help="warm replays for the smoke throughput sample (full bench "
        "uses 6; 2 keeps the gate fast — quality numbers need only one)",
    )
    ap.add_argument(
        "--out", default=os.path.join(REPO, ".tpusim_obs"),
        help="smoke-profile output dir (JSONL + Prometheus textfile)",
    )
    ap.add_argument(
        "--svc-only", action="store_true",
        help="run only the replay-service smoke (ISSUE 7) — the "
        "`make svc-smoke` mode",
    )
    ap.add_argument(
        "--serve-latency-only", action="store_true",
        help="run only the interactive what-if serving smoke (ISSUE 16: "
        "real-HTTP base run + warm fork wave with boundary joins, fork "
        "vs from-0 bit-identity, zero recompiles, hard admission->"
        "result p99 SLO) — the `make serve-latency-smoke` mode",
    )
    ap.add_argument(
        "--tune-only", action="store_true",
        help="run only the learned-scoring smoke (ISSUE 9) — the "
        "`make tune-smoke` mode",
    )
    ap.add_argument(
        "--chaos-only", action="store_true",
        help="run only the chaos-sweep smoke (ISSUE 10) — the "
        "`make chaos-smoke` mode",
    )
    ap.add_argument(
        "--mesh-chaos-only", action="store_true",
        help="run only the mesh-chaos smoke (ISSUE 11: pipelined shard "
        "fault replay + donated chunked replay on a forced virtual "
        "mesh) — the `make mesh-chaos-smoke` mode",
    )
    ap.add_argument(
        "--fleet-chaos-only", action="store_true",
        help="run only the fleet-chaos smoke (ISSUE 12: 3 worker "
        "processes, random kill -9 mid-batch, byte-identity vs a "
        "single-worker run, orphan stealing, warm-joiner compile "
        "skip) — the `make fleet-chaos-smoke` mode",
    )
    ap.add_argument(
        "--fleet-ha-only", action="store_true",
        help="run only the coordinator-HA smoke (ISSUE 17: token-armed "
        "leader + standby pair over real HTTP, kill -9 the leader "
        "mid-batch, standby adopts at a bumped epoch, workers re-join, "
        "100%% completion byte-identical to a single-coordinator "
        "reference, stale-epoch 409, forged-token 401s, resurrected "
        "leader fenced) — the `make fleet-ha-smoke` mode",
    )
    ap.add_argument(
        "--fleet-trace-only", action="store_true",
        help="run only the fleet flight-recorder smoke (ISSUE 19: "
        "real-HTTP fleet + supervised workers, kill -9 of a "
        "lease-holder mid-batch, gap-free stitched cross-process "
        "timeline for every job with zero orphan spans, the stolen "
        "attempt stitched as abandoned, hash-chained audit log "
        "verifying end-to-end with the steal + respawn recorded, "
        "aggregated /metrics with per-live-worker labeled series) — "
        "the `make fleet-trace-smoke` mode",
    )
    ap.add_argument(
        "--fleet-wan-only", action="store_true",
        help="run only the fleet-wan smoke (ISSUE 13: remote-mode "
        "workers with NO shared filesystem behind a flaky HTTP shim, "
        "kill -9 + supervisor respawn, byte-identity vs a "
        "single-worker run, forced crash loop tripping the circuit "
        "breaker) — the `make fleet-wan-smoke` mode",
    )
    ap.add_argument(
        "--slo-only", action="store_true",
        help="run only the SLO-plane smoke (ISSUE 20: real-HTTP fleet, "
        "induced fork-latency regression fires a burn-rate page "
        "visible on /alerts + /healthz + `tpusim top`, chained in a "
        "verifying audit log, resolving under live recovery traffic; "
        "crash-loop breaker trip fires the built-in page; /query "
        "history survives a kill -9 takeover with no gap at the "
        "splice) — the `make slo-smoke` mode",
    )
    ap.add_argument(
        "--pallas-hbm-only", action="store_true",
        help="run only the HBM-residency pallas smoke (ISSUE 15: "
        "N=8192/K=151 interpreter replay above the old VMEM ceiling "
        "reconciled bit-exactly against the table engine, two-tier "
        "residency auto-select pinned, DMA-wait counters in the run "
        "record) — the `make pallas-hbm-smoke` mode",
    )
    ap.add_argument(
        "--policy-only", action="store_true",
        help="run only the learned-policy smoke (ISSUE 14: tiny-trace "
        "imitation round-trip, learned-vs-built-in engine bit-identity "
        "on a forced 2-device virtual mesh, one-executable ES "
        "generation, signed-artifact round-trip + torn rejection, "
        "served preset == local run) — the `make policy-smoke` mode",
    )
    args = ap.parse_args(argv)

    if args.pallas_hbm_only:
        os.makedirs(args.out, exist_ok=True)
        ok, msgs = pallas_hbm_smoke(args.out)
        print("\n".join(msgs))
        print(f"[gate] {'PASS' if ok else 'FAIL'}")
        return 0 if ok else 1

    if args.policy_only:
        # force a 2-device virtual CPU mesh BEFORE jax initializes so
        # the bit-identity leg covers the shard_map engine too (the
        # mesh-chaos pattern; no-ops on an already-up backend)
        from tpusim.virtual_mesh import force_virtual_cpu_devices

        force_virtual_cpu_devices(2, force=True)
        os.makedirs(args.out, exist_ok=True)
        ok, msgs = policy_smoke(args.out)
        print("\n".join(msgs))
        print(f"[gate] {'PASS' if ok else 'FAIL'}")
        return 0 if ok else 1

    if args.slo_only:
        ok, msgs = slo_smoke(args.out)
        print("\n".join(msgs))
        print(f"[gate] {'PASS' if ok else 'FAIL'}")
        return 0 if ok else 1

    if args.fleet_ha_only:
        ok, msgs = fleet_ha_smoke(args.out)
        print("\n".join(msgs))
        print(f"[gate] {'PASS' if ok else 'FAIL'}")
        return 0 if ok else 1

    if args.fleet_trace_only:
        ok, msgs = fleet_trace_smoke(args.out)
        print("\n".join(msgs))
        print(f"[gate] {'PASS' if ok else 'FAIL'}")
        return 0 if ok else 1

    if args.fleet_wan_only:
        ok, msgs = fleet_wan_smoke(args.out)
        print("\n".join(msgs))
        print(f"[gate] {'PASS' if ok else 'FAIL'}")
        return 0 if ok else 1

    if args.fleet_chaos_only:
        ok, msgs = fleet_chaos_smoke(args.out)
        print("\n".join(msgs))
        print(f"[gate] {'PASS' if ok else 'FAIL'}")
        return 0 if ok else 1

    if args.mesh_chaos_only:
        # a CPU smoke by design (the Makefile target pins
        # JAX_PLATFORMS=cpu, like chaos-smoke): force a 2-device virtual
        # CPU mesh BEFORE jax initializes. force=True because this image
        # registers inert cuda/rocm/tpu plugin factories that would make
        # the conservative helper bail; it still no-ops on an already-up
        # backend.
        from tpusim.virtual_mesh import force_virtual_cpu_devices

        force_virtual_cpu_devices(2, force=True)
        ok, msgs = mesh_chaos_smoke()
        adv_ok, adv = multichip_advisory(latest_multichip())
        msgs += adv
        ok = ok and adv_ok
        print("\n".join(msgs))
        print(f"[gate] {'PASS' if ok else 'FAIL'}")
        return 0 if ok else 1

    if args.tune_only:
        ok, msgs = tune_smoke(args.out)
        print("\n".join(msgs))
        print(f"[gate] {'PASS' if ok else 'FAIL'}")
        return 0 if ok else 1

    base = latest_baseline()
    sys.path.insert(0, REPO)
    import bench

    import jax

    nodes, pods = bench.load_trace()

    if args.svc_only:
        ok, msgs = svc_smoke(nodes, pods, args.out)
        print("\n".join(msgs))
        print(f"[gate] {'PASS' if ok else 'FAIL'}")
        return 0 if ok else 1
    if args.serve_latency_only:
        ok, msgs = serve_latency_smoke(nodes, pods, args.out)
        print("\n".join(msgs))
        print(f"[gate] {'PASS' if ok else 'FAIL'}")
        return 0 if ok else 1
    if args.chaos_only:
        ok, msgs = chaos_smoke(nodes, pods)
        print("\n".join(msgs))
        print(f"[gate] {'PASS' if ok else 'FAIL'}")
        return 0 if ok else 1
    row = bench.measure_policy(
        nodes, pods,
        *next(r for r in bench.POLICY_ROWS if r[0] == "FGD"),
        warm_runs=args.warm_runs, profile=True,
    )
    telemetry = row.pop("_telemetry", None)
    cur = {
        "throughput": row["placements_per_sec"],
        "events": row["events"],
        "placed": row["placements"],
        "gpu_alloc": row["gpu_alloc_pct"],
        "backend": jax.default_backend(),
    }

    scrape_ok, scrape_msg = True, ""
    if telemetry is not None:
        from tpusim.obs import emitters

        prom_path = os.path.join(args.out, "gate_metrics.prom")
        record = emitters.build_record(
            telemetry, meta={"gate": "bench-gate", "row": row}
        )
        paths = emitters.emit_record(
            record, telemetry.spans,
            jsonl=os.path.join(args.out, "gate_profile.jsonl"),
            metrics=prom_path,
        )
        print(f"[gate] smoke profile: {', '.join(paths)}")
        # live-telemetry smoke: a /metrics scrape of the same record must
        # parse and match the textfile byte-for-byte (ISSUE 5 satellite)
        scrape_ok, scrape_msg = metrics_scrape_check(record, prom_path)
        print(scrape_msg)

    # decision-provenance smoke: the JSONL the explain/diff verbs consume
    # must round-trip (ISSUE 4 satellite) — checked regardless of
    # whether a throughput baseline exists
    dec_ok, dec_msg = decisions_roundtrip(nodes, pods, args.out)
    print(dec_msg)
    # config-axis sweep smoke + advisory throughput comparison (ISSUE 6
    # satellite): the one-compile contract gates, the walls never do
    swp_ok, swp_msgs = sweep_advisory(nodes, pods, latest_sweep())
    print("\n".join(swp_msgs))
    # replay-service smoke (ISSUE 7 satellite): POST path end-to-end —
    # dedup via the digest cache, one batch per wave, zero recompiles
    # across a weights+tune wave
    svc_ok, svc_msgs = svc_smoke(nodes, pods, args.out)
    print("\n".join(svc_msgs))
    # interactive what-if serving smoke (ISSUE 16): warm-state fork wave
    # over real HTTP — bit-identity vs from-0 twins, boundary joins with
    # zero recompiles, hard admission->result p99 SLO
    serve_ok, serve_msgs = serve_latency_smoke(nodes, pods, args.out)
    print("\n".join(serve_msgs))
    # learned-scoring smoke (ISSUE 9 satellite): the tuning loop on one
    # compiled sweep — zero recompiles, signed resumable log
    tune_ok, tune_msgs = tune_smoke(args.out)
    print("\n".join(tune_msgs))
    # chaos-sweep smoke (ISSUE 10 satellite): B-lane fault sweep — hard
    # zero-recompile check + standalone disruption reconciliation
    chaos_ok, chaos_msgs = chaos_smoke(nodes, pods)
    print("\n".join(chaos_msgs))
    # learned-policy smoke (ISSUE 14): imitation round-trip, engine
    # bit-identity of a signed artifact, ES zero-recompile, preset
    pol_ok, pol_msgs = policy_smoke(args.out)
    print("\n".join(pol_msgs))
    # HBM-residency pallas smoke (ISSUE 15): above-the-old-ceiling
    # interpreter replay vs the table engine, residency select, DMA
    # counters
    hbm_ok, hbm_msgs = pallas_hbm_smoke(args.out)
    print("\n".join(hbm_msgs))
    # mesh-chaos smoke (ISSUE 11 satellite): pipelined shard fault
    # replay + donated chunked replay — skips (PASS) on single-device
    # hosts; `make mesh-chaos-smoke` runs the forced-virtual-mesh form
    mesh_ok, mesh_msgs = mesh_chaos_smoke()
    print("\n".join(mesh_msgs))
    # fleet-chaos smoke (ISSUE 12): worker processes + kill -9 mid-batch
    # — byte-identity vs single-worker, orphan stealing, warm joiner
    fleet_ok, fleet_msgs = fleet_chaos_smoke(args.out)
    print("\n".join(fleet_msgs))
    # fleet-wan smoke (ISSUE 13): no-shared-fs remote workers under a
    # flaky transfer plane + supervisor respawn + the circuit breaker
    wan_ok, wan_msgs = fleet_wan_smoke(args.out)
    print("\n".join(wan_msgs))
    # fleet-trace smoke (ISSUE 19): the flight recorder — stitched
    # cross-process timelines across a kill -9 + steal, hash-chained
    # audit log, aggregated per-worker /metrics
    trace_ok, trace_msgs = fleet_trace_smoke(args.out)
    print("\n".join(trace_msgs))
    # fleet-ha smoke (ISSUE 17): leader + standby pair, kill -9 the
    # leader mid-batch — epoch-fenced takeover, auth probes,
    # byte-identity vs a single-coordinator reference
    ha_ok, ha_msgs = fleet_ha_smoke(args.out)
    print("\n".join(ha_msgs))
    # SLO-plane smoke (ISSUE 20): burn-rate page fires on an induced
    # fork regression, resolves under recovery traffic, breaker trip
    # pages, /query history survives a kill -9 takeover
    slo_ok, slo_msgs = slo_smoke(args.out)
    print("\n".join(slo_msgs))
    # scale-lane advisory (ISSUE 11 satellite): newest committed
    # MULTICHIP_r*.json, like the BENCH_r*.json baselines
    mc_ok, mc_msgs = multichip_advisory(latest_multichip())
    print("\n".join(mc_msgs))
    smoke_ok = (dec_ok and scrape_ok and swp_ok and svc_ok and serve_ok
                and tune_ok and chaos_ok and pol_ok and hbm_ok
                and mesh_ok and fleet_ok and wan_ok and trace_ok
                and ha_ok and slo_ok and mc_ok)

    if base is None:
        print("[gate] no committed BENCH_r*.json baseline found — smoke "
              "profile recorded, nothing to diff "
              f"({'PASS' if smoke_ok else 'FAIL'})")
        return 0 if smoke_ok else 1

    ok, msgs = compare(base, cur, args.tol, args.alloc_tol)
    ok = ok and smoke_ok
    print(f"[gate] baseline {os.path.basename(base['path'])} "
          f"(round {base['n']}, backend {base['backend']!r}):")
    print("\n".join(msgs))
    print(f"[gate] {'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
