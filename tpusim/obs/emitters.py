"""Telemetry emitters: JSONL run records, Prometheus textfiles, Chrome
traces — the machine-readable outputs of a profiled run.

Three consumers, three formats:

  JSONL    one self-contained record per run, appended (`--profile PATH`)
           — the regression gate and the reproducibility tests read this
  Prom     node_exporter textfile-collector gauges (`--metrics-out PATH`)
           — scrape-ready; written atomically (tmp + rename) per the
           textfile collector contract so a scraper never sees a torn
           file
  Chrome   chrome://tracing / Perfetto "X" (complete) events from the
           span list (`--trace-out PATH`) — the phase timeline view —
           plus "C" counter tracks (per-event frag/alloc series from the
           metrics postpass) charting fragmentation under the spans

All writers are atomic (tmp + os.replace) except the JSONL append, whose
unit of atomicity is the single O_APPEND write of one line.
"""

from __future__ import annotations

import json
import os
import re
from typing import Iterable, List

_METRIC_RE = re.compile(r"[^a-zA-Z0-9_]")


def _atomic_write(path: str, text: str):
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def append_jsonl(path: str, record: dict) -> str:
    """Append one run record as a single JSON line (sorted keys, so two
    identical records are byte-identical lines — the bit-reproducibility
    contract is checkable with `diff`)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    line = json.dumps(record, sort_keys=True, separators=(",", ":"))
    with open(path, "a") as f:
        f.write(line + "\n")
    return path


def read_jsonl(path: str) -> List[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _metric_name(*parts: str) -> str:
    return _METRIC_RE.sub("_", "_".join(p for p in parts if p)).lower()


def escape_label_value(value: str) -> str:
    """Escape a label VALUE per the Prometheus exposition format (text
    version 0.0.4): backslash, double-quote, and line-feed are the three
    characters with escape sequences — everything else passes through.
    Order matters: backslashes first, or the other escapes' own
    backslashes would be doubled."""
    return (
        str(value)
        .replace("\\", r"\\")
        .replace('"', r"\"")
        .replace("\n", r"\n")
    )


def unescape_label_value(value: str) -> str:
    """Inverse of escape_label_value (the round-trip contract tests pin).
    A manual scan, not chained replaces — `\\n` must decode to
    backslash+n, which replace-ordering cannot express."""
    out = []
    i = 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt == "\\":
                out.append("\\")
            elif nxt == '"':
                out.append('"')
            elif nxt == "n":
                out.append("\n")
            else:  # unknown escape: keep verbatim (prom parsers do too)
                out.append(c + nxt)
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


# one sample line: name, optional {labels}, value. Label values may hold
# any escaped character, including escaped quotes.
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(\{(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*",?)*\})?'
    r' (\S+)$'
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus_text(text: str):
    """Strict-ish parse of an exposition-format snapshot into
    {(name, ((label, value), ...)): float}. Raises ValueError on any
    line that is neither a comment nor a well-formed sample, and on
    duplicate series — the checks the textfile collector applies, used
    by the bench gate's scrape assertion and the round-trip tests."""
    out = {}
    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip() or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {ln}: not a valid sample: {line!r}")
        name, labels_raw, value = m.group(1), m.group(2), m.group(3)
        labels = tuple(
            (k, unescape_label_value(v))
            for k, v in _LABEL_RE.findall(labels_raw or "")
        )
        key = (name, labels)
        if key in out:
            raise ValueError(f"line {ln}: duplicate series {key}")
        try:
            out[key] = float(value)
        except ValueError:
            raise ValueError(f"line {ln}: bad sample value {value!r}")
    return out


def prometheus_lines(record: dict, prefix: str = "tpusim") -> List[str]:
    """Flatten a run record into `# TYPE ... gauge` + sample lines. Only
    the numeric leaves ship; span walls become
    `tpusim_span_seconds{name="...",phase="dispatch|block"}`.

    Each `# TYPE` declaration is emitted ONCE per metric name: two
    samples of the same metric (different labels, or two record keys
    sanitizing to the same name) must share one declaration — strict
    promtext parsers (and node_exporter's textfile collector) reject a
    file with duplicate TYPE lines for a metric. The same strictness
    applies to SAMPLES: only one line per (name, labelset) is legal, so
    when two record keys sanitize to one collision-free name the first
    (sorted-order) writer wins and later duplicates are dropped — an
    invalid file would lose the whole snapshot, not just one sample."""
    det = record.get("deterministic", {})
    lines: List[str] = []
    typed: set = set()
    emitted: set = set()

    def gauge(name: str, value, labels: str = ""):
        if (name, labels) in emitted:
            return
        emitted.add((name, labels))
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{labels} {value}")

    gauge(_metric_name(prefix, "events_total"), det.get("events", 0))
    for group in ("counters", "degrades", "counts", "disruption"):
        for k, v in sorted(det.get(group, {}).items()):
            gauge(_metric_name(prefix, group[:-1] if group.endswith("s")
                               else group, k), v)
    cache = det.get("table_cache", "off")
    gauge(_metric_name(prefix, "table_cache_hit"), int(cache == "hit"))
    # ---- the in-scan time-series plane (ISSUE 5): the LAST sample of
    # every series ships as a gauge — what "live cluster telemetry"
    # means to a scraper — plus the sample count so dashboards can rate
    series = record.get("series") or {}
    if series.get("pos"):
        sname = _metric_name(prefix, "series")
        gauge(f"{sname}_samples", len(series["pos"]))
        gauge(f"{sname}_last_pos", series["pos"][-1])
        for scalar in ("feasible", "nodes_down", "retry_depth"):
            if series.get(scalar):
                gauge(f"{sname}_{scalar}", series[scalar][-1])
        cats = series.get("frag_categories", [])
        if series.get("frag"):
            last = series["frag"][-1]
            for j, cat in enumerate(cats[: len(last)]):
                gauge(
                    f"{sname}_frag_gpu_milli",
                    last[j],
                    f'{{category="{escape_label_value(cat)}"}}',
                )
        if series.get("util_hist"):
            last = series["util_hist"][-1]
            nb = max(len(last), 1)
            for b, v in enumerate(last):
                gauge(
                    f"{sname}_util_nodes", v,
                    f'{{bucket="{100 * b // nb:02d}"}}',
                )
        pols = series.get("policies", [])
        for field in ("score_hi", "score_lo"):
            if series.get(field):
                last = series[field][-1]
                for i, pol in enumerate(pols[: len(last)]):
                    gauge(
                        f"{sname}_{field}", last[i],
                        f'{{policy="{escape_label_value(pol)}"}}',
                    )
    timing = record.get("timing", {})
    if "wall_s" in timing:
        gauge(_metric_name(prefix, "wall_seconds"), timing["wall_s"])
    # aggregate spans per (name, phase): a profiled run records MANY spans
    # with the same name (one 'scan' per chunk/segment/warm run), and the
    # Prometheus text format forbids duplicate series — node_exporter's
    # textfile collector would drop the whole file
    agg: dict = {}
    counts: dict = {}
    for s in timing.get("spans", []):
        # label values are ESCAPED, never stripped: a span named with a
        # quote/backslash/newline must round-trip through a strict
        # exposition-format parser (escape_label_value)
        name = str(s.get("name", ""))
        counts[name] = counts.get(name, 0) + 1
        for phase in ("dispatch", "block"):
            key = (name, phase)
            agg[key] = agg.get(key, 0.0) + float(s.get(f"{phase}_s", 0))
    if agg:
        span_metric = _metric_name(prefix, "span_seconds_total")
        for (name, phase), v in sorted(agg.items()):
            gauge(
                span_metric, round(v, 6),
                f'{{name="{escape_label_value(name)}",phase="{phase}"}}',
            )
        count_metric = _metric_name(prefix, "span_count")
        for name, n in sorted(counts.items()):
            gauge(count_metric, n,
                  f'{{name="{escape_label_value(name)}"}}')
    return lines


def write_prometheus(path: str, record: dict, prefix: str = "tpusim") -> str:
    _atomic_write(path, "\n".join(prometheus_lines(record, prefix)) + "\n")
    return path


def latency_summary_lines(latency: dict,
                          prefix: str = "tpusim") -> List[str]:
    """The /queue per-kind admission->result latency rings as NATIVE
    Prometheus summary series (ISSUE 20): p50/p99 as `quantile`-labeled
    samples plus the `_count` suffix, per job kind — so the tsdb, the
    gate, and external scrapers consume one vocabulary instead of
    parsing the /queue JSON side-channel. `latency` is
    JobQueue.latency_percentiles()'s document. Kind names are escaped
    like every label value here; one `# TYPE ... summary` per metric."""
    lines: List[str] = []
    name = _metric_name(prefix, "queue_latency_seconds")
    adj_name = _metric_name(prefix, "queue_latency_adjusted_seconds")
    typed: set = set()

    def sample(metric: str, labels: str, value):
        lines.append(f"{metric}{labels} {value}")

    def declare(metric: str):
        if metric not in typed:
            typed.add(metric)
            lines.append(f"# TYPE {metric} summary")

    for kind in sorted(latency):
        row = latency[kind]
        k = escape_label_value(str(kind))
        declare(name)
        sample(name, f'{{kind="{k}",quantile="0.5"}}',
               row.get("p50_s", 0.0))
        sample(name, f'{{kind="{k}",quantile="0.99"}}',
               row.get("p99_s", 0.0))
        sample(f"{name}_count", f'{{kind="{k}"}}', row.get("count", 0))
        if "adjusted_p99_s" in row:
            declare(adj_name)
            sample(adj_name, f'{{kind="{k}",quantile="0.5"}}',
                   row.get("adjusted_p50_s", 0.0))
            sample(adj_name, f'{{kind="{k}",quantile="0.99"}}',
                   row.get("adjusted_p99_s", 0.0))
            sample(f"{adj_name}_count", f'{{kind="{k}"}}',
                   row.get("count", 0))
    return lines


def chrome_trace_events(spans: Iterable, pid: int = 1) -> List[dict]:
    """Span list -> Chrome trace "X" events (ts/dur in microseconds).
    Each span renders as two stacked slices — the dispatch (compile)
    half and the block (execute) half — so the compile/execute split is
    visible directly on the timeline."""
    events = []
    for s in spans:
        d = s.to_dict() if hasattr(s, "to_dict") else dict(s)
        base = {"pid": pid, "tid": 1, "ph": "X", "cat": "tpusim"}
        t0 = d["start_s"] * 1e6
        if d.get("dispatch_s", 0) > 0:
            events.append({
                **base, "name": f"{d['name']}:dispatch",
                "ts": t0, "dur": d["dispatch_s"] * 1e6,
                "args": d.get("meta", {}),
            })
        if d.get("block_s", 0) > 0:
            events.append({
                **base, "name": f"{d['name']}:block",
                "ts": t0 + d.get("dispatch_s", 0) * 1e6,
                "dur": d["block_s"] * 1e6,
                "args": d.get("meta", {}),
            })
    return events


# counter tracks denser than this are strided down — Perfetto renders a
# multi-thousand-point counter no better, and the trace file stays small
MAX_COUNTER_POINTS = 2000


def chrome_counter_events(
    counter_series: dict, spans: Iterable, pid: int = 1,
    max_points: int = MAX_COUNTER_POINTS,
) -> List[dict]:
    """Per-event series -> Chrome counter-track events (`"ph": "C"`), so
    the timeline shows fragmentation/allocation evolving UNDER the phase
    spans. `counter_series` maps track name -> one value per event (the
    frag/alloc series the metrics postpass already computes,
    sim/metrics.compute_event_metrics). Events carry no wall timestamps
    — the scan spans do — so the E points are laid out linearly across
    the union of the `scan` spans' wall window (falling back to the full
    span window), which is exactly the stretch of the timeline the
    events executed in."""
    spans = list(spans)
    dicts = [s.to_dict() if hasattr(s, "to_dict") else dict(s) for s in spans]
    windows = [d for d in dicts if d.get("name") == "scan"] or dicts

    def _end_s(d):
        # the stretch the "X" slices actually render: dispatch + block
        # when the span recorded them (profiled runs — the only ones
        # emitting traces). total_s can run past that by whatever host
        # pause hit between dispatched() and span exit, which would
        # strand the tail counter points beyond every rendered slice.
        halves = d.get("dispatch_s", 0) + d.get("block_s", 0)
        return d["start_s"] + (halves if halves > 0 else d.get("total_s", 0))

    if windows:
        t0 = min(d["start_s"] for d in windows) * 1e6
        t1 = max(_end_s(d) for d in windows) * 1e6
    else:
        t0, t1 = 0.0, 1e6
    events: List[dict] = []
    for track, values in sorted(counter_series.items()):
        values = list(values)
        n = len(values)
        if not n:
            continue
        stride = max(1, -(-n // max_points))
        idx = list(range(0, n, stride))
        if idx[-1] != n - 1:
            idx.append(n - 1)  # always chart the final value
        span_us = max(t1 - t0, 1.0)
        for i in idx:
            ts = t0 + span_us * (i / max(n - 1, 1))
            events.append({
                "pid": pid, "tid": 0, "ph": "C", "cat": "tpusim",
                "name": track, "ts": ts, "args": {track: values[i]},
            })
    return events


def write_chrome_trace(path: str, spans: Iterable,
                       counter_series: dict = None) -> str:
    spans = list(spans)
    events = chrome_trace_events(spans)
    if counter_series:
        events.extend(chrome_counter_events(counter_series, spans))
    _atomic_write(
        path,
        json.dumps({"traceEvents": events, "displayTimeUnit": "ms"}),
    )
    return path


def build_record(telemetry, meta: dict = None, series: dict = None) -> dict:
    """One run's JSONL record from its RunTelemetry, plus the caller's
    meta and the in-scan series block (obs.series.series_to_record) —
    built ONCE so every consumer (JSONL append, Prometheus textfile, the
    live /metrics endpoint) renders the same record and the
    final-scrape-equals-textfile contract holds byte-for-byte."""
    record = telemetry.to_record()
    if meta:
        record["deterministic"]["meta"].update(meta)
    if series:
        record["series"] = series
    return record


def emit_record(record: dict, spans, jsonl: str = "", metrics: str = "",
                trace: str = "", counter_series: dict = None) -> List[str]:
    """Write the requested emitter outputs for a prebuilt record; returns
    the paths written. `spans` feeds the Chrome-trace timeline;
    `counter_series` (track name -> per-event values) adds counter
    tracks to it."""
    written = []
    if jsonl:
        written.append(append_jsonl(jsonl, record))
    if metrics:
        written.append(write_prometheus(metrics, record))
    if trace:
        written.append(write_chrome_trace(trace, spans, counter_series))
    return written


def emit_all(telemetry, jsonl: str = "", metrics: str = "", trace: str = "",
             meta: dict = None, counter_series: dict = None,
             series: dict = None) -> List[str]:
    """build_record + emit_record for one RunTelemetry (the historical
    one-call surface)."""
    record = build_record(telemetry, meta=meta, series=series)
    return emit_record(
        record, telemetry.spans, jsonl=jsonl, metrics=metrics, trace=trace,
        counter_series=counter_series,
    )


# ---------------------------------------------------------------------------
# Tuning-curve emitter (ISSUE 9) — the learned-scoring lane's telemetry
# ---------------------------------------------------------------------------
#
# A tuning log (tpusim.learn.loop, digest-signed JSONL) is a generation
# series, not an event series — but it renders through the same two
# surfaces the in-scan series plane uses: a per-track value map (the
# Chrome-counter / plot vocabulary, consumed by `analysis --plot-tuning`)
# and a terminal sparkline summary (the `tpusim report` idiom, printed by
# `tpusim tune` when the loop finishes).


def tuning_curve_series(records) -> dict:
    """Tuning-log generation records -> track name -> per-generation
    values. Tracks: the per-generation best objective, the running best,
    the population mean/min objective, the optimizer's step scale, and
    (when the robustness eval ran) the faulted objective of each
    generation's best candidate."""
    import numpy as np

    gens = [int(r["gen"]) for r in records]
    out = {
        "tune_gen": gens,
        "tune_gen_best": [float(r["gen_best"]["objective"])
                          for r in records],
        "tune_best": [float(r["best"]["objective"]) for r in records],
        "tune_mean": [
            float(np.mean(r["objectives"])) for r in records
        ],
        "tune_min": [
            float(np.min(r["objectives"])) for r in records
        ],
        "tune_sigma": [float(r["state"]["sigma"]) for r in records],
        "tune_unique": [len(r["unique"]) for r in records],
    }
    if records and all("robust" in r for r in records):
        # all-or-none: a partial column could not align with the
        # generation axis (mixed logs are unwritable since the robust
        # knobs joined the resume-checked header, but an emitter must
        # not crash on a foreign file either)
        out["tune_robust"] = [
            float(r["robust"]["objective"]) for r in records
        ]
    return out


def format_tuning_curve(records) -> str:
    """Terminal summary of a tuning run: one sparkline per curve (the
    obs.series report idiom) plus first/last values — reads straight
    from the log records, no recomputation."""
    from tpusim.obs.series import sparkline

    if not records:
        return "[tune] no generations recorded"
    tracks = tuning_curve_series(records)
    gens = tracks.pop("tune_gen")
    lines = [
        f"[tune] {len(gens)} generations "
        f"(gen {gens[0]}..{gens[-1]})",
        f"  {'curve':<16}{'first':>12}{'last':>12}  trend",
    ]
    for name in ("tune_gen_best", "tune_best", "tune_mean",
                 "tune_robust", "tune_sigma", "tune_unique"):
        vals = tracks.get(name)
        if not vals:
            continue
        lines.append(
            f"  {name[5:]:<16}{vals[0]:>12.4f}{vals[-1]:>12.4f}  "
            f"{sparkline(vals)}"
        )
    return "\n".join(lines)
