"""Telemetry emitters: JSONL run records, Prometheus textfiles, Chrome
traces — the machine-readable outputs of a profiled run.

Three consumers, three formats:

  JSONL    one self-contained record per run, appended (`--profile PATH`)
           — the regression gate and the reproducibility tests read this
  Prom     node_exporter textfile-collector gauges (`--metrics-out PATH`)
           — scrape-ready; written atomically (tmp + rename) per the
           textfile collector contract so a scraper never sees a torn
           file
  Chrome   chrome://tracing / Perfetto "X" (complete) events from the
           span list (`--trace-out PATH`) — the phase timeline view —
           plus "C" counter tracks (per-event frag/alloc series from the
           metrics postpass) charting fragmentation under the spans

All writers are atomic (tmp + os.replace) except the JSONL append, whose
unit of atomicity is the single O_APPEND write of one line.
"""

from __future__ import annotations

import json
import os
import re
from typing import Iterable, List

_METRIC_RE = re.compile(r"[^a-zA-Z0-9_]")


def _atomic_write(path: str, text: str):
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def append_jsonl(path: str, record: dict) -> str:
    """Append one run record as a single JSON line (sorted keys, so two
    identical records are byte-identical lines — the bit-reproducibility
    contract is checkable with `diff`)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    line = json.dumps(record, sort_keys=True, separators=(",", ":"))
    with open(path, "a") as f:
        f.write(line + "\n")
    return path


def read_jsonl(path: str) -> List[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _metric_name(*parts: str) -> str:
    return _METRIC_RE.sub("_", "_".join(p for p in parts if p)).lower()


def prometheus_lines(record: dict, prefix: str = "tpusim") -> List[str]:
    """Flatten a run record into `# TYPE ... gauge` + sample lines. Only
    the numeric leaves ship; span walls become
    `tpusim_span_seconds{name="...",phase="dispatch|block"}`.

    Each `# TYPE` declaration is emitted ONCE per metric name: two
    samples of the same metric (different labels, or two record keys
    sanitizing to the same name) must share one declaration — strict
    promtext parsers (and node_exporter's textfile collector) reject a
    file with duplicate TYPE lines for a metric. The same strictness
    applies to SAMPLES: only one line per (name, labelset) is legal, so
    when two record keys sanitize to one collision-free name the first
    (sorted-order) writer wins and later duplicates are dropped — an
    invalid file would lose the whole snapshot, not just one sample."""
    det = record.get("deterministic", {})
    lines: List[str] = []
    typed: set = set()
    emitted: set = set()

    def gauge(name: str, value, labels: str = ""):
        if (name, labels) in emitted:
            return
        emitted.add((name, labels))
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{labels} {value}")

    gauge(_metric_name(prefix, "events_total"), det.get("events", 0))
    for group in ("counters", "degrades", "counts", "disruption"):
        for k, v in sorted(det.get(group, {}).items()):
            gauge(_metric_name(prefix, group[:-1] if group.endswith("s")
                               else group, k), v)
    cache = det.get("table_cache", "off")
    gauge(_metric_name(prefix, "table_cache_hit"), int(cache == "hit"))
    timing = record.get("timing", {})
    if "wall_s" in timing:
        gauge(_metric_name(prefix, "wall_seconds"), timing["wall_s"])
    # aggregate spans per (name, phase): a profiled run records MANY spans
    # with the same name (one 'scan' per chunk/segment/warm run), and the
    # Prometheus text format forbids duplicate series — node_exporter's
    # textfile collector would drop the whole file
    agg: dict = {}
    counts: dict = {}
    for s in timing.get("spans", []):
        name = str(s.get("name", "")).replace('"', "")
        counts[name] = counts.get(name, 0) + 1
        for phase in ("dispatch", "block"):
            key = (name, phase)
            agg[key] = agg.get(key, 0.0) + float(s.get(f"{phase}_s", 0))
    if agg:
        span_metric = _metric_name(prefix, "span_seconds_total")
        for (name, phase), v in sorted(agg.items()):
            gauge(
                span_metric, round(v, 6),
                f'{{name="{name}",phase="{phase}"}}',
            )
        count_metric = _metric_name(prefix, "span_count")
        for name, n in sorted(counts.items()):
            gauge(count_metric, n, f'{{name="{name}"}}')
    return lines


def write_prometheus(path: str, record: dict, prefix: str = "tpusim") -> str:
    _atomic_write(path, "\n".join(prometheus_lines(record, prefix)) + "\n")
    return path


def chrome_trace_events(spans: Iterable, pid: int = 1) -> List[dict]:
    """Span list -> Chrome trace "X" events (ts/dur in microseconds).
    Each span renders as two stacked slices — the dispatch (compile)
    half and the block (execute) half — so the compile/execute split is
    visible directly on the timeline."""
    events = []
    for s in spans:
        d = s.to_dict() if hasattr(s, "to_dict") else dict(s)
        base = {"pid": pid, "tid": 1, "ph": "X", "cat": "tpusim"}
        t0 = d["start_s"] * 1e6
        if d.get("dispatch_s", 0) > 0:
            events.append({
                **base, "name": f"{d['name']}:dispatch",
                "ts": t0, "dur": d["dispatch_s"] * 1e6,
                "args": d.get("meta", {}),
            })
        if d.get("block_s", 0) > 0:
            events.append({
                **base, "name": f"{d['name']}:block",
                "ts": t0 + d.get("dispatch_s", 0) * 1e6,
                "dur": d["block_s"] * 1e6,
                "args": d.get("meta", {}),
            })
    return events


# counter tracks denser than this are strided down — Perfetto renders a
# multi-thousand-point counter no better, and the trace file stays small
MAX_COUNTER_POINTS = 2000


def chrome_counter_events(
    counter_series: dict, spans: Iterable, pid: int = 1,
    max_points: int = MAX_COUNTER_POINTS,
) -> List[dict]:
    """Per-event series -> Chrome counter-track events (`"ph": "C"`), so
    the timeline shows fragmentation/allocation evolving UNDER the phase
    spans. `counter_series` maps track name -> one value per event (the
    frag/alloc series the metrics postpass already computes,
    sim/metrics.compute_event_metrics). Events carry no wall timestamps
    — the scan spans do — so the E points are laid out linearly across
    the union of the `scan` spans' wall window (falling back to the full
    span window), which is exactly the stretch of the timeline the
    events executed in."""
    spans = list(spans)
    dicts = [s.to_dict() if hasattr(s, "to_dict") else dict(s) for s in spans]
    windows = [d for d in dicts if d.get("name") == "scan"] or dicts
    if windows:
        t0 = min(d["start_s"] for d in windows) * 1e6
        t1 = max(d["start_s"] + d.get("total_s", 0) for d in windows) * 1e6
    else:
        t0, t1 = 0.0, 1e6
    events: List[dict] = []
    for track, values in sorted(counter_series.items()):
        values = list(values)
        n = len(values)
        if not n:
            continue
        stride = max(1, -(-n // max_points))
        idx = list(range(0, n, stride))
        if idx[-1] != n - 1:
            idx.append(n - 1)  # always chart the final value
        span_us = max(t1 - t0, 1.0)
        for i in idx:
            ts = t0 + span_us * (i / max(n - 1, 1))
            events.append({
                "pid": pid, "tid": 0, "ph": "C", "cat": "tpusim",
                "name": track, "ts": ts, "args": {track: values[i]},
            })
    return events


def write_chrome_trace(path: str, spans: Iterable,
                       counter_series: dict = None) -> str:
    spans = list(spans)
    events = chrome_trace_events(spans)
    if counter_series:
        events.extend(chrome_counter_events(counter_series, spans))
    _atomic_write(
        path,
        json.dumps({"traceEvents": events, "displayTimeUnit": "ms"}),
    )
    return path


def emit_all(telemetry, jsonl: str = "", metrics: str = "", trace: str = "",
             meta: dict = None, counter_series: dict = None) -> List[str]:
    """Write every requested emitter output for one RunTelemetry; returns
    the paths written. `counter_series` (track name -> per-event values,
    e.g. Simulator.event_counter_series()) adds counter tracks to the
    Chrome trace."""
    record = telemetry.to_record()
    if meta:
        record["deterministic"]["meta"].update(meta)
    written = []
    if jsonl:
        written.append(append_jsonl(jsonl, record))
    if metrics:
        written.append(write_prometheus(metrics, record))
    if trace:
        written.append(write_chrome_trace(trace, telemetry.spans,
                                          counter_series))
    return written
