"""Phase spans + the run recorder — the timing half of obs.

A Span is one named phase of an experiment (trace_load, typical_pods,
init_tables, scan, fetch, metrics_postpass, report, ...) with a
dispatch/block wall split: under JAX's async dispatch, the host returns
from a jitted call once tracing + compilation + enqueue are done and the
device work completes later, so

    dispatch_s  host wall until the call returned — on a COLD call this
                is dominated by trace + XLA compile; on a warm call it is
                the executable-cache lookup + argument transfer
    block_s     wall spent waiting for the device result (the execute
                half). Only attributed when the recorder is enabled
                (profiling mode blocks on the phase result); an
                un-profiled run never adds sync points, so its spans
                carry dispatch walls only.

That is the compile-vs-execute split the JSONL record reports: the first
scan span of a config shows compile in dispatch_s, every later one shows
~0 dispatch + pure execute in block_s.

The Recorder accumulates spans, host counters (degrades, cache hits,
disruption totals), and the engines' in-scan counter vectors
(obs.counters) across every replay a Simulator runs — fault runs note
one scan per segment and the vectors sum. RunTelemetry is the snapshot
the driver attaches to SimulateResult; its to_record() splits the JSONL
payload into a `deterministic` block (bit-identical across same-seed
runs and across kill/resume — the acceptance contract tests pin) and a
`timing` block (machine-dependent walls).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from tpusim.obs.counters import (
    NUM_COUNTERS,
    counters_to_dict,
)

SCHEMA = "tpusim-obs-v1"


@dataclass
class Span:
    name: str
    start_s: float  # relative to the recorder epoch
    dispatch_s: float  # host wall until dispatch returned (compile on cold)
    block_s: float  # wall waiting on the device result (execute); 0 = unknown
    meta: Dict[str, object] = field(default_factory=dict)

    @property
    def total_s(self) -> float:
        return self.dispatch_s + self.block_s

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "start_s": round(self.start_s, 6),
            "dispatch_s": round(self.dispatch_s, 6),
            "block_s": round(self.block_s, 6),
            "total_s": round(self.total_s, 6),
        }
        if self.meta:
            d["meta"] = self.meta
        return d


class _SpanHandle:
    """Yielded by Recorder.span(); call .dispatched() the moment the
    device call returns to split compile/dispatch from execute/block."""

    __slots__ = ("_t0", "_t_dispatch")

    def __init__(self, t0: float):
        self._t0 = t0
        self._t_dispatch = None

    def dispatched(self):
        if self._t_dispatch is None:
            self._t_dispatch = time.perf_counter()


class Recorder:
    """Per-Simulator telemetry accumulator. Always cheap to keep on (a
    span is two perf_counter calls); `enabled` additionally makes the
    driver block on phase results for the compile/execute attribution
    and is what --profile turns on."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self.reset()

    def reset(self):
        self.epoch = time.perf_counter()
        self.spans: List[Span] = []
        self.counts: Dict[str, int] = {}
        self.scan_counters = np.zeros(NUM_COUNTERS, np.int64)
        self._pending_scans: List[tuple] = []  # (device ctr array, pad_skips)
        self.scan_events = 0
        self.engines: List[str] = []
        self.disruption: Dict[str, int] = {}
        self.table_cache = "off"  # off | miss | hit
        # fused-Pallas table residency the last pallas dispatch ran
        # under (ENGINES.md Round 19): off | vmem | hbm — set by the
        # driver's residency select; lands in the run record's
        # deterministic block beside table_cache
        self.pallas_residency = "off"
        # persistent-compilation-cache note (ISSUE 6 satellite): set by
        # note_compile_cache after the run; None = never assessed
        self.compile_cache: Optional[dict] = None

    @contextmanager
    def span(self, name: str, **meta):
        t0 = time.perf_counter()
        h = _SpanHandle(t0)
        try:
            yield h
        finally:
            t1 = time.perf_counter()
            td = h._t_dispatch if h._t_dispatch is not None else t1
            self.spans.append(Span(
                name=name,
                start_s=t0 - self.epoch,
                dispatch_s=td - t0,
                block_s=t1 - td,
                meta=meta,
            ))

    def count(self, name: str, n: int = 1):
        self.counts[name] = self.counts.get(name, 0) + n

    def note_scan(self, engine: str, counters=None, pad_skips: int = 0,
                  events: int = 0):
        """Record one replay dispatch: which engine ran, how many true
        (un-padded) events, and its in-scan counter vector. The device
        array is stashed un-materialized — np.asarray would force a sync
        mid-pipeline — and folded in lazily at snapshot()."""
        self.engines.append(engine)
        self.scan_events += int(events)
        if counters is not None:
            self._pending_scans.append((counters, int(pad_skips)))

    def note_disruption(self, dm):
        """Fold a DisruptionMetrics into machine-readable counters (the
        [Disruption] log block's obs twin)."""
        self.disruption = {
            "node_failures": int(dm.node_failures),
            "node_recoveries": int(dm.node_recoveries),
            "evicted_pods": int(dm.evicted_pods),
            "rescheduled_pods": int(dm.rescheduled_pods),
            "retries_enqueued": int(dm.retries_enqueued),
            "unscheduled_after_retries": int(dm.unscheduled_after_retries),
        }

    def _drain_pending(self):
        for ctr, pad in self._pending_scans:
            vals = np.asarray(ctr).astype(np.int64).copy()
            vals[4] = max(int(vals[4]) - pad, 0)  # drop bucket-padding skips
            self.scan_counters += vals
        self._pending_scans = []

    def snapshot(self, meta: Optional[dict] = None) -> "RunTelemetry":
        self._drain_pending()
        return RunTelemetry(
            spans=list(self.spans),
            counters=counters_to_dict(self.scan_counters),
            counts=dict(self.counts),
            disruption=dict(self.disruption),
            engines=list(self.engines),
            events=self.scan_events,
            table_cache=self.table_cache,
            pallas_residency=self.pallas_residency,
            meta=dict(meta or {}),
            compile_cache=(
                dict(self.compile_cache) if self.compile_cache else None
            ),
        )


def note_compile_cache(recorder: Recorder, enabled: bool, cache_dir: str = "",
                       hit_threshold_s: float = 2.0) -> dict:
    """Stamp the run's persistent-compilation-cache outcome onto the
    recorder (ISSUE 6 satellite). The verdict is a DISPATCH-WALL
    HEURISTIC, not ground truth: jax exposes no portable per-executable
    hit signal, but a cold scan compile costs several seconds of
    dispatch wall while a persistent-cache load costs well under the
    threshold — so `probable_hit` = (cache enabled AND the first scan
    span's dispatch wall stayed under hit_threshold_s). Lands in the
    run record's `timing` block (machine-dependent walls, never the
    deterministic block)."""
    scans = [s for s in recorder.spans if s.name == "scan"]
    first = scans[0] if scans else None
    info = {
        "enabled": bool(enabled),
        "dir": cache_dir,
        "first_scan_dispatch_s": (
            round(first.dispatch_s, 6) if first is not None else None
        ),
        "probable_hit": bool(
            enabled and first is not None
            and first.dispatch_s < hit_threshold_s
        ),
    }
    recorder.compile_cache = info
    return info


@dataclass
class RunTelemetry:
    """One run's telemetry: the object SimulateResult.telemetry carries
    and the JSONL emitter serializes."""

    spans: List[Span]
    counters: Dict[str, int]  # in-scan counters (obs.counters vocabulary)
    counts: Dict[str, int]  # host-side counters (degrades, cache, retries)
    disruption: Dict[str, int]
    engines: List[str]
    events: int
    table_cache: str
    meta: Dict[str, object]
    # fused-Pallas residency tier of this run's pallas dispatches
    # (off | vmem | hbm) — deterministic, like table_cache
    pallas_residency: str = "off"
    # persistent-compilation-cache note (note_compile_cache): enabled /
    # dir / first-scan dispatch wall / probable_hit heuristic. None when
    # never assessed; machine-dependent, so it reports under `timing`.
    compile_cache: Optional[dict] = None

    def to_record(self) -> dict:
        """The JSONL run record. `deterministic` is bit-identical across
        same-seed runs and kill/resume (integer counters + config only);
        `timing` carries the machine-dependent walls."""
        return {
            "schema": SCHEMA,
            "deterministic": {
                "events": self.events,
                "counters": self.counters,
                "degrades": {
                    k: v for k, v in sorted(self.counts.items())
                    if k.startswith("degrade_")
                },
                "counts": {
                    k: v for k, v in sorted(self.counts.items())
                    if not k.startswith("degrade_")
                },
                "disruption": self.disruption,
                "engines": self.engines,
                "table_cache": self.table_cache,
                "pallas_residency": self.pallas_residency,
                "meta": self.meta,
            },
            "timing": {
                "spans": [s.to_dict() for s in self.spans],
                "wall_s": round(
                    max((s.start_s + s.total_s for s in self.spans),
                        default=0.0),
                    6,
                ),
                **(
                    {"compile_cache": self.compile_cache}
                    if self.compile_cache is not None else {}
                ),
            },
        }
