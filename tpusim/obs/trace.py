"""Cross-process job tracing — the fleet flight recorder (ISSUE 19).

The serving plane is a multi-host fleet (PR 12-17): a job's journey runs
`tpusim submit` → coordinator admission → queue wait → worker claim →
trace transfer → compile/dispatch/block → result upload → verify, and
may cross a kill -9 failover or an orphan steal on the way. No single
process sees the whole journey, so no single run record can tell it.
This module makes the journey reconstructable from the artifact dir
alone:

  trace id      minted once per submit (client-side when possible,
                coordinator-side otherwise) and propagated as the
                `X-Tpusim-Trace` HTTP header on EVERY fleet hop —
                /jobs, /workers/claim, /leases, /results upload,
                /workers/complete, and the re-register after an epoch
                bump — so every process tags its spans with the same id
                without any shared state beyond the header.
  SpanRecorder  one per process, appending spans to
                `<artifact_dir>/spans/<process>.spans.jsonl`. Each span
                is TWO records — `begin` at open, `end` at close — so a
                kill -9 mid-span leaves a begin with no end, which the
                stitcher renders as an ABANDONED span (the visible
                corpse of a stolen attempt), never a silent gap. Every
                record is digest-signed (`sig` = sha256 over the rest,
                the io.storage discipline applied per-line because the
                file is append-only), so an edited span fails loudly on
                read while a torn tail line (the killed writer) is
                skipped and reported, not fatal.
  stitch()      `tpusim trace <job-digest>` merges every per-process
                file into one timeline — terminal text plus a
                Chrome-trace export with one track (pid) per process.

Span names reuse the obs.spans phase vocabulary where the phases
coincide (`scan`-like dispatch spans carry the dispatch_s/block_s wall
split in their meta) and add the fleet hops: admit, queue_wait, claim,
trace_transfer, dispatch, upload, verify.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from typing import Dict, List, Optional

TRACE_HEADER = "X-Tpusim-Trace"
SPANS_DIRNAME = "spans"
SPANS_SUFFIX = ".spans.jsonl"
SCHEMA = "tpusim-trace-v1"

# fleet-hop span vocabulary (ENGINES.md Round 22) — the stitcher accepts
# any name, but emitters stick to these so timelines read uniformly
SPAN_ADMIT = "admit"
SPAN_QUEUE_WAIT = "queue_wait"
SPAN_CLAIM = "claim"
SPAN_TRANSFER = "trace_transfer"
SPAN_DISPATCH = "dispatch"
SPAN_UPLOAD = "upload"
SPAN_VERIFY = "verify"


def new_trace_id() -> str:
    """16 hex chars of OS entropy — unique per submit, cheap to log."""
    return os.urandom(8).hex()


def header_trace(headers) -> str:
    """The trace id off a request's header map ('' when absent). Accepts
    email.message.Message (the stdlib server's header object) or any
    mapping with case-sensitive keys."""
    if headers is None:
        return ""
    get = getattr(headers, "get", None)
    if get is None:
        return ""
    val = get(TRACE_HEADER) or get(TRACE_HEADER.lower()) or ""
    return str(val).strip()


def _sign(doc: dict) -> dict:
    """Return doc + `sig` = sha256 over its canonical JSON — the
    per-line integrity key of an append-only span file."""
    body = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    out = dict(doc)
    out["sig"] = hashlib.sha256(body.encode()).hexdigest()
    return out


def _check_sig(doc: dict) -> bool:
    sig = doc.get("sig")
    body = {k: v for k, v in doc.items() if k != "sig"}
    raw = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return sig == hashlib.sha256(raw.encode()).hexdigest()


_PROC_SAFE = re.compile(r"[^A-Za-z0-9._-]+")


def _safe_process(process: str) -> str:
    """Process name → filesystem-safe file stem (worker ids carry
    host:pid colons)."""
    return _PROC_SAFE.sub("_", str(process)) or "proc"


class SpanRecorder:
    """Per-process span appender. Thread-safe; every append is one
    O_APPEND write of a signed JSON line, so concurrent emitters in one
    process interleave whole lines and a kill -9 loses at most the line
    in flight (reported as torn by the reader, never misread)."""

    def __init__(self, artifact_dir: str, process: str):
        self.process = str(process)
        self.path = os.path.join(
            artifact_dir, SPANS_DIRNAME,
            _safe_process(process) + SPANS_SUFFIX,
        )
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self._lock = threading.Lock()
        self._seq = 0

    def _append(self, doc: dict):
        line = json.dumps(
            _sign(doc), sort_keys=True, separators=(",", ":")
        )
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")

    def _next_id(self) -> str:
        with self._lock:
            self._seq += 1
            return f"{os.getpid():x}-{self._seq:x}"

    def begin(self, name: str, job: str = "", trace: str = "",
              t: Optional[float] = None, **meta) -> str:
        """Open a span; returns the span id `end()` closes. `t` lets a
        reconstructing emitter backdate the start (the coordinator's
        queue_wait span opens at the job's submit stamp)."""
        span_id = self._next_id()
        self._append({
            "schema": SCHEMA, "ev": "begin", "span": span_id,
            "name": str(name), "job": str(job), "trace": str(trace),
            "proc": self.process, "pid": os.getpid(),
            "t": round(float(time.time() if t is None else t), 6),
            **({"meta": meta} if meta else {}),
        })
        return span_id

    def end(self, span_id: str, t: Optional[float] = None, **meta):
        self._append({
            "schema": SCHEMA, "ev": "end", "span": str(span_id),
            "proc": self.process, "pid": os.getpid(),
            "t": round(float(time.time() if t is None else t), 6),
            **({"meta": meta} if meta else {}),
        })

    def span(self, name: str, job: str = "", trace: str = "", **meta):
        """Context-manager form; the yielded handle's .meta dict is
        folded into the end record."""
        return _SpanCtx(self, name, job, trace, meta)

    def emit(self, name: str, start: float, end: float, job: str = "",
             trace: str = "", **meta):
        """One closed span with explicit absolute walls — the
        reconstructed-phase form (queue_wait at claim time)."""
        sid = self.begin(name, job=job, trace=trace, t=start, **meta)
        self.end(sid, t=end)


class _SpanCtx:
    def __init__(self, rec: SpanRecorder, name, job, trace, meta):
        self._rec = rec
        self._args = (name, job, trace, meta)
        self.meta: Dict[str, object] = {}
        self._id = None

    def __enter__(self):
        name, job, trace, meta = self._args
        self._id = self._rec.begin(name, job=job, trace=trace, **meta)
        return self

    def __exit__(self, exc_type, exc, tb):
        end_meta = dict(self.meta)
        if exc_type is not None:
            end_meta["error"] = exc_type.__name__
        self._rec.end(self._id, **end_meta)
        return False


# ---------------------------------------------------------------------------
# Stitching — the read side of `tpusim trace`
# ---------------------------------------------------------------------------


def read_span_file(path: str):
    """(records, problems) of one span file. A record with a bad
    signature or a torn line is reported in `problems` and skipped —
    the reader must survive the files a kill -9 leaves behind, but
    never silently accept an edited one."""
    records, problems = [], []
    with open(path) as f:
        for i, raw in enumerate(f):
            line = raw.rstrip("\n")
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                problems.append(f"{path}:{i + 1}: torn record (skipped)")
                continue
            if not isinstance(doc, dict) or not _check_sig(doc):
                problems.append(
                    f"{path}:{i + 1}: signature mismatch (edited?)"
                )
                continue
            records.append(doc)
    return records, problems


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


def stitch(artifact_dir: str, job: str = "", trace: str = ""):
    """Merge every per-process span file under `artifact_dir` into one
    list of stitched spans, optionally filtered by job digest (prefix
    match, the CLI convenience) and/or trace id. Returns (spans,
    problems); each span is a dict:

      name/job/trace/proc/pid/start/end/meta
      status   ok         begin + end paired
               abandoned  begin with no end — the process died (or is
                          still mid-phase); the stolen attempt's corpse
               orphan     end with no begin — file damage, never
                          expected (the smoke gates on zero of these)

    Abandoned spans report end = the file's last-seen timestamp for
    that process (duration = what the recorder witnessed), never a
    fabricated completion."""
    spans_dir = os.path.join(artifact_dir, SPANS_DIRNAME)
    out: List[dict] = []
    problems: List[str] = []
    if not os.path.isdir(spans_dir):
        return out, problems
    for fname in sorted(os.listdir(spans_dir)):
        if not fname.endswith(SPANS_SUFFIX):
            continue
        records, probs = read_span_file(os.path.join(spans_dir, fname))
        problems.extend(probs)
        open_spans: Dict[str, dict] = {}
        last_t = 0.0
        for doc in records:
            last_t = max(last_t, float(doc.get("t") or 0.0))
            key = str(doc.get("span"))
            if doc.get("ev") == "begin":
                open_spans[key] = doc
            elif doc.get("ev") == "end":
                begin = open_spans.pop(key, None)
                if begin is None:
                    out.append({
                        "name": "?", "job": "", "trace": "",
                        "proc": doc.get("proc", fname),
                        "pid": int(doc.get("pid") or 0),
                        "start": float(doc.get("t") or 0.0),
                        "end": float(doc.get("t") or 0.0),
                        "meta": dict(doc.get("meta") or {}),
                        "status": "orphan",
                    })
                    continue
                meta = dict(begin.get("meta") or {})
                meta.update(doc.get("meta") or {})
                out.append({
                    "name": begin.get("name", "?"),
                    "job": begin.get("job", ""),
                    "trace": begin.get("trace", ""),
                    "proc": begin.get("proc", fname),
                    "pid": int(begin.get("pid") or 0),
                    "start": float(begin.get("t") or 0.0),
                    "end": float(doc.get("t") or 0.0),
                    "meta": meta,
                    "status": "ok",
                })
        for begin in open_spans.values():
            pid = int(begin.get("pid") or 0)
            out.append({
                "name": begin.get("name", "?"),
                "job": begin.get("job", ""),
                "trace": begin.get("trace", ""),
                "proc": begin.get("proc", fname),
                "pid": pid,
                "start": float(begin.get("t") or 0.0),
                "end": max(last_t, float(begin.get("t") or 0.0)),
                "meta": dict(begin.get("meta") or {}),
                "status": (
                    "abandoned" if not _pid_alive(pid) else "open"
                ),
            })
    if job:
        out = [s for s in out
               if s["job"] == job or s["job"].startswith(job)]
    if trace:
        out = [s for s in out if s["trace"] == trace]
    out.sort(key=lambda s: (s["start"], s["proc"], s["name"]))
    return out, problems


def format_timeline(spans, out_lines: Optional[List[str]] = None):
    """Terminal rendering: one line per span, grouped nothing — sorted
    by start with a per-process column, offsets relative to the first
    span. The abandoned attempt reads as `ABANDONED`, not a gap."""
    lines = out_lines if out_lines is not None else []
    if not spans:
        lines.append("(no spans)")
        return lines
    t0 = min(s["start"] for s in spans)
    procs = []
    for s in spans:
        if s["proc"] not in procs:
            procs.append(s["proc"])
    lines.append(
        f"{len(spans)} spans across {len(procs)} processes "
        f"({', '.join(procs)})"
    )
    for s in spans:
        dur = max(s["end"] - s["start"], 0.0)
        status = "" if s["status"] == "ok" else f"  [{s['status'].upper()}]"
        extra = ""
        meta = s.get("meta") or {}
        if "dispatch_s" in meta:
            extra = (f"  dispatch={meta['dispatch_s']:.3f}s"
                     if isinstance(meta["dispatch_s"], (int, float))
                     else "")
        lines.append(
            f"  +{s['start'] - t0:8.3f}s  {dur:8.3f}s  "
            f"{s['proc']:<24} {s['name']:<14}"
            f"{extra}{status}"
        )
    return lines


def chrome_trace(spans) -> dict:
    """Chrome-trace document: one pid (track) per process, `X` duration
    events in microseconds, `M` process_name metadata rows — load in
    chrome://tracing or Perfetto. Abandoned/orphan spans carry their
    status in args so they render inspectable, not invisible."""
    procs: Dict[str, int] = {}
    events: List[dict] = []
    t0 = min((s["start"] for s in spans), default=0.0)
    for s in spans:
        pid = procs.setdefault(s["proc"], len(procs) + 1)
        args = {"job": s["job"], "trace": s["trace"],
                "status": s["status"], **(s.get("meta") or {})}
        events.append({
            "name": s["name"] + (
                "" if s["status"] == "ok" else f" [{s['status']}]"
            ),
            "ph": "X", "pid": pid, "tid": 1,
            "ts": round((s["start"] - t0) * 1e6, 3),
            "dur": round(max(s["end"] - s["start"], 0.0) * 1e6, 3),
            "args": args,
        })
    for proc, pid in procs.items():
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 1,
            "args": {"name": proc},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
