"""Heartbeat progress for long compiled scans.

A 100k-node replay is one lax.scan that can run for minutes with zero
host output. When `SimulatorConfig.heartbeat_every > 0` (or bench_scale
--heartbeat), the table engine's scan body calls back to the host every
N processed events via a jax.debug.callback (the io_callback family —
unordered, safe inside lax.cond/scan and a no-op under tracing), and
this module turns those ticks into `events/s + ETA` lines on stderr.

The device side only ships the processed-event count; everything rate-
or time-shaped lives here on the host, so the heartbeat cannot perturb
the replay trajectory (pure side output). Ticks are rate-limited to one
line per MIN_INTERVAL_S of wall time — a warm small run stays silent-ish
no matter how small `every` is — but the driver always fires a final
100% tick (complete(): total wall + mean ev/s) when the scan's result
lands, so even a run that finished inside the rate limit reports once.
"""

from __future__ import annotations

import sys
import time
from typing import Optional

# module-level host state: one scan is in flight per process at a time
# (the driver replays serially); configure() re-arms it per dispatch
_STATE = {
    "total": 0,
    "label": "",
    "t0": 0.0,
    "last_emit": 0.0,
    "ticks": 0,
    "sink": None,  # test hook: callable(line) instead of stderr
    # run-level event window (ISSUE 5): `base` is added to the device's
    # raw processed count (fault segments restart their scan counter at 0
    # but sit `base` events into the run); `resumed` is the count already
    # inside the raw number that THIS process never executed (a
    # checkpoint-resumed carry) — subtracted from the rate so a resumed
    # run's ev/s and ETA describe real progress, not cursor/dt
    "base": 0,
    "resumed": 0,
    # run/job id the armed scan's ticks carry (ISSUE 7): with queued
    # what-if jobs sharing one process, the global listener would
    # otherwise interleave consecutive scans' ticks into one anonymous
    # stream — listeners key per-job progress off this tag instead
    "job": "",
    # worker id (ISSUE 12): in a fleet, ticks additionally say WHICH
    # worker's scan is progressing, and the lease keeper treats any
    # tick as proof of life (renew-on-heartbeat)
    "worker": "",
}

MIN_INTERVAL_S = 1.0

# progress listeners (tpusim.obs.server feeds /progress from these):
# called on EVERY tick — including rate-limited ones — with a dict
# {done, total, rate, eta, label, final}. Must be cheap and non-raising.
_LISTENERS = []


def add_listener(fn):
    if fn not in _LISTENERS:
        _LISTENERS.append(fn)


def remove_listener(fn):
    if fn in _LISTENERS:
        _LISTENERS.remove(fn)


def _notify(done: int, total: int, rate: float, eta: float,
            final: bool = False):
    info = {
        "done": int(done), "total": int(total), "rate": float(rate),
        "eta": float(eta), "label": _STATE["label"], "final": bool(final),
        "job": _STATE["job"], "worker": _STATE["worker"],
    }
    for fn in list(_LISTENERS):
        try:
            fn(info)
        except Exception:  # a broken listener must never kill a replay
            pass


def configure(total_events: int, label: str = "scan", sink=None,
              base: int = 0, job: str = "", worker: str = ""):
    """Arm the heartbeat for the next scan: total event count for the ETA
    and a label for the line. Called by the driver right before each
    dispatch whose engine was built with a heartbeat. `base` = events of
    the RUN already replayed by earlier scans (the fault path's segment
    offset), so chunk/segment ticks report run-level progress. `job` tags
    every tick of this scan with a run/job id (ISSUE 7) so listeners
    serving several queued jobs from one process can keep their progress
    streams apart; empty keeps the anonymous single-run behavior.
    `worker` additionally tags the ticks with the serving worker's id
    (ISSUE 12 — the fleet's /progress and lease-renewal surfaces)."""
    _STATE.update(
        total=int(total_events), label=label, t0=time.perf_counter(),
        last_emit=0.0, ticks=0, sink=sink, base=int(base), resumed=0,
        job=str(job or ""), worker=str(worker or ""),
    )


def note_resume(done0: int):
    """Mark the armed scan as resumed from a checkpoint at `done0`
    processed events: the carry's counter already includes them, so the
    rate denominator must not credit this process with their work."""
    _STATE["resumed"] = int(done0)


def tick(done):
    """Host callback the scan body fires every `heartbeat_every` events
    (jax.debug.callback target — receives the device-side processed-event
    count)."""
    now = time.perf_counter()
    _STATE["ticks"] += 1
    done = _STATE["base"] + int(done)
    total = _STATE["total"]
    dt = max(now - _STATE["t0"], 1e-9)
    fresh = max(done - _STATE["base"] - _STATE["resumed"], 0)
    rate = fresh / dt
    eta = (total - done) / rate if (total > done and rate > 0) else 0.0
    _notify(done, total, rate, eta)
    if now - _STATE["last_emit"] < MIN_INTERVAL_S:
        return
    _STATE["last_emit"] = now
    line = (
        f"[obs] {_STATE['label']}: {done}/{total or '?'} events "
        f"({rate:,.0f} ev/s, eta {eta:,.0f}s)"
    )
    sink = _STATE["sink"]
    if sink is not None:
        sink(line)
    else:
        print(line, file=sys.stderr, flush=True)


def tick_count() -> int:
    """Ticks received since the last configure() (test hook)."""
    return _STATE["ticks"]


def complete(true_total: int = 0):
    """Final 100% tick, emitted by the driver when the scan's result is
    ready: total wall and MEAN events/s over the whole scan, bypassing
    the rate limit — so a short run that finished inside MIN_INTERVAL_S
    (and therefore never printed a periodic tick) still reports one
    line. `true_total` is the PRE-padding event count: the heartbeat is
    armed with the bucket-padded stream size (what the scan body can
    count), but the pad EV_SKIPs are near-free, so reporting them would
    overstate both the total and the mean ev/s of a small run. Disarms
    the heartbeat afterwards; a second call (or a call with nothing
    armed) is a no-op."""
    total = _STATE["total"]
    if not total:
        return
    base = _STATE["base"]
    if true_total:
        # `true_total` is the SCAN's pre-padding event count, but the
        # armed total is run-level (base + this scan's padded events) —
        # clamp on the same clock, or a fault segment's final tick would
        # jump the /progress counter backwards to segment-local numbers
        total = min(total, base + int(true_total))
    now = time.perf_counter()
    dt = max(now - _STATE["t0"], 1e-9)
    # mean rate over the events THIS process actually executed in this
    # scan — the base/resumed discipline of tick()
    fresh = max(total - base - _STATE["resumed"], 0)
    line = (
        f"[obs] {_STATE['label']}: {total}/{total} events done in "
        f"{dt:,.1f}s ({fresh / dt:,.0f} ev/s mean)"
    )
    _STATE["ticks"] += 1
    _STATE["last_emit"] = now
    _STATE["total"] = 0  # disarm
    _notify(total, total, fresh / dt, 0.0, final=True)
    sink = _STATE["sink"]
    if sink is not None:
        sink(line)
    else:
        print(line, file=sys.stderr, flush=True)


def emit_from_scan(processed, every: int):
    """The device-side hook engines inline into their scan body: fire the
    host tick when the processed-event count crosses a multiple of
    `every`. `every` is static (baked into the jaxpr — part of the engine
    cache key); `processed` is the carry's counter-derived event count.
    Adds one scalar cond per event — below the measurement noise floor
    at the bench-scale-smoke shape (ENGINES.md Round 8)."""
    import jax

    if not every:
        return
    jax.lax.cond(
        (processed % every) == 0,
        lambda: jax.debug.callback(tick, processed),
        lambda: None,
    )
