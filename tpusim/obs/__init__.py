"""tpusim.obs — run telemetry, profiling, and bench regression gating.

The observability plane the replay engines report through (ISSUE 3):

  counters   exact in-scan event counters riding the engines' lax.scan
             carries — bit-reproducible, checkpoint/fault-transparent
  decisions  per-event decision provenance (ISSUE 4): winner, per-policy
             score contributions, top-K runner-ups, tie-break ranks —
             engine-invariant, JSONL-persisted, behind `tpusim
             explain`/`diff`
  spans      phase timers with a dispatch(compile)/block(execute) wall
             split; Recorder/RunTelemetry accumulate them per run
  heartbeat  jax.debug.callback progress ticks from inside long scans
  emitters   JSONL run records, Prometheus textfiles, Chrome traces
             (incl. frag/alloc counter tracks)
  bench      the shared cold+warm-minimum timing protocol + JSON writer
             the bench scripts build on
  gate       `python -m tpusim.obs.gate` — smoke profile diffed against
             the committed BENCH_r*.json baselines

Layering: obs imports nothing from sim/ (engines and the driver import
obs, never the reverse), so it can sit under every engine's scan body.
"""

from tpusim.obs.counters import (  # noqa: F401
    COUNTER_FIELDS,
    INVARIANT_FIELDS,
    NUM_COUNTERS,
    counter_delta,
    counters_from_telemetry,
    counters_to_dict,
    zero_counters,
)
from tpusim.obs.decisions import (  # noqa: F401
    DECISION_SCHEMA,
    DECISION_TOPK,
    DecisionLog,
    DecisionRecord,
)
from tpusim.obs.spans import (  # noqa: F401
    SCHEMA,
    Recorder,
    RunTelemetry,
    Span,
)
