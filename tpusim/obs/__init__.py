"""tpusim.obs — run telemetry, profiling, and bench regression gating.

The observability plane the replay engines report through (ISSUE 3):

  counters   exact in-scan event counters riding the engines' lax.scan
             carries — bit-reproducible, checkpoint/fault-transparent
  decisions  per-event decision provenance (ISSUE 4): winner, per-policy
             score contributions, top-K runner-ups, tie-break ranks —
             engine-invariant, JSONL-persisted, behind `tpusim
             explain`/`diff`
  series     in-scan cluster time-series plane (ISSUE 5): fixed-stride
             utilization/frag/score-distribution samples emitted by the
             scan — engine-invariant, checkpoint/fault-continuous,
             rendered by `tpusim report` and the analysis plotter
  server     live monitoring endpoint (ISSUE 5): /metrics, /healthz,
             /progress over stdlib-threaded HTTP — in-process via
             `apply --listen`, standalone via `tpusim serve DIR`
  spans      phase timers with a dispatch(compile)/block(execute) wall
             split; Recorder/RunTelemetry accumulate them per run
  heartbeat  jax.debug.callback progress ticks from inside long scans
             (+ the listener hook /progress feeds from)
  emitters   JSONL run records, Prometheus textfiles, Chrome traces
             (incl. frag/alloc + series counter tracks)
  bench      the shared cold+warm-minimum timing protocol + JSON writer
             the bench scripts build on
  gate       `python -m tpusim.obs.gate` — smoke profile diffed against
             the committed BENCH_r*.json baselines

Layering: obs imports nothing from sim/ (engines and the driver import
obs, never the reverse), so it can sit under every engine's scan body.
"""

from tpusim.obs.counters import (  # noqa: F401
    COUNTER_FIELDS,
    INVARIANT_FIELDS,
    NUM_COUNTERS,
    counter_delta,
    counters_from_telemetry,
    counters_to_dict,
    zero_counters,
)
from tpusim.obs.decisions import (  # noqa: F401
    DECISION_SCHEMA,
    DECISION_TOPK,
    DecisionLog,
    DecisionRecord,
)
from tpusim.obs.series import (  # noqa: F401
    FRAG_CATEGORY_NAMES,
    SERIES_SCHEMA,
    UTIL_BUCKETS,
    SeriesLog,
    SeriesSample,
)
from tpusim.obs.spans import (  # noqa: F401
    SCHEMA,
    Recorder,
    RunTelemetry,
    Span,
    note_compile_cache,
)
