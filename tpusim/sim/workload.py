"""Workload transformations: sort/shuffle, tuning, inflation pods
(ref: pkg/simulator/simulator.go:975-1013 SortClusterPods, :1200-1282
TunePodsByNodeTotalResource, :1015-1132 RunWorkloadInflationEvaluation).

Host-side list manipulation over PodRow; RNG parity is distribution-level
(numpy Generator seeded from the config seed vs Go's global math/rand,
SURVEY.md §7.3 "RNG parity").
"""

from __future__ import annotations

import copy
from dataclasses import replace
from typing import List, Sequence

import numpy as np

from tpusim.io.trace import PodRow


def sort_cluster_pods(pods: List[PodRow], shuffle: bool, rng: np.random.Generator):
    """shuffle=True: name-sort then random shuffle; else stable sort by
    creation time with name tie-break (ref: simulator.go:975-1013; pods
    without a creation annotation all collapse to 'now' i.e. keep order —
    our trace rows always carry creation_time, matching the annotated path).
    """
    if shuffle:
        pods.sort(key=lambda p: p.name)
        rng.shuffle(pods)
    else:
        pods.sort(key=lambda p: (p.creation_time, p.name))
    return pods


def total_pod_gpu_milli(pods: Sequence[PodRow]) -> int:
    return sum(p.total_gpu_milli for p in pods)


def total_pod_cpu_milli(pods: Sequence[PodRow]) -> int:
    return sum(p.cpu_milli for p in pods)


def tune_pods(
    pods: List[PodRow],
    node_total_milli_gpu: int,
    ratio: float,
    rng: np.random.Generator,
) -> List[PodRow]:
    """Prune or clone-append random pods until total GPU request ≈
    ratio × cluster GPU capacity (ref: simulator.go:1200-1282).

    tuneUp preserves the reference's stopping rule bug-for-bug: the break
    test adds the candidate's *per-GPU* milli, while the accumulator adds its
    *total* milli (simulator.go:1271-1276).
    """
    if ratio <= 0:
        return pods
    total = total_pod_gpu_milli(pods)
    tgt = ratio * node_total_milli_gpu
    if total == tgt:
        return pods
    if total > tgt:
        pods = list(pods)
        while total > tgt:
            if not pods:
                raise RuntimeError("empty pod list while tuning down")
            idx = int(rng.integers(len(pods)))
            total -= pods[idx].total_gpu_milli
            pods.pop(idx)
        return pods
    # tune up: clone uniform-random pods from the original workload,
    # appended at the end (they schedule after the originals).
    src = list(pods)
    out = list(pods)
    i = 0
    while True:
        idx = int(rng.integers(len(src)))
        cand = src[idx]
        if total + cand.gpu_milli > tgt:
            break
        clone = replace(cand, name=f"{cand.name}-tuned-{i}")
        total += clone.total_gpu_milli
        out.append(clone)
        i += 1
    return out


def inflation_pods(
    workload: Sequence[PodRow],
    ratio: float,
    rng: np.random.Generator,
    cluster_cpu_milli: int,
    cluster_gpu_milli: int,
    current_cpu_milli: int,
    current_gpu_milli: int,
) -> List[PodRow]:
    """Extra cloned pods for inflation evaluation
    (ref: simulator.go:1039-1132 generateWorkloadInflationPods): clone
    ceil(n×ratio)−n random workload pods, stopping early — break, not skip
    (simulator.go:1063-1070) — at the first clone that would push the running
    request totals past cluster capacity."""
    if ratio <= 1.0 or not workload:
        return []
    n = len(workload)
    extra = int(np.ceil(n * ratio)) - n
    out: List[PodRow] = []
    cpu, gpu = current_cpu_milli, current_gpu_milli
    for i in range(extra):
        idx = int(rng.integers(n))
        cand = workload[idx]
        if (
            cpu + cand.cpu_milli > cluster_cpu_milli
            or gpu + cand.total_gpu_milli > cluster_gpu_milli
        ):
            break
        cpu += cand.cpu_milli
        gpu += cand.total_gpu_milli
        out.append(replace(cand, name=f"{cand.name}-clone-{i}"))
    return out
