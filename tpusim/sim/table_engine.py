"""Incremental score-table replay engine — the throughput path.

Exact-equivalent reformulation of tpusim.sim.engine.make_replay (which
mirrors the reference's strictly serial scheduleOne loop,
vendor .../scheduler/scheduler.go:441): every policy used here scores a node
as a pure function of (that node's state, the pod's resource spec), and one
scheduling/deletion event mutates exactly ONE node. So instead of re-scoring
all N nodes for every event, keep tables

    score_tbl[policy, K, N]  raw plugin scores per (pod type, node)
    sharedev_tbl[K, N]       the gpu_sel policy's Reserve device pick
    feas_tbl[K, N]           Filter-phase feasibility

over the K distinct pod resource types in the trace (openb default: K≈150 vs
N=1523 nodes), and per event recompute only the previously-mutated node's
column before gathering the current pod type's row. Results (placements,
device masks, final state) are bit-identical to the sequential engine — the
same kernels run, just at different times; tests/test_table_engine.py pins
equality on the full openb trace prefix and randomized create/delete mixes.

RandomScore (a per-event PRNG draw over the feasible mask,
plugin/random_score.go:42-68) is NOT table-izable — its score row changes
every event — but since round 5 it runs here anyway: the replay body
follows the sequential engine's key-split discipline exactly (one split
per event, then (k_rand, k_sel) off the sub-key), so the per-event draw is
recomputed in do_create from the same key and the same feasible mask the
oracle sees, bit-identically. The same holds for gpu_sel='random' (the
Reserve-phase draw consumes k_sel in both engines). Only the fused Pallas
engine still rejects per-event randomness (reject_randomized).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpusim.constants import MAX_GPUS_PER_NODE
from tpusim.obs import heartbeat as obs_heartbeat
from tpusim.obs import series as obs_series
from tpusim.obs.counters import counter_delta, zero_counters
from tpusim.obs.decisions import no_decision
from tpusim.policies import (
    NORMALIZE_DEGENERATE,
    ScoreContext,
    minmax_normalize_i32,
    minmax_scale_i32,
    pwr_normalize_i32,
)
from tpusim.sim.engine import EV_RETRY, ReplayResult
from tpusim.sim.step import (
    SELF_SELECT_POLICIES,
    PendingCommit,
    apply_commit,
    block_reduce,
    build_decision,
    choose_devices,
    filter_nodes,
    make_pending_commit,
    no_pending_commit,
    packed_argmax,
)
from tpusim.types import NodeState, PodSpec

_INT_MAX = jnp.int32(jnp.iinfo(jnp.int32).max)

# Below this node count the flat O(N) select wins: the blocked path's extra
# per-event fixed costs (dirty-block refresh + two-level combine) outweigh
# the reduction savings, and openb-scale traces (N=1523) must not regress.
BLOCKED_MIN_NODES = 8192


def resolve_block_size(block_size: int, num_nodes: int, num_types: int) -> int:
    """Static block-size decision for the blocked table engine.

    block_size > 0 forces that block size, < 0 forces the flat path, and 0
    (auto) picks a balanced ~sqrt block: the per-event cost is
    O(K*B) dirty-block aggregate refresh + O(N/B) block-summary combine, so
    the balance point is B ~ sqrt(N/K) (the plain ~sqrt(N) rule, refined by
    the pod-type count K that multiplies the refresh), rounded to a power
    of two and clamped to [16, 1024]. Auto stays flat below
    BLOCKED_MIN_NODES. Returns 0 for "run the flat path"."""
    if block_size < 0:
        return 0
    if block_size > 0:
        return min(block_size, num_nodes)
    if num_nodes < BLOCKED_MIN_NODES:
        return 0
    import math

    b = int(math.sqrt(3.0 * num_nodes / max(num_types, 1)))
    b = max(16, min(1024, 1 << max(b - 1, 1).bit_length()))
    return min(b, num_nodes)


class PodTypes(NamedTuple):
    """Distinct (cpu, mem, gpu_milli, gpu_num, gpu_mask) specs in a trace,
    partitioned by scoring branch: share-GPU types first (indices
    [0, Ks)), whole-GPU / CPU-only types after ([Ks, Ks+Kw)). The static
    partition lets branch-aware policies (fgd_score.branches) run each
    group through its specialized kernel instead of a cond→select that
    computes both branches for every type."""

    share: PodSpec  # [Ks] arrays, pinned == -1
    whole: PodSpec  # [Kw] arrays, pinned == -1
    type_id: jnp.ndarray  # i32[P] pod -> global type index


def _to_specs(uniq: np.ndarray) -> PodSpec:
    k = uniq.shape[0]
    return PodSpec(
        cpu=jnp.asarray(uniq[:, 0].astype(np.int32)),
        mem=jnp.asarray(uniq[:, 1].astype(np.int32)),
        gpu_milli=jnp.asarray(uniq[:, 2].astype(np.int32)),
        gpu_num=jnp.asarray(uniq[:, 3].astype(np.int32)),
        gpu_mask=jnp.asarray(uniq[:, 4].astype(np.int32)),
        pinned=jnp.full(k, -1, jnp.int32),
    )


def _type_cols(specs: PodSpec) -> np.ndarray:
    """The [P, 5] dedup key matrix (pinned is deliberately not part of the
    type key — node pinning is a per-event feasibility mask, not a property
    the score tables see)."""
    return np.stack(
        [
            np.asarray(specs.cpu),
            np.asarray(specs.mem),
            np.asarray(specs.gpu_milli),
            np.asarray(specs.gpu_num),
            np.asarray(specs.gpu_mask),
        ],
        axis=1,
    )


def num_pod_types(specs: PodSpec) -> int:
    """Distinct pod resource types in a spec set (the K the table engine's
    amortization heuristic weighs against the event count)."""
    return int(np.unique(_type_cols(specs), axis=0).shape[0])


def build_pod_types(specs: PodSpec) -> PodTypes:
    """Host-side dedup of pod resource specs."""
    cols = _type_cols(specs)
    uniq, inv = np.unique(cols, axis=0, return_inverse=True)
    # is_gpu_share (types.py): exactly one GPU, fractional milli
    is_share = (uniq[:, 3] == 1) & (uniq[:, 2] > 0) & (uniq[:, 2] < 1000)
    order = np.concatenate([np.flatnonzero(is_share), np.flatnonzero(~is_share)])
    rank = np.empty_like(order)
    rank[order] = np.arange(order.size)
    return PodTypes(
        _to_specs(uniq[is_share]),
        _to_specs(uniq[~is_share]),
        jnp.asarray(rank[inv].astype(np.int32)),
    )


def pad_pod_types(types: PodTypes, multiple: int = 16) -> PodTypes:
    """Pad each type group to a `multiple` with inert dummy types so sweeps
    over seeds/traces (whose K varies slightly) share one compiled replay.
    Dummies request 2^30 milli-CPU — infeasible on any node — and are never
    referenced by type_id, so they only cost dead table columns."""

    def pad_group(spec: PodSpec, share: bool) -> PodSpec:
        k = int(spec.cpu.shape[0])
        k2 = -(-k // multiple) * multiple
        if k2 == k:  # includes k == 0: empty groups keep their static skip
            return spec
        pad = k2 - k
        big = jnp.full(pad, 2**30, jnp.int32)
        return PodSpec(
            cpu=jnp.concatenate([spec.cpu, big]),
            mem=jnp.concatenate([spec.mem, big]),
            gpu_milli=jnp.concatenate(
                [spec.gpu_milli, jnp.full(pad, 1 if share else 0, jnp.int32)]
            ),
            gpu_num=jnp.concatenate(
                [spec.gpu_num, jnp.full(pad, 1 if share else 0, jnp.int32)]
            ),
            gpu_mask=jnp.concatenate([spec.gpu_mask, jnp.zeros(pad, jnp.int32)]),
            pinned=jnp.concatenate([spec.pinned, jnp.full(pad, -1, jnp.int32)]),
        )

    # type_id indexes share types at [0, Ks) and whole types at [Ks, K);
    # padding shifts the whole-group base, so remap ids past the share group
    ks = int(types.share.cpu.shape[0])
    share2 = pad_group(types.share, True)
    ks2 = int(share2.cpu.shape[0])
    tid = types.type_id
    tid = jnp.where(tid >= ks, tid + (ks2 - ks), tid)
    return PodTypes(share2, pad_group(types.whole, False), tid)


def _row_state(state: NodeState, node) -> NodeState:
    """1-node slice of the cluster state at a dynamic index."""
    return jax.tree.map(
        lambda a: jax.lax.dynamic_slice_in_dim(a, node, 1, axis=0), state
    )


def _pad_rank(rank: jnp.ndarray, n_pad: int) -> jnp.ndarray:
    """Tie-break rank padded to the blocked layout's node count; sentinel
    rows carry rank INT_MAX so a pad column can never win a tie."""
    n = rank.shape[0]
    if n_pad == n:
        return rank
    return jnp.pad(
        rank, (0, n_pad - n), constant_values=jnp.iinfo(jnp.int32).max
    )


class FlatTableCarry(NamedTuple):
    """Complete engine state between two events of the FLAT table replay —
    the lax.scan carry, promoted to a serializable pytree so a run can be
    cut at any event boundary, round-tripped through host memory / a
    checkpoint file (tpusim.io.storage.save_checkpoint), and resumed
    bit-identically: the scan body is a pure function of (carry, event), so
    `scan(body, c, ev[:k]); scan(body, ·, ev[k:])` IS `scan(body, c, ev)`.

    All leaves are exact dtypes (i32 / bool / u32 PRNG key) — serialization
    cannot perturb them."""

    state: NodeState
    score_tbl: jnp.ndarray  # i32[num_pol, K, N]
    sdev_tbl: jnp.ndarray  # i32[K, N]
    feas_tbl: jnp.ndarray  # bool[K, N]
    pend: PendingCommit  # the software-pipeline register (one event deep)
    dirty: jnp.ndarray  # i32 node whose column the next event refreshes
    placed: jnp.ndarray  # i32[P+1] (dummy row absorbs skip writes)
    masks: jnp.ndarray  # bool[P+1, 8]
    failed: jnp.ndarray  # bool[P+1]
    arr_cpu: jnp.ndarray  # i32 arrived milli-CPU so far
    arr_gpu: jnp.ndarray  # i32 arrived milli-GPU so far
    key: jnp.ndarray  # PRNG key after the events consumed so far
    ctr: jnp.ndarray  # i32[obs.NUM_COUNTERS] exact in-scan counters


class BlockedTableCarry(NamedTuple):
    """FlatTableCarry plus the blocked select-phase aggregates
    (tables/summaries padded to a whole number of B-node blocks). Same
    resume contract; the extra leaves are exactly the per-(policy, type,
    block) summaries ENGINES.md round 6 describes."""

    state: NodeState
    score_tbl: jnp.ndarray  # i32[num_pol, K, n_pad]
    sdev_tbl: jnp.ndarray  # i32[K, n_pad]
    feas_tbl: jnp.ndarray  # bool[K, n_pad]
    bt: jnp.ndarray  # i32[K, N/B] per-block max weighted total
    br: jnp.ndarray  # i32[K, N/B] min tie-break rank among the maxima
    bn: jnp.ndarray  # i32[K, N/B] the block winner's global node id
    brmin: jnp.ndarray  # i32[pn, K, N/B] block raw-score minima (normalizers)
    brmax: jnp.ndarray  # i32[pn, K, N/B] block raw-score maxima
    slo: jnp.ndarray  # i32[pn, K] stored per-type lo extrema
    shi: jnp.ndarray  # i32[pn, K] stored per-type hi extrema
    pend: PendingCommit
    dirty: jnp.ndarray
    placed: jnp.ndarray
    masks: jnp.ndarray
    failed: jnp.ndarray
    arr_cpu: jnp.ndarray
    arr_gpu: jnp.ndarray
    key: jnp.ndarray
    ctr: jnp.ndarray  # i32[obs.NUM_COUNTERS]; [5] counts summary rebuilds


_TABLE_REPLAY_CACHE = {}
# heavy jitted machinery keyed WITHOUT weights (ISSUE 6): the per-policy
# weight vector is a traced i32[num_pol] operand, so every weight config
# of a (kernels, gpu_sel, layout, obs-flags) family shares one jaxpr —
# the marginal what-if weight change is a device call, not a ~5 s
# recompile, and the config-axis sweep vmaps straight over the operand
_TABLE_ENGINE_CACHE = {}


def reject_randomized(policies, gpu_sel: str):
    """Guard for the fused Pallas engine: per-event PRNG draws cannot run
    inside the fused kernel (no jax.random there), so randomized configs
    stay on the table/sequential engines (which replay them
    bit-identically to each other since round 5)."""
    for fn, _ in policies:
        if fn.policy_name == "RandomScore":
            raise ValueError(
                "RandomScore draws per-event randomness; use the table or "
                "sequential engine for it"
            )
    if gpu_sel == "random":
        raise ValueError(
            "gpu_sel='random' draws per-event randomness; use the table or "
            "sequential engine for it"
        )


def selector_index(policies, gpu_sel: str) -> int:
    """Index of the policy whose Reserve-phase device pick the configured
    gpuSelMethod delegates to (-1 = none; the allocateGpuIdFunc registry,
    plugin/open_gpu_share.go:39)."""
    return next(
        (
            i
            for i, (fn, _) in enumerate(policies)
            if gpu_sel == fn.policy_name and fn.policy_name in SELF_SELECT_POLICIES
        ),
        -1,
    )


def _group_fn(fn, which: str):
    """Branch-specialized kernel when the policy provides one (the type
    partition makes the branch static), else the generic kernel."""
    return getattr(fn, "branches", {}).get(which, fn)


def make_table_builders(policies, sel_idx: int):
    """(columns, init_tables) score-table constructors for a static policy
    list — single-sourced table builders for the incremental engine.

    columns(state1, types, tp, key): one node's scores for all K pod types
      -> (scores i32[num_pol, K], sharedev i32[K], feas bool[K]).
    init_tables(state, types, tp, key): full [*, K, N] tables via a K-serial
      map (bounds peak memory to one node-sweep's intermediates per type).
    """

    def one_type_fn(state: NodeState, tp, key, which: str):
        ctx_feas = jnp.ones(state.num_nodes, jnp.bool_)
        ctx = ScoreContext(tp=tp, feasible=ctx_feas, rng=key)

        def one_type(tpod):
            feas = filter_nodes(state, tpod)
            scores = []
            sdev = jnp.full(state.num_nodes, -1, jnp.int32)
            for i, (fn, _) in enumerate(policies):
                if fn.policy_name == "RandomScore":
                    # its score row is a per-event draw the replay body
                    # recomputes; the table slot is never read
                    scores.append(jnp.zeros(state.num_nodes, jnp.int32))
                    continue
                res = _group_fn(fn, which)(state, tpod, ctx)
                scores.append(res.raw_scores)
                if i == sel_idx:
                    sdev = res.share_dev
            return jnp.stack(scores), sdev, feas

        return one_type

    def columns(state1: NodeState, types: PodTypes, tp, key):
        outs = []
        for which, specs in (("share", types.share), ("whole", types.whole)):
            if specs.cpu.shape[0]:
                outs.append(jax.vmap(one_type_fn(state1, tp, key, which))(specs))
        scores = jnp.concatenate([o[0][:, :, 0] for o in outs], 0)  # [K,π]
        sdev = jnp.concatenate([o[1][:, 0] for o in outs], 0)  # [K]
        feas = jnp.concatenate([o[2][:, 0] for o in outs], 0)  # [K]
        return scores.T, sdev, feas

    def init_tables(state: NodeState, types: PodTypes, tp, key):
        outs = []
        for which, specs in (("share", types.share), ("whole", types.whole)):
            if specs.cpu.shape[0]:
                outs.append(jax.lax.map(one_type_fn(state, tp, key, which), specs))
        scores = jnp.concatenate([o[0] for o in outs], 0)  # [K,π,N]
        sdev = jnp.concatenate([o[1] for o in outs], 0)  # [K,N]
        feas = jnp.concatenate([o[2] for o in outs], 0)  # [K,N]
        return jnp.swapaxes(scores, 0, 1), sdev, feas

    return columns, init_tables


def make_table_replay(
    policies, gpu_sel: str = "best", report: bool = False,
    block_size: int = 0, heartbeat_every: int = 0,
    decisions: bool = False, series_every: int = 0,
    faults: bool = False, fault_frag: bool = False,
    unswitched: bool = False,
):
    """Build the jitted incremental replayer for a static policy config.

    policies: [(policy_fn, weight)] — all must be table-izable (raw score a
    pure function of node state + pod spec; RandomScore is not).

    block_size selects the select-phase data layout (resolve_block_size):
    0 (auto) runs the blocked incremental-reduction path at large N and the
    flat path elsewhere; > 0 forces that block size; < 0 forces flat.
    Configs containing RandomScore always run flat — its score row is a
    per-event draw over all N feasible nodes, so there is nothing
    incremental to reduce. The blocked path maintains, per
    (policy, type, block-of-B-nodes), the block min/max feeding the
    normalizers plus the block's (max total, min tie-break rank, node)
    summary, refreshes only the touched node's block per event (O(B)) and
    reduces the final selectHost over N/B block summaries (O(N/B)) —
    bit-identical to the flat path because the same packed_argmax combine
    consumes exact block maxima (max/min are associative) and the same
    minmax_scale_i32 apply consumes exact global extrema.

    The replay is metric-free: per-event report rows (the reference
    recomputes frag/alloc/power cluster-wide after every event,
    simulator.go:426-427, its dominant cost) are reconstructed from the
    emitted (event_node, event_dev) telemetry by the shared vectorized
    post-pass, tpusim.sim.metrics.compute_event_metrics — identical across
    engines by construction. `report` is accepted for signature
    compatibility and must be False.

    The returned replayer also exposes the checkpoint/resume surface the
    driver's chunked dispatch uses (ENGINES.md "Checkpoint/resume"):

        carry = replay.init_carry(state, pods, types, tp, key, rank)
        carry, (nodes, devs) = replay.run_chunk(
            carry, pods, types, ev_kind_seg, ev_pod_seg, tp, rank)   # × S
        state, placed, masks, failed = replay.finish(carry)

    is bit-identical to one replay(...) call over the concatenated
    segments, for any segmentation — including a host/disk round-trip of
    the carry between run_chunk calls (Flat/BlockedTableCarry hold only
    exact-dtype leaves).

    Observability (tpusim.obs): the carry's `ctr` leaf counts events
    applied/bound/failed/deleted/skipped (and blocked summary rebuilds)
    with the shared obs.counters.counter_delta, so the counts are exact,
    engine-invariant, and — being carry state — transparent to
    checkpoint/resume. heartbeat_every > 0 additionally fires a
    jax.debug.callback progress tick (obs.heartbeat) every that many
    processed events from inside the scan; it is part of the engine
    cache key because it is baked into the jaxpr, and it never touches
    the trajectory (pure side output).

    `replay(..., tables=...)` / `init_carry(..., tables=...)` accept
    precomputed (score_tbl, sdev_tbl, feas_tbl) arrays — the driver's
    content-keyed init_tables cache (io.storage) feeds these to skip the
    K-node-sweep build on repeat runs; `replay.build_tables` is the
    jitted builder whose output that cache persists. Results are
    bit-identical either way (the aggregates are pure functions of the
    tables).

    decisions=True (ISSUE 4) makes the scan additionally emit a
    DecisionRecord per event (tpusim.obs.decisions): run_chunk/replay
    ys become (node, dev, dec). The trajectory is untouched — the flat
    path records out of the score rows the select already computed; the
    blocked path reconstructs the event type's full totals row from the
    score/feas tables with direct normalization (the same
    minmax/pwr_normalize_i32 the flat path and the oracle apply), which
    is exactly what its two-level select is bit-identical to — so the
    records are engine-invariant by construction. Recording costs O(N)
    gathers per create event (plus DECISION_TOPK extra packed_argmax
    reductions), which is why it is a static build flag, not always on.

    series_every > 0 (ISSUE 5) makes the scan additionally emit one
    tpusim.obs.series.SeriesSample per event — a real sample of the
    committed pre-event cluster state whenever the processed-event count
    sits on the stride, a pos == -1 sentinel elsewhere. The sample is
    assembled from the score/feas tables the dirty refresh just brought
    current (== fn(state, ·) for every node by the table invariant), so
    it is bit-identical to the sequential engine's recomputed sample; it
    rides the ys, not the carry, so the checkpoint layout is unchanged.
    ys become (node, dev[, dec][, ser]) in that order.

    Weights as operands (ISSUE 6): replay / init_carry / run_chunk all
    accept `weights=` — the i32[num_pol] traced weight vector
    (sim.step.resolve_weights; None = the static config weights, which
    is bit-identical to the former baked `jnp.int32(weight)` constants).
    The underlying jitted machinery is cached WITHOUT the weight values
    (`replay.engine`), so replayers of one policy family share one
    jaxpr across every weight vector; the tables themselves are
    weight-independent (raw per-policy scores), and the blocked
    summaries `bt/br/bn` are built in-scan FROM the weight operand —
    which is why the whole blocked path works off traced weights with
    zero layout change. A carry initialized under weight vector W must
    be resumed with the same W (the driver's run digest covers that).
    """
    if report:
        raise ValueError(
            "the table engine replays metric-free; build the report series "
            "with tpusim.sim.metrics.compute_event_metrics"
        )
    if faults and (decisions or series_every or heartbeat_every):
        raise ValueError(
            "the in-scan fault plane (faults=True) does not combine with "
            "decisions/series/heartbeat builds; run those through the "
            "segmented fault path (Simulator fault_mode='segments')"
        )
    cache_key = (tuple((fn, w) for fn, w in policies), gpu_sel, report,
                 int(block_size), int(heartbeat_every), bool(decisions),
                 int(series_every), bool(faults), bool(fault_frag),
                 bool(unswitched))
    if cache_key in _TABLE_REPLAY_CACHE:
        return _TABLE_REPLAY_CACHE[cache_key]
    engine_key = (tuple(fn for fn, _ in policies), gpu_sel,
                  int(block_size), int(heartbeat_every), bool(decisions),
                  int(series_every), bool(faults), bool(fault_frag),
                  bool(unswitched))
    eng = _TABLE_ENGINE_CACHE.get(engine_key)
    if eng is None:
        eng = _make_table_engine(
            policies, gpu_sel, block_size, heartbeat_every, decisions,
            series_every, faults, fault_frag, unswitched,
        )
        _TABLE_ENGINE_CACHE[engine_key] = eng

    from tpusim.sim.step import resolve_weights

    def replay(state, pods, types, ev_kind, ev_pod, tp, key,
               tiebreak_rank=None, tables=None, weights=None,
               fault_ops=None, fault_carry0=None) -> ReplayResult:
        if faults:
            return eng.replay(
                state, pods, types, ev_kind, ev_pod, tp, key,
                resolve_weights(policies, weights), tiebreak_rank, tables,
                fault_ops, fault_carry0,
            )
        return eng.replay(
            state, pods, types, ev_kind, ev_pod, tp, key,
            resolve_weights(policies, weights), tiebreak_rank, tables,
        )

    def init_carry(state, pods, types, tp, key, tiebreak_rank=None,
                   tables=None, weights=None, fault_carry0=None):
        if faults:
            return eng.init_carry(
                state, pods, types, tp, key,
                resolve_weights(policies, weights), tiebreak_rank, tables,
                fault_carry0,
            )
        return eng.init_carry(
            state, pods, types, tp, key,
            resolve_weights(policies, weights), tiebreak_rank, tables,
        )

    def run_chunk(carry, pods, types, ev_kind, ev_pod, tp,
                  tiebreak_rank=None, weights=None, fault_ops=None):
        if faults:
            return eng.run_chunk(
                carry, pods, types, ev_kind, ev_pod, tp,
                resolve_weights(policies, weights), tiebreak_rank,
                fault_ops,
            )
        return eng.run_chunk(
            carry, pods, types, ev_kind, ev_pod, tp,
            resolve_weights(policies, weights), tiebreak_rank,
        )

    def run_chunk_donated(carry, pods, types, ev_kind, ev_pod, tp,
                          tiebreak_rank=None, weights=None,
                          fault_ops=None):
        """run_chunk with the input carry DONATED to the outputs
        (ISSUE 11): the segment scan reuses the carry's buffers instead
        of reallocating the O(N*K) tables every chunk. The passed carry
        is consumed — snapshot it (np.asarray) first if it must survive,
        which is exactly the driver checkpoint loop's save-then-advance
        order."""
        if faults:
            return eng.run_chunk_donate(
                carry, pods, types, ev_kind, ev_pod, tp,
                resolve_weights(policies, weights), tiebreak_rank,
                fault_ops,
            )
        return eng.run_chunk_donate(
            carry, pods, types, ev_kind, ev_pod, tp,
            resolve_weights(policies, weights), tiebreak_rank,
        )

    # the compiled-executable census of the donating entry (the
    # mesh-chaos gate's one-executable hard check reads it)
    run_chunk_donated._cache_size = eng.run_chunk_donate._cache_size

    # the chunk-resume surface (driver checkpointing, ENGINES.md
    # "Checkpoint/resume"): replay == finish ∘ run_chunk* ∘ init_carry
    replay.init_carry = init_carry
    replay.run_chunk = run_chunk
    replay.run_chunk_donated = run_chunk_donated
    replay.finish = eng.finish
    # the standalone table builder the driver's content-keyed cache
    # persists (io.storage.save_tables); feeding its output back through
    # `tables=` skips the K-node-sweep init bit-identically. The build
    # never reads weights, so one cached table set serves every weight
    # vector of the family.
    replay.build_tables = eng.build_tables
    # the shared weight-operand machinery (the config-axis sweep vmaps
    # eng.replay over stacked weights/keys/ranks)
    replay.engine = eng
    _TABLE_REPLAY_CACHE[cache_key] = replay
    return replay


class _TableEngine(NamedTuple):
    """The weight-operand jitted surface one policy family shares:
    every callable takes the i32[num_pol] weight vector as a traced
    argument (never baked), so the family compiles once.

    `replay` is also the multi-trace sweep's vmap target (ISSUE 7,
    driver._sweep_engine_multi): pods, types.type_id, and the event
    streams batch per lane while types.share/types.whole — the distinct
    type set the tables index — broadcast, so tuned trace variants are
    data, not jaxpr structure. Nothing in the engine reads type_id
    except as a per-pod gather key, which is what makes the lift
    possible without touching the scan body."""

    replay: object  # (state, pods, types, evk, evp, tp, key, wts, rank, tables)
    init_carry: object  # (state, pods, types, tp, key, wts, rank, tables)
    run_chunk: object  # (carry, pods, types, evk, evp, tp, wts, rank)
    run_chunk_donate: object  # run_chunk with the carry donated (ISSUE 11)
    finish: object  # (carry)
    build_tables: object  # (state, types, tp, key) — weight-independent


def _make_table_engine(
    policies, gpu_sel: str, block_size: int, heartbeat_every: int,
    decisions: bool, series_every: int, faults: bool = False,
    fault_frag: bool = False, unswitched: bool = False,
) -> _TableEngine:
    """Build the jitted weight-operand machinery make_table_replay wraps.
    The closed-over `policies` weights are deliberately never read — only
    the kernel objects and their normalize/name metadata are static; the
    numeric weights always arrive as the `wts` operand.

    faults=True (ISSUE 10) builds the fault-plane variant: the scan
    consumes the MERGED stream (base + fault + retry-slot steps,
    tpusim.sim.fault_lane) with three extra xs (pos/arg/aux), the carry
    becomes (table carry, FaultCarry) — the retry queue rides the same
    checkpoint/resume surface as every other leaf — and fault kinds
    apply as masked one-node ops AFTER the event switch (they clip to
    EV_SKIP inside it, so the base machinery is untouched). Fault
    transitions touch exactly one node, so the existing dirty-column /
    dirty-block refresh keeps the tables exact; DOWN rows carry the
    mem_left == -1 sentinel the Filter already rejects."""
    num_pol = len(policies)
    if faults:
        from tpusim.sim import fault_lane as _fl
    sel_idx = selector_index(policies, gpu_sel)
    _columns, _init_tables = make_table_builders(policies, sel_idx)
    has_random = any(fn.policy_name == "RandomScore" for fn, _ in policies)
    # policies whose normalizer needs global (lo, hi) extrema over feasible
    # nodes; the blocked path maintains these via block min/max aggregates
    norm_idx = [
        i for i, (fn, _) in enumerate(policies)
        if fn.normalize in ("minmax", "pwr")
    ]
    norm_deg = [
        NORMALIZE_DEGENERATE[policies[i][0].normalize] for i in norm_idx
    ]

    def _sample_from_tables(state, score_tbl, feas_tbl, t_id, tp, ctr):
        """One in-scan SeriesSample off the just-refreshed tables — the
        flat and blocked bodies share it. The dirty refresh has already
        made score_tbl/feas_tbl equal to a full rebuild on the committed
        state, so gathering the event type's row is bit-identical to the
        sequential engine recomputing it; blocked pad columns are
        infeasible, so the normalized extrema cannot see them. The
        RandomScore slot is a zero table row and score_stats zeroes it
        anyway — the sample never consumes PRNG."""
        processed = ctr[0] + ctr[3] + ctr[4]

        def build():
            raws = jax.lax.dynamic_index_in_dim(score_tbl, t_id, 1, False)
            feas = jax.lax.dynamic_index_in_dim(feas_tbl, t_id, 0, False)
            return obs_series.build_sample(
                state, tp, raws, feas, policies, processed
            )

        return obs_series.emit_from_scan(
            series_every, processed, build, num_pol
        )

    def _totals(raws, feas, slo, shi, wts):
        """Weighted normalized totals with a -INT_MAX sentinel at
        infeasible entries. raws: i32[num_pol, ..., X]; feas: bool[..., X];
        slo/shi: i32[len(norm_idx), ...] stored extrema per normalized
        policy; wts: the i32[num_pol] weight operand. The apply half is
        the shared minmax_scale_i32, so feasible entries match the
        oracle's minmax/pwr_normalize_i32 bit-for-bit whenever slo/shi
        equal the current feasible extrema."""
        tot = jnp.zeros(feas.shape, jnp.int32)
        for i, (fn, _) in enumerate(policies):
            raw = raws[i]
            if fn.normalize in ("minmax", "pwr"):
                j = norm_idx.index(i)
                raw = minmax_scale_i32(
                    raw, feas, slo[j][..., None], shi[j][..., None],
                    norm_deg[j],
                )
            tot = tot + wts[i] * raw
        return jnp.where(feas, tot, -_INT_MAX)

    def make_blocked_body(
        pods, type_id, types, tp, rank_p, n, num_pods, bsz, k_types, nblk,
        offs, wts, fault_ops=None,
    ):
        """Scan body of the blocked O(B + N/B) select path: tables padded
        to a whole number of B-node blocks (sentinel columns: infeasible,
        rank INT_MAX), plus the incremental aggregates

            brmin/brmax[pn, K, N/B]  block raw-score extrema over feasible
                                     nodes per normalized policy (their
                                     min/max over blocks == the global
                                     feasible_min_max extrema exactly)
            bt/br/bn[K, N/B]         per block: max weighted total, min
                                     tie-break rank among the maxima, and
                                     that winner's node id — the block
                                     summaries the final packed_argmax
                                     reduces over

        bt rows are built with *stored* per-type extrema (slo/shi); a
        per-event drift check against the current blocked extrema rebuilds
        one type's summary row (inside a cond, so the O(N) rebuild only
        costs when an extremum actually moved) before the select consumes
        it — which is what keeps normalized policies bit-identical to the
        flat path."""
        n_norm = len(norm_idx)

        def body(carry, ev):
            if faults:
                carry, fc = carry
                kind, idx, fpos, farg, faux = ev
            (state, score_tbl, sdev_tbl, feas_tbl, bt, br, bn,
             brmin, brmax, slo, shi, pend, dirty,
             placed, masks, failed, arr_cpu, arr_gpu, key, ctr) = carry
            if not faults:
                kind, idx = ev
                kc = jnp.clip(kind, 0, 2)
            else:
                is_slot = kind == EV_RETRY
                fc, has_pop, rpod = _fl.pop_retry(fc, is_slot, fpos, farg)
                idx = jnp.where(has_pop, rpod, idx)
                kc = jnp.where(
                    is_slot, jnp.where(has_pop, 0, 2),
                    jnp.clip(kind, 0, 2),
                )
            pod = jax.tree.map(lambda a: a[idx], pods)
            t_id = type_id[idx]
            # identical key-split discipline to the flat path / oracle
            key, sub = jax.random.split(key)
            k_rand, k_sel = jax.random.split(sub)

            # apply the PREVIOUS event's deferred scatters first — every
            # carried buffer is written before anything reads it, so all
            # updates alias in place (PendingCommit)
            state, placed, masks, failed = apply_commit(
                state, placed, masks, failed, pend
            )

            # dirty-column refresh — same kernels, same order as the flat
            # path; dirty < n always, so sentinel columns are never written
            col_scores, col_sdev, col_feas = _columns(
                _row_state(state, dirty), types, tp, k_rand
            )
            score_tbl = jax.lax.dynamic_update_slice(
                score_tbl, col_scores[:, :, None], (0, 0, dirty)
            )
            sdev_tbl = jax.lax.dynamic_update_slice(
                sdev_tbl, col_sdev[:, None], (0, dirty)
            )
            feas_tbl = jax.lax.dynamic_update_slice(
                feas_tbl, col_feas[:, None], (0, dirty)
            )

            # in-scan series sample (ISSUE 5): committed state + current
            # tables, on the processed-event stride
            ser = (
                _sample_from_tables(state, score_tbl, feas_tbl, t_id, tp,
                                    ctr)
                if series_every else ()
            )

            # dirty-block aggregate refresh for ALL K types: O(K*B)
            blk = dirty // bsz
            j0 = blk * bsz
            raw_blk = jax.lax.dynamic_slice(
                score_tbl, (0, 0, j0), (num_pol, k_types, bsz)
            )
            feas_blk = jax.lax.dynamic_slice(
                feas_tbl, (0, j0), (k_types, bsz)
            )
            rank_blk = jax.lax.dynamic_slice(rank_p, (j0,), (bsz,))
            if n_norm:
                selb = jnp.stack([raw_blk[i] for i in norm_idx])
                mn = jnp.where(feas_blk, selb, _INT_MAX).min(-1)
                mx = jnp.where(feas_blk, selb, -_INT_MAX).max(-1)
                brmin = jax.lax.dynamic_update_slice(
                    brmin, mn[:, :, None], (0, 0, blk)
                )
                brmax = jax.lax.dynamic_update_slice(
                    brmax, mx[:, :, None], (0, 0, blk)
                )
            # block totals use the STORED extrema — consistent with every
            # other block of each type's summary row by construction
            tot_blk = _totals(raw_blk, feas_blk, slo, shi, wts)
            bm, brk, bar = block_reduce(tot_blk, rank_blk)
            bt = jax.lax.dynamic_update_slice(bt, bm[:, None], (0, blk))
            br = jax.lax.dynamic_update_slice(br, brk[:, None], (0, blk))
            bn = jax.lax.dynamic_update_slice(
                bn, (j0 + bar)[:, None], (0, blk)
            )

            # extrema drift check + conditional summary-row rebuild for
            # this event's type — outside the event switch, so only [N/B]
            # rows (never whole tables) cross a cond/switch boundary
            rebuilt = None  # obs: did this event pay the O(N) rebuild?
            if n_norm:
                brmin_row = jax.lax.dynamic_index_in_dim(
                    brmin, t_id, 1, False
                )
                brmax_row = jax.lax.dynamic_index_in_dim(
                    brmax, t_id, 1, False
                )
                lo_cur = brmin_row.min(-1)
                hi_cur = brmax_row.max(-1)
                slo_col = jax.lax.dynamic_index_in_dim(slo, t_id, 1, False)
                shi_col = jax.lax.dynamic_index_in_dim(shi, t_id, 1, False)
                changed = jnp.any(
                    (lo_cur != slo_col) | (hi_cur != shi_col)
                )
                rebuilt = changed

                def rebuild():
                    raws = jax.lax.dynamic_index_in_dim(
                        score_tbl, t_id, 1, False
                    )  # [num_pol, n_pad]
                    fr = jax.lax.dynamic_index_in_dim(
                        feas_tbl, t_id, 0, False
                    )
                    tot = _totals(
                        raws[:, None, :], fr[None, :],
                        lo_cur[:, None], hi_cur[:, None], wts,
                    )[0]
                    m2, r2, a2 = block_reduce(
                        tot.reshape(nblk, bsz), rank_p.reshape(nblk, bsz)
                    )
                    return m2, r2, offs + a2, lo_cur, hi_cur

                def keep():
                    return (
                        jax.lax.dynamic_index_in_dim(bt, t_id, 0, False),
                        jax.lax.dynamic_index_in_dim(br, t_id, 0, False),
                        jax.lax.dynamic_index_in_dim(bn, t_id, 0, False),
                        slo_col,
                        shi_col,
                    )

                bt_row, br_row, bn_row, lo_new, hi_new = jax.lax.cond(
                    changed, rebuild, keep
                )
                bt = jax.lax.dynamic_update_slice(
                    bt, bt_row[None], (t_id, 0)
                )
                br = jax.lax.dynamic_update_slice(
                    br, br_row[None], (t_id, 0)
                )
                bn = jax.lax.dynamic_update_slice(
                    bn, bn_row[None], (t_id, 0)
                )
                slo = jax.lax.dynamic_update_slice(
                    slo, lo_new[:, None], (0, t_id)
                )
                shi = jax.lax.dynamic_update_slice(
                    shi, hi_new[:, None], (0, t_id)
                )
            else:
                bt_row = jax.lax.dynamic_index_in_dim(bt, t_id, 0, False)
                br_row = jax.lax.dynamic_index_in_dim(br, t_id, 0, False)
                bn_row = jax.lax.dynamic_index_in_dim(bn, t_id, 0, False)

            def do_create():
                # selectHost over N/B block summaries — the same
                # packed_argmax combine the oracle runs over N nodes
                blk_i, _, okb = packed_argmax(
                    bt_row, bt_row != -_INT_MAX, br_row
                )
                cand = bn_row[blk_i]
                # nodeSelector-pinned pods have exactly one candidate: the
                # winner is the pinned node iff Filter passes there (score
                # values cannot matter with a single candidate), matching
                # the oracle's per-event pinned feasibility mask. An
                # out-of-range pin (unknown nodeSelector name — trace.py
                # encodes it as index n) can never be feasible.
                pin = jnp.clip(pod.pinned, 0, n - 1)
                pin_feas = (
                    jax.lax.dynamic_slice(feas_tbl, (t_id, pin), (1, 1))[0, 0]
                    & (pod.pinned < n)
                )
                node = jnp.where(
                    pod.pinned >= 0,
                    jnp.where(pin_feas, pin, -1),
                    jnp.where(okb, cand, -1),
                ).astype(jnp.int32)
                ok = node >= 0
                sel = jnp.maximum(node, 0)
                dev_scalar = jax.lax.dynamic_slice(
                    sdev_tbl, (t_id, sel), (1, 1)
                )[0, 0]
                dmask = choose_devices(
                    state.gpu_left[sel], pod, dev_scalar, gpu_sel, k_sel
                ) & ok
                node_f = jnp.where(ok, sel, -1).astype(jnp.int32)
                if not decisions:
                    return node_f, dmask
                # provenance: rebuild this type's full totals row with
                # DIRECT normalization over the pin-masked feasibility —
                # exactly the computation the flat path selects with (and
                # what the blocked two-level select is bit-identical to),
                # so the record cannot depend on the engine. Sentinel pad
                # columns are infeasible + rank INT_MAX: never in the topk.
                raws_row = jax.lax.dynamic_index_in_dim(
                    score_tbl, t_id, 1, False
                )  # [num_pol, n_pad]
                feas_row = jax.lax.dynamic_index_in_dim(
                    feas_tbl, t_id, 0, False
                )
                n_pad_l = feas_row.shape[0]
                pin_m = (pod.pinned < 0) | (
                    jnp.arange(n_pad_l, dtype=jnp.int32) == pod.pinned
                )
                feas_d = feas_row & pin_m
                norm_rows = []
                tot_d = jnp.zeros(n_pad_l, jnp.int32)
                for i, (fn, _) in enumerate(policies):
                    raw = raws_row[i]
                    if fn.normalize == "minmax":
                        nrm = minmax_normalize_i32(raw, feas_d)
                    elif fn.normalize == "pwr":
                        nrm = pwr_normalize_i32(raw, feas_d)
                    else:
                        nrm = raw
                    norm_rows.append(nrm)
                    tot_d = tot_d + wts[i] * nrm
                dec = build_decision(
                    node_f, raws_row, jnp.stack(norm_rows), tot_d, feas_d,
                    rank_p,
                )
                # the engine-specific slot: which block won the two-level
                # select (a pinned pod bypasses blocks — its node's block)
                win_blk = jnp.where(
                    ok,
                    jnp.where(pod.pinned >= 0, pin // bsz, blk_i),
                    -1,
                ).astype(jnp.int32)
                return node_f, dmask, dec._replace(block=win_blk)

            def do_delete():
                base = placed[idx], masks[idx]
                return base + ((no_decision(num_pol),) if decisions else ())

            def do_skip():
                base = (
                    jnp.int32(-1), jnp.zeros(MAX_GPUS_PER_NODE, jnp.bool_)
                )
                return base + ((no_decision(num_pol),) if decisions else ())

            outs = jax.lax.switch(kc, [do_create, do_delete, do_skip])
            if decisions:
                node, dev, dec = outs
            else:
                node, dev = outs
            # defer this event's scatters to the next iteration
            pend = make_pending_commit(kc, idx, node, dev, pod, num_pods)
            arr_cpu = arr_cpu + jnp.where(kc == 0, pod.cpu, 0)
            arr_gpu = arr_gpu + jnp.where(kc == 0, pod.total_gpu_milli(), 0)
            dirty = jnp.where(kc == 2, dirty, jnp.maximum(node, 0))
            ctr = ctr + counter_delta(kc, node, rebuilt)
            if heartbeat_every:
                obs_heartbeat.emit_from_scan(
                    ctr[0] + ctr[3] + ctr[4], heartbeat_every
                )
            if faults:
                pend = pend._replace(failed_val=jnp.where(
                    is_slot, failed[idx] | (node < 0), node < 0
                ))
                (state, placed, masks, failed, fc, ftouch, fy) = (
                    _fl.apply_fault_step(
                        state, placed, masks, failed, fc, pods, kind,
                        farg, faux, fpos, fault_ops, tp,
                        jnp.arange(n, dtype=jnp.int32), fault_frag,
                    )
                )
                fc, lat, _ = _fl.commit_retry(
                    fc, has_pop, rpod, node, fpos, farg, fault_ops.params
                )
                fy = fy._replace(
                    rpod=jnp.where(has_pop, rpod, -1).astype(jnp.int32),
                    lat=lat,
                )
                dirty = jnp.where(ftouch >= 0, ftouch, dirty)
                node = jnp.where(ftouch >= 0, ftouch, node)
            new_carry = BlockedTableCarry(
                state, score_tbl, sdev_tbl, feas_tbl, bt, br, bn,
                brmin, brmax, slo, shi, pend, dirty,
                placed, masks, failed, arr_cpu, arr_gpu, key, ctr,
            )
            ys = (
                (node, dev)
                + ((dec,) if decisions else ())
                + ((ser,) if series_every else ())
            )
            if faults:
                return (new_carry, fc), ys + (fy,)
            return new_carry, ys

        return body

    def make_flat_body(pods, type_id, types, tp, tiebreak_rank, n, num_pods,
                       wts, fault_ops=None):
        """Scan body of the flat O(N) select path.

        Round 18 ports the shard engine's Round-15 unconditional-select
        restructure back here as an A/B layout knob (`unswitched`, the
        shard engine's `pipelined` pattern): with it ON, the select runs
        UNCONDITIONALLY every event (score/feas rows never cross a
        branch boundary — the branch-capture class the shard engine
        shed) and only the small (node, dev[, dec]) results merge by
        kind. Bit-identical to the switch form by construction — the
        same create_result closure runs either inside the switch branch
        or inline, with the same pre-split k_rand/k_sel (pinned by
        tests/test_table_engine.py::test_unswitched_flat_bit_identity).
        MEASURED at N=100k on the CPU backend (bench_scale --nodes
        100000 --block-size -1, creates-only stream): the switch form
        wins, ~5.3 vs ~6.8 ms/event — XLA:CPU lowers the in-branch row
        reads as plain gathers (no whole-table copy), so removing the
        branch only adds merge selects. The default therefore stays on
        the switch; the unswitched layout exists for accelerator
        backends where conditionals serialize the stream (the Round 15
        motivation) and for A/B measurement."""

        def body(carry, ev):
            if faults:
                carry, fc = carry
                kind, idx, fpos, farg, faux = ev
            (state, score_tbl, sdev_tbl, feas_tbl, pend, dirty,
             placed, masks, failed, arr_cpu, arr_gpu, key, ctr) = carry
            if not faults:
                kind, idx = ev
                kc = jnp.clip(kind, 0, 2)
            else:
                # retry slots pop the earliest due evicted pod and run it
                # through the ordinary create branch; fault kinds clip to
                # skip here and apply as masked ops after the switch
                is_slot = kind == EV_RETRY
                fc, has_pop, rpod = _fl.pop_retry(fc, is_slot, fpos, farg)
                idx = jnp.where(has_pop, rpod, idx)
                kc = jnp.where(
                    is_slot, jnp.where(has_pop, 0, 2),
                    jnp.clip(kind, 0, 2),
                )
            pod = jax.tree.map(lambda a: a[idx], pods)
            t_id = type_id[idx]
            # the sequential oracle's split discipline exactly (engine.py
            # body: key, sub = split(key); schedule_one: k_rand, k_sel =
            # split(sub)) — this is what makes the per-event random draws
            # below bit-identical to the oracle's
            key, sub = jax.random.split(key)
            k_rand, k_sel = jax.random.split(sub)

            # apply the PREVIOUS event's deferred scatters first: every
            # carried buffer is written before anything reads it this
            # iteration, so all updates alias in place (PendingCommit)
            state, placed, masks, failed = apply_commit(
                state, placed, masks, failed, pend
            )

            # refresh the one column whose node changed last event (from
            # the just-committed state)
            col_scores, col_sdev, col_feas = _columns(
                _row_state(state, dirty), types, tp, k_rand
            )
            score_tbl = jax.lax.dynamic_update_slice(
                score_tbl, col_scores[:, :, None], (0, 0, dirty)
            )
            sdev_tbl = jax.lax.dynamic_update_slice(
                sdev_tbl, col_sdev[:, None], (0, dirty)
            )
            feas_tbl = jax.lax.dynamic_update_slice(
                feas_tbl, col_feas[:, None], (0, dirty)
            )

            # in-scan series sample (ISSUE 5): committed state + current
            # tables, on the processed-event stride
            ser = (
                _sample_from_tables(state, score_tbl, feas_tbl, t_id, tp,
                                    ctr)
                if series_every else ()
            )

            def create_result():
                """The full create computation — ONE definition serving
                both select layouts below (Round 18)."""
                feasible = feas_tbl[t_id] & (
                    (pod.pinned < 0)
                    | (jnp.arange(n, dtype=jnp.int32) == pod.pinned)
                )
                total = jnp.zeros(n, jnp.int32)
                raw_rows, norm_rows = [], []
                for i, (fn, _) in enumerate(policies):
                    if fn.policy_name == "RandomScore":
                        # per-event draw, recomputed instead of
                        # table-read — through the ONE canonical kernel
                        # (the oracle's schedule_one calls the same fn
                        # with the same feasible mask and k_rand)
                        ctx = ScoreContext(
                            tp=tp, feasible=feasible, rng=k_rand
                        )
                        raw = fn(state, pod, ctx).raw_scores
                    else:
                        raw = score_tbl[i, t_id]
                    if fn.normalize == "minmax":
                        nrm = minmax_normalize_i32(raw, feasible)
                    elif fn.normalize == "pwr":
                        nrm = pwr_normalize_i32(raw, feasible)
                    else:
                        nrm = raw
                    if decisions:
                        raw_rows.append(raw)
                        norm_rows.append(nrm)
                    total = total + wts[i] * nrm
                # the oracle's selectHost + Reserve halves; the Bind
                # scatter is deferred via PendingCommit
                sel, _, ok = packed_argmax(total, feasible, tiebreak_rank)
                dmask = choose_devices(
                    state.gpu_left[sel], pod, sdev_tbl[t_id, sel],
                    gpu_sel, k_sel,
                ) & ok
                node_f = jnp.where(ok, sel, -1).astype(jnp.int32)
                if not decisions:
                    return node_f, dmask
                # provenance off the very rows the select consumed
                dec = build_decision(
                    node_f, jnp.stack(raw_rows), jnp.stack(norm_rows),
                    total, feasible, tiebreak_rank,
                )
                return node_f, dmask, dec

            if unswitched:
                # the shard engine's Round-15 form: the select runs
                # UNCONDITIONALLY (table rows never cross a branch
                # boundary) and only the small (node, dev[, dec])
                # results merge by kind
                outs_c = create_result()
                is_create = kc == 0
                is_delete = kc == 1
                node = jnp.where(
                    is_create, outs_c[0],
                    jnp.where(is_delete, placed[idx], jnp.int32(-1)),
                ).astype(jnp.int32)
                dev = jnp.where(
                    is_create, outs_c[1],
                    jnp.where(is_delete, masks[idx],
                              jnp.zeros(MAX_GPUS_PER_NODE, jnp.bool_)),
                )
                if decisions:
                    dec = jax.tree.map(
                        lambda a, b: jnp.where(is_create, a, b),
                        outs_c[2], no_decision(num_pol),
                    )
            else:
                # the event switch (the measured-faster layout on the
                # single-device CPU flat path — ENGINES.md Round 18)

                def do_delete():
                    base = placed[idx], masks[idx]
                    return base + (
                        (no_decision(num_pol),) if decisions else ()
                    )

                def do_skip():
                    base = (
                        jnp.int32(-1),
                        jnp.zeros(MAX_GPUS_PER_NODE, jnp.bool_),
                    )
                    return base + (
                        (no_decision(num_pol),) if decisions else ()
                    )

                outs = jax.lax.switch(
                    kc, [create_result, do_delete, do_skip]
                )
                if decisions:
                    node, dev, dec = outs
                else:
                    node, dev = outs
            # defer this event's scatters to the next iteration; arrived
            # counters accumulate per creation event regardless of outcome
            # (simulator.go:406-408)
            pend = make_pending_commit(kc, idx, node, dev, pod, num_pods)
            arr_cpu = arr_cpu + jnp.where(kc == 0, pod.cpu, 0)
            arr_gpu = arr_gpu + jnp.where(kc == 0, pod.total_gpu_milli(), 0)
            dirty = jnp.where(kc == 2, dirty, jnp.maximum(node, 0))
            ctr = ctr + counter_delta(kc, node)
            if heartbeat_every:
                obs_heartbeat.emit_from_scan(
                    ctr[0] + ctr[3] + ctr[4], heartbeat_every
                )
            if faults:
                # retry creates accumulate ever-failed with OR (the
                # segmented path's per-segment `|=`); base creates still
                # overwrite (they run once per pod)
                pend = pend._replace(failed_val=jnp.where(
                    is_slot, failed[idx] | (node < 0), node < 0
                ))
                (state, placed, masks, failed, fc, ftouch, fy) = (
                    _fl.apply_fault_step(
                        state, placed, masks, failed, fc, pods, kind,
                        farg, faux, fpos, fault_ops, tp,
                        jnp.arange(n, dtype=jnp.int32), fault_frag,
                    )
                )
                fc, lat, _ = _fl.commit_retry(
                    fc, has_pop, rpod, node, fpos, farg, fault_ops.params
                )
                fy = fy._replace(
                    rpod=jnp.where(has_pop, rpod, -1).astype(jnp.int32),
                    lat=lat,
                )
                dirty = jnp.where(ftouch >= 0, ftouch, dirty)
                node = jnp.where(ftouch >= 0, ftouch, node)
            new_carry = FlatTableCarry(
                state, score_tbl, sdev_tbl, feas_tbl, pend, dirty,
                placed, masks, failed, arr_cpu, arr_gpu, key, ctr,
            )
            ys = (
                (node, dev)
                + ((dec,) if decisions else ())
                + ((ser,) if series_every else ())
            )
            if faults:
                return (new_carry, fc), ys + (fy,)
            return new_carry, ys

        return body

    # FaultCarry pod-axis pad/trim to the carry's P+1 bookkeeping rows —
    # shared with the shard engine (fault_lane.pad/trim_fault_carry)
    def _pad_fc(fc0):
        from tpusim.sim import fault_lane as _fl

        return _fl.pad_fault_carry(fc0)

    def _trim_fc(fc):
        from tpusim.sim import fault_lane as _fl

        return _fl.trim_fault_carry(fc)

    @jax.jit
    def init_carry(state, pods, types, tp, key, wts, tiebreak_rank=None,
                   tables=None, fault_carry0=None):
        """Engine state at event 0: score/sdev/feas tables from the
        committed state + an inert pipeline register (and, on the blocked
        path, the per-(policy, type, block) aggregates built from the
        `wts` weight operand).

        `tables` short-circuits the K-node-sweep build with precomputed
        (score_tbl, sdev_tbl, feas_tbl) — the driver's content-keyed
        cache path; every downstream aggregate derives from them, so a
        cached init is bit-identical to a built one.

        The event key chain must stay byte-for-byte the sequential
        oracle's (it never burns a split before its scan), so the random
        replay path sees identical per-event keys; no table-ized column
        kernel consumes rng, so init can reuse the root key as-is."""
        n = state.num_nodes
        num_pods = pods.cpu.shape[0]
        k_types = int(types.share.cpu.shape[0]) + int(types.whole.cpu.shape[0])
        bsz = 0 if has_random else resolve_block_size(block_size, n, k_types)
        if tiebreak_rank is None:
            tiebreak_rank = jnp.arange(n, dtype=jnp.int32)
        if tables is None:
            score_tbl, sdev_tbl, feas_tbl = _init_tables(state, types, tp, key)
        else:
            score_tbl, sdev_tbl, feas_tbl = tables

        # one extra dummy row absorbs skip-event writes of the pipelined
        # commit (PendingCommit.pod_write); sliced off by finish()
        placed = jnp.full(num_pods + 1, -1, jnp.int32)
        masks = jnp.zeros((num_pods + 1, MAX_GPUS_PER_NODE), jnp.bool_)
        failed = jnp.zeros(num_pods + 1, jnp.bool_)
        pend = no_pending_commit(num_pods)
        z = jnp.int32(0)
        if not bsz:
            flat = FlatTableCarry(
                state, score_tbl, sdev_tbl, feas_tbl, pend, z,
                placed, masks, failed, z, z, key, zero_counters(),
            )
            return (flat, _pad_fc(fault_carry0)) if faults else flat

        nblk = -(-n // bsz)
        n_pad = nblk * bsz
        n_norm = len(norm_idx)
        rank_p = _pad_rank(tiebreak_rank, n_pad)
        if n_pad != n:
            pad = n_pad - n
            score_tbl = jnp.pad(score_tbl, ((0, 0), (0, 0), (0, pad)))
            sdev_tbl = jnp.pad(
                sdev_tbl, ((0, 0), (0, pad)), constant_values=-1
            )
            feas_tbl = jnp.pad(feas_tbl, ((0, 0), (0, pad)))
        offs = jnp.arange(nblk, dtype=jnp.int32) * bsz

        if n_norm:
            sel0 = jnp.stack([score_tbl[i] for i in norm_idx])
            brmin = jnp.where(feas_tbl, sel0, _INT_MAX).reshape(
                n_norm, k_types, nblk, bsz
            ).min(-1)
            brmax = jnp.where(feas_tbl, sel0, -_INT_MAX).reshape(
                n_norm, k_types, nblk, bsz
            ).max(-1)
            slo = brmin.min(-1)  # [pn, K] == per-row feasible_min_max
            shi = brmax.max(-1)
        else:
            brmin = jnp.zeros((0, k_types, nblk), jnp.int32)
            brmax = jnp.zeros((0, k_types, nblk), jnp.int32)
            slo = jnp.zeros((0, k_types), jnp.int32)
            shi = jnp.zeros((0, k_types), jnp.int32)

        tot0 = _totals(score_tbl, feas_tbl, slo, shi, wts)  # [K, n_pad]
        bt, br, ba = block_reduce(
            tot0.reshape(k_types, nblk, bsz), rank_p.reshape(nblk, bsz)
        )
        bn = offs[None, :] + ba  # [K, nblk] global winner node ids
        blocked = BlockedTableCarry(
            state, score_tbl, sdev_tbl, feas_tbl, bt, br, bn,
            brmin, brmax, slo, shi, pend, z,
            placed, masks, failed, z, z, key, zero_counters(),
        )
        return (blocked, _pad_fc(fault_carry0)) if faults else blocked

    def _run_chunk_impl(carry, pods, types, ev_kind, ev_pod, tp, wts,
                        tiebreak_rank=None, fault_ops=None):
        """Advance `carry` over a segment of the event stream; returns
        (carry', (event_node, event_dev)) for the segment — extended with
        a per-event DecisionRecord element when the engine was built with
        decisions=True, then a per-event SeriesSample element when built
        with series_every > 0. Chaining
        run_chunk calls over any partition of the stream is bit-identical
        to one replay() over the whole stream — the scan body is a pure
        function of (carry, event), and every carry leaf is an exact dtype
        (i32/bool/u32), so even a host/disk round-trip between chunks
        cannot perturb the trajectory. `wts` must be the weight vector
        the carry was initialized under (the blocked summaries embed it)."""
        base = carry[0] if faults else carry
        n = base.state.num_nodes
        num_pods = pods.cpu.shape[0]
        if tiebreak_rank is None:
            tiebreak_rank = jnp.arange(n, dtype=jnp.int32)
        type_id = types.type_id
        if isinstance(base, BlockedTableCarry):
            k_types, nblk = base.bt.shape
            bsz = base.score_tbl.shape[2] // nblk
            rank_p = _pad_rank(tiebreak_rank, nblk * bsz)
            offs = jnp.arange(nblk, dtype=jnp.int32) * bsz
            body = make_blocked_body(
                pods, type_id, types, tp, rank_p, n, num_pods, bsz,
                k_types, nblk, offs, wts, fault_ops,
            )
        else:
            body = make_flat_body(
                pods, type_id, types, tp, tiebreak_rank, n, num_pods, wts,
                fault_ops,
            )
        xs = (
            (ev_kind, ev_pod, fault_ops.pos, fault_ops.arg, fault_ops.aux)
            if faults else (ev_kind, ev_pod)
        )
        # unroll amortizes per-iteration fixed costs (~20% wall on the openb
        # replay); higher factors showed no further gain
        return jax.lax.scan(body, carry, xs, unroll=4)

    run_chunk = jax.jit(_run_chunk_impl)
    # the donating twin (ISSUE 11): identical jaxpr, but the input carry's
    # buffers are donated to the outputs, so a long chunked replay stops
    # reallocating its O(N*K) score tables every segment. The caller must
    # treat the input carry as CONSUMED (the driver's _run_chunked takes
    # its host checkpoint copy before the next chunk dispatch); callers
    # that reuse a carry (tests probing arbitrary cut points) stay on the
    # non-donating entry.
    run_chunk_donate = jax.jit(_run_chunk_impl, donate_argnums=0)

    @jax.jit
    def finish(carry):
        """Post-scan epilogue: apply the last event's still-pending commit
        and strip the dummy bookkeeping row. Returns (state, placed,
        masks, failed). A finished carry must not be resumed — the pending
        commit has landed."""
        if faults:
            carry = carry[0]
        state, placed, masks, failed = apply_commit(
            carry.state, carry.placed, carry.masks, carry.failed, carry.pend
        )
        return state, placed[:-1], masks[:-1], failed[:-1]

    @jax.jit
    def _replay_impl(
        state: NodeState,
        pods: PodSpec,  # [P]
        types: PodTypes,  # host-side build_pod_types(pods)
        ev_kind: jnp.ndarray,  # i32[E]
        ev_pod: jnp.ndarray,  # i32[E]
        tp,
        key,
        wts,  # i32[num_pol] traced weight operand
        tiebreak_rank=None,
        tables=None,
        fault_ops=None,
        fault_carry0=None,
    ) -> ReplayResult:
        carry = init_carry(
            state, pods, types, tp, key, wts, tiebreak_rank, tables,
            fault_carry0,
        )
        carry, ys = run_chunk(
            carry, pods, types, ev_kind, ev_pod, tp, wts, tiebreak_rank,
            fault_ops,
        )
        state, placed, masks, failed = finish(carry)
        nodes, devs = ys[0], ys[1]
        rest = list(ys[2:])
        decs = rest.pop(0) if decisions else None
        sers = rest.pop(0) if series_every else None
        if faults:
            base, fc = carry
            return ReplayResult(
                state, placed, masks, failed, None, nodes, devs, base.ctr,
                None, None, rest.pop(0), _trim_fc(fc),
            )
        return ReplayResult(
            state, placed, masks, failed, None, nodes, devs, carry.ctr,
            decs, sers,
        )

    return _TableEngine(
        replay=_replay_impl,
        init_carry=init_carry,
        run_chunk=run_chunk,
        run_chunk_donate=run_chunk_donate,
        finish=finish,
        build_tables=jax.jit(
            lambda state, types, tp, key: _init_tables(state, types, tp, key)
        ),
    )
