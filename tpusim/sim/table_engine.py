"""Incremental score-table replay engine — the throughput path.

Exact-equivalent reformulation of tpusim.sim.engine.make_replay (which
mirrors the reference's strictly serial scheduleOne loop,
vendor .../scheduler/scheduler.go:441): every policy used here scores a node
as a pure function of (that node's state, the pod's resource spec), and one
scheduling/deletion event mutates exactly ONE node. So instead of re-scoring
all N nodes for every event, keep tables

    score_tbl[policy, K, N]  raw plugin scores per (pod type, node)
    sharedev_tbl[K, N]       the gpu_sel policy's Reserve device pick
    feas_tbl[K, N]           Filter-phase feasibility

over the K distinct pod resource types in the trace (openb default: K≈150 vs
N=1523 nodes), and per event recompute only the previously-mutated node's
column before gathering the current pod type's row. Results (placements,
device masks, final state) are bit-identical to the sequential engine — the
same kernels run, just at different times; tests/test_table_engine.py pins
equality on the full openb trace prefix and randomized create/delete mixes.

RandomScore (a per-event PRNG draw over the feasible mask,
plugin/random_score.go:42-68) is NOT table-izable — its score row changes
every event — but since round 5 it runs here anyway: the replay body
follows the sequential engine's key-split discipline exactly (one split
per event, then (k_rand, k_sel) off the sub-key), so the per-event draw is
recomputed in do_create from the same key and the same feasible mask the
oracle sees, bit-identically. The same holds for gpu_sel='random' (the
Reserve-phase draw consumes k_sel in both engines). Only the fused Pallas
engine still rejects per-event randomness (reject_randomized).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpusim.constants import MAX_GPUS_PER_NODE
from tpusim.policies import ScoreContext, minmax_normalize_i32, pwr_normalize_i32
from tpusim.sim.engine import ReplayResult
from tpusim.sim.step import (
    SELF_SELECT_POLICIES,
    Placement,
    filter_nodes,
    select_and_bind,
    unschedule,
)
from tpusim.types import NodeState, PodSpec

_INT_MAX = jnp.int32(jnp.iinfo(jnp.int32).max)


class PodTypes(NamedTuple):
    """Distinct (cpu, mem, gpu_milli, gpu_num, gpu_mask) specs in a trace,
    partitioned by scoring branch: share-GPU types first (indices
    [0, Ks)), whole-GPU / CPU-only types after ([Ks, Ks+Kw)). The static
    partition lets branch-aware policies (fgd_score.branches) run each
    group through its specialized kernel instead of a cond→select that
    computes both branches for every type."""

    share: PodSpec  # [Ks] arrays, pinned == -1
    whole: PodSpec  # [Kw] arrays, pinned == -1
    type_id: jnp.ndarray  # i32[P] pod -> global type index


def _to_specs(uniq: np.ndarray) -> PodSpec:
    k = uniq.shape[0]
    return PodSpec(
        cpu=jnp.asarray(uniq[:, 0].astype(np.int32)),
        mem=jnp.asarray(uniq[:, 1].astype(np.int32)),
        gpu_milli=jnp.asarray(uniq[:, 2].astype(np.int32)),
        gpu_num=jnp.asarray(uniq[:, 3].astype(np.int32)),
        gpu_mask=jnp.asarray(uniq[:, 4].astype(np.int32)),
        pinned=jnp.full(k, -1, jnp.int32),
    )


def _type_cols(specs: PodSpec) -> np.ndarray:
    """The [P, 5] dedup key matrix (pinned is deliberately not part of the
    type key — node pinning is a per-event feasibility mask, not a property
    the score tables see)."""
    return np.stack(
        [
            np.asarray(specs.cpu),
            np.asarray(specs.mem),
            np.asarray(specs.gpu_milli),
            np.asarray(specs.gpu_num),
            np.asarray(specs.gpu_mask),
        ],
        axis=1,
    )


def num_pod_types(specs: PodSpec) -> int:
    """Distinct pod resource types in a spec set (the K the table engine's
    amortization heuristic weighs against the event count)."""
    return int(np.unique(_type_cols(specs), axis=0).shape[0])


def build_pod_types(specs: PodSpec) -> PodTypes:
    """Host-side dedup of pod resource specs."""
    cols = _type_cols(specs)
    uniq, inv = np.unique(cols, axis=0, return_inverse=True)
    # is_gpu_share (types.py): exactly one GPU, fractional milli
    is_share = (uniq[:, 3] == 1) & (uniq[:, 2] > 0) & (uniq[:, 2] < 1000)
    order = np.concatenate([np.flatnonzero(is_share), np.flatnonzero(~is_share)])
    rank = np.empty_like(order)
    rank[order] = np.arange(order.size)
    return PodTypes(
        _to_specs(uniq[is_share]),
        _to_specs(uniq[~is_share]),
        jnp.asarray(rank[inv].astype(np.int32)),
    )


def pad_pod_types(types: PodTypes, multiple: int = 16) -> PodTypes:
    """Pad each type group to a `multiple` with inert dummy types so sweeps
    over seeds/traces (whose K varies slightly) share one compiled replay.
    Dummies request 2^30 milli-CPU — infeasible on any node — and are never
    referenced by type_id, so they only cost dead table columns."""

    def pad_group(spec: PodSpec, share: bool) -> PodSpec:
        k = int(spec.cpu.shape[0])
        k2 = -(-k // multiple) * multiple
        if k2 == k:  # includes k == 0: empty groups keep their static skip
            return spec
        pad = k2 - k
        big = jnp.full(pad, 2**30, jnp.int32)
        return PodSpec(
            cpu=jnp.concatenate([spec.cpu, big]),
            mem=jnp.concatenate([spec.mem, big]),
            gpu_milli=jnp.concatenate(
                [spec.gpu_milli, jnp.full(pad, 1 if share else 0, jnp.int32)]
            ),
            gpu_num=jnp.concatenate(
                [spec.gpu_num, jnp.full(pad, 1 if share else 0, jnp.int32)]
            ),
            gpu_mask=jnp.concatenate([spec.gpu_mask, jnp.zeros(pad, jnp.int32)]),
            pinned=jnp.concatenate([spec.pinned, jnp.full(pad, -1, jnp.int32)]),
        )

    # type_id indexes share types at [0, Ks) and whole types at [Ks, K);
    # padding shifts the whole-group base, so remap ids past the share group
    ks = int(types.share.cpu.shape[0])
    share2 = pad_group(types.share, True)
    ks2 = int(share2.cpu.shape[0])
    tid = types.type_id
    tid = jnp.where(tid >= ks, tid + (ks2 - ks), tid)
    return PodTypes(share2, pad_group(types.whole, False), tid)


def _row_state(state: NodeState, node) -> NodeState:
    """1-node slice of the cluster state at a dynamic index."""
    return jax.tree.map(
        lambda a: jax.lax.dynamic_slice_in_dim(a, node, 1, axis=0), state
    )


_TABLE_REPLAY_CACHE = {}


def reject_randomized(policies, gpu_sel: str):
    """Guard for the fused Pallas engine: per-event PRNG draws cannot run
    inside the fused kernel (no jax.random there), so randomized configs
    stay on the table/sequential engines (which replay them
    bit-identically to each other since round 5)."""
    for fn, _ in policies:
        if fn.policy_name == "RandomScore":
            raise ValueError(
                "RandomScore draws per-event randomness; use the table or "
                "sequential engine for it"
            )
    if gpu_sel == "random":
        raise ValueError(
            "gpu_sel='random' draws per-event randomness; use the table or "
            "sequential engine for it"
        )


def selector_index(policies, gpu_sel: str) -> int:
    """Index of the policy whose Reserve-phase device pick the configured
    gpuSelMethod delegates to (-1 = none; the allocateGpuIdFunc registry,
    plugin/open_gpu_share.go:39)."""
    return next(
        (
            i
            for i, (fn, _) in enumerate(policies)
            if gpu_sel == fn.policy_name and fn.policy_name in SELF_SELECT_POLICIES
        ),
        -1,
    )


def _group_fn(fn, which: str):
    """Branch-specialized kernel when the policy provides one (the type
    partition makes the branch static), else the generic kernel."""
    return getattr(fn, "branches", {}).get(which, fn)


def make_table_builders(policies, sel_idx: int):
    """(columns, init_tables) score-table constructors for a static policy
    list — single-sourced table builders for the incremental engine.

    columns(state1, types, tp, key): one node's scores for all K pod types
      -> (scores i32[num_pol, K], sharedev i32[K], feas bool[K]).
    init_tables(state, types, tp, key): full [*, K, N] tables via a K-serial
      map (bounds peak memory to one node-sweep's intermediates per type).
    """

    def one_type_fn(state: NodeState, tp, key, which: str):
        ctx_feas = jnp.ones(state.num_nodes, jnp.bool_)
        ctx = ScoreContext(tp=tp, feasible=ctx_feas, rng=key)

        def one_type(tpod):
            feas = filter_nodes(state, tpod)
            scores = []
            sdev = jnp.full(state.num_nodes, -1, jnp.int32)
            for i, (fn, _) in enumerate(policies):
                if fn.policy_name == "RandomScore":
                    # its score row is a per-event draw the replay body
                    # recomputes; the table slot is never read
                    scores.append(jnp.zeros(state.num_nodes, jnp.int32))
                    continue
                res = _group_fn(fn, which)(state, tpod, ctx)
                scores.append(res.raw_scores)
                if i == sel_idx:
                    sdev = res.share_dev
            return jnp.stack(scores), sdev, feas

        return one_type

    def columns(state1: NodeState, types: PodTypes, tp, key):
        outs = []
        for which, specs in (("share", types.share), ("whole", types.whole)):
            if specs.cpu.shape[0]:
                outs.append(jax.vmap(one_type_fn(state1, tp, key, which))(specs))
        scores = jnp.concatenate([o[0][:, :, 0] for o in outs], 0)  # [K,π]
        sdev = jnp.concatenate([o[1][:, 0] for o in outs], 0)  # [K]
        feas = jnp.concatenate([o[2][:, 0] for o in outs], 0)  # [K]
        return scores.T, sdev, feas

    def init_tables(state: NodeState, types: PodTypes, tp, key):
        outs = []
        for which, specs in (("share", types.share), ("whole", types.whole)):
            if specs.cpu.shape[0]:
                outs.append(jax.lax.map(one_type_fn(state, tp, key, which), specs))
        scores = jnp.concatenate([o[0] for o in outs], 0)  # [K,π,N]
        sdev = jnp.concatenate([o[1] for o in outs], 0)  # [K,N]
        feas = jnp.concatenate([o[2] for o in outs], 0)  # [K,N]
        return jnp.swapaxes(scores, 0, 1), sdev, feas

    return columns, init_tables


def make_table_replay(policies, gpu_sel: str = "best", report: bool = False):
    """Build the jitted incremental replayer for a static policy config.

    policies: [(policy_fn, weight)] — all must be table-izable (raw score a
    pure function of node state + pod spec; RandomScore is not).

    The replay is metric-free: per-event report rows (the reference
    recomputes frag/alloc/power cluster-wide after every event,
    simulator.go:426-427, its dominant cost) are reconstructed from the
    emitted (event_node, event_dev) telemetry by the shared vectorized
    post-pass, tpusim.sim.metrics.compute_event_metrics — identical across
    engines by construction. `report` is accepted for signature
    compatibility and must be False.
    """
    if report:
        raise ValueError(
            "the table engine replays metric-free; build the report series "
            "with tpusim.sim.metrics.compute_event_metrics"
        )
    cache_key = (tuple((fn, w) for fn, w in policies), gpu_sel, report)
    if cache_key in _TABLE_REPLAY_CACHE:
        return _TABLE_REPLAY_CACHE[cache_key]
    num_pol = len(policies)
    sel_idx = selector_index(policies, gpu_sel)
    _columns, _init_tables = make_table_builders(policies, sel_idx)

    @jax.jit
    def replay(
        state: NodeState,
        pods: PodSpec,  # [P]
        types: PodTypes,  # host-side build_pod_types(pods)
        ev_kind: jnp.ndarray,  # i32[E]
        ev_pod: jnp.ndarray,  # i32[E]
        tp,
        key,
        tiebreak_rank=None,
    ) -> ReplayResult:
        n = state.num_nodes
        num_pods = pods.cpu.shape[0]
        if tiebreak_rank is None:
            tiebreak_rank = jnp.arange(n, dtype=jnp.int32)
        type_id = types.type_id

        # the event key chain must stay byte-for-byte the sequential
        # oracle's (it never burns a split before its scan), so the random
        # replay path below sees identical per-event keys; no table-ized
        # column kernel consumes rng, so init can reuse the root key as-is
        score_tbl, sdev_tbl, feas_tbl = _init_tables(state, types, tp, key)

        placed = jnp.full(num_pods, -1, jnp.int32)
        masks = jnp.zeros((num_pods, MAX_GPUS_PER_NODE), jnp.bool_)
        failed = jnp.zeros(num_pods, jnp.bool_)

        def body(carry, ev):
            (state, score_tbl, sdev_tbl, feas_tbl, dirty,
             placed, masks, failed, arr_cpu, arr_gpu, key) = carry
            kind, idx = ev
            pod = jax.tree.map(lambda a: a[idx], pods)
            t_id = type_id[idx]
            # the sequential oracle's split discipline exactly (engine.py
            # body: key, sub = split(key); schedule_one: k_rand, k_sel =
            # split(sub)) — this is what makes the per-event random draws
            # below bit-identical to the oracle's
            key, sub = jax.random.split(key)
            k_rand, k_sel = jax.random.split(sub)

            # refresh the one column whose node changed last event
            col_scores, col_sdev, col_feas = _columns(
                _row_state(state, dirty), types, tp, k_rand
            )
            score_tbl = jax.lax.dynamic_update_slice(
                score_tbl, col_scores[:, :, None], (0, 0, dirty)
            )
            sdev_tbl = jax.lax.dynamic_update_slice(
                sdev_tbl, col_sdev[:, None], (0, dirty)
            )
            feas_tbl = jax.lax.dynamic_update_slice(
                feas_tbl, col_feas[:, None], (0, dirty)
            )

            def do_create():
                feasible = feas_tbl[t_id] & (
                    (pod.pinned < 0) | (jnp.arange(n, dtype=jnp.int32) == pod.pinned)
                )
                total = jnp.zeros(n, jnp.int32)
                for i, (fn, weight) in enumerate(policies):
                    if fn.policy_name == "RandomScore":
                        # per-event draw, recomputed instead of table-read —
                        # through the ONE canonical kernel (the oracle's
                        # schedule_one calls the same fn with the same
                        # feasible mask and k_rand)
                        ctx = ScoreContext(tp=tp, feasible=feasible, rng=k_rand)
                        raw = fn(state, pod, ctx).raw_scores
                    else:
                        raw = score_tbl[i, t_id]
                    if fn.normalize == "minmax":
                        raw = minmax_normalize_i32(raw, feasible)
                    elif fn.normalize == "pwr":
                        raw = pwr_normalize_i32(raw, feasible)
                    total = total + jnp.int32(weight) * raw
                new_state, pl = select_and_bind(
                    state, pod, feasible, total, sdev_tbl[t_id], gpu_sel,
                    k_sel, tiebreak_rank,
                )
                return (
                    new_state,
                    placed.at[idx].set(pl.node),
                    masks.at[idx].set(pl.dev_mask),
                    failed.at[idx].set(pl.node < 0),
                    jnp.maximum(pl.node, 0),
                    # arrived counters accumulate per creation event
                    # regardless of outcome (simulator.go:406-408)
                    arr_cpu + pod.cpu,
                    arr_gpu + pod.total_gpu_milli(),
                    pl.node,
                    pl.dev_mask,
                )

            def do_delete():
                pl = Placement(placed[idx], masks[idx])
                new_state = unschedule(state, pod, pl)
                return (
                    new_state,
                    placed.at[idx].set(-1),
                    masks.at[idx].set(False),
                    failed,
                    jnp.maximum(pl.node, 0),
                    arr_cpu,
                    arr_gpu,
                    pl.node,
                    pl.dev_mask,
                )

            def do_skip():
                return (
                    state, placed, masks, failed, dirty, arr_cpu, arr_gpu,
                    jnp.int32(-1), jnp.zeros(MAX_GPUS_PER_NODE, jnp.bool_),
                )

            (state2, placed2, masks2, failed2, dirty2, arr_cpu2, arr_gpu2,
             node, dev) = jax.lax.switch(
                jnp.clip(kind, 0, 2), [do_create, do_delete, do_skip]
            )
            return (
                state2, score_tbl, sdev_tbl, feas_tbl, dirty2,
                placed2, masks2, failed2, arr_cpu2, arr_gpu2, key,
            ), (node, dev)

        init = (state, score_tbl, sdev_tbl, feas_tbl, jnp.int32(0),
                placed, masks, failed, jnp.int32(0), jnp.int32(0), key)
        # unroll amortizes per-iteration fixed costs (~20% wall on the openb
        # replay); higher factors showed no further gain
        (state, _, _, _, _, placed, masks, failed, _, _, _), (
            nodes, devs
        ) = jax.lax.scan(body, init, (ev_kind, ev_pod), unroll=4)
        return ReplayResult(state, placed, masks, failed, None, nodes, devs)

    _TABLE_REPLAY_CACHE[cache_key] = replay
    return replay
