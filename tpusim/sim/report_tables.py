"""End-of-run ASCII report tables (ref: pkg/apply/apply.go:289-548 report()).

The reference builds tablewriter tables for per-pod placement, per-node
utilization, and per-GPU-device occupancy. That function is defined but not
wired into Run() in the reference revision; here it is a first-class output
surface behind the CLI's --report flag.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from tpusim.constants import MILLI
from tpusim.io.trace import NodeRow, PodRow


def _table(header: List[str], rows: List[List[str]]) -> str:
    widths = [len(h) for h in header]
    for r in rows:
        for i, cell in enumerate(r):
            widths[i] = max(widths[i], len(cell))
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    out = [sep, "| " + " | ".join(h.ljust(w) for h, w in zip(header, widths)) + " |", sep]
    for r in rows:
        out.append("| " + " | ".join(c.ljust(w) for c, w in zip(r, widths)) + " |")
    out.append(sep)
    return "\n".join(out)


def pod_info_table(
    pods: Sequence[PodRow],
    placed_node: np.ndarray,
    nodes: Sequence[NodeRow],
    gpu: bool = True,
) -> str:
    """Per-pod placement table (apply.go:291-372), sorted by node."""
    rows = []
    for i, p in enumerate(pods):
        ni = int(placed_node[i])
        if ni < 0:
            continue
        n = nodes[ni]
        cpu_frac = 100.0 * p.cpu_milli / n.cpu_milli if n.cpu_milli else 0
        mem_frac = 100.0 * p.memory_mib / n.memory_mib if n.memory_mib else 0
        row = [
            n.name,
            p.name,
            f"{p.cpu_milli}m({int(cpu_frac)}%)",
            f"{p.memory_mib}Mi({int(mem_frac)}%)",
        ]
        if gpu:
            milli = p.total_gpu_milli
            ratio = int(100.0 * milli / (n.gpu * MILLI)) if n.gpu else 0
            row.append(f"{milli}({ratio}%)")
        row.append(p.workload_name)
        rows.append(row)
    rows.sort(key=lambda r: r[0])
    header = ["Node", "Pod", "CPU Requests", "Memory Requests"]
    if gpu:
        header.append("GPU Milli Requests")
    header.append("APP Name")
    return "Pod Info\n" + _table(header, rows)


def node_info_table(
    pods: Sequence[PodRow],
    placed_node: np.ndarray,
    nodes: Sequence[NodeRow],
    gpu: bool = True,
) -> str:
    """Per-node utilization table (apply.go:374-470) + cluster totals."""
    n_nodes = len(nodes)
    cpu_req = np.zeros(n_nodes, np.int64)
    mem_req = np.zeros(n_nodes, np.int64)
    gpu_req = np.zeros(n_nodes, np.int64)
    cnt = np.zeros(n_nodes, np.int64)
    for i, p in enumerate(pods):
        ni = int(placed_node[i])
        if ni < 0:
            continue
        cpu_req[ni] += p.cpu_milli
        mem_req[ni] += p.memory_mib
        gpu_req[ni] += p.total_gpu_milli
        cnt[ni] += 1
    rows = []
    for ni, n in enumerate(nodes):
        cpu_frac = 100.0 * cpu_req[ni] / n.cpu_milli if n.cpu_milli else 0
        mem_frac = 100.0 * mem_req[ni] / n.memory_mib if n.memory_mib else 0
        row = [
            n.name,
            f"{n.cpu_milli}m",
            f"{int(cpu_req[ni])}m({int(cpu_frac)}%)",
            f"{n.memory_mib}Mi",
            f"{int(mem_req[ni])}Mi({int(mem_frac)}%)",
        ]
        if gpu:
            frac = 100.0 * gpu_req[ni] / (n.gpu * MILLI) if n.gpu else 0
            row += [str(n.gpu), f"{int(gpu_req[ni])}({int(frac)}%)"]
        row.append(str(int(cnt[ni])))
        rows.append(row)
    header = ["Node", "CPU", "CPU Requests", "Memory", "Memory Requests"]
    if gpu:
        header += ["GPU", "GPU Milli Requests"]
    header.append("Pod Count")
    return "Node Info\n" + _table(header, rows)


def gpu_device_table(
    pods: Sequence[PodRow],
    placed_node: np.ndarray,
    dev_mask: np.ndarray,
    nodes: Sequence[NodeRow],
) -> str:
    """Per-device occupancy (apply.go:472-548: node × GPU index → milli
    used and resident pods)."""
    rows = []
    for ni, n in enumerate(nodes):
        if n.gpu == 0:
            continue
        for d in range(n.gpu):
            on_dev = [
                (i, p)
                for i, p in enumerate(pods)
                if int(placed_node[i]) == ni and bool(dev_mask[i, d])
            ]
            if not on_dev:
                continue
            milli = sum(p.gpu_milli for _, p in on_dev)
            rows.append(
                [
                    n.name,
                    n.model,
                    str(d),
                    f"{milli}/{MILLI}",
                    ", ".join(p.name for _, p in on_dev),
                ]
            )
    return "GPU Device Info\n" + _table(
        ["Node", "Model", "GPU Index", "Milli Used", "Pods"], rows
    )


def _bytes_str(n: int) -> str:
    """Binary-SI quantity rendering like k8s resource.Quantity.String()."""
    for suf, div in (("Ti", 1024**4), ("Gi", 1024**3), ("Mi", 1024**2), ("Ki", 1024)):
        if n and n % div == 0:
            return f"{n // div}{suf}"
        if n >= div:
            return f"{n / div:.1f}{suf}"
    return str(n)


def node_storage_table(nodes: Sequence[NodeRow]) -> str:
    """Node Local Storage table (ref: apply.go:440-490): one VG row per
    volume group with requested% and one row per exclusive device."""
    from tpusim.io.storage import parse_node_storage

    rows = []
    for n in nodes:
        st = parse_node_storage(n.local_storage)
        if st is None:
            continue
        for vg in st.vgs:
            pct = int(vg.requested / vg.capacity * 100) if vg.capacity else 0
            rows.append(
                [n.name, "VG", vg.name, _bytes_str(vg.capacity),
                 f"{_bytes_str(vg.requested)}({pct}%)"]
            )
        for dev in st.devices:
            rows.append(
                [n.name, f"Device({dev.media_type})", dev.device,
                 _bytes_str(dev.capacity),
                 "used" if dev.is_allocated else "unused"]
            )
    return "Node Local Storage\n" + _table(
        ["Node", "Storage Kind", "Storage Name", "Storage Allocatable",
         "Storage Requests"],
        rows,
    )


def full_report(
    pods: Sequence[PodRow],
    placed_node: np.ndarray,
    dev_mask: np.ndarray,
    nodes: Sequence[NodeRow],
    extended_resources: Sequence[str] = ("gpu",),
) -> str:
    gpu = "gpu" in extended_resources
    parts = [
        pod_info_table(pods, placed_node, nodes, gpu),
        node_info_table(pods, placed_node, nodes, gpu),
    ]
    if gpu:
        parts.append(gpu_device_table(pods, placed_node, dev_mask, nodes))
    if "open-local" in extended_resources:
        parts.append(node_storage_table(nodes))
    return "\n\n".join(parts)
