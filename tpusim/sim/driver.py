"""Host-side experiment driver — the Simulate() orchestration
(ref: pkg/simulator/core.go:86-268 + the Simulator struct's Interface
surface, core.go:43-74).

The driver owns everything that happens once per experiment (trace prep,
typical pods, tuning, config); the per-event hot loop runs entirely on
device via tpusim.sim.engine.make_replay.
"""

from __future__ import annotations

import math
import os
import sys
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpusim.constants import MILLI
from tpusim.io.trace import (
    NodeRow,
    PodRow,
    build_events,
    nodes_to_state,
    pods_to_specs,
    tiebreak_rank,
)
from tpusim.policies import make_policy
from tpusim.sim.engine import make_replay
from tpusim.sim.fetch import device_fetch
from tpusim.sim.reports import (
    LogSink,
    cluster_analysis_block,
    report_failed_pods,
)
from tpusim.sim.typical import (
    TypicalPodsConfig,
    get_skyline_pods,
    get_typical_pods,
    pad_typical_pods,
)
from tpusim.sim.workload import sort_cluster_pods, tune_pods
from tpusim.types import NodeState, TypicalPods


@dataclass
class SimulatorConfig:
    """Experiment knobs (ref: CustomConfig, pkg/api/v1alpha1/types.go:57-109,
    + scheduler-config plugin selection, §5.6)."""

    policies: Sequence[Tuple[str, int]] = (("FGDScore", 1000),)
    gpu_sel_method: str = "best"  # best | worst | random | <policy name>
    dim_ext_method: str = "share"
    norm_method: str = "max"
    shuffle_pod: bool = False
    tuning_ratio: float = 0.0
    tuning_seed: int = 233
    inflation_ratio: float = 1.0
    inflation_seed: int = 233
    typical_pods: TypicalPodsConfig = field(default_factory=TypicalPodsConfig)
    deschedule_ratio: float = 0.0
    deschedule_policy: str = ""
    seed: int = 42  # node tie-break permutation + jax PRNG
    report_per_event: bool = True
    use_timestamps: bool = False
    # replay engine: auto (fastest supported), or force one of
    # sequential | table | pallas (ENGINES.md). `auto` picks the fused
    # Pallas engine on TPU backends for supported configs, else the
    # incremental table engine, else the sequential oracle. Degenerate
    # workloads (zero distinct pod types / fewer events than types) always
    # run the sequential path — the table init would cost more than it
    # saves; a forced table/pallas engine still applies whenever at least
    # one pod type exists. The seed-batched sweep (schedule_pods_batch)
    # honors `sequential`; `pallas` has no batched form and batches run
    # the (bit-identical) table engine instead.
    engine: str = "auto"
    # table-engine select layout (tpusim.sim.table_engine.resolve_block_size):
    # 0 = auto (blocked incremental reductions over ~sqrt(N/K)-node blocks
    # at large N, flat elsewhere — openb-scale traces stay flat), > 0 =
    # force that block size, < 0 = force the flat O(N) select. Placements
    # are bit-identical either way; this is purely a throughput knob for
    # the 100k-node scale lane.
    block_size: int = 0
    # Flat-path select layout A/B (ENGINES.md Round 18): True replaces
    # the flat table engine's event switch with the shard engine's
    # unconditional-select form (score rows never cross a branch
    # boundary; small results merge by kind). Bit-identical either way;
    # MEASURED slower on the CPU backend at N=100k (the switch's
    # in-branch row reads lower as plain gathers there), so the default
    # keeps the switch — the knob exists for accelerator backends and
    # A/B measurement (bench_scale --unswitched).
    unswitched_select: bool = False
    # Fused-Pallas table residency (ENGINES.md Round 19): where the
    # [K, N] score/sdev/feas tables live across the kernel's grid steps.
    # "vmem" is the original all-resident layout (fastest, zero DMA,
    # ceiling N <= 4096 at K = 151); "hbm" keeps the tables (and the
    # mutable node state) HBM-resident and crosses only the event's
    # active working set into VMEM by per-event double-buffered async
    # DMA, with selectHost running over VMEM-resident block summaries —
    # ceiling HBM-bounded (>= 256k at K = 151). "auto" (default) picks
    # the first tier whose footprint fits the budget
    # (pallas_engine.select_residency); only when NEITHER fits does the
    # dispatch degrade to the blocked table engine — the [Degrade] path,
    # narrowed from "any table set over ~14 MiB" to genuinely
    # VMEM-impossible shapes. Placements are bit-identical across all
    # three (the interpreter-mode oracle tests pin it); this is purely a
    # capacity/throughput knob.
    table_residency: str = "auto"
    # HTTP scheduler extenders (tpusim.sim.extender.ExtenderConfig tuple).
    # When set, every replay runs the host-loop extender engine — the only
    # execution mode that can splice per-cycle HTTP round-trips between
    # Score and selectHost (ref: simulator.go:196 WithExtenders)
    extenders: tuple = ()
    # Exact checkpoint/resume of the event scan (ENGINES.md
    # "Checkpoint/resume"): > 0 cuts every table/shard-engine replay into
    # checkpoint_every-event segments and persists the full engine carry
    # (state + score/feas/sdev tables + blocked summaries + the
    # PendingCommit pipeline register + the PRNG key) plus the telemetry
    # accumulated so far to a content-addressed file after each segment. A
    # killed run re-invoked with identical inputs resumes at the last
    # completed segment and finishes bit-identically to an uninterrupted
    # scan. 0 disables (the default: one unsegmented scan).
    checkpoint_every: int = 0
    # Where checkpoint files live; resolution order: this field if
    # non-empty, else $TPUSIM_CHECKPOINT_DIR, else
    # <repo>/.tpusim_checkpoints. Only consulted when checkpoint_every > 0.
    checkpoint_dir: str = ""
    # Checkpoint retention (ISSUE 16, `--checkpoint-keep`): 0 keeps the
    # PR 2 resume-only discipline — each save prunes its predecessors and
    # run completion prunes everything (checkpoints exist only to survive
    # a kill). -1 retains EVERY mid-trace checkpoint: the warm-state fork
    # mode, where the svc fork index maps a what-if job to the nearest
    # checkpoint at-or-before its divergence point — pruning would delete
    # exactly what the index needs. N > 0 bounds disk instead: the newest
    # N checkpoints survive, older fork points degrade to full replay.
    checkpoint_keep: int = 0
    # ---- observability (tpusim.obs; ENGINES.md "Round 8") ----
    # profile=True switches the always-on span recorder into profiling
    # mode: the driver blocks on each phase result so spans carry the
    # compile(dispatch)/execute(block) wall split, and derives counters
    # from telemetry for engines whose scan does not count (pallas,
    # extender). Placements and metrics are unaffected either way; the
    # extra sync points cost < 2% on `make bench-scale-smoke` (measured,
    # ENGINES.md Round 8).
    profile: bool = False
    # > 0 fires an obs.heartbeat progress line (events/s, ETA) from
    # INSIDE the table engine's compiled scan every N processed events —
    # long-scan liveness for the 100k-node lane. Baked into the engine
    # jaxpr (part of its cache key); 0 = off. Table engine only (the
    # shard/pallas loops carry no host callback).
    heartbeat_every: int = 0
    # Content-keyed init_tables cache (ROADMAP open item): a directory
    # here (or $TPUSIM_TABLE_CACHE_DIR when empty) lets repeat runs skip
    # the ~27 s N=100k K-node-sweep table build by reloading the tables
    # under the checkpoint content-addressing discipline
    # (io.storage.save_tables; digest = engine-source salt + config +
    # state/types/typical). Bit-identical by construction; obs records
    # the hit/miss. Empty + unset env = disabled. Single-device table
    # engine only (the shard engine builds its tables sharded).
    table_cache_dir: str = ""
    # Decision-provenance flight recorder (ISSUE 4; tpusim.obs.decisions):
    # True makes every replay additionally emit a per-event
    # DecisionRecord stream — winner + per-policy raw/normalized score
    # contributions, top-K runner-ups with tie-break ranks, feasible
    # count, winning block — surfaced as ReplayResult.decisions →
    # SimulateResult.decisions (a DecisionLog) and persisted by `tpusim
    # apply --decisions-out`. Bit-reproducible and engine-invariant
    # (decisions.INVARIANT_FIELDS) across the sequential/flat/blocked/
    # shard engines, and transparent to checkpoint kill/resume and fault
    # segmentation. Unsupported by the fused Pallas kernel (auto falls
    # back to the table engine; a forced engine: pallas raises) and by
    # extender configs / the seed-batched sweep path.
    record_decisions: bool = False
    # In-scan cluster time-series plane (ISSUE 5; tpusim.obs.series):
    # > 0 makes every replay emit one bounded-shape SeriesSample each
    # `series_every` processed events FROM INSIDE the scan — node-
    # utilization histogram, per-FGD-category frag, feasible-node count,
    # per-policy normalized score extrema, DOWN-node count — surfaced as
    # ReplayResult.series → SimulateResult.series (a SeriesLog) and
    # persisted in the JSONL run record / Chrome counter tracks /
    # `tpusim apply --listen` live endpoint. Bit-identical across the
    # sequential/flat/blocked/shard engines and continuous across
    # checkpoint kill/resume and fault segmentation (the stride clock is
    # the carry's event counter). A static build flag (the sampling cond
    # bakes into the jaxpr): 0 = off, scan bodies compile identical to
    # pre-series builds. Unsupported by the fused Pallas kernel (auto
    # falls back to the table engine; a forced engine: pallas raises)
    # and by extender configs / the seed-batched sweep path.
    series_every: int = 0
    # JAX persistent compilation cache (ISSUE 6 satellite): a directory
    # here (or $TPUSIM_COMPILE_CACHE_DIR when empty) makes apply /
    # bench_scale wire jax_compilation_cache_dir before the first
    # dispatch, so a re-run of the same job family loads its compiled
    # scan from disk instead of paying the ~5 s XLA compile. Empty +
    # unset env = disabled. The obs run record notes whether the first
    # scan compile looked like a cache hit (dispatch-wall heuristic —
    # obs.spans.note_compile_cache).
    compile_cache_dir: str = ""
    # Fault-replay execution mode (ISSUE 10): "auto" runs fault
    # schedules INSIDE the compiled scan (tpusim.sim.fault_lane — fault
    # events + an in-carry retry queue as merged stream operands, the
    # chaos-sweep lane) whenever the config allows, falling back to the
    # PR 2 segmented host loop for configs only it can serve (per-event
    # reporting, extenders, decisions/series recording, checkpointing,
    # pallas, heartbeat). "scan" forces the in-scan lane (raises on
    # unsupported configs); "segments" forces the host loop. Both paths
    # are bit-identical for deterministic configs (the acceptance pin);
    # per-event-random configs (RandomScore / gpu_sel random) draw a
    # different — still seeded and reproducible — PRNG chain on the scan
    # lane, because the segmented path's per-segment key fold-in was an
    # artifact of the segmentation.
    fault_mode: str = "auto"
    # Device-mesh width: 0 = single device; N > 1 shards the node axis
    # over an N-device jax.sharding.Mesh and replays on the
    # explicit-collective shard_map engine (tpusim.parallel.shard_engine;
    # MULTICHIP.md). Placements stay bit-identical to the single-device
    # table engine, so merged analysis CSVs are unchanged. Requires N
    # visible devices and a deterministic config (no RandomScore /
    # gpuSelMethod random / extenders).
    mesh: int = 0


@dataclass
class UnscheduledPod:
    """ref: pkg/type/simulate_result.go:10-13."""

    pod: PodRow
    reason: str = "unschedulable"


@dataclass
class SimulateResult:
    """ref: pkg/type/simulate_result.go:5-18 + replay telemetry."""

    unscheduled_pods: List[UnscheduledPod]
    placed_node: np.ndarray  # i32[P] final node per pod (-1 = none)
    dev_mask: np.ndarray  # bool[P, 8]
    state: NodeState
    pods: List[PodRow]
    node_names: List[str]
    wall_seconds: float
    events: int
    # i64[P] position of each pod's creation event in scheduling order
    # (-1 = never created); feeds the assume-time annotation, whose purpose
    # is recovering scheduling order from a snapshot
    creation_rank: np.ndarray = None
    # tpusim.obs.RunTelemetry snapshot for this run: phase spans
    # (compile/execute split), exact in-scan counters, degrade/fault
    # counts, table-cache outcome. Always populated (the recorder is
    # always on); walls are only phase-attributed under cfg.profile.
    telemetry: object = None
    # tpusim.obs.decisions.DecisionLog for this run (records + the event
    # stream they describe), host-side. None unless
    # SimulatorConfig.record_decisions; fault runs concatenate their
    # segment streams, schedule_additional appends.
    decisions: object = None
    # tpusim.obs.series.SeriesLog for this run (filtered samples on the
    # run-global event clock, host-side). None unless
    # SimulatorConfig.series_every > 0; fault runs concatenate their
    # segment logs (pos rebased, retry_depth filled per segment),
    # schedule_additional appends.
    series: object = None


_BELLMAN_SRC_DIGEST = None
_ENGINE_SRC_DIGEST = None


def _engine_source_digest() -> bytes:
    """sha256 over every source file that determines a replay trajectory —
    the checkpoint content key's version salt (the Bellman-cache pattern):
    changing any engine/policy/op code invalidates all prior checkpoints
    instead of resuming into divergence."""
    global _ENGINE_SRC_DIGEST
    if _ENGINE_SRC_DIGEST is None:
        import glob
        import hashlib

        h = hashlib.sha256()
        base = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        files = [
            os.path.join(base, rel)
            for rel in (
                "sim/engine.py", "sim/step.py", "sim/table_engine.py",
                "parallel/shard_engine.py", "io/storage.py", "constants.py",
                "types.py",
                # the counter vocabulary shapes the carry's ctr leaf (and
                # thus the checkpoint layout); changing it must invalidate
                # old checkpoints and cached tables rather than resume into
                # a layout mismatch
                "obs/counters.py",
                # the decision vocabulary shapes the checkpointed decision
                # stream (ISSUE 4) — same invalidation discipline
                "obs/decisions.py",
                # the series vocabulary shapes the checkpointed sample
                # stream (ISSUE 5) — same invalidation discipline
                "obs/series.py",
                # the fault vocabulary shapes the fault-lane trajectory
                # and the FaultCarry layout (ISSUE 10) — same discipline
                "sim/fault_lane.py",
                # the learned-policy feature kernels are score plugins
                # like everything under policies/ (ISSUE 14): editing a
                # feature must invalidate checkpoints and cached tables
                # built from the old vocabulary
                "learn/policy.py",
            )
        ]
        files += glob.glob(os.path.join(base, "policies", "*.py"))
        files += glob.glob(os.path.join(base, "ops", "*.py"))
        for path in sorted(files):
            if os.path.isfile(path):
                with open(path, "rb") as f:
                    h.update(f.read())
        _ENGINE_SRC_DIGEST = h.digest()
    return _ENGINE_SRC_DIGEST


def enable_compile_cache(cache_dir: str = "") -> Optional[str]:
    """Wire the JAX persistent compilation cache (ISSUE 6 satellite).

    Resolution order: `cache_dir` (SimulatorConfig.compile_cache_dir)
    if non-empty, else $TPUSIM_COMPILE_CACHE_DIR, else disabled (returns
    None). Must run before the first jitted dispatch to cover the scan
    compile; apply/bench_scale call it right after argument parsing.
    The min-compile-time/entry-size floors are dropped so even the
    smoke-sized scans populate the cache (knob names vary across jax
    versions — absent ones are skipped)."""
    d = cache_dir or os.environ.get("TPUSIM_COMPILE_CACHE_DIR", "")
    if not d:
        return None
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    for opt, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(opt, val)
        except Exception:
            pass
    try:
        # jax latches "is the cache used" ONCE per process, at the first
        # compile — and importing tpusim compiles a few tiny jits before
        # any caller can wire the dir, pinning the cache off for the
        # whole run. Clear the latch so the next compile re-checks the
        # (now set) cache dir.
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass
    return d


def validate_events(ev_kind, ev_pod, num_pods: int) -> None:
    """Trace validation at run_events entry: a malformed event stream must
    fail loudly HERE, not produce silent wrong answers downstream — under
    jit, an out-of-range pod index turns the Bind scatter into a dropped
    write (XLA scatter semantics) and an unknown kind is clipped into
    EV_SKIP, both of which replay 'successfully' with quietly wrong
    placements and metrics."""
    from tpusim.sim.engine import EV_CREATE, EV_SKIP

    kinds = np.asarray(ev_kind)
    pods = np.asarray(ev_pod)
    if kinds.ndim != 1 or pods.shape != kinds.shape:
        raise ValueError(
            f"event stream shape mismatch: ev_kind {kinds.shape} vs "
            f"ev_pod {pods.shape} (want matching 1-D arrays)"
        )
    bad = (kinds < EV_CREATE) | (kinds > EV_SKIP)
    if bad.any():
        i = int(np.flatnonzero(bad)[0])
        raise ValueError(
            f"event {i}: unknown kind {int(kinds[i])} (expected EV_CREATE=0"
            " | EV_DELETE=1 | EV_SKIP=2; NodeFail/NodeRecover/Evict fault"
            " events are host-level — route them through"
            " Simulator.schedule_pods_with_faults, not run_events)"
        )
    oob = (pods < 0) | (pods >= num_pods)
    if oob.any():
        i = int(np.flatnonzero(oob)[0])
        raise ValueError(
            f"event {i}: pod index {int(pods[i])} out of range for "
            f"{num_pods} pods — a bad trace would otherwise become a "
            "silent no-op scatter under jit"
        )


def _bellman_source_digest() -> bytes:
    """sha256 of the native Bellman evaluator source + the Python fallback
    — the cache-key version salt (computed once per process)."""
    global _BELLMAN_SRC_DIGEST
    if _BELLMAN_SRC_DIGEST is None:
        import hashlib

        h = hashlib.sha256()
        base = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for rel in ("native/bellman.cpp", "native/__init__.py",
                    "ops/frag.py"):
            path = os.path.join(base, rel)
            if os.path.isfile(path):
                with open(path, "rb") as f:
                    h.update(f.read())
        _BELLMAN_SRC_DIGEST = h.digest()
    return _BELLMAN_SRC_DIGEST


class Simulator:
    """Drives one cluster + workload through the compiled replay.

    Method surface mirrors simulator.Interface (core.go:43-74); the fake
    API server / informer machinery has no equivalent — cluster state is
    the NodeState array itself.
    """

    def __init__(self, nodes: Sequence[NodeRow], cfg: SimulatorConfig = None):
        self.cfg = cfg or SimulatorConfig()
        self.nodes = list(nodes)
        self.node_names = [n.name for n in self.nodes]
        self.node_index = {n.name: i for i, n in enumerate(self.nodes)}
        self.init_state = nodes_to_state(self.nodes)
        self.rank = jnp.asarray(tiebreak_rank(len(self.nodes), self.cfg.seed))
        self.log = LogSink(stream=None)
        # the observability plane (tpusim.obs): spans + counters are
        # always recorded (two perf_counter calls per phase); profile=True
        # additionally blocks per phase for the compile/execute split
        from tpusim.obs import Recorder

        self.obs = Recorder(enabled=self.cfg.profile)
        self._bellman_eval = None
        self._bellman_pending_replay = None
        self.workload_pods: List[PodRow] = []
        self.typical: Optional[TypicalPods] = None
        self.node_total_milli_cpu = int(sum(n.cpu_milli for n in self.nodes))
        self.node_total_milli_gpu = int(sum(n.gpu * MILLI for n in self.nodes))
        self.total_gpus = int(sum(n.gpu for n in self.nodes))
        self._policy_fns = [
            (
                make_policy(
                    name,
                    dim_ext_method=self.cfg.dim_ext_method,
                    norm_method=self.cfg.norm_method,
                ),
                weight,
            )
            for name, weight in self.cfg.policies
        ]
        # the sequential oracle replay; run_events() below picks between it
        # and the incremental table engine per call. Engines always run
        # metric-free: the per-event report series is reconstructed from
        # replay telemetry by the shared post-pass (tpusim.sim.metrics) —
        # identical across engines by construction
        if self.cfg.record_decisions and self.cfg.extenders:
            raise ValueError(
                "record_decisions cannot combine with extenders (the "
                "host-loop extender engine splices HTTP scores the "
                "flight recorder does not capture)"
            )
        if self.cfg.series_every and self.cfg.extenders:
            raise ValueError(
                "series_every cannot combine with extenders (the "
                "host-loop extender engine has no in-scan sampling "
                "plane)"
            )
        if self.cfg.series_every < 0:
            raise ValueError(
                f"series_every must be >= 0 (got {self.cfg.series_every})"
            )
        self.replay_fn = make_replay(
            self._policy_fns,
            gpu_sel=self.cfg.gpu_sel_method,
            report=False,
            decisions=self.cfg.record_decisions,
            series_every=self.cfg.series_every,
        )
        # device-phase wall of the last schedule_pods_batch call this sim
        # led (dispatch + fetch, excluding host spec prep/result slicing);
        # read by bench.py's batched row for like-for-like throughput
        self._last_batch_device_s = None
        # which engine the last run_events call dispatched to
        # (pallas | table | sequential) — bench/log labeling
        self._last_engine = None
        # run-level event offset the next heartbeat arm reports from
        # (the fault loop sets it per segment; plain runs leave it 0)
        self._hb_base = 0
        # run/job id the heartbeat ticks of this sim's scans carry
        # (ISSUE 7): the replay service sets it per job batch so the
        # shared /progress listener can keep per-job streams apart;
        # empty = the anonymous single-run behavior
        self._hb_job = ""
        # direct-CSV-path stashes (experiments/analysis.py analyze_sim):
        # per-event structured report data (one entry per reporting replay,
        # main schedule + inflation/deschedule stages, in log order) + the
        # accumulated cluster-analysis summary key/values across stages
        self.event_reports = []
        self.analysis_summary = {}
        self.failed_pod_lists = []
        from tpusim.sim.table_engine import make_table_replay

        # incremental score-table engine (tpusim.sim.table_engine): exact
        # same placements/state, ~4x faster. Since round 5 it also replays
        # per-event-random configs (RandomScore / gpuSelMethod random)
        # bit-identically — it follows the oracle's key-split discipline
        # and recomputes the draw per event instead of reading a table row
        self._table_fn = make_table_replay(
            self._policy_fns,
            gpu_sel=self.cfg.gpu_sel_method,
            report=False,
            block_size=self.cfg.block_size,
            heartbeat_every=self.cfg.heartbeat_every,
            decisions=self.cfg.record_decisions,
            series_every=self.cfg.series_every,
            unswitched=self.cfg.unswitched_select,
        )
        # fused whole-replay Pallas engine (tpusim.sim.pallas_engine): one
        # kernel for the entire event loop, ~4x the table engine on chip;
        # needs a column kernel per enabled policy. On CPU backends it runs
        # in interpreter mode — only sensible when forced (engine: pallas).
        if self.cfg.engine not in ("auto", "sequential", "table", "pallas"):
            raise ValueError(
                f"unknown engine {self.cfg.engine!r}: expected auto | "
                "sequential | table | pallas"
            )
        if self.cfg.table_residency not in ("auto", "vmem", "hbm"):
            raise ValueError(
                f"unknown table_residency {self.cfg.table_residency!r}: "
                "expected auto | vmem | hbm (the fused-Pallas table "
                "placement, ENGINES.md Round 19)"
            )
        from tpusim.sim import pallas_engine

        # report configs are no longer a pallas blocker: the engine replays
        # metric-free and the shared post-pass reconstructs the series
        self._pallas_ok = pallas_engine.supports(
            self._policy_fns, self.cfg.gpu_sel_method
        )
        if self.cfg.engine == "pallas" and not self._pallas_ok:
            raise ValueError(
                "engine: pallas requires a registered Pallas column kernel "
                "for every enabled policy and a non-random gpuSelMethod "
                "(see tpusim.sim.pallas_engine.supports)"
            )
        self._pallas_fn = None
        # HBM-residency twin (ENGINES.md Round 19), built lazily on the
        # first dispatch the residency select routes to it
        self._pallas_hbm_fn = None
        self._extender_fn = None  # built lazily on first extender replay
        self._shard_fn = None
        if self.cfg.mesh:
            # node-axis sharding over an N-device mesh: the shard_map
            # engine with hand-written collectives (flat per-event cost;
            # MULTICHIP.md). Built eagerly so misconfigurations (too few
            # devices, randomized configs) fail at construction.
            from tpusim.parallel import make_mesh
            from tpusim.parallel.shard_engine import make_shardmap_table_replay

            if self.cfg.extenders:
                raise ValueError("mesh and extenders cannot combine")
            if self.cfg.engine != "auto":
                # the mesh path IS an engine choice (the sharded table
                # engine); silently overriding a forced engine would
                # attribute shard_map numbers to whatever was requested
                raise ValueError(
                    f"mesh={self.cfg.mesh} selects the shard_map engine; "
                    f"it cannot combine with engine={self.cfg.engine!r} "
                    "(leave engine: auto)"
                )
            if self.cfg.mesh > len(jax.devices()):
                raise ValueError(
                    f"mesh={self.cfg.mesh} needs {self.cfg.mesh} devices; "
                    f"{len(jax.devices())} visible (virtual CPU meshes: set "
                    "XLA_FLAGS=--xla_force_host_platform_device_count=N "
                    "JAX_PLATFORMS=cpu)"
                )
            self._mesh = make_mesh(self.cfg.mesh)
            self._shard_fn = make_shardmap_table_replay(
                self._policy_fns, self._mesh,
                gpu_sel=self.cfg.gpu_sel_method,
                block_size=self.cfg.block_size,
                decisions=self.cfg.record_decisions,
                series_every=self.cfg.series_every,
            )
        if self.cfg.record_decisions and self.cfg.engine == "pallas":
            raise ValueError(
                "engine: pallas cannot record decisions (the fused kernel "
                "emits no per-event provenance); use the table, "
                "sequential, or shard engine"
            )
        if self.cfg.series_every and self.cfg.engine == "pallas":
            raise ValueError(
                "engine: pallas cannot emit the in-scan series (the fused "
                "kernel has no per-event sampling plane); use the table, "
                "sequential, or shard engine"
            )
        if self._pallas_ok and self.cfg.engine in ("auto", "pallas"):
            # Mosaic lowers on TPU backends only; anywhere else (cpu, gpu)
            # a forced `engine: pallas` runs the interpreter — correct but
            # slow, the CPU test lane's harness. `auto` never picks it off
            # TPU (run_events gates on the same predicate).
            self._pallas_fn = pallas_engine.make_pallas_replay(
                self._policy_fns,
                gpu_sel=self.cfg.gpu_sel_method,
                interpret=jax.default_backend() != "tpu",
            )

    def _attach_metrics(self, out, state, specs, ev_kind, ev_pod,
                        n_events=None):
        """Reconstruct the per-event report series from the replay's
        telemetry (the shared post-pass) when reporting is on, record the
        scan in obs (engine + in-scan counters, padding-corrected), and
        log the engine the dispatch used. `n_events` = true (pre-padding)
        event count for the log line."""
        true_e = int(ev_kind.shape[0]) if n_events is None else int(n_events)
        if self.cfg.heartbeat_every:
            # final 100% heartbeat tick (obs.heartbeat.complete): short
            # runs beat the 1/s rate limit and would otherwise finish
            # silently. The block is a no-op cost-wise — every consumer
            # of this result syncs on it right after anyway.
            from tpusim.obs import heartbeat as obs_heartbeat

            jax.block_until_ready(out.event_node)
            obs_heartbeat.complete(true_e)
        ctr = out.counters
        if ctr is None and self.obs.enabled:
            # engines whose loop does not count (fused pallas, extender):
            # derive the invariant prefix from the per-event telemetry —
            # exact for everything but `rebuilds` (which those engines
            # never pay). Profiling mode only: the readback syncs.
            from tpusim.obs.counters import counters_from_telemetry

            ctr = counters_from_telemetry(
                np.asarray(ev_kind), np.asarray(out.event_node)
            )
        self.obs.note_scan(
            self._last_engine, counters=ctr,
            pad_skips=int(out.event_node.shape[0]) - true_e, events=true_e,
        )
        if self.cfg.report_per_event:
            from tpusim.sim.metrics import compute_event_metrics

            with self.obs.span("metrics_postpass", events=true_e) as h:
                out = out._replace(
                    metrics=compute_event_metrics(
                        state, specs, ev_kind, ev_pod, out.event_node,
                        out.event_dev, self.typical,
                    )
                )
                h.dispatched()
                if self.obs.enabled:
                    jax.block_until_ready(out.metrics)
        # name the engine in the log: the fused engine's documented f32
        # divergence channel means TPU-vs-CPU result diffs must be
        # diagnosable from simon.log alone (the analysis parser ignores
        # unknown line families, so the CSV lanes are unaffected)
        if n_events is None:
            n_events = int(ev_kind.shape[0])
        self.log.info(
            f"[Engine] replay of {n_events} events ran on: {self._last_engine}"
        )
        return out

    def _dispatch_span(self, thunk, **meta):
        """Run one engine dispatch under an obs "scan" span. The
        dispatch/block split is the compile/execute split: the host
        returns from the jitted call once tracing+compile+enqueue are
        done, so dispatch_s on a cold call is dominated by compilation;
        profiling mode then blocks so block_s is the device execution.
        Un-profiled runs never add the sync point — async pipelining is
        untouched."""
        with self.obs.span("scan", **meta) as h:
            out = thunk()
            h.dispatched()
            if self.obs.enabled and out is not None:
                jax.block_until_ready(
                    [l for l in jax.tree.leaves(out)
                     if isinstance(l, jax.Array)]
                )
        return out

    def run_events(
        self, state, specs, ev_kind, ev_pod, key, bucket: int = 512,
        types=None, pod_rows=None, fork=None
    ):
        """Run the compiled replay on prepared arrays, auto-selecting the
        fastest engine that supports the configuration. Small batches
        (descheduler victims, inflation clones) stay on the sequential
        engine: the table init alone costs K full node-sweeps, which only
        amortizes when there are more events than distinct pod types.

        Pod/event axes are padded to `bucket` multiples (inert zero pods +
        EV_SKIP events) so that different seeds/traces of a sweep hit the
        same compiled executable instead of re-jitting per experiment;
        outputs are sliced back to true sizes. Callers replaying the same
        pod specs repeatedly (chunked streams) may pass a prebuilt
        `types = build_pod_types(specs)` to skip the host-side dedup."""
        from tpusim.sim.table_engine import build_pod_types, pad_pod_types

        # fail loudly on malformed traces BEFORE anything is dispatched —
        # under jit a bad pod index or kind degrades into silent no-op
        # scatters (see validate_events)
        validate_events(ev_kind, ev_pod, int(specs.cpu.shape[0]))

        if self.cfg.extenders:
            # extenders splice HTTP round-trips into every cycle — only
            # the host-loop engine can honor them; no padding needed
            if pod_rows is None:
                raise ValueError(
                    "extender-configured replays need the PodRow list "
                    "(run_events(..., pod_rows=...)) to build the "
                    "ExtenderArgs payloads"
                )
            if self._extender_fn is None:
                from tpusim.sim.extender import make_extender_replay

                self._extender_fn = make_extender_replay(
                    self._policy_fns, self.cfg.gpu_sel_method,
                    self.cfg.extenders,
                )
            self._last_engine = "extender"
            out = self._dispatch_span(
                lambda: self._extender_fn(
                    state, specs, ev_kind, ev_pod, self.typical, key,
                    self.rank, pod_rows, self.nodes,
                ),
                engine="extender", events=int(ev_kind.shape[0]),
            )
            return self._attach_metrics(out, state, specs, ev_kind, ev_pod)

        p, e = int(specs.cpu.shape[0]), int(ev_kind.shape[0])
        p2, e2 = _bucket_sizes(p, e, bucket)
        if fork is not None:
            # warm-state what-if (ISSUE 16): `fork = (base_ev_kind,
            # base_ev_pod, fork_event)` — this stream shares the base
            # run's prefix up to fork_event; _run_chunked resumes from
            # the base's nearest checkpoint at-or-before it. Only the
            # chunked table/shard paths can honor a fork; anything else
            # would silently full-replay, so fail loudly instead.
            if not (0 < self.cfg.checkpoint_every < e):
                raise ValueError(
                    "forked replay needs the chunked path: set "
                    "checkpoint_every in (0, num_events) "
                    f"(got {self.cfg.checkpoint_every} for {e} events)"
                )
            if self.cfg.engine not in ("table", "auto") and not self.cfg.mesh:
                raise ValueError(
                    f"forked replay needs the table or shard engine, "
                    f"not {self.cfg.engine!r}"
                )
            bk, bp, fev = fork
            if not 0 <= int(fev) <= int(np.asarray(bk).shape[0]):
                raise ValueError(
                    f"fork_event {fev} outside the base stream "
                    f"(0..{int(np.asarray(bk).shape[0])})"
                )
            # the base streams must carry the identical padding
            # discipline — the fork lookup's digest math is byte-exact
            _, be2 = _bucket_sizes(p, int(np.asarray(bk).shape[0]), bucket)
            bk, bp = _pad_events(jnp.asarray(bk), jnp.asarray(bp), be2,
                                 xp=jnp)
            fork = (bk, bp, int(fev))
        if self.cfg.heartbeat_every:
            # arm the host side of the in-scan progress ticks for this
            # dispatch (ETA needs the event total; the engine only ships
            # its processed count). The total is the PADDED stream e2 —
            # that is what the scan processes and what the carry counter
            # counts, so progress can never read > 100%
            from tpusim.obs import heartbeat as obs_heartbeat

            # base = events of the RUN already replayed by earlier
            # segments (the fault loop sets it; 0 otherwise), so chunked
            # and fault-segmented ticks report run-level progress/ETA
            obs_heartbeat.configure(
                self._hb_base + e2, "replay", base=self._hb_base,
                job=self._hb_job, worker=getattr(self, "_hb_worker", ""),
            )
        # dedup types from the UNPADDED specs (no spurious zero type); the
        # type_id axis is padded alongside the pod axis (padded events only
        # ever reference pod 0)
        if self.cfg.engine == "sequential" and not self.cfg.mesh:
            types = None
        elif types is None:
            types = build_pod_types(specs)
        specs, tid = _pad_specs(
            specs, p2, types.type_id if types is not None else None, xp=jnp
        )
        if types is not None and tid is not None:
            types = types._replace(type_id=tid)
        ev_kind, ev_pod = _pad_events(ev_kind, ev_pod, e2, xp=jnp)

        if self._shard_fn is not None:
            # mesh path: pad the node axis to the mesh width, shard state
            # + tie-break rank, replay with explicit collectives, then
            # slice the node axis back (pad rows are never chosen and
            # metric-inert)
            from tpusim.parallel import pad_nodes, shard_state

            n0 = state.num_nodes
            state_p, rank_p = pad_nodes(state, self.rank, self.cfg.mesh)
            state_p = shard_state(state_p, self._mesh)
            self._last_engine = f"shard_map (mesh={self.cfg.mesh})"
            # guard on the TRUE event count e, not the padded stream: a
            # tiny replay padded to a 512 bucket must not pay the digest/
            # checkpoint machinery it can never benefit from
            if 0 < self.cfg.checkpoint_every < e:
                # chunked scan with gather-to-host snapshots between
                # segments (exact resume; ENGINES.md "Checkpoint/resume").
                # Streams that fit in one segment skip the machinery — no
                # checkpoint could ever be written, so the digest/eval_shape
                # overhead would buy nothing
                out = self._dispatch_span(
                    lambda: self._run_chunked(
                        self._shard_fn, state_p, specs, types, ev_kind,
                        ev_pod, key, rank_p, fork=fork,
                    ),
                    engine=self._last_engine, events=e,
                )
            else:
                out = self._dispatch_span(
                    lambda: self._shard_fn(
                        state_p, specs, types, ev_kind, ev_pod,
                        self.typical, key, rank_p,
                    ),
                    engine=self._last_engine, events=e,
                )
            # the post-pass runs on the UNPADDED state: pad rows are never
            # chosen (every valid event_node < n0), and the f32 initial
            # totals then bracket exactly like a single-device run — so
            # the analysis CSVs come out byte-identical, not merely close
            out = self._attach_metrics(out, state, specs, ev_kind, ev_pod, e)
            out = out._replace(
                state=jax.tree.map(lambda a: a[:n0], out.state)
            )
            return _slice_result(out, p, e)

        out = None
        if types is not None:
            k = int(types.share.cpu.shape[0]) + int(types.whole.cpu.shape[0])
            big = k > 0 and e >= 2 * k
            if (big or (self.cfg.engine in ("table", "pallas") and k > 0)
                    or (fork is not None and k > 0)):
                if p2 != p or e2 != e:  # bucketed run: stabilize K too
                    types = pad_pod_types(types)
                # the fused Pallas engine wins whenever it applies; its
                # Mosaic path needs a real accelerator (auto never picks
                # the CPU interpreter — that is only for a forced
                # `engine: pallas` under the test lane). Decision-recording
                # runs never take it (the fused kernel emits no per-event
                # provenance; a forced engine: pallas raised at init)
                use_pallas = (
                    self._pallas_fn is not None
                    and fork is None  # fused kernel has no carry surface
                    and not self.cfg.record_decisions
                    and not self.cfg.series_every
                    and (
                        self.cfg.engine == "pallas"
                        or (self.cfg.engine == "auto" and big
                            and jax.default_backend() == "tpu")
                    )
                )
                if use_pallas:
                    # graceful degradation: a replay that would overflow
                    # the fused kernel's VMEM budget, or whose kernel dies
                    # / returns corrupt telemetry (the NaN/inf channel of
                    # its f32 score math), falls back to the blocked table
                    # engine with a [Degrade] warning instead of dying
                    out = self._run_pallas_degradable(
                        state, specs, types, ev_kind, ev_pod, key
                    )
                if out is None:
                    self._last_engine = "table"
                    # single-segment streams (true count e, not the padded
                    # stream) skip the checkpoint machinery entirely. The
                    # content-keyed init_tables reuse (obs records the
                    # hit/miss; None when disabled) resolves LAZILY on the
                    # chunked path: a run that resumes from a checkpoint
                    # restores its carry — tables included — and must not
                    # pay a table build/load it would immediately discard
                    if 0 < self.cfg.checkpoint_every < e:
                        out = self._dispatch_span(
                            lambda: self._run_chunked(
                                self._table_fn, state, specs, types,
                                ev_kind, ev_pod, key, self.rank,
                                tables_thunk=lambda: self._cached_tables(
                                    state, types, key
                                ),
                                fork=fork,
                            ),
                            engine="table", events=e,
                        )
                    else:
                        out = self._dispatch_span(
                            lambda: self._table_fn(
                                state, specs, types, ev_kind, ev_pod,
                                self.typical, key, self.rank,
                                tables=self._cached_tables(
                                    state, types, key
                                ),
                            ),
                            engine="table", events=e,
                        )
        if out is None:
            if fork is not None:
                raise ValueError(
                    "forked replay fell through to the sequential engine "
                    "(no pod types / carry surface) — run the base and "
                    "fork on the table or shard engine"
                )
            self._last_engine = "sequential"
            out = self._dispatch_span(
                lambda: self.replay_fn(
                    state, specs, ev_kind, ev_pod, self.typical, key,
                    self.rank,
                ),
                engine="sequential", events=e,
            )
        # post-pass metrics stay on device: the caller's device_fetch
        # moves everything in one transfer
        out = self._attach_metrics(out, state, specs, ev_kind, ev_pod, e)
        return _slice_result(out, p, e)

    # ---- graceful degradation (ISSUE 2: survive instead of dying) ----

    def _run_pallas_degradable(self, state, specs, types, ev_kind, ev_pod,
                               key):
        """Run the fused Pallas engine behind the degradation guards.
        Returns its ReplayResult, or None after a [Degrade] log line when
        the replay must fall back to the (blocked) table engine.

        Residency is two-tier (ENGINES.md Round 19): tier 1 is the
        all-VMEM-resident kernel (pallas_engine.fits_vmem — the measured
        ceiling N ≤ 4096 at K = 151), tier 2 the HBM-resident-table
        kernel whose VMEM working set is O(K·B + row scratch)
        (fits_hbm — HBM-bounded, ≥ 256k nodes at K = 151).
        cfg.table_residency forces a tier or lets select_residency pick;
        only when the chosen tier's footprint cannot fit does the
        dispatch degrade — the [Degrade] path is narrowed to genuinely
        VMEM-impossible shapes. A kernel that dies mid-scan or returns
        out-of-range telemetry (the observable shadow of NaN/inf
        contaminating its f32 score tables) is still caught AFTER
        dispatch. The table engine replays the identical schedule, so
        degradation changes throughput, never results."""
        from tpusim.sim import pallas_engine

        n = state.num_nodes
        k = int(types.share.cpu.shape[0]) + int(types.whole.cpu.shape[0])
        num_pol = len(self._policy_fns)
        p = int(specs.cpu.shape[0])
        e = int(ev_kind.shape[0])
        n_norm = pallas_engine.num_normalized(self._policy_fns)
        res = self.cfg.table_residency
        if res == "auto":
            res = pallas_engine.select_residency(n, k, num_pol, p, e, n_norm)
        elif res == "vmem" and not pallas_engine.fits_vmem(
                n, k, num_pol, p, e):
            res = None
        elif res == "hbm" and not pallas_engine.fits_hbm(
                n, k, num_pol, p, e, n_norm):
            res = None
        if res is None:
            # every [Degrade] channel also lands in an obs counter so a
            # degraded run is machine-detectable from the JSONL record,
            # not just greppable from stdout prose
            self.obs.count("degrade_vmem")
            self.log.info(
                f"[Degrade] fused pallas kernel would overflow VMEM at "
                f"N={n}, K={k} under table_residency="
                f"{self.cfg.table_residency!r} (neither the VMEM- nor "
                "the HBM-residency tier fits the budget): falling back "
                "to the blocked table engine"
            )
            return None
        if res == "hbm" and self._pallas_hbm_fn is None:
            self._pallas_hbm_fn = pallas_engine.make_pallas_replay(
                self._policy_fns, gpu_sel=self.cfg.gpu_sel_method,
                interpret=jax.default_backend() != "tpu",
                residency="hbm",
            )
        fn = self._pallas_fn if res == "vmem" else self._pallas_hbm_fn
        self._last_engine = "pallas" if res == "vmem" else "pallas (hbm)"
        dma_stats = None
        try:
            out = self._dispatch_span(
                lambda: fn(
                    state, specs, types, ev_kind, ev_pod, self.typical,
                    key, self.rank,
                ),
                engine=self._last_engine, events=e,
            )
            if res == "hbm":
                # the kernel's exact in-kernel DMA counters (semaphore
                # waits, DMA starts, extrema-drift summary rebuilds) —
                # surfaced in the obs run record below
                out, dma_stats = out
            bad = self._pallas_result_suspect(out, n)
        except (AttributeError, NameError, ImportError):
            # definite programming errors in the pallas path — degradation
            # must not silently paper over a broken build
            raise
        except Exception as err:  # Mosaic OOM / lowering / runtime death
            self.obs.count("degrade_runtime")
            self.log.info(
                f"[Degrade] pallas replay died mid-scan "
                f"({type(err).__name__}: {err}): falling back to the "
                "blocked table engine"
            )
            return None
        if bad:
            self.obs.count("degrade_corrupt")
            self.log.info(
                f"[Degrade] pallas replay returned corrupt telemetry "
                f"({bad}; NaN/inf in the f32 score tables?): falling back "
                "to the blocked table engine"
            )
            return None
        # the residency note/counters land only on a COMPLETED pallas
        # replay — a mid-scan death or corrupt-telemetry degrade ran the
        # blocked table engine, and the run record must say so
        self.obs.pallas_residency = res
        self.obs.count(f"pallas_residency_{res}")
        if dma_stats is not None:
            waits, starts, rebuilds = (int(v) for v in np.asarray(dma_stats))
            self.obs.count("pallas_dma_waits", waits)
            self.obs.count("pallas_dma_starts", starts)
            self.obs.count("pallas_hbm_rebuilds", rebuilds)
        return out

    def _pallas_result_suspect(self, out, num_nodes: int):
        """Cheap host-side sanity screen over a fused-kernel result: every
        placement/telemetry index must lie in [-1, N). NaN/inf poisoning
        the kernel's f32 score path surfaces as wild argmax indices, which
        this catches without exporting the tables themselves. Returns a
        description or None. Costs one [E]+[P] i32 readback — noise next
        to the replay itself."""
        ev_node = np.asarray(out.event_node)
        placed = np.asarray(out.placed_node)
        if ev_node.size and ((ev_node < -1) | (ev_node >= num_nodes)).any():
            return "event_node out of range"
        if placed.size and ((placed < -1) | (placed >= num_nodes)).any():
            return "placed_node out of range"
        return None

    # ---- exact checkpoint/resume of the chunked event scan ----

    def _checkpoint_dir(self) -> str:
        d = self.cfg.checkpoint_dir or os.environ.get(
            "TPUSIM_CHECKPOINT_DIR", ""
        )
        if not d:
            d = os.path.join(
                os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))), ".tpusim_checkpoints")
        return d

    # ---- content-keyed init_tables cache (ROADMAP open item) ----

    def _table_cache_dir(self) -> str:
        return self.cfg.table_cache_dir or os.environ.get(
            "TPUSIM_TABLE_CACHE_DIR", ""
        )

    def _tables_digest(self, state, types) -> str:
        """Content key of one table build: the engine-source salt + the
        scoring config + every input init_tables reads (initial state,
        the DISTINCT pod type set, typical pods). Deliberately NOT the
        event stream, PRNG key, tie-break rank, the per-policy WEIGHTS,
        or the per-pod `type_id` map — the build never consumes them
        (tables hold raw per-policy scores per distinct type; weights
        joined the run inputs when they became a traced operand, ISSUE 6,
        and type_id — which fingerprints the TUNED workload, i.e. the
        tune factor — moved to the run key with the trace-operand lift,
        ISSUE 7: the run digest's specs/events already embed it). So
        every seed/weight-vector/tune-factor over the same cluster +
        type set shares one entry — a whole what-if batch reuses one
        table build."""
        from tpusim.io.storage import checkpoint_digest

        cfg = self.cfg

        def chunks():
            yield _engine_source_digest()
            yield repr((
                tuple(name for name, _ in cfg.policies),
                cfg.gpu_sel_method, cfg.dim_ext_method,
                cfg.norm_method,
            )).encode()
            for leaf in (
                jax.tree.leaves(state)
                + jax.tree.leaves(types.share) + jax.tree.leaves(types.whole)
                + jax.tree.leaves(self.typical)
            ):
                yield np.asarray(leaf).tobytes()

        return checkpoint_digest(chunks())

    def _cached_tables(self, state, types, key):
        """(score_tbl, sdev_tbl, feas_tbl) for the single-device table
        engine from the content-keyed disk cache, building + persisting
        on miss — or None when caching is disabled (the engine then
        builds the tables inside init_carry exactly as before). A hit
        skips the K-node-sweep build (~27 s at N=100k); results are
        bit-identical either way because every downstream aggregate is a
        pure function of the tables. obs records the outcome."""
        cache_dir = self._table_cache_dir()
        if not cache_dir:
            return None
        from tpusim.io import storage

        names = ("score_tbl", "sdev_tbl", "feas_tbl")
        digest = self._tables_digest(state, types)
        found = storage.find_tables(cache_dir, digest)
        if found is not None:
            try:
                with self.obs.span("init_tables", cache="hit") as h:
                    arrays = storage.load_tables(found)
                    tables = tuple(jnp.asarray(arrays[k]) for k in names)
                    h.dispatched()
                self.obs.table_cache = "hit"
                self.obs.count("table_cache_hit")
                self.log.info(
                    f"[TableCache] reused init tables from "
                    f"{os.path.basename(found)}"
                )
                return tables
            except Exception as err:
                # torn/stale file: content addressing makes a rebuild
                # always safe; drop the unusable entry
                self.log.info(
                    f"[TableCache] dropping unusable entry "
                    f"{os.path.basename(found)} ({err}); rebuilding"
                )
                try:
                    os.unlink(found)
                except OSError:
                    pass
        with self.obs.span("init_tables", cache="miss") as h:
            tables = self._table_fn.build_tables(
                state, types, self.typical, key
            )
            h.dispatched()
            host = [np.asarray(t) for t in tables]  # also blocks the build
        self.obs.table_cache = "miss"
        self.obs.count("table_cache_miss")
        path = storage.save_tables(
            cache_dir, digest, dict(zip(names, host))
        )
        self.log.info(
            f"[TableCache] saved init tables to {os.path.basename(path)}"
        )
        return tables

    def _run_digest(self, state, specs, ev_kind, ev_pod, key, rank) -> str:
        """Content key of one replay run: the engine-source version salt +
        every input that determines the trajectory (initial state, pod
        specs, typical pods, event stream, PRNG key, tie-break rank, and
        — since the weight vector became a traced operand, ISSUE 6 — the
        per-policy weights, hashed as a RUN INPUT leaf rather than part
        of the static config vocabulary) + the scheduling config.
        checkpoint_every deliberately does NOT participate — chunk
        boundaries are an arbitrary partition, so a resume may use a
        different segment length. A weight change still invalidates
        (different operand bytes ⇒ different digest): the blocked
        summaries inside a checkpointed carry embed the weights, so
        resuming one under different weights would silently diverge."""
        from tpusim.io.storage import checkpoint_digest

        cfg = self.cfg

        def chunks():
            yield _engine_source_digest()
            # record_decisions/series_every participate: a recording run's
            # checkpoints carry the accumulated decision/sample streams,
            # which a non-recording run's do not — the layouts must never
            # mix (and the sample stream's stride is series_every itself)
            yield repr((
                tuple(name for name, _ in cfg.policies),
                cfg.gpu_sel_method, cfg.dim_ext_method,
                cfg.norm_method, cfg.block_size, cfg.mesh,
                cfg.record_decisions, cfg.series_every,
            )).encode()
            for leaf in (
                jax.tree.leaves(state) + jax.tree.leaves(specs)
                + jax.tree.leaves(self.typical)
                + [ev_kind, ev_pod, key, rank,
                   np.asarray([w for _, w in cfg.policies], np.int32)]
            ):
                yield np.asarray(leaf).tobytes()

        return checkpoint_digest(chunks())

    def _run_chunked(self, fn, state, specs, types, ev_kind, ev_pod, key,
                     rank, tables_thunk=None, fork=None):
        """Chunked replay with exact checkpoint/resume: cut the event scan
        into checkpoint_every-event segments via the engine's carry surface
        (fn.init_carry / run_chunk / finish), snapshot the full carry to
        host after each segment (for the shard engine this IS the
        gather-to-host snapshot — np.asarray collects the shards), persist
        it content-addressed (tpusim.io.storage), and on entry resume from
        the newest matching checkpoint. Chaining segments is bit-identical
        to one unsegmented scan (see table_engine.FlatTableCarry), so a
        killed-and-resumed run reproduces the uninterrupted run's
        placements, telemetry, metrics, and final tables exactly.

        `fork = (base_ev_kind, base_ev_pod, fork_event)` is the
        warm-state what-if mode (ISSUE 16): this run's stream shares the
        base run's prefix up to `fork_event`, so when no checkpoint of
        THIS run exists, resume instead from the base run's nearest
        checkpoint at-or-before the divergence point (the base streams
        must already carry this run's padding — the digest math demands
        byte-equal inputs) and replay only the divergent tail. A carry
        restored at cursor c <= fork_event has consumed only shared
        events, so the continuation is bit-identical to the from-event-0
        replay of the forked stream. Missing/torn fork sources degrade
        loudly to a full replay — correct, just cold."""
        from tpusim.io import storage as ckpt
        from tpusim.obs import heartbeat as obs_heartbeat
        from tpusim.obs.decisions import DecisionRecord
        from tpusim.obs.series import SeriesSample
        from tpusim.sim.engine import ReplayResult

        e = int(ev_kind.shape[0])
        every = max(1, int(self.cfg.checkpoint_every))
        cache_dir = self._checkpoint_dir()
        digest = self._run_digest(state, specs, ev_kind, ev_pod, key, rank)
        # expose the run's content identity: the svc fork index persists
        # it so what-if jobs can find this run's checkpoints later
        self.last_run_digest = digest
        self.last_checkpoint_dir = cache_dir
        self._fork_stats = None
        template = jax.eval_shape(
            fn.init_carry, state, specs, types, self.typical, key, rank
        )
        tleaves, tdef = jax.tree.flatten(template)
        record_dec = self.cfg.record_decisions
        dec_fields = DecisionRecord._fields
        record_ser = bool(self.cfg.series_every)
        ser_fields = SeriesSample._fields

        carry = None
        cursor = 0
        node_parts: list = []
        dev_parts: list = []
        dec_parts: list = []  # DecisionRecord-of-np per segment (ISSUE 4)
        ser_parts: list = []  # SeriesSample-of-np per segment (ISSUE 5)
        def _validate(arrays):
            """Layout check against the carry template — a vocabulary or
            shape drift reads as corrupt and the resume walks back."""
            leaves = [arrays[f"c{i:03d}"] for i in range(len(tleaves))]
            if any(
                a.shape != t.shape or a.dtype != t.dtype
                for a, t in zip(leaves, tleaves)
            ):
                raise ValueError("carry layout mismatch")
            arrays["event_node"], arrays["event_dev"]  # must exist
            if record_dec:
                for f in dec_fields:
                    arrays[f"dec_{f}"]
            if record_ser:
                for f in ser_fields:
                    arrays[f"ser_{f}"]

        def _on_skip(path, err):
            # torn/truncated/stale file (ISSUE 10 satellite): skip it
            # with a [Degrade] warning and fall back to the newest VALID
            # checkpoint instead of crashing (or silently restarting).
            # The unusable file is deleted so it cannot shadow future
            # saves below its cursor.
            self.obs.count("degrade_checkpoint")
            self.log.info(
                f"[Degrade] skipping unusable checkpoint "
                f"{os.path.basename(path)} ({err}); trying the newest "
                "valid predecessor"
            )

        found = ckpt.load_valid_checkpoint(
            cache_dir, digest, validate=_validate, on_skip=_on_skip
        )
        if fork is not None:
            base_kind, base_pod, fork_event = fork
            fork_event = int(fork_event)
            # the base run's content identity: same inputs except its
            # OWN event stream (identical prefix, different tail)
            base_digest = self._run_digest(
                state, specs, base_kind, base_pod, key, rank
            )
            self._fork_stats = {
                "base_digest": base_digest, "fork_event": fork_event,
                "source_cursor": 0, "degrade": False,
            }
            if found is None:
                # nearest base checkpoint at-or-before the divergence
                # point: its carry consumed only the SHARED prefix, so
                # continuing it with the forked stream is exact
                found = ckpt.load_valid_checkpoint(
                    cache_dir, base_digest, validate=_validate,
                    on_skip=_on_skip, max_cursor=fork_event,
                    delete_invalid=False,
                )
                if found is None:
                    self.obs.count("degrade_fork")
                    self._fork_stats["degrade"] = True
                    self.log.info(
                        f"[Degrade] no usable fork source at-or-before "
                        f"event {fork_event} for base "
                        f"{base_digest[:12]}…; full replay from event 0"
                    )
        if found is not None:
            cursor, arrays, path = found
            if self._fork_stats is not None:
                self._fork_stats["source_cursor"] = cursor
            leaves = [arrays[f"c{i:03d}"] for i in range(len(tleaves))]
            carry = jax.tree.unflatten(
                tdef, [jnp.asarray(a) for a in leaves]
            )
            node_parts = [arrays["event_node"]]
            dev_parts = [arrays["event_dev"]]
            if record_dec:
                # the decision stream accumulated so far rides the
                # checkpoint beside event_node/event_dev, so a resumed
                # run's stream is continuous
                dec_parts = [DecisionRecord(
                    *(arrays[f"dec_{f}"] for f in dec_fields)
                )]
            if record_ser:
                # likewise the per-event sample stream (ISSUE 5): the
                # stride clock itself is the carry's ctr leaf, so the
                # resumed scan keeps sampling on the same grid
                ser_parts = [SeriesSample(
                    *(arrays[f"ser_{f}"] for f in ser_fields)
                )]
            if self.cfg.heartbeat_every:
                # the resumed carry's event counter already includes
                # `cursor` events this process never executed — keep
                # the tick line / /progress ev-per-s honest
                obs_heartbeat.note_resume(cursor)
            self.log.info(
                f"[Checkpoint] resumed replay at event {cursor}/{e} "
                f"from {os.path.basename(path)}"
            )
        if carry is None:
            # only now resolve the table cache (table engine only): a
            # resumed run never reaches here and must not pay the build
            tables = tables_thunk() if tables_thunk is not None else None
            if tables is not None:
                carry = fn.init_carry(
                    state, specs, types, self.typical, key, rank, tables
                )
            else:
                carry = fn.init_carry(
                    state, specs, types, self.typical, key, rank
                )

        # chunk advances go through the DONATING entry (ISSUE 11): the
        # input carry's buffers are reused by the next segment instead of
        # reallocating the O(N*K) tables every chunk. Safe by
        # construction: the checkpoint snapshot below (np.asarray) copies
        # the carry to host BEFORE the next donating dispatch consumes
        # it, and nothing else holds a reference — the loop variable is
        # rebound. Bit-identity is untouched (same jaxpr, only buffer
        # aliasing moves).
        run_chunk = getattr(fn, "run_chunk_donated", None) or fn.run_chunk
        while cursor < e:
            end = min(cursor + every, e)
            carry, ys = run_chunk(
                carry, specs, types, ev_kind[cursor:end],
                ev_pod[cursor:end], self.typical, rank,
            )
            nseg, dseg = ys[0], ys[1]
            rest = list(ys[2:])
            if record_dec:
                dec_parts.append(jax.tree.map(np.asarray, rest.pop(0)))
            if record_ser:
                ser_parts.append(jax.tree.map(np.asarray, rest.pop(0)))
            node_parts.append(np.asarray(nseg))
            dev_parts.append(np.asarray(dseg))
            cursor = end
            if cursor < e:
                # gather-to-host snapshot + atomic content-addressed save;
                # the final segment skips it (the run completes right after)
                host = jax.tree.map(np.asarray, carry)
                arrays = {
                    f"c{i:03d}": a
                    for i, a in enumerate(jax.tree.leaves(host))
                }
                arrays["event_node"] = np.concatenate(node_parts)
                arrays["event_dev"] = np.concatenate(dev_parts)
                if record_dec:
                    for f in dec_fields:
                        arrays[f"dec_{f}"] = np.concatenate(
                            [np.asarray(getattr(p, f)) for p in dec_parts]
                        )
                if record_ser:
                    for f in ser_fields:
                        arrays[f"ser_{f}"] = np.concatenate(
                            [np.asarray(getattr(p, f)) for p in ser_parts]
                        )
                ckpt.save_checkpoint(cache_dir, digest, cursor, arrays)
                ckpt.prune_checkpoints(
                    cache_dir, digest, cursor, keep=self.cfg.checkpoint_keep
                )

        state_f, placed, masks, failed = fn.finish(carry)
        # run completed: retention-gated (checkpoint_keep != 0 preserves
        # the mid-trace ladder the svc fork index references)
        ckpt.prune_checkpoints(
            cache_dir, digest, e + 1, keep=self.cfg.checkpoint_keep
        )
        nodes = (
            np.concatenate(node_parts) if node_parts
            else np.zeros(0, np.int32)
        )
        devs = (
            np.concatenate(dev_parts) if dev_parts
            else np.zeros((0, 8), bool)
        )
        decs = None
        if record_dec and dec_parts:
            decs = DecisionRecord(*(
                np.concatenate([np.asarray(getattr(p, f)) for p in dec_parts])
                for f in dec_fields
            ))
        sers = None
        if record_ser and ser_parts:
            # the concatenation of segment sample streams IS the
            # unsegmented scan's stream (per-event ys, sentinels included)
            sers = SeriesSample(*(
                np.concatenate([np.asarray(getattr(p, f)) for p in ser_parts])
                for f in ser_fields
            ))
        # the carry's counter leaf accumulated across every segment AND
        # any resumed-from checkpoint — telemetry continuity through
        # kill/resume comes for free from the carry being the checkpoint
        return ReplayResult(
            state_f, placed, masks, failed, None,
            jnp.asarray(nodes), jnp.asarray(devs), carry.ctr, decs, sers,
        )

    # ---- workload prep (core.go:103-142) ----

    def set_workload_pods(self, pods: Sequence[PodRow]):
        self.workload_pods = list(pods)

    def set_typical_pods(self):
        with self.obs.span("typical_pods", pods=len(self.workload_pods)):
            self._set_typical_pods_impl()

    def _set_typical_pods_impl(self):
        self.typical, self._typical_info = get_typical_pods(
            self.workload_pods, self.cfg.typical_pods
        )
        # pad the typical axis to a bucket with zero-frequency rows: every
        # frag/score kernel weights contributions by freq, so zero rows are
        # exact no-ops, and a stable T means sweeps across trace variants
        # (whose distribution sizes differ) reuse one compiled replay
        self.typical = pad_typical_pods(self.typical)
        # host copy for the native Bellman evaluator, one transfer

        self._typical_host = device_fetch(self.typical)
        # The Bellman evaluator (and its memo) is scoped to ONE experiment
        # run, like the reference's fragMemo (simulator.go:58): memoized
        # values embed the cum_prob cutoff context of their first
        # computation, so sharing across experiments would make report
        # values depend on sweep order.
        self._bellman_eval = None
        self._bellman_pending_replay = None
        self.log.info(f"Num of Total Pods: {len(self.workload_pods)}")
        self.log.info(f"Num of Total Pod Sepc: {len(self._typical_info)}")

    def adopt_typical_pods(self, other: "Simulator"):
        """set_typical_pods, copying the (immutable) distribution from a
        same-workload sibling instead of recomputing + re-uploading it —
        the seed-batched sweep path, where all S sims share the workload
        the distribution derives from (schedule_pods_batch validates
        that). Emits the same log lines; the Bellman evaluator stays
        per-experiment (its memo embeds evaluation-order context)."""
        self.typical = other.typical
        self._typical_info = other._typical_info
        self._typical_host = other._typical_host
        self._bellman_eval = None
        self._bellman_pending_replay = None
        self.log.info(f"Num of Total Pods: {len(self.workload_pods)}")
        self.log.info(f"Num of Total Pod Sepc: {len(self._typical_info)}")

    def set_skyline_pods(self):
        self.skyline = get_skyline_pods(self.workload_pods)

    def get_custom_config(self) -> SimulatorConfig:
        """ref: GetCustomConfig (core.go:69)."""
        return self.cfg

    def record_pod_total_resource(self, pods: Sequence[PodRow] = None):
        """Total workload CPU/GPU milli (ref: RecordPodTotalResource,
        core.go:132; consumed by tuning/inflation ratios)."""
        from tpusim.sim.workload import total_pod_cpu_milli, total_pod_gpu_milli

        pods = self.workload_pods if pods is None else pods
        self.pod_total_milli_cpu = total_pod_cpu_milli(pods)
        self.pod_total_milli_gpu = total_pod_gpu_milli(pods)
        return self.pod_total_milli_cpu, self.pod_total_milli_gpu

    def record_node_total_resource(self):
        """Total cluster CPU/GPU milli (ref: RecordNodeTotalResource,
        core.go:133). Computed at construction; exposed for parity."""
        return self.node_total_milli_cpu, self.node_total_milli_gpu

    def get_cluster_node_status(self):
        """[(NodeRow, [PodRow placed on it])] (ref: GetClusterNodeStatus,
        core.go:56 → simontype.NodeStatus)."""
        res = self.last_result
        by_node = [[] for _ in self.nodes]
        for i, n in enumerate(res.placed_node):
            if n >= 0:
                by_node[int(n)].append(res.pods[i])
        return list(zip(self.nodes, by_node))

    def prepare_pods(
        self, tuning_ratio: float = None, tuning_seed: int = None
    ) -> List[PodRow]:
        """SortClusterPods + tuning (core.go:131-142). The tune knobs
        default to the config's; per-call overrides feed the multi-trace
        sweep (ISSUE 7) — a lane prepared with (ratio, seed) here is
        byte-identical to a standalone run configured with them, because
        the rng discipline is the same: one generator seeded by
        tuning_seed drives the shuffle and then the clone draws."""
        ratio = (
            self.cfg.tuning_ratio if tuning_ratio is None
            else float(tuning_ratio)
        )
        seed = (
            self.cfg.tuning_seed if tuning_seed is None else int(tuning_seed)
        )
        rng = np.random.default_rng(seed)
        pods = sort_cluster_pods(
            list(self.workload_pods), self.cfg.shuffle_pod, rng
        )
        if ratio > 0:
            pods = tune_pods(
                pods, self.node_total_milli_gpu, ratio, rng
            )
        return pods

    # ---- the run (core.go:148 RunCluster → SchedulePods) ----

    def _replay_pods(self, state, pods: Sequence[PodRow], key, use_timestamps: bool):
        """Run the compiled replay for `pods` on `state`. Returns
        (replay output, events, unscheduled list). Pods carrying the
        simon/pod-unscheduled annotation are skipped by the event loop and
        reported as failed (simulator.go:391-399). The full replay output
        moves to host in ONE transfer (fetch.device_fetch) — per-leaf
        readbacks pay ~100 ms tunnel latency each on the axon backend."""

        specs = pods_to_specs(pods, self.node_index)
        ev_kind, ev_pod = build_events(pods, use_timestamps)
        out = self.run_events(
            state, specs, jnp.asarray(ev_kind), jnp.asarray(ev_pod), key,
            pod_rows=pods,
        )
        with self.obs.span("fetch", events=len(ev_kind)):
            out = device_fetch(out)
        return self._finish_replay(out, pods, ev_kind, ev_pod, state)

    def _finish_replay(self, out, pods, ev_kind, ev_pod, state):
        """Host-side tail of a replay: per-event report lines, unscheduled
        list, creation ranks. `out` must already be on host."""
        if out.decisions is not None:
            # pair the decision stream with the events it describes — the
            # DecisionLog the emitter/explain/diff surface consumes
            from tpusim.obs.decisions import DecisionLog

            out = out._replace(decisions=DecisionLog(
                jax.tree.map(np.asarray, out.decisions),
                np.asarray(ev_kind), np.asarray(ev_pod),
            ))
        if out.series is not None:
            # filter the stacked per-event samples down to the real
            # stride points (the host-side SeriesLog); standalone replays
            # start the event clock at 0 with an empty retry queue
            from tpusim.obs.series import log_from_stacked

            out = out._replace(series=log_from_stacked(out.series))
        self._emit_event_reports(out, pods, ev_kind, ev_pod, state)
        skipped = np.array([p.unscheduled for p in pods], bool)
        failed_mask = np.asarray(out.ever_failed) | skipped
        unscheduled = [
            UnscheduledPod(
                pods[i],
                reason="pod-unscheduled annotation" if skipped[i] else "unschedulable",
            )
            for i in np.flatnonzero(failed_mask)
        ]
        from tpusim.sim.engine import EV_CREATE

        rank = np.full(len(pods), -1, np.int64)
        creates = np.asarray(ev_pod)[np.asarray(ev_kind) == EV_CREATE]
        rank[creates] = np.arange(len(creates))
        return out, len(ev_kind), unscheduled, rank

    def schedule_pods(self, pods: Sequence[PodRow]) -> SimulateResult:
        if self.typical is None:
            self.set_typical_pods()
        t0 = time.perf_counter()
        result, events, unscheduled, rank = self._replay_pods(
            self.init_state,
            pods,
            jax.random.PRNGKey(self.cfg.seed),
            self.cfg.use_timestamps,
        )
        return self._record_result(
            result, pods, events, unscheduled, rank,
            time.perf_counter() - t0,
        )

    def schedule_pods_fork(self, pods: Sequence[PodRow], fork_event: int,
                           tail_kind, tail_pod) -> SimulateResult:
        """Warm-state what-if replay (ISSUE 16): run the event stream
        `base[:fork_event] + tail` over the SAME prepared pods, resuming
        from the base run's nearest checkpoint at-or-before fork_event
        instead of event 0 — bit-identical to schedule_pods over the
        spliced stream, but the device only executes the divergent tail
        (plus at most one chunk of shared prefix to reach the fork
        point). The base run must have executed on this Simulator's
        config with checkpoint_every > 0 and checkpoint_keep != 0 so its
        mid-trace carry ladder survives; a missing/torn source degrades
        loudly to a full replay (`self.last_fork["degrade"]`). The tail
        reuses the base's pod specs/weights/seed by construction — the
        checkpointed carry embeds the weight vector via its blocked
        summaries, which is exactly why a weight-changing fork can never
        match a base checkpoint (different run digest) and must be
        rejected upstream, not silently degraded here."""
        if self.typical is None:
            self.set_typical_pods()
        t0 = time.perf_counter()
        base_kind, base_pod = build_events(pods, self.cfg.use_timestamps)
        fev = int(fork_event)
        if not 0 <= fev <= len(base_kind):
            raise ValueError(
                f"fork_event {fev} outside the base stream "
                f"(0..{len(base_kind)})"
            )
        tail_kind = np.asarray(tail_kind, base_kind.dtype)
        tail_pod = np.asarray(tail_pod, base_pod.dtype)
        ev_kind = np.concatenate([base_kind[:fev], tail_kind])
        ev_pod = np.concatenate([base_pod[:fev], tail_pod])
        specs = pods_to_specs(pods, self.node_index)
        out = self.run_events(
            self.init_state, specs, jnp.asarray(ev_kind),
            jnp.asarray(ev_pod), jax.random.PRNGKey(self.cfg.seed),
            pod_rows=pods, fork=(base_kind, base_pod, fev),
        )
        with self.obs.span("fetch", events=len(ev_kind)):
            out = device_fetch(out)
        stats = dict(getattr(self, "_fork_stats", None) or {})
        if stats:
            # REAL events this process fed (pad skips excluded): the
            # tail-only latency-win counter the svc result doc reports
            stats["events_executed"] = max(
                0, len(ev_kind) - int(stats.get("source_cursor", 0))
            )
            stats["events_total"] = int(len(ev_kind))
        self.last_fork = stats
        result, events, unscheduled, rank = self._finish_replay(
            out, pods, ev_kind, ev_pod, self.init_state
        )
        return self._record_result(
            result, pods, events, unscheduled, rank,
            time.perf_counter() - t0,
        )

    def _telemetry_meta(self) -> dict:
        """Deterministic run description for the telemetry record (must be
        identical across same-seed runs — no walls, no paths)."""
        cfg = self.cfg
        return {
            "policies": [[n, w] for n, w in cfg.policies],
            "gpu_sel": cfg.gpu_sel_method,
            "norm": cfg.norm_method,
            "dim_ext": cfg.dim_ext_method,
            "seed": cfg.seed,
            "engine_cfg": cfg.engine,
            "block_size": cfg.block_size,
            "mesh": cfg.mesh,
            "nodes": len(self.nodes),
        }

    def run_telemetry(self):
        """Current RunTelemetry snapshot (spans, counters, degrade/fault
        counts) — also attached to every SimulateResult."""
        return self.obs.snapshot(meta=self._telemetry_meta())

    def event_counter_series(self) -> dict:
        """Per-event counter-track series for the Chrome-trace emitter
        (obs.emitters counter tracks): the cluster frag gpu-milli (total
        AND decomposed by the 7 FGD failure categories — the
        `frag_amounts` columns the postpass already computed), used
        gpu-milli, and used cpu-milli, one value per reported event,
        concatenated across this run's reporting replays. Category
        columns share the in-scan series plane's vocabulary
        (obs.series.FRAG_CATEGORY_NAMES). Empty when per-event reporting
        is off — the trace then simply carries no counter tracks."""
        from tpusim.obs.series import FRAG_CATEGORY_NAMES

        frag: list = []
        used: list = []
        used_cpu: list = []
        cats: list = [[] for _ in FRAG_CATEGORY_NAMES]
        for rep in self.event_reports:
            s = rep.get("series", {})
            if "_frag_milli_f" in s:  # numeric twin of origin_milli
                frag.extend(
                    np.asarray(s["_frag_milli_f"], np.float64).tolist()
                )
            amounts = rep.get("frag_amounts")
            if amounts is not None:
                a = np.asarray(amounts, np.float64)
                for j in range(min(a.shape[1], len(cats))):
                    cats[j].extend(a[:, j].tolist())
            used.extend(
                np.asarray(rep["used_gpu_milli"]).astype(np.int64).tolist()
            )
            used_cpu.extend(
                np.asarray(rep["used_cpu_milli"]).astype(np.int64).tolist()
            )
        out = {}
        if frag:
            out["frag_gpu_milli"] = frag
        if used:
            out["used_gpu_milli"] = used
        if used_cpu:
            out["used_cpu_milli"] = used_cpu
        for name, vals in zip(FRAG_CATEGORY_NAMES, cats):
            if vals:
                out[f"frag_{name}_milli"] = vals
        return out

    def _record_result(self, result, pods, events, unscheduled, rank, wall):
        # exact in-scan counters + creation-failure mask of the newest
        # run: the svc serving path summarizes results in the SweepLane
        # vocabulary (counters included) without re-deriving them
        self.last_counters = (
            np.asarray(result.counters)
            if getattr(result, "counters", None) is not None else None
        )
        self.last_ever_failed = np.asarray(result.ever_failed)
        self.last_result = SimulateResult(
            unscheduled_pods=unscheduled,
            placed_node=np.asarray(result.placed_node),
            dev_mask=np.asarray(result.dev_mask),
            state=jax.tree.map(np.asarray, result.state),
            pods=list(pods),
            node_names=self.node_names,
            wall_seconds=wall,
            events=events,
            creation_rank=rank,
            telemetry=self.run_telemetry(),
            decisions=getattr(result, "decisions", None),
            series=getattr(result, "series", None),
        )
        return self.last_result

    def schedule_additional(self, pods: Sequence[PodRow]) -> List[UnscheduledPod]:
        """Continue scheduling `pods` on the CURRENT cluster state, appending
        them to the run's bookkeeping. This is the engine behind ScheduleApp
        (core.go:255-261) and the new-workload swap (core.go:195-209) — both
        schedule extra pods on top of the already-placed cluster."""
        if self.typical is None:
            self.set_typical_pods()
        res = self.last_result
        out, events, failed, rank = self._replay_pods(
            jax.tree.map(jnp.asarray, res.state),
            pods,
            jax.random.PRNGKey(self.cfg.seed + len(res.pods)),
            use_timestamps=False,
        )
        res.state = jax.tree.map(np.asarray, out.state)
        res.pods = list(res.pods) + list(pods)
        res.placed_node = np.concatenate(
            [res.placed_node, np.asarray(out.placed_node)]
        )
        res.dev_mask = np.concatenate([res.dev_mask, np.asarray(out.dev_mask)])
        res.unscheduled_pods = list(res.unscheduled_pods) + failed
        prior_events = res.events
        res.events += events
        if out.series is not None:
            from tpusim.obs.series import concat_series

            # the appended replay's sample clock starts at 0; rebase onto
            # the run's global event clock before appending
            res.series = concat_series([
                res.series,
                out.series._replace(
                    pos=np.asarray(out.series.pos) + prior_events
                ),
            ])
        if out.decisions is not None:
            from tpusim.obs.decisions import concat_logs

            # the appended replay's events index ITS pod list; shift to
            # the run's concatenated indexing before appending the log
            shifted = out.decisions._replace(
                ev_pod=np.asarray(out.decisions.ev_pod)
                + (len(res.pods) - len(pods))
            )
            res.decisions = concat_logs([res.decisions, shifted])
        base = int(res.creation_rank.max(initial=-1)) + 1
        res.creation_rank = np.concatenate(
            [res.creation_rank, np.where(rank >= 0, rank + base, -1)]
        )
        return failed

    def schedule_app(
        self, name: str, pods: Sequence[PodRow], use_greed: bool = False
    ) -> List[UnscheduledPod]:
        """ScheduleApp (simulator.go:224-237): sort the app's pods through
        the affinity → toleration queues (greed first when --use-greed),
        then schedule them on the current state."""
        from tpusim.sim.queues import app_queue

        ordered = app_queue(pods, self.nodes, use_greed)
        self.log.info(f"Scheduling app {name}: {len(ordered)} pods")
        return self.schedule_additional(ordered)

    def _reset_run_state(self):
        """A reused Simulator must not double-count a previous run's series:
        the direct-CSV stashes accumulate per schedule/report call, and the
        log-reparse lane reads whatever log the caller kept — reset both
        lanes' inputs so they stay byte-identical for any call pattern
        (ADVICE r4). An attached log stream is NOT rewound — the apply path
        wires sys.stdout there, possibly shell-redirected into a file we
        must not clobber; callers re-dumping sim.log after the last run
        (the run.py flow) always get the consistent single-run log."""
        self.event_reports = []
        self.analysis_summary = {}
        self.failed_pod_lists = []
        self.log.lines = []
        self.obs.reset()

    def run(self) -> SimulateResult:
        """Full experiment (core.go:86-268 minus deschedule/inflation, which
        the CLI layers on)."""
        self._reset_run_state()
        self.set_typical_pods()
        self.set_skyline_pods()
        pods = self.prepare_pods()
        self.log.info(f"Number of original workload pods: {len(self.workload_pods)}")
        res = self.schedule_pods(pods)
        # failed-pods detail block (core.go:156 ReportFailedPods)
        self.report_failed([u.pod for u in res.unscheduled_pods])
        self.cluster_analysis("InitSchedule")
        return res

    def run_sweep(self, weights, seeds=None, bucket: int = 512, tunes=None,
                  faults=None):
        """run()'s workload prep + ONE vmapped config-axis sweep replay
        (ISSUE 6): evaluate B (weight-vector, seed) what-if configs of
        this Simulator's policy family in a single compiled scan. See
        schedule_pods_sweep for the contract; returns [SweepLane].

        `tunes` (ISSUE 7, the trace-operand lift): an optional length-B
        list of per-lane tuning ratios. When given, each lane's workload
        is prepared exactly like a standalone run with that
        tuning_ratio (same tuning_seed → same shuffle + clone draws) and
        the batch dispatches through schedule_pods_sweep_multi — the
        tuned traces ride the sweep as DATA (specs/events/type_id
        operands, padded to common buckets), so jobs differing only in
        tune factor pack onto the same compiled scan instead of forcing
        a new jaxpr."""
        self._reset_run_state()
        self.set_typical_pods()
        self.log.info(
            f"Number of original workload pods: {len(self.workload_pods)}"
        )
        if faults is not None:
            # the chaos sweep (ISSUE 10): one trace, B fault schedules as
            # per-lane operands — ONE compiled vmapped scan
            if tunes is not None:
                # the chaos x tune lift (ISSUE 12): per-lane TUNED traces
                # each with their OWN fault schedule (compiled against
                # that lane's base stream) — mixed fault/tune/weight
                # what-ifs still share one compiled scan
                w = np.asarray(weights, np.int32)
                if w.ndim != 2 or len(tunes) != int(w.shape[0]):
                    raise ValueError(
                        f"tunes has {len(tunes)} entries for weight grid "
                        f"of shape {w.shape} (want one tuning ratio per "
                        "weight row)"
                    )
                pods_list = [
                    self.prepare_pods(tuning_ratio=t) for t in tunes
                ]
                return schedule_pods_sweep_multi(
                    self, pods_list, w, seeds=seeds, bucket=bucket,
                    fault_specs=faults,
                )
            pods = self.prepare_pods()
            return schedule_pods_sweep_faults(
                self, pods, weights, faults, seeds=seeds, bucket=bucket
            )
        if tunes is None:
            pods = self.prepare_pods()
            return schedule_pods_sweep(
                self, pods, weights, seeds=seeds, bucket=bucket
            )
        w = np.asarray(weights, np.int32)
        if w.ndim != 2 or len(tunes) != int(w.shape[0]):
            raise ValueError(
                f"tunes has {len(tunes)} entries for weight grid of shape "
                f"{w.shape} (want one tuning ratio per weight row)"
            )
        pods_list = [self.prepare_pods(tuning_ratio=t) for t in tunes]
        return schedule_pods_sweep_multi(
            self, pods_list, w, seeds=seeds, bucket=bucket
        )

    def run_with_faults(self, fault_cfg=None, faults=None) -> SimulateResult:
        """run() under fault injection: same experiment orchestration, the
        main schedule replaced by schedule_pods_with_faults (the CLI's
        --fault-* flags land here)."""
        self._reset_run_state()
        self.set_typical_pods()
        self.set_skyline_pods()
        pods = self.prepare_pods()
        self.log.info(
            f"Number of original workload pods: {len(self.workload_pods)}"
        )
        res = self.schedule_pods_with_faults(
            pods, faults=faults, fault_cfg=fault_cfg
        )
        self.report_failed([u.pod for u in res.unscheduled_pods])
        self.cluster_analysis("InitSchedule")
        return res

    def report_failed(self, pods) -> None:
        """Failed-pods detail block + the direct-CSV path's stash (every
        block the log carries contributes to the fail-spec grouping, like
        the parser's in_fail_block accumulation)."""
        report_failed_pods(self.log, pods)
        self.failed_pod_lists.append(list(pods))

    def finish(self):
        """Emit the unscheduled-count line (apply.go:228). It is the
        analysis parser's stop marker, so it must come after the LAST
        Cluster Analysis block of the experiment — call once, at the end."""
        self.log.info(
            f"there are {len(self.last_result.unscheduled_pods)} unscheduled pods"
        )

    # ---- snapshot export (export.go) ----

    def export_pod_snapshot_yaml(self, path: str):
        from tpusim.io.export import export_pod_snapshot_yaml

        r = self.last_result
        export_pod_snapshot_yaml(
            r.pods, r.placed_node, r.dev_mask, self.node_names, path,
            creation_rank=r.creation_rank,
        )

    def export_pod_snapshot_csv(self, path: str):
        from tpusim.io.export import export_pod_snapshot_csv

        r = self.last_result
        export_pod_snapshot_csv(r.pods, r.placed_node, r.dev_mask, self.nodes, path)

    def export_node_snapshot_csv(self, path: str):
        from tpusim.io.export import export_node_snapshot_csv

        r = self.last_result
        num_pods = np.zeros(len(self.nodes), np.int64)
        placed = r.placed_node[r.placed_node >= 0]
        np.add.at(num_pods, placed, 1)
        export_node_snapshot_csv(r.state, self.nodes, num_pods, path)

    # ---- workload inflation (simulator.go:1015-1132) ----

    def run_workload_inflation_evaluation(self, tag: str):
        """Clone extra pods onto the current cluster state, schedule them,
        run ClusterAnalysis under `tag`, then drop them (the committed state
        is untouched — we simply never persist the inflated one)."""
        from tpusim.sim.workload import inflation_pods, total_pod_cpu_milli, total_pod_gpu_milli

        rng = np.random.default_rng(self.cfg.inflation_seed)
        extra = inflation_pods(
            self.workload_pods,
            self.cfg.inflation_ratio,
            rng,
            self.node_total_milli_cpu,
            self.node_total_milli_gpu,
            total_pod_cpu_milli(self.workload_pods),
            total_pod_gpu_milli(self.workload_pods),
        )
        if not extra:
            return None
        self.log.info(f"(Inflation) Num of Total Pods: {len(extra)}")
        state = jax.tree.map(jnp.asarray, self.last_result.state)
        # same reporting replay as the main workload (the reference's
        # inflation path reuses SchedulePods + ReportFailedPods,
        # simulator.go:1023-1024)
        out, _, unscheduled, _ = self._replay_pods(
            state, extra, jax.random.PRNGKey(self.cfg.inflation_seed),
            use_timestamps=False,
        )
        self.report_failed([u.pod for u in unscheduled])
        failed = len(unscheduled)
        self.log.info(f"[ReportFailedPods] {failed} unscheduled inflation pods")
        saved = self.last_result.state
        self.last_result.state = jax.tree.map(np.asarray, out.state)
        analysis = self.cluster_analysis(tag)
        self.last_result.state = saved  # inflation pods all deleted
        return analysis

    # ---- descheduling (deschedule.go) ----

    def deschedule_cluster(self) -> List[UnscheduledPod]:
        """Evict pods per the configured policy, report PostEviction, then
        reschedule the victims (ref: DescheduleCluster, deschedule.go:20-47,
        + the core.go:213-218 orchestration: the caller follows up with
        ClusterAnalysis(PostDeschedule))."""
        from tpusim.sim.deschedule import evict, select_victims

        res = self.last_result
        specs = pods_to_specs(res.pods)
        state = jax.tree.map(jnp.asarray, res.state)
        victims = select_victims(
            state,
            specs,
            res.placed_node,
            res.dev_mask,
            self.typical,
            self.cfg.deschedule_policy,
            self.cfg.deschedule_ratio,
            self.node_names,
        )
        self.log.info(
            f"maximum number of pods that can be descheduled: "
            f"{math.ceil(self.cfg.deschedule_ratio * int((res.placed_node >= 0).sum()))}, "
            f"deschedule policy: {self.cfg.deschedule_policy}"
        )
        state = evict(state, specs, res.placed_node, res.dev_mask, victims)
        res.state = jax.tree.map(np.asarray, state)
        res.placed_node = res.placed_node.copy()
        res.dev_mask = res.dev_mask.copy()
        res.placed_node[victims] = -1
        res.dev_mask[victims] = False
        self.cluster_analysis("PostEviction")
        self.log.info(f"[DescheduleCluster] Num of Descheduled Pods: {len(victims)}")

        # reschedule the victims, in eviction order (deschedule.go:89-91)
        if not victims:
            return []
        v = np.asarray(victims, np.int32)
        vspecs = jax.tree.map(lambda a: a[jnp.asarray(v)], specs)
        ev_kind = np.zeros(len(victims), np.int32)  # EV_CREATE stream
        ev_pod = np.arange(len(victims), dtype=np.int32)

        out = device_fetch(
            self.run_events(
                state, vspecs, jnp.asarray(ev_kind), jnp.asarray(ev_pod),
                jax.random.PRNGKey(self.cfg.seed + 1),
                pod_rows=[res.pods[int(i)] for i in v],
            )
        )
        # the victim reschedule goes through the reporting loop in the
        # reference too (deschedule.go:91 → SchedulePods)
        self._emit_event_reports(
            out, [res.pods[int(i)] for i in v], ev_kind, ev_pod, state
        )
        if out.decisions is not None:
            from tpusim.obs.decisions import DecisionLog, concat_logs

            # the victim replay's events index vspecs; remap to the run's
            # global pod indices so the appended log names the right pods
            res.decisions = concat_logs([
                res.decisions,
                DecisionLog(
                    jax.tree.map(np.asarray, out.decisions),
                    np.asarray(ev_kind), v[np.asarray(ev_pod)],
                ),
            ])
        if out.series is not None:
            from tpusim.obs.series import concat_series, log_from_stacked

            # victim reschedules append their samples past the run's
            # event clock (deschedule events are host-level, not trace
            # events, so res.events itself is unchanged)
            res.series = concat_series([
                res.series,
                log_from_stacked(out.series, base_pos=res.events),
            ])
        placed_v = np.asarray(out.placed_node)
        mask_v = np.asarray(out.dev_mask)
        res.placed_node[v] = placed_v
        res.dev_mask[v] = mask_v
        res.state = jax.tree.map(np.asarray, out.state)
        if res.creation_rank is not None:  # victims re-enter last, in order
            base = int(res.creation_rank.max(initial=-1)) + 1
            res.creation_rank = res.creation_rank.copy()
            res.creation_rank[v] = base + np.arange(len(v))
        failed = [
            UnscheduledPod(res.pods[v[i]]) for i in np.flatnonzero(placed_v < 0)
        ]
        res.unscheduled_pods = list(res.unscheduled_pods) + failed
        self.log.info(f"[DescheduleCluster] Num of Failed Pods: {len(failed)}")
        return failed

    # ---- fault injection (tpusim.sim.faults / fault_lane) ----

    def _fault_scan_blockers(self) -> list:
        """Reasons this config cannot run the in-scan fault lane (each
        one is a capability only the segmented host loop provides)."""
        cfg = self.cfg
        out = []
        if cfg.report_per_event:
            out.append("per-event reporting (the report postpass does not "
                       "model fault transitions)")
        if cfg.extenders:
            out.append("extenders")
        if cfg.record_decisions:
            out.append("decision recording")
        if cfg.series_every:
            out.append("the in-scan series plane")
        if cfg.checkpoint_every:
            out.append("checkpointing (composes with the segmented path)")
        if cfg.engine == "pallas":
            out.append("the fused pallas engine")
        if cfg.heartbeat_every:
            out.append("the in-scan heartbeat")
        return out

    def _fault_randomized(self) -> bool:
        """Per-event-random configs (RandomScore / gpu_sel random): the
        scan lane replays them seeded-and-reproducibly, but its one-key-
        chain-per-merged-stream discipline necessarily differs from the
        segmented path's per-segment fold-in — so fault_mode='auto'
        keeps them on the segmented path (same-seed results stay what
        PR 2 produced) and only an explicit fault_mode='scan' opts into
        the lane's chain."""
        return (
            any(fn.policy_name == "RandomScore"
                for fn, _ in self._policy_fns)
            or self.cfg.gpu_sel_method == "random"
        )

    def schedule_pods_with_faults(
        self, pods: Sequence[PodRow], faults=None, fault_cfg=None
    ) -> SimulateResult:
        """schedule_pods under a fault schedule. Since ISSUE 10 the
        default execution is the IN-SCAN fault lane
        (tpusim.sim.fault_lane): the schedule merges into the event
        stream as fixed-shape operands and the retry queue rides the
        scan carry, so the whole disruption trajectory is ONE compiled
        scan — and, crucially, a vmappable one (Simulator.run_sweep's
        `faults=` axis). Configs the lane cannot serve (see
        _fault_scan_blockers) fall back to the PR 2 segmented host loop,
        which remains bit-identical for deterministic configs;
        SimulatorConfig.fault_mode forces either path."""
        mode = getattr(self.cfg, "fault_mode", "auto")
        if mode not in ("auto", "scan", "segments"):
            raise ValueError(
                f"unknown fault_mode {mode!r}: expected auto | scan | "
                "segments"
            )
        blockers = self._fault_scan_blockers()
        if mode == "scan" and blockers:
            raise ValueError(
                f"fault_mode='scan' cannot serve this config: {blockers[0]}"
            )
        if mode == "auto" and not blockers and self._fault_randomized():
            # soft preference, not a capability gap: the lane CAN replay
            # randomized configs (fault_mode='scan' opts in), but auto
            # must not silently change PR 2's same-seed results
            blockers = [
                "per-event randomness draws a different (still seeded) "
                "PRNG chain on the scan lane; fault_mode='scan' opts in"
            ]
        if mode == "segments" or blockers:
            if blockers and mode == "auto":
                self.log.info(
                    f"[Fault] segmented replay ({blockers[0]})"
                )
            return self._schedule_pods_with_faults_segmented(
                pods, faults, fault_cfg
            )
        return self._schedule_pods_faults_scan(pods, faults, fault_cfg)

    def _schedule_pods_with_faults_segmented(
        self, pods: Sequence[PodRow], faults=None, fault_cfg=None
    ) -> SimulateResult:
        """The PR 2 host loop: NodeFail / NodeRecover /
        Evict events fire between compiled replay segments, evicted pods
        re-enter through a capped-exponential-backoff retry queue
        (tpusim.sim.queues.RetryQueue), and pods out of retries become
        terminal UnscheduledPods (reason "max-retries-exceeded").

        `faults`: an explicit FaultEvent list (the trace-column mode), or
        None to generate an MTBF-style schedule from `fault_cfg`
        (tpusim.sim.faults.generate_fault_schedule — seeded, so the whole
        disruption outcome is bit-reproducible; tests/test_faults.py pins
        that). Segments run through run_events unchanged, so fault replays
        inherit engine selection AND checkpoint/resume.

        Creation-ordered traces only (use_timestamps=False, the experiment
        pipeline's mode): a trace-deletion of a pod created in an earlier
        segment would need cross-segment placement memory the engine call
        surface does not carry — deletions under faults are modeled as
        Evict events instead. Disruption totals land in
        `self.last_disruption` and the `[Disruption]` log block."""
        from tpusim.sim.engine import (
            EV_CREATE,
            EV_EVICT,
            EV_NODE_FAIL,
            EV_NODE_RECOVER,
        )
        from tpusim.sim.deschedule import evict as evict_pods
        from tpusim.sim.faults import (
            FaultConfig,
            fail_node,
            generate_fault_schedule,
            pick_eviction_victim,
            recover_node,
            validate_fault_schedule,
        )
        from tpusim.sim.metrics import DisruptionMetrics
        from tpusim.sim.queues import RetryQueue
        from tpusim.sim.reports import disruption_report_block
        from tpusim.sim.table_engine import build_pod_types

        if self.cfg.use_timestamps:
            raise ValueError(
                "schedule_pods_with_faults replays creation-ordered traces "
                "(use_timestamps=False); model deletions as Evict fault "
                "events instead"
            )
        if self.typical is None:
            self.set_typical_pods()
        fcfg = fault_cfg or FaultConfig()
        pods = list(pods)
        ev_kind, ev_pod = build_events(pods, False)
        num_events = len(ev_kind)
        if faults is None:
            faults = generate_fault_schedule(
                len(self.nodes), num_events, fcfg
            )
        faults = sorted(faults, key=lambda f: f.pos)  # stable: ties keep order
        validate_fault_schedule(faults, len(self.nodes), len(pods))
        t0 = time.perf_counter()

        num_pods = len(pods)
        specs = pods_to_specs(pods, self.node_index)
        types = build_pod_types(specs)
        state = jax.tree.map(jnp.asarray, self.init_state)
        gpu_cnt = np.asarray(self.init_state.gpu_cnt)
        ndev = int(self.init_state.gpu_left.shape[1])
        placed = np.full(num_pods, -1, np.int32)
        masks = np.zeros((num_pods, ndev), bool)
        ever_failed = np.zeros(num_pods, bool)
        creation_rank = np.full(num_pods, -1, np.int64)
        base_key = jax.random.PRNGKey(self.cfg.seed)
        rq = RetryQueue(
            fcfg.backoff_base, fcfg.backoff_cap, fcfg.max_retries
        )
        dm = DisruptionMetrics()
        dec_logs: list = []  # per-segment DecisionLogs (ISSUE 4)
        ser_logs: list = []  # per-segment SeriesLogs (ISSUE 5)
        attempts: dict = {}  # pod -> consecutive failed retries so far
        evicted_at: dict = {}  # pod -> eviction position (latency clock)
        down_at: dict = {}  # node -> failure position
        state_box = {"state": state, "rank": 0, "events": 0, "segs": 0}

        def frag_total(st):
            from tpusim.ops.frag import cluster_frag_report, frag_sum_except_q3

            return float(frag_sum_except_q3(
                cluster_frag_report(st, self.typical)[0]
            ))

        def run_segment(seg_kind, seg_pod):
            """One compiled segment via the normal run_events dispatch;
            merges its placements into the host bookkeeping."""
            seg_kind = np.asarray(seg_kind)
            seg_pod = np.asarray(seg_pod)
            seg_key = jax.random.fold_in(base_key, state_box["segs"])
            state_box["segs"] += 1
            pre_state = state_box["state"]
            # run-level heartbeat window: this segment's ticks report
            # `events-so-far + segment progress` out of the run total
            self._hb_base = state_box["events"]
            out = device_fetch(self.run_events(
                pre_state, specs, jnp.asarray(seg_kind),
                jnp.asarray(seg_pod), seg_key, types=types, pod_rows=pods,
            ))
            self._emit_event_reports(out, pods, seg_kind, seg_pod, pre_state)
            if out.series is not None:
                from tpusim.obs.series import log_from_stacked

                # every segment is a fresh scan, so it OPENS with a sample
                # of the post-fault cluster at stride position 0; rebase
                # onto the run's global event clock and stamp the current
                # retry-queue depth (host state the scan cannot see)
                ser_logs.append(log_from_stacked(
                    out.series, base_pos=state_box["events"],
                    retry_depth=len(rq),
                ))
            if out.decisions is not None:
                # the fault replay's provenance is the concatenation of
                # its segments' streams, in replay order — continuous
                # across the segmentation like the counters
                from tpusim.obs.decisions import DecisionLog

                dec_logs.append(DecisionLog(
                    jax.tree.map(np.asarray, out.decisions),
                    seg_kind, seg_pod,
                ))
            state_box["state"] = jax.tree.map(jnp.asarray, out.state)
            created = seg_pod[seg_kind == EV_CREATE]
            placed[created] = np.asarray(out.placed_node)[created]
            masks[created] = np.asarray(out.dev_mask)[created]
            ever_failed[created] |= np.asarray(out.ever_failed)[created]
            creation_rank[created] = (
                state_box["rank"] + np.arange(created.size)
            )
            state_box["rank"] += int(created.size)
            state_box["events"] += int(seg_kind.size)

        def evict_bookkeep(pod_i: int, pos: int):
            placed[pod_i] = -1
            masks[pod_i] = False
            evicted_at[pod_i] = pos
            dm.evicted_pods += 1
            att = attempts.get(pod_i, 0) + 1
            attempts[pod_i] = att
            # rq.dead is THE terminal list; totals are read off it after
            # the loop instead of being double-counted here
            if rq.push(pod_i, pos, att) is None:
                ever_failed[pod_i] = True
            else:
                dm.retries_enqueued += 1

        def apply_fault(f, pos: int):
            if f.kind == EV_NODE_FAIL:
                if f.node in down_at:
                    return  # already down
                victims = np.flatnonzero(placed == f.node)
                state_box["state"] = fail_node(state_box["state"], f.node)
                down_at[f.node] = pos
                dm.node_failures += 1
                self.log.info(
                    f"[Fault] node {self.node_names[f.node]} failed at "
                    f"event {pos}: {victims.size} pods evicted"
                )
                for v in victims.tolist():
                    evict_bookkeep(int(v), pos)
            elif f.kind == EV_NODE_RECOVER:
                if f.node not in down_at:
                    return  # never failed / already recovered
                before = frag_total(state_box["state"])
                state_box["state"] = recover_node(state_box["state"], f.node)
                after = frag_total(state_box["state"])
                dm.post_recovery_frag_delta.append(after - before)
                dm.node_recoveries += 1
                dm.failed_node_gpu_events += int(gpu_cnt[f.node]) * (
                    pos - down_at.pop(f.node)
                )
                self.log.info(
                    f"[Fault] node {self.node_names[f.node]} recovered at "
                    f"event {pos} (frag delta {after - before:+.1f})"
                )
            else:  # EV_EVICT
                v = pick_eviction_victim(placed, pos, fcfg.seed, f.pod)
                if v is None:
                    return  # nothing placed to evict
                state_box["state"] = evict_pods(
                    state_box["state"], specs, jnp.asarray(placed),
                    jnp.asarray(masks), [v],
                )
                self.log.info(
                    f"[Fault] pod {pods[v].name} evicted from node "
                    f"{self.node_names[int(placed[v])]} at event {pos}"
                )
                evict_bookkeep(int(v), pos)

        fi = 0
        cursor = 0
        while True:
            candidates = [num_events] if cursor < num_events else []
            if fi < len(faults):
                candidates.append(min(faults[fi].pos, num_events))
            nr = rq.next_ready()
            if nr is not None:
                candidates.append(min(nr, num_events))
            if not candidates:
                break
            stop = min(candidates)
            if stop > cursor:
                run_segment(ev_kind[cursor:stop], ev_pod[cursor:stop])
                cursor = stop
            pos = stop
            # faults fire first so a retry due at the same position sees
            # the post-fault cluster (never re-lands on the dying node)
            while fi < len(faults) and min(faults[fi].pos, num_events) <= pos:
                apply_fault(faults[fi], pos)
                fi += 1
            # once the trace and fault stream are drained, flush the queue
            # regardless of backoff — there is nothing left to wait for
            thresh = (
                pos if (cursor < num_events or fi < len(faults))
                else float("inf")
            )
            due = rq.pop_due(thresh)
            if due:
                retry_idx = np.array([p for p, _ in due], np.int32)
                run_segment(
                    np.zeros(retry_idx.size, np.int32), retry_idx
                )
                for pod_i, _att in due:
                    if placed[pod_i] >= 0:
                        dm.rescheduled_pods += 1
                        dm.reschedule_latency_events.append(
                            pos - evicted_at.pop(pod_i)
                        )
                        # the budget is max_retries CONSECUTIVE failures
                        # (FaultConfig doc): a successful reschedule resets
                        # it, so a long-lived pod evicted many separate
                        # times is not eventually killed by accumulation
                        attempts.pop(pod_i, None)
                    else:
                        att = attempts[pod_i] + 1
                        attempts[pod_i] = att
                        if rq.push(pod_i, pos, att) is not None:
                            dm.retries_enqueued += 1

        # capacity still dark at trace end counts to the end-of-trace clock
        for node_i, t_fail in down_at.items():
            dm.failed_node_gpu_events += int(gpu_cnt[node_i]) * max(
                num_events - t_fail, 0
            )
        # the retry queue's dead list is the single source of truth for
        # out-of-retries pods
        dead_pods = {p for p, _ in rq.dead}
        dm.unscheduled_after_retries = len(rq.dead)

        self.analysis_summary.update(disruption_report_block(self.log, dm))
        self.last_disruption = dm
        # the [Disruption] block's machine-readable twin: fault totals in
        # the JSONL record instead of stdout-only prose
        self.obs.note_disruption(dm)

        skipped = np.array([p.unscheduled for p in pods], bool)
        unscheduled = []
        for i in range(num_pods):
            if skipped[i]:
                unscheduled.append(UnscheduledPod(
                    pods[i], reason="pod-unscheduled annotation"
                ))
            elif i in dead_pods:
                unscheduled.append(UnscheduledPod(
                    pods[i], reason="max-retries-exceeded"
                ))
            elif placed[i] < 0 and bool(ever_failed[i]):
                unscheduled.append(UnscheduledPod(pods[i]))
        from tpusim.obs.decisions import concat_logs
        from tpusim.obs.series import concat_series

        self._hb_base = 0  # later replays report from a fresh clock
        self.last_result = SimulateResult(
            unscheduled_pods=unscheduled,
            placed_node=placed,
            dev_mask=masks,
            state=jax.tree.map(np.asarray, state_box["state"]),
            pods=pods,
            node_names=self.node_names,
            wall_seconds=time.perf_counter() - t0,
            events=state_box["events"],
            creation_rank=creation_rank,
            telemetry=self.run_telemetry(),
            decisions=concat_logs(dec_logs),
            series=concat_series(ser_logs),
        )
        return self.last_result

    # ---- the in-scan fault lane (ISSUE 10; tpusim.sim.fault_lane) ----

    def _schedule_pods_faults_scan(
        self, pods: Sequence[PodRow], faults=None, fault_cfg=None
    ) -> SimulateResult:
        """schedule_pods_with_faults on the in-scan lane: ONE compiled
        scan over the merged (base + fault + retry-slot) stream, the
        retry queue in the carry, DisruptionMetrics assembled from exact
        in-scan counters + per-event fault telemetry. Bit-identical to
        the segmented path for deterministic configs (the acceptance
        pin, tests/test_fault_lane.py)."""
        from tpusim.sim import fault_lane
        from tpusim.sim.faults import FaultConfig, generate_fault_schedule
        from tpusim.sim.reports import disruption_report_block

        if self.cfg.use_timestamps:
            raise ValueError(
                "schedule_pods_with_faults replays creation-ordered traces "
                "(use_timestamps=False); model deletions as Evict fault "
                "events instead"
            )
        if self.typical is None:
            self.set_typical_pods()
        fcfg = fault_cfg or FaultConfig()
        pods = list(pods)
        ev_kind, ev_pod = build_events(pods, False)
        if faults is None:
            faults = generate_fault_schedule(
                len(self.nodes), len(ev_kind), fcfg
            )
        t0 = time.perf_counter()
        specs = pods_to_specs(pods, self.node_index)
        plan = fault_lane.compile_fault_plan(
            ev_kind, ev_pod, faults, fcfg, len(self.nodes), len(pods)
        )
        out = self._dispatch_fault_scan(specs, plan)
        with self.obs.span("fetch", events=int(plan.kind.shape[0])):
            out = device_fetch(out)
        dm, dead, attempts_run = fault_lane.assemble_disruption(
            plan, out.fault_ys, out.fault_carry,
            np.asarray(self.init_state.gpu_cnt),
            # the shard engine never captures recover frag deltas — drop
            # the series (with the [Degrade] warning above) instead of
            # reporting placeholder zeros as measurements
            frag_delta=self._shard_fn is None,
        )
        e_m = int(plan.kind.shape[0])
        # fault events + inert retry slots counted as skips in-scan; the
        # true event count is base events + actual retry attempts
        self.obs.note_scan(
            self._last_engine, counters=out.counters,
            pad_skips=e_m - plan.num_events - attempts_run,
            events=plan.num_events + attempts_run,
        )
        self.log.info(
            f"[Engine] fault-lane replay of {plan.num_events} events "
            f"(+{attempts_run} retries, merged stream {e_m}) ran on: "
            f"{self._last_engine}"
        )
        self._emit_fault_log_lines(plan, out.fault_ys, pods)
        self.analysis_summary.update(disruption_report_block(self.log, dm))
        self.last_disruption = dm
        self.obs.note_disruption(dm)
        placed = np.asarray(out.placed_node)
        ever_failed = np.asarray(out.ever_failed)
        skipped = np.array([p.unscheduled for p in pods], bool)
        dead = np.asarray(dead)[: len(pods)]
        unscheduled = []
        for i in range(len(pods)):
            if skipped[i]:
                unscheduled.append(UnscheduledPod(
                    pods[i], reason="pod-unscheduled annotation"
                ))
            elif dead[i]:
                unscheduled.append(UnscheduledPod(
                    pods[i], reason="max-retries-exceeded"
                ))
            elif placed[i] < 0 and bool(ever_failed[i]):
                unscheduled.append(UnscheduledPod(pods[i]))
        self.last_result = SimulateResult(
            unscheduled_pods=unscheduled,
            placed_node=placed,
            dev_mask=np.asarray(out.dev_mask),
            state=jax.tree.map(np.asarray, out.state),
            pods=pods,
            node_names=self.node_names,
            wall_seconds=time.perf_counter() - t0,
            events=plan.num_events + attempts_run,
            creation_rank=fault_lane.fault_creation_rank(
                plan, out.fault_ys, len(pods)
            ),
            telemetry=self.run_telemetry(),
        )
        return self.last_result

    def _dispatch_fault_scan(self, specs, plan):
        """Engine dispatch for one fault-lane replay: shard_map under a
        mesh, else the table engine when the workload amortizes its init
        (the run_events heuristic), else the sequential oracle."""
        from tpusim.sim import fault_lane
        from tpusim.sim.engine import make_replay
        from tpusim.sim.table_engine import (
            build_pod_types,
            make_table_replay,
            num_pod_types,
        )

        key = jax.random.PRNGKey(self.cfg.seed)
        e = plan.num_events
        kind_d = jnp.asarray(plan.kind)
        idx_d = jnp.asarray(plan.idx)
        p = int(specs.cpu.shape[0])
        if self._shard_fn is not None:
            from tpusim.parallel import pad_nodes, shard_state
            from tpusim.parallel.shard_engine import (
                make_shardmap_table_replay,
            )

            n0 = self.init_state.num_nodes
            state_p, rank_p = pad_nodes(
                self.init_state, self.rank, self.cfg.mesh
            )
            n_pad = state_p.num_nodes
            state_p = shard_state(state_p, self._mesh)
            ops = fault_lane.FaultOps(
                pos=jnp.asarray(plan.pos), arg=jnp.asarray(plan.arg),
                aux=jnp.asarray(plan.aux), draws=jnp.asarray(plan.draws),
                params=jnp.asarray(plan.params),
                gcnt=jnp.pad(
                    jnp.asarray(self.init_state.gpu_cnt), (0, n_pad - n0)
                ),
            )
            fc0 = fault_lane.init_fault_carry(p, n_pad, plan.capacity)
            if plan.has_recover:
                # the shard engine cannot capture recover frag deltas (a
                # psum of f32 partials is not bit-equal to the
                # single-device cluster sum, ENGINES.md Round 14) — say
                # so loudly instead of reporting silent 0.0 deltas
                # (ISSUE 11 satellite): counter + [Degrade] line, and
                # assemble_disruption below drops the series entirely
                self.obs.count("degrade_mesh_frag")
                self.log.info(
                    "[Degrade] mesh fault replay: recover frag-delta "
                    "capture is unsupported on the shard engine (psum of "
                    "f32 partials != the one-device sum); "
                    "post_recovery_frag_delta will be empty — run "
                    "mesh=0 to capture it"
                )
            fn = make_shardmap_table_replay(
                self._policy_fns, self._mesh,
                gpu_sel=self.cfg.gpu_sel_method,
                block_size=self.cfg.block_size, faults=True,
            )
            self._last_engine = (
                f"shard_map (mesh={self.cfg.mesh}, fault lane)"
            )
            out = self._dispatch_span(
                lambda: fn(
                    state_p, specs, build_pod_types(specs), kind_d, idx_d,
                    self.typical, key, rank_p, fault_ops=ops,
                    fault_carry0=fc0,
                ),
                engine=self._last_engine, events=e,
            )
            return out._replace(
                state=jax.tree.map(lambda a: a[:n0], out.state)
            )

        ops = fault_lane.FaultOps(
            pos=jnp.asarray(plan.pos), arg=jnp.asarray(plan.arg),
            aux=jnp.asarray(plan.aux), draws=jnp.asarray(plan.draws),
            params=jnp.asarray(plan.params),
            gcnt=jnp.asarray(self.init_state.gpu_cnt),
        )
        fc0 = fault_lane.init_fault_carry(
            p, self.init_state.num_nodes, plan.capacity
        )
        types = build_pod_types(specs)
        k = int(types.share.cpu.shape[0]) + int(types.whole.cpu.shape[0])
        use_table = (
            self.cfg.engine != "sequential"
            and k > 0
            and (self.cfg.engine == "table" or e >= 2 * num_pod_types(specs))
        )
        if use_table:
            fn = make_table_replay(
                self._policy_fns, gpu_sel=self.cfg.gpu_sel_method,
                report=False, block_size=self.cfg.block_size, faults=True,
                fault_frag=plan.has_recover,
                unswitched=self.cfg.unswitched_select,
            )
            self._last_engine = "table (fault lane)"
            out = self._dispatch_span(
                lambda: fn(
                    self.init_state, specs, types, kind_d, idx_d,
                    self.typical, key, self.rank,
                    tables=self._cached_tables(self.init_state, types, key),
                    fault_ops=ops, fault_carry0=fc0,
                ),
                engine=self._last_engine, events=e,
            )
        else:
            fn = make_replay(
                self._policy_fns, gpu_sel=self.cfg.gpu_sel_method,
                report=False, faults=True, fault_frag=plan.has_recover,
            )
            self._last_engine = "sequential (fault lane)"
            out = self._dispatch_span(
                lambda: fn(
                    self.init_state, specs, kind_d, idx_d, self.typical,
                    key, self.rank, fault_ops=ops, fault_carry0=fc0,
                ),
                engine=self._last_engine, events=e,
            )
        return out

    def _emit_fault_log_lines(self, plan, ys, pods):
        """The segmented path's [Fault] narration, reconstructed from the
        plan + per-event fault telemetry (down/up transitions are a pure
        function of the schedule; victims come from the ys)."""
        from tpusim.sim.engine import EV_EVICT, EV_NODE_FAIL, EV_NODE_RECOVER

        nvict = np.asarray(ys.nvict)
        vpod = np.asarray(ys.vpod)
        vnode = np.asarray(ys.vnode)
        fb = np.asarray(ys.fb, np.float64)
        fa = np.asarray(ys.fa, np.float64)
        down: set = set()
        for i, k in enumerate(plan.kind.tolist()):
            pos = int(plan.pos[i])
            a = int(plan.arg[i])
            if k == EV_NODE_FAIL and a not in down:
                down.add(a)
                self.log.info(
                    f"[Fault] node {self.node_names[a]} failed at event "
                    f"{pos}: {int(nvict[i])} pods evicted"
                )
            elif k == EV_NODE_RECOVER and a in down:
                down.discard(a)
                delta = float(fa[i]) - float(fb[i])
                self.log.info(
                    f"[Fault] node {self.node_names[a]} recovered at "
                    f"event {pos} (frag delta {delta:+.1f})"
                )
            elif k == EV_EVICT and int(vpod[i]) >= 0:
                self.log.info(
                    f"[Fault] pod {pods[int(vpod[i])].name} evicted from "
                    f"node {self.node_names[int(vnode[i])]} at event {pos}"
                )

    # ---- reporting (analysis.go) ----

    def _typical_host_rows(self):
        """Typical-pod distribution as host tuples
        [(cpu, gpu_milli, gpu_num, gpu_mask, freq)] — the BellmanEvaluator's
        constructor format."""
        t = getattr(self, "_typical_host", None)
        if t is None:
            t = self._typical_host = device_fetch(self.typical)
        return list(
            zip(
                np.asarray(t.cpu).tolist(),
                np.asarray(t.gpu_milli).tolist(),
                np.asarray(t.gpu_num).tolist(),
                np.asarray(t.gpu_mask).tolist(),
                np.asarray(t.freq).tolist(),
            )
        )

    def _bellman_series(self, start_state, pods, ev_kind, ev_pod, out):
        """Per-event cluster Bellman frag (ref: the `(bellman)` [Report]
        variant, analysis.go:110): reconstruct each event's touched node
        from the replay's (event_node, event_dev) telemetry and update only
        that node's memoized value — mathematically equal to the reference's
        per-event full-cluster sweep because the value function depends on
        node state alone. The whole event stream is evaluated in ONE native
        call (BellmanEvaluator.eval_series) instead of per-event ctypes
        round-trips.

        The series is a deterministic pure function of (typical rows, start
        state, event stream incl. telemetry), so — like XLA's persistent
        compilation cache — a content-keyed disk cache (TPUSIM_BELLMAN_CACHE,
        default <repo>/.bellman_cache, empty disables) lets artifact
        REGENERATION skip the dominant per-experiment host cost. Caching is
        first-call-only per Simulator: later calls (inflation/deschedule
        stages) depend on the warmed memo, whose state embeds evaluation
        order; a first-call cache hit therefore stashes its inputs and
        replays them before any later call evaluates, keeping multi-stage
        values bit-identical to an uncached run."""
        from tpusim.sim.engine import EV_CREATE

        kinds = np.asarray(ev_kind)
        ev_pods = np.asarray(ev_pod)
        pod_cpu = np.fromiter(
            (p.cpu_milli for p in pods), np.int32, count=len(pods)
        )
        pod_gpu = np.fromiter(
            (p.gpu_milli for p in pods), np.int32, count=len(pods)
        )

        start_state = device_fetch(start_state)
        inputs = (
            np.ascontiguousarray(np.asarray(start_state.cpu_left, np.int32)),
            np.ascontiguousarray(np.asarray(start_state.gpu_left, np.int32)),
            np.ascontiguousarray(np.asarray(start_state.gpu_type, np.int32)),
            np.ascontiguousarray(np.asarray(out.event_node, np.int32)),
            np.ascontiguousarray(np.asarray(out.event_dev, np.uint8)),
            np.where(kinds == EV_CREATE, 1, -1).astype(np.int8),
            np.ascontiguousarray(pod_cpu[ev_pods]),
            np.ascontiguousarray(pod_gpu[ev_pods]),
        )

        # "first call" = nothing evaluated OR pending yet: after a cache
        # hit the evaluator is still unbuilt, but later stages must NOT
        # read/write the cache (their values embed the warmed memo's
        # evaluation order — caching them would poison the content keys)
        first_call = (
            self._bellman_eval is None and self._bellman_pending_replay is None
        )
        cache_path = self._bellman_cache_path(inputs) if first_call else None
        if cache_path is not None and os.path.isfile(cache_path):
            self._bellman_pending_replay = inputs
            return np.load(cache_path)

        if self._bellman_eval is None:
            from tpusim.native import BellmanEvaluator

            self._bellman_eval = BellmanEvaluator(self._typical_host_rows())
            pending = getattr(self, "_bellman_pending_replay", None)
            if pending is not None:
                # a later stage after a first-call cache hit: rebuild the
                # memo state the cached call would have produced
                self._bellman_eval.eval_series(*pending)
                self._bellman_pending_replay = None

        series = self._bellman_eval.eval_series(*inputs)
        if cache_path is not None:
            os.makedirs(os.path.dirname(cache_path), exist_ok=True)
            tmp = f"{cache_path}.{os.getpid()}.tmp.npy"
            with open(tmp, "wb") as f:
                np.save(f, series)
            os.replace(tmp, cache_path)
        return series

    def _bellman_cache_path(self, inputs):
        """Content-keyed cache file for a FIRST bellman series of this
        simulator, or None when caching is disabled."""
        import hashlib

        cache_dir = os.environ.get(
            "TPUSIM_BELLMAN_CACHE",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))), ".bellman_cache"),
        )
        if not cache_dir:
            return None
        h = hashlib.sha256()
        # version salt: the evaluator SOURCE participates in the key (like
        # the compiler version in XLA's persistent cache), so changing the
        # native Bellman logic invalidates every cached series
        h.update(_bellman_source_digest())
        for row in self._typical_host_rows():
            h.update(repr(row).encode())
        for a in inputs:
            h.update(a.tobytes())
        return os.path.join(cache_dir, h.hexdigest() + ".npy")

    def _emit_event_reports(self, out, pods, ev_kind, ev_pod, start_state):
        """Per-event log block: `[i] attempt to ...` line (simulator.go:410,
        420; failures echo the deletePod rollback line :354), then the
        frag/alloc/power report lines incl. the bellman variant
        (simulator.go:426-427, analysis.go:109-110). Skip events
        (pod-unscheduled annotation) emit nothing (simulator.go:391-399).
        No-op when per-event reporting is off (the replay carries no
        metrics then). All line families format vectorized over the event
        axis (reports.batch_event_report_msgs) and append in one bulk
        call. The whole block (the Bellman series dominates) runs under
        the obs "report" span."""
        m = out.metrics
        if not self.cfg.report_per_event or m is None:
            return
        with self.obs.span("report", events=int(np.asarray(ev_kind).shape[0])):
            self._emit_event_reports_impl(out, pods, ev_kind, ev_pod,
                                          start_state)

    def _emit_event_reports_impl(self, out, pods, ev_kind, ev_pod,
                                 start_state):
        from tpusim.sim.engine import EV_CREATE, EV_DELETE
        from tpusim.sim.reports import (
            batch_event_report_msgs,
            event_report_series,
        )

        m = out.metrics
        amounts = np.asarray(m.frag_amounts)
        total_gpus = self.total_gpus
        kinds = np.asarray(ev_kind)
        bellman = self._bellman_series(start_state, pods, ev_kind, ev_pod, out)
        names = np.array([p.name for p in pods])
        ev_pods = np.asarray(ev_pod)
        pod_names = names[ev_pods]
        ev_failed = np.asarray(out.ever_failed)[ev_pods]
        series = event_report_series(
            amounts, np.asarray(m.power_cpu), np.asarray(m.power_gpu), bellman
        )
        # stash the structured per-event data for the direct CSV path
        # (experiments/analysis.py analyze_sim — the formatted `series`
        # strings are the SAME objects the log lines embed, so both lanes
        # are byte-identical by construction)
        self.event_reports.append({
            "series": series,
            "frag_amounts": amounts,  # f32[E, 7], FGD category order
            "kinds": kinds,
            "pod_names": pod_names,
            "failed": ev_failed,
            "used_nodes": np.asarray(m.used_nodes),
            "used_gpus": np.asarray(m.used_gpus),
            "used_gpu_milli": np.asarray(m.used_gpu_milli),
            "arrived_gpu_milli": np.asarray(m.arrived_gpu_milli),
            "used_cpu_milli": np.asarray(m.used_cpu_milli),
            "arrived_cpu_milli": np.asarray(m.arrived_cpu_milli),
            "total_gpus": total_gpus,
        })
        self.log.info_many(
            batch_event_report_msgs(
                amounts,
                total_gpus,
                np.asarray(m.used_nodes),
                np.asarray(m.used_gpus),
                np.asarray(m.used_gpu_milli),
                np.asarray(m.arrived_gpu_milli),
                np.asarray(m.used_cpu_milli),
                np.asarray(m.arrived_cpu_milli),
                np.asarray(m.power_cpu),
                np.asarray(m.power_gpu),
                bellman=bellman,
                kinds=kinds,
                ev_create=EV_CREATE,
                ev_delete=EV_DELETE,
                pod_names=pod_names,
                failed=ev_failed,
                series=series,
            )
        )

    def alloc_maps(self, state: NodeState):
        """Cluster requested/allocatable per resource (ref: alloc.go:90-127
        GetNodeAllocMap aggregated)."""
        s = jax.tree.map(np.asarray, state)
        slot = np.arange(s.gpu_left.shape[1])[None, :] < s.gpu_cnt[:, None]
        used_dev = slot & (s.gpu_left < MILLI)
        requested = {
            "MilliCpu": int((s.cpu_cap - s.cpu_left).sum()),
            "Memory": int(np.int64(s.mem_cap - s.mem_left).sum() * 1024 * 1024),
            "Gpu": int(used_dev.sum()),
            "MilliGpu": int((np.where(slot, MILLI - s.gpu_left, 0)).sum()),
        }
        allocatable = {
            "MilliCpu": int(np.int64(s.cpu_cap).sum()),
            "Memory": int(np.int64(s.mem_cap).sum() * 1024 * 1024),
            "Gpu": int(s.gpu_cnt.sum()),
            "MilliGpu": int(s.gpu_cnt.sum()) * MILLI,
        }
        return requested, allocatable

    def cluster_analysis(self, tag: str = "InitSchedule", _amounts=None):
        """The end-of-stage 16-line analysis block (analysis.go:145-199).

        `_amounts` lets run_batch supply precomputed cluster frag amounts
        (one vmapped device call + one fetch for the whole seed group,
        instead of a ~100 ms tunnel round trip per sim)."""
        from tpusim.ops.frag import cluster_frag_report

        state = (
            self.last_result.state if hasattr(self, "last_result") else self.init_state
        )
        if _amounts is not None:
            amounts = np.asarray(_amounts)
        else:
            state_j = jax.tree.map(jnp.asarray, state)
            amounts = np.asarray(cluster_frag_report(state_j, self.typical)[0])
        requested, allocatable = self.alloc_maps(state)
        kv = cluster_analysis_block(
            self.log, tag, amounts, requested, allocatable
        )
        # running summary across stages, in emission order (the direct CSV
        # path's stand-in for re-parsing the blocks out of the log)
        self.analysis_summary.update(kv)
        return amounts, requested, allocatable


# ---------------------------------------------------------------------------
# Shared replay-shape plumbing (single path + seed-batched path)
# ---------------------------------------------------------------------------


def _bucket_sizes(p: int, e: int, bucket: int) -> Tuple[int, int]:
    """Size-adaptive padding targets: large runs share one bucketed
    executable; small runs (descheduler victims, inflation clones) round to
    the next power of two so padding waste stays <= 2x."""
    b = bucket if max(p, e) >= bucket else max(32, 1 << (max(p, e) - 1).bit_length())
    return -(-p // b) * b, -(-e // b) * b


def _pad_specs(specs, p2: int, type_id=None, xp=jnp):
    """Pad pod specs (and their type ids) to p2 rows with inert zero pods
    (pinned -1, never referenced by any event). xp=jnp pads on device
    (single runs); xp=np keeps host arrays (the batched path stacks several
    padded sets before ONE upload — per-leaf device round-trips cost ~100ms
    each over the axon tunnel)."""
    from tpusim.types import PodSpec

    p = int(specs.cpu.shape[0])
    if p2 == p:
        return specs, type_id
    pad = p2 - p
    z = xp.zeros(pad, xp.int32)
    out = PodSpec(
        cpu=xp.concatenate([specs.cpu, z]),
        mem=xp.concatenate([specs.mem, z]),
        gpu_milli=xp.concatenate([specs.gpu_milli, z]),
        gpu_num=xp.concatenate([specs.gpu_num, z]),
        gpu_mask=xp.concatenate([specs.gpu_mask, z]),
        pinned=xp.concatenate([specs.pinned, xp.full(pad, -1, xp.int32)]),
    )
    if type_id is not None:
        type_id = xp.concatenate([type_id, z])
    return out, type_id


def _pad_events(ev_kind, ev_pod, e2: int, xp=jnp):
    """Pad event streams to e2 with EV_SKIP events referencing pod 0."""
    from tpusim.sim.engine import EV_SKIP

    e = int(ev_kind.shape[0])
    if e2 == e:
        return ev_kind, ev_pod
    ev_kind = xp.concatenate(
        [ev_kind, xp.full(e2 - e, EV_SKIP, ev_kind.dtype)]
    )
    ev_pod = xp.concatenate([ev_pod, xp.zeros(e2 - e, ev_pod.dtype)])
    return ev_kind, ev_pod


def _slice_result(out, p: int, e: int):
    """Slice a (possibly padded) ReplayResult back to true pod/event sizes."""
    if int(out.placed_node.shape[0]) == p and int(out.event_node.shape[0]) == e:
        return out
    return out._replace(
        placed_node=out.placed_node[:p],
        dev_mask=out.dev_mask[:p],
        ever_failed=out.ever_failed[:p],
        event_node=out.event_node[:e],
        event_dev=out.event_dev[:e],
        metrics=(
            None
            if out.metrics is None
            else jax.tree.map(lambda a: a[:e], out.metrics)
        ),
        decisions=(
            None
            if out.decisions is None
            else jax.tree.map(lambda a: a[:e], out.decisions)
        ),
        series=(
            None
            if out.series is None
            else jax.tree.map(lambda a: a[:e], out.series)
        ),
    )


# ---------------------------------------------------------------------------
# Seed-batched execution (TPU-native sweep acceleration)
# ---------------------------------------------------------------------------
#
# The reference parallelizes its 1020-experiment sweep across processes on a
# 256-vCPU machine (experiments/README.md step 2, xargs --max-procs). The
# TPU-native equivalent is batching the replays themselves: the per-event
# scan is kernel-launch-bound on one chip (~40 small fused kernels per
# event, see ENGINES.md), so running S same-shape experiments under one
# jax.vmap amortizes every launch S-fold. Measured on the openb FGD replay:
# ~4x aggregate throughput at S=16, per-seed placements bit-identical to
# single runs (metric float rows agree to ~1e-5 relative — vmapped
# reductions may order f32 partial sums differently).

_BATCH_WRAP_CACHE = {}
_BATCHED_METRICS_FN = None


def _batched_metrics_fn():
    """compute_event_metrics vmapped over the seed axis (shared cluster +
    typical pods, per-seed specs/events/telemetry)."""
    global _BATCHED_METRICS_FN
    if _BATCHED_METRICS_FN is None:
        from tpusim.sim.metrics import compute_event_metrics
        from tpusim.types import PodSpec

        _BATCHED_METRICS_FN = jax.jit(
            jax.vmap(
                compute_event_metrics,
                in_axes=(None, PodSpec(0, 0, 0, 0, 0, 0), 0, 0, 0, 0, None),
            )
        )
    return _BATCHED_METRICS_FN


def _batched_engine(fn, table: bool):
    from tpusim.sim.table_engine import PodTypes
    from tpusim.types import PodSpec

    if fn not in _BATCH_WRAP_CACHE:
        spec0 = PodSpec(0, 0, 0, 0, 0, 0)
        none_spec = PodSpec(*(None,) * 6)
        if table:
            in_axes = (None, spec0, PodTypes(none_spec, none_spec, 0),
                       0, 0, None, 0, 0)
        else:
            in_axes = (None, spec0, 0, 0, None, 0, 0)
        _BATCH_WRAP_CACHE[fn] = jax.jit(jax.vmap(fn, in_axes=in_axes))
    return _BATCH_WRAP_CACHE[fn]


def schedule_pods_batch(
    sims: Sequence["Simulator"], pods_list, bucket: int = 512
) -> List[SimulateResult]:
    """Run the main schedule of S same-config experiments (different seeds:
    shuffle order, tuning, tie-break permutation) in ONE vmapped replay.

    Every sim must share the full scheduling configuration and the node
    cluster; pod counts may differ slightly (tuning variance) — all axes
    are padded to common bucketed shapes, exactly like
    Simulator.run_events does for a single run. Results are bit-identical
    to per-sim schedule_pods calls (same engine kernels, vmapped)."""
    return finish_pods_batch(dispatch_pods_batch(sims, pods_list, bucket))


def dispatch_pods_batch(
    sims: Sequence["Simulator"], pods_list, bucket: int = 512
) -> dict:
    """The host-prep + device-dispatch half of schedule_pods_batch. JAX
    dispatch is asynchronous, so the returned handle's device work runs
    while the caller does host work (the sweep pipelines group i's host
    tails under group i+1's replay — the only concurrency available on a
    1-vCPU host driving a remote chip). finish_pods_batch(handle) blocks
    on the results and completes the per-sim bookkeeping."""
    from tpusim.sim.table_engine import build_pod_types, pad_pod_types
    from tpusim.types import PodSpec

    lead = sims[0]
    if lead.cfg.extenders:
        raise ValueError(
            "schedule_pods_batch cannot run extender configs (per-cycle "
            "HTTP round-trips do not batch); run each sim's run() instead"
        )
    if lead.cfg.mesh:
        raise ValueError(
            "schedule_pods_batch cannot run mesh configs (the shard_map "
            "engine owns the device axis); run each sim's run() instead"
        )
    if any(s.cfg.record_decisions for s in sims):
        # ANY recording sim (not just the lead): the batch replays on the
        # lead's engine, so a non-lead recorder would silently get
        # decisions=None instead of its stream
        raise ValueError(
            "schedule_pods_batch cannot record decisions (the vmapped "
            "replay has no per-seed provenance surface); run each sim's "
            "run() instead"
        )
    if any(s.cfg.series_every for s in sims):
        raise ValueError(
            "schedule_pods_batch cannot emit the in-scan series (the "
            "vmapped replay has no per-seed sampling surface); run each "
            "sim's run() instead"
        )
    for s in sims[1:]:
        same = (
            s.cfg.policies == lead.cfg.policies
            and s.cfg.gpu_sel_method == lead.cfg.gpu_sel_method
            and s.cfg.dim_ext_method == lead.cfg.dim_ext_method
            and s.cfg.norm_method == lead.cfg.norm_method
            and s.cfg.report_per_event == lead.cfg.report_per_event
            and s.cfg.use_timestamps == lead.cfg.use_timestamps
            and s.cfg.engine == lead.cfg.engine
            and s.cfg.block_size == lead.cfg.block_size
            and s.cfg.typical_pods == lead.cfg.typical_pods
            and s.nodes == lead.nodes
            # the batched replay scores every seed against lead's typical
            # pods (vmap in_axes None), which is only sound when the seeds
            # share the workload the distribution derives from
            and s.workload_pods == lead.workload_pods
        )
        if not same:
            raise ValueError(
                "schedule_pods_batch requires same-config sims (policies, "
                "gpu/dim/norm methods, report flag, typical-pod knobs, the "
                "node cluster, and the workload may not differ across the "
                "batch)"
            )
    t0 = time.perf_counter()
    specs_list, ev_list = [], []
    for sim, pods in zip(sims, pods_list):
        if sim.typical is None:
            sim.set_typical_pods()
        specs_list.append(pods_to_specs(pods, sim.node_index, device=False))
        ev_list.append(build_events(pods, sim.cfg.use_timestamps))

    p = max(int(s.cpu.shape[0]) for s in specs_list)
    e = max(len(k) for k, _ in ev_list)
    p2, e2 = _bucket_sizes(p, e, bucket)

    # engine knob: `sequential` is honored; `pallas` has no batched form
    # (vmap over the fused kernel is untested), so batches run the
    # bit-identical table engine (SimulatorConfig.engine docstring)
    use_table = lead.cfg.engine != "sequential"
    tids = [None] * len(sims)
    if use_table:
        # one shared type table across the batch: dedup over the
        # concatenated specs; each seed's type_id is its segment of the
        # concat build
        cat = PodSpec(
            *(
                np.concatenate([getattr(s, f) for s in specs_list])
                for f in PodSpec._fields
            )
        )
        types = build_pod_types(cat)
        k = int(types.share.cpu.shape[0]) + int(types.whole.cpu.shape[0])
        # auto: same amortization heuristic run_events applies, per seed
        # (table init costs K node-sweeps; only worth it with enough
        # events); a forced engine='table' is honored whenever any type
        # exists, exactly like the single-run path — so the [Engine] log
        # lines cannot diverge between batched and standalone execution
        from tpusim.sim.table_engine import num_pod_types

        if k == 0 or (
            lead.cfg.engine != "table"
            and any(
                len(kinds) < 2 * num_pod_types(s)
                for s, (kinds, _) in zip(specs_list, ev_list)
            )
        ):
            use_table = False
        else:
            offs = np.cumsum([0] + [int(s.cpu.shape[0]) for s in specs_list])
            tid_all = np.asarray(types.type_id)
            tids = [
                tid_all[offs[i] : offs[i + 1]] for i in range(len(sims))
            ]

    padded = [
        _pad_specs(specs, p2, tid, xp=np)
        for specs, tid in zip(specs_list, tids)
    ]
    padded_ev = [
        _pad_events(
            np.asarray(k, np.int32), np.asarray(pd, np.int32), e2, xp=np
        )
        for k, pd in ev_list
    ]

    specs_b = PodSpec(
        *(
            jnp.asarray(np.stack([getattr(sp, f) for sp, _ in padded]))
            for f in PodSpec._fields
        )
    )
    ev_kind_b = jnp.asarray(np.stack([k for k, _ in padded_ev]))
    ev_pod_b = jnp.asarray(np.stack([pd for _, pd in padded_ev]))
    keys = jnp.stack([jax.random.PRNGKey(s.cfg.seed) for s in sims])
    ranks = jnp.stack([s.rank for s in sims])

    if use_table:
        types_b = types._replace(
            type_id=jnp.asarray(np.stack([tid for _, tid in padded]))
        )
        # stabilize K across sweep groups like run_events does (the
        # type_id remap works elementwise on the stacked [S, P] ids)
        types_b = pad_pod_types(types_b)
        fn = _batched_engine(lead._table_fn, table=True)
        t_dev = time.perf_counter()
        out = fn(
            lead.init_state, specs_b, types_b, ev_kind_b, ev_pod_b,
            lead.typical, keys, ranks,
        )
    else:
        fn = _batched_engine(lead.replay_fn, table=False)
        t_dev = time.perf_counter()
        out = fn(
            lead.init_state, specs_b, ev_kind_b, ev_pod_b,
            lead.typical, keys, ranks,
        )
    if lead.cfg.report_per_event:
        out = out._replace(
            metrics=_batched_metrics_fn()(
                lead.init_state, specs_b, ev_kind_b, ev_pod_b,
                out.event_node, out.event_dev, lead.typical,
            )
        )
    return {
        "sims": sims, "pods_list": pods_list, "ev_list": ev_list,
        "out": out, "use_table": use_table, "t0": t0, "t_dev": t_dev,
        # dispatch-phase host wall: under the sweep's pipeline, unrelated
        # groups' work runs between dispatch and finish, so wall clocks
        # must sum the two phases rather than span them
        "prep_s": time.perf_counter() - t0,
    }


def finish_pods_batch(handle: dict) -> List[SimulateResult]:
    """Block on a dispatch_pods_batch handle and finish per-sim host work
    (fetch, slicing, report emission, result recording)."""
    sims = handle["sims"]
    pods_list = handle["pods_list"]
    ev_list = handle["ev_list"]
    use_table = handle["use_table"]
    lead = sims[0]
    t_fin = time.perf_counter()
    out = device_fetch(handle["out"])
    # device-phase wall (replay dispatch + fetch), excluding the host-side
    # spec padding and result slicing — the like-for-like number against a
    # single run_events call (bench.py batched row). Only meaningful when
    # dispatch and finish run back-to-back (schedule_pods_batch, the bench
    # path); a pipelined caller interleaves other work in between
    lead._last_batch_device_s = time.perf_counter() - handle["t_dev"]
    wall = handle["prep_s"] + (time.perf_counter() - t_fin)

    # the logged name is the engine SEMANTICS (what a cross-backend result
    # diff needs) and must match a single run's line exactly — the batch
    # tests pin line-for-line log equality across execution modes; the
    # batched-execution detail stays in _last_engine for bench labeling
    engine_name = "table" if use_table else "sequential"
    results = []
    for i, (sim, pods) in enumerate(zip(sims, pods_list)):
        ev_kind_i, ev_pod_i = ev_list[i]
        o = _slice_result(
            jax.tree.map(lambda a: a[i], out), len(pods), len(ev_kind_i)
        )
        sim._last_engine = f"{engine_name} ({len(sims)}-seed vmap batch)"
        sim.log.info(
            f"[Engine] replay of {len(ev_kind_i)} events ran on: {engine_name}"
        )
        res, events, unscheduled, rank = sim._finish_replay(
            o, pods, ev_kind_i, ev_pod_i, sim.init_state
        )
        results.append(
            sim._record_result(
                res, pods, events, unscheduled, rank, wall / len(sims)
            )
        )
    return results


_FRAG_BATCH_FN = None


def _batched_frag_amounts(sims) -> np.ndarray:
    """Cluster frag amounts for every sim's final state in ONE vmapped
    device call + ONE fetch (the per-sim cluster_analysis round trip costs
    ~100 ms of tunnel latency each)."""
    global _FRAG_BATCH_FN
    from tpusim.ops.frag import cluster_frag_amounts

    if _FRAG_BATCH_FN is None:
        _FRAG_BATCH_FN = jax.jit(
            jax.vmap(lambda s, tp: cluster_frag_amounts(s, tp).sum(0), (0, None))
        )
    states = jax.tree.map(
        lambda *xs: jnp.asarray(np.stack([np.asarray(x) for x in xs])),
        *[s.last_result.state for s in sims],
    )
    return np.asarray(
        device_fetch(_FRAG_BATCH_FN(states, sims[0].typical))
    )


def run_batch(sims: Sequence["Simulator"]) -> List[SimulateResult]:
    """run() for a seed batch: per-sim host prep and reporting, one
    batched device replay (see schedule_pods_batch)."""
    return finish_run_batch(dispatch_run_batch(sims))


def dispatch_run_batch(sims: Sequence["Simulator"]) -> dict:
    """Host prep + async device dispatch of a seed batch (the dispatch
    half of run_batch; see dispatch_pods_batch). The typical-pod
    distribution is computed once on the lead sim and adopted by its
    same-workload siblings."""
    pods_list = []
    lead = sims[0]
    for sim in sims:
        sim._reset_run_state()
        if (
            sim is lead
            or sim.workload_pods != lead.workload_pods
            or sim.cfg.typical_pods != lead.cfg.typical_pods
        ):
            sim.set_typical_pods()
        else:
            sim.adopt_typical_pods(lead)
        sim.set_skyline_pods()
        pods_list.append(sim.prepare_pods())
        sim.log.info(
            f"Number of original workload pods: {len(sim.workload_pods)}"
        )
    return dispatch_pods_batch(sims, pods_list)


def finish_run_batch(handle: dict) -> List[SimulateResult]:
    sims = handle["sims"]
    results = finish_pods_batch(handle)
    amounts = _batched_frag_amounts(sims)
    for i, (sim, res) in enumerate(zip(sims, results)):
        sim.report_failed([u.pod for u in res.unscheduled_pods])
        sim.cluster_analysis("InitSchedule", _amounts=amounts[i])
    return results


# ---------------------------------------------------------------------------
# Config-axis sweep: one compiled scan, B what-if configurations (ISSUE 6)
# ---------------------------------------------------------------------------
#
# schedule_pods_batch vmaps S same-config experiments whose SEEDS differ
# (per-seed specs/events/keys/ranks). The config-axis sweep generalizes it
# along the axis the reference grids with a process per experiment
# (1020 policy × weight × seed replays): the per-policy WEIGHT VECTOR is
# now a traced engine operand (sim.step.resolve_weights), so a [B, num_pol]
# weight matrix plus per-config seeds vmaps over ONE workload and ONE
# compiled replay — the jaxpr is the policy family's, the weights are
# data. The weight-independent score tables are built once and shared
# across every lane (in_axes None), so the marginal what-if costs only
# its share of the vmapped scan, never a table build or a compile.

_SWEEP_WRAP_CACHE = {}
_SWEEP_METRICS_FN = None


@dataclass
class SweepLane:
    """One configuration's result out of a config-axis sweep — the
    per-lane slice of the vmapped replay plus the summary scalars the
    CLI table prints. Placements are bit-identical to a standalone run
    with `weights` baked into the config and `seed` as cfg.seed
    (tests/test_sweep.py pins this per engine)."""

    weights: np.ndarray  # i32[num_pol] this lane's weight vector
    seed: int
    placed_node: np.ndarray  # i32[P]
    dev_mask: np.ndarray  # bool[P, 8]
    ever_failed: np.ndarray  # bool[P]
    counters: Optional[np.ndarray]  # i32[obs.NUM_COUNTERS], pad-corrected
    metrics: object  # EventMetrics (per-event rows) or None
    state: object  # final NodeState (host arrays)
    events: int
    placed: int  # pods placed at end of trace
    failed: int  # creation attempts rejected
    gpu_alloc_pct: float
    frag_gpu_milli: float
    # pods that ended the trace unplaced AFTER a rejected creation — the
    # schedule_pods_with_faults "unscheduled" semantics (a later retry may
    # place an ever-failed pod; a placed-then-deleted pod is neither).
    # The learned-scoring objective's third term (ISSUE 9): gpu_alloc up,
    # frag down, unscheduled bounded.
    unscheduled: int = 0
    # tpusim.sim.metrics.DisruptionMetrics of this lane's fault schedule
    # (ISSUE 10; None on fault-free sweeps) — bit-identical to the
    # standalone run_with_faults run with the same schedule/seed.
    disruption: object = None


def _sweep_engine(engine, table: bool, donate: bool = True):
    """jit(vmap(engine)) over (key, weights, tiebreak_rank); everything
    else — cluster state, pod specs, types, events, typical pods, and
    the shared score tables — broadcasts (in_axes None). Cached per
    underlying weight-operand engine, which is itself shared across
    weight configs (one jaxpr per job family).

    donate=True (the dispatched form, ISSUE 14 satellite — the PR 11
    run_chunk_donated pattern applied to the batched surfaces): the
    per-lane stacked tiebreak_rank operand — the [B, N] buffer, the one
    whose shape/dtype matches output state leaves — is donated, so a
    repeated-wave caller (the svc worker's batch loop, a tuning run's
    generations) reuses it for a [B, N] output leaf instead of
    reallocating per wave (keys/weights are byte-tiny and alias
    nothing). Safe by construction at every dispatch site: the ranks
    are built fresh inside the schedule_pods_sweep* call and never
    read after dispatch. The
    non-donating twin (donate=False) serves callers that drive the
    wrapper directly with reusable buffers."""
    ck = (engine, bool(donate))
    if ck not in _SWEEP_WRAP_CACHE:
        if table:
            # (state, pods, types, ev_kind, ev_pod, tp, key, wts, rank,
            #  tables)
            in_axes = (None, None, None, None, None, None, 0, 0, 0, None)
            dn = (8,)
        else:
            # (state, pods, ev_kind, ev_pod, tp, key, wts, rank)
            in_axes = (None, None, None, None, None, 0, 0, 0)
            dn = (7,)
        _SWEEP_WRAP_CACHE[ck] = jax.jit(
            jax.vmap(engine, in_axes=in_axes),
            donate_argnums=dn if donate else (),
        )
    return _SWEEP_WRAP_CACHE[ck]


def _sweep_metrics_fn():
    """compute_event_metrics vmapped over the config axis: ONE cluster,
    ONE workload, per-lane telemetry."""
    global _SWEEP_METRICS_FN
    if _SWEEP_METRICS_FN is None:
        from tpusim.sim.metrics import compute_event_metrics

        _SWEEP_METRICS_FN = jax.jit(
            jax.vmap(
                compute_event_metrics,
                in_axes=(None, None, None, None, 0, 0, None),
            )
        )
    return _SWEEP_METRICS_FN


def _reject_unsweepable(cfg) -> None:
    """The execution modes no vmapped config-axis sweep can serve —
    shared by the single-trace and multi-trace (ISSUE 7) paths."""
    if cfg.extenders:
        raise ValueError(
            "schedule_pods_sweep cannot run extender configs (per-cycle "
            "HTTP round-trips do not batch)"
        )
    if cfg.mesh:
        raise ValueError(
            "schedule_pods_sweep cannot run mesh configs (the shard_map "
            "engine owns the device axis)"
        )
    if cfg.record_decisions:
        raise ValueError(
            "schedule_pods_sweep cannot record decisions (the vmapped "
            "replay has no per-config provenance surface)"
        )
    if cfg.series_every:
        raise ValueError(
            "schedule_pods_sweep cannot emit the in-scan series (the "
            "vmapped replay has no per-config sampling surface)"
        )


def _check_sweep_grid(cfg, weights, seeds):
    """Validate the [B, num_pol] weight grid + per-lane seeds; returns
    (w, B, seeds) with defaults resolved."""
    w = np.asarray(weights, np.int32)
    if w.ndim != 2 or w.shape[1] != len(cfg.policies):
        raise ValueError(
            f"weights must be a [B, {len(cfg.policies)}] matrix (one row "
            f"per config, columns in cfg.policies order); got shape "
            f"{w.shape}"
        )
    b = int(w.shape[0])
    if b < 1:
        raise ValueError("weights needs at least one config row")
    if seeds is None:
        seeds = [cfg.seed] * b
    seeds = [int(s) for s in seeds]
    if len(seeds) != b:
        raise ValueError(
            f"seeds has {len(seeds)} entries for {b} weight rows"
        )
    return w, b, seeds


def _slice_sweep_lane(out, amounts, i, wrow, seed, p, e, pad_skips):
    """Slice lane i out of a fetched (host) vmapped sweep result into its
    SweepLane — shared by the single-trace and multi-trace sweep paths
    (the latter passes per-lane true sizes, ISSUE 7)."""
    from tpusim.ops.frag import frag_sum_except_q3

    pn = np.asarray(out.placed_node[i][:p])
    failed_i = np.asarray(out.ever_failed[i][:p])
    ctr = None
    if out.counters is not None:
        ctr = np.asarray(out.counters[i]).astype(np.int64).copy()
        ctr[4] = max(int(ctr[4]) - pad_skips, 0)  # bucket-padding skips
    st = jax.tree.map(lambda a: np.asarray(a[i]), out.state)
    slot = (
        np.arange(st.gpu_left.shape[1])[None, :] < st.gpu_cnt[:, None]
    )
    denom = max(int(st.gpu_cnt.sum()) * MILLI, 1)
    alloc = 100.0 * float(
        np.where(slot, MILLI - st.gpu_left, 0).sum()
    ) / denom
    metrics_i = None
    if out.metrics is not None:
        metrics_i = jax.tree.map(lambda a: np.asarray(a[i][:e]), out.metrics)
    return SweepLane(
        weights=np.asarray(wrow, np.int32).copy(),
        seed=int(seed),
        placed_node=pn,
        dev_mask=np.asarray(out.dev_mask[i][:p]),
        ever_failed=failed_i,
        counters=ctr,
        metrics=metrics_i,
        state=st,
        events=e,
        placed=int((pn >= 0).sum()),
        failed=int(failed_i.sum()),
        gpu_alloc_pct=alloc,
        frag_gpu_milli=float(frag_sum_except_q3(amounts[i])),
        unscheduled=int(((pn < 0) & failed_i).sum()),
    )


def lane_from_arrays(state, placed_node, dev_mask, ever_failed, counters,
                     typical, weights, seed, events,
                     pad_skips: int = 0) -> SweepLane:
    """SweepLane from raw final-run arrays — the shared summary math of
    lane_from_run (standalone/forked chunked runs) and the ChunkWave
    serving path (ISSUE 16). Mirrors _slice_sweep_lane exactly: same
    counters pad-correction, same gpu_alloc slot mask, same frag
    post-pass — so every result document of a family is field-for-field
    comparable regardless of which execution path produced it."""
    from tpusim.ops.frag import cluster_frag_amounts, frag_sum_except_q3

    pn = np.asarray(placed_node, np.int32)
    failed = np.asarray(ever_failed, bool)
    ctr = None
    if counters is not None:
        ctr = np.asarray(counters).astype(np.int64).copy()
        ctr[4] = max(int(ctr[4]) - int(pad_skips), 0)  # bucket padding
    st = jax.tree.map(np.asarray, state)
    slot = (
        np.arange(st.gpu_left.shape[1])[None, :] < st.gpu_cnt[:, None]
    )
    denom = max(int(st.gpu_cnt.sum()) * MILLI, 1)
    alloc = 100.0 * float(
        np.where(slot, MILLI - st.gpu_left, 0).sum()
    ) / denom
    amounts = np.asarray(
        cluster_frag_amounts(
            jax.tree.map(jnp.asarray, st), typical
        ).sum(0)
    )
    return SweepLane(
        weights=np.asarray(weights, np.int32).copy(),
        seed=int(seed),
        placed_node=pn,
        dev_mask=np.asarray(dev_mask),
        ever_failed=failed,
        counters=ctr,
        metrics=None,
        state=st,
        events=int(events),
        placed=int((pn >= 0).sum()),
        failed=int(failed.sum()),
        gpu_alloc_pct=alloc,
        frag_gpu_milli=float(frag_sum_except_q3(amounts)),
        unscheduled=int(((pn < 0) & failed).sum()),
    )


def lane_from_run(sim: "Simulator", weights, seed,
                  pad_skips: int = 0) -> SweepLane:
    """SweepLane view of the Simulator's newest STANDALONE run
    (schedule_pods / schedule_pods_fork) — the svc serving path's result
    vocabulary (learn.objective.lane_terms) applied to base runs and
    warm-state forks, which execute through the chunked replay rather
    than a vmapped sweep."""
    res = sim.last_result
    return lane_from_arrays(
        res.state, res.placed_node, res.dev_mask, sim.last_ever_failed,
        sim.last_counters, sim.typical, weights, seed, int(res.events),
        pad_skips,
    )


class ChunkWave:
    """The continuous-batching chunk surface of the what-if serving
    plane (ISSUE 16): B lanes of one job family stepping through the
    donated `run_chunk` twin TOGETHER, one vmapped dispatch per chunk,
    with per-lane event streams as operands. Because every lane shares
    the family's state/specs/types/typical/weights/rank (forks of one
    base run agree on all of them — the fork index enforces it), the
    vmap axis carries only (carry, ev_kind chunk, ev_pod chunk): a lane
    can be restored from a mid-trace base checkpoint, joined at ANY
    chunk boundary via the scatter entry (replacing a padding lane),
    and finished independently — all through exactly three jitted
    callables whose executable count is the zero-recompile metric.

    Padding discipline mirrors run_events byte-for-byte (_bucket_sizes
    pow2 adaptation included), so `base_digest` here equals the digest
    the standalone base run persisted its checkpoints under — the fork
    index's content contract. Idle/free lanes are fed EV_SKIP chunks:
    the scan body splits the PRNG key BEFORE branching on kind, so a
    skip advances only the key and the skip counter — trailing skip
    count differences between lanes are inert for every extracted
    result (pinned by tests/test_fork.py), and the host-tracked pad
    count corrects the skip counter per lane."""

    def __init__(self, sim: "Simulator", pods, lanes: int, chunk: int,
                 bucket: int = 512):
        from tpusim.io.trace import build_events
        from tpusim.sim.table_engine import build_pod_types, pad_pod_types

        if sim.cfg.mesh or sim.cfg.engine not in ("table", "auto"):
            raise ValueError(
                "chunk waves run on the table engine (engine table/auto, "
                "no mesh)"
            )
        if (sim.cfg.extenders or sim.cfg.record_decisions
                or sim.cfg.series_every):
            raise ValueError(
                "chunk waves have no extender/decision/series surface"
            )
        if sim.typical is None:
            sim.set_typical_pods()
        self.sim = sim
        self.lanes = int(lanes)
        self.chunk = max(1, int(chunk))
        fn = sim._table_fn
        self._fn = fn
        state = sim.init_state
        specs = pods_to_specs(pods, sim.node_index)
        bk, bp = build_events(pods, sim.cfg.use_timestamps)
        bk, bp = jnp.asarray(bk), jnp.asarray(bp)
        validate_events(bk, bp, int(specs.cpu.shape[0]))
        p, e = int(specs.cpu.shape[0]), int(bk.shape[0])
        p2, e2 = _bucket_sizes(p, e, bucket)
        types = build_pod_types(specs)
        k = int(types.share.cpu.shape[0]) + int(types.whole.cpu.shape[0])
        if k == 0:
            raise ValueError(
                "no distinct pod types — the table carry surface needs "
                "at least one"
            )
        specs, tid = _pad_specs(specs, p2, types.type_id, xp=jnp)
        types = types._replace(type_id=tid)
        if p2 != p or e2 != e:
            types = pad_pod_types(types)
        self.base_kind, self.base_pod = _pad_events(bk, bp, e2, xp=jnp)
        self.p, self.e, self.p2, self.e2 = p, e, p2, e2
        self.specs, self.types = specs, types
        self.state = state
        self.key = jax.random.PRNGKey(sim.cfg.seed)
        self.rank = sim.rank
        self.base_digest = sim._run_digest(
            state, specs, self.base_kind, self.base_pod, self.key,
            sim.rank
        )
        self.checkpoint_dir = sim._checkpoint_dir()
        template = jax.eval_shape(
            fn.init_carry, state, specs, types, sim.typical, self.key,
            sim.rank
        )
        self._tleaves, self._tdef = jax.tree.flatten(template)
        typical, rank = sim.typical, sim.rank

        def _chunk1(carry, evk, evp):
            carry, _ys = fn.run_chunk(
                carry, specs, types, evk, evp, typical, rank
            )
            # strip weak_type from every carry leaf: the scan body
            # leaves one weakly-typed counter, and a weak-vs-strong
            # signature flip between host-built carries (stack/restore,
            # strong) and jit outputs (weak) would re-trace step AND
            # scatter once mid-wave — churn the zero-recompile census
            # must not carry
            return ChunkWave._strong(carry)

        # the three compiled entries of the wave: a lane join, a lane
        # finish, and the B-wide chunk advance — each traces exactly
        # once per family (the donated carry keeps buffers in place)
        self._step = jax.jit(
            jax.vmap(_chunk1, in_axes=(0, 0, 0)), donate_argnums=(0,)
        )
        self._scatter = jax.jit(
            lambda batch, lane, i: jax.tree.map(
                lambda b, l: b.at[i].set(l), batch, lane
            ),
            donate_argnums=(0,),
        )

        def _finish1(batch, i):
            lane = jax.tree.map(lambda x: x[i], batch)
            st, placed, masks, failed = fn.finish(lane)
            return st, placed, masks, failed, lane.ctr

        self._finish = jax.jit(_finish1)

    # ---- lane carries ----

    @staticmethod
    def _strong(tree):
        """Strip weak_type from every leaf (values/dtypes unchanged, so
        checkpoints and digests are unaffected) — the wave's signature
        stability contract: every carry that circulates, whether
        host-built or a jit output, presents the same strong-typed
        avals to step/scatter/finish."""
        return jax.tree.map(lambda x: x.astype(x.dtype), tree)

    def init_lane(self):
        """Fresh event-0 carry — full-replay twins and degraded forks."""
        tables = self.sim._cached_tables(self.state, self.types, self.key)
        return self._strong(self._fn.init_carry(
            self.state, self.specs, self.types, self.sim.typical,
            self.key, self.rank, tables=tables,
        ))

    def restore_lane(self, fork_event: int):
        """(cursor, carry) restored from the base run's nearest persisted
        checkpoint at-or-before the divergence event, or None (the
        degrade path — the caller falls back to init_lane). Never
        deletes a base checkpoint it merely fails to interpret."""
        from tpusim.io import storage as ckpt

        def _validate(arrays):
            leaves = [
                arrays[f"c{i:03d}"] for i in range(len(self._tleaves))
            ]
            if any(
                a.shape != t.shape or a.dtype != t.dtype
                for a, t in zip(leaves, self._tleaves)
            ):
                raise ValueError("carry layout mismatch")

        found = ckpt.load_valid_checkpoint(
            self.checkpoint_dir, self.base_digest, validate=_validate,
            max_cursor=int(fork_event), delete_invalid=False,
        )
        if found is None:
            return None
        cursor, arrays, _path = found
        leaves = [
            jnp.asarray(arrays[f"c{i:03d}"])
            for i in range(len(self._tleaves))
        ]
        return cursor, jax.tree.unflatten(self._tdef, leaves)

    def fork_stream(self, fork_event: int, tail):
        """(ev_kind, ev_pod, real) of the forked run: the shared base
        prefix up to fork_event + the divergent ((kind, pod), ...) tail,
        as host arrays. `real` is the true event count; the wave pads
        each lane's final partial chunk with inert EV_SKIPs."""
        bk = np.asarray(self.base_kind)
        bp = np.asarray(self.base_pod)
        tk = np.asarray([k for k, _ in tail], bk.dtype)
        tpd = np.asarray([pd for _, pd in tail], bp.dtype)
        evk = np.concatenate([bk[: int(fork_event)], tk])
        evp = np.concatenate([bp[: int(fork_event)], tpd])
        return evk, evp, int(evk.shape[0])

    # ---- the wave surface ----

    def stack(self, carries):
        """Lane carries -> the batched wave carry (leading lane axis)."""
        return jax.tree.map(lambda *xs: jnp.stack(xs), *carries)

    def step(self, batch_carry, evk, evp):
        """Advance every lane one chunk: evk/evp are [lanes, chunk].
        DONATES batch_carry — the caller rebinds."""
        return self._step(batch_carry, jnp.asarray(evk), jnp.asarray(evp))

    def scatter(self, batch_carry, lane_carry, i: int):
        """Install a joining lane's carry into slot i at a chunk
        boundary (donates batch_carry; i is traced — one executable
        serves every slot)."""
        return self._scatter(batch_carry, lane_carry, jnp.int32(i))

    def finish_lane(self, batch_carry, i: int):
        """(state, placed, masks, failed, counters) of lane i — the
        batch carry survives (not donated) and keeps stepping."""
        return self._finish(batch_carry, jnp.int32(i))

    def executables(self) -> int:
        """Compiled-executable census across the wave's three entries —
        the zero-recompile acceptance metric: stable across join waves,
        lane scatters, and finishes of one family."""
        return (
            self._step._cache_size() + self._scatter._cache_size()
            + self._finish._cache_size()
        )


def schedule_pods_sweep(
    sim: "Simulator", pods, weights, seeds=None, bucket: int = 512,
) -> List[SweepLane]:
    """Evaluate B what-if configurations of one workload in ONE vmapped
    replay: `weights` is a [B, num_pol] i32 matrix (one row per config,
    columns in cfg.policies order), `seeds` an optional length-B list of
    per-config seeds (default: cfg.seed for every lane; a lane's seed
    drives its PRNG key AND its tie-break permutation, exactly like a
    standalone run's cfg.seed). Each lane's placements/counters/metrics
    are bit-identical to a standalone run with that weight vector in the
    config — same kernels, same key splits, vmapped — and the whole
    batch shares one compiled scan and one (weight-independent) table
    build. Engine selection mirrors schedule_pods_batch: the table
    engine unless forced sequential or the workload is too small to
    amortize the table init; pallas has no batched form; extenders /
    mesh / decision-recording / series configs are rejected."""
    from tpusim.ops.frag import cluster_frag_amounts
    from tpusim.sim.table_engine import (
        build_pod_types,
        num_pod_types,
        pad_pod_types,
    )
    from tpusim.types import PodSpec

    cfg = sim.cfg
    _reject_unsweepable(cfg)
    w, b, seeds = _check_sweep_grid(cfg, weights, seeds)
    if sim.typical is None:
        sim.set_typical_pods()

    specs = pods_to_specs(pods, sim.node_index, device=False)
    ev_kind_l, ev_pod_l = build_events(pods, cfg.use_timestamps)
    validate_events(ev_kind_l, ev_pod_l, int(specs.cpu.shape[0]))
    p, e = int(specs.cpu.shape[0]), len(ev_kind_l)
    p2, e2 = _bucket_sizes(p, e, bucket)

    types = build_pod_types(specs)
    k = int(types.share.cpu.shape[0]) + int(types.whole.cpu.shape[0])
    use_table = (
        cfg.engine != "sequential"
        and k > 0
        and (cfg.engine == "table" or e >= 2 * num_pod_types(specs))
    )

    specs_h, tid = _pad_specs(
        specs, p2, types.type_id if use_table else None, xp=np
    )
    ev_kind_h, ev_pod_h = _pad_events(
        np.asarray(ev_kind_l, np.int32), np.asarray(ev_pod_l, np.int32),
        e2, xp=np,
    )
    specs_d = PodSpec(
        *(jnp.asarray(np.asarray(getattr(specs_h, f)))
          for f in PodSpec._fields)
    )
    ev_kind_d, ev_pod_d = jnp.asarray(ev_kind_h), jnp.asarray(ev_pod_h)
    keys = jnp.stack([jax.random.PRNGKey(s) for s in seeds])
    ranks = jnp.stack(
        [jnp.asarray(tiebreak_rank(len(sim.nodes), s)) for s in seeds]
    )
    weights_d = jnp.asarray(w)
    state = sim.init_state

    if use_table:
        types = types._replace(type_id=jnp.asarray(tid))
        if p2 != p or e2 != e:  # bucketed run: stabilize K too
            types = pad_pod_types(types)
        # ONE table build for the whole sweep: the tables hold raw
        # per-policy scores (weight-independent), so every lane shares
        # them bit-identically — through the content-keyed disk cache
        # when configured, else built here once instead of B times
        # under the vmap
        key0 = jax.random.PRNGKey(seeds[0])
        table_fn = sim._table_fn
        if cfg.heartbeat_every:
            # the in-scan heartbeat cond doesn't survive vmap (a batched
            # predicate executes both branches, firing the host tick
            # callback every event per lane) — the sweep replays on the
            # heartbeat-free build of the same family instead
            from tpusim.sim.table_engine import make_table_replay

            sim.log.info(
                "[Sweep] in-scan heartbeat has no batched form; "
                "disabled for the sweep replay"
            )
            table_fn = make_table_replay(
                sim._policy_fns, gpu_sel=cfg.gpu_sel_method, report=False,
                block_size=cfg.block_size,
            )
        tables = sim._cached_tables(state, types, key0)
        if tables is None:
            with sim.obs.span("init_tables", cache="sweep-shared") as h:
                tables = table_fn.build_tables(
                    state, types, sim.typical, key0
                )
                h.dispatched()
        fn = _sweep_engine(table_fn.engine.replay, table=True)
        sim._last_engine = f"table ({b}-config vmap sweep)"
        out = sim._dispatch_span(
            lambda: fn(
                state, specs_d, types, ev_kind_d, ev_pod_d, sim.typical,
                keys, weights_d, ranks, tables,
            ),
            engine=sim._last_engine, events=e,
        )
    else:
        fn = _sweep_engine(sim.replay_fn.engine, table=False)
        sim._last_engine = f"sequential ({b}-config vmap sweep)"
        out = sim._dispatch_span(
            lambda: fn(
                state, specs_d, ev_kind_d, ev_pod_d, sim.typical, keys,
                weights_d, ranks,
            ),
            engine=sim._last_engine, events=e,
        )
    sim.obs.note_scan(sim._last_engine, counters=None, events=e * b)
    sim.log.info(
        f"[Engine] sweep of {b} configs x {e} events ran on: "
        f"{sim._last_engine}"
    )
    if cfg.report_per_event:
        out = out._replace(
            metrics=_sweep_metrics_fn()(
                state, specs_d, ev_kind_d, ev_pod_d,
                out.event_node, out.event_dev, sim.typical,
            )
        )
    # per-lane frag of the final states in one vmapped call (the same
    # reduction cluster_analysis reports), before the single fetch
    amounts = jax.jit(
        jax.vmap(
            lambda s, tp: cluster_frag_amounts(s, tp).sum(0),
            in_axes=(0, None),
        )
    )(out.state, sim.typical)
    with sim.obs.span("fetch", events=e * b):
        out = device_fetch(out)
        amounts = np.asarray(amounts)

    pad_skips = e2 - e
    return [
        _slice_sweep_lane(out, amounts, i, w[i], seeds[i], p, e, pad_skips)
        for i in range(b)
    ]


# ---------------------------------------------------------------------------
# Multi-trace sweep: the trace-operand lift (ISSUE 7)
# ---------------------------------------------------------------------------
#
# schedule_pods_sweep broadcasts ONE workload across every lane (in_axes
# None on specs/types/events) — so two what-if jobs differing in their
# TUNE FACTOR (a different tuned pod list, hence different specs/events)
# could not share its compiled scan. The multi-trace sweep lifts the
# remaining scalar: each lane carries its own tuned trace as DATA —
# per-lane specs [B, P], type_id [B, P], and event streams [B, E], all
# padded to common buckets, vmapped alongside (key, weights, rank) —
# while the cluster state, the DISTINCT type set (concat-dedup across
# lanes, the dispatch_pods_batch discipline), the typical pods, and the
# once-built score tables still broadcast. The jaxpr is the policy
# family's at the padded shapes; the tune factor is an operand, so the
# replay service packs tune-differing jobs onto one compiled sweep.

_SWEEP_MULTI_WRAP_CACHE = {}
_SWEEP_MULTI_FAULT_WRAP_CACHE = {}
_SWEEP_MULTI_METRICS_FN = None


def _sweep_engine_multi(engine, table: bool, donate: bool = True,
                        donate_streams: bool = False):
    """jit(vmap(engine)) over per-lane (specs, type_id, events, key,
    weights, rank); cluster state, distinct type set, typical pods, and
    the shared score tables broadcast (in_axes None). The trace-operand
    generalization of _sweep_engine: lanes may replay different tuned
    workloads and still share one compiled scan. donate=True donates
    the per-lane rank like _sweep_engine.

    donate_streams=True additionally donates the per-lane ev_pod stream
    (ISSUE 15 satellite — the PR 11 run_chunk_donated pattern finishing
    the ROADMAP's "sweep/service lane carries reallocate per wave"
    leftover): the [B, E] i32 buffer's shape/dtype matches the
    event_node output leaf exactly, so a repeated-wave caller (the svc
    worker's batch loop) reuses it instead of reallocating per wave.
    Only legal when nothing reads the stream after dispatch — the
    metrics postpass does, so schedule_pods_sweep_multi passes it as
    `not report_per_event`. The (engine, donate, donate_streams) cache
    key keeps the zero-recompile bookkeeping intact: consecutive waves
    of one family resolve to the same jitted wrapper, donation being
    part of the executable's aliasing contract, not its jaxpr."""
    from tpusim.sim.table_engine import PodTypes
    from tpusim.types import PodSpec

    ck = (engine, bool(donate), bool(donate_streams))
    if ck not in _SWEEP_MULTI_WRAP_CACHE:
        spec0 = PodSpec(0, 0, 0, 0, 0, 0)
        none_spec = PodSpec(*(None,) * 6)
        if table:
            # (state, pods, types, ev_kind, ev_pod, tp, key, wts, rank,
            #  tables) — type_id is per-lane, the distinct set broadcasts
            in_axes = (None, spec0, PodTypes(none_spec, none_spec, 0),
                       0, 0, None, 0, 0, 0, None)
            dn = (8,) + ((4,) if donate_streams else ())
        else:
            # (state, pods, ev_kind, ev_pod, tp, key, wts, rank)
            in_axes = (None, spec0, 0, 0, None, 0, 0, 0)
            dn = (7,) + ((3,) if donate_streams else ())
        _SWEEP_MULTI_WRAP_CACHE[ck] = jax.jit(
            jax.vmap(engine, in_axes=in_axes),
            donate_argnums=dn if donate else (),
        )
    return _SWEEP_MULTI_WRAP_CACHE[ck]


def _sweep_multi_fault_engine(engine, table: bool, donate: bool = True,
                              donate_streams: bool = True):
    """The chaos x tune lift (ISSUE 12): jit(vmap(engine)) over per-lane
    (specs, type_id, MERGED fault streams, key, weights, rank, fault
    ops) — the union of _sweep_engine_multi's per-lane trace operands
    and _sweep_fault_engine's per-lane fault operands. Cluster state,
    the distinct type set, typical pods, the shared tables, and the
    initial fault carry broadcast, so mixed fault/tune/weight jobs share
    ONE compiled scan. donate_streams donates the per-lane merged pod
    stream like _sweep_engine_multi — default ON here because the chaos
    tail computes no metrics postpass and never re-reads it (the
    disruption assembly reads out.fault_ys, not the operands)."""
    from tpusim.sim.fault_lane import FaultOps
    from tpusim.sim.table_engine import PodTypes
    from tpusim.types import PodSpec

    ck = (engine, bool(donate), bool(donate_streams))
    if ck not in _SWEEP_MULTI_FAULT_WRAP_CACHE:
        spec0 = PodSpec(0, 0, 0, 0, 0, 0)
        none_spec = PodSpec(*(None,) * 6)
        fops_axes = FaultOps(0, 0, 0, 0, 0, None)
        if table:
            # (state, pods, types, evk, evp, tp, key, wts, rank, tables,
            #  fault_ops, fault_carry0)
            in_axes = (None, spec0, PodTypes(none_spec, none_spec, 0),
                       0, 0, None, 0, 0, 0, None, fops_axes, None)
            dn = (8,) + ((4,) if donate_streams else ())
        else:
            # (state, pods, evk, evp, tp, key, wts, rank, fault_ops,
            #  fault_carry0)
            in_axes = (None, spec0, 0, 0, None, 0, 0, 0, fops_axes, None)
            dn = (7,) + ((3,) if donate_streams else ())
        _SWEEP_MULTI_FAULT_WRAP_CACHE[ck] = jax.jit(
            jax.vmap(engine, in_axes=in_axes),
            donate_argnums=dn if donate else (),
        )
    return _SWEEP_MULTI_FAULT_WRAP_CACHE[ck]


def _sweep_multi_metrics_fn():
    """compute_event_metrics vmapped over per-lane specs/events (the
    _batched_metrics_fn axes): ONE cluster, per-lane workloads."""
    global _SWEEP_MULTI_METRICS_FN
    if _SWEEP_MULTI_METRICS_FN is None:
        from tpusim.sim.metrics import compute_event_metrics
        from tpusim.types import PodSpec

        _SWEEP_MULTI_METRICS_FN = jax.jit(
            jax.vmap(
                compute_event_metrics,
                in_axes=(None, PodSpec(0, 0, 0, 0, 0, 0), 0, 0, 0, 0, None),
            )
        )
    return _SWEEP_MULTI_METRICS_FN


def schedule_pods_sweep_multi(
    sim: "Simulator", pods_list, weights, seeds=None, bucket: int = 512,
    min_pods: int = 0, min_events: int = 0, fault_specs=None,
) -> List[SweepLane]:
    """Evaluate B what-if configurations that may each carry their OWN
    workload (tuned trace variants of one cluster — the tune-factor
    operand lift, ISSUE 7) in ONE vmapped replay: lane i replays
    `pods_list[i]` under weight row i and seed i. Every lane must share
    the Simulator's cluster, policy family, and typical-pod distribution
    (the service's batching rule — jaxpr identity); the traces
    themselves are data. Each lane's placements/counters/metrics are
    bit-identical to a standalone run over that trace with those
    weights/seed/tune baked into the config — the type table is the
    concat-dedup across lanes (the schedule_pods_batch discipline, which
    pins that a shared sorted type set replays identically) and the
    weight-independent score tables are built once and broadcast.
    Engine selection mirrors schedule_pods_sweep.

    `fault_specs` (ISSUE 12, the chaos x tune lift): an optional
    length-B list of per-lane fault schedules — FaultConfig /
    (FaultConfig, events) per resolve_fault_spec, or None for a
    fault-free lane riding the faulted build under an empty schedule.
    Each lane's schedule is compiled against ITS OWN tuned base stream
    (the merged per-lane streams replace the base event operands), so
    mixed fault/tune/weight jobs share one compiled scan and each lane
    stays bit-identical to the standalone run_with_faults run over that
    tuned trace (given the sweep's unified retry-queue capacity —
    explicit queue_capacity pins it, the chaos-sweep contract)."""
    from tpusim.ops.frag import cluster_frag_amounts
    from tpusim.sim.table_engine import (
        build_pod_types,
        num_pod_types,
        pad_pod_types,
    )
    from tpusim.types import PodSpec

    cfg = sim.cfg
    _reject_unsweepable(cfg)
    w, b, seeds = _check_sweep_grid(cfg, weights, seeds)
    if len(pods_list) != b:
        raise ValueError(
            f"pods_list has {len(pods_list)} traces for {b} weight rows "
            "(want one workload per config lane)"
        )
    if fault_specs is not None:
        if len(fault_specs) != b:
            raise ValueError(
                f"fault_specs has {len(fault_specs)} entries for {b} "
                "weight rows (want one fault schedule — or None — per "
                "lane)"
            )
        if cfg.use_timestamps:
            raise ValueError(
                "the chaos sweep replays creation-ordered traces "
                "(use_timestamps=False)"
            )
    if sim.typical is None:
        sim.set_typical_pods()

    specs_list, ev_list = [], []
    for pods in pods_list:
        specs = pods_to_specs(pods, sim.node_index, device=False)
        ev_kind_l, ev_pod_l = build_events(pods, cfg.use_timestamps)
        validate_events(ev_kind_l, ev_pod_l, int(specs.cpu.shape[0]))
        specs_list.append(specs)
        ev_list.append((ev_kind_l, ev_pod_l))
    # `min_pods`/`min_events` are sticky shape floors: below the 512
    # bucket the padding targets are size-adaptive, so a service batch of
    # slightly smaller tuned traces would otherwise land on a SMALLER
    # padded shape than its predecessor and force a pointless recompile —
    # the worker passes each job family's high-water marks here so
    # consecutive batches share one executable (jaxpr identity includes
    # the padded shapes)
    p = max(max(int(s.cpu.shape[0]) for s in specs_list), int(min_pods))
    e = max(max(len(k) for k, _ in ev_list), int(min_events))
    p2, e2 = _bucket_sizes(p, e, bucket)

    # one shared type table across the lanes: dedup over the concatenated
    # specs (np.unique's sorted order is canonical, so any lane set that
    # EQUALS the union — e.g. every tuned variant of one base trace —
    # gets the exact table layout its standalone bucketed run builds);
    # each lane's type_id is its segment of the concat build
    cat = PodSpec(
        *(
            np.concatenate([np.asarray(getattr(s, f)) for s in specs_list])
            for f in PodSpec._fields
        )
    )
    types = build_pod_types(cat)
    k = int(types.share.cpu.shape[0]) + int(types.whole.cpu.shape[0])
    use_table = (
        cfg.engine != "sequential"
        and k > 0
        and (
            cfg.engine == "table"
            or all(
                len(kinds) >= 2 * num_pod_types(s)
                for s, (kinds, _) in zip(specs_list, ev_list)
            )
        )
    )

    tids = [None] * b
    if use_table:
        offs = np.cumsum([0] + [int(s.cpu.shape[0]) for s in specs_list])
        tid_all = np.asarray(types.type_id)
        tids = [tid_all[offs[i]: offs[i + 1]] for i in range(b)]

    padded = [
        _pad_specs(s, p2, tid, xp=np) for s, tid in zip(specs_list, tids)
    ]
    specs_b = PodSpec(
        *(
            jnp.asarray(np.stack([np.asarray(getattr(sp, f))
                                  for sp, _ in padded]))
            for f in PodSpec._fields
        )
    )
    keys = jnp.stack([jax.random.PRNGKey(s) for s in seeds])
    ranks = jnp.stack(
        [jnp.asarray(tiebreak_rank(len(sim.nodes), s)) for s in seeds]
    )
    weights_d = jnp.asarray(w)
    state = sim.init_state
    true_events = sum(len(kk) for kk, _ in ev_list)

    if fault_specs is not None:
        if use_table:
            types = types._replace(
                type_id=jnp.asarray(np.stack([tid for _, tid in padded]))
            )
            types = pad_pod_types(types)
        return _dispatch_sweep_multi_faults(
            sim, fault_specs, specs_list, ev_list, specs_b, types,
            use_table, keys, weights_d, ranks, w, seeds, state, p2,
            bucket,
        )

    padded_ev = [
        _pad_events(
            np.asarray(kk, np.int32), np.asarray(pp, np.int32), e2, xp=np
        )
        for kk, pp in ev_list
    ]
    ev_kind_b = jnp.asarray(np.stack([kk for kk, _ in padded_ev]))
    ev_pod_b = jnp.asarray(np.stack([pp for _, pp in padded_ev]))

    if use_table:
        types = types._replace(
            type_id=jnp.asarray(np.stack([tid for _, tid in padded]))
        )
        # ALWAYS stabilize K (pad_pod_types works elementwise on the
        # stacked [B, P] ids): consecutive service batches whose tuned
        # traces differ slightly in K must hit one compiled executable
        types = pad_pod_types(types)
        key0 = jax.random.PRNGKey(seeds[0])
        table_fn = sim._table_fn
        if cfg.heartbeat_every:
            # same contract as schedule_pods_sweep: the in-scan heartbeat
            # cond has no batched form — replay the heartbeat-free build
            from tpusim.sim.table_engine import make_table_replay

            sim.log.info(
                "[Sweep] in-scan heartbeat has no batched form; "
                "disabled for the sweep replay"
            )
            table_fn = make_table_replay(
                sim._policy_fns, gpu_sel=cfg.gpu_sel_method, report=False,
                block_size=cfg.block_size,
            )
        # the tables broadcast: init_tables reads only the DISTINCT type
        # set (never type_id), so one build — disk-cached under the
        # type_id-free digest (ISSUE 7) — serves every tuned lane
        tables = sim._cached_tables(state, types, key0)
        if tables is None:
            with sim.obs.span("init_tables", cache="sweep-shared") as h:
                tables = table_fn.build_tables(
                    state, types, sim.typical, key0
                )
                h.dispatched()
        fn = _sweep_engine_multi(
            table_fn.engine.replay, table=True,
            donate_streams=not cfg.report_per_event,
        )
        sim._last_sweep_fn = fn  # executables() tracking (svc worker)
        sim._last_engine = f"table ({b}-trace vmap sweep)"
        out = sim._dispatch_span(
            lambda: fn(
                state, specs_b, types, ev_kind_b, ev_pod_b, sim.typical,
                keys, weights_d, ranks, tables,
            ),
            engine=sim._last_engine, events=true_events,
        )
    else:
        fn = _sweep_engine_multi(
            sim.replay_fn.engine, table=False,
            donate_streams=not cfg.report_per_event,
        )
        sim._last_sweep_fn = fn  # executables() tracking (svc worker)
        sim._last_engine = f"sequential ({b}-trace vmap sweep)"
        out = sim._dispatch_span(
            lambda: fn(
                state, specs_b, ev_kind_b, ev_pod_b, sim.typical, keys,
                weights_d, ranks,
            ),
            engine=sim._last_engine, events=true_events,
        )
    sim.obs.note_scan(sim._last_engine, counters=None, events=true_events)
    sim.log.info(
        f"[Engine] sweep of {b} traces x <= {e} events ran on: "
        f"{sim._last_engine}"
    )
    if cfg.report_per_event:
        out = out._replace(
            metrics=_sweep_multi_metrics_fn()(
                state, specs_b, ev_kind_b, ev_pod_b,
                out.event_node, out.event_dev, sim.typical,
            )
        )
    amounts = jax.jit(
        jax.vmap(
            lambda s, tp: cluster_frag_amounts(s, tp).sum(0),
            in_axes=(0, None),
        )
    )(out.state, sim.typical)
    with sim.obs.span("fetch", events=true_events):
        out = device_fetch(out)
        amounts = np.asarray(amounts)

    return [
        _slice_sweep_lane(
            out, amounts, i, w[i], seeds[i],
            int(specs_list[i].cpu.shape[0]), len(ev_list[i][0]),
            e2 - len(ev_list[i][0]),
        )
        for i in range(b)
    ]


def _dispatch_sweep_multi_faults(
    sim, fault_specs, specs_list, ev_list, specs_b, types, use_table,
    keys, weights_d, ranks, w, seeds, state, p2, bucket,
):
    """The fault tail of schedule_pods_sweep_multi (ISSUE 12): per-lane
    fault plans compiled against each lane's OWN tuned base stream, the
    merged streams replacing the base event operands. The sticky
    per-Simulator chaos shape floors (`sim._chaos_hw` — merged-stream
    length, draw rows, queue capacity, frag flag) are shared with
    schedule_pods_sweep_faults, so a service family's consecutive mixed
    fault/tune waves hold one compiled executable."""
    from tpusim.ops.frag import cluster_frag_amounts
    from tpusim.sim import fault_lane
    from tpusim.sim.engine import make_replay
    from tpusim.sim.faults import FaultConfig
    from tpusim.sim.table_engine import make_table_replay

    cfg = sim.cfg
    b = len(specs_list)
    resolved = []
    for spec, (kinds_l, _) in zip(fault_specs, ev_list):
        if spec is None:
            # a fault-free lane of a mixed batch: an empty schedule is
            # an exact no-op on the fault lane (no merged steps beyond
            # the base stream, the carry never moves)
            resolved.append((FaultConfig(), []))
        else:
            resolved.append(
                resolve_fault_spec(spec, len(sim.nodes), len(kinds_l))
            )
    hw_em, hw_rows, hw_cap, hw_rec = getattr(
        sim, "_chaos_hw", (0, 0, 0, False)
    )
    capacity = max(
        max(
            fault_lane.resolve_capacity(fcfg, int(s.cpu.shape[0]))
            for (fcfg, _), s in zip(resolved, specs_list)
        ),
        hw_cap,
    )
    plan_cache: dict = {}
    plans = []
    for (fcfg, events), (kinds_l, pods_l) in zip(resolved, ev_list):
        key = (repr(fcfg), tuple(events), len(kinds_l))
        plan = plan_cache.get(key)
        if plan is None:
            plan = fault_lane.compile_fault_plan(
                kinds_l, pods_l, events, fcfg, len(sim.nodes),
                int(specs_b.cpu.shape[1]), capacity=capacity,
            )
            plan_cache[key] = plan
        plans.append(plan)
    (kinds, idxs, poss, args, auxs, draws, params, capacity, has_rec) = (
        fault_lane.pad_fault_plans(
            plans, bucket=bucket, min_stream=hw_em, min_rows=hw_rows,
        )
    )
    e_m = int(kinds.shape[1])
    has_rec = bool(has_rec or hw_rec)
    sim._chaos_hw = (e_m, int(draws.shape[1]), capacity, has_rec)

    ops = fault_lane.FaultOps(
        pos=jnp.asarray(poss), arg=jnp.asarray(args),
        aux=jnp.asarray(auxs), draws=jnp.asarray(draws),
        params=jnp.asarray(params), gcnt=jnp.asarray(state.gpu_cnt),
    )
    fc0 = fault_lane.init_fault_carry(p2, state.num_nodes, capacity)
    kinds_d, idxs_d = jnp.asarray(kinds), jnp.asarray(idxs)
    true_events = sum(len(kk) for kk, _ in ev_list)

    if use_table:
        key0 = jax.random.PRNGKey(seeds[0])
        table_fn = make_table_replay(
            sim._policy_fns, gpu_sel=cfg.gpu_sel_method, report=False,
            block_size=cfg.block_size, faults=True, fault_frag=has_rec,
        )
        tables = sim._cached_tables(state, types, key0)
        if tables is None:
            with sim.obs.span("init_tables", cache="sweep-shared") as h:
                tables = table_fn.engine.build_tables(
                    state, types, sim.typical, key0
                )
                h.dispatched()
        fn = _sweep_multi_fault_engine(table_fn.engine.replay, table=True)
        sim._last_sweep_fn = fn  # executables() tracking (svc worker)
        sim._last_engine = f"table ({b}-lane chaos x trace sweep)"
        out = sim._dispatch_span(
            lambda: fn(
                state, specs_b, types, kinds_d, idxs_d, sim.typical,
                keys, weights_d, ranks, tables, ops, fc0,
            ),
            engine=sim._last_engine, events=true_events,
        )
    else:
        seq_fn = make_replay(
            sim._policy_fns, gpu_sel=cfg.gpu_sel_method, report=False,
            faults=True, fault_frag=has_rec,
        )
        fn = _sweep_multi_fault_engine(seq_fn.engine, table=False)
        sim._last_sweep_fn = fn  # executables() tracking (svc worker)
        sim._last_engine = f"sequential ({b}-lane chaos x trace sweep)"
        out = sim._dispatch_span(
            lambda: fn(
                state, specs_b, kinds_d, idxs_d, sim.typical, keys,
                weights_d, ranks, ops, fc0,
            ),
            engine=sim._last_engine, events=true_events,
        )
    sim.obs.note_scan(sim._last_engine, counters=None, events=true_events)
    sim.log.info(
        f"[Engine] chaos x trace sweep of {b} lanes (merged stream "
        f"{e_m}) ran on: {sim._last_engine}"
    )
    amounts = jax.jit(
        jax.vmap(
            lambda s, tp: cluster_frag_amounts(s, tp).sum(0),
            in_axes=(0, None),
        )
    )(out.state, sim.typical)
    with sim.obs.span("fetch", events=true_events):
        out = device_fetch(out)
        amounts = np.asarray(amounts)

    gcnt_h = np.asarray(state.gpu_cnt)
    lanes = []
    for i in range(b):
        ys_i = jax.tree.map(lambda a: np.asarray(a)[i], out.fault_ys)
        fc_i = jax.tree.map(lambda a: np.asarray(a)[i], out.fault_carry)
        dm, dead, attempts_run = fault_lane.assemble_disruption(
            plans[i], ys_i, fc_i, gcnt_h
        )
        p_i = int(specs_list[i].cpu.shape[0])
        e_i = plans[i].num_events
        lane = _slice_sweep_lane(
            out, amounts, i, w[i], seeds[i], p_i, e_i,
            e_m - e_i - attempts_run,
        )
        lane.disruption = dm
        lane.events = e_i + attempts_run
        lane.unscheduled = int(
            ((lane.placed_node < 0)
             & (lane.ever_failed | dead[:p_i])).sum()
        )
        lanes.append(lane)
    return lanes


# ---------------------------------------------------------------------------
# Chaos sweep: fault schedules as sweep operands (ISSUE 10)
# ---------------------------------------------------------------------------
#
# The config-axis sweep's last missing operand: a fault schedule used to
# force one full compile+replay per scenario (the segmented host loop
# cannot vmap). With the fault plane inside the scan
# (tpusim.sim.fault_lane), a schedule is just five i32 streams + a draw
# table + a param vector — per-lane DATA. B disruption what-ifs over one
# trace (varying fault seed / MTBF / evict cadence / backoff) therefore
# run as ONE compiled vmapped scan; each lane is bit-identical to the
# standalone run_with_faults run with that schedule.

_SWEEP_FAULT_WRAP_CACHE = {}


def _sweep_fault_engine(engine, table: bool, donate: bool = True):
    """jit(vmap(engine)) for the chaos sweep: per-lane (merged streams,
    key, weights, rank, fault ops); cluster state, pod specs, types,
    typical pods, tables, the initial fault carry, and the global
    gpu-count row broadcast. donate=True donates the per-lane rank like
    _sweep_engine."""
    from tpusim.sim.fault_lane import FaultOps

    ck = (engine, bool(donate))
    if ck not in _SWEEP_FAULT_WRAP_CACHE:
        fops_axes = FaultOps(0, 0, 0, 0, 0, None)
        if table:
            # (state, pods, types, evk, evp, tp, key, wts, rank, tables,
            #  fault_ops, fault_carry0)
            in_axes = (None, None, None, 0, 0, None, 0, 0, 0, None,
                       fops_axes, None)
            dn = (8,)
        else:
            # (state, pods, evk, evp, tp, key, wts, rank, fault_ops,
            #  fault_carry0)
            in_axes = (None, None, 0, 0, None, 0, 0, 0, fops_axes, None)
            dn = (7,)
        _SWEEP_FAULT_WRAP_CACHE[ck] = jax.jit(
            jax.vmap(engine, in_axes=in_axes),
            donate_argnums=dn if donate else (),
        )
    return _SWEEP_FAULT_WRAP_CACHE[ck]


def resolve_fault_spec(spec, num_nodes: int, num_events: int):
    """One chaos-sweep lane spec -> (FaultConfig, [FaultEvent]): a bare
    FaultConfig generates its seeded MTBF schedule; a (FaultConfig,
    events) tuple carries an explicit schedule with the config supplying
    the retry/backoff knobs."""
    from tpusim.sim.faults import FaultConfig, generate_fault_schedule

    if isinstance(spec, tuple) and len(spec) == 2:
        fcfg, events = spec
        return fcfg, list(events)
    if isinstance(spec, FaultConfig):
        return spec, generate_fault_schedule(num_nodes, num_events, spec)
    raise ValueError(
        "each fault lane must be a FaultConfig (seeded MTBF schedule) or "
        f"a (FaultConfig, [FaultEvent]) tuple, got {type(spec).__name__}"
    )


def schedule_pods_sweep_faults(
    sim: "Simulator", pods, weights, fault_specs, seeds=None,
    bucket: int = 512,
) -> List[SweepLane]:
    """Evaluate B fault what-ifs of ONE workload in ONE vmapped replay:
    lane i replays the shared trace under weight row i, seed i, and
    fault spec i (resolve_fault_spec). Lanes share the compiled scan —
    the merged streams are padded to a common bucketed length (inert
    EV_SKIP steps), draw tables to a common row count, and the retry
    queue capacity is unified to the lanes' max — so a later sweep with
    DIFFERENT schedules of similar size hits the same executable (the
    chaos-smoke zero-recompile pin). Each SweepLane carries its
    DisruptionMetrics, bit-identical to the standalone run_with_faults
    run with that schedule (tests/test_fault_lane.py)."""
    from tpusim.ops.frag import cluster_frag_amounts
    from tpusim.sim import fault_lane
    from tpusim.sim.engine import make_replay
    from tpusim.sim.table_engine import (
        build_pod_types,
        make_table_replay,
        num_pod_types,
        pad_pod_types,
    )
    from tpusim.types import PodSpec

    cfg = sim.cfg
    _reject_unsweepable(cfg)
    if cfg.use_timestamps:
        raise ValueError(
            "the chaos sweep replays creation-ordered traces "
            "(use_timestamps=False)"
        )
    w, b, seeds = _check_sweep_grid(cfg, weights, seeds)
    if len(fault_specs) != b:
        raise ValueError(
            f"fault_specs has {len(fault_specs)} entries for {b} weight "
            "rows (want one fault schedule per lane)"
        )
    if sim.typical is None:
        sim.set_typical_pods()

    specs = pods_to_specs(pods, sim.node_index, device=False)
    ev_kind_l, ev_pod_l = build_events(pods, False)
    validate_events(ev_kind_l, ev_pod_l, int(specs.cpu.shape[0]))
    p, e = int(specs.cpu.shape[0]), len(ev_kind_l)

    resolved = [
        resolve_fault_spec(s, len(sim.nodes), e) for s in fault_specs
    ]
    # sticky per-Simulator shape floors (the svc worker's min_pods/
    # min_events discipline): queue capacity, padded stream length, and
    # draw-table rows only ever grow, so consecutive chaos waves on one
    # sim share one executable (the zero-recompile pin)
    hw_em, hw_rows, hw_cap, hw_rec = getattr(
        sim, "_chaos_hw", (0, 0, 0, False)
    )
    capacity = max(
        max(fault_lane.resolve_capacity(fcfg, p) for fcfg, _ in resolved),
        hw_cap,
    )
    # dedup identical lane specs before compiling: a tuning population
    # rolls EVERY lane under one schedule (learn.rollout), and each plan
    # compile walks the merged stream + pre-draws victim tables — paying
    # it once per distinct schedule instead of once per lane
    plan_cache: dict = {}
    plans = []
    for fcfg, events in resolved:
        key = (repr(fcfg), tuple(events))
        plan = plan_cache.get(key)
        if plan is None:
            plan = fault_lane.compile_fault_plan(
                ev_kind_l, ev_pod_l, events, fcfg, len(sim.nodes), p,
                capacity=capacity,
            )
            plan_cache[key] = plan
        plans.append(plan)
    (kinds, idxs, poss, args, auxs, draws, params, capacity, has_rec) = (
        fault_lane.pad_fault_plans(
            plans, bucket=bucket, min_stream=hw_em, min_rows=hw_rows,
        )
    )
    e_m = int(kinds.shape[1])
    # the frag-delta capture is a static build flag (engine cache key) —
    # sticky too, so a recover-free wave after a recovering one reuses
    # the recovering build (the extra ys are just zeros)
    has_rec = bool(has_rec or hw_rec)
    sim._chaos_hw = (e_m, int(draws.shape[1]), capacity, has_rec)

    specs_d = PodSpec(
        *(jnp.asarray(np.asarray(getattr(specs, f)))
          for f in PodSpec._fields)
    )
    keys = jnp.stack([jax.random.PRNGKey(s) for s in seeds])
    ranks = jnp.stack(
        [jnp.asarray(tiebreak_rank(len(sim.nodes), s)) for s in seeds]
    )
    weights_d = jnp.asarray(w)
    state = sim.init_state
    ops = fault_lane.FaultOps(
        pos=jnp.asarray(poss), arg=jnp.asarray(args),
        aux=jnp.asarray(auxs), draws=jnp.asarray(draws),
        params=jnp.asarray(params), gcnt=jnp.asarray(state.gpu_cnt),
    )
    fc0 = fault_lane.init_fault_carry(p, state.num_nodes, capacity)
    kinds_d, idxs_d = jnp.asarray(kinds), jnp.asarray(idxs)

    types = build_pod_types(specs)
    k = int(types.share.cpu.shape[0]) + int(types.whole.cpu.shape[0])
    use_table = (
        cfg.engine != "sequential"
        and k > 0
        and (cfg.engine == "table" or e >= 2 * num_pod_types(specs))
    )
    if use_table:
        types = pad_pod_types(types)  # stabilize K across chaos batches
        key0 = jax.random.PRNGKey(seeds[0])
        table_fn = make_table_replay(
            sim._policy_fns, gpu_sel=cfg.gpu_sel_method, report=False,
            block_size=cfg.block_size, faults=True, fault_frag=has_rec,
        )
        tables = sim._cached_tables(state, types, key0)
        if tables is None:
            with sim.obs.span("init_tables", cache="sweep-shared") as h:
                tables = table_fn.engine.build_tables(
                    state, types, sim.typical, key0
                )
                h.dispatched()
        fn = _sweep_fault_engine(table_fn.engine.replay, table=True)
        sim._last_sweep_fn = fn  # executables() tracking (learn.rollout)
        sim._last_engine = f"table ({b}-lane chaos sweep)"
        out = sim._dispatch_span(
            lambda: fn(
                state, specs_d, types, kinds_d, idxs_d, sim.typical,
                keys, weights_d, ranks, tables, ops, fc0,
            ),
            engine=sim._last_engine, events=e * b,
        )
    else:
        seq_fn = make_replay(
            sim._policy_fns, gpu_sel=cfg.gpu_sel_method, report=False,
            faults=True, fault_frag=has_rec,
        )
        fn = _sweep_fault_engine(seq_fn.engine, table=False)
        sim._last_sweep_fn = fn  # executables() tracking (learn.rollout)
        sim._last_engine = f"sequential ({b}-lane chaos sweep)"
        out = sim._dispatch_span(
            lambda: fn(
                state, specs_d, kinds_d, idxs_d, sim.typical, keys,
                weights_d, ranks, ops, fc0,
            ),
            engine=sim._last_engine, events=e * b,
        )
    sim.obs.note_scan(sim._last_engine, counters=None, events=e * b)
    sim.log.info(
        f"[Engine] chaos sweep of {b} fault lanes x {e} events "
        f"(merged stream {e_m}) ran on: {sim._last_engine}"
    )
    amounts = jax.jit(
        jax.vmap(
            lambda s, tp: cluster_frag_amounts(s, tp).sum(0),
            in_axes=(0, None),
        )
    )(out.state, sim.typical)
    with sim.obs.span("fetch", events=e * b):
        out = device_fetch(out)
        amounts = np.asarray(amounts)

    gcnt_h = np.asarray(state.gpu_cnt)
    lanes = []
    for i in range(b):
        ys_i = jax.tree.map(lambda a: np.asarray(a)[i], out.fault_ys)
        fc_i = jax.tree.map(lambda a: np.asarray(a)[i], out.fault_carry)
        dm, dead, attempts_run = fault_lane.assemble_disruption(
            plans[i], ys_i, fc_i, gcnt_h
        )
        lane = _slice_sweep_lane(
            out, amounts, i, w[i], seeds[i], p, e,
            e_m - plans[i].num_events - attempts_run,
        )
        lane.disruption = dm
        lane.events = plans[i].num_events + attempts_run
        # dead pods are terminal max-retries-exceeded — the standalone
        # path's unscheduled accounting includes them
        lane.unscheduled = int(
            ((lane.placed_node < 0)
             & (lane.ever_failed | dead[:p])).sum()
        )
        lanes.append(lane)
    return lanes


def format_chaos_table(lanes: Sequence[SweepLane], policies) -> str:
    """Per-lane disruption frontier of a chaos sweep — the `tpusim apply
    --sweep-faults` output: placements plus the DisruptionMetrics
    headline numbers per fault schedule."""
    names = [n for n, _ in policies]
    head = (
        f"{'lane':>4} {'weights(' + ','.join(names) + ')':<28} "
        f"{'seed':>6} {'placed':>7} {'evicted':>8} {'resched':>8} "
        f"{'dead':>5} {'fails':>6} {'lat_mean':>9} {'gpu_alloc%':>10} "
        f"{'frag_gpu_milli':>15}"
    )
    rows = [head, "-" * len(head)]
    for i, ln in enumerate(lanes):
        dm = ln.disruption
        wstr = ",".join(str(int(x)) for x in ln.weights)
        rows.append(
            f"{i:>4} {wstr:<28} {ln.seed:>6} {ln.placed:>7} "
            f"{dm.evicted_pods:>8} {dm.rescheduled_pods:>8} "
            f"{dm.unscheduled_after_retries:>5} {dm.node_failures:>6} "
            f"{dm.mean_reschedule_latency():>9.2f} "
            f"{ln.gpu_alloc_pct:>10.2f} {ln.frag_gpu_milli:>15.0f}"
        )
    return "\n".join(rows)


def format_sweep_table(lanes: Sequence[SweepLane], policies) -> str:
    """Per-config summary table of a sweep — the `tpusim apply
    --sweep-weights` output: one row per lane with its weight vector,
    seed, placed/failed counts, GPU allocation, and frag gpu-milli."""
    names = [n for n, _ in policies]
    head = (
        f"{'cfg':>4} {'weights(' + ','.join(names) + ')':<32} "
        f"{'seed':>6} {'placed':>7} {'failed':>7} "
        f"{'gpu_alloc%':>10} {'frag_gpu_milli':>15}"
    )
    rows = [head, "-" * len(head)]
    for i, ln in enumerate(lanes):
        wstr = ",".join(str(int(x)) for x in ln.weights)
        rows.append(
            f"{i:>4} {wstr:<32} {ln.seed:>6} {ln.placed:>7} "
            f"{ln.failed:>7} {ln.gpu_alloc_pct:>10.2f} "
            f"{ln.frag_gpu_milli:>15.0f}"
        )
    return "\n".join(rows)
