"""Typical-pod and skyline-pod extraction (ref: pkg/utils/frag.go:285-409).

Host-side (runs once per workload swap, core.go:195-209); the result is a
fixed [T] TypicalPods array consumed by every frag kernel.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

from tpusim.constants import (
    DEFAULT_TYPICAL_POD_INCREASE_STEP,
    DEFAULT_TYPICAL_POD_POPULARITY,
    MILLI,
    gpu_spec_to_mask,
)
from tpusim.io.trace import PodRow
from tpusim.types import TypicalPods, make_typical_pods


@dataclass
class TypicalPodsConfig:
    """ref: pkg/api/v1alpha1/types.go:104-109."""

    is_involved_cpu_pods: bool = True
    pod_popularity_threshold: int = 0  # 0 → default 60
    pod_increase_step: int = 0  # 0 → default 10
    gpu_res_weight: float = 0.0


def get_typical_pods(
    pods: Sequence[PodRow], cfg: TypicalPodsConfig = TypicalPodsConfig()
) -> Tuple[TypicalPods, List[Tuple[tuple, float]]]:
    """Histogram pod specs, keep the top specs covering the popularity
    threshold in increase-step batches, renormalize to Σfreq = 1
    (ref: frag.go:285-380 GetTypicalPods).

    Returns (TypicalPods arrays, [(spec_key, freq)] for logging/debugging).
    """
    counts: dict = {}
    total = 0.0
    for p in pods:
        if not cfg.is_involved_cpu_pods and p.num_gpu == 0:
            continue
        w = 1.0
        if cfg.gpu_res_weight > 0 and p.gpu_milli == MILLI:
            w = 1.0 + p.num_gpu * cfg.gpu_res_weight
        key = p.spec_key()
        counts[key] = counts.get(key, 0.0) + w
        total += w
    if not counts:
        return make_typical_pods([]), []

    # sort.Reverse over (Percentage, PodResource.Less): descending count,
    # ties by descending (cpu, milli, gpu_num, gpu_type) (resource.go:18-42).
    ordered = sorted(counts.items(), key=lambda kv: (-kv[1],) + _neg_key(kv[0]))

    threshold = cfg.pod_popularity_threshold or DEFAULT_TYPICAL_POD_POPULARITY
    step = cfg.pod_increase_step or DEFAULT_TYPICAL_POD_INCREASE_STEP
    expected = threshold * total / 100.0
    i, pod_res_num, cum = 0, 0, 0.0
    while cum < expected:
        pod_res_num += step
        while i < pod_res_num and i < len(ordered):
            cum += ordered[i][1]
            i += 1
        if pod_res_num >= len(ordered):
            break

    kept = ordered[:i]
    denom = cum if i < len(ordered) else total
    rows, info = [], []
    for key, cnt in kept:
        cpu, milli, num, spec = key
        freq = cnt / denom
        rows.append((cpu, milli, num, gpu_spec_to_mask(spec), freq))
        info.append((key, freq))
    return make_typical_pods(rows), info


def _neg_key(key: tuple) -> tuple:
    cpu, milli, num, spec = key
    return (-cpu, -milli, -num, _neg_str(spec))


class _neg_str(str):
    """Reverses string comparison for the descending GpuType tie-break."""

    def __lt__(self, other):  # noqa: D105
        return str.__gt__(self, other)


def get_skyline_pods(pods: Sequence[PodRow]) -> List[Tuple[int, int]]:
    """Pareto skyline over (MilliCpu, MilliGpu) (ref: frag.go:382-409):
    stable-sort ascending by (cpu, milli), then keep points with strictly
    larger CPU and strictly smaller GPU than the last kept one."""
    res = sorted(pods, key=lambda p: (p.cpu_milli, p.gpu_milli))
    skyline: List[Tuple[int, int]] = []
    for p in res:
        if not skyline or (
            p.cpu_milli > skyline[-1][0] and p.gpu_milli < skyline[-1][1]
        ):
            skyline.append((p.cpu_milli, p.gpu_milli))
    return skyline


def pad_typical_pods(tp: TypicalPods, multiple: int = 16) -> TypicalPods:
    """Pad the typical-pod axis with zero-frequency rows to a stable
    multiple. freq == 0 rows contribute nothing to any frag amount, score,
    or Bellman value (all are freq-weighted sums), so results are unchanged;
    the stable T lets a sweep over trace variants share compiled replays."""
    import jax.numpy as jnp

    t = int(tp.cpu.shape[0])
    t2 = -(-max(t, 1) // multiple) * multiple
    if t2 == t:
        return tp
    pad = t2 - t
    z = jnp.zeros(pad, tp.cpu.dtype)
    return TypicalPods(
        cpu=jnp.concatenate([tp.cpu, z]),
        gpu_milli=jnp.concatenate([tp.gpu_milli, z]),
        gpu_num=jnp.concatenate([tp.gpu_num, z]),
        gpu_mask=jnp.concatenate([tp.gpu_mask, z]),
        freq=jnp.concatenate([tp.freq, jnp.zeros(pad, tp.freq.dtype)]),
    )
