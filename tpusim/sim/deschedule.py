"""Descheduling: evict pods to reduce fragmentation, then reschedule
(ref: pkg/simulator/deschedule.go + deschedule_utils.go).

Three policies (deschedule.go:14-18):
- cosSim:       on congested nodes (cpu_left < 2000, some device > 500 milli
                free), evict the pod whose removal leaves the node's free
                vector least similar to the pod's request vector
                (deschedule_utils.go:15-45).
- fragOnePod:   walk nodes in descending frag order, evict the single pod
                whose removal reduces node frag the most (score > 0)
                (deschedule.go:94-119).
- fragMultiPod: same victim rule, but a max-heap over node frag amounts lets
                one node be revisited after its priority drops
                (deschedule.go:121-178).

TPU-first structure: every candidate score — the hypothetical node frag /
cosine similarity after evicting each placed pod — is one batched vmap over
the pod axis (`eviction_scores`), computed once. The reference makes this
exact precomputation legal: its nodeResMap snapshot is taken at entry and
never refreshed during the eviction loop (deschedule.go:24 vs :111,160 —
deletePod mutates the fake cluster, not the map), so victim scores are
entry-state functions even under fragMultiPod's revisits. The remaining host
loop is heap bookkeeping over a few hundred victims.
"""

from __future__ import annotations

import heapq
import math
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpusim.constants import MILLI
from tpusim.ops.frag import node_frag_score
from tpusim.types import NodeState, PodSpec, TypicalPods

COS_SIM_CPU_BAR = 2000  # deschedule.go:52-53, "temporarily hard-code"
COS_SIM_GPU_BAR = 500

DESCHEDULE_POLICIES = ("cosSim", "fragOnePod", "fragMultiPod")


@jax.jit
def eviction_scores(
    state: NodeState, pods: PodSpec, placed, dev_mask, tp: TypicalPods
):
    """Batched candidate scoring for all placed pods.

    Returns (new_frag f32[P], cos_sim f32[P], old_frag f32[N]):
    - new_frag[p]: frag score of pod p's node after evicting p
      (ref: nodeRes.Add(podRes) → NodeGpuShareFragAmount,
      deschedule_utils.go:86-92)
    - cos_sim[p]: similarity of the node's post-eviction free vector
      [cpu_left, total_gpu_left] with p's request vector
      (ref: GetResourceSimilarity, utils.go:1181-1212); -1 where undefined
    - old_frag[n]: current frag score per node (the heap priorities)
    """
    n_idx = jnp.maximum(placed, 0)

    def per_pod(i):
        node = n_idx[i]
        cpu_left = state.cpu_left[node] + pods.cpu[i]
        gpu_left = state.gpu_left[node] + dev_mask[i].astype(jnp.int32) * pods.gpu_milli[i]
        frag = node_frag_score(cpu_left, gpu_left, state.gpu_type[node], tp)
        free = jnp.array(
            [cpu_left, gpu_left.sum()], jnp.float32
        )
        req = jnp.array(
            [pods.cpu[i], pods.gpu_milli[i] * pods.gpu_num[i]], jnp.float32
        )
        denom = jnp.linalg.norm(free) * jnp.linalg.norm(req)
        sim = jnp.where(denom > 0, free @ req / denom, -1.0)
        sim = jnp.where((sim >= -1e-3) & (sim <= 1 + 1e-3), jnp.clip(sim, 0, 1), -1.0)
        return frag, sim

    new_frag, cos_sim = jax.vmap(per_pod)(jnp.arange(placed.shape[0]))
    old_frag = jax.vmap(
        lambda c, g, t: node_frag_score(c, g, t, tp)
    )(state.cpu_left, state.gpu_left, state.gpu_type)
    return new_frag, cos_sim, old_frag


def _pods_by_node(placed: np.ndarray, num_nodes: int) -> List[List[int]]:
    by_node: List[List[int]] = [[] for _ in range(num_nodes)]
    for i, n in enumerate(placed):
        if n >= 0:
            by_node[n].append(i)
    return by_node


def select_victims(
    state: NodeState,
    pods: PodSpec,
    placed: np.ndarray,
    dev_mask: np.ndarray,
    tp: TypicalPods,
    policy: str,
    ratio: float,
    node_names: Sequence[str] = None,
) -> List[int]:
    """Pick pods to deschedule; returns victim pod indices in eviction order
    (ref: DescheduleCluster, deschedule.go:20-47; budget = ceil(ratio ×
    current pods), deschedule.go:27)."""
    placed = np.asarray(placed)
    dev_mask = np.asarray(dev_mask)
    n_pods_placed = int((placed >= 0).sum())
    budget = math.ceil(ratio * n_pods_placed)
    if budget <= 0 or n_pods_placed == 0:
        return []

    new_frag, cos_sim, old_frag = (
        np.asarray(x)
        for x in eviction_scores(
            state, pods, jnp.asarray(placed), jnp.asarray(dev_mask), tp
        )
    )
    num_nodes = state.num_nodes
    by_node = _pods_by_node(placed, num_nodes)
    s = jax.tree.map(np.asarray, state)
    names = node_names or [f"node-{i:05d}" for i in range(num_nodes)]

    if policy == "cosSim":
        return _victims_cos_sim(s, by_node, cos_sim, names, budget)
    if policy == "fragOnePod":
        return _victims_frag_one(by_node, new_frag, old_frag, budget)
    if policy == "fragMultiPod":
        return _victims_frag_multi(by_node, new_frag, old_frag, names, budget)
    raise ValueError(f"DeschedulePolicy not found: {policy!r}")


def _victims_cos_sim(s, by_node, cos_sim, names, budget) -> List[int]:
    """deschedule.go:49-92: congested-node walk, min-similarity victim."""
    total_gpu_left = s.gpu_left.sum(-1)
    below = s.cpu_left < COS_SIM_CPU_BAR
    # stable partition: below-bar nodes first, each group by total GPU left
    # desc then name asc (sortNodeStatusByResource, deschedule_utils.go:47-71)
    order = sorted(
        range(len(names)), key=lambda i: (~below[i], -total_gpu_left[i], names[i])
    )
    victims: List[int] = []
    for n in order:
        if len(victims) >= budget:
            break
        if s.cpu_left[n] >= COS_SIM_CPU_BAR:
            continue
        if not (s.gpu_left[n] > COS_SIM_GPU_BAR).any():
            continue
        best, best_sim = -1, 1.0  # strict < 1 (deschedule_utils.go:17,34)
        for p in by_node[n]:
            if 0 <= cos_sim[p] < best_sim:
                best, best_sim = p, cos_sim[p]
        if best >= 0:
            victims.append(best)
    return victims


def _victims_frag_one(by_node, new_frag, old_frag, budget) -> List[int]:
    """deschedule.go:94-119: one victim per node, desc frag order."""
    order = np.argsort(-old_frag, kind="stable")
    victims: List[int] = []
    for n in order:
        if len(victims) >= budget:
            break
        best, best_score = -1, 0  # strictly positive (deschedule_utils.go:75,93)
        for p in by_node[n]:
            score = int(old_frag[n] - new_frag[p])  # int64 truncation, :92
            if score > best_score:
                best, best_score = p, score
        if best >= 0:
            victims.append(best)
    return victims


def _victims_frag_multi(by_node, new_frag, old_frag, names, budget) -> List[int]:
    """deschedule.go:121-178: max-heap over node frag; a node re-enters the
    heap with its victim's post-eviction frag as the new priority. Scores
    keep using the entry-state new_frag (the reference's stale nodeResMap)."""
    heap = [(-old_frag[n], names[n], n) for n in range(len(by_node))]
    heapq.heapify(heap)
    remaining = [list(ps) for ps in by_node]
    victims: List[int] = []
    while len(victims) < budget and heap:
        neg_pri, name, n = heapq.heappop(heap)
        pri = -neg_pri
        best, best_score = -1, 0
        for p in remaining[n]:
            score = int(pri - new_frag[p])
            if score > best_score:
                best, best_score = p, score
        if best >= 0:
            victims.append(best)
            remaining[n].remove(best)
            heapq.heappush(heap, (-float(new_frag[best]), name, n))
    return victims


def evict(
    state: NodeState, pods: PodSpec, placed, dev_mask, victims: Sequence[int]
) -> NodeState:
    """Return resources of all victim pods at once (ref: deletePod per victim,
    simulator.go:334-357; batched scatter-add here)."""
    if len(victims) == 0:
        return state
    from tpusim.policies.clustering import pod_affinity_class

    v = jnp.asarray(np.asarray(victims, np.int32))
    placed = jnp.asarray(placed)
    dev_mask = jnp.asarray(dev_mask)
    nodes = placed[v]
    vpods = jax.tree.map(lambda a: a[v], pods)
    cls = jax.vmap(pod_affinity_class)(vpods)
    return state._replace(
        cpu_left=state.cpu_left.at[nodes].add(pods.cpu[v]),
        mem_left=state.mem_left.at[nodes].add(pods.mem[v]),
        gpu_left=state.gpu_left.at[nodes].add(
            dev_mask[v].astype(jnp.int32) * pods.gpu_milli[v][:, None]
        ),
        aff_cnt=state.aff_cnt.at[nodes, jnp.maximum(cls, 0)].add(
            jnp.where(cls >= 0, -1, 0)
        ),
    )
