"""One scheduling cycle as a pure function (replaces vendored
scheduleOne: Filter → Score → Normalize → selectHost → Reserve → Bind,
generic_scheduler.go:143-210 + plugin/open_gpu_share.go Reserve).

The reference's per-cycle node parallelism (a 16-way parallelize helper over
nodes) becomes a vmap over the node axis; the annotation/patch round-trips of
Reserve/Bind become a scatter update of the NodeState arrays.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp

from tpusim.constants import MAX_GPUS_PER_NODE, MILLI
from tpusim.ops.resource import (
    allocate_share_best,
    allocate_share_random,
    allocate_share_worst,
    allocate_two_pointer,
    can_allocate,
    is_accessible,
)
from tpusim.policies import ScoreContext, minmax_normalize_i32, pwr_normalize_i32
from tpusim.policies.clustering import pod_affinity_class
from tpusim.types import NodeState, PodSpec

_INT_MAX = jnp.int32(jnp.iinfo(jnp.int32).max)


def resolve_weights(policies, weights=None) -> jnp.ndarray:
    """The per-policy weight vector as an i32[num_pol] OPERAND (ISSUE 6).

    Weights used to be trace-time Python constants (`jnp.int32(weight)`
    baked into every engine's jaxpr), so each what-if weight change paid
    a full recompile. Every engine now multiplies by this traced vector
    instead; None resolves to the static weights carried in `policies`,
    which is bit-identical to the former baked form (the same i32
    multiply on the same values — only the jaxpr's operand/constant
    split moves). The config-axis sweep vmaps over a [B, num_pol] stack
    of these."""
    if weights is None:
        return jnp.asarray([w for _, w in policies], jnp.int32)
    w = jnp.asarray(weights, jnp.int32)
    if w.shape != (len(policies),):
        raise ValueError(
            f"weights shape {w.shape} does not match the {len(policies)} "
            "configured policies"
        )
    return w


# Score policies whose kernel hands its own Reserve-phase GPU choice to the
# gpuSelMethod machinery (ref: the allocateGpuIdFunc registry,
# plugin/open_gpu_share.go:39 + fgd_score.go:36 / pwr_score.go:41 /
# dot_product_score.go:37)
SELF_SELECT_POLICIES = frozenset({"FGDScore", "PWRScore", "DotProductScore"})


def filter_nodes(state: NodeState, pod: PodSpec) -> jnp.ndarray:
    """Filter phase → bool[N] feasibility.

    Combines the default NodeResourcesFit (cpu/mem request fit) with the
    Open-Gpu-Share Filter (open_gpu_share.go:81-118): GPU pods need a GPU
    node, a matching GPU model, and an AllocateGpuId packing
    (gpunodeinfo.go:136-204 — can_allocate reproduces its feasibility).
    """
    # node-axis padding rows (parallel.pad_nodes) need no special casing:
    # they carry mem_left == -1, failing the mem check for every request
    fit = (state.cpu_left >= pod.cpu) & (state.mem_left >= pod.mem)
    # nodeSelector pinning (snapshot re-bind, export.go:44-58): a pinned pod
    # is only feasible on its pinned node; pinned == -1 means unconstrained.
    n = state.num_nodes
    fit = fit & (
        (pod.pinned < 0) | (jnp.arange(n, dtype=jnp.int32) == pod.pinned)
    )
    gpu_ok = (
        (state.gpu_cnt > 0)
        & is_accessible(state.gpu_type, pod.gpu_mask)
        & jax.vmap(can_allocate, in_axes=(0, None, None))(
            state.gpu_left, pod.gpu_milli, pod.gpu_num
        )
    )
    needs_gpu = pod.total_gpu_milli() > 0
    return fit & (~needs_gpu | gpu_ok)


class Placement(NamedTuple):
    """Result of one cycle. node == -1 → unschedulable (the reference marks
    the pod condition and deletes it, simulator.go:444-455)."""

    node: jnp.ndarray  # i32, -1 = failed
    dev_mask: jnp.ndarray  # bool[8] devices taken (all False for CPU pods)


def _choose_share_device(gpu_left, pod, policy_dev, gpu_sel: str, key):
    """Reserve-phase device choice for a share-GPU pod
    (open_gpu_share.go:252-343): the configured gpuSelMethod either delegates
    to the scoring policy's own pick or uses best/worst/random fit."""
    if gpu_sel == "best":
        return allocate_share_best(gpu_left, pod.gpu_milli)
    if gpu_sel == "worst":
        return allocate_share_worst(gpu_left, pod.gpu_milli)
    if gpu_sel == "random":
        return allocate_share_random(gpu_left, pod.gpu_milli, key)
    # policy-provided (FGDScore / PWRScore / DotProductScore): fall back to
    # best-fit if the policy had no pick (defensive; post-Filter it has one).
    return jnp.where(
        policy_dev >= 0, policy_dev, allocate_share_best(gpu_left, pod.gpu_milli)
    )


def choose_devices(gpu_left, pod, policy_dev_scalar, gpu_sel: str, key):
    """Reserve-phase device mask for one node row: share-GPU pods go through
    the gpuSelMethod machinery (_choose_share_device), whole/multi-GPU pods
    through the two-pointer pack in device-index order (gpunodeinfo.go:
    182-201; == first fully-free devices when milli == 1000). Shared by the
    global select_and_bind and the shard_map engine's owner-local bind."""
    share_dev = _choose_share_device(gpu_left, pod, policy_dev_scalar, gpu_sel, key)
    share_mask = jax.nn.one_hot(share_dev, MAX_GPUS_PER_NODE, dtype=jnp.bool_) & (
        share_dev >= 0
    )
    units, _ = allocate_two_pointer(gpu_left, pod.gpu_milli, pod.gpu_num)
    whole_mask = units > 0
    is_share = pod.is_gpu_share()
    has_gpu = pod.total_gpu_milli() > 0
    return jnp.where(has_gpu, jnp.where(is_share, share_mask, whole_mask), False)


def packed_argmax(
    total: jnp.ndarray,  # i32[M] scores (any granularity: nodes or blocks)
    valid: jnp.ndarray,  # bool[M]
    rank: jnp.ndarray,  # i32[M] tie-break rank (smaller wins)
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """selectHost's lexicographic (max score, min tie-break rank) argmax —
    the ONE packed-key reduction shared by the sequential oracle, the flat
    table engine, and the blocked table engine (which runs it twice: per
    block over nodes, then globally over block summaries; identical combine
    in, bit-identical winner out). Returns (index, best_score, ok).

    Two reductions: max score over valid entries, then argmax of -rank
    among the winners (= min rank); validity of the result is read off the
    winner key instead of a third reduction
    (generic_scheduler.go:187-212)."""
    best = jnp.max(jnp.where(valid, total, -_INT_MAX))
    wkey = jnp.where(valid & (total == best), -rank, -_INT_MAX)
    idx = jnp.argmax(wkey).astype(jnp.int32)
    ok = wkey[idx] != -_INT_MAX
    return idx, best, ok


def packed_topk(
    total: jnp.ndarray,  # i32[M] scores (nodes, blocks, or merge candidates)
    valid: jnp.ndarray,  # bool[M]
    rank: jnp.ndarray,  # i32[M] tie-break rank (smaller wins)
    k: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """K-extension of packed_argmax for the decision flight recorder
    (ISSUE 4): the first k entries of selectHost's (max score, min
    tie-break rank) selection order — entry 0 IS the packed_argmax
    winner, entries 1.. are the runner-ups. Returns (pos i32[k],
    total i32[k], rank i32[k], ok bool[k]); invalid tail entries carry
    pos/rank -1, total 0. Exact by construction: k iterated
    packed_argmax reductions, each masking the previous winner out, so
    the ordering cannot drift from the single-winner combine any engine
    selects with."""
    m = total.shape[0]
    iota = jnp.arange(m, dtype=jnp.int32)
    pos, tot, rnk, oks = [], [], [], []
    v = valid
    for _ in range(k):
        idx, best, ok = packed_argmax(total, v, rank)
        pos.append(jnp.where(ok, idx, -1).astype(jnp.int32))
        tot.append(jnp.where(ok, best, 0).astype(jnp.int32))
        rnk.append(jnp.where(ok, rank[idx], -1).astype(jnp.int32))
        oks.append(ok)
        v = v & (iota != idx)
    return jnp.stack(pos), jnp.stack(tot), jnp.stack(rnk), jnp.stack(oks)


def build_decision(
    node: jnp.ndarray,  # i32 committed winner (-1 = no feasible node)
    raws: jnp.ndarray,  # i32[num_pol, M] per-policy raw score rows
    norms: jnp.ndarray,  # i32[num_pol, M] per-policy NORMALIZED rows
    total: jnp.ndarray,  # i32[M] weighted totals (what selectHost reduced)
    feasible: jnp.ndarray,  # bool[M] Filter mask incl. pinning
    rank: jnp.ndarray,  # i32[M] tie-break rank
):
    """DecisionRecord for one create event from full per-policy score
    rows — the ONE record builder shared by the sequential oracle and the
    flat/blocked table engines (the shard engine reproduces the same
    record through its collective merge), so the captured provenance is
    engine-invariant by construction. Positions in the row arrays must be
    global node ids (the blocked path's sentinel pad columns are
    infeasible and rank-INT_MAX, so they can never enter the top-K).
    `block` is left at -1; blocked selects overwrite it with the winning
    block id (an engine-specific slot, like the counters' `rebuilds`)."""
    from tpusim.obs.decisions import DECISION_TOPK, DecisionRecord

    ok = node >= 0
    sel = jnp.maximum(node, 0)
    pos, tot, rnk, oks = packed_topk(total, feasible, rank, DECISION_TOPK)
    return DecisionRecord(
        node=node.astype(jnp.int32),
        total=jnp.where(ok, total[sel], 0).astype(jnp.int32),
        raw=jnp.where(ok, raws[:, sel], 0).astype(jnp.int32),
        norm=jnp.where(ok, norms[:, sel], 0).astype(jnp.int32),
        topk_node=pos,
        topk_total=tot,
        topk_rank=rnk,
        feasible=feasible.sum().astype(jnp.int32),
        block=jnp.int32(-1),
    )


def block_reduce(tot: jnp.ndarray, rank: jnp.ndarray):
    """Per-block (max total, min tie-break rank among the maxima, argmax)
    over the trailing axis — the in-block half of the blocked two-level
    selectHost, shared by the single-device blocked table engine and the
    shard_map engine's blocked local select so the combine cannot drift
    between them. `tot` uses -INT_MAX as the infeasible/empty sentinel;
    rows whose max stays at the sentinel are discarded by the global
    combine's validity gate, so their (rank, argmax) outputs are
    don't-cares. `rank` broadcasts against `tot`."""
    m = tot.max(-1)
    wkey = jnp.where(tot == m[..., None], -rank, -_INT_MAX)
    a = jnp.argmax(wkey, -1).astype(jnp.int32)
    r = jnp.take_along_axis(
        jnp.broadcast_to(rank, tot.shape), a[..., None], -1
    )[..., 0]
    return m, r, a


def bind_selected(
    state: NodeState,
    pod: PodSpec,
    node: jnp.ndarray,  # i32 chosen node index in [0, N) (ignored when ~ok)
    ok: jnp.ndarray,  # bool — selection succeeded
    policy_dev_scalar: jnp.ndarray,  # i32 policy device pick at `node`
    gpu_sel: str,
    key,
) -> Tuple[NodeState, Placement]:
    """Reserve + Bind for an already-selected node — the post-selectHost
    half of the cycle, shared by every engine so the scatter semantics
    cannot diverge."""
    # Reserve: concrete device allocation on the chosen node.
    dev_mask = choose_devices(state.gpu_left[node], pod, policy_dev_scalar, gpu_sel, key)
    dev_mask = dev_mask & ok

    # Bind: scatter-commit the placement.
    cls = pod_affinity_class(pod)
    new_state = state._replace(
        cpu_left=state.cpu_left.at[node].add(jnp.where(ok, -pod.cpu, 0)),
        mem_left=state.mem_left.at[node].add(jnp.where(ok, -pod.mem, 0)),
        gpu_left=state.gpu_left.at[node].add(
            -dev_mask.astype(jnp.int32) * pod.gpu_milli
        ),
        aff_cnt=state.aff_cnt.at[node, jnp.maximum(cls, 0)].add(
            jnp.where(ok & (cls >= 0), 1, 0)
        ),
    )
    return new_state, Placement(jnp.where(ok, node, -1).astype(jnp.int32), dev_mask)


class PendingCommit(NamedTuple):
    """One event's deferred effects, applied at the START of the next scan
    iteration (or in the post-scan epilogue for the last event).

    The table engines software-pipeline every carried-buffer write by one
    event: within a scan body, a buffer read scheduled before a write to
    the same buffer forces XLA to preserve the old value — a whole-buffer
    copy per event (at 100k nodes the state copies alone cost more than
    the actual per-event compute on the CPU backend). Deferring the commit
    makes every body strictly write-then-read: apply the previous event's
    scatters first, then read state/tables freely. Bit-identical by
    construction — the same scatters land before anything reads them.

    node == -1 encodes a no-op state commit (failed create / skip / the
    pre-first-event initial value). pod_write is the bookkeeping row index
    (the P-th dummy row for skip events); failed_write is the row for the
    ever-failed flag (dummy unless the event was a creation attempt)."""

    node: jnp.ndarray  # i32 touched node, -1 = none
    dev_mask: jnp.ndarray  # bool[8]
    rs: jnp.ndarray  # i32 +1 delete (returns resources), -1 create
    cpu: jnp.ndarray  # i32 pod milli-CPU
    mem: jnp.ndarray  # i32 pod MiB
    gpu_milli: jnp.ndarray  # i32 pod per-GPU milli
    cls: jnp.ndarray  # i32 affinity class (-1 none)
    pod_write: jnp.ndarray  # i32 row for placed/masks ([P] = dummy)
    placed_val: jnp.ndarray  # i32 value for placed[pod_write]
    mask_val: jnp.ndarray  # bool[8] value for masks[pod_write]
    failed_write: jnp.ndarray  # i32 row for failed ([P] = dummy)
    failed_val: jnp.ndarray  # bool


def no_pending_commit(num_pods: int) -> "PendingCommit":
    """The inert pre-first-event PendingCommit (all writes hit dummies)."""
    z = jnp.int32(0)
    return PendingCommit(
        node=jnp.int32(-1),
        dev_mask=jnp.zeros(MAX_GPUS_PER_NODE, jnp.bool_),
        rs=jnp.int32(-1), cpu=z, mem=z, gpu_milli=z, cls=jnp.int32(-1),
        pod_write=jnp.int32(num_pods), placed_val=jnp.int32(-1),
        mask_val=jnp.zeros(MAX_GPUS_PER_NODE, jnp.bool_),
        failed_write=jnp.int32(num_pods), failed_val=jnp.bool_(False),
    )


def make_pending_commit(
    kind: jnp.ndarray,  # i32 clipped event kind: 0 create, 1 delete, 2 skip
    idx: jnp.ndarray,  # i32 pod index of the event
    node: jnp.ndarray,  # i32 touched node (-1 = none: failed create / skip)
    dev_mask: jnp.ndarray,  # bool[8] devices touched
    pod: PodSpec,
    num_pods: int,
) -> "PendingCommit":
    """Encode one event's effects for the next iteration's apply_commit.

    Semantics match the former in-branch commits exactly: a successful
    create consumes (node, dev_mask); a delete returns the recorded
    resources (node/dev_mask are the freed placement); failed creates and
    skips are state-inert via node == -1; placed/masks are written for
    create (the placement / -1 on failure) and delete (-1/False) but not
    skip; the ever-failed flag is only written by creation attempts
    (simulator.go:444-455)."""
    is_create = kind == 0
    is_skip = kind == 2
    return PendingCommit(
        node=node,
        dev_mask=dev_mask,
        rs=jnp.where(kind == 1, 1, -1),  # delete returns, create consumes
        cpu=pod.cpu, mem=pod.mem, gpu_milli=pod.gpu_milli,
        cls=pod_affinity_class(pod),
        pod_write=jnp.where(is_skip, num_pods, idx).astype(jnp.int32),
        placed_val=jnp.where(is_create, node, -1).astype(jnp.int32),
        mask_val=jnp.where(is_create, dev_mask, False),
        failed_write=jnp.where(is_create, idx, num_pods).astype(jnp.int32),
        failed_val=node < 0,
    )


def apply_commit(state: NodeState, placed, masks, failed, p: "PendingCommit"):
    """Apply a PendingCommit's scatters — the write-only half of the
    pipelined event loop. placed/masks/failed carry one extra dummy row
    ([P]) that absorbs skip-event writes. The global view of
    apply_commit_sharded (offset 0, the full node window), so the commit
    arithmetic exists exactly once."""
    return apply_commit_sharded(
        state, placed, masks, failed, p, jnp.int32(0), state.num_nodes
    )


def apply_commit_sharded(state: NodeState, placed, masks, failed,
                         p: "PendingCommit", offset, nloc: int):
    """apply_commit for a node-axis-sharded carry (the shard_map engine's
    software pipeline, ISSUE 11): `p.node` is a GLOBAL node id, so each
    shard lands the state scatters owner-masked on its local row window
    (`offset` = this shard's first global id, `nloc` rows) while the
    [P+1] bookkeeping writes — replicated by construction — apply
    identically on every shard. Strictly write-only on every touched
    buffer, like apply_commit, so the scatters alias in place under scan.
    With offset == 0 and nloc == N this IS apply_commit on a global view
    (the shard engine's finish epilogue uses apply_commit directly)."""
    li = p.node - offset
    owns = (p.node >= 0) & (li >= 0) & (li < nloc)
    sel = jnp.clip(li, 0, nloc - 1)
    state = state._replace(
        cpu_left=state.cpu_left.at[sel].add(jnp.where(owns, p.rs * p.cpu, 0)),
        mem_left=state.mem_left.at[sel].add(jnp.where(owns, p.rs * p.mem, 0)),
        gpu_left=state.gpu_left.at[sel].add(
            jnp.where(owns, p.rs, 0) * p.dev_mask.astype(jnp.int32)
            * p.gpu_milli
        ),
        aff_cnt=state.aff_cnt.at[sel, jnp.maximum(p.cls, 0)].add(
            jnp.where(owns & (p.cls >= 0), -p.rs, 0)
        ),
    )
    placed = placed.at[p.pod_write].set(p.placed_val)
    masks = masks.at[p.pod_write].set(p.mask_val)
    failed = failed.at[p.failed_write].set(p.failed_val)
    return state, placed, masks, failed


def select_and_bind(
    state: NodeState,
    pod: PodSpec,
    feasible: jnp.ndarray,  # bool[N]
    total: jnp.ndarray,  # i32[N] weighted scores
    policy_dev: jnp.ndarray,  # i32[N] per-node policy device pick (-1 none)
    gpu_sel: str,
    key,
    tiebreak_rank: jnp.ndarray,
) -> Tuple[NodeState, Placement]:
    """selectHost + Reserve + Bind for already-computed scores — the single
    source of truth shared by the sequential engine (schedule_one) and the
    incremental table engine, so the two stay bit-identical by construction.
    Composed from packed_argmax (selectHost) + bind_selected (Reserve/Bind)
    so the blocked table engine can reuse both halves around its
    block-summary reduction."""
    node, _, ok = packed_argmax(total, feasible, tiebreak_rank)
    return bind_selected(state, pod, node, ok, policy_dev[node], gpu_sel, key)


def score_pod_rows(
    state: NodeState,
    pod: PodSpec,
    k_rand,
    policies: Sequence[Tuple[object, int]],
    gpu_sel: str = "best",
    tp=None,
    weights=None,
):
    """score_pod with the per-policy breakdown kept: returns
    (feasible bool[N], total i32[N], policy_share_dev i32[N],
    raws i32[num_pol, N], norms i32[num_pol, N]) where `norms` are the
    normalized rows the weighted sum consumed (== raws for
    normalize-'none' policies). The decision flight recorder gathers the
    winner's columns out of raws/norms; callers that only need the total
    (score_pod) let XLA dead-code the stacks.

    `weights` is the traced i32[num_pol] weight operand (resolve_weights;
    None = the static config weights) — engines pass it through so one
    jaxpr serves every weight vector of a policy family."""
    n = state.num_nodes
    feasible = filter_nodes(state, pod)
    ctx = ScoreContext(tp=tp, feasible=feasible, rng=k_rand)
    wts = resolve_weights(policies, weights)

    total = jnp.zeros(n, jnp.int32)
    policy_share_dev = jnp.full(n, -1, jnp.int32)
    raws, norms = [], []
    for i, (fn, _) in enumerate(policies):
        res = fn(state, pod, ctx)
        raw = res.raw_scores
        if fn.normalize == "minmax":
            nrm = minmax_normalize_i32(raw, feasible)
        elif fn.normalize == "pwr":
            nrm = pwr_normalize_i32(raw, feasible)
        else:
            nrm = raw
        raws.append(raw)
        norms.append(nrm)
        total = total + wts[i] * nrm
        if gpu_sel == fn.policy_name and fn.policy_name in SELF_SELECT_POLICIES:
            policy_share_dev = res.share_dev
    return feasible, total, policy_share_dev, jnp.stack(raws), jnp.stack(norms)


def score_pod(
    state: NodeState,
    pod: PodSpec,
    k_rand,
    policies: Sequence[Tuple[object, int]],
    gpu_sel: str = "best",
    tp=None,
    weights=None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Filter + Score + Normalize for one pod — the pre-selection half of
    the cycle, shared by schedule_one and the extender host loop (which
    splices HTTP extender filter/prioritize results between this and
    select_and_bind, mirroring where the vendored generic_scheduler calls
    its extenders, generic_scheduler.go:143-210 + 520-560). Returns
    (feasible bool[N], total i32[N] weighted scores, policy_share_dev
    i32[N])."""
    feasible, total, policy_share_dev, _, _ = score_pod_rows(
        state, pod, k_rand, policies, gpu_sel, tp, weights
    )
    return feasible, total, policy_share_dev


def schedule_one(
    state: NodeState,
    pod: PodSpec,
    key,
    policies: Sequence[Tuple[object, int]],
    gpu_sel: str = "best",
    tp=None,
    tiebreak_rank=None,
    weights=None,
) -> Tuple[NodeState, Placement]:
    """Run one full scheduling cycle for `pod` and commit the binding.

    policies: [(policy_fn, weight)] — the enabled Score plugins with their
    config weights (policy selection in the reference = one plugin at weight
    1000, §5.6). tiebreak_rank: i32[N] fixed per-run permutation. This models
    the reference exactly: its vendored selectHost REPLACES upstream k8s's
    random reservoir sampling with "smallest lexicographic name among ties"
    (generic_scheduler.go:187-212, the rand.Intn branch is commented out),
    and node names carry a random 4-digit per-run prefix
    (simulator.go:584-588) — i.e. a fixed random permutation as tie-break
    order. A per-pod random draw instead costs ~2pt of FGD allocation ratio
    (spreads load across tied idle nodes instead of packing).
    """
    n = state.num_nodes
    k_rand, k_sel = jax.random.split(key)
    if tiebreak_rank is None:
        tiebreak_rank = jnp.arange(n, dtype=jnp.int32)
    feasible, total, policy_share_dev = score_pod(
        state, pod, k_rand, policies, gpu_sel, tp, weights
    )
    return select_and_bind(
        state, pod, feasible, total, policy_share_dev, gpu_sel, k_sel,
        tiebreak_rank,
    )


def schedule_one_recorded(
    state: NodeState,
    pod: PodSpec,
    key,
    policies: Sequence[Tuple[object, int]],
    gpu_sel: str = "best",
    tp=None,
    tiebreak_rank=None,
    weights=None,
):
    """schedule_one plus its DecisionRecord — identical trajectory (same
    key splits, same score/select/bind kernels in the same order; the
    extra gathers feed only the record), so a recording replay's
    placements are bit-identical to an unrecorded one. Returns
    (new_state, Placement, DecisionRecord)."""
    n = state.num_nodes
    k_rand, k_sel = jax.random.split(key)
    if tiebreak_rank is None:
        tiebreak_rank = jnp.arange(n, dtype=jnp.int32)
    feasible, total, policy_share_dev, raws, norms = score_pod_rows(
        state, pod, k_rand, policies, gpu_sel, tp, weights
    )
    new_state, placement = select_and_bind(
        state, pod, feasible, total, policy_share_dev, gpu_sel, k_sel,
        tiebreak_rank,
    )
    dec = build_decision(
        placement.node, raws, norms, total, feasible, tiebreak_rank
    )
    return new_state, placement, dec


def unschedule(state: NodeState, pod: PodSpec, placement: Placement) -> NodeState:
    """Evict a placed pod, returning resources to its recorded devices
    (ref: deletePod → cache removal + NodeResource.Add, simulator.go:334-357,
    resource.go:482-531)."""
    node = jnp.maximum(placement.node, 0)
    placed = placement.node >= 0
    cls = pod_affinity_class(pod)
    return state._replace(
        cpu_left=state.cpu_left.at[node].add(jnp.where(placed, pod.cpu, 0)),
        mem_left=state.mem_left.at[node].add(jnp.where(placed, pod.mem, 0)),
        gpu_left=state.gpu_left.at[node].add(
            jnp.where(placed, placement.dev_mask.astype(jnp.int32) * pod.gpu_milli, 0)
        ),
        aff_cnt=state.aff_cnt.at[node, jnp.maximum(cls, 0)].add(
            jnp.where(placed & (cls >= 0), -1, 0)
        ),
    )
