"""Simulator core: filter/score/bind step, trace replay engine, analysis
(ref: pkg/simulator/ + the vendored kube-scheduler event loop it drives)."""
