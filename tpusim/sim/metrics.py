"""Per-event metrics as a vectorized post-pass over replay telemetry.

The reference recomputes the full cluster frag/alloc/power report after
EVERY event (simulator.go:426-427, analysis.go:24-126) — its dominant cost.
Round 2-4 engines moved that into the replay scan (one touched-node metric
row refresh + a cluster reduce per scan step), which still serializes ~10
kernel launches per event and forced the fused Pallas engine to reject
reporting configs entirely.

This module removes per-event metric work from every engine: a replay runs
metric-free and emits only its placement telemetry — `event_node` i32[E]
(the node each event touched) and `event_dev` bool[E,8] — which all engines
already produce bit-identically (it IS the pinned equality contract). The
per-event metric series is then reconstructed from that telemetry in a few
large batched ops, with no sequential scan:

  1. per-event touched-node states via a segmented (per-node) cumulative
     sum over the event axis — integer arithmetic, exact;
  2. per-event touched-node frag/power rows via the SAME vmapped kernels
     (ops.frag.node_frag_amounts / ops.energy.node_power) the engines'
     in-scan report paths used, batched over all E events at once;
  3. cluster series as initial totals + a cumulative sum of per-event row
     deltas along the event axis.

Exactness: every integer series ([Alloc]/[AllocCPU] lines, arrived
counters) is exact — integer sums in any order. The f32 frag/power series
are deterministic but use a cumulative-delta order instead of the per-event
full re-sum the round-4 scan paths used, so their last ulps differ from
round 4 (drift ~1e-6 relative over a full trace; the analysis CSVs' merged
percent-scale values are unaffected). What matters is byte-identity ACROSS
engines, and that now holds by construction: identical telemetry in →
identical series out, for the sequential, table, fused-Pallas, and batched
paths alike. The sequential oracle keeps its in-scan report mode as a
cross-check (tests/test_metrics.py pins post-pass == in-scan exactly for
integers and to f32 tolerance for the float series).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

import jax
import jax.numpy as jnp

from tpusim.constants import MILLI
from tpusim.ops.energy import node_power
from tpusim.ops.frag import node_frag_amounts
from tpusim.sim.engine import (
    EV_CREATE,
    EV_DELETE,
    EventMetrics,
    cluster_usage,
    power_rows,
)
from tpusim.types import NodeState, PodSpec


def _segment_inclusive_cumsum(delta_s, head):
    """Inclusive cumulative sum of `delta_s` (leading axis) restarting at
    every True in `head` — the standard cumsum-minus-group-base trick, all
    parallel ops."""
    csum = jnp.cumsum(delta_s, axis=0)
    excl = csum - delta_s
    idx = jnp.arange(head.shape[0])
    head_idx = jax.lax.associative_scan(jnp.maximum, jnp.where(head, idx, 0))
    group_base = excl[head_idx]
    return csum - group_base


def _usage_contrib(cpu_left, gpu_left, cpu_cap, gpu_cnt):
    """One node's contribution to the [Alloc]/[AllocCPU] aggregates
    (cluster_usage semantics, analysis.go:91-99), for batched [E] states."""
    fully_free = (gpu_left == MILLI).sum(-1)
    used = (fully_free < gpu_cnt) | (cpu_left < cpu_cap)
    u = used.astype(jnp.int32)
    return (
        u,
        u * gpu_cnt,
        u * (gpu_cnt * MILLI - gpu_left.sum(-1)),
        u * (cpu_cap - cpu_left),
    )


_frag_rows = jax.vmap(node_frag_amounts, in_axes=(0, 0, 0, None))
_power_rows_b = jax.vmap(node_power)


@jax.jit
def compute_event_metrics(
    init_state: NodeState,
    specs: PodSpec,
    ev_kind: jnp.ndarray,  # i32[E]
    ev_pod: jnp.ndarray,  # i32[E]
    event_node: jnp.ndarray,  # i32[E] touched node (-1 = state untouched)
    event_dev: jnp.ndarray,  # bool[E, 8] touched devices
    tp,
) -> EventMetrics:
    """EventMetrics for a replayed event stream, from telemetry alone."""
    n = init_state.num_nodes
    pod = jax.tree.map(lambda a: a[ev_pod], specs)

    valid = event_node >= 0
    # resources the event TAKES from its node (negative take = release)
    sign = jnp.where(
        valid & (ev_kind == EV_CREATE),
        1,
        jnp.where(valid & (ev_kind == EV_DELETE), -1, 0),
    )
    taken_cpu = sign * pod.cpu  # i32[E]
    taken_gpu = sign[:, None] * event_dev.astype(jnp.int32) * pod.gpu_milli[:, None]

    # ---- group events by touched node (stable: intra-node event order kept)
    key = jnp.where(valid, event_node, n)
    order = jnp.argsort(key, stable=True)
    key_s = key[order]
    head = jnp.concatenate([jnp.ones(1, bool), key_s[1:] != key_s[:-1]])
    node_s = jnp.minimum(key_s, n - 1)  # clamped gather index (invalid rows
    # land in the trailing key==n group and are masked out of every delta)
    valid_s = key_s < n

    # ---- per-event post-state of the touched node (integer, exact)
    cum_cpu = _segment_inclusive_cumsum(taken_cpu[order], head)
    cum_gpu = _segment_inclusive_cumsum(taken_gpu[order], head)
    post_cpu_s = init_state.cpu_left[node_s] - cum_cpu
    post_gpu_s = init_state.gpu_left[node_s] - cum_gpu
    pre_cpu_s = post_cpu_s + taken_cpu[order]
    pre_gpu_s = post_gpu_s + taken_gpu[order]
    cap_s = init_state.cpu_cap[node_s]
    gcnt_s = init_state.gpu_cnt[node_s]
    gtyp_s = init_state.gpu_type[node_s]
    ctyp_s = init_state.cpu_type[node_s]

    def to_events(x_s):
        """Scatter a sorted-order series back to event order."""
        return jnp.zeros_like(x_s).at[order].set(x_s)

    # ---- frag series: init totals + cumsum of touched-row deltas
    init_rows = _frag_rows(
        init_state.cpu_left, init_state.gpu_left, init_state.gpu_type, tp
    )  # f32[N, 7]
    new_row_s = _frag_rows(post_cpu_s, post_gpu_s, gtyp_s, tp)  # f32[E, 7]
    prev_row_s = jnp.concatenate(
        [jnp.zeros((1, new_row_s.shape[1]), new_row_s.dtype), new_row_s[:-1]]
    )
    old_row_s = jnp.where(head[:, None], init_rows[node_s], prev_row_s)
    frag_delta = to_events(
        jnp.where(valid_s[:, None], new_row_s - old_row_s, 0.0)
    )
    frag_amounts = init_rows.sum(0)[None, :] + jnp.cumsum(frag_delta, axis=0)

    # ---- power series: same shape, (cpu_watts, gpu_watts) per node
    pc0, pg0 = power_rows(init_state)
    new_pw_s = jnp.stack(
        _power_rows_b(post_cpu_s, cap_s, post_gpu_s, gcnt_s, gtyp_s, ctyp_s),
        axis=-1,
    )  # f32[E, 2]
    init_pw = jnp.stack([pc0, pg0], axis=-1)  # f32[N, 2]
    prev_pw_s = jnp.concatenate(
        [jnp.zeros((1, 2), new_pw_s.dtype), new_pw_s[:-1]]
    )
    old_pw_s = jnp.where(head[:, None], init_pw[node_s], prev_pw_s)
    pw_delta = to_events(jnp.where(valid_s[:, None], new_pw_s - old_pw_s, 0.0))
    pw = init_pw.sum(0)[None, :] + jnp.cumsum(pw_delta, axis=0)

    # ---- usage series ([Alloc]/[AllocCPU]): integer deltas, exact
    init_usage = cluster_usage(init_state)
    post_c = _usage_contrib(post_cpu_s, post_gpu_s, cap_s, gcnt_s)
    pre_c = _usage_contrib(pre_cpu_s, pre_gpu_s, cap_s, gcnt_s)
    usage = [
        i + jnp.cumsum(to_events(jnp.where(valid_s, po - pr, 0)))
        for i, po, pr in zip(init_usage, post_c, pre_c)
    ]

    # ---- arrived counters: accumulate per creation event regardless of
    # outcome (simulator.go:406-408) — failed creations included
    is_create = ev_kind == EV_CREATE
    arr_cpu = jnp.cumsum(jnp.where(is_create, pod.cpu, 0))
    arr_gpu = jnp.cumsum(jnp.where(is_create, pod.total_gpu_milli(), 0))

    return EventMetrics(
        frag_amounts=frag_amounts,
        used_nodes=usage[0],
        used_gpus=usage[1],
        used_gpu_milli=usage[2],
        used_cpu_milli=usage[3],
        arrived_gpu_milli=arr_gpu,
        arrived_cpu_milli=arr_cpu,
        power_cpu=pw[:, 0],
        power_gpu=pw[:, 1],
    )


@dataclass
class DisruptionMetrics:
    """Fault-replay disruption accounting (ISSUE 2; filled by
    Simulator.schedule_pods_with_faults, reported by
    reports.disruption_report_block). The clock is the EVENT counter —
    trace positions, not wall time — so every number is bit-reproducible
    under a fixed fault seed; that reproducibility is itself a pinned
    acceptance criterion (tests/test_faults.py)."""

    node_failures: int = 0
    node_recoveries: int = 0
    evicted_pods: int = 0  # node-crash evictions + single-pod preemptions
    retries_enqueued: int = 0
    rescheduled_pods: int = 0  # evicted pods that found a home again
    unscheduled_after_retries: int = 0  # hit max_retries -> terminal
    # Σ gpu_count × events-down per failed node: "failed-node GPU-hours"
    # with the event counter as the clock
    failed_node_gpu_events: int = 0
    # per rescheduled pod: placement position - eviction position
    reschedule_latency_events: List[int] = field(default_factory=list)
    # per recovery: cluster frag (frag_sum_except_q3 of the amounts row)
    # right after the node returned minus right before — how much
    # fragmentation the re-added empty capacity exposes
    post_recovery_frag_delta: List[float] = field(default_factory=list)

    def mean_reschedule_latency(self) -> float:
        lat = self.reschedule_latency_events
        return float(sum(lat)) / len(lat) if lat else 0.0

    def as_dict(self) -> dict:
        """Scalar summary for the direct-CSV stash / log parsing."""
        return {
            "node_failures": self.node_failures,
            "node_recoveries": self.node_recoveries,
            "evicted_pods": self.evicted_pods,
            "retries_enqueued": self.retries_enqueued,
            "rescheduled_pods": self.rescheduled_pods,
            "unscheduled_after_retries": self.unscheduled_after_retries,
            "failed_node_gpu_events": self.failed_node_gpu_events,
            "mean_reschedule_latency_events": self.mean_reschedule_latency(),
            "max_reschedule_latency_events": (
                max(self.reschedule_latency_events)
                if self.reschedule_latency_events else 0
            ),
            "post_recovery_frag_delta_sum": float(
                sum(self.post_recovery_frag_delta)
            ),
        }
