"""Single-transfer device→host fetch.

On the axon TPU backend every device→host readback is a tunnel round-trip
with ~100 ms latency regardless of payload size, so fetching a replay
output leaf-by-leaf (np.asarray per array: ~20 transfers) dominates the
warm per-experiment wall clock. device_fetch() packs every device leaf of
a pytree into ONE uint8 buffer on device (bitcast, so f32/i32 bits survive
exactly) and reads it back in a single transfer, then reslices host-side.

The reference has no equivalent host/device boundary — its "transfer" is
the in-memory fake API server (SURVEY.md §5.8); this helper is the cost
model that boundary turns into on real accelerator hardware.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=None)
def _packer(sig):
    """Jitted byte-packer for a fixed (shape, dtype) leaf signature."""

    def pack(leaves):
        parts = []
        for x in leaves:
            if x.dtype == jnp.bool_:
                x = x.astype(jnp.uint8)
            if x.dtype != jnp.uint8:
                x = jax.lax.bitcast_convert_type(x, jnp.uint8)
            parts.append(x.reshape(-1))
        return jnp.concatenate(parts)

    return jax.jit(pack)


def device_fetch(tree):
    """Return `tree` with every jax.Array leaf replaced by a host numpy
    array, moving all of them in one device→host transfer. Non-array
    leaves (None, python scalars, numpy arrays) pass through untouched."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    idx = [i for i, l in enumerate(leaves) if isinstance(l, jax.Array)]
    if not idx:
        return tree
    dev = [leaves[i] for i in idx]
    sig = tuple((tuple(l.shape), str(l.dtype)) for l in dev)
    buf = np.asarray(_packer(sig)(dev))
    off = 0
    for i, l in zip(idx, dev):
        if l.dtype == jnp.bool_:
            dt, out_dt = np.dtype(np.uint8), None
        else:
            dt = out_dt = np.dtype(str(l.dtype))
        n = int(np.prod(l.shape, dtype=np.int64)) * dt.itemsize
        arr = buf[off : off + n].view(dt).reshape(l.shape)
        leaves[i] = arr.astype(bool) if out_dt is None else arr
        off += n
    return jax.tree_util.tree_unflatten(treedef, leaves)
