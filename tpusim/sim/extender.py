"""Scheduler extenders — the k8s HTTP extender protocol over the array state.

The reference passes configured extenders straight into the vendored
scheduler (simulator.go:196 `scheduler.WithExtenders(...)`), which speaks
the extenderv1 HTTP contract (vendored core/extender.go): per scheduling
cycle, each extender's `filterVerb` receives ExtenderArgs{Pod, Nodes |
NodeNames} and returns a node subset, then `prioritizeVerb` returns a
HostPriorityList whose weighted scores, scaled by MaxNodeScore /
MaxExtenderPriority (100/10), are ADDED to the plugin score sum before
selectHost (generic_scheduler.go:520-560).

This build reproduces that contract with a host-driven event loop: the
Filter/Score half of the cycle runs as the same jitted kernel every engine
uses (sim.step.score_pod), the extender HTTP round-trips splice between it
and the jitted select_and_bind, and deletions run the jitted unschedule.
Semantics mirrored from the vendored code:

  - interest gate: an extender with managedResources is only consulted for
    pods requesting one of them (IsInterested); an empty list means every
    pod. GPU requests are surfaced as the openb annotation resource name
    (alibabacloud.com/gpu-milli).
  - filter: missing filterVerb passes all nodes through; a returned name
    not in the input is an error; FailedNodes are simply absent from the
    subset; a transport/Error failure fails the CYCLE (pod unschedulable)
    unless the extender is `ignorable` (findNodesThatPassExtenders).
    DEVIATION: the membership check applies to BOTH payload shapes here,
    while the vendored scheduler only enforces it on the nodeCacheCapable
    NodeNames path — for non-nodeCacheCapable extenders it accepts the
    returned Nodes items verbatim (extender.go:331-335), trusting the
    extender to echo real node objects. This build's nodes are rows of a
    fixed array, so an out-of-set name cannot be scheduled onto and
    raising ExtenderError (or skipping, if ignorable) is the closest
    array-state behavior; a verbatim-echo extender that renames nodes
    would proceed upstream but fail the cycle here.
  - prioritize: errors are IGNORED (the vendored goroutine drops them);
    combinedScores[host] += score × weight; the sum joins the plugin total
    as combined × (MaxNodeScore / MaxExtenderPriority).
  - nodeCacheCapable: NodeNames-only payloads both ways.
  - bindVerb / preemptVerb are rejected at config parse: binding is an
    array scatter here, not a delegable side effect (config.scheduler).

A per-event HTTP + device round-trip is inherently serial, so this path is
for correctness/integration (the reference ships no extender experiment);
run_events dispatches to it whenever extenders are configured.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpusim.constants import MAX_GPUS_PER_NODE, MAX_NODE_SCORE

# extenderv1.MaxExtenderPriority (vendored extender/v1/types.go)
MAX_EXTENDER_PRIORITY = 10

ANNO_GPU_MILLI = "alibabacloud.com/gpu-milli"
ANNO_GPU_COUNT = "alibabacloud.com/gpu-count"
ANNO_GPU_MODEL = "alibabacloud.com/gpu-card-model"


@dataclass(frozen=True)
class ExtenderConfig:
    """One `extenders:` entry of KubeSchedulerConfiguration (the v1beta1
    Extender fields this build supports; apis/config/types.go:109)."""

    url_prefix: str
    filter_verb: str = ""
    prioritize_verb: str = ""
    weight: int = 1
    node_cache_capable: bool = False
    ignorable: bool = False
    # resource names from managedResources[].name; empty = all pods
    managed_resources: Tuple[str, ...] = ()
    http_timeout_s: float = 30.0

    def is_interested(self, pod) -> bool:
        """IsInterested (core/extender.go): no managed resources = every
        pod; otherwise the pod must request one of them."""
        if not self.managed_resources:
            return True
        requested = set()
        if pod.cpu_milli > 0:
            requested.add("cpu")
        if pod.memory_mib > 0:
            requested.add("memory")
        if pod.num_gpu > 0 or pod.gpu_milli > 0:
            requested.add(ANNO_GPU_MILLI)
            requested.add(ANNO_GPU_COUNT)
        return bool(requested & set(self.managed_resources))


class ExtenderError(RuntimeError):
    pass


def _pod_json(pod) -> dict:
    """v1.Pod-shaped payload for one trace pod (the openb annotation
    contract the reference's pods carry, open-gpu-share/utils/const.go)."""
    annotations = {}
    if pod.gpu_milli or pod.num_gpu:
        annotations[ANNO_GPU_MILLI] = str(pod.gpu_milli)
        annotations[ANNO_GPU_COUNT] = str(pod.num_gpu)
    if pod.gpu_spec:
        annotations[ANNO_GPU_MODEL] = pod.gpu_spec
    return {
        "metadata": {"name": pod.name, "annotations": annotations},
        "spec": {
            "containers": [
                {
                    "name": "app",
                    "resources": {
                        "requests": {
                            "cpu": f"{pod.cpu_milli}m",
                            "memory": f"{pod.memory_mib}Mi",
                        }
                    },
                }
            ]
        },
    }


def _node_json(node) -> dict:
    labels = {}
    if node.model:
        labels[ANNO_GPU_MODEL] = node.model
    return {
        "metadata": {"name": node.name, "labels": labels},
        "status": {
            "allocatable": {
                "cpu": f"{node.cpu_milli}m",
                "memory": f"{node.memory_mib}Mi",
                ANNO_GPU_COUNT: str(node.gpu),
            }
        },
    }


def _post(url: str, payload: dict, timeout: float) -> dict:
    """POST one extender verb on the SHARED kube_client retry schedule
    (ISSUE 14 satellite — this was the last bare-timeout HTTP call in
    the tree): connection-level failures (retryable_conn_excs) and
    429/5xx answers retry under capped-exponential-backoff-with-jitter
    honoring Retry-After, with the TPUSIM_HTTP_RETRIES attempt budget
    the rest client uses. After the schedule is exhausted the last
    error surfaces unchanged, so the callers' ExtenderError wrapping
    (and the `ignorable` policy) behave exactly as before."""
    from tpusim.io.kube_client import _retry_attempts, with_backoff

    data = json.dumps(payload).encode()

    def call():
        req = urllib.request.Request(
            url,
            data=data,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, dict(resp.headers), resp.read()
        except urllib.error.HTTPError as e:
            # carry the error object through the schedule: retryable
            # statuses re-attempt; anything else re-raises below with
            # the original traceback semantics
            return e.code, dict(e.headers or {}), e

    code, _, body = with_backoff(call, max_attempts=_retry_attempts())
    if isinstance(body, Exception):
        raise body
    return json.loads(body.decode())


class ExtenderClient:
    """Filter/Prioritize round-trips for one configured extender."""

    def __init__(self, cfg: ExtenderConfig):
        self.cfg = cfg

    def _args(self, pod, nodes) -> dict:
        args = {"pod": _pod_json(pod)}
        if self.cfg.node_cache_capable:
            args["nodenames"] = [n.name for n in nodes]
        else:
            args["nodes"] = {"items": [_node_json(n) for n in nodes]}
        return args

    def filter(self, pod, nodes) -> List[str]:
        """Surviving node names (subset of input). Raises ExtenderError on
        transport failure or a result carrying Error/unknown names —
        the caller applies the `ignorable` policy."""
        if not self.cfg.filter_verb:
            return [n.name for n in nodes]
        url = f"{self.cfg.url_prefix.rstrip('/')}/{self.cfg.filter_verb}"
        try:
            result = _post(url, self._args(pod, nodes), self.cfg.http_timeout_s)
        except (urllib.error.URLError, OSError, json.JSONDecodeError) as e:
            raise ExtenderError(f"extender {url} filter failed: {e}") from e
        if result.get("error"):
            raise ExtenderError(
                f"extender {url} returned error: {result['error']}"
            )
        known = {n.name for n in nodes}
        if self.cfg.node_cache_capable and result.get("nodenames") is not None:
            names = list(result["nodenames"])
        elif result.get("nodes") is not None:
            names = [
                item["metadata"]["name"]
                for item in result["nodes"].get("items") or []
            ]
        else:
            names = [n.name for n in nodes]
        for name in names:
            if name not in known:
                raise ExtenderError(
                    f"extender {url} claims a filtered node {name!r} not in "
                    "the input node list"
                )
        return names

    def prioritize(self, pod, nodes) -> Optional[dict]:
        """{node name: extender score} or None on error (the vendored
        scheduler ignores prioritize errors, generic_scheduler.go:536)."""
        if not self.cfg.prioritize_verb:
            return {}
        url = f"{self.cfg.url_prefix.rstrip('/')}/{self.cfg.prioritize_verb}"
        try:
            result = _post(url, self._args(pod, nodes), self.cfg.http_timeout_s)
            return {
                item["host"]: int(item["score"]) for item in (result or [])
            }
        except (urllib.error.URLError, OSError, json.JSONDecodeError,
                KeyError, TypeError, ValueError):
            return None


def extend_cycle(
    clients: Sequence[ExtenderClient],
    pod_row,
    node_rows,
    feasible: np.ndarray,  # bool[N] plugin-filter survivors
    total: np.ndarray,  # i32[N] weighted plugin scores
) -> Tuple[np.ndarray, np.ndarray, bool]:
    """Splice the extender protocol into one scheduling cycle: filter each
    interested extender sequentially over the surviving set, then add the
    weighted prioritize sum. Returns (feasible, total, ok) — ok=False means
    a non-ignorable extender failed and the cycle must fail the pod."""
    name_to_idx = {n.name: i for i, n in enumerate(node_rows)}
    feasible = np.asarray(feasible).copy()
    total = np.asarray(total).copy()
    interested = [c for c in clients if c.cfg.is_interested(pod_row)]

    # filter phase: sequential subsetting (findNodesThatPassExtenders)
    for c in interested:
        if not c.cfg.filter_verb:
            continue
        nodes = [node_rows[i] for i in np.flatnonzero(feasible)]
        if not nodes:
            break
        try:
            survivors = c.filter(pod_row, nodes)
        except ExtenderError:
            if c.cfg.ignorable:
                continue
            return feasible, total, False
        keep = np.zeros_like(feasible)
        for name in survivors:
            keep[name_to_idx[name]] = True
        feasible &= keep

    # prioritize phase: combinedScores scaled into the plugin range
    # (generic_scheduler.go:555-557)
    combined = np.zeros(len(node_rows), np.int64)
    nodes = [node_rows[i] for i in np.flatnonzero(feasible)]
    if nodes:
        for c in interested:
            scores = c.prioritize(pod_row, nodes)
            if not scores:
                continue
            for name, score in scores.items():
                idx = name_to_idx.get(name)
                if idx is not None:
                    combined[idx] += score * c.cfg.weight
    total = total + (
        combined * (MAX_NODE_SCORE // MAX_EXTENDER_PRIORITY)
    ).astype(np.int32)
    return feasible, total, True


def make_extender_replay(policies, gpu_sel, extenders: Sequence[ExtenderConfig]):
    """Host-driven replay honoring configured extenders. Same call shape as
    the other engines minus the types table:
    replay(state, specs, ev_kind, ev_pod, tp, key, rank, pod_rows,
    node_rows) -> ReplayResult. Placements with NO extender interference
    are bit-identical to the sequential engine (same kernels, same key
    discipline); extender filter/prioritize splice between score_pod and
    select_and_bind exactly where the vendored scheduler calls them."""
    from tpusim.sim.engine import EV_CREATE, EV_DELETE, ReplayResult
    from tpusim.sim.step import (
        Placement,
        score_pod,
        select_and_bind,
        unschedule,
    )

    clients = [ExtenderClient(c) for c in extenders]

    @jax.jit
    def _score(state, pod, k_rand):
        return score_pod(state, pod, k_rand, policies, gpu_sel, None)

    @jax.jit
    def _score_tp(state, pod, k_rand, tp):
        return score_pod(state, pod, k_rand, policies, gpu_sel, tp)

    @jax.jit
    def _bind(state, pod, feasible, total, sdev, k_sel, rank):
        return select_and_bind(
            state, pod, feasible, total, sdev, gpu_sel, k_sel, rank
        )

    @jax.jit
    def _unbind(state, pod, node, mask):
        return unschedule(state, pod, Placement(node, mask))

    def replay(state, specs, ev_kind, ev_pod, tp, key, rank, pod_rows,
               node_rows) -> ReplayResult:
        num_pods = int(specs.cpu.shape[0])
        placed = np.full(num_pods, -1, np.int32)
        masks = np.zeros((num_pods, MAX_GPUS_PER_NODE), bool)
        failed = np.zeros(num_pods, bool)
        ev_kind = np.asarray(ev_kind)
        ev_pod = np.asarray(ev_pod)
        e = len(ev_kind)
        event_node = np.full(e, -1, np.int32)
        event_dev = np.zeros((e, MAX_GPUS_PER_NODE), bool)
        if rank is None:
            rank = jnp.arange(state.num_nodes, dtype=jnp.int32)

        for i in range(e):
            kind, idx = int(ev_kind[i]), int(ev_pod[i])
            pod = jax.tree.map(lambda a: a[idx], specs)
            # the sequential oracle's per-event key discipline
            key, sub = jax.random.split(key)
            k_rand, k_sel = jax.random.split(sub)
            if kind == EV_CREATE:
                feasible, total, sdev = (
                    _score_tp(state, pod, k_rand, tp)
                    if tp is not None
                    else _score(state, pod, k_rand)
                )
                feasible_h, total_h, ok = extend_cycle(
                    clients, pod_rows[idx], node_rows,
                    np.asarray(feasible), np.asarray(total),
                )
                if not ok:
                    failed[idx] = True
                    continue
                state, pl = _bind(
                    state, pod, jnp.asarray(feasible_h),
                    jnp.asarray(total_h), sdev, k_sel, rank,
                )
                node = int(pl.node)
                placed[idx] = node
                masks[idx] = np.asarray(pl.dev_mask)
                failed[idx] = node < 0
                event_node[i] = node
                event_dev[i] = masks[idx]
            elif kind == EV_DELETE:
                node, mask = placed[idx], masks[idx]
                state = _unbind(
                    state, pod, jnp.int32(node), jnp.asarray(mask)
                )
                event_node[i] = node
                event_dev[i] = mask
                placed[idx] = -1
                masks[idx] = False

        return ReplayResult(
            state,
            jnp.asarray(placed),
            jnp.asarray(masks),
            jnp.asarray(failed),
            None,
            jnp.asarray(event_node),
            jnp.asarray(event_dev),
        )

    return replay
