"""Failure injection: node crash/recover and pod eviction as simulable
events (ISSUE 2; the Tesserae / Gavel line in PAPERS.md treats preemption
and node churn as first-class scheduler inputs — this module gives the
replay the same vocabulary).

Fault events are HOST-LEVEL: a node failure evicts every pod on the node
at once, which breaks the one-node-one-pod-per-event invariant the
compiled engines are built on. The driver therefore splits the base trace
at fault positions, replays each segment on the normal compiled engines
(run_events — so fault runs inherit checkpoint/resume and engine
selection unchanged), and applies the fault transitions between segments
(Simulator.schedule_pods_with_faults).

Schedules are either explicit FaultEvent lists (the "trace column" mode —
callers build them from real incident logs) or generated MTBF-style from
a seeded generator (generate_fault_schedule): geometric inter-failure and
repair gaps measured in EVENTS, not wall time, so a fixed seed gives a
bit-reproducible schedule on any backend.

A DOWN node is encoded as mem_left == -1 — the same sentinel node-axis
padding rows carry (tpusim.parallel.pad_nodes; filter_nodes fails the mem
check for every request, pod.mem >= 0 always), so no engine needs a new
feasibility input. The rest of the row is reset to idle so a down node
never skews the used-capacity aggregates; the capacity it holds while
down is accounted separately (DisruptionMetrics.failed_node_gpu_events).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from tpusim.constants import MAX_GPUS_PER_NODE, MILLI
from tpusim.sim.engine import EV_EVICT, EV_NODE_FAIL, EV_NODE_RECOVER
from tpusim.types import NodeState

FAULT_KINDS = (EV_NODE_FAIL, EV_NODE_RECOVER, EV_EVICT)


@dataclass(frozen=True)
class FaultEvent:
    """One fault, anchored between two base-trace events.

    pos: the fault fires after `pos` base events have been replayed
    (clamped to the trace length; several faults may share a position and
    fire in list order). kind: EV_NODE_FAIL | EV_NODE_RECOVER | EV_EVICT.
    node: target node index (fail/recover). pod: target pod index for
    EV_EVICT; -1 picks a seeded-random placed pod at replay time."""

    pos: int
    kind: int
    node: int = -1
    pod: int = -1


@dataclass
class FaultConfig:
    """Knobs of the seeded MTBF-style schedule + the retry policy.

    mtbf_events / mttr_events: mean events between node failures / until a
    failed node returns (0 disables failures / makes them permanent).
    evict_every_events: mean events between single-pod evictions (0 = off).
    Backoff: an evicted pod re-enters the stream
    min(backoff_base * 2^(attempt-1), backoff_cap) events after its
    eviction; after max_retries CONSECUTIVE failed attempts it is terminal
    (UnscheduledPod, reason "max-retries-exceeded") — a successful
    reschedule resets the budget."""

    mtbf_events: float = 0.0
    mttr_events: float = 0.0
    evict_every_events: float = 0.0
    seed: int = 0
    max_retries: int = 3
    backoff_base: int = 8
    backoff_cap: int = 256
    # static capacity of the IN-SCAN retry queue (ISSUE 10;
    # fault_lane.resolve_capacity): 0 = auto (min(num_pods, 256)). The
    # host-loop RetryQueue is unbounded; on the scan lane an eviction
    # wave past this capacity goes terminal ("max-retries-exceeded")
    # instead of silently corrupting — size it at the worst simultaneous
    # outstanding-retry count the schedule can produce.
    queue_capacity: int = 0


def _geometric(rng: np.random.Generator, mean: float) -> int:
    """Integer gap >= 1 with the given mean (geometric — the discrete
    memoryless distribution, i.e. MTBF measured in events)."""
    p = min(1.0, 1.0 / max(mean, 1.0))
    return int(rng.geometric(p))


def generate_fault_schedule(
    num_nodes: int, num_events: int, cfg: FaultConfig
) -> List[FaultEvent]:
    """Seeded MTBF-style schedule over a num_events-long trace.

    A time walk draws geometric inter-failure gaps; each failure hits a
    uniformly-chosen currently-UP node and (when mttr_events > 0)
    schedules that node's recovery a geometric repair gap later. An
    independent walk emits single-pod evictions (pod chosen at replay
    time from the placed set, seeded by position). Deterministic for a
    fixed (cfg.seed, num_nodes, num_events) — the acceptance contract for
    reproducible disruption metrics."""
    rng = np.random.default_rng(cfg.seed)
    events: List[FaultEvent] = []
    if cfg.mtbf_events > 0 and num_nodes > 0:
        recover_at = {}  # node -> scheduled recovery position
        t = _geometric(rng, cfg.mtbf_events)
        while t < num_events:
            up = [
                i for i in range(num_nodes)
                if recover_at.get(i, -1) <= t
            ]
            if not up:
                t += _geometric(rng, cfg.mtbf_events)
                continue
            node = int(up[rng.integers(0, len(up))])
            events.append(FaultEvent(pos=t, kind=EV_NODE_FAIL, node=node))
            if cfg.mttr_events > 0:
                back = t + _geometric(rng, cfg.mttr_events)
                recover_at[node] = back
                if back < num_events:
                    events.append(
                        FaultEvent(pos=back, kind=EV_NODE_RECOVER, node=node)
                    )
            else:
                recover_at[node] = num_events + 1  # permanent loss
            t += _geometric(rng, cfg.mtbf_events)
    if cfg.evict_every_events > 0:
        t = _geometric(rng, cfg.evict_every_events)
        while t < num_events:
            events.append(FaultEvent(pos=t, kind=EV_EVICT))
            t += _geometric(rng, cfg.evict_every_events)
    events.sort(key=lambda e: e.pos)  # stable: same-pos order preserved
    return events


def is_down(state: NodeState) -> jnp.ndarray:
    """bool[N] — which nodes carry the down sentinel."""
    return state.mem_left < 0


def _reset_node(state: NodeState, node: int, mem_left) -> NodeState:
    """Reset one node's row to empty-at-capacity with the given mem_left —
    the shared core of fail/recover (only the mem sentinel differs)."""
    node = jnp.asarray(node, jnp.int32)
    gpu_full = (
        jnp.arange(MAX_GPUS_PER_NODE, dtype=jnp.int32) < state.gpu_cnt[node]
    ).astype(jnp.int32) * MILLI
    return state._replace(
        cpu_left=state.cpu_left.at[node].set(state.cpu_cap[node]),
        mem_left=state.mem_left.at[node].set(mem_left),
        gpu_left=state.gpu_left.at[node].set(gpu_full),
        aff_cnt=state.aff_cnt.at[node].set(0),
    )


def fail_node(state: NodeState, node: int) -> NodeState:
    """Crash one node: the row is reset wholesale to the DOWN encoding
    (mem_left -1 blocks every request; cpu/gpu read as idle so the dead
    node doesn't leak into the used-capacity aggregates). The caller owns
    evicting the node's pods into the retry queue — their resources do not
    need returning because the whole row is re-derived from capacity."""
    return _reset_node(state, node, -1)


def recover_node(state: NodeState, node: int) -> NodeState:
    """Bring a failed node back, EMPTY (a recovered host rejoins with no
    pods — its previous tenants are in the retry queue or already placed
    elsewhere)."""
    return _reset_node(state, node, state.mem_cap[jnp.asarray(node, jnp.int32)])


def pick_eviction_victim(
    placed: np.ndarray, pos: int, seed: int, explicit_pod: int = -1
) -> Optional[int]:
    """Victim of an EV_EVICT event: the explicit pod if it is currently
    placed, else a seeded-uniform draw over the placed set (seeded by
    schedule seed + position, so two runs of the same schedule evict the
    same pods). None when nothing is placed."""
    if explicit_pod >= 0:
        return explicit_pod if placed[explicit_pod] >= 0 else None
    candidates = np.flatnonzero(placed >= 0)
    if candidates.size == 0:
        return None
    rng = np.random.default_rng(np.uint64(seed) + np.uint64(pos) * 2654435761)
    return int(candidates[rng.integers(0, candidates.size)])


def validate_fault_schedule(
    faults: Sequence[FaultEvent], num_nodes: int, num_pods: int
) -> None:
    """Same fail-loudly contract as driver.validate_events, for the fault
    stream: bad targets must raise here, not become silent no-ops."""
    for i, ev in enumerate(faults):
        if ev.kind not in FAULT_KINDS:
            raise ValueError(
                f"fault {i}: kind {ev.kind} is not EV_NODE_FAIL={EV_NODE_FAIL}"
                f" | EV_NODE_RECOVER={EV_NODE_RECOVER} | EV_EVICT={EV_EVICT}"
            )
        if ev.kind in (EV_NODE_FAIL, EV_NODE_RECOVER) and not (
            0 <= ev.node < num_nodes
        ):
            raise ValueError(
                f"fault {i}: node {ev.node} out of range for {num_nodes} nodes"
            )
        if ev.kind == EV_EVICT and ev.pod >= num_pods:
            raise ValueError(
                f"fault {i}: pod {ev.pod} out of range for {num_pods} pods"
            )
        if ev.pos < 0:
            raise ValueError(f"fault {i}: negative position {ev.pos}")
