"""Trace-replay engine: the event hot loop as one compiled lax.scan.

Replaces the reference's driver↔scheduler goroutine pair with its fake API
server and 2 ms spin-waits (simulator.go:377-433 SchedulePods,
:490-568 sync*): each creation event runs the full scheduling cycle
synchronously on device; each deletion event returns the pod's recorded
resources. The per-event ClusterGpuFragReport/ClusterPowerConsumptionReport
(simulator.go:426-427, analysis.go:24-126) — the reference's dominant cost —
becomes a vmapped array reduction emitted as scan outputs.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from tpusim.constants import MAX_GPUS_PER_NODE, MILLI
from tpusim.obs import series as obs_series
from tpusim.obs.counters import counter_delta, zero_counters
from tpusim.obs.decisions import no_decision
from tpusim.ops.energy import node_power
from tpusim.ops.frag import cluster_frag_amounts, frag_sum_except_q3, frag_sum_q1q2q4
from tpusim.policies import ScoreContext
from tpusim.sim.step import (
    Placement,
    filter_nodes,
    schedule_one,
    schedule_one_recorded,
    unschedule,
)
from tpusim.types import NodeState, PodSpec

EV_CREATE = 0
EV_DELETE = 1
EV_SKIP = 2  # padding / `simon/pod-unscheduled`-annotated pods (simulator.go:391-399)
# Fault-injection vocabulary (ISSUE 2; tpusim.sim.faults): host-level
# events the DRIVER replays between compiled segments — they touch many
# pods at once (a node failure evicts every pod on the node), which breaks
# the one-node-one-pod-per-event invariant the compiled engines are built
# on, so they must never enter run_events (validate_events rejects them).
EV_NODE_FAIL = 3  # node crashes; its pods are evicted into the retry queue
EV_NODE_RECOVER = 4  # node returns, empty
EV_EVICT = 5  # single-pod eviction (preemption), pod re-enters via retry
# Since ISSUE 10 fault kinds ALSO run inside the compiled scan: engines
# built with faults=True accept merged streams carrying all seven kinds
# plus EV_RETRY slots (tpusim.sim.fault_lane), handling them with an
# in-carry retry queue — run_events (the fault-free dispatch) still
# rejects them, routing callers at Simulator.run_with_faults instead.
EV_RETRY = 6  # retry-queue slot: pops the earliest due evicted pod

_power_nodes = jax.vmap(node_power)


class EventMetrics(NamedTuple):
    """Per-event report rows (ref: analysis.go:59-126 [Report]/[Alloc] lines)."""

    frag_amounts: jnp.ndarray  # f32[E, 7]
    used_nodes: jnp.ndarray  # i32[E]
    used_gpus: jnp.ndarray  # i32[E]
    used_gpu_milli: jnp.ndarray  # i32[E]
    used_cpu_milli: jnp.ndarray  # i32[E]
    arrived_gpu_milli: jnp.ndarray  # i32[E]
    arrived_cpu_milli: jnp.ndarray  # i32[E]
    power_cpu: jnp.ndarray  # f32[E]
    power_gpu: jnp.ndarray  # f32[E]

    def frag_gpu_milli(self):
        return frag_sum_except_q3(self.frag_amounts)

    def idle_gpu_milli(self):
        return self.frag_amounts.sum(-1)

    def frag_ratio_pct(self):
        return 100.0 * self.frag_gpu_milli() / self.idle_gpu_milli()

    def q124_ratio_pct(self):
        return 100.0 * frag_sum_q1q2q4(self.frag_amounts) / self.idle_gpu_milli()


class ReplayResult(NamedTuple):
    state: NodeState
    placed_node: jnp.ndarray  # i32[P], -1 = unscheduled/not-arrived/deleted
    dev_mask: jnp.ndarray  # bool[P, 8]
    ever_failed: jnp.ndarray  # bool[P] creation attempted and rejected
    metrics: EventMetrics
    event_node: jnp.ndarray  # i32[E] node touched at each event (-1 none):
    # the chosen node for creations, the freed node for deletions
    event_dev: jnp.ndarray  # bool[E, 8] devices touched at each event
    # i32[obs.NUM_COUNTERS] exact in-scan counters (tpusim.obs.counters
    # vocabulary), carried through the scan so they survive chunking,
    # checkpoint/resume, and fault segmentation bit-identically. None on
    # engines whose loop does not count (fused pallas, extender) — the
    # driver derives the invariant prefix from telemetry there.
    counters: jnp.ndarray = None
    # tpusim.obs.decisions.DecisionRecord stacked over the event axis —
    # the per-event decision-provenance stream (ISSUE 4). None unless the
    # engine was built with decisions=True; engine-invariant on
    # decisions.INVARIANT_FIELDS and bit-reproducible like the counters.
    decisions: object = None
    # tpusim.obs.series.SeriesSample stacked over the event axis — the
    # in-scan cluster time-series plane (ISSUE 5): a real sample at every
    # series_every-th processed event, sentinel rows (pos == -1)
    # elsewhere. None unless the engine was built with series_every > 0;
    # fully engine-invariant and bit-reproducible like the counters.
    series: object = None
    # tpusim.sim.fault_lane.FaultY stacked over the merged event axis +
    # the final FaultCarry — the in-scan fault plane's telemetry
    # (ISSUE 10). None unless the engine was built with faults=True; the
    # driver assembles DisruptionMetrics / dead pods / creation ranks
    # from these host-side (fault_lane.assemble_disruption).
    fault_ys: object = None
    fault_carry: object = None


def cluster_usage(state: NodeState):
    """[Alloc]/[AllocCPU] aggregates (ref: analysis.go:91-99): a node is
    'used' if any GPU is non-idle or any CPU is taken; used GPUs count every
    device on a used node."""
    used = (state.fully_free_gpus() < state.gpu_cnt) | (
        state.cpu_left < state.cpu_cap
    )
    used_nodes = used.sum().astype(jnp.int32)
    used_gpus = jnp.where(used, state.gpu_cnt, 0).sum().astype(jnp.int32)
    used_gpu_milli = (
        jnp.where(used, state.gpu_cnt * MILLI - state.total_gpu_left(), 0)
        .sum()
        .astype(jnp.int32)
    )
    used_cpu_milli = (
        jnp.where(used, state.cpu_cap - state.cpu_left, 0).sum().astype(jnp.int32)
    )
    return used_nodes, used_gpus, used_gpu_milli, used_cpu_milli


def power_rows(state: NodeState):
    """(cpu_watts, gpu_watts) per node (ref: ClusterPowerConsumptionReport,
    analysis.go:24-56)."""
    return _power_nodes(
        state.cpu_left, state.cpu_cap, state.gpu_left, state.gpu_cnt,
        state.gpu_type, state.cpu_type,
    )


def assemble_metrics_row(amounts, state, arr_cpu, arr_gpu, power_cpu, power_gpu):
    """The EventMetrics row layout, single-sourced so every engine stays
    positionally aligned with the NamedTuple fields."""
    used_nodes, used_gpus, used_gpu_milli, used_cpu_milli = cluster_usage(state)
    return (
        amounts, used_nodes, used_gpus, used_gpu_milli, used_cpu_milli,
        arr_gpu, arr_cpu, power_cpu, power_gpu,
    )


def _metrics_row(state, tp, arr_cpu, arr_gpu):
    amounts = cluster_frag_amounts(state, tp).sum(0)
    pc, pg = power_rows(state)
    return assemble_metrics_row(amounts, state, arr_cpu, arr_gpu, pc.sum(), pg.sum())


_REPLAY_CACHE = {}
# heavy jitted machinery keyed WITHOUT weights: the weight vector is a
# traced operand (sim.step.resolve_weights), so every weight config of a
# policy family shares one jaxpr — a what-if weight change costs a device
# call, not a recompile (ISSUE 6). The cached engine is also the
# multi-trace sweep's sequential vmap target (ISSUE 7): pod specs and
# event streams batch per lane (tuned trace variants are data), so the
# replay service's sequential fallback shares it too.
_ENGINE_CACHE = {}


def make_replay(policies, gpu_sel: str = "best", report: bool = True,
                decisions: bool = False, series_every: int = 0,
                faults: bool = False, fault_frag: bool = False):
    """Build a jitted trace replayer for a static policy configuration.

    policies: [(policy_fn, weight)]; gpu_sel: Reserve-phase gpuSelMethod.
    report=False skips per-event metric rows (pure-throughput mode).
    decisions=True additionally emits the per-event DecisionRecord stream
    (tpusim.obs.decisions; ISSUE 4) as an extra scan output — the
    trajectory itself is untouched (same kernels, same key splits; the
    record is built from gathers on values the cycle already computed).
    series_every > 0 likewise adds the in-scan time-series plane
    (tpusim.obs.series; ISSUE 5): one SeriesSample per event, real at
    stride points, sentinel elsewhere. The sample consumes NO PRNG
    (RandomScore's slot is zeros) and reads the pre-event state, so the
    trajectory is untouched; it is a static build flag because the
    sampling cond bakes into the jaxpr.

    Replayers are cached per (policy kernels, gpu_sel, report, decisions,
    series_every) so that a sweep constructing many Simulators
    (experiments/sweep.py) reuses one compiled engine per configuration
    instead of re-jitting per experiment. Since ISSUE 6 the per-policy
    WEIGHTS are a traced i32[num_pol] operand, not part of the compiled
    jaxpr: the returned replayer accepts `weights=` (None = the static
    config weights, bit-identical to the former baked constants), and
    two replayers differing only in weights share the same underlying
    jitted engine (`replay.engine`) — the one-jaxpr-per-job-family
    contract the config-axis sweep vmaps over.
    """
    if faults and (decisions or series_every):
        raise ValueError(
            "the in-scan fault plane (faults=True) does not combine with "
            "decisions/series builds; run those through the segmented "
            "fault path (Simulator fault_mode='segments')"
        )
    cache_key = (tuple((fn, w) for fn, w in policies), gpu_sel, report,
                 decisions, int(series_every), bool(faults),
                 bool(fault_frag))
    if cache_key in _REPLAY_CACHE:
        return _REPLAY_CACHE[cache_key]
    engine_key = (tuple(fn for fn, _ in policies), gpu_sel, report,
                  decisions, int(series_every), bool(faults),
                  bool(fault_frag))
    engine = _ENGINE_CACHE.get(engine_key)
    if engine is None:
        engine = _make_sequential_engine(
            policies, gpu_sel, report, decisions, series_every, faults,
            fault_frag,
        )
        _ENGINE_CACHE[engine_key] = engine

    from tpusim.sim.step import resolve_weights

    def replay(state, pods, ev_kind, ev_pod, tp, key, tiebreak_rank=None,
               weights=None, fault_ops=None,
               fault_carry0=None) -> ReplayResult:
        if faults:
            return engine(
                state, pods, ev_kind, ev_pod, tp, key,
                resolve_weights(policies, weights), tiebreak_rank,
                fault_ops, fault_carry0,
            )
        return engine(
            state, pods, ev_kind, ev_pod, tp, key,
            resolve_weights(policies, weights), tiebreak_rank,
        )

    replay.engine = engine  # the weight-operand jitted impl (sweep vmaps it)
    _REPLAY_CACHE[cache_key] = replay
    return replay


def _make_sequential_engine(policies, gpu_sel, report, decisions,
                            series_every, faults=False, fault_frag=False):
    """The weight-operand jitted machinery behind make_replay: `weights`
    is an i32[num_pol] traced argument, never baked, so every weight
    vector of the (kernels, gpu_sel, flags) family runs one jaxpr. The
    closed-over `policies` weights are deliberately never read — only the
    kernel objects and their normalize/name metadata are."""
    num_pol = len(policies)
    if faults:
        if report:
            raise ValueError(
                "fault-plane replays run metric-free (the merged stream "
                "interleaves fault transitions the report postpass does "
                "not model); reconstruct reports via the segmented path"
            )
        return _make_sequential_fault_engine(policies, gpu_sel, fault_frag)

    @jax.jit
    def replay(
        state: NodeState,
        pods: PodSpec,  # [P] arrays
        ev_kind: jnp.ndarray,  # i32[E]
        ev_pod: jnp.ndarray,  # i32[E]
        tp,
        key,
        weights,  # i32[num_pol] traced weight operand
        tiebreak_rank=None,
    ) -> ReplayResult:
        num_pods = pods.cpu.shape[0]
        placed = jnp.full(num_pods, -1, jnp.int32)
        masks = jnp.zeros((num_pods, state.gpu_left.shape[1]), jnp.bool_)
        failed = jnp.zeros(num_pods, jnp.bool_)

        def body(carry, ev):
            state, placed, masks, failed, arr_cpu, arr_gpu, ctr, key = carry
            kind, idx = ev
            pod = jax.tree.map(lambda a: a[idx], pods)
            key, sub = jax.random.split(key)

            if series_every:
                # sample of the committed state BEFORE this event (every
                # engine agrees on it); consumes no PRNG — RandomScore's
                # slot stays zeros, matching its inert table row
                processed = ctr[0] + ctr[3] + ctr[4]

                def _build_sample():
                    n = state.num_nodes
                    unpinned = pod._replace(
                        pinned=jnp.full_like(pod.pinned, -1)
                    )
                    feas = filter_nodes(state, unpinned)
                    # raw rows exactly as the table build computes them:
                    # all-ones ctx feasibility, constant rng
                    ctx = ScoreContext(
                        tp=tp, feasible=jnp.ones(n, jnp.bool_),
                        rng=jax.random.PRNGKey(0),
                    )
                    raws = [
                        jnp.zeros(n, jnp.int32)
                        if fn.policy_name == "RandomScore"
                        else fn(state, pod, ctx).raw_scores
                        for fn, _ in policies
                    ]
                    return obs_series.build_sample(
                        state, tp, jnp.stack(raws), feas, policies,
                        processed,
                    )

                ser = obs_series.emit_from_scan(
                    series_every, processed, _build_sample, num_pol
                )
            else:
                ser = ()

            def do_create(_):
                # arrived counters accumulate per creation event regardless
                # of outcome (simulator.go:406-408).
                if decisions:
                    new_state, pl, dec = schedule_one_recorded(
                        state, pod, sub, policies, gpu_sel, tp,
                        tiebreak_rank, weights,
                    )
                else:
                    new_state, pl = schedule_one(
                        state, pod, sub, policies, gpu_sel, tp,
                        tiebreak_rank, weights,
                    )
                    dec = ()
                return (
                    new_state,
                    placed.at[idx].set(pl.node),
                    masks.at[idx].set(pl.dev_mask),
                    failed.at[idx].set(pl.node < 0),
                    arr_cpu + pod.cpu,
                    arr_gpu + pod.total_gpu_milli(),
                    pl.node,
                    pl.dev_mask,
                    dec if decisions else (),
                )

            def do_delete(_):
                pl = Placement(placed[idx], masks[idx])
                new_state = unschedule(state, pod, pl)
                return (
                    new_state,
                    placed.at[idx].set(-1),
                    masks.at[idx].set(False),
                    failed,
                    arr_cpu,
                    arr_gpu,
                    pl.node,
                    pl.dev_mask,
                    no_decision(num_pol) if decisions else (),
                )

            def do_skip(_):
                return (
                    state, placed, masks, failed, arr_cpu, arr_gpu,
                    jnp.int32(-1), jnp.zeros(MAX_GPUS_PER_NODE, jnp.bool_),
                    no_decision(num_pol) if decisions else (),
                )

            kc = jnp.clip(kind, 0, 2)
            (state2, placed2, masks2, failed2, arr_cpu2, arr_gpu2, node,
             dev, dec) = jax.lax.switch(
                kc, [do_create, do_delete, do_skip], None
            )
            # exact in-scan counters (obs vocabulary) — the same
            # counter_delta every engine adds, so counts cannot diverge
            ctr2 = ctr + counter_delta(kc, node)
            if report:
                row = _metrics_row(state2, tp, arr_cpu2, arr_gpu2)
            else:
                row = ()
            return (
                state2, placed2, masks2, failed2, arr_cpu2, arr_gpu2, ctr2,
                key,
            ), (
                row,
                node,
                dev,
                dec,
                ser,
            )

        init = (
            state, placed, masks, failed, jnp.int32(0), jnp.int32(0),
            zero_counters(), key,
        )
        (state, placed, masks, failed, _, _, ctr, _), (
            rows, nodes, devs, decs, sers
        ) = jax.lax.scan(body, init, (ev_kind, ev_pod))
        metrics = EventMetrics(*rows) if report else None
        return ReplayResult(
            state, placed, masks, failed, metrics, nodes, devs, ctr,
            decs if decisions else None,
            sers if series_every else None,
        )

    return replay


def _make_sequential_fault_engine(policies, gpu_sel, fault_frag):
    """Fault-plane sequential engine (ISSUE 10): the oracle's scan over a
    MERGED stream (base events + fault transitions + retry slots,
    tpusim.sim.fault_lane.compile_fault_plan) with the retry queue as
    carry state. Base kinds replay through the identical schedule_one
    cycle (one key split per merged step); fault kinds apply as masked
    one-node row ops after the switch; retry slots pop the earliest due
    evicted pod and run it through the same create branch. The engine is
    the chaos sweep's vmap target — every stream/draw/param is a traced
    operand, and the initial FaultCarry arrives as an input so its
    static queue capacity is just an input shape."""
    from tpusim.sim import fault_lane

    @jax.jit
    def replay(
        state: NodeState,
        pods: PodSpec,
        ev_kind: jnp.ndarray,  # i32[E_m] merged stream kinds
        ev_pod: jnp.ndarray,  # i32[E_m] base pod index per step
        tp,
        key,
        weights,
        tiebreak_rank=None,
        fault_ops: "fault_lane.FaultOps" = None,
        fault_carry0: "fault_lane.FaultCarry" = None,
    ) -> ReplayResult:
        num_pods = pods.cpu.shape[0]
        n = state.num_nodes
        node_ids = jnp.arange(n, dtype=jnp.int32)
        placed = jnp.full(num_pods, -1, jnp.int32)
        masks = jnp.zeros((num_pods, state.gpu_left.shape[1]), jnp.bool_)
        failed = jnp.zeros(num_pods, jnp.bool_)

        def body(carry, ev):
            state, placed, masks, failed, ctr, key, fc = carry
            kind, idx, pos, arg, aux = ev
            is_slot = kind == EV_RETRY
            fc, has, rpod = fault_lane.pop_retry(fc, is_slot, pos, arg)
            eff_idx = jnp.where(has, rpod, idx)
            kc = jnp.where(
                is_slot, jnp.where(has, 0, 2), jnp.clip(kind, 0, 2)
            )
            pod = jax.tree.map(lambda a: a[eff_idx], pods)
            key, sub = jax.random.split(key)

            def do_create(_):
                new_state, pl = schedule_one(
                    state, pod, sub, policies, gpu_sel, tp,
                    tiebreak_rank, weights,
                )
                newf = pl.node < 0
                return (
                    new_state,
                    placed.at[eff_idx].set(pl.node),
                    masks.at[eff_idx].set(pl.dev_mask),
                    # retry attempts accumulate ever-failed with OR — the
                    # segmented path's `ever_failed[created] |=` per
                    # segment; a base create still overwrites (it runs
                    # exactly once per pod)
                    failed.at[eff_idx].set(
                        jnp.where(is_slot, failed[eff_idx] | newf, newf)
                    ),
                    pl.node,
                    pl.dev_mask,
                )

            def do_delete(_):
                pl = Placement(placed[eff_idx], masks[eff_idx])
                new_state = unschedule(state, pod, pl)
                return (
                    new_state,
                    placed.at[eff_idx].set(-1),
                    masks.at[eff_idx].set(False),
                    failed,
                    pl.node,
                    pl.dev_mask,
                )

            def do_skip(_):
                return (
                    state, placed, masks, failed,
                    jnp.int32(-1), jnp.zeros(MAX_GPUS_PER_NODE, jnp.bool_),
                )

            (state2, placed2, masks2, failed2, node, dev) = jax.lax.switch(
                kc, [do_create, do_delete, do_skip], None
            )
            ctr2 = ctr + counter_delta(kc, node)
            # fault transitions: masked one-node ops, inert off-kind
            (state2, placed2, masks2, failed2, fc, ftouch, fy) = (
                fault_lane.apply_fault_step(
                    state2, placed2, masks2, failed2, fc, pods, kind,
                    arg, aux, pos, fault_ops, tp, node_ids, fault_frag,
                )
            )
            fc, lat, _ = fault_lane.commit_retry(
                fc, has, rpod, node, pos, arg, fault_ops.params
            )
            fy = fy._replace(
                rpod=jnp.where(has, rpod, -1).astype(jnp.int32), lat=lat
            )
            node_out = jnp.where(ftouch >= 0, ftouch, node)
            return (
                state2, placed2, masks2, failed2, ctr2, key, fc,
            ), (node_out, dev, fy)

        init = (state, placed, masks, failed, zero_counters(), key,
                fault_carry0)
        (state, placed, masks, failed, ctr, _, fc), (
            nodes, devs, fys
        ) = jax.lax.scan(body, init, (
            ev_kind, ev_pod, fault_ops.pos, fault_ops.arg, fault_ops.aux,
        ))
        return ReplayResult(
            state, placed, masks, failed, None, nodes, devs, ctr,
            None, None, fys, fc,
        )

    return replay
