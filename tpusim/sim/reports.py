"""analysis.py-compatible log emission (ref: §5.5 of SURVEY.md — the metric
contract is logrus Info lines parsed by scripts/analysis.py:120-260).

Formats reproduce pkg/simulator/analysis.go + pkg/utils/alloc.go exactly
(alloc keys use the parser-side names 'MilliCpu' etc. from
scripts/analysis.py ALLO_KEYS). Each emitted line carries a literal ``\\n``
escape before the closing quote, as logrus renders embedded newlines.
"""

from __future__ import annotations

import math
from typing import IO, List, Optional, Sequence

import numpy as np

from tpusim.constants import FRAG_CLASS_NAMES, Q3_SATISFIED

_ALLOC_KEYS = ("MilliCpu", "Memory", "Gpu", "MilliGpu")


class LogSink:
    """Collects logrus-text-format info lines (`level=info msg="..."`)."""

    def __init__(self, stream: Optional[IO] = None):
        self.lines: List[str] = []
        self.stream = stream

    def info(self, msg: str):
        line = f'time="2000-01-01T00:00:00Z" level=info msg="{msg}\\n"'
        self.lines.append(line)
        if self.stream is not None:
            self.stream.write(line + "\n")

    def info_many(self, msgs: Sequence[str]):
        """Bulk append of preformatted messages (the per-event report block
        builds its ~7 lines x |events| in vectorized numpy string ops; one
        write instead of per-line stream writes)."""
        if not msgs:
            return
        prefix = 'time="2000-01-01T00:00:00Z" level=info msg="'
        lines = [f'{prefix}{m}\\n"' for m in msgs]
        self.lines.extend(lines)
        if self.stream is not None:
            self.stream.write("\n".join(lines) + "\n")

    def infoln(self):
        line = 'time="2000-01-01T00:00:00Z" level=info'
        self.lines.append(line)
        if self.stream is not None:
            self.stream.write(line + "\n")

    def dump(self) -> str:
        return "\n".join(self.lines) + "\n"


def report_frag_line(log: LogSink, amounts: np.ndarray):
    """Per-event `[Report] ... (origin)` line (analysis.go:109)."""
    idle = float(amounts.sum())
    frag = idle - float(amounts[Q3_SATISFIED])
    q124 = float(amounts[0] + amounts[1] + amounts[3])
    fr = 100.0 * frag / idle if idle else 0.0
    qr = 100.0 * q124 / idle if idle else 0.0
    log.info(
        f"[Report]; Frag amount: {frag:.2f}; Frag ratio: {fr:.2f}%; "
        f"Q124 ratio: {qr:.2f}%; (origin)"
    )


def report_bellman_line(log: LogSink, bellman: float, idle: float):
    """`[Report] ... (bellman)` variant (analysis.go:110)."""
    r = 100.0 * bellman / idle if idle else 0.0
    log.info(f"[Report]; Frag amount: {bellman:.2f}; Frag ratio: {r:.2f}%; (bellman)")


def report_alloc_lines(
    log: LogSink,
    used_nodes: int,
    used_gpus: int,
    used_gpu_milli: int,
    total_gpus: int,
    arrived_gpu_milli: int,
    used_cpu_milli: int,
    arrived_cpu_milli: int,
):
    """Per-event `[Alloc]`/`[AllocCPU]` lines (analysis.go:115-118)."""
    log.info(
        f"[Alloc]; Used nodes: {used_nodes}; Used GPUs: {used_gpus}; "
        f"Used GPU Milli: {used_gpu_milli}; Total GPUs: {total_gpus}; "
        f"Arrived GPU Milli: {arrived_gpu_milli}"
    )
    log.info(
        f"[AllocCPU]; Used CPU Milli: {used_cpu_milli}; "
        f"Arrived CPU Milli: {arrived_cpu_milli}"
    )


def report_power_line(log: LogSink, power_cpu: float, power_gpu: float):
    """`[Power]` line (analysis.go:54-55)."""
    log.info(
        f"[Power]; cluster: {power_cpu + power_gpu:.1f}; "
        f"ClusterCPU: {power_cpu:.1f}; ClusterGPU: {power_gpu:.1f}"
    )


def pod_resource_repr(
    cpu_milli: int, gpu_num: int, gpu_milli: int, gpu_spec: str = "",
    cpu_spec: str = "",
) -> str:
    """PodResource.Repr (ref: pkg/type/resource.go:104-127): empty CPU type
    renders ANY; empty GPU type renders ANY for GPU pods, NONE otherwise."""
    cputype = cpu_spec or "ANY"
    gputype = gpu_spec or ("ANY" if gpu_milli > 0 else "NONE")
    return (
        f"<CPU: {cpu_milli / 1000:6.2f}, GPU: {gpu_num}"
        f" x {{{gpu_milli:<4d}}}m (CPUREQ: {cputype}) (GPUREQ: {gputype})>"
    )


def report_failed_pods(log: LogSink, pods) -> None:
    """`Failed Pods in detail:` block (ref: utils.ReportFailedPods,
    pkg/utils/utils.go:1344-1354, called from core.go:156 after RunCluster).
    `pods` is a sequence of PodRow-likes with name/cpu_milli/num_gpu/
    gpu_milli/gpu_spec."""
    if not pods:
        return
    log.info("Failed Pods in detail:")
    for p in pods:
        log.info(
            f"  {p.name}: "
            + pod_resource_repr(p.cpu_milli, p.num_gpu, p.gpu_milli, p.gpu_spec)
        )
    log.infoln()


def batch_event_report_msgs(
    amounts: np.ndarray,  # f32[E, 7]
    total_gpus: int,
    used_nodes: np.ndarray,
    used_gpus: np.ndarray,
    used_gpu_milli: np.ndarray,
    arrived_gpu_milli: np.ndarray,
    used_cpu_milli: np.ndarray,
    arrived_cpu_milli: np.ndarray,
    power_cpu: np.ndarray,
    power_gpu: np.ndarray,
    bellman: Optional[np.ndarray] = None,  # f64[E]
    kinds: Optional[np.ndarray] = None,  # event kind per event
    ev_create: int = 0,
    ev_delete: int = 1,
    pod_names: Optional[np.ndarray] = None,  # str[E] name of event's pod
    failed: Optional[np.ndarray] = None,  # bool[E] creation was rejected
) -> List[str]:
    """The whole per-event report block, vectorized: every line family is
    formatted as one numpy string op over the event axis, then interleaved
    into per-event order (attempt → rollback → frag → bellman → alloc →
    alloccpu → power; simulator.go:410-427, analysis.go:109-118). Skip
    events (pod-unscheduled annotation) emit nothing (simulator.go:391-399).

    Intermediate sums/ratios reproduce the scalar emitters' float32-sum →
    float64-divide sequencing exactly, so printed values are bit-identical
    to the per-event path this replaces.
    """
    e_count = amounts.shape[0]
    if e_count == 0:
        return []
    active = (
        np.ones(e_count, bool)
        if kinds is None
        else (kinds == ev_create) | (kinds == ev_delete)
    )

    def f2(a):
        return np.char.mod("%.2f", a)

    def cat(*parts):
        out = parts[0]
        for p in parts[1:]:
            out = np.char.add(out, p)
        return out

    # [Report] (origin): float32 row-sums, float64 ratios (report_frag_line)
    idle32 = amounts.sum(axis=1, dtype=np.float32)
    idle = idle32.astype(np.float64)
    frag = idle - amounts[:, Q3_SATISFIED].astype(np.float64)
    q124 = (amounts[:, 0] + amounts[:, 1] + amounts[:, 3]).astype(np.float64)
    safe = np.where(idle != 0, idle, 1.0)
    fr = np.where(idle != 0, 100.0 * frag / safe, 0.0)
    qr = np.where(idle != 0, 100.0 * q124 / safe, 0.0)
    frag_l = cat(
        "[Report]; Frag amount: ", f2(frag), "; Frag ratio: ", f2(fr),
        "%; Q124 ratio: ", f2(qr), "%; (origin)",
    )

    rows = []  # (mask, msgs) in per-event emission order
    if kinds is not None and pod_names is not None:
        verb = np.where(kinds == ev_create, "create", "delete")
        attempt_l = cat(
            "[", np.char.mod("%d", np.arange(e_count)), "] attempt to ",
            verb, " pod(", pod_names, ")",
        )
        rows.append((active, attempt_l))
        if failed is not None:
            rows.append(
                (
                    (kinds == ev_create) & failed,
                    cat(
                        "[deletePod] attempt to delete a non-scheduled pod(",
                        pod_names, ")",
                    ),
                )
            )
    rows.append((active, frag_l))
    if bellman is not None:
        br = np.where(idle != 0, 100.0 * bellman / safe, 0.0)
        rows.append(
            (
                active,
                cat(
                    "[Report]; Frag amount: ", f2(bellman),
                    "; Frag ratio: ", f2(br), "%; (bellman)",
                ),
            )
        )
    d = lambda a: np.char.mod("%d", a)
    rows.append(
        (
            active,
            cat(
                "[Alloc]; Used nodes: ", d(used_nodes),
                "; Used GPUs: ", d(used_gpus),
                "; Used GPU Milli: ", d(used_gpu_milli),
                "; Total GPUs: ", str(int(total_gpus)),
                "; Arrived GPU Milli: ", d(arrived_gpu_milli),
            ),
        )
    )
    rows.append(
        (
            active,
            cat(
                "[AllocCPU]; Used CPU Milli: ", d(used_cpu_milli),
                "; Arrived CPU Milli: ", d(arrived_cpu_milli),
            ),
        )
    )
    pc = power_cpu.astype(np.float64)
    pg = power_gpu.astype(np.float64)
    rows.append(
        (
            active,
            cat(
                "[Power]; cluster: ", np.char.mod("%.1f", pc + pg),
                "; ClusterCPU: ", np.char.mod("%.1f", pc),
                "; ClusterGPU: ", np.char.mod("%.1f", pg),
            ),
        )
    )

    # interleave: [R, E] row-per-line-family → event-major order
    mask = np.stack([m for m, _ in rows])
    grid = np.empty(mask.shape, dtype=object)
    for i, (_, msgs) in enumerate(rows):
        grid[i] = msgs
    return grid.T.ravel()[mask.T.ravel()].tolist()


def cluster_analysis_block(
    log: LogSink,
    tag: str,
    frag_amounts: np.ndarray,  # f32[7]
    alloc_requested: dict,
    alloc_allocatable: dict,
):
    """The 16-line `Cluster Analysis Results` block
    (analysis.go:177-199 + alloc.go:65-88)."""
    log.infoln()
    log.info(f"========== Cluster Analysis Results ({tag}) ==========")
    log.info("Allocation Ratio:")
    for k in _ALLOC_KEYS:
        rval = alloc_requested[k]
        aval = alloc_allocatable[k]
        ratio = 100.0 * rval / aval if aval else 0.0
        log.info(f"    {k:<8}: {ratio:4.1f}% ({rval}/{aval})")
    total = float(frag_amounts.sum())
    denom = total if total else 1.0
    for v, name in enumerate(FRAG_CLASS_NAMES):
        val = float(frag_amounts[v])
        log.info(f"{name:<13}: {val / 1000:6.2f} x 10^3 ({100 * val / denom:5.2f}%)")
    log.info("--------------------")
    log.info(f"{'idle_gpu_milli':<13}: {total / 1000:6.2f} x 10^3 (100.0%)")
    frag = total - float(frag_amounts[Q3_SATISFIED])
    log.info(
        f"{'frag_gpu_milli':<13}: {frag / 1000:6.2f} x 10^3 ({100 * frag / denom:5.2f}%)"
    )
    log.info("==============================================")
    log.infoln()
