"""analysis.py-compatible log emission (ref: §5.5 of SURVEY.md — the metric
contract is logrus Info lines parsed by scripts/analysis.py:120-260).

Formats reproduce pkg/simulator/analysis.go + pkg/utils/alloc.go exactly
(alloc keys use the parser-side names 'MilliCpu' etc. from
scripts/analysis.py ALLO_KEYS). Each emitted line carries a literal ``\\n``
escape before the closing quote, as logrus renders embedded newlines.
"""

from __future__ import annotations

import math
from typing import IO, List, Optional, Sequence

import numpy as np

from tpusim.constants import FRAG_CLASS_NAMES, Q3_SATISFIED

_ALLOC_KEYS = ("MilliCpu", "Memory", "Gpu", "MilliGpu")


class LogSink:
    """Collects logrus-text-format info lines (`level=info msg="..."`)."""

    def __init__(self, stream: Optional[IO] = None):
        self.lines: List[str] = []
        self.stream = stream

    def info(self, msg: str):
        line = f'time="2000-01-01T00:00:00Z" level=info msg="{msg}\\n"'
        self.lines.append(line)
        if self.stream is not None:
            self.stream.write(line + "\n")

    def infoln(self):
        line = 'time="2000-01-01T00:00:00Z" level=info'
        self.lines.append(line)
        if self.stream is not None:
            self.stream.write(line + "\n")

    def dump(self) -> str:
        return "\n".join(self.lines) + "\n"


def report_frag_line(log: LogSink, amounts: np.ndarray):
    """Per-event `[Report] ... (origin)` line (analysis.go:109)."""
    idle = float(amounts.sum())
    frag = idle - float(amounts[Q3_SATISFIED])
    q124 = float(amounts[0] + amounts[1] + amounts[3])
    fr = 100.0 * frag / idle if idle else 0.0
    qr = 100.0 * q124 / idle if idle else 0.0
    log.info(
        f"[Report]; Frag amount: {frag:.2f}; Frag ratio: {fr:.2f}%; "
        f"Q124 ratio: {qr:.2f}%; (origin)"
    )


def report_bellman_line(log: LogSink, bellman: float, idle: float):
    """`[Report] ... (bellman)` variant (analysis.go:110)."""
    r = 100.0 * bellman / idle if idle else 0.0
    log.info(f"[Report]; Frag amount: {bellman:.2f}; Frag ratio: {r:.2f}%; (bellman)")


def report_alloc_lines(
    log: LogSink,
    used_nodes: int,
    used_gpus: int,
    used_gpu_milli: int,
    total_gpus: int,
    arrived_gpu_milli: int,
    used_cpu_milli: int,
    arrived_cpu_milli: int,
):
    """Per-event `[Alloc]`/`[AllocCPU]` lines (analysis.go:115-118)."""
    log.info(
        f"[Alloc]; Used nodes: {used_nodes}; Used GPUs: {used_gpus}; "
        f"Used GPU Milli: {used_gpu_milli}; Total GPUs: {total_gpus}; "
        f"Arrived GPU Milli: {arrived_gpu_milli}"
    )
    log.info(
        f"[AllocCPU]; Used CPU Milli: {used_cpu_milli}; "
        f"Arrived CPU Milli: {arrived_cpu_milli}"
    )


def report_power_line(log: LogSink, power_cpu: float, power_gpu: float):
    """`[Power]` line (analysis.go:54-55)."""
    log.info(
        f"[Power]; cluster: {power_cpu + power_gpu:.1f}; "
        f"ClusterCPU: {power_cpu:.1f}; ClusterGPU: {power_gpu:.1f}"
    )


def cluster_analysis_block(
    log: LogSink,
    tag: str,
    frag_amounts: np.ndarray,  # f32[7]
    alloc_requested: dict,
    alloc_allocatable: dict,
):
    """The 16-line `Cluster Analysis Results` block
    (analysis.go:177-199 + alloc.go:65-88)."""
    log.infoln()
    log.info(f"========== Cluster Analysis Results ({tag}) ==========")
    log.info("Allocation Ratio:")
    for k in _ALLOC_KEYS:
        rval = alloc_requested[k]
        aval = alloc_allocatable[k]
        ratio = 100.0 * rval / aval if aval else 0.0
        log.info(f"    {k:<8}: {ratio:4.1f}% ({rval}/{aval})")
    total = float(frag_amounts.sum())
    denom = total if total else 1.0
    for v, name in enumerate(FRAG_CLASS_NAMES):
        val = float(frag_amounts[v])
        log.info(f"{name:<13}: {val / 1000:6.2f} x 10^3 ({100 * val / denom:5.2f}%)")
    log.info("--------------------")
    log.info(f"{'idle_gpu_milli':<13}: {total / 1000:6.2f} x 10^3 (100.0%)")
    frag = total - float(frag_amounts[Q3_SATISFIED])
    log.info(
        f"{'frag_gpu_milli':<13}: {frag / 1000:6.2f} x 10^3 ({100 * frag / denom:5.2f}%)"
    )
    log.info("==============================================")
    log.infoln()
