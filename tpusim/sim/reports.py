"""analysis.py-compatible log emission (ref: §5.5 of SURVEY.md — the metric
contract is logrus Info lines parsed by scripts/analysis.py:120-260).

Formats reproduce pkg/simulator/analysis.go + pkg/utils/alloc.go exactly
(alloc keys use the parser-side names 'MilliCpu' etc. from
scripts/analysis.py ALLO_KEYS). Each emitted line carries a literal ``\\n``
escape before the closing quote, as logrus renders embedded newlines.
"""

from __future__ import annotations

import math
import re
from typing import IO, Dict, List, Optional, Sequence

import numpy as np

from tpusim.constants import FRAG_CLASS_NAMES, Q3_SATISFIED

_ALLOC_KEYS = ("MilliCpu", "Memory", "Gpu", "MilliGpu")


class LogSink:
    """Collects logrus-text-format info lines (`level=info msg="..."`)."""

    def __init__(self, stream: Optional[IO] = None):
        self.lines: List[str] = []
        self.stream = stream

    def info(self, msg: str):
        line = f'time="2000-01-01T00:00:00Z" level=info msg="{msg}\\n"'
        self.lines.append(line)
        if self.stream is not None:
            self.stream.write(line + "\n")

    def info_many(self, msgs: Sequence[str]):
        """Bulk append of preformatted messages (the per-event report block
        builds its ~7 lines x |events| in vectorized numpy string ops; one
        write instead of per-line stream writes)."""
        if not msgs:
            return
        prefix = 'time="2000-01-01T00:00:00Z" level=info msg="'
        lines = [f'{prefix}{m}\\n"' for m in msgs]
        self.lines.extend(lines)
        if self.stream is not None:
            self.stream.write("\n".join(lines) + "\n")

    def infoln(self):
        line = 'time="2000-01-01T00:00:00Z" level=info'
        self.lines.append(line)
        if self.stream is not None:
            self.stream.write(line + "\n")

    def dump(self) -> str:
        return "\n".join(self.lines) + "\n"


def report_frag_line(log: LogSink, amounts: np.ndarray):
    """Per-event `[Report] ... (origin)` line (analysis.go:109)."""
    idle = float(amounts.sum())
    frag = idle - float(amounts[Q3_SATISFIED])
    q124 = float(amounts[0] + amounts[1] + amounts[3])
    fr = 100.0 * frag / idle if idle else 0.0
    qr = 100.0 * q124 / idle if idle else 0.0
    log.info(
        f"[Report]; Frag amount: {frag:.2f}; Frag ratio: {fr:.2f}%; "
        f"Q124 ratio: {qr:.2f}%; (origin)"
    )


def report_bellman_line(log: LogSink, bellman: float, idle: float):
    """`[Report] ... (bellman)` variant (analysis.go:110)."""
    r = 100.0 * bellman / idle if idle else 0.0
    log.info(f"[Report]; Frag amount: {bellman:.2f}; Frag ratio: {r:.2f}%; (bellman)")


def report_alloc_lines(
    log: LogSink,
    used_nodes: int,
    used_gpus: int,
    used_gpu_milli: int,
    total_gpus: int,
    arrived_gpu_milli: int,
    used_cpu_milli: int,
    arrived_cpu_milli: int,
):
    """Per-event `[Alloc]`/`[AllocCPU]` lines (analysis.go:115-118)."""
    log.info(
        f"[Alloc]; Used nodes: {used_nodes}; Used GPUs: {used_gpus}; "
        f"Used GPU Milli: {used_gpu_milli}; Total GPUs: {total_gpus}; "
        f"Arrived GPU Milli: {arrived_gpu_milli}"
    )
    log.info(
        f"[AllocCPU]; Used CPU Milli: {used_cpu_milli}; "
        f"Arrived CPU Milli: {arrived_cpu_milli}"
    )


def report_power_line(log: LogSink, power_cpu: float, power_gpu: float):
    """`[Power]` line (analysis.go:54-55)."""
    log.info(
        f"[Power]; cluster: {power_cpu + power_gpu:.1f}; "
        f"ClusterCPU: {power_cpu:.1f}; ClusterGPU: {power_gpu:.1f}"
    )


def pod_resource_repr(
    cpu_milli: int, gpu_num: int, gpu_milli: int, gpu_spec: str = "",
    cpu_spec: str = "",
) -> str:
    """PodResource.Repr (ref: pkg/type/resource.go:104-127): empty CPU type
    renders ANY; empty GPU type renders ANY for GPU pods, NONE otherwise."""
    cputype = cpu_spec or "ANY"
    gputype = gpu_spec or ("ANY" if gpu_milli > 0 else "NONE")
    return (
        f"<CPU: {cpu_milli / 1000:6.2f}, GPU: {gpu_num}"
        f" x {{{gpu_milli:<4d}}}m (CPUREQ: {cputype}) (GPUREQ: {gputype})>"
    )


def report_failed_pods(log: LogSink, pods) -> None:
    """`Failed Pods in detail:` block (ref: utils.ReportFailedPods,
    pkg/utils/utils.go:1344-1354, called from core.go:156 after RunCluster).
    `pods` is a sequence of PodRow-likes with name/cpu_milli/num_gpu/
    gpu_milli/gpu_spec."""
    if not pods:
        return
    log.info("Failed Pods in detail:")
    for p in pods:
        log.info(
            f"  {p.name}: "
            + pod_resource_repr(p.cpu_milli, p.num_gpu, p.gpu_milli, p.gpu_spec)
        )
    log.infoln()


def event_report_series(
    amounts: np.ndarray,  # f32[E, 7]
    power_cpu: np.ndarray,
    power_gpu: np.ndarray,
    bellman: Optional[np.ndarray] = None,  # f64[E]
) -> Dict[str, np.ndarray]:
    """The per-event float series of the report block, as FORMATTED string
    arrays — the single source both the log emitter
    (batch_event_report_msgs) and the direct CSV path
    (experiments/analysis.py analyze_sim) consume, so the two lanes are
    byte-identical by construction.

    Intermediate sums/ratios reproduce the scalar emitters' float32-sum →
    float64-divide sequencing exactly (report_frag_line/report_power_line).
    """

    def f2(a):
        return np.char.mod("%.2f", a)

    idle32 = amounts.sum(axis=1, dtype=np.float32)
    idle = idle32.astype(np.float64)
    frag = idle - amounts[:, Q3_SATISFIED].astype(np.float64)
    q124 = (amounts[:, 0] + amounts[:, 1] + amounts[:, 3]).astype(np.float64)
    safe = np.where(idle != 0, idle, 1.0)
    fr = np.where(idle != 0, 100.0 * frag / safe, 0.0)
    qr = np.where(idle != 0, 100.0 * q124 / safe, 0.0)
    pc = power_cpu.astype(np.float64)
    pg = power_gpu.astype(np.float64)
    series = {
        "origin_milli": f2(frag),
        "origin_ratio": f2(fr),
        "origin_q124": f2(qr),
        "power_cluster": np.char.mod("%.1f", pc + pg),
        "power_cluster_CPU": np.char.mod("%.1f", pc),
        "power_cluster_GPU": np.char.mod("%.1f", pg),
        # numeric twin of origin_milli for consumers that chart rather
        # than format (obs chrome counter tracks) — underscore-prefixed
        # so the CSV lanes, which read explicit keys, never see it
        "_frag_milli_f": frag,
    }
    if bellman is not None:
        br = np.where(idle != 0, 100.0 * bellman / safe, 0.0)
        series["bellman_milli"] = f2(bellman)
        series["bellman_ratio"] = f2(br)
    return series


def batch_event_report_msgs(
    amounts: np.ndarray,  # f32[E, 7]
    total_gpus: int,
    used_nodes: np.ndarray,
    used_gpus: np.ndarray,
    used_gpu_milli: np.ndarray,
    arrived_gpu_milli: np.ndarray,
    used_cpu_milli: np.ndarray,
    arrived_cpu_milli: np.ndarray,
    power_cpu: np.ndarray,
    power_gpu: np.ndarray,
    bellman: Optional[np.ndarray] = None,  # f64[E]
    kinds: Optional[np.ndarray] = None,  # event kind per event
    ev_create: int = 0,
    ev_delete: int = 1,
    pod_names: Optional[np.ndarray] = None,  # str[E] name of event's pod
    failed: Optional[np.ndarray] = None,  # bool[E] creation was rejected
    series: Optional[Dict[str, np.ndarray]] = None,  # event_report_series
) -> List[str]:
    """The whole per-event report block, vectorized: every line family is
    formatted as one numpy string op over the event axis, then interleaved
    into per-event order (attempt → rollback → frag → bellman → alloc →
    alloccpu → power; simulator.go:410-427, analysis.go:109-118). Skip
    events (pod-unscheduled annotation) emit nothing (simulator.go:391-399).

    Number formatting comes from event_report_series (pass a prebuilt one
    to share it with the direct CSV path).
    """
    e_count = amounts.shape[0]
    if e_count == 0:
        return []
    active = (
        np.ones(e_count, bool)
        if kinds is None
        else (kinds == ev_create) | (kinds == ev_delete)
    )
    if series is None:
        series = event_report_series(amounts, power_cpu, power_gpu, bellman)

    def cat(*parts):
        out = parts[0]
        for p in parts[1:]:
            out = np.char.add(out, p)
        return out

    frag_l = cat(
        "[Report]; Frag amount: ", series["origin_milli"],
        "; Frag ratio: ", series["origin_ratio"],
        "%; Q124 ratio: ", series["origin_q124"], "%; (origin)",
    )

    rows = []  # (mask, msgs) in per-event emission order
    if kinds is not None and pod_names is not None:
        verb = np.where(kinds == ev_create, "create", "delete")
        attempt_l = cat(
            "[", np.char.mod("%d", np.arange(e_count)), "] attempt to ",
            verb, " pod(", pod_names, ")",
        )
        rows.append((active, attempt_l))
        if failed is not None:
            rows.append(
                (
                    (kinds == ev_create) & failed,
                    cat(
                        "[deletePod] attempt to delete a non-scheduled pod(",
                        pod_names, ")",
                    ),
                )
            )
    rows.append((active, frag_l))
    if "bellman_milli" in series:
        rows.append(
            (
                active,
                cat(
                    "[Report]; Frag amount: ", series["bellman_milli"],
                    "; Frag ratio: ", series["bellman_ratio"],
                    "%; (bellman)",
                ),
            )
        )
    d = lambda a: np.char.mod("%d", a)
    rows.append(
        (
            active,
            cat(
                "[Alloc]; Used nodes: ", d(used_nodes),
                "; Used GPUs: ", d(used_gpus),
                "; Used GPU Milli: ", d(used_gpu_milli),
                "; Total GPUs: ", str(int(total_gpus)),
                "; Arrived GPU Milli: ", d(arrived_gpu_milli),
            ),
        )
    )
    rows.append(
        (
            active,
            cat(
                "[AllocCPU]; Used CPU Milli: ", d(used_cpu_milli),
                "; Arrived CPU Milli: ", d(arrived_cpu_milli),
            ),
        )
    )
    rows.append(
        (
            active,
            cat(
                "[Power]; cluster: ", series["power_cluster"],
                "; ClusterCPU: ", series["power_cluster_CPU"],
                "; ClusterGPU: ", series["power_cluster_GPU"],
            ),
        )
    )

    # interleave: [R, E] row-per-line-family → event-major order
    mask = np.stack([m for m, _ in rows])
    grid = np.empty(mask.shape, dtype=object)
    for i, (_, msgs) in enumerate(rows):
        grid[i] = msgs
    return grid.T.ravel()[mask.T.ravel()].tolist()


def camel_to_snake(name: str) -> str:
    """scripts/analysis.py's key normalization (shared with the direct CSV
    path so summary keys match the log-parse lane exactly)."""
    name = re.sub("(.)([A-Z][a-z]+)", r"\1_\2", name)
    return re.sub("([a-z0-9])([A-Z])", r"\1_\2", name).lower()


def cluster_analysis_block(
    log: LogSink,
    tag: str,
    frag_amounts: np.ndarray,  # f32[7]
    alloc_requested: dict,
    alloc_allocatable: dict,
) -> Dict[str, float]:
    """The 16-line `Cluster Analysis Results` block
    (analysis.go:177-199 + alloc.go:65-88).

    Returns the summary key/values scripts/analysis.py's parser would
    extract from this block (each value round-tripped through the SAME
    formatted string the log line carries), in the parser's insertion
    order — the direct CSV path consumes this instead of re-parsing."""
    summary: Dict[str, float] = {}
    log.infoln()
    log.info(f"========== Cluster Analysis Results ({tag}) ==========")
    log.info("Allocation Ratio:")
    for k in _ALLOC_KEYS:
        rval = alloc_requested[k]
        aval = alloc_allocatable[k]
        ratio = 100.0 * rval / aval if aval else 0.0
        log.info(f"    {k:<8}: {ratio:4.1f}% ({rval}/{aval})")
        summary[camel_to_snake(k + tag)] = float(f"{ratio:4.1f}")
        summary[camel_to_snake(k + "Amount" + tag)] = float(rval)
        summary[camel_to_snake(k + "Total")] = float(aval)
    total = float(frag_amounts.sum())
    denom = total if total else 1.0
    for v, name in enumerate(FRAG_CLASS_NAMES):
        val = float(frag_amounts[v])
        pct = 100 * val / denom
        log.info(f"{name:<13}: {val / 1000:6.2f} x 10^3 ({pct:5.2f}%)")
        summary[camel_to_snake(name + tag)] = float(f"{pct:5.2f}")
    log.info("--------------------")
    log.info(f"{'idle_gpu_milli':<13}: {total / 1000:6.2f} x 10^3 (100.0%)")
    frag = total - float(frag_amounts[Q3_SATISFIED])
    fpct = 100 * frag / denom
    log.info(
        f"{'frag_gpu_milli':<13}: {frag / 1000:6.2f} x 10^3 ({fpct:5.2f}%)"
    )
    summary[camel_to_snake("frag_gpu_milli" + tag)] = float(f"{fpct:5.2f}")
    log.info("==============================================")
    log.infoln()
    return summary


def disruption_report_block(log: LogSink, dm) -> Dict[str, float]:
    """The `[Disruption]` block a fault replay emits after its last
    segment (dm: tpusim.sim.metrics.DisruptionMetrics). A new line family
    — the analysis parser ignores unknown families, so the existing CSV
    lanes are unaffected; the returned summary dict feeds the direct-CSV
    stash like cluster_analysis_block's does."""
    log.info(
        f"[Disruption] node failures: {dm.node_failures}, recoveries: "
        f"{dm.node_recoveries}, evicted pods: {dm.evicted_pods}, retries "
        f"enqueued: {dm.retries_enqueued}"
    )
    lat = dm.reschedule_latency_events
    log.info(
        f"[Disruption] rescheduled: {dm.rescheduled_pods} "
        f"(latency events mean {dm.mean_reschedule_latency():.1f}, max "
        f"{max(lat) if lat else 0}), unscheduled after retries: "
        f"{dm.unscheduled_after_retries}"
    )
    log.info(
        f"[Disruption] failed-node GPU capacity lost: "
        f"{dm.failed_node_gpu_events} GPU-events"
    )
    if dm.post_recovery_frag_delta:
        log.info(
            f"[Disruption] post-recovery frag delta: "
            f"{sum(dm.post_recovery_frag_delta) / 1000:.2f} x 10^3 over "
            f"{len(dm.post_recovery_frag_delta)} recoveries"
        )
    return {f"disruption_{k}": float(v) for k, v in dm.as_dict().items()}
